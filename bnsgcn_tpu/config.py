"""Typed run configuration.

Flag-compatible with the reference CLI (reference helper/parser.py:4-61): every
reference flag has a field of the same name here, plus TPU-specific knobs. The
reference threads a raw argparse namespace through every module; here the
config is a frozen dataclass created once and passed explicitly.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from dataclasses import dataclass
from typing import Optional


class ConfigError(ValueError):
    """A named configuration error: main.py prints it and exits 2 (the
    deterministic-argument-error code the bench supervisor and requeue
    wrappers never relaunch), instead of a stack trace from deep inside
    mesh construction."""


@dataclass(frozen=True)
class Config:
    # --- data / partitioning (reference helper/parser.py:6-13,37-41) ---
    dataset: str = "reddit"
    data_path: str = "./dataset/"
    part_path: str = "./partition/"
    graph_name: str = ""
    n_partitions: int = 2
    partition_obj: str = "vol"          # 'vol' | 'cut'
    partition_method: str = "metis"     # 'metis' | 'random'  (metis → native partitioner)
    inductive: bool = False
    skip_partition: bool = False

    # --- model (reference helper/parser.py:14-31,42-46) ---
    model: str = "graphsage"            # 'gcn' | 'graphsage' | 'gat'
    n_layers: int = 2
    n_hidden: int = 16
    n_linear: int = 0
    heads: int = 1
    norm: Optional[str] = "layer"       # 'layer' | 'batch' | None
    dropout: float = 0.5
    use_pp: bool = False

    # --- optimization (reference helper/parser.py:16-19,32-34) ---
    lr: float = 1e-2
    weight_decay: float = 0.0
    n_epochs: int = 200
    sampling_rate: float = 1.0

    # --- bookkeeping ---
    log_every: int = 10
    eval: bool = True
    fix_seed: bool = False
    seed: int = 0
    ckpt_path: str = "./checkpoint/"
    results_path: str = "./results/"
    resume: bool = False                # capability upgrade: reference is save-only (train.py:428)
    keep_ckpt: int = 5                  # retain the newest N periodic checkpoints (0 = keep all;
                                        # the reference keeps every snapshot, train.py:428)

    # --- distributed / launcher (reference helper/parser.py:47-56) ---
    backend: str = "xla"                # XLA collectives; 'gloo'/'mpi' accepted as aliases
    port: int = 18118
    master_addr: str = "127.0.0.1"
    node_rank: int = 0
    parts_per_node: int = 10
    n_nodes: int = 1                    # multi-host: number of processes (jax.distributed)

    # --- TPU-specific knobs (no reference equivalent) ---
    replicas: int = 1                   # replica-axis size of the 2-D
                                        # ('replicas','parts') mesh: each of N
                                        # full graph replicas draws an
                                        # independent BNS boundary sample and
                                        # the gradient is the fused cross-
                                        # replica mean (~1/N sampling variance
                                        # at constant epoch math/replica).
                                        # Needs replicas*n_partitions devices;
                                        # 1 = the historical 1-D parts mesh,
                                        # bit-identical
    feat: int = 1                       # feat-axis size of the 3-D
                                        # ('replicas','parts','feat') mesh:
                                        # shard hidden dimensions T-ways —
                                        # perfectly load-balanced (no boundary
                                        # nodes on this axis), halo wire bytes
                                        # drop ~T x, weight/optimizer HBM and
                                        # matmul FLOPs /T; one feat psum per
                                        # layer. Needs replicas*parts*feat
                                        # devices; 1 = no axis, bit-identical
    dtype: str = "float32"              # compute dtype: 'float32' | 'bfloat16'
    edge_chunk: int = 0                 # >0: aggregate edges in chunks of this size (bounds HBM)
    spmm: str = "ell"                   # 'ell' (scatter-free bucketed) | 'hybrid'
                                        # (dense int8 MXU tiles + ELL residual) | 'auto'
                                        # (estimate tile coverage, pick hybrid/ell) | 'segment'
    use_pallas: bool = False            # use Pallas aggregation kernels where available
    spmm_gather: str = "native"         # 'native' | 'fp8' | 'int8': quantize SpMM gather rows to
                                        # e4m3 (+1 scale per call) — the gather unit is
                                        # row-rate bound, so 256B rows move ~1.5x faster
    spmm_dense: str = "native"          # hybrid SpMM dense-tile matmul dtype: 'native'
                                        # (compute dtype) | 'int8' (quantized slabs,
                                        # int8x int8 MXU at ~2x bf16 rate)
    block_occupancy: int = 0            # hybrid SpMM: min edges for a tile to densify.
                                        # 0 = auto: the tile's byte break-even,
                                        # tile*tile/512 (512 at the default 512x512
                                        # tile, 128 at 256x256); explicit values are
                                        # absolute (MXU-time break-even is nearer
                                        # ~1200 at 31 TFLOP/s for 512x512)
    block_tile_budget_mb: int = 2048    # hybrid SpMM: int8 dense-tile HBM budget per
                                        # direction (8192 tiles at 512x512)
    block_tile: int = 512               # hybrid SpMM: square tile edge (512 default;
                                        # 256 = 4x more tiles per budget byte, finer
                                        # edge capture on clustered graphs at ~2x the
                                        # slab-gather traffic per tile byte)
    reorder: str = "off"                # graph-reordering artifact pass
                                        # (data/reorder.py): 'cluster' permutes
                                        # each part's inner rows ONCE at load
                                        # (degree-anchored label propagation +
                                        # FFD tile packing) so edge mass
                                        # concentrates into dense MXU tiles;
                                        # 'auto' applies it only when measured
                                        # tile coverage improves; 'off' is the
                                        # bit-identical pre-reorder pipeline.
                                        # Results stay in global id order (the
                                        # permuted global_nid inverts at every
                                        # user-visible edge); the order is
                                        # cached like layouts under --cache-dir
    profile_dir: str = ""               # write a jax.profiler trace of a few epochs here
    comm_trace: bool = True             # auto-trace a short post-warmup window and report
                                        # trace-derived in-step Comm/Reduce columns
                                        # ([traced]); --no-comm-trace keeps the
                                        # exchange-only microbench ([sampled])
    remat: bool = False                 # rematerialize each layer in backward (saves HBM,
                                        # recomputes activations incl. the halo exchange)
    eval_device: str = "host"           # 'host' (background thread, full graph) |
                                        # 'mesh' (distributed full-rate eval on the parts mesh)
    halo_exchange: str = "padded"       # 'padded' (one all_to_all, uniform pad) |
                                        # 'shift' (P-1 ppermute rounds, per-shift pads —
                                        #  wire bytes track skewed boundary sizes) |
                                        # 'ragged' (one lax.ragged_all_to_all, exact
                                        #  per-pair bytes; emulated off-TPU) |
                                        # 'auto' (pick per run from wire_bytes() +
                                        #  hop-count tiebreak; logged at startup)
    halo_wire: str = "native"           # interconnect payload dtype for the training halo
                                        # exchange: 'native' | 'bf16' | 'fp8' (e4m3 + scales)
    halo_refresh: int = 1               # staleness-bounded halo cache: reuse each
                                        # layer's received halo block for up to K
                                        # epochs, refreshing ~1/K of every boundary
                                        # set per epoch (round-robin over position
                                        # chunks) so steady-state wire bytes drop
                                        # ~K x without a synchronized staleness
                                        # cliff. Gradients stop at stale cached
                                        # rows (exact w.r.t. the forward actually
                                        # computed). 1 = the historical per-epoch
                                        # exchange, bit-identical. The cache is
                                        # never checkpointed: rollback/--resume
                                        # invalidate it and force one full-refresh
                                        # (peak-wire) epoch
    halo_mode: str = "exchange"         # 'exchange' (activations cross the wire as
                                        # configured above) | 'grad-only' (the
                                        # Grappa extreme: skip the activation
                                        # exchange entirely and aggregate from
                                        # local rows only — zero halo block,
                                        # presence-masked out of GAT softmax;
                                        # the per-step gradient all-reduce is the
                                        # only collective left)
    tune: str = "off"                   # closed-loop comm auto-tuner (tune.py):
                                        # 'off' (launch levers frozen, bit-
                                        # identical pre-tune loop) | 'schedule'
                                        # (declarative per-epoch lever schedule,
                                        # --tune-schedule) | 'auto' (feedback
                                        # anneal on the obs bus: staleness
                                        # tightens as loss flattens, strategy/
                                        # codec re-picked from MEASURED comm
                                        # share; single-process only). Every
                                        # move is a tune_decision event and a
                                        # full-refresh rebuild of the step fns
    tune_schedule: str = ""             # --tune schedule grammar: comma-
                                        # separated lever=value@epoch, levers
                                        # K/mode/strategy/wire (e.g.
                                        # 'K=4@0,K=2@30,K=1@60,wire=bf16@30')
    tune_prior: str = "ladder"          # --tune auto launch point: 'ladder'
                                        # (coarse K=4 start, tighten rung by
                                        # rung — the historical controller,
                                        # bit-identical default) | 'model'
                                        # (the graftperf cost model
                                        # (analysis/perf) predicts the comm
                                        # fraction and picks the starting
                                        # rung, then auto refines locally)
    overlap: str = "off"                # 'off' (fused exchange-then-aggregate; the
                                        # historical step graph) | 'split' (interior/
                                        # frontier row-split aggregation: the halo
                                        # collective is dispatched first and the
                                        # interior SpMM — rows with no halo
                                        # in-neighbor — runs while it is in flight;
                                        # numerically row-exact vs 'off')
    streaming_artifacts: str = "auto"   # 'auto' (> 30M edges) | 'always' | 'never':
                                        # build partition artifacts one part at a time
    feat_storage: str = "float32"       # on-disk feature dtype for streamed artifacts
                                        # ('bfloat16' halves papers100M-scale feature IO)
    resilience: str = "on"              # 'on' (divergence rollback + preemption-
                                        # safe shutdown + hung-step watchdog,
                                        # resilience.py) | 'off' (bit-identical
                                        # pre-resilience loop: no checks, no
                                        # threads, no signal handlers)
    inject: str = ""                    # deterministic fault injection:
                                        # 'kind@E<epoch>,...' with kinds
                                        # nan|sigterm|hang|ckpt-corrupt|
                                        # ranklost (env $BNSGCN_FAULT); CI
                                        # proves every recovery path with it.
                                        # ranklost requires :r<rank> — losing
                                        # every rank is not a resize. Serving
                                        # kinds fire on the Nth routed data-
                                        # path request inside one backend:
                                        # servekill@<N>:p<P>.r<R> (hard exit)
                                        # | servehang@<N>:p<P>.r<R> (wedge) |
                                        # servedrop@<N>[:p<P>.r<R>] (torn
                                        # connection, no response)
    elastic: str = "off"                # 'on': a heartbeat-detected rank
                                        # loss becomes an agreed RESIZE
                                        # verdict (survivors re-host the P
                                        # parts via mesh.plan_slots and keep
                                        # training; a rejoining replacement
                                        # grows the world back) instead of
                                        # CoordTimeout -> exit 77. 'off'
                                        # (default): the exact pre-elastic
                                        # protocol, bit-identical, exit-code
                                        # table unchanged. Requires the
                                        # coordinator (--coord tcp|file)
    elastic_min_world: int = 1          # smallest world a RESIZE may shrink
                                        # to; fewer survivors -> agreed abort
                                        # (78) instead of overloaded workers
    resil_retries: int = 3              # divergence rollbacks (exponential
                                        # backoff) before aborting with a
                                        # diagnostic report
    coord: str = "auto"                 # multi-host rank coordination channel
                                        # (parallel/coord.py): 'auto' (tcp
                                        # when >1 rank, else off) | 'tcp'
                                        # (rank 0 serves --coord-port) |
                                        # 'file' (shared --coord-dir) |
                                        # 'off' (bit-identical PR-4 paths:
                                        # no agreed verdicts, multi-host
                                        # resilience downgraded)
    coord_addr: str = ""                # coordinator host (default
                                        # master_addr) for --coord tcp
    coord_port: int = 18119             # rank 0's KV-server port (tcp)
    coord_dir: str = ""                 # shared dir for --coord file
                                        # (default {ckpt_path}/.coord)
    coord_rank: int = -1                # this process's coordination rank;
                                        # -1 = jax.process_index(). Explicit
                                        # values enable the no-XLA-collective
                                        # subprocess harness (each process a
                                        # full single-host trainer, coupled
                                        # only through the coordinator)
    coord_world: int = 0                # total coordination ranks; 0 =
                                        # jax.process_count()
    # --- online inference serving (serve.py; `python -m bnsgcn_tpu.main
    # serve ...` or `python -m bnsgcn_tpu.serve ...`) ---
    serve_port: int = 18120             # line-JSON TCP port the node-
                                        # prediction server listens on
                                        # (same wire protocol/framing as the
                                        # rank coordinator's KV server)
    serve_addr: str = ""                # bind address (server) / connect
                                        # address (clients); default all
                                        # interfaces / 127.0.0.1
    serve_dir: str = ""                 # serving state dir (resumable delta
                                        # log flushed on SIGTERM drain);
                                        # default {ckpt_path}/serve
    serve_max_batch: int = 64           # max tier-B requests coalesced into
                                        # one padded-SpMM bucket step
    serve_refresh_s: float = 0.2        # background dirty-embedding refresh
                                        # cadence (0 = refresh only on
                                        # demand / 'flush')
    embeddings: str = ""                # embedding-table artifact
                                        # (--dump-embeddings output) to
                                        # cold-start serving from instead of
                                        # recomputing the all-node table
    dump_embeddings: str = ""           # eval path: write the all-node
                                        # embedding table (penultimate
                                        # activations + final-layer logits,
                                        # checkpoint integrity header) here
    serve_compact_deltas: int = 0       # delta-log compaction threshold: at
                                        # >= N logged deltas, snapshot the
                                        # mutated graph + tables (write_blob
                                        # integrity header) and truncate the
                                        # log to a tail so relaunch replay is
                                        # O(snapshot + tail); 0 = never
                                        # compact (full replay, PR-7 exact)
    # --- partition-sharded distributed serving (serve_router.py /
    # serve_backend.py; `serve-router` + `serve-backend` subcommands) ---
    parts: int = 0                      # serving fleet width: number of
                                        # partition shards the router expects
                                        # backends for; 0 = read it from the
                                        # partition artifacts' meta.json
    part_replicas: int = 1              # read replicas per part behind the
                                        # router (deltas broadcast to all,
                                        # reads round-robined)
    serve_part: int = -1                # which partition shard THIS backend
                                        # process owns (serve-backend only)
    serve_replica: int = 0              # this backend's replica ordinal
                                        # within its part (serve-backend)
    serve_backend_port: int = 0         # backend listen port (serve-backend;
                                        # 0 = ephemeral, reported to the
                                        # router at registration)
    serve_router: str = ""              # router address a backend registers
                                        # with / clients connect to, as
                                        # 'host:port' (default
                                        # 127.0.0.1:{serve_port})
    serve_degraded: str = "off"         # router answer when a part has no
                                        # live backend: 'off' = named
                                        # RouteError (PR-16 protocol),
                                        # 'partial' = per-node
                                        # status:"unavailable" rows, the rest
                                        # answered; 'stale-ok' = additionally
                                        # serve tier-A from a non-up replica,
                                        # tagged status:"stale"
    serve_probe_s: float = 0.0          # router health-probe cadence in
                                        # seconds (up/suspect/down states,
                                        # breaker quarantine, WAL replay on
                                        # recovery); 0 = probes off —
                                        # evict-on-error exactly as PR 16.
                                        # Thresholds are env knobs:
                                        # BNSGCN_SERVE_{SUSPECT_AFTER,
                                        # DOWN_AFTER,READMIT,BREAKER_FLAPS,
                                        # BREAKER_WINDOW_S,BREAKER_HOLD_S,
                                        # PROBE_TIMEOUT_S}
    serve_hedge: str = "off"            # 'on' = hedge tier-A reads: fire a
                                        # second replica after a p99-derived
                                        # delay, first answer wins, loser
                                        # cancelled (reads only — writes stay
                                        # at-most-once)
    serve_wal_cap: int = 256            # router-side WAL bound: queued
                                        # delta writes per DOWN part before
                                        # new writes fail loudly (replayed in
                                        # order on recovery; only active with
                                        # --serve-degraded != off)
    # --- continual training on an evolving graph (continual.py +
    # data/incremental.py; `python -m bnsgcn_tpu.main continual ...`).
    # All defaults are inert: a run that never passes --warm-start /
    # --cycle-nonce and never invokes the subcommand is bit-identical. ---
    cycle_epochs: int = 5               # fine-tune epochs per continual cycle
    cycles: int = 1                     # continual cycles to run (looped
                                        # train->promote; 1 = one-shot)
    continual_source: str = "auto"      # where cycle deltas come from:
                                        # 'server' (live export_deltas RPC
                                        # handshake), 'log' (flushed delta-log
                                        # files in serve_dir), 'auto' = server
                                        # if reachable else log
    continual_cut_growth: float = 1.5   # staleness budget: re-partition from
                                        # scratch when edge-cut grows past
                                        # baseline*factor (else incremental
                                        # update at the pinned assignment)
    continual_imbalance: float = 2.0    # staleness budget: re-partition when
                                        # max/mean per-part edge load exceeds
                                        # this factor
    continual_acc_drop: float = 0.02    # promotion gate: refuse to promote
                                        # (keep serving the prior weights)
    #                                     when the fine-tuned val accuracy
    #                                     drops more than this below the old
    #                                     weights' accuracy on the SAME
    #                                     mutated graph
    warm_start: str = ""                # checkpoint blob to warm-start
                                        # params/BN state from (optimizer
                                        # starts fresh; mutually exclusive
                                        # with --resume)
    cycle_nonce: int = 0                # continual-cycle fold of the
                                        # sampling/dropout streams (the
                                        # retry-nonce pattern, high-bit fold
                                        # domain); 0 = historical streams,
                                        # bit-identical

    # --- observability (obs.py: unified telemetry bus) ---
    obs: str = "on"                     # 'on' (process-wide metrics registry +
                                        # structured event log + post-mortem
                                        # capture, obs.py) | 'off' (constructs
                                        # none of it: bit-identical loop,
                                        # pinned by tests/test_obs.py)
    obs_log: str = ""                   # rank-tagged JSONL event log path
                                        # (default $BNSGCN_OBS_LOG; ranks > 0
                                        # write PATH.r<rank>); size-bounded
                                        # with rotation ($BNSGCN_OBS_MAX_MB).
                                        # Empty = registry only, no file
    obs_dir: str = ""                   # post-mortem dir (watchdog/divergence
                                        # dumps, SIGUSR1 stack+metrics+trace
                                        # snapshots); default
                                        # {ckpt_path}/postmortem
    strict_exec: bool = False           # strict-execution runtime guard
                                        # (strict.py): jax.transfer_guard
                                        # around the hot-loop step (implicit
                                        # host transfer = error) + a compile
                                        # listener (recompile after a step
                                        # variant's first epoch = error).
                                        # Proof-of-cleanliness for pod runs;
                                        # the static half is graftlint
                                        # (python -m bnsgcn_tpu.analysis)

    cache_dir: str = ""                 # persistent dir for SpMM layout pickles
                                        # (content-addressed by hybrid_layout_key);
                                        # default from $BNSGCN_CACHE_DIR — point it at
                                        # a persistent volume and the ~980 s hybrid
                                        # layout build survives container wipes.
                                        # Empty = rebuild every run.

    # fields injected from partition meta.json at load time
    # (reference helper/utils.py:134-138)
    n_feat: int = 0
    n_class: int = 0
    n_train: int = 0

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    @property
    def multilabel(self) -> bool:
        return self.dataset == "yelp"

    def layer_sizes(self) -> list[int]:
        """[n_feat, hidden, ..., hidden, n_class] — reference helper/utils.py:233-241."""
        assert self.n_layers >= 1
        return [self.n_feat] + [self.n_hidden] * (self.n_layers - 1) + [self.n_class]

    def derive_graph_name(self) -> str:
        """Reference main.py:18-24."""
        mode = "induc" if self.inductive else "trans"
        return (f"{self.dataset}-{self.n_partitions}-{self.partition_method}-"
                f"{self.partition_obj}-{mode}")


def create_parser() -> argparse.ArgumentParser:
    """Argparse front-end accepting the reference's flags (helper/parser.py:4-61)."""
    p = argparse.ArgumentParser(description="bnsgcn_tpu — TPU-native BNS-GCN-capability framework")

    def both(name, **kw):
        p.add_argument(f"--{name}", f"--{name.replace('-', '_')}", **kw)

    p.add_argument("--dataset", type=str, default="reddit")
    both("data-path", type=str, default="./dataset/")
    both("part-path", type=str, default="./partition/")
    both("graph-name", type=str, default="")
    p.add_argument("--model", type=str, default="graphsage",
                   choices=["gcn", "graphsage", "gat"])
    p.add_argument("--dropout", type=float, default=0.5)
    p.add_argument("--lr", type=float, default=1e-2)
    both("sampling-rate", type=float, default=1.0)
    p.add_argument("--heads", type=int, default=1)
    both("n-epochs", type=int, default=200)
    both("n-partitions", type=int, default=2)
    both("n-hidden", type=int, default=16)
    both("n-layers", type=int, default=2)
    both("log-every", type=int, default=10)
    both("weight-decay", type=float, default=0.0)
    p.add_argument("--norm", choices=["layer", "batch", "none"], default="layer")
    both("partition-obj", choices=["vol", "cut"], default="vol")
    both("partition-method", choices=["metis", "random"], default="metis")
    both("n-linear", type=int, default=0)
    both("use-pp", action="store_true", default=False)
    p.add_argument("--inductive", action="store_true")
    both("fix-seed", action="store_true", default=False)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--backend", type=str, default="xla")
    p.add_argument("--port", type=int, default=18118)
    both("master-addr", type=str, default="127.0.0.1")
    both("node-rank", type=int, default=0)
    both("parts-per-node", type=int, default=10)
    p.add_argument("--skip-partition", action="store_true")
    p.add_argument("--eval", action="store_true", dest="eval")
    p.add_argument("--no-eval", action="store_false", dest="eval")
    p.set_defaults(eval=True)
    # TPU-specific
    p.add_argument("--replicas", type=int, default=1,
                   help="replica-axis size: train N independently-BNS-sampled "
                        "graph replicas on a ('replicas','parts') mesh and "
                        "average gradients (needs N*n_partitions devices; "
                        "use when devices > partitions)")
    p.add_argument("--feat", type=int, default=1,
                   help="feat/tensor-axis size: shard hidden dimensions "
                        "T-ways on the innermost mesh axis (zero boundary "
                        "nodes on this axis; halo wire bytes and matmul "
                        "FLOPs drop ~T x; one psum per layer) — wins on "
                        "wide-hidden runs; needs replicas*parts*feat devices")
    p.add_argument("--dtype", type=str, default="float32", choices=["float32", "bfloat16"])
    p.add_argument("--spmm", type=str, default="ell",
                   choices=["ell", "hybrid", "auto", "segment"])
    both("profile-dir", type=str, default="")
    p.add_argument("--no-comm-trace", action="store_false", dest="comm_trace",
                   help="disable the auto-traced in-step Comm/Reduce columns")
    p.set_defaults(comm_trace=True)
    p.add_argument("--remat", action="store_true")
    both("eval-device", type=str, default="host", choices=["host", "mesh"])
    both("halo-exchange", type=str, default="padded",
         choices=["padded", "shift", "ragged", "auto"])
    both("halo-wire", type=str, default="native", choices=["native", "bf16", "fp8", "int8"])
    both("halo-refresh", type=int, default=1,
         help="reuse each layer's received halo block for up to K epochs, "
              "refreshing ~1/K of every boundary set per epoch round-robin "
              "(steady-state wire bytes drop ~K x; 1 = exchange every epoch, "
              "bit-identical to the pre-cache path)")
    both("halo-mode", type=str, default="exchange",
         choices=["exchange", "grad-only"],
         help="'grad-only' skips the activation exchange entirely "
              "(local-only aggregation; the per-step gradient all-reduce is "
              "the only collective left)")
    p.add_argument("--tune", type=str, default="off",
                   choices=["off", "schedule", "auto"],
                   help="closed-loop comm auto-tuner (tune.py): retune "
                        "staleness/strategy/codec at epoch boundaries from "
                        "the obs-bus metrics ('auto', single-process) or a "
                        "declarative --tune-schedule ('schedule'); every "
                        "move is an audited tune_decision event")
    both("tune-schedule", type=str, default="",
         help="--tune schedule grammar: comma-separated lever=value@epoch "
              "with levers K/mode/strategy/wire, e.g. "
              "'K=4@0,K=2@30,K=1@60,wire=bf16@30'")
    both("tune-prior", type=str, default="ladder",
         choices=["ladder", "model"],
         help="--tune auto launch point: 'ladder' starts coarse (K=4) and "
              "tightens rung by rung; 'model' asks the graftperf cost model "
              "(analysis/perf) for the predicted-optimal starting rung from "
              "the partition geometry + calibration tables, then refines "
              "locally — fewer retune windows when the model is right")
    p.add_argument("--overlap", type=str, default="off", choices=["off", "split"])
    both("streaming-artifacts", type=str, default="auto",
         choices=["auto", "always", "never"])
    both("feat-storage", type=str, default="float32",
         choices=["float32", "bfloat16"])
    p.add_argument("--resilience", type=str, default="on",
                   choices=["on", "off"],
                   help="divergence rollback, preemption-safe checkpointing "
                        "and the hung-step watchdog (off = the exact "
                        "pre-resilience loop)")
    p.add_argument("--inject", type=str,
                   default=os.environ.get("BNSGCN_FAULT", ""),
                   help="deterministic fault injection, e.g. "
                        "'nan@E12,sigterm@E20,hang@E8,ckpt-corrupt@E10,"
                        "ranklost@E6:r1'")
    both("resil-retries", type=int, default=3)
    p.add_argument("--elastic", type=str, default="off",
                   choices=["off", "on"],
                   help="elastic world size: agree a coordinated RESIZE on "
                        "heartbeat-detected rank loss (survivors re-host all "
                        "parts and keep training; a rejoin grows back) "
                        "instead of exiting 77 (off = the exact pre-elastic "
                        "protocol, bit-identical)")
    both("elastic-min-world", type=int, default=1,
         help="smallest world --elastic may shrink to before an agreed "
              "abort (exit 78)")
    p.add_argument("--coord", type=str, default="auto",
                   choices=["auto", "tcp", "file", "off"],
                   help="multi-host rank-coordination channel for agreed "
                        "abort/rollback (off = the uncoordinated PR-4 "
                        "behavior, bit-identical)")
    both("coord-addr", type=str, default="")
    both("coord-port", type=int, default=18119)
    both("coord-dir", type=str, default="")
    both("coord-rank", type=int, default=-1,
         help="explicit coordination rank (with --coord-world: run the "
              "coordinator without jax.distributed — the subprocess fault "
              "harness)")
    both("coord-world", type=int, default=0)
    # online inference serving (serve.py)
    both("serve-port", type=int, default=18120)
    both("serve-addr", type=str, default="")
    both("serve-dir", type=str, default="")
    both("serve-max-batch", type=int, default=64)
    both("serve-refresh-s", type=float, default=0.2)
    p.add_argument("--embeddings", type=str, default="",
                   help="embedding-table artifact (--dump-embeddings "
                        "output) to cold-start serving from")
    both("dump-embeddings", type=str, default="",
         help="write the all-node embedding table (+ integrity header) "
              "here after eval — serve.py cold-starts from it")
    both("serve-compact-deltas", type=int, default=0,
         help="compact the serving delta log past N entries: integrity-"
              "headed snapshot + truncated tail, so relaunch replay is "
              "O(snapshot + tail) instead of O(all deltas ever); 0 = off")
    # partition-sharded distributed serving (serve_router/serve_backend)
    p.add_argument("--parts", type=int, default=0,
                   help="serving fleet width (number of partition shards "
                        "the router fronts); 0 = read it from the partition "
                        "artifacts' meta.json")
    both("part-replicas", type=int, default=1,
         help="read replicas per part behind the serving router (deltas "
              "broadcast, reads round-robined)")
    both("serve-part", type=int, default=-1,
         help="partition shard this serve-backend owns")
    both("serve-replica", type=int, default=0,
         help="replica ordinal of this serve-backend within its part")
    both("serve-backend-port", type=int, default=0,
         help="serve-backend listen port (0 = ephemeral; reported to the "
              "router at registration)")
    both("serve-router", type=str, default="",
         help="router 'host:port' a serve-backend registers with (default "
              "127.0.0.1:{serve-port})")
    both("serve-degraded", type=str, default="off",
         choices=["off", "partial", "stale-ok"],
         help="router behavior for a part with no live backend: 'off' = "
              "named RouteError (PR-16), 'partial' = per-node "
              "status:'unavailable' rows while the rest answer, 'stale-ok' "
              "= also serve possibly-stale tier-A from a non-up replica, "
              "tagged status:'stale'")
    both("serve-probe-s", type=float, default=0.0,
         help="router health-probe cadence in seconds (up/suspect/down, "
              "breaker quarantine, rejoin warm-up + WAL replay); 0 = "
              "probes off, evict-on-error exactly as PR 16 "
              "(thresholds: BNSGCN_SERVE_* env knobs)")
    both("serve-hedge", type=str, default="off", choices=["off", "on"],
         help="hedge tier-A fleet reads: fire a second replica after a "
              "p99-derived delay, first answer wins, loser cancelled")
    both("serve-wal-cap", type=int, default=256,
         help="bounded router-side WAL: queued delta writes per down part "
              "before writes fail loudly (replayed in order on recovery)")
    # continual training (continual.py; `continual` subcommand)
    both("cycle-epochs", type=int, default=5,
         help="fine-tune epochs per continual cycle")
    p.add_argument("--cycles", type=int, default=1,
                   help="continual cycles to run (looped train->promote; "
                        "1 = one-shot)")
    both("continual-source", type=str, default="auto",
         choices=["auto", "server", "log"],
         help="cycle delta source: live export_deltas RPC ('server'), "
              "flushed delta-log files ('log'), or 'auto'")
    both("continual-cut-growth", type=float, default=1.5,
         help="re-partition from scratch when edge-cut exceeds "
              "baseline*factor; below it the cycle updates artifacts "
              "incrementally at the pinned assignment")
    both("continual-imbalance", type=float, default=2.0,
         help="re-partition when max/mean per-part edge load exceeds this")
    both("continual-acc-drop", type=float, default=0.02,
         help="refuse to promote when fine-tuned val accuracy drops more "
              "than this below the old weights on the same mutated graph")
    both("warm-start", type=str, default="",
         help="checkpoint blob to warm-start params/BN state from (fresh "
              "optimizer; mutually exclusive with --resume)")
    both("cycle-nonce", type=int, default=0,
         help="continual-cycle sampling/dropout stream fold (0 = "
              "bit-identical historical streams)")
    # observability (obs.py)
    p.add_argument("--obs", type=str, default="on", choices=["on", "off"],
                   help="unified telemetry bus: metrics registry + "
                        "structured JSONL event log + post-mortem capture "
                        "(off = the exact pre-obs loop, bit-identical)")
    both("obs-log", type=str, default=os.environ.get("BNSGCN_OBS_LOG", ""),
         help="structured JSONL event log path (rank-tagged; ranks > 0 "
              "write PATH.r<rank>; size-bounded, $BNSGCN_OBS_MAX_MB)")
    both("obs-dir", type=str, default="",
         help="post-mortem dir for watchdog/divergence dumps and SIGUSR1 "
              "snapshots (default {ckpt_path}/postmortem)")
    both("strict-exec", action="store_true", default=False,
         help="strict-execution runtime guard: transfer_guard('disallow') "
              "around every hot-loop step plus a compile listener — any "
              "implicit host transfer in the step, or any recompile after "
              "a step variant's first epoch, aborts the run "
              "(StrictExecError). Pairs with the graftlint static gate")
    both("cache-dir", type=str,
         default=os.environ.get("BNSGCN_CACHE_DIR", ""))
    both("edge-chunk", type=int, default=0)
    both("use-pallas", action="store_true", default=False)
    both("spmm-gather", type=str, default="native", choices=["native", "fp8", "int8"])
    both("spmm-dense", type=str, default="native", choices=["native", "int8"])
    both("block-occupancy", type=int, default=0)
    both("block-tile-budget-mb", type=int, default=2048)
    both("block-tile", type=int, default=512)
    p.add_argument("--reorder", type=str, default="off",
                   choices=["auto", "cluster", "off"],
                   help="graph-reordering artifact pass: permute each "
                        "part's rows once at load to concentrate edge mass "
                        "into dense MXU tiles (order cached like layouts; "
                        "outputs stay in global id order; 'auto' applies "
                        "only on measured coverage improvement; 'off' is "
                        "bit-identical)")
    both("ckpt-path", type=str, default="./checkpoint/")
    both("results-path", type=str, default="./results/")
    p.add_argument("--resume", action="store_true")
    both("keep-ckpt", type=int, default=5)
    both("n-nodes", type=int, default=1)
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    d = vars(args).copy()
    if d.get("norm") == "none":
        d["norm"] = None
    valid = {f.name for f in dataclasses.fields(Config)}
    d = {k: v for k, v in d.items() if k in valid}
    return Config(**d)


def parse_config(argv=None) -> Config:
    return config_from_args(create_parser().parse_args(argv))
