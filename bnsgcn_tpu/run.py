"""End-to-end training orchestration (reference train.py:300-456 `run()`).

One Python process drives the whole mesh (SPMD replaces the reference's
process-per-partition fork, main.py:35-50): load or build partition
artifacts, place sharded device data, precompute, then the epoch loop — a
single jitted step per epoch plus host-side timing, logging, background
evaluation, checkpointing and a results file in the reference's format.

On the Reduce(s) column: the reference overlaps its gradient all-reduce with
the backward pass via hooks and side streams and reports the residual
synchronize time (train.py:410-412). Here the reduction is *inside* the
compiled step where XLA overlaps it with backward compute — there is no
separable host-visible reduce phase, so Reduce(s) reports 0; Comm(s) is
measured by a compiled exchange-only microbench on identical inputs.
"""

from __future__ import annotations

import contextlib
import math
import os
import shutil
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu import resilience
from bnsgcn_tpu import strict as strict_mod
from bnsgcn_tpu import tune as tune_mod
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.data.artifacts import (PartitionArtifacts, build_artifacts,
                                       load_artifacts, save_artifacts)
from bnsgcn_tpu.data.datasets import inductive_split, load_data
from bnsgcn_tpu.data.graph import Graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.data.reorder import maybe_reorder
from bnsgcn_tpu.evaluate import evaluate_induc, evaluate_mesh, evaluate_trans
from bnsgcn_tpu.models.gnn import ModelSpec, spec_from_config
from bnsgcn_tpu.parallel import coord as coord_mod
from bnsgcn_tpu.parallel import feat as feat_mod
from bnsgcn_tpu.parallel.mesh import replicated_sharding
from bnsgcn_tpu.parallel.replicas import make_mesh, mesh_desc, slot_desc
from bnsgcn_tpu.trainer import (LAST_BUILD_TIMINGS, build_block_arrays,
                                build_step_fns, init_training,
                                local_part_ids, param_global_norm, place_blocks,
                                place_blocks_local, place_replicated,
                                warm_start_state)
from bnsgcn_tpu.utils import traceparse
from bnsgcn_tpu.utils.timers import EpochTimer, estimate_static_hbm, format_memory_stats


def artifacts_dir(cfg: Config) -> str:
    name = cfg.graph_name or cfg.derive_graph_name()
    return os.path.join(cfg.part_path, name)


def artifact_digest(art) -> str:
    """Content address of the partition: sha1 over (n_b, src, dst), the
    same recipe the layout and reorder caches key by. The continual cycle
    records it in promotion lineage / run_header so a promoted model is
    traceable to the exact mutated artifact it was fine-tuned on."""
    import hashlib
    dg = hashlib.sha1()
    for a in (art.n_b, art.src, art.dst):
        # buffer protocol, not .tobytes(): no transient copy of the
        # (papers100M-scale: multi-GB) edge arrays just to hash them
        dg.update(np.ascontiguousarray(a))
    return dg.hexdigest()[:12]


def prepare_partition(cfg: Config, g: Optional[Graph] = None,
                      force: bool = False, load: bool = True
                      ) -> Optional[PartitionArtifacts]:
    """Offline partitioning step (reference graph_partition, helper/utils.py:73-98):
    skipped when the artifact dir already exists, like the reference's config-
    JSON existence check (:87).

    Large graphs route through the streaming builder (one part resident at a
    time, vectorized passes — the papers100M-scale path; cfg.streaming_artifacts
    'auto' switches at 30M edges). `load=False` (offline partition_cli) writes
    the artifacts without stacking them back into host memory."""
    path = artifacts_dir(cfg)
    if not force and os.path.exists(os.path.join(path, "meta.json")):
        return load_artifacts(path) if load else None
    if g is None:
        g, _, _ = load_data(cfg)
        if cfg.inductive:
            g = g.subgraph(g.train_mask)        # helper/utils.py:76-77
    pid = partition_graph(g, cfg.n_partitions, method=cfg.partition_method,
                          obj=cfg.partition_obj, seed=cfg.seed)
    streaming = (cfg.streaming_artifacts == "always" or
                 (cfg.streaming_artifacts == "auto" and g.n_edges > 30_000_000))
    if streaming:
        from bnsgcn_tpu.data.artifacts import build_artifacts_streaming
        build_artifacts_streaming(g, pid, path, feat_dtype=cfg.feat_storage,
                                  log=print)
        return load_artifacts(path) if load else None
    art = build_artifacts(g, pid)
    save_artifacts(art, path)
    return art


# best-params recovery contract: consolidated in checkpoint.py (PR 7) so the
# serving loader shares the exact same selection/validation entry points
_final_best_payload = ckpt.final_best_payload


def step_variants(fns) -> tuple:
    """Strict-exec step-program variant names the epoch loop can execute
    with these step fns: the `--halo-refresh` pair ('full' at epoch 0 and
    after every cache invalidation, 'cached' in steady state) when the
    cached program exists, else the single 'step' program. The loop below
    derives the per-epoch pick from the cache state; this is the static
    vocabulary — what `--strict-exec` arms per variant and what the
    analysis/ir preflight traces per lever state."""
    return (("full", "cached") if fns.train_step_full is not None
            else ("step",))


def check_mesh_budget(cfg: Config, devices=None) -> None:
    """ONE named config error when R x P x T exceeds the device budget,
    raised before any mesh/axis-specific constructor can fail with its own
    partial message (previously only the replicas path raised, from inside
    make_mesh). Lists which axis to shrink; main.py maps ConfigError to
    exit 2."""
    have = len(devices if devices is not None else jax.devices())
    R, P_, T = max(cfg.replicas, 1), max(cfg.n_partitions, 1), max(cfg.feat, 1)
    need = R * P_ * T
    if need <= have:
        return
    fixes = []
    if T > 1 and R * P_ <= have:
        fixes.append(f"--feat to <= {have // (R * P_)}")
    if R > 1 and P_ * T <= have:
        fixes.append(f"--replicas to <= {have // (P_ * T)}")
    if P_ > have:
        fixes.append(f"--n-partitions to <= {have} (re-partition the graph)")
    if not fixes:
        fixes.append(f"some axis so replicas*parts*feat <= {have}")
    raise ConfigError(
        f"mesh does not fit: --replicas {R} x --n-partitions {P_} x "
        f"--feat {T} needs {need} devices, have {have}; shrink "
        + " or ".join(fixes)
        + (f", or use a CPU mesh via XLA_FLAGS="
           f"--xla_force_host_platform_device_count={need}"))


@dataclass
class RunResult:
    best_val_acc: float = 0.0
    test_acc: float = 0.0
    epoch_time: float = 0.0
    comm_time: float = 0.0
    reduce_time: float = 0.0
    final_loss: float = 0.0
    losses: list = field(default_factory=list)
    memory: str = ""
    overlap_buckets: dict = field(default_factory=dict)
    # --overlap split: trace-derived per-step exchange/interior/frontier/
    # hidden ms means (EpochTimer.bucket_means); empty for fused runs
    rollbacks: list = field(default_factory=list)
    # divergence recoveries this run performed: [{'epoch', 'restart',
    # 'source', 'nonce'}, ...] (resilience.ResilienceManager.rollbacks)


def run_training(cfg: Config, g: Optional[Graph] = None,
                 art: Optional[PartitionArtifacts] = None,
                 devices=None, verbose: bool = True) -> RunResult:
    log = print if verbose else (lambda *a, **k: None)

    multi_host = jax.process_count() > 1
    is_rank0 = jax.process_index() == 0

    # ---- out-of-band rank coordination (multi-host resilience) ----
    # parallel/coord.py: failure verdicts travel OUTSIDE the XLA collectives
    # so a faulting rank can tell its peers instead of hanging them. None
    # under --coord off / single-rank runs — those paths are bit-identical
    # to the uncoordinated loop. --coord-rank/--coord-world run the same
    # layer without jax.distributed (each process a full single-host
    # trainer, coupled only through the coordinator): the subprocess fault
    # harness the CPU container can actually execute.
    coordinator, coord_rank = None, jax.process_index()
    if cfg.resilience == "on" and cfg.coord != "off":
        coordinator, coord_rank, _ = coord_mod.make_coordinator(cfg, log)
        if coordinator is not None and not multi_host:
            # external-rank harness mode: coordination rank 0 owns the
            # checkpoint dir (and host eval), exactly like jax rank 0 does
            # in a real multi-host run
            is_rank0 = coord_rank == 0

    # ---- elastic world size (--elastic on): a heartbeat-detected rank
    # loss becomes a coordinated RESIZE verdict (re-map the P parts onto
    # the survivors via mesh.plan_slots, rebuild step fns, resume from the
    # agreed checkpoint) instead of a CoordTimeout exit. Harness-mode only:
    # a real jax.distributed pod cannot reshape its process grid in place.
    # `joiner` marks a process relaunched AFTER a shrink verdict — it must
    # not replay the pre-loop collectives (those seq-space keys are retired
    # on the survivors) and instead re-enters through the rejoin handshake
    # below the resume block.
    joiner = False
    if cfg.elastic == "on":
        if coordinator is None:
            raise ConfigError(
                "--elastic on needs the rank coordinator: run with "
                "--resilience on and --coord tcp|file (got --coord "
                f"{cfg.coord}, --resilience {cfg.resilience})")
        if multi_host:
            raise ConfigError(
                "--elastic on is harness-mode only (--coord-world/"
                "--coord-rank): a jax.distributed process grid cannot be "
                "resized in place")
        coordinator.enable_elastic(cfg.elastic_min_world)
        if coord_rank != 0:
            joiner = coordinator.detect_rejoin()
            if joiner:
                log(f"[elastic] rank {coord_rank}: rejoining a resized "
                    f"world (lost-rank beacon found)")

    # ---- telemetry bus (obs.py): rank-tagged structured event log +
    # metrics registry. None under --obs off — every emit below is guarded,
    # so off constructs nothing and stays bit-identical (pinned). ----
    obs = obs_mod.make_obs(cfg, rank=coord_rank, log=log)

    # ---- data + eval graphs (train.py:313-319) ----
    # multi-host: only rank 0 ever needs the full undistributed graph (host
    # eval); the other ranks read just their partition artifacts
    val_g = test_g = None
    # transductive mesh eval runs entirely from partition artifacts — the
    # full undistributed graph is only needed for host eval / inductive splits
    trans_mesh_eval = (cfg.eval and cfg.eval_device == "mesh"
                       and not cfg.inductive)
    need_graph_eval = (cfg.eval and not trans_mesh_eval
                       and (is_rank0 or not multi_host))
    need_graph_partition = art is None and not (multi_host or cfg.skip_partition)
    if g is None and (need_graph_eval or need_graph_partition):
        g, _, _ = load_data(cfg)
    if cfg.eval and g is not None:
        if cfg.inductive:
            _, val_g, test_g = inductive_split(g)
        else:
            val_g = test_g = g
    train_g = g.subgraph(g.train_mask) if (cfg.inductive and g is not None) else g

    # ---- mesh + partition artifacts ----
    # --replicas N > 1: each replica row trains the same partitioned graph
    # under an independent BNS draw, gradients are the fused cross-replica
    # mean (parallel/replicas.py). --feat T > 1: the innermost mesh axis
    # shards hidden dimensions T-ways — zero boundary nodes on that axis,
    # halo payloads H/T wide, one feat psum per layer (parallel/feat.py).
    if (cfg.replicas > 1 or cfg.feat > 1) and multi_host:
        raise ValueError(
            "--replicas/--feat > 1 are single-host for now (multi-host "
            "processes map to parts slots only); run with --replicas 1 "
            "--feat 1 across hosts")
    check_mesh_budget(cfg, devices)
    mesh = make_mesh(cfg.n_partitions, cfg.replicas, cfg.feat, devices)
    if multi_host and art is not None:
        n_local = len(local_part_ids(mesh))
        if art.feat.shape[0] != n_local:
            raise ValueError(
                f"multi-host run_training(art=...) needs artifacts holding "
                f"only this process's {n_local} parts "
                f"(load_artifacts(parts=local_part_ids(mesh))), got "
                f"{art.feat.shape[0]} part rows")
    if art is None:
        if multi_host:
            # each process loads only the parts whose mesh slots it hosts
            # (main.py already partitioned on rank 0 behind a barrier)
            mine = local_part_ids(mesh)
            if not mine:
                raise ValueError(
                    f"process {jax.process_index()} hosts no partition: use "
                    f"n_partitions >= {jax.process_count()} x local device "
                    f"count (mesh takes the first n_partitions global devices)")
            art = load_artifacts(artifacts_dir(cfg), parts=mine)
        elif cfg.skip_partition:
            art = load_artifacts(artifacts_dir(cfg))
        elif coordinator is not None:
            # harness mode without --skip-partition: only rank 0 builds;
            # peers wait at a coordinator barrier, then load the finished
            # artifacts — two concurrent builders would tear the shared dir
            # (real multi-host has main.py's XLA barrier for this)
            if coord_rank == 0:
                art = prepare_partition(cfg, train_g)
                coordinator.broadcast("parts-ready", {"ok": 1})
            elif joiner:
                # rejoining rank: the parts-ready broadcast key was retired
                # long ago on the survivors; the artifacts are already on
                # disk from the original build, so load them directly
                art = prepare_partition(cfg, train_g)
            else:
                coordinator.broadcast("parts-ready")
                art = prepare_partition(cfg, train_g)
        else:
            art = prepare_partition(cfg, train_g)
    cfg = cfg.replace(n_feat=art.n_feat, n_class=art.n_class, n_train=art.n_train)
    if (multi_host and cfg.spmm in ("ell", "auto")
            and art.ell_geometry is None):
        # pre-v2 artifacts lack the global ELL geometry a partial load needs
        # (hybrid gcn/graphsage is exempt: its shapes agree via a host-side
        # allgather, no meta.json geometry required — but 'auto' may resolve
        # to ell, which would build per-host tables of different shapes, so
        # it falls back too; GAT on hybrid still needs gat_fwd geometry and
        # falls back to segment attention inside the trainer)
        log("multi-host: artifacts carry no ELL geometry (old format); "
            "falling back to --spmm segment")
        cfg = cfg.replace(spmm="segment")
    # ---- reorder pass (before the layout digest: the digest below hashes
    # the POST-perm edge arrays, so permuted and raw layouts can never
    # alias each other in the cache) ----
    art, ro_resolved, _ro_info = maybe_reorder(cfg, art, log=log, obs=obs)
    cfg = cfg.replace(reorder=ro_resolved)

    # ---- closed-loop comm auto-tuner (--tune, tune.py): fold the launch
    # point of the schedule/anneal into cfg BEFORE the first build so a
    # coarse start (K=4, grad-only) never pays a throwaway compile ----
    _tune_start = None
    tune_mod.validate_mode(cfg, multi_host=multi_host,
                           coordinated=coordinator is not None)
    if cfg.tune != "off":
        _prior = None
        if cfg.tune == "auto" and cfg.tune_prior == "model":
            # --tune-prior model: the graftperf roofline (analysis/perf)
            # predicts the comm fraction from the partition geometry +
            # calibration tables and picks the launch rung, skipping
            # ladder rungs whose wire saving it prices as immaterial. A
            # prediction failure must never kill a run: fall back to the
            # default coarse start and say so.
            try:
                import jax as _jax

                from bnsgcn_tpu.analysis.perf import calibration as _pcal
                from bnsgcn_tpu.analysis.perf import model as _pmod
                _table = _pcal.backend_table(_pcal.load_calibration(),
                                             _jax.default_backend())
                _strat = (cfg.halo_exchange if cfg.halo_exchange in
                          ("padded", "shift", "ragged") else "padded")
                _feat = _pmod.run_features(cfg, art, strategy=_strat)
                _prior = _pmod.model_prior(_feat, _table,
                                           comm_frac=tune_mod.AUTO_COMM_FRAC)
                log(f"[tune] prior: predicted step "
                    f"{_prior['step_s'] * 1e3:.1f} ms, wire "
                    f"{_prior['wire_s'] * 1e3:.2f} ms "
                    f"(comm {_prior['comm_frac']:.1%})")
            except Exception as ex:
                log(f"[tune] model prior unavailable "
                    f"({type(ex).__name__}: {ex}); using ladder start")
        _ch0, _why0 = tune_mod.startup_changes(cfg, prior=_prior)
        if _ch0:
            cfg = cfg.replace(**_ch0)
            _tune_start = (_ch0, _why0)
            log(f"[tune] {_why0}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(_ch0.items())))

    # ---- step functions + device data ----
    spec = spec_from_config(cfg)
    # --cache-dir / $BNSGCN_CACHE_DIR: persist SpMM layout builds (~980 s at
    # bench scale for hybrid) across container wipes. Files are addressed by
    # (graph name, trainer.hybrid_layout_key), the same content keys bench.py
    # uses, so knob changes can never read a stale geometry.
    layout_cache = lc_loaded = None
    if cfg.cache_dir:
        from bnsgcn_tpu.trainer import (ell_layout_key, gat_layout_key,
                                        hybrid_layout_key)
        from bnsgcn_tpu.utils.diskcache import (atomic_dump, sweep_stale_tmp,
                                                try_load)
        os.makedirs(cfg.cache_dir, exist_ok=True)
        # a crashed/preempted writer mid-atomic_dump leaves a torn *.tmp —
        # sweep them on open so the dir can't accumulate garbage
        sweep_stale_tmp(cfg.cache_dir, log)
        gname = cfg.graph_name or cfg.derive_graph_name()
        # content-address the PARTITION, not just its name: layouts are a
        # pure function of (src, dst) — a re-partition under the same graph
        # name (changed seed, random method) or another host's partial-load
        # rows must never read each other's files
        digest = artifact_digest(art)

        def _lc_path(key):
            return os.path.join(
                cfg.cache_dir,
                f"layouts_{gname}_{digest}_{key.replace(':', '-')}.pkl")

        # preload both the fused and (under --overlap split) the ':ovl'
        # split-layout namespaces — build_step_fns may fall back to off,
        # and a downgraded run must still find its fused tables
        keys = {ell_layout_key(cfg.replace(overlap="off")),
                gat_layout_key(cfg),
                hybrid_layout_key(cfg.replace(overlap="off"))}
        if cfg.overlap == "split":
            keys |= {ell_layout_key(cfg), hybrid_layout_key(cfg)}
        layout_cache, lc_loaded = {}, {}
        for key in sorted(keys):
            obj = try_load(_lc_path(key), log)
            if obj is not None:
                layout_cache[key] = obj
                lc_loaded[key] = id(obj)
    elif cfg.tune != "off":
        # no disk cache, but the --tune controller may rebuild the step fns
        # mid-run: an in-memory layout cache makes those rebuilds hit the
        # already-built SpMM layouts (the layout keys do not depend on any
        # tuned lever), so a retune never pays the layout build twice
        layout_cache, lc_loaded = {}, {}
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh,
                                                     layout_cache=layout_cache)
    if obs is not None:
        for _st in LAST_BUILD_TIMINGS:
            obs.emit("layout_build", **_st)
    if cfg.cache_dir and layout_cache is not None:
        for key, obj in layout_cache.items():
            # new or repaired-in-place entries (id changed) get persisted
            if lc_loaded.get(key) != id(obj):
                atomic_dump(obj, _lc_path(key))
                log(f"  layout cache: wrote {_lc_path(key)}")
    np_dtype = np.float32  # norms/feat host dtype; bf16 cast happens on device
    blk_np = build_block_arrays(art, spec.model, dtype=np_dtype)
    blk_np.update(fns.extra_blk)        # ELL SpMM layouts, if enabled
    for k in fns.drop_blk_keys:         # COO unused under ELL: save the HBM
        blk_np.pop(k, None)
    blk = place_blocks_local(blk_np, mesh) if multi_host else place_blocks(blk_np, mesh)
    if cfg.dtype == "bfloat16":
        blk["feat"] = blk["feat"].astype(jnp.bfloat16)
    tables = place_replicated(tables, mesh)
    tables_full_d = place_replicated(tables_full, mesh)
    tables_refresh_d = (place_replicated(fns.tables_refresh, mesh)
                        if fns.tables_refresh is not None else None)
    if spec.use_pp:
        out = fns.precompute(blk, tables_full_d)
        if cfg.dtype == "bfloat16":
            out = out.astype(jnp.bfloat16)
        if spec.model == "gat":
            blk["feat0_ext"] = out
        else:
            blk["feat"] = out
    from bnsgcn_tpu.parallel.halo import wire_bytes
    nb = 2 if cfg.dtype == "bfloat16" else 4
    # Comm column context: the halo label is the RESOLVED strategy (under
    # --halo-exchange auto the pick was logged by build_step_fns; 'auto->'
    # here keeps the per-run record self-describing). --overlap split tags
    # the label '+ovl' the same way; the EXCHANGE itself is unchanged by the
    # split (same spec, same per-layer bytes, still one forward + one
    # backward hop per layer), so wire_bytes below is reported exactly once
    # — the interior/frontier split must never double-count it.
    halo_label = (f"auto->{hspec.strategy}"
                  if cfg.halo_exchange == "auto" else hspec.strategy)
    if fns.overlap == "split":
        halo_label += "+ovl"
    if fns.n_replicas > 1:
        halo_label += f"+rep{fns.n_replicas}"
    if fns.n_feat > 1:
        halo_label += f"+feat{fns.n_feat}"
    use_refresh = fns.train_step_full is not None   # --halo-refresh K > 1
    grad_only = fns.halo_mode == "grad-only"
    if grad_only:
        halo_label += "+go"
    elif use_refresh:
        halo_label += f"+hr{fns.halo_refresh}"
    if cfg.reorder != "off":
        halo_label += "+ro"
    # wire bytes are PER REPLICA per device (each replica row runs its own
    # parts-axis exchange) and reported exactly once — the replica axis adds
    # one fused gradient all-reduce per step, never more halo traffic. The
    # feat axis SHRINKS the parts-axis payload instead: a feat-sharded
    # layer's exchange ships its H/T activation slice, so the per-axis
    # numbers below drop ~T x vs feat=1 (GAT exchanges stay full-width —
    # that model shards heads, not the exchanged input).
    T_fe = fns.n_feat

    def _wire_w(fin):
        # GAT exchanges its full-width input (it shards heads, not the
        # exchanged activations); GCN/SAGE ship the H/T slice
        return feat_mod.shard_width(fin, T_fe,
                                    spec.model in ("gcn", "graphsage"))

    per_rep = "/replica" if fns.n_replicas > 1 else ""
    hid_w = _wire_w(cfg.n_hidden)
    feat_note = (f" (H/T={hid_w} of {cfg.n_hidden} on the parts wire: "
                 f"~{cfg.n_hidden // max(hid_w, 1)}x less than feat=1)"
                 if hid_w != cfg.n_hidden else "")
    log(f"Mesh: {mesh_desc(mesh)} | pad_inner={art.pad_inner} "
        f"pad_boundary={art.pad_boundary} pad_send={hspec.pad_send} "
        f"edges/part={art.pad_edges} | halo {halo_label}/{hspec.wire}: "
        f"{wire_bytes(hspec, hid_w, nb) / 1e6:.2f} MB/exchange/device{per_rep} "
        f"at hidden width {cfg.n_hidden}" + feat_note
        + ("" if spec.use_pp or spec.model == "gat" else
           f" ({wire_bytes(hspec, _wire_w(max(cfg.n_feat, 1)), nb) / 1e6:.2f}"
           f" MB at layer-0 feature width {cfg.n_feat})"))

    # one machine-readable run header: everything the per-run log line above
    # says, plus the config the run is actually executing — the record
    # obs_report joins epochs/lifecycle events against
    halo_wire_mb = wire_bytes(hspec, hid_w, nb) / 1e6
    # --halo-refresh K: halo_wire_mb above is the PEAK (full-refresh-epoch)
    # cost; steady-state (cache-hit) epochs ship only the ~1/K partial
    # exchange. Both numbers go to the log and the run_header — reporting
    # just the peak was the old header's lie for duty-cycled runs.
    # grad-only ships nothing per step at all.
    steady_wire_mb = halo_wire_mb
    if grad_only:
        steady_wire_mb = 0.0
        log("  halo grad-only: 0.00 MB/exchange steady-state (no activation "
            "exchange; the gradient all-reduce is the only collective)")
    elif use_refresh:
        from bnsgcn_tpu.parallel.halo import make_refresh_spec
        hspec_r, _ = make_refresh_spec(
            art.n_b, art.pad_inner, art.pad_boundary, cfg.sampling_rate,
            fns.halo_refresh, strategy=hspec.strategy, wire=hspec.wire)
        steady_wire_mb = wire_bytes(hspec_r, hid_w, nb) / 1e6
        log(f"  halo refresh K={fns.halo_refresh}: peak {halo_wire_mb:.2f} "
            f"MB/exchange (full-refresh epochs), steady-state "
            f"{steady_wire_mb:.2f} MB "
            f"({steady_wire_mb / max(halo_wire_mb, 1e-12):.0%} of peak)")
    if obs is not None:
        # continual-cycle provenance: only attached when a cycle is live, so
        # every pre-continual run_header stays byte-identical
        continual_hdr = ({"warm_start": cfg.warm_start,
                          "cycle_nonce": int(cfg.cycle_nonce),
                          "artifact_digest": artifact_digest(art)}
                         if (cfg.warm_start or cfg.cycle_nonce) else None)
        obs.emit(
            "run_header", mesh=mesh_desc(mesh),
            **({"continual": continual_hdr} if continual_hdr else {}),
            replicas=int(fns.n_replicas), parts=int(cfg.n_partitions),
            feat=int(fns.n_feat), halo=halo_label, wire=hspec.wire,
            wire_mb_per_exchange=round(halo_wire_mb, 4),
            wire_mb_steady=round(steady_wire_mb, 4),
            halo_refresh=int(fns.halo_refresh), halo_mode=fns.halo_mode,
            partition={"pad_inner": int(art.pad_inner),
                       "pad_boundary": int(art.pad_boundary),
                       "pad_send": int(hspec.pad_send),
                       "edges_per_part": int(art.pad_edges)},
            config={k: getattr(cfg, k) for k in (
                "dataset", "graph_name", "model", "n_layers", "n_hidden",
                "heads", "sampling_rate", "lr", "dtype", "spmm",
                "use_pallas", "spmm_gather", "spmm_dense", "halo_exchange",
                "halo_wire", "halo_refresh", "halo_mode", "overlap",
                "reorder", "tune", "tune_schedule", "tune_prior",
                "n_epochs", "log_every", "seed",
                "inductive", "use_pp", "resilience", "coord")})

    # ---- --tune controller, bound to the RESOLVED levers (post
    # startup fold, post `--halo-exchange auto` pick): the base every
    # later rewind/restore diffs against ----
    tuner = None
    if cfg.tune != "off":
        tuner = tune_mod.Tuner(cfg, levers={
            "halo_refresh": int(fns.halo_refresh),
            "halo_mode": fns.halo_mode,
            "halo_exchange": fns.halo_strategy,
            "halo_wire": hspec.wire,
        }, log=log)
        if _tune_start is not None:
            _ent0 = tuner.record_startup(*_tune_start)
            if obs is not None:
                obs.emit("tune_decision", **_ent0)
        if cfg.tune == "auto":
            from bnsgcn_tpu.parallel.halo import retune_strategy
            # precompute the byte-estimate strategy re-pick once — the
            # partition geometry it reads never changes mid-run
            tuner.strategy_alt = retune_strategy(
                art.n_b, art.pad_inner, art.pad_boundary, cfg.sampling_rate,
                current=fns.halo_strategy, wire=hspec.wire)

    # ---- mesh-distributed eval resources (--eval-device mesh) ----
    mesh_eval = cfg.eval and cfg.eval_device == "mesh"
    eval_val = None                    # (fns, blk, tables_full_d, art)

    def _eval_resources(graph, name_suffix):
        if not cfg.inductive:
            # same graph as training: share every placed training array and
            # swap only 'feat' for the raw (non-precomputed, f32) features
            b = dict(blk)
            raw = {"feat": build_block_arrays(art, spec.model)["feat"]}
            if multi_host:
                b["feat"] = place_blocks_local(raw, mesh)["feat"]
            else:
                b["feat"] = jax.device_put(jnp.asarray(raw["feat"]),
                                           blk["inner_mask"].sharding)
            return fns, b, tables_full_d, art
        base = cfg.graph_name or cfg.derive_graph_name()
        cfg_e = cfg.replace(graph_name=base + name_suffix)
        if multi_host:
            # rank 0 (which holds the eval subgraph) partitions it; everyone
            # else waits at the barrier, then loads only its own parts
            from jax.experimental import multihost_utils
            if is_rank0 and not os.path.exists(
                    os.path.join(artifacts_dir(cfg_e), "meta.json")):
                prepare_partition(cfg_e, graph, load=False)  # build+save only when missing
            multihost_utils.sync_global_devices(f"bnsgcn_eval_parts{name_suffix}")
            # agree across ranks so EVERY process fails fast (a rank that has
            # the files must not sail into the next collective alone)
            have = int(os.path.exists(
                os.path.join(artifacts_dir(cfg_e), "meta.json")))
            all_have = np.asarray(
                multihost_utils.process_allgather(np.int64(have))).min()
            if not all_have:
                raise FileNotFoundError(
                    f"eval partition artifacts missing at {artifacts_dir(cfg_e)} "
                    f"on at least one host: part_path must be a shared "
                    f"filesystem, or pre-distribute the eval artifact dirs "
                    f"(partition_cli --inductive --eval-device mesh builds "
                    f"them), or use --eval-device host")
            art_e = load_artifacts(artifacts_dir(cfg_e),
                                   parts=local_part_ids(mesh))
        else:
            art_e = prepare_partition(cfg_e, graph)
        # the training cfg already carries the RESOLVED reorder mode, so the
        # eval subgraph gets the same treatment (its own perm — row ids are
        # per-artifact) and gather_parts' global_nid indexing undoes it
        art_e, _, _ = maybe_reorder(cfg_e.replace(reorder=cfg.reorder),
                                    art_e, log=log)
        fns_e, _, _, tf = build_step_fns(cfg, spec, art_e, mesh)
        b = build_block_arrays(art_e, spec.model)
        b.update(fns_e.extra_blk)
        for k in fns_e.drop_blk_keys:
            b.pop(k, None)
        placed = place_blocks_local(b, mesh) if multi_host else place_blocks(b, mesh)
        return fns_e, placed, place_replicated(tf, mesh), art_e

    if mesh_eval:
        eval_val = _eval_resources(val_g, "-val")

    # ---- model / optimizer init, optionally resumed ----
    seed = cfg.seed
    if joiner:
        # rejoining rank: the seed broadcast key is long retired on the
        # survivors; rank 0's bootstrap facts live under the never-retired
        # el/boot key exactly so late joiners can adopt the run seed
        seed = int(coordinator.boot_info()["seed"])
    elif coordinator is not None and not multi_host:
        # harness-mode analogue of main.py's XLA seed broadcast: every rank
        # must adopt rank 0's (possibly randomized) seed or the shared-PRNG
        # sampling/dropout/init streams desync across ranks
        seed = int(coordinator.broadcast(
            "seed", {"seed": seed} if coord_rank == 0 else None)["seed"])
    if cfg.elastic == "on" and coord_rank == 0:
        coordinator.publish_boot({"seed": seed})
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    params, state, opt_state = init_training(cfg, spec, mesh, seed=seed, dtype=dtype)
    # every resume/rollback below restores HOST trees back onto the mesh;
    # feat-sharded meshes re-place them under the captured template
    # shardings (weights + Adam moments sharded over 'feat' — checkpoints
    # themselves are always saved unsharded via jax.device_get, so they
    # stay feat-invariant); feat=1 keeps the historical replicated
    # placement verbatim, including the multi-host local-data path
    if cfg.feat > 1:
        _p_sh = jax.tree.map(lambda x: x.sharding, params)
        _o_sh = jax.tree.map(lambda x: x.sharding, opt_state)

        def place_p(h):
            return feat_mod.place_like(h, _p_sh)

        def place_o(h):
            return feat_mod.place_like(h, _o_sh)
    else:
        def place_p(h):
            return place_replicated(h, mesh)
        place_o = place_p
    start_epoch, best_acc, best_params = 0, 0.0, None
    retry_nonce = 0     # cumulative divergence-rollback count: folds the
                        # sampling/dropout key streams (resilience.py) and
                        # round-trips through checkpoint extra so a resumed
                        # run continues the post-rollback streams bit-for-bit
    resize_nonce = 0    # cumulative elastic-shrink count (--elastic on):
                        # folds the same streams under a disjoint high-bit
                        # domain so a resized world resamples its boundary
                        # sets; 0 (never shrunk) is bit-identical. Grows
                        # never change it — rejoin replays deterministically.
    tune_state = None   # --tune controller history from checkpoint extra:
                        # only the single-host path reads it (auto is
                        # single-process; a multi-rank schedule run
                        # reconstructs the same history from the schedule
                        # text, which every rank already has)
    if cfg.resume and coordinator is not None and not joiner:
        # ---- rank-consistent recovery: rank 0 WALKS the chain, everyone
        # else loads exactly rank 0's choice. Two ranks walking
        # independently can pick DIFFERENT files (one rank's newest local
        # copy torn, the other's fine) and silently desync the epoch
        # schedule; and the uncoordinated multi-host path broadcast rank
        # 0's epoch without ever checking the peers could load it. Every
        # rank acks loadability through the coordinator BEFORE any state is
        # adopted — a torn local file aborts the resume loudly on ALL
        # ranks (exit 78), not mid-epoch. ----
        choice = None
        if coord_rank == 0:
            found = ckpt.latest_valid_checkpoint(cfg, log=log)
            if found:
                path0, payload0 = found
                _rx0 = ckpt.resilience_extra(payload0)
                choice = {"have": 1, "file": os.path.basename(path0),
                          "epoch": int(payload0["epoch"]) + 1,
                          "seed": int(payload0.get("seed", seed)),
                          "nonce": _rx0["retry_nonce"],
                          "rnonce": _rx0["resize_nonce"],
                          "best_acc": float(payload0["best_acc"])}
            else:
                choice = {"have": 0}
        choice = coordinator.broadcast("resume-choice", choice)
        if choice["have"]:
            cpath = os.path.join(cfg.ckpt_path, choice["file"])
            # one load per rank, reused for the restore below: rank 0's walk
            # above already read + checksummed its payload (multi-GB at
            # papers100M scale — never read the same file twice); each peer
            # loads its local copy once, and the load IS the ack
            payload1, err = (payload0, None) if coord_rank == 0 else (None, None)
            if coord_rank != 0:
                payload1, err = ckpt.load_or_error(cpath)
                if err is not None and multi_host and not os.path.exists(cpath):
                    # no local copy at all: fine on a real pod — the state
                    # arrives via the rank-0 XLA broadcast below. A PRESENT
                    # but torn copy is never fine: this rank's disk lies.
                    err = None
            all_ok, fails = coordinator.gather_ok("resume", err is None,
                                                  err or "")
            if not all_ok:
                raise coord_mod.CoordAbort(
                    "resume aborted by agreement: rank(s) cannot load the "
                    f"chosen checkpoint {choice['file']!r}: "
                    + "; ".join(f"rank {r}: {d}"
                                for r, d in sorted(fails.items())))
            seed = int(choice["seed"])
            retry_nonce = int(choice["nonce"])
            resize_nonce = int(choice.get("rnonce", 0))
            start_epoch = int(choice["epoch"])
            best_acc = float(choice["best_acc"])
            if multi_host:
                # state still travels the proven XLA broadcast: rank 0
                # restores its validated payload, peers receive the trees
                from jax.experimental import multihost_utils
                host = (ckpt.restore_into(payload1, jax.device_get(params),
                                          jax.device_get(opt_state),
                                          jax.device_get(state))
                        if is_rank0 else
                        (jax.device_get(params), jax.device_get(opt_state),
                         jax.device_get(state)))
                host = multihost_utils.broadcast_one_to_all(host)
            else:
                host = ckpt.restore_into(payload1, jax.device_get(params),
                                         jax.device_get(opt_state),
                                         jax.device_get(state))
            params = place_p(host[0])
            opt_state = place_o(host[1])
            state = place_replicated(host[2], mesh)
            log(f"Resumed (agreed via coordinator) from {choice['file']} at "
                f"epoch {start_epoch}")
            if best_acc > 0:
                # best-params recovery, same contract as the uncoordinated
                # paths: the final ckpt must carry the matching best_acc or
                # best tracking restarts. One load per participating rank
                # (multi-host: rank 0 only — peers receive the XLA
                # broadcast; harness mode: every rank restores its local
                # copy), reused for both the best_acc probe and the
                # restore. The ranks AGREE before adopting anything, so a
                # torn/stale local copy on one harness rank degrades best
                # tracking on ALL ranks instead of crashing that rank or
                # desyncing the final eval.
                payf = (_final_best_payload(cfg, best_acc, log)
                        if coord_rank == 0 or not multi_host else None)
                if multi_host:
                    rec = coordinator.broadcast(
                        "resume-best",
                        {"recovered": int(payf is not None)}
                        if coord_rank == 0 else None)
                    recovered = bool(rec["recovered"])
                else:
                    recovered, _ = coordinator.gather_ok(
                        "resume-best", payf is not None)
                if recovered and multi_host:
                    from jax.experimental import multihost_utils
                    bp = (ckpt.restore_into(payf, jax.device_get(params))[0]
                          if is_rank0 else jax.device_get(params))
                    best_params = multihost_utils.broadcast_one_to_all(bp)
                elif recovered:
                    best_params = ckpt.restore_into(
                        payf, jax.device_get(params))[0]
                else:
                    best_acc = 0.0
    elif cfg.resume and multi_host:
        # rank 0 reads (and integrity-validates) the checkpoint; everything
        # restored must be broadcast so all processes drive the SPMD loop
        # over the same epoch range
        from jax.experimental import multihost_utils
        payload = None
        if is_rank0:
            found = ckpt.latest_valid_checkpoint(cfg, log=log)
            if found:
                payload = found[1]
        # broadcast [next_epoch, saved_seed, retry_nonce, resize_nonce]
        # together: the resumed run must continue the checkpoint's
        # BNS-sampling/dropout streams, and every process must agree on
        # them (shared-PRNG invariant)
        _rx = ckpt.resilience_extra(payload) if payload is not None else {
            "retry_nonce": 0, "resize_nonce": 0}
        have, saved_seed, saved_nonce, saved_rnonce = (
            int(x) for x in multihost_utils.broadcast_one_to_all(
                np.asarray(
                    [0 if payload is None else int(payload["epoch"]) + 1,
                     seed if payload is None else int(payload.get("seed", seed)),
                     _rx["retry_nonce"], _rx["resize_nonce"]],
                    dtype=np.int64)))
        if int(have) > 0:
            seed = saved_seed
            retry_nonce = saved_nonce
            resize_nonce = saved_rnonce
            host = ckpt.restore_into(payload, jax.device_get(params),
                                     jax.device_get(opt_state),
                                     jax.device_get(state)) if is_rank0 else (
                jax.device_get(params), jax.device_get(opt_state),
                jax.device_get(state))
            host = multihost_utils.broadcast_one_to_all(host)
            params = place_p(host[0])
            opt_state = place_o(host[1])
            state = place_replicated(host[2], mesh)
            start_epoch = int(have)
            best_acc = float(multihost_utils.broadcast_one_to_all(np.float64(
                payload["best_acc"] if payload else 0.0)))
            # recover best params (rank 0 reads the matching final ckpt, all
            # ranks receive them — the final mesh test eval is a collective);
            # no match -> restart best tracking, same as single-host
            fp = (_final_best_payload(cfg, best_acc, log)
                  if is_rank0 and best_acc > 0 else None)
            recovered = int(multihost_utils.broadcast_one_to_all(
                np.int64(fp is not None)))
            if best_acc > 0 and recovered:
                bp = (ckpt.restore_into(fp, jax.device_get(params))[0]
                      if is_rank0 else jax.device_get(params))
                best_params = multihost_utils.broadcast_one_to_all(bp)
            elif best_acc > 0:
                best_acc = 0.0
            log(f"Resumed (broadcast from rank 0) at epoch {start_epoch}")
    elif cfg.resume:
        # latest_valid_checkpoint walks past corrupt/torn files: a bad
        # newest checkpoint costs the epochs since the previous periodic
        # save instead of crashing the resume
        found = ckpt.latest_valid_checkpoint(cfg, log=log)
        if found:
            latest, payload = found
            p, o, s = ckpt.restore_into(payload, jax.device_get(params),
                                        jax.device_get(opt_state),
                                        jax.device_get(state))
            params = place_p(p)
            opt_state = place_o(o)
            state = place_replicated(s, mesh)
            start_epoch = int(payload["epoch"]) + 1
            best_acc = float(payload["best_acc"])
            # adopt the checkpoint's seed: main.py re-randomizes cfg.seed per
            # launch, but a resumed run must continue the saved sampling and
            # dropout streams (checkpoint.py's round-trip contract)
            seed = int(payload.get("seed", seed))
            _rx = ckpt.resilience_extra(payload)
            retry_nonce = _rx["retry_nonce"]
            resize_nonce = _rx["resize_nonce"]
            tune_state = (payload.get("extra") or {}).get("tune")
            log(f"Resumed from {latest} at epoch {start_epoch}")
            # recover the best-so-far params (final ckpt) so a resumed run that
            # never beats the old best still saves/evaluates a best model
            # (_final_best_payload owns the matching-best_acc contract)
            fp = (_final_best_payload(cfg, best_acc, log)
                  if best_acc > 0 else None)
            if fp is not None:
                best_params = ckpt.restore_into(fp, jax.device_get(params))[0]
            elif best_acc > 0:
                best_acc = 0.0      # no matching best params: restart tracking

    if cfg.warm_start:
        # continual-cycle fine-tune entry: params + BN state come from the
        # serving checkpoint, the optimizer stays fresh, the epoch counter
        # starts at 0 — a different contract from --resume (which continues
        # one run's own history), so combining them is a named config error
        # rather than a silent winner
        if cfg.resume:
            raise ConfigError(
                "--warm-start and --resume are mutually exclusive: resume "
                "continues a run's own optimizer/epoch history, warm start "
                "re-initializes both from another run's weights")
        p, s = warm_start_state(cfg, params, state, log=log)
        params = place_p(p)
        state = place_replicated(s, mesh)

    # Both keys derive from cfg.seed: every process of a multi-host run MUST
    # agree on the sampling key or the shared-PRNG BNS exchange desyncs
    # (main.py broadcasts the randomized seed from process 0).
    base_sample_key = jax.random.key(seed)
    base_drop_key = jax.random.key(seed + 1)
    if cfg.cycle_nonce:
        # continual-cycle refold (the retry-nonce pattern one level up):
        # each fine-tune cycle draws fresh BNS/dropout streams instead of
        # replaying cycle 0's schedule on a mutated graph. The high-bit
        # offset keeps the cycle fold domain disjoint from the small
        # positive divergence-retry folds applied on top (fold_in data is
        # uint32); nonce 0 is bit-identical.
        cyc = (1 << 31) | (int(cfg.cycle_nonce) & 0x7FFFFFFF)
        base_sample_key = jax.random.fold_in(base_sample_key, cyc)
        base_drop_key = jax.random.fold_in(base_drop_key, cyc)

    def _fold_keys(nonce: int, rnonce: int = 0):
        """Retry-nonce fold of the sampling/dropout streams: after the n-th
        divergence rollback every subsequent epoch draws from fold_in(base,
        n), so the retried epoch resamples its BNS boundary sets (PAPER §3:
        a diverged epoch is cheap to retry under a fresh fold) instead of
        deterministically re-diverging. nonce 0 — every run that never
        rolled back — is the historical keys, bit-identical.

        `rnonce` is the elastic resize nonce, folded on top under the
        (1 << 30) high-bit domain — disjoint from both the small-int retry
        folds and the (1 << 31) continual-cycle folds — so a shrunk world
        draws fresh boundary sets instead of replaying the schedule that
        straddled the loss; rnonce 0 (and every grow, which keeps the
        nonce) stays on the unfolded streams."""
        sk, dk = base_sample_key, base_drop_key
        if rnonce:
            rdom = (1 << 30) | (int(rnonce) & 0x3FFFFFFF)
            sk = jax.random.fold_in(sk, rdom)
            dk = jax.random.fold_in(dk, rdom)
        if nonce:
            sk, dk = (jax.random.fold_in(sk, nonce),
                      jax.random.fold_in(dk, nonce))
        if cfg.strict_exec and jax.process_count() == 1:
            # --strict-exec: commit the keys to the mesh up front. The
            # transfer guard treats the lazy first-use resharding of an
            # uncommitted host-born array as an implicit transfer, so the
            # one-time placement happens here, outside any guarded step.
            sh = replicated_sharding(mesh)
            sk, dk = jax.device_put(sk, sh), jax.device_put(dk, sh)
        return sk, dk

    sample_key, drop_key = _fold_keys(retry_nonce, resize_nonce)

    # ---- resilience subsystem (divergence rollback, preemption-safe
    # shutdown, hung-step watchdog, fault injection) ----
    resil = None
    if cfg.resilience == "on" and (not multi_host or coordinator is not None):
        resil = resilience.ResilienceManager(cfg, log, start_epoch=start_epoch,
                                             retry_nonce=retry_nonce,
                                             resize_nonce=resize_nonce,
                                             coord=coordinator, obs=obs)
        # host snapshot of the fresh/resumed state: the rollback target
        # until the first periodic checkpoint exists (under coordination,
        # every rank keeps one — the '<initial state>' source restores it
        # rank-locally, params being replicated)
        resil.set_initial_snapshot(jax.device_get(params),
                                   jax.device_get(opt_state),
                                   jax.device_get(state))
        resil.start()
    elif cfg.resilience == "on":
        log("[resilience] multi-host run with --coord off: in-process "
            "divergence rollback/watchdog disabled (agreed abort/rollback "
            "needs the rank coordinator — drop --coord off); the "
            "checkpoint integrity chain still protects rank-0 resume")
    if resil is None and (cfg.inject or os.environ.get("BNSGCN_FAULT")):
        log("[resilience] WARNING: --inject is armed but the resilience "
            "loop is disabled here — no fault will fire")

    os.makedirs(cfg.ckpt_path, exist_ok=True)
    os.makedirs(cfg.results_path, exist_ok=True)
    result_file = os.path.join(
        cfg.results_path,
        "%s_n%d_p%.2f.txt" % (cfg.dataset, cfg.n_partitions, cfg.sampling_rate))

    timer = EpochTimer(warmup=5)
    pool = ThreadPoolExecutor(max_workers=1)     # async eval (train.py:370,437-441)
    pending = None
    comm_t = 0.0
    res = RunResult()
    # widths of the per-layer exchanges: hidden-wide for layers >= 1, and a
    # raw-feature-wide layer-0 exchange when use_pp is off; feat-sharded
    # layers ship their H/T slice, so the microbench must too
    exch_widths = [_wire_w(cfg.n_hidden)] * max(spec.n_graph_layers - 1, 0)
    if not spec.use_pp and spec.model != "gat" and spec.n_graph_layers > 0:
        exch_widths.append(_wire_w(max(cfg.n_feat, 1)))
    if grad_only:
        # no per-step activation exchange exists: an exchange microbench
        # would report a collective the training step never runs
        exch_widths = []

    def _comm_bench(w):
        """One exchange-microbench call at width w — the partial-refresh
        geometry when the run is in steady state (K > 1), else the full
        exchange. This is the sampled Comm(s) twin of what the step on the
        wire actually does."""
        if use_refresh:
            return fns.exchange_only_refresh(blk, tables_refresh_d,
                                             jnp.uint32(epoch), sample_key,
                                             width=w)
        return fns.exchange_only(blk, tables, jnp.uint32(epoch), sample_key,
                                 width=w)

    # compile the comm microbenches outside the timed region
    epoch = 0
    for w in set(exch_widths):
        _comm_bench(w).block_until_ready()

    # profiler window (SURVEY §5.1 upgrade: the reference's wall-clock comm
    # spans are meaningless under XLA; named traces are the TPU equivalent),
    # clamped into the epochs this run actually executes
    # +2 past start_epoch: a resumed run compiles on its first executed
    # epoch, and a step that compiles INSIDE the trace window records no
    # device ops on XLA:CPU (observed: 1 launch, 0 collective events) —
    # the window must hold only post-compile steps
    prof_start = max(timer.warmup + 1, start_epoch + 2)
    prof_stop = min(prof_start + 3, cfg.n_epochs - 1)
    tracing = False
    # The Comm(s) microbench overstates the real in-step collective cost by
    # 1.5-26x (hardware cross-check, hw_logs/trace_comm_table.log: host
    # dispatch dominates for small quantized payloads — the int8 wire's
    # microbench reads 26x its traced in-step exchange). The reference's
    # column is a direct in-step measurement (helper/timer/comm_timer.py:
    # 21-25), so ours must be too: trace a short window (the user's
    # --profile-dir if given, else an auto temp dir on rank 0) and derive
    # per-epoch in-step exchange/reduce from the device collective spans
    # (utils/traceparse.step_comm_per_epoch). Until the window closes the
    # microbench prints, tagged [sampled]; after it, [traced] numbers.
    # Single-process only: the trace stop/serialize/parse stalls THIS rank
    # between epochs while its peers run ahead into the next collective —
    # XLA:CPU's rendezvous watchdog (default ~40 s) then terminates them
    # (observed as test_multihost subprocess timeouts). Multi-host runs
    # keep the [sampled] microbench column; --profile-dir is still honored
    # there for explicit traced sessions.
    auto_trace_dir = None
    trace_dir = cfg.profile_dir
    if (not trace_dir and cfg.comm_trace and not multi_host
            and prof_stop > prof_start):
        auto_trace_dir = tempfile.mkdtemp(prefix="bnsgcn_commtrace_")
        trace_dir = auto_trace_dir
    comm_traced = reduce_traced = None

    def _eval_job(e, thunk):
        """Async host eval wrapper: a raise inside the thread must NOT kill
        training a full log_every later when .result() re-raises — label the
        failure with the epoch it belongs to and let the consumer log it and
        keep training (best-acc tracking just skips that sample)."""
        try:
            return e, thunk(), None
        except Exception as ex:     # noqa: BLE001 — every eval failure is soft
            return e, None, ex

    def _drain_eval(fut):
        """(params, acc) from a finished eval future, or None on failure."""
        e, out, err = fut.result()
        if err is not None:
            log(f"[resilience] host eval for epoch {e} failed "
                f"({type(err).__name__}: {err}); continuing training")
            return None
        if obs is not None:
            obs.emit("eval", epoch=e, val_acc=round(float(out[1]), 6))
        return out

    loss = jnp.zeros(())
    loss_f = 0.0
    trace_done = False          # one trace window per run, even across rollbacks
    # on-demand profiling (SIGUSR1, obs on): a bounded profiler window into
    # the post-mortem dir, captured WITHOUT stopping training
    usr1_tracing, usr1_stop, usr1_dir = False, -1, None
    loss_base = start_epoch     # epoch of res.losses[0]: a rollback behind the
                                # resume point (newer ckpts all corrupt) rebases
                                # the list instead of corrupting its indexing
    epoch = start_epoch
    # --strict-exec: runtime proof the steady-state step is clean — a
    # transfer guard around every step (implicit host transfer = error)
    # plus a compile listener (recompile after a variant's first guarded
    # step = error). The loss fetch goes through strict.fetch (audited
    # explicit device_get); the per-epoch uint32 upload is hoisted before
    # the guard below.
    strict = strict_mod.StrictExec(obs=obs, log=log) if cfg.strict_exec \
        else None
    # --halo-refresh cache state: None means the next step runs the
    # full-refresh geometry and rebuilds the cache. Starts invalid (fresh run
    # OR resume — checkpoints never hold the cache) and is re-invalidated at
    # every rollback, which is what keeps --resume/rollback deterministic.
    halo_cache = None
    cache_reason = "resume" if start_epoch > 0 else "start"
    # --elastic on: the part -> hosting-slot map agreed at the last RESIZE
    # verdict (mesh.plan_slots over the survivors). None until a shrink;
    # threaded into build_step_fns so rebuilt HaloSpecs carry the layout
    # (host-side metadata only — the traced program is slot-invariant).
    slot_map = None

    def _ckpt_extra():
        """Checkpoint `extra` payload: retry nonce + (under --tune) the
        controller's sticky decision history, so a resumed run replays the
        same schedule deterministically. The elastic resize nonce rides
        along only when it could matter (--elastic on, or a nonzero count
        inherited through resume) so pre-elastic checkpoints stay
        byte-identical."""
        ex = {"retry_nonce": retry_nonce}
        if cfg.elastic == "on" or resize_nonce:
            ex["resize_nonce"] = resize_nonce
        if tuner is not None:
            ex["tune"] = tuner.state_dict()
        return ex

    # ---- --tune actuation: rebuild the comm stack at an epoch boundary.
    # build_step_fns hits the shared layout cache (the SpMM layout keys do
    # not depend on any tuned lever), the halo cache is invalidated so the
    # next epoch is a logged full refresh, strict-exec's per-variant compile
    # allowance is re-armed (a retune is the one sanctioned recompile), and
    # the comm microbench is recompiled HERE, outside the timed region. ----
    retune_cool = -1    # epochs <= this carry retune compiles in dt: excluded
                        # from the timer/histogram like warmup epochs

    def _apply_tune(changes, reason, trigger, at_epoch):
        nonlocal cfg, fns, hspec, tables, tables_full_d, tables_refresh_d
        nonlocal halo_label, halo_wire_mb, steady_wire_mb
        nonlocal use_refresh, grad_only, exch_widths
        nonlocal halo_cache, cache_reason, retune_cool
        from bnsgcn_tpu.parallel.halo import make_refresh_spec, wire_bytes
        cfg = cfg.replace(**changes)
        fns, hspec, tb, tbf = build_step_fns(cfg, spec, art, mesh,
                                             layout_cache=layout_cache,
                                             slot_map=slot_map)
        tables = place_replicated(tb, mesh)
        tables_full_d = place_replicated(tbf, mesh)
        tables_refresh_d = (place_replicated(fns.tables_refresh, mesh)
                            if fns.tables_refresh is not None else None)
        use_refresh = fns.train_step_full is not None
        grad_only = fns.halo_mode == "grad-only"
        halo_label = hspec.strategy
        if fns.overlap == "split":
            halo_label += "+ovl"
        if fns.n_replicas > 1:
            halo_label += f"+rep{fns.n_replicas}"
        if fns.n_feat > 1:
            halo_label += f"+feat{fns.n_feat}"
        if grad_only:
            halo_label += "+go"
        elif use_refresh:
            halo_label += f"+hr{fns.halo_refresh}"
        if cfg.reorder != "off":
            halo_label += "+ro"
        halo_wire_mb = wire_bytes(hspec, hid_w, nb) / 1e6
        steady_wire_mb = halo_wire_mb
        if grad_only:
            steady_wire_mb = 0.0
        elif use_refresh:
            hspec_r, _ = make_refresh_spec(
                art.n_b, art.pad_inner, art.pad_boundary, cfg.sampling_rate,
                fns.halo_refresh, strategy=hspec.strategy, wire=hspec.wire)
            steady_wire_mb = wire_bytes(hspec_r, hid_w, nb) / 1e6
        # the old cache was built by the OLD exchange geometry: the next
        # epoch must be a full refresh under the new one. resume/rollback/
        # resize keep their own lifecycle reason; fresh decisions log as
        # 'retune'
        halo_cache = None
        cache_reason = (reason if reason in ("resume", "rollback", "resize")
                        else "retune")
        if strict is not None and strict.steps:
            # new compiled programs: each variant's next step legitimately
            # compiles once more (before the first step nothing is armed)
            strict.rearm(reason)
        exch_widths = ([_wire_w(cfg.n_hidden)]
                       * max(spec.n_graph_layers - 1, 0))
        if not spec.use_pp and spec.model != "gat" and spec.n_graph_layers > 0:
            exch_widths.append(_wire_w(max(cfg.n_feat, 1)))
        if grad_only:
            exch_widths = []
        for w in set(exch_widths):
            _comm_bench(w).block_until_ready()
        retune_cool = at_epoch + 1
        if resil is not None:
            resil.watchdog.touch()      # rebuild+compile is boundary work
        log(f"[tune] epoch {at_epoch}: {reason} -> " + ", ".join(
            f"{k}={v}" for k, v in sorted(changes.items()))
            + f" (halo {halo_label}/{hspec.wire}, steady "
              f"{steady_wire_mb:.2f} MB/exchange)")
        if obs is not None:
            # peak rides along with steady: the forced full-refresh epoch
            # right after a retune pays the NEW geometry's peak figure,
            # and gate 4's obs contract checks epochs against DECLARED
            # numbers only
            obs.emit("tune_decision", epoch=int(at_epoch), reason=reason,
                     changes=dict(changes), trigger=dict(trigger or {}),
                     halo=halo_label, wire=hspec.wire,
                     wire_mb_steady=round(steady_wire_mb, 4),
                     wire_mb_peak=round(halo_wire_mb, 4))

    if joiner:
        # ---- rejoin handshake (--elastic on): this process replaces a
        # rank the survivors already voted out of the world. It cannot
        # replay the retired pre-loop collectives; instead it posts a
        # rejoin request against the lost-rank beacon, rank 0 folds the
        # grow verdict into its next agree boundary, and the grant carries
        # everything needed to fall into lockstep — the agreed restore
        # point, both nonces, the part -> rank map, and the survivors'
        # seq/agree-call position. The first collective this rank joins is
        # the grow restore ack, shoulder to shoulder with the survivors'
        # own resize-arm restore. ----
        token = f"{os.getpid():x}-{os.urandom(4).hex()}"
        log(f"[elastic] rank {coord_rank}: requesting rejoin "
            f"(token {token})")
        grant = coordinator.request_rejoin(token)
        coordinator.adopt_grant(grant)
        restart = int(grant["restart"])
        retry_nonce = int(grant["retry_nonce"])
        resize_nonce = int(grant["nonce"])
        slot_map = (tuple(int(s) for s in grant["slots"])
                    if grant.get("slots") else None)
        resil.nonce = retry_nonce
        resil.resize_nonce = resize_nonce
        templates = (jax.device_get(params), jax.device_get(opt_state),
                     jax.device_get(state))
        p_h, o_h, s_h = resil.coord_restore(grant, *templates,
                                            ack_name="resize")
        params = place_p(p_h)
        opt_state = place_o(o_h)
        state = place_replicated(s_h, mesh)
        sample_key, drop_key = _fold_keys(retry_nonce, resize_nonce)
        start_epoch = epoch = loss_base = restart
        _apply_tune({}, "resize",
                    {"world": grant.get("world"), "trigger": "rejoin"},
                    restart)
        resil._emit("resize", epoch=int(grant["epoch"]),
                    old_world=int(grant["old_world"]),
                    world=int(grant["world"]),
                    members=[int(r) for r in grant["members"]],
                    lost=[], slots=[int(s) for s in grant.get("slots", [])],
                    trigger="rejoin", nonce=int(resize_nonce),
                    restart=int(restart), source=str(grant["source"]))
        log(f"[elastic] rank {coord_rank}: rejoined world "
            f"{grant.get('world')} (members {grant.get('members')}); "
            f"parts now "
            + slot_desc(slot_map, grant.get("members") or [])
            + f"; replaying from epoch {restart} in lockstep")
    if tuner is not None and start_epoch > 0 and not joiner:
        # resumed run: reconstruct/adopt the controller history and actuate
        # the levers that were live at the resume point BEFORE the first
        # step — the healed run replays the same schedule deterministically
        _tdiff = tuner.restore(start_epoch, tune_state)
        if _tdiff:
            _apply_tune(_tdiff, "resume", {}, start_epoch)
    # The loop is a `while` so the divergence guard can move `epoch`
    # BACKWARD (rollback to the last good checkpoint, resilience.py); with
    # --resilience off no hook below fires and the schedule is exactly the
    # historical `for epoch in range(start_epoch, n_epochs)`.
    # $BNSGCN_EPOCH_THROTTLE_S: minimum wall time per epoch (sleep before
    # the timed region). A test/demo knob — the elastic e2e harness uses it
    # to keep a fast CPU run alive long enough for a relaunched rank to pay
    # its startup cost and rejoin; 0 (default) sleeps nothing.
    epoch_throttle = float(os.environ.get("BNSGCN_EPOCH_THROTTLE_S", 0) or 0)
    try:
        while epoch < cfg.n_epochs:
            if epoch_throttle > 0:
                time.sleep(epoch_throttle)
            if resil is not None:
                resil.watchdog.beat(epoch)
                # deterministic fault injection at the step boundary
                # (--inject / $BNSGCN_FAULT); 'nan' poisons the params so
                # the divergence shows up through the REAL loss path
                if resil.fire_injections(epoch)["nan"]:
                    params = jax.tree.map(
                        lambda x: x * jnp.nan
                        if jnp.issubdtype(x.dtype, jnp.floating) else x,
                        params)
                # ---- on-demand profiling: SIGUSR1 was received; capture
                # stacks + registry snapshot NOW and trace a bounded window
                # of the next epochs into the post-mortem dir — training
                # never stops ----
                if obs is not None and resil.take_profile_request():
                    pm = resil.postmortem_dir or obs_mod.postmortem_dir(cfg)
                    snap = obs_mod.write_postmortem(
                        pm, f"sigusr1_E{epoch}",
                        text=f"SIGUSR1 snapshot at epoch {epoch}",
                        registry=obs.registry) or None
                    if snap:
                        log(f"[obs] SIGUSR1: stacks + metrics snapshot -> "
                            f"{snap}")
                    else:
                        log("[obs] SIGUSR1: post-mortem snapshot write "
                            "FAILED (disk?); still arming the trace window")
                    if not tracing and not usr1_tracing:
                        usr1_dir = os.path.join(pm, f"trace_E{epoch}")
                        try:
                            jax.profiler.start_trace(usr1_dir)
                            usr1_tracing, usr1_stop = True, epoch + 2
                            log(f"[obs] SIGUSR1: profiling epochs {epoch}.."
                                f"{usr1_stop} -> {usr1_dir}")
                        except Exception as ex:
                            log(f"[obs] SIGUSR1 trace failed to start: {ex}")
                            usr1_dir = None
                    obs.emit("profile_request", epoch=epoch, snapshot=snap,
                             trace_dir=usr1_dir if usr1_tracing else None)
                    resil.watchdog.touch()      # capture is boundary work
            # >= (not ==): a SIGUSR1 window covering prof_start must only
            # DELAY the one-shot comm-trace start, never cancel it
            if (trace_dir and prof_start <= epoch < prof_stop
                    and not tracing and not trace_done and not usr1_tracing):
                jax.profiler.start_trace(trace_dir)
                tracing = True
            t0 = time.perf_counter()
            # --halo-refresh K: an invalidated cache (run start, resume,
            # rollback) forces one full-refresh epoch at peak wire cost;
            # every other epoch runs the ~1/K partial exchange against
            # the cache. The cache is never checkpointed — it is
            # host-held device state only, rebuilt by the next
            # full-refresh epoch after any restore. full/cached are two
            # distinct compiled programs, so each is its own strict-exec
            # variant.
            refresh_full = use_refresh and halo_cache is None
            variant = (("full" if refresh_full else "cached")
                       if use_refresh else "step")
            # the one deliberate per-epoch host->device upload, hoisted
            # BEFORE the strict guard: everything else the step consumes
            # is already device-resident. Under strict the scalar is also
            # committed to the mesh's replicated sharding here — otherwise
            # its first use inside the guarded step reshards it and the
            # guard flags that device-to-device move.
            epoch_dev = jnp.uint32(epoch)
            if strict is not None and jax.process_count() == 1:
                epoch_dev = jax.device_put(epoch_dev,
                                           replicated_sharding(mesh))
            with (strict.step(variant) if strict is not None
                  else contextlib.nullcontext()):
                if use_refresh:
                    if refresh_full:
                        params, state, opt_state, loss, halo_cache = (
                            fns.train_step_full(
                                params, state, opt_state, epoch_dev, blk,
                                tables, sample_key, drop_key))
                    else:
                        params, state, opt_state, loss, halo_cache = (
                            fns.train_step_cached(
                                params, state, opt_state, epoch_dev, blk,
                                tables_refresh_d, halo_cache, sample_key,
                                drop_key))
                else:
                    params, state, opt_state, loss = fns.train_step(
                        params, state, opt_state, epoch_dev, blk, tables,
                        sample_key, drop_key)
                loss.block_until_ready()
            dt = time.perf_counter() - t0
            # identical float either way; under strict the fetch is the
            # audited explicit path (counted in the end-of-run summary)
            loss_f = (float(strict.fetch(loss)) if strict is not None
                      else float(loss))
            usr1_in_step = usr1_tracing     # profiler overhead rides dt
            if use_refresh and refresh_full:
                # lifecycle marker: this epoch rebuilt the halo cache at peak
                # wire cost (obs_report surfaces these against the
                # duty-cycled steady-state epochs)
                if obs is not None:
                    obs.emit("halo_refresh", epoch=epoch,
                             k=int(fns.halo_refresh), reason=cache_reason)
                log(f"  halo cache: full refresh at epoch {epoch} "
                    f"({cache_reason}); next {fns.halo_refresh - 1}+ epochs "
                    f"reuse cached blocks")

            # ---- divergence guard: free loss check every step (the loop
            # fetched it for res.losses anyway) + param-norm probe every
            # log_every; rollback BEFORE the checkpoint write below so a
            # non-finite state can never become "last good" ----
            bad = resil is not None and not math.isfinite(loss_f)
            pnorm = None        # the probe's value, reused by the obs epoch
                                # record — never an extra device op
            if (resil is not None and not bad
                    and (epoch + 1) % cfg.log_every == 0):
                pnorm = float(param_global_norm(params))
                bad = not math.isfinite(pnorm)
            if resil is not None and resil.coord is not None:
                # ---- multi-host agreed verdict: every rank contributes
                # its local state out-of-band, rank 0 reduces worst-wins,
                # and ALL ranks act on the one decision — a SIGTERM or NaN
                # on a single rank can no longer strand its peers inside
                # the next collective ----
                local = ("diverged" if bad
                         else "preempted" if resil.preempt_requested
                         else "ok")
                # piggyback this rank's epoch telemetry on the verdict the
                # exchange already carries: rank 0 merges every rank's
                # summary into ONE epoch_ranks record, no new collective
                summary = ({"loss": round(loss_f, 6),
                            "step_ms": round(dt * 1e3, 3)}
                           if obs is not None else None)
                decision = resil.agree_step(epoch, local, loss_f,
                                            summary=summary,
                                            final=epoch + 1 >= cfg.n_epochs)
                act = decision["decision"]
                if act == "abort":
                    resil.raise_abort(decision)
                if act == "preempt":
                    # agreed all-rank resumable shutdown: rank 0 writes the
                    # checkpoint (the agree() confirm phase already
                    # guaranteed every rank has read the verdict)
                    ppath = ckpt.periodic_path(cfg, epoch)
                    if is_rank0:
                        ckpt.save_checkpoint(ppath, params=params,
                                             opt_state=opt_state,
                                             bn_state=state, epoch=epoch,
                                             best_acc=best_acc, seed=seed,
                                             extra=_ckpt_extra())
                        ckpt.prune_checkpoints(cfg, cfg.keep_ckpt)
                    log(f"[resilience] agreed preemption (requested by "
                        f"rank(s) {decision.get('ranks')}) at the epoch-"
                        f"{epoch} step boundary: resumable checkpoint at "
                        f"{ppath}")
                    if obs is not None:
                        obs.emit("preempt", epoch=epoch, ckpt=ppath,
                                 agreed=True, ranks=decision.get("ranks"))
                    raise resilience.PreemptedError(epoch, ppath)
                if act == "rollback":
                    templates = (jax.device_get(params),
                                 jax.device_get(opt_state),
                                 jax.device_get(state))
                    if multi_host:
                        # real pod: rank 0 restores its validated payload
                        # and the trees travel the proven XLA broadcast.
                        # Every rank joins the restore ack FIRST — a rank-0
                        # restore failure must abort all ranks (78) before
                        # anyone blocks inside the XLA collective
                        from jax.experimental import multihost_utils
                        host = resil.coord_restore(decision, *templates,
                                                   restore_local=is_rank0)
                        p_h, o_h, s_h = multihost_utils.broadcast_one_to_all(
                            host)
                    else:
                        # harness mode: each rank restores the agreed source
                        # from its own checkpoint dir and acks — a torn
                        # local copy aborts ALL ranks loudly (exit 78)
                        p_h, o_h, s_h = resil.coord_restore(decision,
                                                            *templates)
                    restart = int(decision["restart"])
                    retry_nonce = int(decision["nonce"])
                    params = place_p(p_h)
                    opt_state = place_o(o_h)
                    state = place_replicated(s_h, mesh)
                    sample_key, drop_key = _fold_keys(retry_nonce, resize_nonce)
                    if restart < loss_base:
                        res.losses.clear()
                        loss_base = restart
                    else:
                        del res.losses[restart - loss_base:]
                    # the halo cache was built by epochs past the restore
                    # point — rolled-back training must not see them (the
                    # replayed epoch re-runs full-refresh, bitwise like a
                    # fresh run from that checkpoint)
                    halo_cache, cache_reason = None, "rollback"
                    if tuner is not None:
                        # revert to the levers live when `restart` first ran;
                        # the kept history REPLAYS from there (deterministic)
                        _td = tuner.rewind(restart)
                        if _td:
                            _apply_tune(_td, "rollback", {}, restart)
                    resil.watchdog.touch()      # restore+ack was boundary
                    epoch = restart             # work, not step time
                    continue
                if act == "resize":
                    # ---- elastic RESIZE verdict (--elastic on): shrink
                    # after a heartbeat-detected rank loss, or grow when a
                    # lost rank rejoins. Every surviving rank re-maps the P
                    # parts onto the new membership (decision['slots'],
                    # mesh.plan_slots — no METIS rerun), restores the
                    # agreed checkpoint, rebuilds the step fns through the
                    # shared layout cache like a retune, refolds the
                    # sampling/dropout streams under the resize nonce, and
                    # keeps training. ----
                    coordinator.apply_resize(decision)
                    templates = (jax.device_get(params),
                                 jax.device_get(opt_state),
                                 jax.device_get(state))
                    p_h, o_h, s_h = resil.coord_restore(decision, *templates,
                                                        ack_name="resize")
                    restart = int(decision["restart"])
                    retry_nonce = int(decision["retry_nonce"])
                    resize_nonce = int(decision["nonce"])
                    slot_map = (tuple(int(s) for s in decision["slots"])
                                if decision.get("slots") else None)
                    params = place_p(p_h)
                    opt_state = place_o(o_h)
                    state = place_replicated(s_h, mesh)
                    sample_key, drop_key = _fold_keys(retry_nonce,
                                                      resize_nonce)
                    if restart < loss_base:
                        res.losses.clear()
                        loss_base = restart
                    else:
                        del res.losses[restart - loss_base:]
                    # rebuild unconditionally: the halo spec must adopt the
                    # new slot map even when no tune lever moved (rewind
                    # returns {} then); _apply_tune invalidates the halo
                    # cache, re-arms strict-exec, and touches the watchdog
                    _td = tuner.rewind(restart) if tuner is not None else {}
                    _apply_tune(_td or {}, "resize",
                                {"world": decision.get("world"),
                                 "trigger": decision.get("trigger")},
                                restart)
                    log(f"[elastic] epoch {epoch}: "
                        f"{decision.get('trigger')} resize to world "
                        f"{decision.get('world')} "
                        f"(members {decision.get('members')}); parts now "
                        + slot_desc(slot_map, decision.get("members") or [])
                        + f"; resuming from epoch {restart}")
                    epoch = restart
                    continue
            elif bad:
                p_h, o_h, s_h, restart, retry_nonce = resil.rollback(
                    epoch, loss_f, jax.device_get(params),
                    jax.device_get(opt_state), jax.device_get(state))
                params = place_p(p_h)
                opt_state = place_o(o_h)
                state = place_replicated(s_h, mesh)
                sample_key, drop_key = _fold_keys(retry_nonce, resize_nonce)
                # retried epochs get re-recorded on the healthy pass
                if restart < loss_base:
                    res.losses.clear()
                    loss_base = restart
                else:
                    del res.losses[restart - loss_base:]
                # stale halo cache from the diverged timeline: invalidate so
                # the replayed epoch rebuilds it (full-refresh, deterministic)
                halo_cache, cache_reason = None, "rollback"
                if tuner is not None:
                    # revert to the levers live when `restart` first ran; the
                    # kept history REPLAYS from there (deterministic heal)
                    _td = tuner.rewind(restart)
                    if _td:
                        _apply_tune(_td, "rollback", {}, restart)
                resil.watchdog.touch()      # restore+backoff was boundary
                epoch = restart             # work, not step time
                continue

            if tracing and epoch >= prof_stop:
                jax.profiler.stop_trace()
                tracing = False
                trace_done = True
                if cfg.profile_dir:
                    log(f"profiler trace written to {cfg.profile_dir}")
                # load the trace ONCE; both the Comm/Reduce attribution and
                # the overlap report parse the same event list
                try:
                    trace_events, _ = traceparse.load_trace_events(trace_dir)
                except Exception:
                    trace_events = None
                parsed = (traceparse.step_comm_from_events(trace_events)
                          if trace_events is not None else None)
                if parsed is not None:
                    comm_traced, reduce_traced = parsed[0], parsed[1]
                    if obs is not None:
                        # the comm-vs-compute split obs_report renders:
                        # trace-derived in-step collective seconds per epoch
                        obs.emit("trace", epoch=epoch,
                                 comm_s=round(comm_traced, 6),
                                 reduce_s=round(reduce_traced, 6),
                                 trace_dir=cfg.profile_dir or None)
                    # drop the microbench samples recorded so far so the
                    # printed means are purely the traced in-step numbers;
                    # seed one sample immediately — the window-closing epoch
                    # itself is excluded from record(), and a log line firing
                    # on it would otherwise print an empty (0.0) mean
                    timer.comm_dur.clear()
                    timer.reduce_dur.clear()
                    timer.comm_dur.append(comm_traced)
                    timer.reduce_dur.append(reduce_traced)
                if fns.overlap == "split":
                    # --overlap split observability: per-step phase buckets +
                    # whether the collective ran under interior compute
                    try:
                        rep = (traceparse.overlap_from_events(trace_events)
                               if trace_events is not None else None)
                    except Exception:
                        rep = None
                    if rep is not None:
                        for k in ("exchange_ms", "interior_ms", "frontier_ms",
                                  "hidden_ms"):
                            timer.record_bucket(k, rep[k])
                        if obs is not None:
                            obs.emit("overlap", epoch=epoch,
                                     **{k: round(float(rep[k]), 4)
                                        for k in ("exchange_ms",
                                                  "interior_ms",
                                                  "frontier_ms", "hidden_ms")},
                                     overlapped=bool(rep["overlapped"]))
                        log("overlap[traced]: exchange {exchange_ms:.3f} ms | "
                            "interior {interior_ms:.3f} ms | frontier "
                            "{frontier_ms:.3f} ms | hidden {hidden_ms:.3f} ms "
                            "per step — collective overlapped interior "
                            "compute: {verdict}".format(
                                verdict="YES" if rep["overlapped"] else "NO",
                                **{k: rep[k] for k in rep}))
                    else:
                        log("overlap[traced]: no interior/frontier scope "
                            "spans in the trace window (tools/trace_comm.py "
                            "--overlap-check <dir> on a --profile-dir trace "
                            "gives the full report)")
                if auto_trace_dir:
                    shutil.rmtree(auto_trace_dir, ignore_errors=True)

            # ---- SIGUSR1 bounded window closes here; training continues ----
            if usr1_tracing and epoch >= usr1_stop:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                usr1_tracing = False
                log(f"[obs] SIGUSR1 profiler window written to {usr1_dir}")
                if obs is not None:
                    obs.emit("profile", epoch=epoch, trace_dir=usr1_dir)
                if trace_dir and not trace_done and epoch >= prof_start:
                    # the SIGUSR1 window swallowed (part of) the one-shot
                    # comm-trace window: re-arm it right after — delayed,
                    # never cancelled
                    prof_start = epoch + 1
                    prof_stop = min(prof_start + 3, cfg.n_epochs - 1)

            if comm_traced is not None:
                comm_t = comm_traced
            elif exch_widths and (epoch == timer.warmup
                                  or (epoch + 1) % cfg.log_every == 0):
                # comm microbench: exchange-only programs at each real layer
                # width, x2 for the backward (transposed) exchange. Under
                # --halo-refresh _comm_bench runs the partial-refresh
                # geometry — the steady-state cost, matching what all but
                # the 1-in-K full-refresh epochs put on the wire
                comm_t = 0.0
                for w in exch_widths:
                    t1 = time.perf_counter()
                    _comm_bench(w).block_until_ready()
                    comm_t += (time.perf_counter() - t1) * 2
            # epochs inside the trace window carry profiler-collection
            # overhead in dt — exclude them from the reported means like
            # warmup epochs (same rule as bench.py, whose traced runs are
            # tagged profiled-diagnostic and never update best_known)
            # retune epochs compile the rebuilt step programs inside dt —
            # excluded from the reported means exactly like warmup epochs
            clean_step = (not (trace_dir and prof_start <= epoch <= prof_stop)
                          and not usr1_in_step and epoch > retune_cool)
            if clean_step:
                timer.record(epoch, dt, comm_t,
                             reduce_traced if reduce_traced is not None else 0.0)
            res.losses.append(loss_f)
            # wire_mb is THIS epoch's actual exchange cost: duty-cycled under
            # --halo-refresh (peak on full-refresh epochs, the ~1/K steady
            # cost otherwise), 0 under grad-only — the per-epoch evidence for
            # the K-vs-bytes regression, and the --tune controller's wire
            # trigger
            epoch_wire_mb = (halo_wire_mb if (not use_refresh and
                                              not grad_only)
                             else halo_wire_mb if refresh_full
                             else steady_wire_mb)

            if obs is not None:
                # the per-epoch record everything downstream joins on; the
                # registry histogram gives p50/p99 step time without storing
                # samples (the snapshot rides post-mortem dumps). Same
                # exclusions as timer.record — compile/warmup and profiled
                # epochs must not report as p99 step time
                if clean_step and epoch >= timer.warmup:
                    obs.registry.histogram("train/step_s").observe(dt)
                rec = {"epoch": epoch, "loss": round(loss_f, 6),
                       "step_s": round(dt, 6),
                       "wire_mb": round(epoch_wire_mb, 4)}
                if pnorm is not None:
                    rec["param_norm"] = round(pnorm, 6)
                if comm_t:
                    rec["comm_s"] = round(comm_t, 6)
                    rec["comm_tag"] = ("traced" if comm_traced is not None
                                       else "sampled")
                obs.emit("epoch", **rec)

            # ---- --tune decision point: the epoch's measured metrics feed
            # the controller AFTER the epoch record lands on the bus; a
            # decision retunes the comm stack now and takes effect from the
            # next epoch (the rebuild/compile happens here, at the boundary,
            # never inside a timed step) ----
            if tuner is not None:
                _dec = tuner.on_epoch_end(epoch, {
                    "loss": loss_f, "step_s": dt,
                    "comm_s": comm_t if comm_t else None,
                    "wire_mb": epoch_wire_mb})
                if _dec is not None and _dec["changes"]:
                    _apply_tune(_dec["changes"], _dec["reason"],
                                _dec.get("trigger") or {}, epoch + 1)

            if (epoch + 1) % cfg.log_every == 0:
                mt, mc, mr = timer.means()
                # [traced]: per-epoch in-step collective time attributed from
                # the profiler window (the reference's comm_timer equivalent).
                # [sampled]: the exchange-only microbench at the training
                # compute dtype, which overstates quantized wires (dispatch-
                # dominated; measured up to 26x for int8) — printed only
                # until the trace window closes or under --no-comm-trace.
                tag = "[traced]" if comm_traced is not None else "[sampled]"
                log("Process 000 | Epoch {:05d} | Time(s) {:.4f} | Comm(s) "
                    "{:.4f} {} | Reduce(s) {:.4f} | Loss {:.4f}".format(
                        epoch, mt, mc, tag, mr, loss_f))

            wrote_ckpt = False
            if (epoch + 1) % cfg.log_every == 0 and is_rank0 and not bad:
                # periodic checkpoint regardless of eval, so --no-eval runs
                # resume too; rank 0 only (reference train.py:427-428).
                # `not bad` is vacuous at the default verdict cadence (a
                # diverged epoch rolled back above before reaching here)
                # but load-bearing under $BNSGCN_COORD_AGREE_EVERY > 1:
                # a latched-not-yet-agreed NaN state must never become
                # the newest "last good" checkpoint
                ckpt.save_checkpoint(ckpt.periodic_path(cfg, epoch),
                                     params=params, opt_state=opt_state,
                                     bn_state=state, epoch=epoch,
                                     best_acc=best_acc, seed=seed,
                                     extra=_ckpt_extra())
                ckpt.prune_checkpoints(cfg, cfg.keep_ckpt)
                wrote_ckpt = True
            if mesh_eval and (epoch + 1) % cfg.log_every == 0:
                fns_e, blk_e, tf_e, art_e = eval_val
                modes = ("val",) if cfg.inductive else ("val", "test")
                accs = evaluate_mesh("Epoch %05d" % epoch, fns_e.eval_forward,
                                     params, state, blk_e, tf_e, art_e, modes,
                                     result_file)
                if obs is not None:
                    obs.emit("eval", epoch=epoch,
                             **{f"{m}_acc": round(float(accs[m]), 6)
                                for m in modes})
                if accs["val"] > best_acc:
                    best_acc, best_params = accs["val"], jax.device_get(params)
            elif cfg.eval and is_rank0 and (epoch + 1) % cfg.log_every == 0:
                if pending is not None:
                    done = _drain_eval(pending)
                    if done is not None and done[1] > best_acc:
                        best_acc, best_params = done[1], done[0]
                p_host = jax.device_get(params)
                s_host = jax.device_get(state)
                # bind the epoch label like the params: the thread may run
                # after the loop has advanced, and a late-bound `epoch`
                # mislabels the eval line (observed as an "Epoch 00020" eval
                # in a log_every=10 run)
                if cfg.inductive:
                    pending = pool.submit(
                        _eval_job, epoch,
                        lambda p=p_host, s=s_host, e=epoch: (p, evaluate_induc(
                            "Epoch %05d" % e, p, s, spec, val_g, "val",
                            result_file)))
                else:
                    pending = pool.submit(
                        _eval_job, epoch,
                        lambda p=p_host, s=s_host, e=epoch: (p, evaluate_trans(
                            "Epoch %05d" % e, p, s, spec, val_g,
                            result_file)[0]))

            if resil is not None and (epoch + 1) % cfg.log_every == 0:
                if wrote_ckpt:
                    # a guard-verified checkpoint strictly past the last
                    # rollback heals the divergence retry budget
                    resil.note_progress(epoch)
                # checkpoint fsync + (mesh) eval — incl. the eval compile on
                # its first call — are epoch-boundary work: reset the
                # liveness clock so they never eat into the next step's
                # watchdog deadline
                resil.watchdog.touch()

            # ---- preemption-safe shutdown: the SIGTERM/SIGINT flag is read
            # at the step boundary only — mid-step device state is never
            # torn. The resumable checkpoint carries seed + retry nonce, so
            # --resume continues the exact sampling/dropout streams. Under
            # coordination the flag already went through the agreed-verdict
            # exchange above (a signal landing after it waits one epoch). ----
            if (resil is not None and resil.coord is None
                    and resil.preempt_requested):
                ppath = ckpt.periodic_path(cfg, epoch)
                if is_rank0 and not wrote_ckpt:
                    ckpt.save_checkpoint(ppath, params=params,
                                         opt_state=opt_state, bn_state=state,
                                         epoch=epoch, best_acc=best_acc,
                                         seed=seed,
                                         extra=_ckpt_extra())
                    ckpt.prune_checkpoints(cfg, cfg.keep_ckpt)
                log(f"[resilience] {resil.preempt_requested} honored at the "
                    f"epoch-{epoch} step boundary: resumable checkpoint at "
                    f"{ppath}")
                if obs is not None:
                    obs.emit("preempt", epoch=epoch, ckpt=ppath,
                             signal=resil.preempt_requested)
                raise resilience.PreemptedError(epoch, ppath)
            epoch += 1
    finally:
        # trace-window leak fix: a crash/preemption anywhere in the loop
        # (including the normal shorter-than-prof_stop ending) must not
        # leave a dangling profiler session or the auto temp dir behind
        if tracing or usr1_tracing:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
            if usr1_tracing:
                # the open trace was the SIGUSR1 one (the two never overlap)
                # — announce ITS location and complete its event pair, not
                # the comm-trace's profile_dir
                log(f"[obs] SIGUSR1 profiler window written to {usr1_dir}")
                if obs is not None:
                    obs.emit("profile", epoch=epoch, trace_dir=usr1_dir,
                             at_exit=True)
            elif cfg.profile_dir:
                log(f"profiler trace written to {cfg.profile_dir}")
            tracing = usr1_tracing = False
        if auto_trace_dir:
            shutil.rmtree(auto_trace_dir, ignore_errors=True)
        if resil is not None:
            res.rollbacks = list(resil.rollbacks)
            resil.close()
        if strict is not None:
            # the audit summary must land (log + obs event) on EVERY exit
            # path — an interrupted strict run still proves what it proved
            strict.finish()
        if obs is not None and sys.exc_info()[0] is not None:
            # an interrupted run (preempt 75, divergence 76, abort 78 —
            # anything raising out of the loop) still ends its log with a
            # terminal record; the normal path emits a richer run_end below
            mt_i, _, _ = timer.means()
            obs.emit("run_end", interrupted=sys.exc_info()[0].__name__,
                     epochs_done=len(res.losses), final_loss=loss_f,
                     epoch_time_s=round(mt_i, 6))
            obs.close()
        if coordinator is not None:
            # terminal decisions (preempt/abort) were already confirmed by
            # every peer inside agree(); a NORMAL completion still needs a
            # barrier, or rank 0 could tear the server down while a peer —
            # up to one step boundary behind — is fetching its last verdict
            if sys.exc_info()[0] is None:
                coordinator.finish()
            coordinator.close()
        if sys.exc_info()[0] is not None:
            # propagate without waiting on a queued eval. An in-flight eval
            # still runs in its (non-daemon) worker; the CLI preemption path
            # therefore ends with os._exit in main.py so the exit-75
            # contract can't be stalled past the platform's grace window
            pool.shutdown(wait=False, cancel_futures=True)
    if pending is not None:
        done = _drain_eval(pending)
        if done is not None and done[1] > best_acc:
            best_acc, best_params = done[1], done[0]
    pool.shutdown(wait=True)

    res.epoch_time, res.comm_time, res.reduce_time = timer.means()
    res.overlap_buckets = timer.bucket_means()
    res.final_loss = float(loss)
    res.memory = format_memory_stats()
    log(res.memory)
    # transductive mesh eval shares the training blocks (only 'feat' is new);
    # inductive keeps a separate val-graph block set resident
    hbm_parts = [blk]
    if mesh_eval:
        hbm_parts.append(eval_val[1] if cfg.inductive else eval_val[1]["feat"])
    log("static HBM/device ~{:.1f} MB (blocks + params + opt)".format(
        estimate_static_hbm(hbm_parts, [params, opt_state, state], cfg.n_partitions)))

    if cfg.eval and best_params is not None:
        # checkpoint/log I/O is rank-0-only, but the mesh test eval is a
        # COLLECTIVE — every process must join it or the mesh deadlocks
        if is_rank0:
            ckpt.save_checkpoint(ckpt.final_path(cfg), params=best_params,
                                 bn_state=jax.device_get(state),
                                 epoch=cfg.n_epochs - 1, best_acc=best_acc,
                                 seed=seed)
            log("model saved")
            log("Max Validation Accuracy {:.2%}".format(best_acc))
        res.best_val_acc = best_acc
        if mesh_eval:
            # test resources built lazily (inductive test graph = full graph;
            # no reason to pin it in HBM during training)
            fns_e, blk_e, tf_e, art_e = (
                _eval_resources(test_g, "-test") if cfg.inductive else eval_val)
            pb = place_p(best_params)
            res.test_acc = evaluate_mesh("Test Result", fns_e.eval_forward,
                                         pb, state, blk_e, tf_e, art_e,
                                         ("test",))["test"]
        elif is_rank0:
            res.test_acc = evaluate_induc("Test Result", best_params,
                                          jax.device_get(state), spec, test_g,
                                          "test")

    # ---- embedding-table export (--dump-embeddings): the all-node
    # penultimate activations + final-layer logits, written under the
    # checkpoint integrity header so serve.py can cold-start from the
    # artifact instead of recomputing. Uses the best-val params when
    # available (what serving should score with), else the final params —
    # so `--resume --n-epochs 0 --dump-embeddings PATH` is a standalone
    # embedding-export tool over a finished run. ----
    if cfg.dump_embeddings and is_rank0:
        from bnsgcn_tpu import serve as serve_mod
        from bnsgcn_tpu.evaluate import full_graph_embeddings, gather_parts
        dump_params = (best_params if best_params is not None
                       else jax.device_get(params))
        t0 = time.time()
        hidden = logits = None
        if multi_host:
            log("[serve] --dump-embeddings skipped: multi-host export needs "
                "a gather of remote part rows (single-host only for now)")
        elif mesh_eval and not cfg.inductive:
            # mesh seam: the eval forward returning (hidden, logits) per
            # part (trainer.embed_forward), assembled to global node order
            fns_e, blk_e, tf_e, art_e = eval_val
            hid, lg = fns_e.embed_forward(place_p(dump_params), state,
                                          blk_e, tf_e)
            hidden = gather_parts(art_e, hid)
            logits = gather_parts(art_e, lg)
        elif test_g is not None or g is not None:
            graph = test_g if test_g is not None else g
            hidden, logits = full_graph_embeddings(
                dump_params, jax.device_get(state), spec, graph,
                cfg.edge_chunk)
        else:
            log("[serve] --dump-embeddings skipped: no eval graph loaded "
                "(run with --eval, or --eval-device mesh transductive)")
        if hidden is not None:
            serve_mod.save_table(cfg.dump_embeddings, hidden, logits, meta={
                "graph_name": cfg.graph_name or cfg.derive_graph_name(),
                "model": cfg.model, "n_nodes": int(hidden.shape[0]),
                "epoch": cfg.n_epochs - 1,
                "best_acc": float(best_acc)})
            log(f"[serve] embedding table [{hidden.shape[0]} x "
                f"{hidden.shape[1]}] + logits [{logits.shape[1]} classes] "
                f"-> {cfg.dump_embeddings} ({time.time() - t0:.1f}s)")
    if obs is not None:
        obs.emit("run_end", final_loss=res.final_loss,
                 epoch_time_s=round(res.epoch_time, 6),
                 comm_time_s=round(res.comm_time, 6),
                 reduce_time_s=round(res.reduce_time, 6),
                 best_val_acc=round(res.best_val_acc, 6),
                 test_acc=round(res.test_acc, 6),
                 rollbacks=len(res.rollbacks),
                 step_hist=obs.registry.histogram("train/step_s").snapshot())
        obs.close()
    return res
