"""Closed-loop communication auto-tuner (`--tune {off,schedule,auto}`).

BNS-GCN's five comm levers — BNS rate, halo strategy, wire codec,
`--halo-refresh K`, `--halo-mode` — were all frozen at launch. This module
moves three of them (staleness K/mode, strategy, codec) at EPOCH
BOUNDARIES, driven by the per-epoch telemetry the obs bus already records
(loss trajectory, measured comm share, wire MB):

* **`schedule`** — a declarative user schedule, e.g.
  ``K=4@0,K=2@30,K=1@60`` (grammar: comma-separated ``lever=value@epoch``;
  levers ``K``/``mode``/``strategy``/``wire`` alias the config fields
  ``halo_refresh``/``halo_mode``/``halo_exchange``/``halo_wire``). A pure
  function of the epoch — rank-symmetric, allowed everywhere.
* **`auto`** — the DistGNN->Grappa staleness axis as a feedback anneal:
  start coarse (K=4, or the launch point if it is already coarser — e.g.
  grad-only) while gradients are large, tighten one ladder rung
  (grad-only -> K=4 -> K=2 -> K=1) each time the loss flattens, and when
  the MEASURED comm share stays high, re-pick the halo strategy
  (`parallel/halo.retune_strategy`, the `--halo-exchange auto` byte
  estimate re-evaluated against observed cost) or anneal the wire codec
  native -> bf16. Rank-local timings would desync the compiled programs of
  a multi-rank run, so `auto` is single-process only (ConfigError).

Hysteresis is structural, not tuned: the staleness ladder only ever
TIGHTENS (monotone), the strategy re-pick and codec anneal fire at most
once per run, a flatness verdict must hold `AUTO_HOLD` consecutive epochs,
and every move starts an `AUTO_COOLDOWN`-epoch dwell — the controller
cannot flip-flop by construction (`test_tune.py` proves it on synthetic
streams).

Every applied move is a `tune_decision` lifecycle event (obs.EVENT_KINDS)
carrying the trigger metrics, and every move is STICKY: the Tuner records
its decision history, run.py round-trips it through checkpoint
``extra["tune"]``, and after a rollback/resume the recorded decisions are
REPLAYED by epoch (reason ``replay``/``resume``) instead of re-derived —
a healed run executes the same schedule deterministically even though its
post-rollback metrics differ. Fresh (metric-driven) decisions happen only
past the furthest epoch the run has ever reached.

run.py owns the actuation: a decision rebuilds the step fns through
`trainer.build_step_fns` with the shared layout cache (SpMM layout keys do
not depend on the halo levers, so a retune never rebuilds layouts),
invalidates the PR-10 halo cache (the next epoch is a logged full-refresh,
reason ``retune``), and re-arms `--strict-exec`'s per-variant compile
allowance (`StrictExec.rearm` — a retune is the one sanctioned recompile).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Optional

from bnsgcn_tpu.config import ConfigError

__all__ = ["Tuner", "AutoState", "decide", "parse_schedule",
           "startup_changes", "validate_mode", "bench_schedule",
           "reachable_lever_states"]

# schedule grammar lever aliases -> Config field names
LEVER_ALIASES = {
    "K": "halo_refresh", "k": "halo_refresh",
    "mode": "halo_mode",
    "strategy": "halo_exchange",
    "wire": "halo_wire",
}
# values a schedule (or auto) may set; halo_exchange excludes 'auto' on
# purpose — a retune picks a CONCRETE strategy, never re-delegates
VALID_VALUES = {
    "halo_mode": ("exchange", "grad-only"),
    "halo_exchange": ("padded", "shift", "ragged"),
    "halo_wire": ("native", "bf16", "fp8", "int8"),
}
TUNED_LEVERS = ("halo_refresh", "halo_mode", "halo_exchange", "halo_wire")

# --- auto-policy constants (see module docstring for the hysteresis story)
# staleness ladder, coarse -> fine; position only ever increases
STALENESS_LADDER = (("grad-only", 1), ("exchange", 4),
                    ("exchange", 2), ("exchange", 1))
AUTO_WINDOW = 5        # loss/comm samples a verdict needs
AUTO_HOLD = 2          # consecutive flat verdicts before tightening
AUTO_COOLDOWN = 3      # post-move dwell epochs (no further decisions)
# per-rung flatness thresholds: relative loss improvement PER EPOCH below
# which the current staleness level has extracted its value (coarser rungs
# tolerate less flatness — they should hand off while gradients still move)
TIGHTEN_RTOL = (0.03, 0.02, 0.005)
AUTO_COMM_FRAC = 0.30  # measured comm_s/step_s share that justifies
                       # strategy/codec moves
# the only codec move auto may take by itself: bf16 halos are the
# established near-lossless wire; fp8/int8 stay opt-in (quantization error
# is a per-model judgement the controller must not make)
WIRE_ANNEAL = {"native": "bf16"}


def _ladder_pos(levers: dict) -> int:
    if levers.get("halo_mode") == "grad-only":
        return 0
    k = int(levers.get("halo_refresh", 1))
    if k >= 4:
        return 1
    if k >= 2:
        return 2
    return 3


def parse_schedule(text: str) -> list:
    """``K=4@0,K=2@30,wire=bf16@30`` -> sorted ``[(epoch, {field: value})]``
    with same-epoch entries merged. Raises ConfigError on bad grammar, an
    unknown lever/value, or the same lever set twice at one epoch."""
    entries: dict[int, dict] = {}
    for raw in (text or "").split(","):
        item = raw.strip()
        if not item:
            continue
        try:
            lhs, ep_s = item.rsplit("@", 1)
            lever_s, val_s = lhs.split("=", 1)
            ep = int(ep_s)
        except ValueError:
            raise ConfigError(
                f"--tune-schedule entry {item!r}: expected lever=value@epoch "
                f"(e.g. K=4@0,K=2@30,K=1@60)") from None
        lever = LEVER_ALIASES.get(lever_s.strip())
        if lever is None:
            raise ConfigError(
                f"--tune-schedule entry {item!r}: unknown lever "
                f"{lever_s.strip()!r} (one of {sorted(set(LEVER_ALIASES))})")
        val_s = val_s.strip()
        if lever == "halo_refresh":
            try:
                val = int(val_s)
            except ValueError:
                raise ConfigError(
                    f"--tune-schedule entry {item!r}: K must be an integer") \
                    from None
            if val < 1:
                raise ConfigError(
                    f"--tune-schedule entry {item!r}: K must be >= 1")
        else:
            if val_s not in VALID_VALUES[lever]:
                raise ConfigError(
                    f"--tune-schedule entry {item!r}: {lever_s.strip()} must "
                    f"be one of {VALID_VALUES[lever]}")
            val = val_s
        if ep < 0:
            raise ConfigError(
                f"--tune-schedule entry {item!r}: epoch must be >= 0")
        at = entries.setdefault(ep, {})
        if lever in at:
            raise ConfigError(
                f"--tune-schedule sets {lever_s.strip()} twice at epoch {ep}")
        at[lever] = val
    return sorted(entries.items())


def validate_mode(cfg, multi_host: bool = False,
                  coordinated: bool = False) -> None:
    """Launch-time mode checks run.py applies before the first build."""
    if cfg.tune not in ("off", "schedule", "auto"):
        raise ConfigError(f"--tune must be off/schedule/auto, got {cfg.tune!r}")
    if cfg.tune == "schedule" and not (cfg.tune_schedule or "").strip():
        raise ConfigError("--tune schedule needs a --tune-schedule "
                          "(e.g. 'K=4@0,K=2@30,K=1@60')")
    if cfg.tune_schedule and cfg.tune != "schedule":
        raise ConfigError("--tune-schedule is only read under --tune schedule")
    prior = getattr(cfg, "tune_prior", "ladder")
    if prior not in ("ladder", "model"):
        raise ConfigError(f"--tune-prior must be ladder/model, got {prior!r}")
    if prior == "model" and cfg.tune != "auto":
        raise ConfigError("--tune-prior model only applies to --tune auto "
                          "(the schedule/off modes have no starting rung "
                          "to pick)")
    if cfg.tune == "auto" and (multi_host or coordinated):
        # rank-LOCAL step timings drive auto's decisions; two ranks reading
        # different clocks would retune into different compiled programs and
        # desync the SPMD collectives. The declarative schedule is a pure
        # function of the epoch and stays rank-symmetric everywhere.
        raise ConfigError(
            "--tune auto is single-process only (rank-local timings would "
            "desync the retuned programs across ranks); use --tune schedule "
            "for multi-rank runs")


def startup_changes(cfg, prior=None) -> tuple:
    """(changes, reason) to fold into cfg BEFORE the first build — the
    schedule's epoch-0 entries, or auto's staleness start. Empty changes
    mean the launch config already sits at the starting point.

    `prior` (only read under --tune auto) is the graftperf model-prior
    dict ({"halo_refresh": rung, "why": ...} from
    analysis/perf/model.model_prior) the run computed for
    --tune-prior model: it REPLACES the default coarse K=4 launch rung
    with the predicted-optimal one. The fold never loosens — a user who
    launched coarser than the pick keeps their state, exactly like the
    default ladder start — so the prior can only skip wasted rungs,
    never add staleness the config didn't ask for."""
    if cfg.tune == "schedule":
        for ep, levers in parse_schedule(cfg.tune_schedule):
            if ep != 0:
                continue
            ch = {k: v for k, v in levers.items() if getattr(cfg, k) != v}
            return ch, "schedule@0"
        return {}, "schedule@0"
    if cfg.tune == "auto":
        target = STALENESS_LADDER[1][1]
        why = "auto-start: coarse staleness while gradients are large"
        if prior is not None:
            target = int(prior["halo_refresh"])
            why = f"auto-start: {prior.get('why', 'model prior')}"
        if (cfg.halo_mode == "exchange"
                and int(cfg.halo_refresh) < target):
            return {"halo_refresh": target}, why
        return {}, "auto-start"
    return {}, ""


def reachable_lever_states(cfg) -> list:
    """Every TUNED_LEVERS state a run under `cfg` can be retuned into, as a
    deduplicated list of {halo_exchange, halo_wire, halo_refresh, halo_mode}
    dicts with the effective launch state first.

    This is the static enumeration the analysis/ir preflight traces: a
    retune swaps the compiled step programs at an epoch boundary, so every
    state listed here is a program the run may execute and must satisfy the
    same rank-symmetry / donation / wire contracts as the launch program.

    * ``off``      — the launch levers only.
    * ``schedule`` — the cumulative fold of ``parse_schedule`` entries onto
      the launch levers, in epoch order (exactly the states
      ``Tuner.on_epoch_end`` walks, including after rollback replay).
    * ``auto``     — a conservative SUPERSET: the startup fold, then every
      ladder rung at or past the starting position (the ladder is
      monotone), crossed with the one-shot strategy re-pick (any concrete
      strategy — the byte-estimate pick depends on the runtime n_b table,
      so all of VALID_VALUES is reachable in principle) and the one-shot
      wire anneal. Tracing a superset keeps the preflight sound when the
      controller's runtime choice cannot be known statically.

    ``halo_exchange='auto'`` is left as-is here; callers resolving it to a
    concrete strategy (run.py's select_halo_strategy) should fold the
    resolved value into `cfg` first."""
    launch = {k: getattr(cfg, k) for k in TUNED_LEVERS}
    launch["halo_refresh"] = int(launch["halo_refresh"])
    ch, _ = startup_changes(cfg)
    start = {**launch, **ch}
    states: list[dict] = []

    def add(st: dict):
        if st not in states:
            states.append(dict(st))

    add(launch)
    add(start)
    if cfg.tune == "schedule":
        cur = dict(start)
        for _ep, levers in parse_schedule(cfg.tune_schedule):
            cur.update(levers)
            add(cur)
    elif cfg.tune == "auto":
        strategies = {start["halo_exchange"]}
        if start["halo_exchange"] in VALID_VALUES["halo_exchange"]:
            strategies.update(VALID_VALUES["halo_exchange"])
        wires = {start["halo_wire"]}
        nxt = WIRE_ANNEAL.get(start["halo_wire"])
        if nxt is not None:
            wires.add(nxt)
        rungs = STALENESS_LADDER[_ladder_pos(start):]
        for strat in sorted(strategies):
            for wire in sorted(wires):
                for mode, k in rungs:
                    add({"halo_exchange": strat, "halo_wire": wire,
                         "halo_refresh": k, "halo_mode": mode})
    return states


def bench_schedule(n_epochs: int) -> list:
    """The fixed anneal bench.py's ``+at`` candidates execute: K=4 from
    epoch 0, K=2 at 40%, K=1 at 70% of the run — the default coarse->fine
    staleness schedule at bench's epoch counts (auto's loss-feedback needs
    more epochs than a bench run has)."""
    e2 = max(n_epochs * 2 // 5, 1)
    e1 = max(n_epochs * 7 // 10, e2 + 1)
    return [(0, {"halo_refresh": 4}), (e2, {"halo_refresh": 2}),
            (e1, {"halo_refresh": 1})]


@dataclass
class AutoState:
    """Mutable feedback-policy state. NOT serialized: applied decisions are
    what persistence replays; after a rollback/resume the metric windows
    refill from the replayed epochs before any fresh decision can fire."""
    losses: list = field(default_factory=list)      # last <= AUTO_WINDOW
    comm_fracs: list = field(default_factory=list)  # last <= AUTO_WINDOW
    flat: int = 0            # consecutive flat-loss verdicts
    cooldown: int = 0        # epochs left in the post-move dwell
    strategy_moved: bool = False   # one-shot flags: strategy re-pick and
    wire_moved: bool = False       # codec anneal each fire at most once

    def observe(self, metrics: dict) -> None:
        loss = metrics.get("loss")
        if loss is not None and math.isfinite(float(loss)):
            self.losses.append(float(loss))
            del self.losses[:-AUTO_WINDOW]
        step_s, comm_s = metrics.get("step_s"), metrics.get("comm_s")
        if comm_s is not None and step_s:
            self.comm_fracs.append(float(comm_s) / float(step_s))
            del self.comm_fracs[:-AUTO_WINDOW]


def _rel_improvement(losses: list) -> Optional[float]:
    """Relative loss improvement per epoch over the window; None until the
    window is full."""
    if len(losses) < AUTO_WINDOW:
        return None
    first, last = losses[0], losses[-1]
    return (first - last) / ((abs(first) + 1e-12) * (len(losses) - 1))


def decide(st: AutoState, levers: dict,
           strategy_alt: Optional[tuple] = None) -> Optional[tuple]:
    """The pure decision core of `--tune auto`. Reads the metric windows in
    `st` and the currently-applied `levers`, returns
    ``(changes, reason, trigger)`` for at most ONE lever move — or None.
    Mutates only `st`'s counters. `strategy_alt` is the precomputed
    ``(strategy, why)`` byte-estimate re-pick from
    `parallel.halo.retune_strategy` (None when the launch strategy already
    wins on bytes).

    Priority: staleness anneal > strategy re-pick > codec anneal. The
    hysteresis invariants (monotone ladder, one-shot strategy/codec moves,
    hold + cooldown) live here so unit tests can prove them on synthetic
    streams without a mesh."""
    if st.cooldown > 0:
        st.cooldown -= 1
        return None
    pos = _ladder_pos(levers)
    if pos + 1 < len(STALENESS_LADDER):
        imp = _rel_improvement(st.losses)
        if imp is not None:
            thr = TIGHTEN_RTOL[pos]
            st.flat = st.flat + 1 if imp < thr else 0
            if st.flat >= AUTO_HOLD:
                mode, k = STALENESS_LADDER[pos + 1]
                changes = {}
                if levers.get("halo_mode") != mode:
                    changes["halo_mode"] = mode
                if int(levers.get("halo_refresh", 1)) != k:
                    changes["halo_refresh"] = k
                st.flat, st.cooldown = 0, AUTO_COOLDOWN
                st.losses.clear()
                return (changes,
                        f"loss flat ({imp:+.4f}/epoch < {thr}): tighten "
                        f"staleness to mode={mode} K={k}",
                        {"rel_improvement": round(imp, 6), "threshold": thr})
    if len(st.comm_fracs) >= AUTO_WINDOW:
        cf = sorted(st.comm_fracs)[len(st.comm_fracs) // 2]
        if cf >= AUTO_COMM_FRAC:
            if (strategy_alt is not None and not st.strategy_moved
                    and strategy_alt[0] != levers.get("halo_exchange")):
                st.strategy_moved, st.cooldown = True, AUTO_COOLDOWN
                return ({"halo_exchange": strategy_alt[0]},
                        f"comm share {cf:.0%}: re-pick strategy "
                        f"({strategy_alt[1]})",
                        {"comm_frac": round(cf, 4),
                         "threshold": AUTO_COMM_FRAC})
            nxt = WIRE_ANNEAL.get(levers.get("halo_wire"))
            if nxt is not None and not st.wire_moved:
                st.wire_moved, st.cooldown = True, AUTO_COOLDOWN
                return ({"halo_wire": nxt},
                        f"comm share {cf:.0%}: anneal wire "
                        f"{levers.get('halo_wire')}->{nxt}",
                        {"comm_frac": round(cf, 4),
                         "threshold": AUTO_COMM_FRAC})
    return None


class Tuner:
    """Per-run controller state: current levers, sticky decision history,
    and the auto-policy feedback windows. Single-threaded — run.py drives
    it from the epoch loop only (no `# guarded-by:` state here; the shared
    obs/strict objects it feeds have their own)."""

    def __init__(self, cfg, levers: dict, log: Callable = print):
        self.mode = cfg.tune
        self.log = log
        # RESOLVED launch levers (post startup_changes, post `--halo-exchange
        # auto` resolution): the fold base every rewind/restore starts from
        self.base = {k: levers[k] for k in TUNED_LEVERS}
        self.levers = dict(self.base)
        self.schedule = (parse_schedule(cfg.tune_schedule)
                         if self.mode == "schedule" else [])
        self._sched_by_epoch = dict(self.schedule)
        self.history: list = []          # applied decisions, sticky
        self._by_epoch: dict[int, dict] = {}
        self.max_seen = -1               # furthest epoch already decided for
        self._auto = AutoState() if self.mode == "auto" else None
        self.strategy_alt: Optional[tuple] = None  # set by run.py (auto only)

    # -- history -----------------------------------------------------------
    def _record(self, epoch: int, changes: dict, reason: str,
                trigger: dict) -> dict:
        ent = {"epoch": int(epoch), "changes": dict(changes),
               "reason": reason, "trigger": dict(trigger or {})}
        self.history.append(ent)
        self._by_epoch[ent["epoch"]] = ent
        self.levers.update(changes)
        return ent

    def record_startup(self, changes: dict, reason: str) -> dict:
        """Sticky epoch-0 entry for the startup_changes() fold run.py applied
        before the first build (`self.base` already includes it — the fold
        is idempotent, which is what keeps rewind(0) correct)."""
        self.max_seen = max(self.max_seen, 0)
        return self._record(0, changes, reason, {})

    # -- epoch-boundary decision -------------------------------------------
    def on_epoch_end(self, epoch: int, metrics: dict) -> Optional[dict]:
        """Called after epoch `epoch` completes with its measured metrics;
        returns the decision (entry dict) taking effect at ``epoch + 1``, or
        None. Epochs at or below `max_seen` REPLAY the recorded history
        (deterministic recovery); fresh decisions only extend past it."""
        if self._auto is not None:
            self._auto.observe(metrics)     # windows warm up during replay too
        nxt = epoch + 1
        if nxt <= self.max_seen:
            ent = self._by_epoch.get(nxt)
            if ent is not None and ent["changes"]:
                self.levers.update(ent["changes"])
                return {**ent, "reason": "replay"}
            return None
        self.max_seen = nxt
        if self.mode == "schedule":
            want = self._sched_by_epoch.get(nxt)
            if want:
                changes = {k: v for k, v in want.items()
                           if self.levers.get(k) != v}
                if changes:
                    return self._record(nxt, changes, "schedule", {})
            return None
        out = decide(self._auto, self.levers, self.strategy_alt)
        if out is None or not out[0]:
            return None
        changes, reason, trigger = out
        return self._record(nxt, changes, reason, trigger)

    # -- recovery ----------------------------------------------------------
    def _fold(self, upto_epoch: int) -> dict:
        levers = dict(self.base)
        for ent in self.history:
            if ent["epoch"] <= upto_epoch:
                levers.update(ent["changes"])
        return levers

    def rewind(self, restart: int) -> Optional[dict]:
        """Rollback support: revert to the levers active when epoch
        `restart` originally ran. History PAST the restart point is kept —
        on_epoch_end replays it by epoch, so the healed run walks the same
        schedule. Returns the lever diff to actuate, or None."""
        target = self._fold(restart)
        diff = {k: v for k, v in target.items() if self.levers.get(k) != v}
        if self._auto is not None:
            # metric windows refill from the replayed epochs; the extra
            # cooldown keeps the first post-recovery fresh decision dwelled
            self._auto.losses.clear()
            self._auto.comm_fracs.clear()
            self._auto.flat, self._auto.cooldown = 0, AUTO_COOLDOWN
        if not diff:
            return None
        self.levers = target
        return diff

    def restore(self, start_epoch: int, state: Optional[dict]) -> \
            Optional[dict]:
        """Resume support: adopt the checkpointed controller state (or, for
        schedule mode, reconstruct it — the schedule is a pure function of
        the epoch) and return the lever diff the resumed run must actuate
        before its first step, or None."""
        applied = dict(self.levers)     # what run.py actually built with —
        # _record below mutates self.levers while reconstructing history;
        # rewind() must diff against the BUILT levers, so restore them first
        if self.mode == "schedule":
            for ep, want in self.schedule:
                if 0 < ep <= start_epoch:
                    ch = {k: v for k, v in want.items()
                          if self._fold(start_epoch).get(k) != v}
                    if ch:
                        self._record(ep, ch, "schedule", {})
            self.max_seen = max(self.max_seen, start_epoch)
        elif state:
            if state.get("mode") != self.mode:
                self.log(f"[tune] checkpoint carries tune state for mode "
                         f"{state.get('mode')!r}, this run is {self.mode!r}; "
                         f"ignoring it")
            else:
                self.history = [dict(e) for e in state.get("history", [])]
                self._by_epoch = {int(e["epoch"]): e for e in self.history}
                self.max_seen = int(state.get("max_seen", start_epoch))
        elif start_epoch > 0:
            # resumed from a checkpoint written without tune state (e.g. a
            # pre-tune run): anneal continues fresh from the launch levers
            self.log("[tune] resumed checkpoint has no controller state; "
                     "starting fresh from the launch levers")
            self.max_seen = max(self.max_seen, start_epoch)
        self.levers = applied
        return self.rewind(start_epoch)

    def state_dict(self) -> dict:
        """Checkpoint payload (``extra["tune"]``): the sticky decision
        history is all deterministic replay needs — AutoState's windows
        refill from the replayed epochs."""
        return {"mode": self.mode, "max_seen": self.max_seen,
                "history": [dict(e) for e in self.history]}
