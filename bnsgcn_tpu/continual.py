"""Continual training on an evolving graph: the train -> deploy cycle.

One cycle (`python -m bnsgcn_tpu.main continual --serve-dir ... \
--cycle-epochs N`, loop with --cycles):

  1. CONSUME — pull the serving delta journal past the cycle's consumed
     cursor: live `export_deltas` handshake against a running server
     (one lock hold on the server marks the handoff point, so a delta
     landing mid-export is never double-consumed or dropped), or the
     flushed delta-log/snapshot files when no server answers. A cursor
     that predates a compaction fold resyncs from the snapshot blob +
     tail instead — nothing in history is ever lost to compaction.
  2. FOLD — update the partition artifacts (data/incremental.py):
     append edges into the per-part CSR and boundary/halo tables,
     recompute only touched degree/norm rows, no METIS rerun. The
     staleness budget (--continual-cut-growth / --continual-imbalance)
     decides when cumulative drift justifies a from-scratch re-partition
     instead; either way the decision is an `artifact_update` obs event.
     Artifacts are versioned per cycle (`<graph_name>-c<N>`) — the prior
     dir is never mutated, so a crashed cycle re-runs cleanly.
  3. FINE-TUNE — warm-start run_training from the serving checkpoint on
     the mutated graph: fresh optimizer, cycle-nonce-refolded BNS/dropout
     streams, reorder perms migrated for untouched parts only.
  4. PROMOTE — gate on validation accuracy (the OLD weights evaluated on
     the SAME mutated graph are the bar: regressions past
     --continual-acc-drop keep serving the prior weights), then publish a
     promotion blob through the checkpoint integrity chain and ask the
     server to adopt it at a drain boundary (serve.ServeCore.promote;
     offline servers adopt at next startup). The consumed cursor always
     advances — graph deltas are facts; only the WEIGHTS roll back.

Cycle state (consumed cursor, cycle counter, artifact lineage, staleness
baseline) lives in `continual_state.json` inside the serve dir, written
atomically, so the artifacts and meta.json of a non-continual run stay
byte-identical.

Exit codes: 0 ok (including a no-op cycle with nothing to consume),
2 config/usable-input error.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

import numpy as np

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu.config import Config, ConfigError, parse_config
from bnsgcn_tpu.data import incremental as inc
from bnsgcn_tpu.data.artifacts import (build_artifacts, load_artifacts,
                                       save_artifacts)
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.run import artifact_digest, artifacts_dir, run_training
from bnsgcn_tpu.utils.metrics import calc_acc

STATE = "continual_state.json"


# ---------------------------------------------------------------------------
# cycle state (consumed cursor + lineage), atomic like the delta log
# ---------------------------------------------------------------------------

def state_path(serve_dir: str) -> str:
    return os.path.join(serve_dir, STATE)


def load_state(serve_dir: str) -> dict:
    path = state_path(serve_dir)
    if not os.path.exists(path):
        return {"cycle": 0, "consumed": 0, "artifact_dir": "",
                "base_artifact_dir": "", "graph_name": "", "baseline": None}
    with open(path) as f:
        return json.load(f)


def save_state(serve_dir: str, st: dict) -> str:
    os.makedirs(serve_dir, exist_ok=True)
    path = state_path(serve_dir)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(st, f, sort_keys=True, indent=1)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# delta acquisition: live handshake or flushed files
# ---------------------------------------------------------------------------

def _server_export(cfg: Config, cursor: int, log) -> Optional[dict]:
    """One export_deltas round trip, or None when no server answers (the
    'auto' source falls back to the flushed files)."""
    from bnsgcn_tpu import serve
    from bnsgcn_tpu.parallel import coord as coord_mod
    try:
        resp = serve.request(cfg.serve_port,
                             {"op": "export_deltas", "cursor": int(cursor)},
                             addr=cfg.serve_addr or "127.0.0.1",
                             timeout_s=5.0)
    except coord_mod.CoordTimeout:
        return None
    if not resp.get("ok"):
        raise ConfigError(f"export_deltas rejected: {resp.get('err')} — "
                          f"the consumed cursor in {STATE} is ahead of the "
                          f"server's journal (wrong serve dir?)")
    return resp


def acquire_deltas(cfg: Config, serve_dir: str, consumed: int,
                   log) -> tuple[list, int, Optional[dict], str]:
    """(tail entries, new consumed cursor, snapshot mutation_state or None,
    source used). A non-None snapshot means the entries before it were
    compacted away: the cycle must resync the mutated graph from the base
    artifacts + snapshot + tail instead of splicing just the tail."""
    source = cfg.continual_source
    resp = None
    if source in ("server", "auto"):
        resp = _server_export(cfg, consumed, log)
        if resp is None and source == "server":
            raise ConfigError(
                f"--continual-source server: no serve server answering on "
                f"port {cfg.serve_port}")
    if resp is not None:
        if not resp.get("snapshot_required"):
            return list(resp["deltas"]), int(resp["total"]), None, "server"
        # cursor predates the last compaction fold: the snapshot holds the
        # folded prefix; re-export at the fold point for the live tail
        snap = ckpt.read_blob(os.path.join(serve_dir, "serve_snapshot.blob"))
        tail = _server_export(cfg, int(resp["folded"]), log)
        if tail is None or not tail.get("ok"):
            raise ConfigError("server vanished mid-export handshake")
        return list(tail["deltas"]), int(tail["total"]), snap, "server"

    # offline: flushed delta-log (the tail) + optional compaction snapshot
    log_path = os.path.join(serve_dir, "delta_log.jsonl")
    snap_path = os.path.join(serve_dir, "serve_snapshot.blob")
    entries = inc.read_delta_entries(log_path) if os.path.exists(log_path) \
        else []
    if os.path.exists(snap_path):
        snap = ckpt.read_blob(snap_path)
        folded = int(snap["n_deltas"])
        if consumed < folded:
            return entries, folded + len(entries), snap, "log"
        return entries[consumed - folded:], folded + len(entries), None, "log"
    return entries[consumed:], len(entries), None, "log"


# ---------------------------------------------------------------------------
# one cycle
# ---------------------------------------------------------------------------

def _eval_acc(params, state, spec, g, edge_chunk: int) -> float:
    from bnsgcn_tpu.evaluate import full_graph_logits
    logits = full_graph_logits(params, state, spec, g, edge_chunk)
    return calc_acc(logits[g.val_mask], np.asarray(g.label)[g.val_mask])


def _restore_templates(cfg: Config, payload: dict, g):
    """(params, state, spec) with the checkpoint's weights restored into
    fresh templates sized for graph g."""
    import jax

    from bnsgcn_tpu.models.gnn import init_params, spec_from_config
    cfg = cfg.replace(n_feat=g.n_feat, n_class=g.n_class, n_train=g.n_train)
    spec = spec_from_config(cfg)
    # template init only — every leaf is overwritten by restore_into, so
    # the key's value is irrelevant; seed-derived keeps stream hygiene
    params, state = init_params(jax.random.key(int(cfg.seed)), spec)
    p, _, s = ckpt.restore_into(payload, jax.device_get(params), None,
                                jax.device_get(state))
    return p, s, spec


def run_cycle(cfg: Config, log=print,
              obs: Optional[obs_mod.Obs] = None) -> dict:
    """One consume -> fold -> fine-tune -> promote cycle. Returns a summary
    dict ({"noop": True} when there was nothing to consume)."""
    serve_dir = cfg.serve_dir or os.path.join(cfg.ckpt_path, "serve")
    st = load_state(serve_dir)
    cycle = int(st["cycle"]) + 1
    consumed = int(st["consumed"])
    base_name = st.get("graph_name") or cfg.graph_name \
        or cfg.derive_graph_name()
    cfg = cfg.replace(graph_name=base_name)
    cur_dir = st.get("artifact_dir") or artifacts_dir(cfg)
    base_dir = st.get("base_artifact_dir") or artifacts_dir(cfg)

    entries, new_consumed, snap, source = acquire_deltas(
        cfg, serve_dir, consumed, log)
    if not entries and snap is None:
        log(f"[continual] cycle {cycle}: nothing to consume past cursor "
            f"{consumed} ({source}) — no-op")
        if obs is not None:
            obs.emit("continual_cycle", cycle=cycle, noop=True,
                     consumed=consumed, source=source)
        return {"ok": True, "noop": True, "cycle": cycle}

    t0 = time.perf_counter()
    art = load_artifacts(cur_dir)
    n_parts = art.n_parts
    baseline = st.get("baseline") or inc.artifact_stats(art)

    # ---- fold the tail into the artifacts ----
    repartition_why = None
    touched_edges: list = []
    info: dict = {}
    if snap is not None:
        # compaction swallowed part of the un-consumed history: rebuild the
        # mutated graph from the BASE artifacts + snapshot + tail at the
        # CURRENT part assignment (still no METIS rerun)
        base_art = load_artifacts(base_dir)
        g2 = inc.apply_delta_batch(inc.graph_from_artifacts(base_art),
                                   inc.batch_from_snapshot(snap))
        g2 = inc.apply_delta_batch(g2, inc.delta_batch(entries))
        _, part_of, _ = inc._global_maps(art)
        new_art = build_artifacts(g2, part_of)
        touched_edges = list(range(n_parts))
        info = {"resync": True, "new_edges": int(g2.n_edges - art.src.shape[0])}
    else:
        batch = inc.delta_batch(entries)
        try:
            new_art, info = inc.update_artifacts(art, batch)
            touched_edges = list(info["touched_edges"])
        except inc.IncrementalUnsupported as ex:
            log(f"[continual] incremental splice unsupported ({ex}); "
                f"from-scratch build at the pinned assignment")
            g2 = inc.apply_delta_batch(inc.graph_from_artifacts(art), batch)
            _, part_of, _ = inc._global_maps(art)
            new_art = build_artifacts(g2, part_of)
            touched_edges = list(range(n_parts))
            info = {"fallback": str(ex), "new_edges": int(len(batch.edges))}

    # ---- staleness budget: incremental vs re-partition ----
    stats = inc.artifact_stats(new_art)
    repart, why = inc.staleness_decision(
        stats, baseline, cfg.continual_cut_growth, cfg.continual_imbalance)
    if repart:
        g2 = inc.graph_from_artifacts(new_art)
        pid = partition_graph(g2, n_parts, method=cfg.partition_method,
                              obj=cfg.partition_obj, seed=cfg.seed)
        new_art = build_artifacts(g2, pid)
        touched_edges = list(range(n_parts))
        stats = inc.artifact_stats(new_art)
        baseline = stats            # drift resets against the fresh cut
        repartition_why = why
        log(f"[continual] staleness budget crossed ({why}): re-partitioned "
            f"from scratch (cut {stats['cut']})")
    digest = artifact_digest(new_art)
    name = f"{base_name}-c{cycle}"
    new_dir = os.path.join(cfg.part_path, name)
    save_artifacts(new_art, new_dir)
    if not repart and not snap:
        inc.migrate_reorder_cache(cfg, art, new_art, touched_edges, log=log)
    if obs is not None:
        obs.emit("artifact_update", cycle=cycle, dir=new_dir, digest=digest,
                 repartitioned=bool(repart), reason=repartition_why or "",
                 cut=int(stats["cut"]), imbalance=float(stats["imbalance"]),
                 touched=sorted(int(p) for p in touched_edges),
                 new_edges=int(info.get("new_edges", 0)),
                 consumed_from=consumed, consumed_to=new_consumed,
                 elapsed_s=round(time.perf_counter() - t0, 3))
    log(f"[continual] cycle {cycle}: folded deltas [{consumed}, "
        f"{new_consumed}) into {new_dir} "
        f"({'re-partitioned' if repart else 'incremental'}, "
        f"digest {digest}, {time.perf_counter() - t0:.1f}s)")

    # ---- warm-start fine-tune on the mutated graph ----
    found = ckpt.serving_checkpoint(cfg, log=log)
    if found is None:
        raise ConfigError(
            f"no usable serving checkpoint under {cfg.ckpt_path} to "
            f"warm-start from — train once before running continual")
    warm_path, warm_payload = found
    g2 = inc.graph_from_artifacts(new_art)
    before_acc = _eval_acc(*_restore_templates(cfg, warm_payload, g2), g2,
                           cfg.edge_chunk)
    cfg2 = cfg.replace(graph_name=name, skip_partition=True, resume=False,
                       n_epochs=cfg.cycle_epochs, warm_start=warm_path,
                       cycle_nonce=cycle, inductive=False, eval=True,
                       # a short fine-tune must still eval (and so
                       # checkpoint a best model) at least once
                       log_every=max(1, min(cfg.log_every,
                                            cfg.cycle_epochs)),
                       ckpt_path=os.path.join(cfg.ckpt_path,
                                              f"continual_c{cycle}"))
    res = run_training(cfg2, g=g2, art=new_art, verbose=False)
    after_acc = float(res.best_val_acc)

    # ---- promotion gate + publish ----
    promoted = False
    if after_acc + cfg.continual_acc_drop < before_acc:
        log(f"[continual] cycle {cycle}: fine-tuned val acc {after_acc:.4f} "
            f"regressed past the gate (old weights on the same graph: "
            f"{before_acc:.4f}, budget {cfg.continual_acc_drop}) — keeping "
            f"the serving weights (the consumed cursor still advances)")
        if obs is not None:
            obs.emit("promote", status="rolled_back", cycle=cycle,
                     before_acc=round(before_acc, 6),
                     after_acc=round(after_acc, 6))
    else:
        tuned = ckpt.serving_checkpoint(cfg2, log=log)
        if tuned is None:
            raise ConfigError(
                f"fine-tune cycle {cycle} left no usable checkpoint under "
                f"{cfg2.ckpt_path}")
        tuned_path, tuned_payload = tuned
        from bnsgcn_tpu.evaluate import full_graph_embeddings
        p2, s2, spec2 = _restore_templates(cfg, tuned_payload, g2)
        hidden, logits = full_graph_embeddings(p2, s2, spec2, g2,
                                               cfg.edge_chunk)
        promo = ckpt.write_promotion(
            serve_dir, params=p2, bn_state=s2, hidden=hidden, logits=logits,
            lineage={"cycle": cycle, "consumed": int(new_consumed),
                     "artifact_dir": new_dir, "artifact_digest": digest,
                     "ckpt": tuned_path,
                     "before_acc": round(before_acc, 6),
                     "after_acc": round(after_acc, 6)})
        promoted = True
        adopt = None
        if source == "server":
            from bnsgcn_tpu import serve
            adopt = serve.request(cfg.serve_port,
                                  {"op": "promote", "path": promo},
                                  addr=cfg.serve_addr or "127.0.0.1",
                                  timeout_s=60.0)
            if not adopt.get("ok"):
                log(f"[continual] server declined the promotion "
                    f"({adopt.get('err')}); the blob stays published for "
                    f"startup adoption")
        log(f"[continual] cycle {cycle}: promoted {promo} (val "
            f"{before_acc:.4f} -> {after_acc:.4f}"
            + (f", adopted live, {adopt.get('dirty', 0)} node(s) re-marked"
               if adopt and adopt.get("ok") else ", adopt-at-startup") + ")")

    if obs is not None:
        obs.emit("continual_cycle", cycle=cycle, source=source,
                 consumed_from=consumed, consumed_to=new_consumed,
                 repartitioned=bool(repart), artifact_dir=new_dir,
                 digest=digest, before_acc=round(before_acc, 6),
                 after_acc=round(after_acc, 6), promoted=promoted,
                 test_acc=round(float(res.test_acc), 6),
                 epochs=int(cfg.cycle_epochs))

    save_state(serve_dir, {
        "cycle": cycle, "consumed": int(new_consumed),
        "artifact_dir": new_dir, "base_artifact_dir": base_dir,
        "graph_name": base_name, "baseline": {
            "cut": int(baseline["cut"]),
            "edges": [int(e) for e in baseline["edges"]],
            "imbalance": float(baseline["imbalance"])},
        "last": {"promoted": promoted, "before_acc": before_acc,
                 "after_acc": after_acc, "digest": digest}})
    return {"ok": True, "cycle": cycle, "promoted": promoted,
            "consumed": int(new_consumed), "artifact_dir": new_dir,
            "before_acc": before_acc, "after_acc": after_acc}


def continual_main(argv=None) -> int:
    """`python -m bnsgcn_tpu.main continual ...` — one-shot (--cycles 1)
    or looped train->deploy cycles."""
    cfg = parse_config(argv)
    if not cfg.graph_name:
        cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    log = print
    obs = obs_mod.make_obs(cfg, rank=0, log=log)
    rc = 0
    try:
        for _ in range(max(int(cfg.cycles), 1)):
            out = run_cycle(cfg, log=log, obs=obs)
            if out.get("noop"):
                break
    except (ConfigError, ckpt.CheckpointCorrupt, inc.IncrementalError,
            FileNotFoundError) as ex:
        print(f"[config] {ex}", file=sys.stderr)
        rc = 2
    finally:
        if obs is not None:
            obs.close()
    return rc


if __name__ == "__main__":
    sys.exit(continual_main())
