"""Out-of-band rank coordination for multi-host resilience.

XLA collectives are the WRONG channel for failure verdicts: a rank that just
received SIGTERM (or whose loss went NaN, or whose checkpoint is torn) must
tell its peers *without* entering another collective — on a preemptible pod a
fault on one rank otherwise hangs every other rank inside the next
all-reduce until an external watchdog kills the job (ROADMAP, PR 4 follow-
up). This module is that side channel: a tiny key-value coordinator that
rank 0 serves and every rank (including 0) talks to, carrying only
host-side control state — never tensors.

Two transports, selected by `--coord`:

* **tcp** (default for multi-rank runs) — rank 0 binds a threaded line-JSON
  KV server on `--coord-port`; clients open one short-lived connection per
  request. The server thread keeps answering peers even while rank 0's main
  thread is stuck inside a hung collective — exactly the failure the peer
  liveness dump must observe.
* **file** — a shared-filesystem directory (`--coord-dir`, default
  `{ckpt_path}/.coord`): put = atomic rename, get = poll, liveness = mtime.
  No sockets at all; useful where only the checkpoint filesystem is shared.

Every exchange has a bounded deadline (`$BNSGCN_COORD_TIMEOUT_S`, default
120 s) with exponential poll backoff — there is no way to wait forever. On
expiry the coordinator prints the peer-liveness table (who last heartbeat,
at which epoch) and raises `CoordTimeout`, which `main.py` maps to the
watchdog exit code 77: a hung collective now *names the rank that stalled*.

The collectives built on the KV store (`agree`, `broadcast`, `gather_ok`)
assume lockstep call order across ranks — guaranteed because every rank
performs exactly one exchange per step boundary and acts on the same agreed
decision. A per-coordinator sequence number isolates successive exchanges
(a rollback revisits epochs, so epoch numbers alone would collide).

Needs no jax and no XLA collectives, so the whole layer — and the recovery
paths above it — is provable with real subprocesses on the CPU container
where jaxlib refuses multiprocess computations (tests/test_coord_e2e.py).
`--coord off` constructs none of this and is bit-identical to the
uncoordinated loop.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from typing import Callable, Optional

__all__ = [
    "CoordError", "CoordTimeout", "CoordAbort", "CoordCancelled",
    "Coordinator", "TcpTransport", "FileTransport", "make_coordinator",
    "STATE_PRIORITY", "reduce_states",
    "LineJsonServer", "rpc_line_json", "probe_line_json",
]


class CoordError(Exception):
    """Base class for coordination failures."""


class CoordTimeout(CoordError):
    """A bounded exchange expired: a peer (or the rank-0 server) stopped
    responding. main.py maps this to the watchdog exit code (77); the peer
    liveness table was already printed by the raising coordinator."""


class CoordAbort(CoordError):
    """The ranks agreed to abort (a peer cannot restore the chosen state,
    or a peer reported an unrecoverable fault). main.py maps this to
    EXIT_COORD_ABORT (78) — needs triage, not a blind requeue."""


class CoordCancelled(CoordError):
    """An in-flight pooled request was cancelled from another thread
    (LineJsonClient.cancel) — the hedged-read loser path. Distinct from
    CoordTimeout so callers never mistake a deliberate abort for a dead
    peer and mark the backend unhealthy."""


# local step-boundary states, worst-wins; the agreed decision is the reduce
# of every rank's contribution. 'diverged' outranks 'preempted': a preempt
# checkpoint written from NaN state would poison the resume, so the rollback
# happens first and the still-set preempt flag fires at the next boundary.
# 'lost' is never contributed locally — rank 0 imputes it (elastic mode)
# for a peer whose process is provably gone; it outranks 'diverged' because
# the RESIZE restores the agreed checkpoint anyway, healing the divergence
# with the same restore while the member set actually matches the verdict.
STATE_PRIORITY = {"ok": 0, "preempted": 1, "diverged": 2, "lost": 3,
                  "abort": 4}
_DECISION_OF = {"ok": "ok", "preempted": "preempt", "diverged": "rollback",
                "lost": "resize", "abort": "abort"}


def reduce_states(states: dict[int, str]) -> str:
    """Worst local state across ranks -> the agreed decision name."""
    worst = max(states.values(), key=lambda s: STATE_PRIORITY.get(s, 99))
    return _DECISION_OF.get(worst, "abort")


def _now() -> float:
    return time.time()


def _host() -> str:
    """Sanitized short hostname for the FileTransport run token (the token
    prefixes flat file names, so only filename-safe characters)."""
    h = socket.gethostname()
    return ("".join(c if c.isalnum() or c in ".-" else "-" for c in h)[:64]
            or "host")


def _token_is_dead(token: str) -> bool:
    """True when `token` was minted by a same-host process that no longer
    exists — a previous run's leftover `.boot`. Cross-host tokens can't be
    probed and are trusted as-is."""
    host, sep, rest = token.partition(":")
    if not sep or host != _host():
        return False
    try:
        pid = int(rest.split("-", 1)[0], 16)
    except ValueError:
        return True         # malformed = torn write, never adopt
    try:
        os.kill(pid, 0)
        return False
    except ProcessLookupError:
        return True
    except OSError:
        return False        # EPERM etc.: alive under another uid


# ----------------------------------------------------------------------------
# transports: a key-value store with put / blocking-get / liveness dump
# ----------------------------------------------------------------------------

class _KVStore:
    """In-memory store behind the rank-0 TCP server. Tracks the server-side
    receive time of every put so liveness ages are measured on one clock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, tuple[str, float]] = {}  # guarded-by: self._lock

    def put(self, key: str, value: str):
        with self._lock:
            self._data[key] = (value, _now())

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            hit = self._data.get(key)
        return hit[0] if hit else None

    def delete(self, key: str):
        with self._lock:
            self._data.pop(key, None)

    def dump(self, prefix: str) -> dict[str, tuple[str, float]]:
        now = _now()
        with self._lock:
            return {k: (v, now - t) for k, (v, t) in self._data.items()
                    if k.startswith(prefix)}


class _LineJsonHandler(socketserver.StreamRequestHandler):
    timeout = 10.0

    def handle(self):
        try:
            self.connection.setsockopt(socket.IPPROTO_TCP,
                                       socket.TCP_NODELAY, 1)
        except OSError:
            pass
        try:
            # persistent connections: keep answering request lines until the
            # client closes (one-shot clients send one line then FIN, so the
            # loop exits promptly; pooled clients amortize the TCP handshake
            # across many requests — the router -> backend forwarding path)
            while True:
                line = self.rfile.readline(1 << 20)
                if not line:
                    return
                req = json.loads(line)
                try:
                    resp = self.server.handle_fn(req)     # type: ignore[attr-defined]
                except Exception as ex:                   # noqa: BLE001
                    # a handler bug answers the one request with an error —
                    # it never takes the server (or its siblings) down
                    resp = {"ok": False, "err": f"{type(ex).__name__}: {ex}"}
                if resp is None:
                    # the handler opted to tear the connection without a
                    # response (serving-fault injection: 'servedrop')
                    return
                self.wfile.write(json.dumps(resp).encode() + b"\n")
                self.wfile.flush()
        except (OSError, ValueError, KeyError):
            pass        # a torn request never takes the server down


class LineJsonServer(socketserver.ThreadingTCPServer):
    """Threaded one-line-JSON-per-connection TCP server: each request is a
    single JSON line, dispatched to `handle_fn(dict) -> dict`, answered with
    one JSON line. The transport layer both the rank coordinator (KV verdict
    store, below) and the online inference server (serve.py) run on — one
    wire protocol, one framing implementation."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, port: int, handle_fn: Callable[[dict], dict],
                 addr: str = ""):
        super().__init__((addr, port), _LineJsonHandler)
        self.handle_fn = handle_fn
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="bnsgcn-linejson-server",
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        return self.server_address[1]

    def stop(self):
        self.shutdown()
        self.server_close()


def rpc_line_json(addr: str, port: int, req: dict, deadline: float,
                  what: str = "coordinator", retry_sent: bool = True) -> dict:
    """One request/response round trip against a LineJsonServer, retried
    with backoff until `deadline` (connect refusals during peer startup are
    expected — retrying makes client/server start order free).

    `retry_sent=False` never re-sends a request the server may already have
    received: once the payload went out, a torn/slow response raises
    instead of retrying, and the per-attempt read timeout stretches to the
    full remaining deadline. The KV coordinator's ops are idempotent so it
    keeps the resilient default; serve clients (add_edges, flush) are NOT —
    a silent re-send would ingest a delta twice or start a second flush."""
    delay = 0.05
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise CoordTimeout(
                f"{what} at {addr}:{port} unreachable "
                f"(op {req.get('op')!r} key {req.get('k', '')!r})")
        sent = False
        try:
            with socket.create_connection(
                    (addr, port),
                    timeout=min(max(remaining, 0.05), 5.0)) as s:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                s.settimeout(max(remaining, 0.05) if not retry_sent
                             else min(max(remaining, 0.05), 10.0))
                s.sendall(json.dumps(req).encode() + b"\n")
                sent = True
                line = s.makefile("rb").readline(1 << 20)
            if line:
                return json.loads(line)
        except (OSError, ValueError) as ex:
            if sent and not retry_sent:
                err = CoordTimeout(
                    f"{what} at {addr}:{port} accepted op "
                    f"{req.get('op')!r} but the response was lost "
                    f"({type(ex).__name__}: {ex}); not re-sending a "
                    f"non-idempotent request — check server state before "
                    f"retrying")
                # the payload reached the wire: the server MAY have applied
                # it. Callers that queue failed writes for replay (the
                # router's failover WAL) must treat this as
                # delivered-unknown, never as safe-to-resend.
                err.request_sent = True
                raise err from ex
        if sent and not retry_sent:
            # connection closed with no response line: same at-most-once rule
            err = CoordTimeout(
                f"{what} at {addr}:{port} closed the connection after op "
                f"{req.get('op')!r} was sent; not re-sending a "
                f"non-idempotent request")
            err.request_sent = True
            raise err
        time.sleep(min(delay, max(deadline - time.monotonic(), 0)))
        delay = min(delay * 2, 1.0)


class LineJsonClient:
    """Pooled persistent connection to one LineJsonServer peer.

    Amortizes the per-request TCP handshake the one-shot `rpc_line_json`
    pays: the socket stays open across calls (the handler loop on the server
    side keeps answering lines until EOF). ONLY safe for idempotent requests
    — on a torn response the request is retried ONCE over a fresh
    connection, so a non-idempotent op could execute twice; route those
    through `rpc_line_json(..., retry_sent=False)` instead.

    Thread-safe: one in-flight request at a time per client (the line
    protocol has no request ids to demux interleaved responses)."""

    def __init__(self, addr: str, port: int, timeout_s: float = 30.0,
                 what: str = "peer"):
        self.addr, self.port = addr, port
        self.timeout_s = timeout_s
        self.what = what
        self._lock = threading.Lock()
        self._sock = None           # guarded-by: self._lock
        self._rfile = None          # guarded-by: self._lock
        self._cancelled = False     # set lock-FREE by cancel(); read by
                                    # the in-flight request holding _lock
        self._cancel_sock = None    # lock-FREE alias of _sock for cancel()
                                    # (atomic ref read; see cancel())

    def _connect_locked(self):
        s = socket.create_connection((self.addr, self.port),
                                     timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self.timeout_s)
        self._sock, self._rfile = s, s.makefile("rb")
        self._cancel_sock = s

    def _close_locked(self):
        for f in (self._rfile, self._sock):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._sock = self._rfile = self._cancel_sock = None

    def _round_trip_locked(self, payload: bytes) -> dict:
        if self._sock is None:
            self._connect_locked()
        self._sock.sendall(payload)
        line = self._rfile.readline(1 << 20)
        if not line:
            raise OSError("connection closed by peer")
        return json.loads(line)

    def request(self, req: dict) -> dict:
        """One idempotent round trip; retries once on a fresh connection."""
        payload = json.dumps(req).encode() + b"\n"
        with self._lock:
            self._cancelled = False
            try:
                return self._round_trip_locked(payload)
            except (OSError, ValueError):
                if self._cancelled:
                    # deliberate abort from cancel(): do NOT retry — the
                    # caller (a hedged-read loser) wants out, and a retry
                    # would re-issue a request nobody is waiting for
                    self._close_locked()
                    raise CoordCancelled(
                        f"{self.what} at {self.addr}:{self.port} request "
                        f"(op {req.get('op')!r}) cancelled in flight")
                # stale pooled socket (idle-timeout FIN, peer restart):
                # retry exactly once over a fresh connection
                self._close_locked()
                try:
                    return self._round_trip_locked(payload)
                except (OSError, ValueError) as ex:
                    self._close_locked()
                    if self._cancelled:
                        raise CoordCancelled(
                            f"{self.what} at {self.addr}:{self.port} "
                            f"request (op {req.get('op')!r}) cancelled in "
                            f"flight") from ex
                    raise CoordTimeout(
                        f"{self.what} at {self.addr}:{self.port} "
                        f"unreachable (op {req.get('op')!r}): "
                        f"{type(ex).__name__}: {ex}") from ex

    def cancel(self):
        """Abort the in-flight request from ANOTHER thread: shuts the
        pooled socket down so the blocked read fails now, and the victim
        raises CoordCancelled instead of retrying. Deliberately lock-free
        — the victim holds `_lock` for the whole round trip, so taking it
        here would deadlock until the timeout this call exists to beat.
        A no-op when nothing is in flight."""
        self._cancelled = True
        s = self._cancel_sock
        if s is not None:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    def close(self):
        with self._lock:
            self._close_locked()


def probe_line_json(addr: str, port: int, timeout_s: float = 1.0,
                    what: str = "backend") -> dict:
    """One liveness probe against a LineJsonServer: a single fresh-socket
    ping with NO retry and NO backoff — the health checker's primitive.

    Deliberately not pooled and not `rpc_line_json` (which retries until a
    deadline): a probe must report THIS attempt's truth, because the
    caller's consecutive-failure counter is the retry policy. Returns
    `{"ok": True, "rtt_s": ...}` plus the server's ping payload, or
    `{"ok": False, "err": ...}` on any failure within `timeout_s`."""
    t0 = time.monotonic()
    try:
        with socket.create_connection((addr, port), timeout=timeout_s) as s:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(timeout_s)
            s.sendall(b'{"op": "ping"}\n')
            line = s.makefile("rb").readline(1 << 20)
        resp = json.loads(line) if line else None
        if not (isinstance(resp, dict) and resp.get("ok")):
            return {"ok": False,
                    "err": f"{what} at {addr}:{port} answered {resp!r}"}
        resp["rtt_s"] = time.monotonic() - t0
        return resp
    except (OSError, ValueError) as ex:
        return {"ok": False,
                "err": f"{what} at {addr}:{port}: "
                       f"{type(ex).__name__}: {ex}"}


def _kv_handle(store: _KVStore, req: dict) -> dict:
    op = req.get("op")
    if op == "put":
        store.put(req["k"], req["v"])
        return {"ok": True}
    if op == "get":
        v = store.get(req["k"])
        return {"ok": v is not None, "v": v}
    if op == "del":
        store.delete(req["k"])
        return {"ok": True}
    if op == "dump":
        return {"ok": True, "items": store.dump(req.get("p", ""))}
    if op == "ping":
        return {"ok": True}
    return {"ok": False, "err": f"unknown op {op!r}"}


class TcpTransport:
    """Rank 0 hosts the KV server; every rank (rank 0 included — one code
    path) talks to it with one short-lived connection per request, retrying
    with backoff on connect failures so client startup order is free."""

    def __init__(self, addr: str, port: int, serve: bool):
        self.addr, self.port = addr, port
        self._server = None
        if serve:
            store = _KVStore()
            self._server = LineJsonServer(
                port, lambda req: _kv_handle(store, req)).start()

    # -- one request/response round trip, retried until `deadline` --
    def _rpc(self, req: dict, deadline: float) -> dict:
        return rpc_line_json(self.addr, self.port, req, deadline)

    def put(self, key: str, value: str, deadline: float):
        self._rpc({"op": "put", "k": key, "v": value}, deadline)

    def try_get(self, key: str, deadline: float) -> Optional[str]:
        resp = self._rpc({"op": "get", "k": key}, deadline)
        return resp.get("v") if resp.get("ok") else None

    def delete(self, key: str, deadline: float):
        self._rpc({"op": "del", "k": key}, deadline)

    def dump(self, prefix: str, deadline: float) -> dict:
        resp = self._rpc({"op": "dump", "p": prefix}, deadline)
        return resp.get("items", {}) if resp.get("ok") else {}

    def close(self):
        if self._server is not None:
            self._server.stop()
            self._server = None


class FileTransport:
    """Shared-directory KV: put = write-tmp + atomic rename, get = read,
    liveness age = file mtime. Key slashes map to '@' so every key is one
    flat file. No server process — nothing to outlive or crash.

    Unlike the TCP store (in-memory, dies with the run) the directory
    OUTLIVES a run, and sequence numbers restart at 0 — a resumed run must
    never read the previous run's keys (e.g. adopt a stale 'preempt'
    decision at the same seq). So every run gets a fresh namespace: rank 0
    purges the directory and publishes a run token in `.boot`; peers adopt
    the token before their first exchange and every key is prefixed with
    it. A peer that races ahead of a RELAUNCHING rank 0 (a tpu_watchdog5
    requeue — the previous run's `.boot` AND its keys, under the same
    deterministic names, are still on disk) must not adopt the dead run's
    namespace: the token embeds the minting host+pid, and a peer rejects a
    same-host token whose process is gone, polling until the new rank 0
    purges and re-mints. A token is only PINNED once a get under it
    succeeds; every miss before that re-reads `.boot`. Cross-host minting
    (the future GCS-fuse pod transport) cannot be pid-probed — there the
    relaunch must use a fresh --coord-dir (ROADMAP)."""

    BOOT = ".boot"

    def __init__(self, root: str, rank: int):
        self.root = root
        self._rank = rank
        os.makedirs(root, exist_ok=True)
        # same time seam as Coordinator: the `.boot` poll below goes
        # through these so analysis/proto can explore relaunch races
        # under a virtual clock (production: the stdlib functions).
        self._clock = time.monotonic
        self._sleep = time.sleep
        self._token: Optional[str] = None
        self._pinned = False        # peers: token confirmed by a real get
        if rank == 0:
            for fn in os.listdir(root):
                try:
                    os.unlink(os.path.join(root, fn))
                except OSError:
                    pass        # a peer's in-flight tmp file — harmless
            self._token = f"{_host()}:{os.getpid():x}-{int(_now() * 1000):x}"
            self._pinned = True
            tmp = os.path.join(root, f"{self.BOOT}.tmp0")
            with open(tmp, "w") as f:
                f.write(self._token)
            os.replace(tmp, os.path.join(root, self.BOOT))

    def _ns(self, deadline: float) -> str:
        """This run's key namespace: rank 0 minted it; peers poll `.boot`,
        refusing a token whose same-host minting process is dead (the
        previous run's leftover) until the new rank 0 re-mints."""
        delay = 0.02
        while self._token is None:
            try:
                with open(os.path.join(self.root, self.BOOT)) as f:
                    tok = f.read().strip() or None
            except OSError:
                tok = None
            if tok is not None and not _token_is_dead(tok):
                self._token = tok
                break
            if self._clock() >= deadline:
                raise CoordTimeout(
                    f"rank {self._rank}: no {self.BOOT} run token in "
                    f"{self.root} (is rank 0 up?)")
            self._sleep(min(delay, max(deadline - self._clock(), 0)))
            delay = min(delay * 2, 0.5)
        return self._token

    def _path(self, key: str, deadline: float) -> str:
        return os.path.join(
            self.root, self._ns(deadline) + "@" + key.replace("/", "@"))

    def put(self, key: str, value: str, deadline: float):
        path = self._path(key, deadline)
        tmp = f"{path}.tmp.{self._rank}"
        with open(tmp, "w") as f:
            f.write(value)
        os.replace(tmp, path)

    def try_get(self, key: str, deadline: float) -> Optional[str]:
        try:
            with open(self._path(key, deadline)) as f:
                v = f.read()
            self._pinned = True     # a hit proves the token is this run's
            return v
        except OSError:
            if not self._pinned:
                # provisional token may be the previous run's leftover
                # .boot — drop it so the next poll re-reads what rank 0
                # has (re-)minted by then
                self._token = None
            return None

    def delete(self, key: str, deadline: float):
        try:
            os.unlink(self._path(key, deadline))
        except OSError:
            pass        # already gone / transient fs error — prune retries

    def dump(self, prefix: str, deadline: float) -> dict:
        ns = self._ns(deadline) + "@"
        pfx = ns + prefix.replace("/", "@")
        out = {}
        now = _now()
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for fn in names:
            if not fn.startswith(pfx) or fn.rpartition(".")[2].isdigit():
                continue        # skip in-flight .tmp.<rank> files
            path = os.path.join(self.root, fn)
            try:
                with open(path) as f:
                    v = f.read()
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            out[fn[len(ns):].replace("@", "/")] = (v, age)
        return out

    def close(self):
        pass


# ----------------------------------------------------------------------------
# the coordinator: collectives over the KV store
# ----------------------------------------------------------------------------

class Coordinator:
    """One per rank per run. All collectives are worst-case-bounded by
    `timeout_s` per phase; every raise path first prints peer liveness."""

    ALIVE_KEY = "wa"        # watchdog-thread heartbeat (process is alive)
    STEP_KEY = "hb"         # step-boundary heartbeat (training is advancing)
    PRUNE_HORIZON = 16      # collectives a spent exchange's keys survive.
                            # Peers lag rank 0 by at most the longest run of
                            # consecutive broadcasts (rank 0 returns without
                            # waiting on those; <= 4 anywhere in the code —
                            # every agree/gather_ok re-syncs), so 16 is
                            # comfortably past any legal drift.

    def __init__(self, rank: int, world: int, transport, timeout_s: float,
                 log=print):
        if world < 2:
            raise ValueError("Coordinator needs world >= 2 "
                             "(use --coord off for single-rank runs)")
        self.rank = int(rank)
        self.world = int(world)
        self.transport = transport
        self.timeout_s = float(timeout_s)
        self.log = log
        # time seam: every wait in this class goes through these two
        # attributes so the protocol checker (analysis/proto) can run the
        # real collectives under a virtual clock. Production constructs
        # nothing extra — these ARE the stdlib functions.
        self._clock = time.monotonic
        self._sleep = time.sleep
        self.last_infos: dict[int, dict] = {}   # rank 0: the piggybacked
                            # per-rank info payloads of the latest agree()
                            # (obs epoch summaries — merged into ONE
                            # cross-rank record with no extra collective)
        self._seq = 0       # collective counter: all ranks call collectives
                            # in lockstep, so equal seq == the same exchange
        self._spent: list[tuple[int, list[str]]] = []   # rank 0: (seq, keys)
                            # of completed exchanges, pruned past the horizon
        self._closed = False
        # elastic membership: the live rank ids, never renumbered (transport
        # keys keep the original rank numbers). A RESIZE verdict shrinks or
        # grows this set; `world` tracks len(members). Non-elastic runs never
        # change it, so members == range(world) and every loop below is
        # byte-identical to the historical range() form.
        self.members: tuple[int, ...] = tuple(range(self.world))
        self.elastic = False
        self.min_world = 1
        # a peer is provably dead once its alive-beat (the watchdog thread's
        # 2 s cadence, resilience._Watchdog.ALIVE_BEAT_S) is this stale
        self.dead_after_s = float(os.environ.get("BNSGCN_ELASTIC_DEAD_S",
                                                 6.0))
        self._peer_dead = self._liveness_dead   # seam: analysis/proto wires
                            # scheduler ground truth (the sim runs no
                            # watchdog thread feeding alive heartbeats)
        self._lost: set[int] = set()    # rank 0: ranks resized away, still
                            # owed a rejoin beacon (el/lost/<r>)
        # agree cadence: exchange verdicts every K step boundaries; local
        # states latch worst-wins in between. All ranks read the same env
        # knob and count calls in lockstep, so the boundary schedule is
        # globally consistent and `_seq` never drifts.
        self.agree_every = max(1, int(os.environ.get(
            "BNSGCN_COORD_AGREE_EVERY", "1") or 1))
        self._agree_calls = 0
        self._latched = "ok"

    # -- plumbing --

    def _deadline(self, timeout_s: Optional[float] = None) -> float:
        return self._clock() + (self.timeout_s if timeout_s is None
                                else timeout_s)

    def _peers(self) -> list[int]:
        return [r for r in self.members if r != self.rank]

    def _get(self, key: str, deadline: float, what: str) -> str:
        """Blocking get with poll backoff; CoordTimeout (after a liveness
        dump) once the deadline passes. The initial poll is fine-grained
        (2 ms) because this sits on the healthy per-epoch agree path —
        every peer's first decision fetch almost always misses while rank 0
        gathers, and a 20 ms granularity there would tax fast full-graph
        epochs by a comparable amount; backoff caps at 50 ms so a pending
        key costs at most one extra poll interval of latency while an
        absent peer costs ~20 polls/s, not a busy loop burning a core."""
        delay = 0.002
        while True:
            try:
                v = self.transport.try_get(key, deadline)
            except CoordTimeout:
                v = None        # transport-level expiry: fall through to the
                                # descriptive raise (with liveness) below
            if v is not None:
                return v
            if self._clock() >= deadline:
                self.log_liveness()
                raise CoordTimeout(
                    f"rank {self.rank}: timed out waiting for {what} "
                    f"(key {key!r}; per-exchange bound {self.timeout_s:.1f}s)")
            self._sleep(min(delay, max(deadline - self._clock(), 0)))
            delay = min(delay * 2, 0.05)

    def _put(self, key: str, value: str, deadline: Optional[float] = None):
        self.transport.put(key, value,
                           deadline if deadline is not None
                           else self._deadline())

    def _retire(self, seq: int, keys: list[str]):
        """Rank 0, best-effort: remember a completed exchange's per-seq keys
        and delete the ones older than PRUNE_HORIZON, so a long run's KV
        store stays O(world), not O(epochs) — the agree() per epoch would
        otherwise grow rank 0's store (and the --coord file dir the
        liveness dump os.listdir's) for the run's whole lifetime."""
        if self.rank != 0:
            return
        self._spent.append((seq, keys))
        cutoff = seq - self.PRUNE_HORIZON
        deadline = self._deadline(min(5.0, self.timeout_s))
        keep = []
        for s, ks in self._spent:
            if s > cutoff:
                keep.append((s, ks))
                continue
            for k in ks:
                try:
                    self.transport.delete(k, deadline)
                except (CoordError, OSError):
                    pass        # a missed prune only leaks one tiny key
        self._spent = keep

    # -- heartbeats / liveness --

    def heartbeat(self, epoch: int, kind: str = "hb"):
        """Best-effort: a failed heartbeat must never take down the rank
        that is still healthy enough to send one."""
        key = f"{kind}/{self.rank}"
        try:
            self._put(key, json.dumps({"epoch": int(epoch), "t": _now()}),
                      self._deadline(min(5.0, self.timeout_s)))
        except (CoordError, OSError):
            # OSError: FileTransport.put hits the raw filesystem (ENOSPC,
            # a flaky NFS) — same best-effort contract as a dead server
            pass

    def liveness(self) -> dict[int, dict]:
        """{rank: {'epoch', 'step_age_s', 'alive_age_s'}} from the server's
        receive clock (file transport: mtimes). Missing entries mean the
        rank never reported."""
        out: dict[int, dict] = {r: {} for r in self.members}
        deadline = self._deadline(min(5.0, self.timeout_s))
        for kind, field in ((self.STEP_KEY, "step_age_s"),
                            (self.ALIVE_KEY, "alive_age_s")):
            try:
                items = self.transport.dump(f"{kind}/", deadline)
            except CoordError:
                continue
            for key, (v, age) in items.items():
                try:
                    r = int(key.rsplit("/", 1)[1])
                    out[r][field] = float(age)
                    if kind == self.STEP_KEY:
                        out[r]["epoch"] = int(json.loads(v).get("epoch", -1))
                except (ValueError, KeyError, IndexError):
                    continue
        return out

    def log_liveness(self, write=None):
        """Print the per-rank heartbeat table — the watchdog and every
        timeout path call this so a hung collective names its straggler."""
        write = write or (lambda s: self.log(s))
        try:
            live = self.liveness()
        except Exception:
            write("[coord] peer liveness unavailable (coordinator "
                  "unreachable)")
            return
        ages = {r: info.get("step_age_s", float("inf"))
                for r, info in live.items()}
        stalest, stale_age = max(ages.items(), key=lambda kv: kv[1])
        # only finger a rank when it is genuinely behind its peers (or
        # never reported while others did): everyone-fresh,
        # everyone-equally-old and nobody-reported-yet dumps should not
        # invent a culprit
        freshest = min(ages.values())
        if stale_age == float("inf"):
            if freshest == float("inf"):
                stalest = None      # startup failure before ANY heartbeat
        elif stale_age - freshest < 10.0:
            stalest = None
        write(f"[coord] peer liveness (world {self.world}, viewed from "
              f"rank {self.rank}):")
        for r in self.members:
            info = live.get(r, {})
            step = (f"step hb {info['step_age_s']:.1f}s ago "
                    f"(epoch {info.get('epoch', -1)})"
                    if "step_age_s" in info else "no step heartbeat")
            alive = (f"alive {info['alive_age_s']:.1f}s ago"
                     if "alive_age_s" in info else "no alive heartbeat")
            mark = "   <- stalled" if r == stalest else ""
            write(f"[coord]   rank {r}: {step}, {alive}{mark}")

    def _liveness_dead(self, ranks: list[int]) -> list[int]:
        """Subset of `ranks` whose process is provably gone: the alive-beat
        (the watchdog thread's, independent of step progress) is older than
        `dead_after_s`. A rank with NO alive beat on record is NOT imputed
        dead — a startup race must time out loudly, never resize."""
        try:
            live = self.liveness()
        except CoordError:
            return []
        out = []
        for r in ranks:
            age = live.get(r, {}).get("alive_age_s")
            if age is not None and age > self.dead_after_s:
                out.append(r)
        return out

    def _gather_elastic(self, keymap: dict[int, str], deadline: float,
                        what_fn) -> tuple[dict[int, str], list[int]]:
        """Interleaved gather with dead-peer imputation (elastic mode):
        poll every missing key round-robin; a rank whose process is provably
        gone is imputed 'lost' instead of awaited, so one dead peer costs
        ~`dead_after_s`, not the whole exchange window. An alive-but-silent
        rank still hits the standard CoordTimeout — a hung rank remains a
        77 (on a real pod its own watchdog fires first, converting the hang
        into the very death this path absorbs)."""
        vals: dict[int, str] = {}
        lost: list[int] = []
        missing = dict(keymap)
        delay = 0.002
        check_every = min(1.0, self.dead_after_s / 2)
        next_check = self._clock() + check_every
        while missing:
            for r in sorted(missing):
                try:
                    v = self.transport.try_get(missing[r], deadline)
                except CoordTimeout:
                    v = None
                if v is not None:
                    vals[r] = v
                    del missing[r]
            if not missing:
                break
            now = self._clock()
            if now >= next_check:
                next_check = now + check_every
                for r in self._peer_dead(sorted(missing)):
                    self.log(f"[coord] rank {r} is gone (alive-beat older "
                             f"than {self.dead_after_s:.1f}s) — imputing "
                             f"'lost' instead of waiting on {what_fn(r)}")
                    lost.append(r)
                    del missing[r]
                continue
            if self._clock() >= deadline:
                self.log_liveness()
                r = sorted(missing)[0]
                raise CoordTimeout(
                    f"rank {self.rank}: timed out waiting for {what_fn(r)} "
                    f"(key {missing[r]!r}; per-exchange bound "
                    f"{self.timeout_s:.1f}s)")
            self._sleep(min(delay, max(deadline - self._clock(), 0)))
            delay = min(delay * 2, 0.05)
        return vals, lost

    # -- collectives (lockstep call order across ranks) --

    def agree(self, epoch: int, state: str,
              decide_fn: Optional[Callable[[str, dict], dict]] = None,
              info: Optional[dict] = None, final: bool = False) -> dict:
        """The per-step-boundary agreed verdict.

        Every rank contributes its local state; rank 0 reduces worst-wins
        and publishes one decision dict every rank returns. `decide_fn`
        (rank 0 only) maps (decision_name, {rank: state}) to the full
        decision payload — e.g. choosing the rollback checkpoint/nonce, or
        escalating to abort when retries are exhausted. Terminal decisions
        (anything but 'ok') are confirmed by every rank before rank 0
        returns, so a rank about to exit can never strand a peer that has
        not yet read the verdict.

        `info` piggybacks a small host-side payload (the obs epoch summary:
        loss, step ms) on the verdict this exchange already carries — rank 0
        exposes the gathered `{rank: info}` as `self.last_infos`, so a
        merged cross-rank record costs NO new collective. A rank that
        passes no info keeps the historical bare-string wire value.

        Cadence ($BNSGCN_COORD_AGREE_EVERY = K): only every K-th call (and
        a `final=True` call — the last step boundary, so a latched verdict
        can never die with the run) performs the exchange; in between the
        worst local state latches and an immediate `{'decision': 'ok',
        'deferred': True}` is returned. Verdict latency is therefore at
        most K step boundaries. K=1 (default) is exactly the historical
        every-boundary behavior.

        Elastic mode: a peer whose process is provably dead is imputed
        state 'lost' instead of timing out the exchange; worst-wins then
        maps it to a RESIZE decision (decide_fn supplies the payload)."""
        if (STATE_PRIORITY.get(state, 99)
                > STATE_PRIORITY.get(self._latched, 0)):
            self._latched = state
        calls = self._agree_calls
        self._agree_calls += 1
        if not final and (calls + 1) % self.agree_every != 0:
            return {"decision": "ok", "epoch": int(epoch), "deferred": True}
        state = self._latched
        self._latched = "ok"
        seq = self._seq
        self._seq += 1
        self.heartbeat(epoch, self.STEP_KEY)
        deadline = self._deadline()
        self._put(f"v/{seq}/{self.rank}",
                  state if info is None
                  else json.dumps({"s": state, "i": info}), deadline)
        if self.rank == 0:
            def _parse(v):
                if v.startswith("{"):
                    try:
                        d = json.loads(v)
                        return str(d.get("s", "abort")), d.get("i")
                    except ValueError:
                        return "abort", None
                return v, None

            states = {0: state}
            self.last_infos = {0: info} if info is not None else {}
            lost: list[int] = []
            if self.elastic:
                vals, lost = self._gather_elastic(
                    {r: f"v/{seq}/{r}" for r in self._peers()}, deadline,
                    lambda r: f"rank {r}'s epoch-{epoch} verdict")
                for r in sorted(vals):
                    s, i = _parse(vals[r])
                    states[r] = s
                    if i is not None:
                        self.last_infos[r] = i
                for r in lost:
                    states[r] = "lost"
            else:
                for r in self._peers():
                    s, i = _parse(self._get(
                        f"v/{seq}/{r}", deadline,
                        f"rank {r}'s epoch-{epoch} verdict"))
                    states[r] = s
                    if i is not None:
                        self.last_infos[r] = i
            name = reduce_states(states)
            decision = {"decision": name, "epoch": int(epoch),
                        "states": {str(r): s for r, s in states.items()}}
            if decide_fn is not None:
                decision = decide_fn(name, states)
                decision.setdefault("decision", name)
                decision.setdefault("epoch", int(epoch))
                # decide_fn may have done real checkpoint I/O past the
                # gather deadline — publish on a fresh window (the peers'
                # doubled fetch window below absorbs both)
                deadline = self._deadline()
            self._put(f"d/{seq}", json.dumps(decision), deadline)
        else:
            # the decision window must cover rank 0's gather of EVERY
            # verdict plus decide_fn's checkpoint I/O (plan_rollback reads
            # and checksums real files — multi-GB at papers100M scale), so
            # peers allow one extra timeout before calling rank 0 hung: a
            # healthy large-scale rollback is not a 77. Still bounded.
            decision = json.loads(self._get(
                f"d/{seq}", self._deadline(2 * self.timeout_s),
                f"rank 0's epoch-{epoch} decision"))
        terminal = decision.get("decision", "ok") != "ok"
        if terminal:
            # fresh window: a late-arriving decision (slow decide_fn) must
            # not leave the confirm with an already-expired deadline.
            # A RESIZE verdict's confirm set excludes the ranks it just
            # declared lost — their death is the verdict; waiting a full
            # deadline on each would stall every survivor.
            gone = {int(r) for r in decision.get("lost", [])}
            self._confirm(seq, self._deadline(),
                          ranks=[r for r in self.members if r not in gone])
        self._retire(seq, [f"v/{seq}/{r}" for r in self.members]
                     + [f"d/{seq}"]
                     + ([f"c/{seq}/{r}" for r in self.members]
                        if terminal else []))
        return decision

    def _confirm(self, seq: int, deadline: float,
                 ranks: Optional[list[int]] = None):
        """All (surviving) ranks acknowledge a terminal decision; rank 0
        waits (best effort — a peer that died before confirming must not
        block the survivors' orderly exit past the deadline). `ranks`
        narrows the wait set: a RESIZE must not spend a deadline waiting
        for the very rank whose death it just agreed on."""
        self._put(f"c/{seq}/{self.rank}", "1", deadline)
        if self.rank == 0:
            for r in (self.members if ranks is None else ranks):
                if r == 0:
                    continue
                try:
                    self._get(f"c/{seq}/{r}", deadline,
                              f"rank {r}'s decision confirmation")
                except CoordTimeout:
                    self.log(f"[coord] rank {r} never confirmed the "
                             f"decision (seq {seq}); proceeding")

    def broadcast(self, name: str, payload: Optional[dict] = None) -> dict:
        """Rank 0 publishes `payload`; every rank returns it."""
        seq = self._seq
        self._seq += 1
        deadline = self._deadline()
        if self.rank == 0:
            if payload is None:
                raise ValueError("rank 0 broadcast() needs a payload")
            self._put(f"b/{name}/{seq}", json.dumps(payload), deadline)
            self._retire(seq, [f"b/{name}/{seq}"])
            return payload
        # doubled window like agree()'s decision fetch: rank 0 may be
        # walking the checkpoint chain to compute the payload (resume-choice)
        return json.loads(self._get(f"b/{name}/{seq}",
                                    self._deadline(2 * self.timeout_s),
                                    f"rank 0's {name!r} broadcast"))

    def gather_ok(self, name: str, ok: bool, detail: str = ""
                  ) -> tuple[bool, dict[int, str]]:
        """All-ranks ack: returns (all_ok, {rank: failure detail}). Rank 0
        reduces and publishes, so every rank sees the same verdict and the
        same culprit list."""
        seq = self._seq
        self._seq += 1
        deadline = self._deadline()
        self._put(f"a/{name}/{seq}/{self.rank}",
                  json.dumps({"ok": bool(ok), "detail": detail}), deadline)
        if self.rank == 0:
            # doubled collection window: each peer's ack follows real work
            # (the resume/rollback ack IS a full checkpoint load+checksum),
            # and rank 0 — whose own payload was already validated —
            # arrives here first; a healthy-but-slow peer must not turn an
            # agreed resume into a spurious 77. Mirrors the peers' doubled
            # verdict fetch below.
            gather_dl = self._deadline(2 * self.timeout_s)
            fails: dict[int, str] = {}
            if self.elastic:
                vals, lost = self._gather_elastic(
                    {r: f"a/{name}/{seq}/{r}" for r in self._peers()},
                    gather_dl, lambda r: f"rank {r}'s {name!r} ack")
                vals[self.rank] = json.dumps({"ok": bool(ok),
                                              "detail": detail})
                for r in lost:
                    # a peer that died mid-ack: impute success so the
                    # survivors' exchange completes — the next agree
                    # boundary re-detects the death and resolves it as a
                    # RESIZE verdict instead of stranding this ack
                    self.log(f"[coord] rank {r} died before acking "
                             f"{name!r}; deferring the loss to the next "
                             f"agree boundary")
                for r in sorted(vals):
                    got = json.loads(vals[r])
                    if not got.get("ok"):
                        fails[r] = str(got.get("detail", ""))
            else:
                for r in self.members:
                    got = json.loads(self._get(
                        f"a/{name}/{seq}/{r}", gather_dl,
                        f"rank {r}'s {name!r} ack"))
                    if not got.get("ok"):
                        fails[r] = str(got.get("detail", ""))
            verdict = {"ok": not fails,
                       "fails": {str(r): d for r, d in fails.items()}}
            self._put(f"ad/{name}/{seq}", json.dumps(verdict), deadline)
        else:
            # doubled window like agree()'s decision fetch: rank 0 must
            # first gather EVERY rank's ack (each possibly slow — the
            # resume ack is a full checkpoint load) before publishing
            verdict = json.loads(self._get(
                f"ad/{name}/{seq}", self._deadline(2 * self.timeout_s),
                f"the {name!r} ack verdict"))
        if not verdict["ok"]:
            # a failed ack is terminal (the callers abort on it): confirm
            # like agree() does, so rank 0 cannot tear the server down
            # before every peer has read the verdict it is about to die on.
            # Fresh window: a late-arriving verdict must not leave the
            # confirm already expired (exit 77 masking the agreed 78).
            self._confirm(seq, self._deadline())
        self._retire(seq, [f"a/{name}/{seq}/{r}" for r in self.members]
                     + [f"ad/{name}/{seq}"]
                     + ([f"c/{seq}/{r}" for r in self.members]
                        if not verdict["ok"] else []))
        return (bool(verdict["ok"]),
                {int(r): d for r, d in verdict.get("fails", {}).items()})

    def finish(self):
        """Best-effort completion barrier before rank 0 tears down its KV
        server: ranks drift by up to one step boundary, so the first rank
        to finish must not strand a peer still fetching its last decision.
        Never raises — a peer that died near the end must not turn the
        survivors' clean exit into a failure."""
        try:
            deadline = self._deadline()
            self._put(f"fin/{self.rank}", "1", deadline)
            if self.rank == 0:
                for r in self._peers():
                    try:
                        self._get(f"fin/{r}", deadline,
                                  f"rank {r}'s completion")
                    except CoordTimeout:
                        self.log(f"[coord] rank {r} never reached "
                                 f"completion; closing anyway")
        except CoordError:
            pass

    # -- elastic membership: RESIZE verdicts and the rejoin handshake --
    #
    # Key namespaces OUTSIDE the seq-space collectives (so a joiner can talk
    # to the incumbent run before it holds a seq position):
    #   el/boot       rank 0's bootstrap facts (the seed) a replacement
    #                 needs before it can build anything
    #   el/lost/<r>   persistent beacon: rank r was resized away; its
    #                 replacement probes this to pick the rejoin path
    #   rj/req/<r>    joiner -> rank 0: ready to rejoin (carries a fresh
    #                 per-incarnation token)
    #   rj/ack/<r>    rank 0 -> joiner: the grow grant (echoes the token;
    #                 a stale grant from an earlier incarnation is ignored)

    def enable_elastic(self, min_world: int = 1):
        self.elastic = True
        self.min_world = max(1, int(min_world))

    def publish_boot(self, payload: dict):
        """Rank 0, elastic: persist the run's bootstrap facts for future
        replacement ranks (kept for the whole run — never retired)."""
        self._put("el/boot", json.dumps(dict(payload)))

    def boot_info(self) -> dict:
        return json.loads(self._get("el/boot", self._deadline(),
                                    "the elastic boot record"))

    def detect_rejoin(self) -> bool:
        """Replacement-rank startup probe: this rank was declared lost by an
        incumbent run iff rank 0 left an `el/lost/<rank>` beacon. One
        bounded probe ($BNSGCN_ELASTIC_JOIN_PROBE_S, default 5 s — that is
        only the connect-retry budget; a live server answers instantly).
        Relaunch replacements AFTER the shrink verdict lands (watch for the
        resize obs event), or raise the probe window."""
        probe = float(os.environ.get("BNSGCN_ELASTIC_JOIN_PROBE_S", 5.0))
        try:
            return self.transport.try_get(f"el/lost/{self.rank}",
                                          self._deadline(probe)) is not None
        except CoordError:
            return False

    def apply_resize(self, decision: dict):
        """Adopt an agreed RESIZE: update the member set; rank 0 marks the
        lost ranks (the beacon their replacements probe) and clears their
        stale rejoin keys. Survivors call this BEFORE the resize ack
        exchange so a grow's joiner is already in the gather set."""
        members = tuple(int(r) for r in decision["members"])
        gone = [r for r in self.members if r not in members]
        joined = [r for r in members if r not in self.members]
        self.members = members
        self.world = len(members)
        if self.rank == 0:
            self._lost.update(gone)
            self._lost.difference_update(joined)
            deadline = self._deadline(min(5.0, self.timeout_s))
            for r in gone:
                try:
                    self._put(f"el/lost/{r}", json.dumps({"seq": self._seq}),
                              deadline)
                    self.transport.delete(f"rj/req/{r}", deadline)
                    self.transport.delete(f"rj/ack/{r}", deadline)
                except (CoordError, OSError):
                    pass    # best-effort: a missed beacon only delays rejoin
            for r in joined:
                try:
                    # the grant (rj/ack) stays — the joiner may still be
                    # reading it; its token goes stale with the next req
                    self.transport.delete(f"el/lost/{r}", deadline)
                except (CoordError, OSError):
                    pass
        self.log(f"[coord] world resized to {self.world} "
                 f"(members {list(self.members)}"
                 + (f", lost {gone}" if gone else "")
                 + (f", rejoined {joined}" if joined else "") + ")")

    def poll_rejoin(self) -> list[tuple[int, str]]:
        """Rank 0, at an agree boundary: pending rejoin requests from lost
        ranks. A request for a rank still in `members` is a replacement
        racing an undetected death — ignored until the loss verdict lands
        (the stale-incumbent's silence resolves it within dead_after_s)."""
        if not self._lost:
            return []
        out = []
        deadline = self._deadline(min(5.0, self.timeout_s))
        for r in sorted(self._lost):
            try:
                v = self.transport.try_get(f"rj/req/{r}", deadline)
            except CoordError:
                continue
            if v is None:
                continue
            try:
                tok = str(json.loads(v).get("token", ""))
            except ValueError:
                continue
            if tok:
                out.append((r, tok))
        return out

    def grant_rejoin(self, rank: int, token: str, payload: dict):
        """Rank 0 (inside the grow decide): answer `rank`'s rejoin request.
        The grant echoes the joiner's token so only THIS incarnation of the
        replacement adopts it."""
        body = dict(payload)
        body["token"] = str(token)
        self._put(f"rj/ack/{rank}", json.dumps(body))
        try:
            self.transport.delete(f"rj/req/{rank}",
                                  self._deadline(min(5.0, self.timeout_s)))
        except (CoordError, OSError):
            pass

    def request_rejoin(self, token: str,
                       info: Optional[dict] = None) -> dict:
        """Replacement rank: announce readiness and block until rank 0's
        grant for THIS incarnation. Grants carrying any other token are
        stale (minted for an earlier, dead replacement) and are skipped —
        the wait continues until rank 0 answers the fresh request. Bounded
        by $BNSGCN_ELASTIC_JOIN_WAIT_S (default 2x the exchange timeout);
        the window must cover rank 0 reaching its next agree boundary."""
        self._put(f"rj/req/{self.rank}",
                  json.dumps({"token": str(token), "info": info or {}}))
        wait_s = float(os.environ.get("BNSGCN_ELASTIC_JOIN_WAIT_S",
                                      2 * self.timeout_s))
        deadline = self._deadline(wait_s)
        delay = 0.002
        while True:
            try:
                v = self.transport.try_get(f"rj/ack/{self.rank}", deadline)
            except CoordTimeout:
                v = None
            if v is not None:
                try:
                    grant = json.loads(v)
                except ValueError:
                    grant = {}
                if str(grant.get("token", "")) == str(token):
                    return grant
                # stale grant from a previous incarnation: keep waiting
            if self._clock() >= deadline:
                self.log_liveness()
                raise CoordTimeout(
                    f"rank {self.rank}: no rejoin grant within {wait_s:.1f}s "
                    f"(is the incumbent run still alive and elastic?)")
            self._sleep(min(delay, max(deadline - self._clock(), 0)))
            delay = min(delay * 2, 0.05)

    def adopt_grant(self, grant: dict):
        """Joiner: step into the incumbent run's collective schedule at the
        seq / agree-cadence position the grant names. After this, the very
        next collective call lands in lockstep with the survivors'."""
        self.members = tuple(int(r) for r in grant["members"])
        self.world = len(self.members)
        self._seq = int(grant["seq"])
        self._agree_calls = int(grant.get("agree_calls", 0))
        self._latched = "ok"

    def close(self):
        if not self._closed:
            self._closed = True
            try:
                self.transport.close()
            except Exception:
                pass


# ----------------------------------------------------------------------------
# construction from a Config
# ----------------------------------------------------------------------------

def resolve_rank_world(cfg) -> tuple[int, int]:
    """(rank, world) for coordination: explicit --coord-rank/--coord-world
    override (the subprocess harness / pseudo-multi-host mode); otherwise
    the jax.distributed process grid."""
    if cfg.coord_world and cfg.coord_world > 1:
        if cfg.coord_rank < 0:
            # defaulting to 0 would make every misconfigured peer a serving
            # rank 0: EADDRINUSE on one host, a 2-minute split-brain
            # timeout across hosts — fail as a named config error instead
            raise ValueError(
                "--coord-world > 1 needs an explicit --coord-rank per "
                "process (0..world-1)")
        if cfg.coord_rank >= cfg.coord_world:
            raise ValueError(
                f"--coord-rank {cfg.coord_rank} out of range for "
                f"--coord-world {cfg.coord_world}")
        return int(cfg.coord_rank), int(cfg.coord_world)
    import jax
    return jax.process_index(), jax.process_count()


def make_coordinator(cfg, log=print) -> tuple[Optional["Coordinator"], int, int]:
    """(coordinator | None, rank, world). None when coordination is off:
    `--coord off`, a single-rank run, or `--coord auto` resolving to off —
    all bit-identical to the uncoordinated code path."""
    rank, world = resolve_rank_world(cfg)
    mode = cfg.coord
    if mode == "auto":
        mode = "tcp" if world > 1 else "off"
    if mode == "off" or world < 2:
        return None, rank, world
    timeout_s = float(os.environ.get("BNSGCN_COORD_TIMEOUT_S", 120.0))
    if mode == "tcp":
        addr = cfg.coord_addr or cfg.master_addr or "127.0.0.1"
        transport = TcpTransport(addr, cfg.coord_port, serve=(rank == 0))
    elif mode == "file":
        root = cfg.coord_dir or os.path.join(cfg.ckpt_path, ".coord")
        transport = FileTransport(root, rank)
    else:
        raise ValueError(f"unknown --coord mode {mode!r} "
                         "(tcp | file | auto | off)")
    log(f"[coord] rank {rank}/{world}: {mode} coordinator "
        + (f"at {cfg.coord_addr or cfg.master_addr}:{cfg.coord_port}"
           if mode == "tcp"
           else f"dir {cfg.coord_dir or os.path.join(cfg.ckpt_path, '.coord')}")
        + f", per-exchange timeout {timeout_s:.0f}s")
    return Coordinator(rank, world, transport, timeout_s, log), rank, world
