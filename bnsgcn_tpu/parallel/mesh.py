"""Device mesh construction for partition parallelism.

The reference maps partition rank -> process -> GPU (main.py:35-50, mpirun
path :51-62). Here partitions map onto a 1-D ``('parts',)`` axis of a
`jax.sharding.Mesh`; on a pod slice the axis rides ICI, and a multi-host
papers100M-scale run lays parts over (DCN, ICI) transparently via
`jax.distributed` + `jax.make_mesh`.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-portable `shard_map`: top-level `jax.shard_map` where it
    exists (jax >= 0.5), else the `jax.experimental.shard_map` original
    (0.4.x — the CPU-mesh test container). Call sites only ever pass
    (mesh, in_specs, out_specs), which both signatures accept."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def make_parts_mesh(n_parts: int, devices=None) -> Mesh:
    """1-D mesh with one mesh slot per partition.

    n_parts must divide (or equal) the available device count; with fewer
    devices than parts the caller should re-partition (no oversubscription —
    SPMD shard_map owns the axis)."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < n_parts:
        raise ValueError(
            f"need >= {n_parts} devices for {n_parts} partitions, have {len(devices)}; "
            f"re-partition the graph or use a CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n_parts}")
    return Mesh(np.asarray(devices[:n_parts]), ("parts",))


def parts_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("parts"))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def plan_slots(n_parts: int, n_slots: int) -> tuple[int, ...]:
    """Part -> slot assignment for an elastic world of `n_slots` workers
    hosting `n_parts` METIS parts: contiguous balanced blocks (the first
    `n_parts % n_slots` slots take one extra part), so a RESIZE never
    re-partitions the graph — it only re-hosts whole parts. Contiguity
    matters: METIS orders parts so neighbors tend to be adjacent, and a
    contiguous block keeps the heaviest halo pairs intra-slot (free on the
    resized worker) rather than cross-slot wire. Pure host-side metadata —
    the traced step programs keep the full P-wide 'parts' axis regardless
    (see halo.HaloSpec.slot_map).

    plan_slots(4, 2) -> (0, 0, 1, 1); plan_slots(5, 2) -> (0, 0, 0, 1, 1);
    plan_slots(P, P) is the identity (worker == part, today's layout)."""
    if n_slots < 1:
        raise ValueError(f"plan_slots needs >= 1 slot, got {n_slots}")
    if n_parts < n_slots:
        raise ValueError(
            f"cannot spread {n_parts} parts over {n_slots} slots without "
            f"empty workers; shrink the world to <= {n_parts}")
    base, extra = divmod(n_parts, n_slots)
    out = []
    for slot in range(n_slots):
        out.extend([slot] * (base + (1 if slot < extra else 0)))
    return tuple(out)


def slot_members(slot_map: tuple[int, ...]) -> dict[int, list[int]]:
    """{slot: [part ids it hosts]} — the inverse view of `plan_slots`,
    used for logging/obs and the cross-slot wire accounting."""
    out: dict[int, list[int]] = {}
    for part, slot in enumerate(slot_map):
        out.setdefault(int(slot), []).append(part)
    return out
