"""Boundary-Node Sampling with a shared PRNG — zero-communication BNS.

The reference samples boundary subsets on the sender with numpy and ships the
chosen indices to the receiver every epoch (train.py:225-236, 389). Here both
endpoints of a pair (sender p, receiver j) derive the *same* uniform
without-replacement sample from a common key `pair_key(base, epoch, p, j)`,
so no index exchange happens at all, and sampling lives inside the one
compiled train step.

Sizes follow the reference exactly (train.py:107-119): for each ordered pair,
send_size = int(rate * |boundary|) and ratio = send_size / |boundary| are
fixed for the whole run — which is precisely what makes the exchange a
static-shape collective.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _fold_guard(x, name: str):
    """Edge guard for `jax.random.fold_in` operands.

    fold_in folds its data argument as a single uint32 word; a Python int
    outside [0, 2**32) would silently wrap (two distinct epochs 2**32 apart
    collide onto one key — the same stream for "different" draws), and a
    negative id would alias a large positive one. Static (Python/numpy
    scalar) inputs are range-checked here; traced values (the vmapped peer
    index, the uint32 epoch counter) are already dtype-bounded by
    construction. Returns x unchanged."""
    if isinstance(x, (int, np.integer)):
        if not 0 <= int(x) < 2 ** 32:
            raise ValueError(
                f"pair_key {name}={x} outside the uint32 fold_in range "
                f"[0, 2**32): fold_in would silently wrap and alias another "
                f"{name}'s sampling stream")
    return x


def pair_key(base_key: jax.Array, epoch: jax.Array, p, j,
             replica=None) -> jax.Array:
    """Key shared by sender p and receiver j for one epoch.

    `replica` (2-D replica-axis meshes, parallel/replicas.py) is folded
    FIRST, so ``pair_key(base, e, p, j, replica=r)`` equals
    ``pair_key(fold_in(base, r), e, p, j)`` — replica r of a 2-D run draws
    exactly the stream a single-replica run with the folded base key would,
    which is what makes the cross-replica gradient mean testable against
    independently-seeded 1-D runs (tests/test_replicas.py). ``replica=None``
    (the 1-D path) performs no fold at all: bit-identical to the historical
    keys. Distinctness of (replica, epoch, p, j) tuples is pinned by an
    exhaustive-grid test (threefry fold_in is injective per word; the guard
    above keeps every operand inside the one-word range)."""
    if replica is not None:
        base_key = jax.random.fold_in(base_key, _fold_guard(replica, "replica"))
    k = jax.random.fold_in(base_key, _fold_guard(epoch, "epoch"))
    k = jax.random.fold_in(k, _fold_guard(p, "p"))
    return jax.random.fold_in(k, _fold_guard(j, "j"))


def pair_sample(key: jax.Array, n_valid: jax.Array, s_valid: jax.Array,
                pad_b: int, pad_s: int) -> tuple[jax.Array, jax.Array]:
    """Uniform random s_valid-subset of positions [0, n_valid), static shape.

    Returns (positions [pad_s] int32, valid [pad_s] bool). Implementation:
    random scores on the n_valid real positions (+2 on padding), take the
    pad_s smallest — the first s_valid of a uniform random permutation of the
    valid positions is exactly a uniform without-replacement sample
    (reference semantics: np.random.choice(replace=False), train.py:233).

    Deterministic in (key, n_valid, s_valid): sender and receiver compute
    identical results with zero communication. Requires s_valid <= n_valid
    and pad_s <= pad_b.
    """
    scores = jax.random.uniform(key, (pad_b,))
    scores = jnp.where(jnp.arange(pad_b) < n_valid, scores, 2.0)
    _, idx = jax.lax.top_k(-scores, pad_s)
    valid = jnp.arange(pad_s) < s_valid
    return idx.astype(jnp.int32), valid


def identity_sample(n_valid: jax.Array, pad_s: int) -> tuple[jax.Array, jax.Array]:
    """Full-rate 'sample': positions 0..pad_s with the first n_valid marked
    valid. Used at sampling_rate=1.0 and by the precompute exchange — keeps
    exact runs deterministic and skips the top_k."""
    pos = jnp.arange(pad_s, dtype=jnp.int32)
    return pos, pos < n_valid


def chunk_sample(key: jax.Array, n_valid: jax.Array, s_valid: jax.Array,
                 chunk, stride: int, pad_b: int,
                 pad_s: int) -> tuple[jax.Array, jax.Array]:
    """`pair_sample` restricted to one residue class of the boundary list.

    The staleness-bounded refresh (--halo-refresh K, parallel/halo.py)
    redraws only the positions {k : k % K == chunk} of each boundary set per
    epoch. Those positions form their own contiguous domain t = 0..n_valid-1
    (full position = chunk + stride*t with stride = K); sampling in that
    domain through the SAME `pair_key` stream keeps the refreshed subset
    deterministic per (epoch, pair, replica, nonce) — exactly the property
    BNS relies on for zero-communication agreement — and preserves
    pair_sample's contiguous-valid-prefix contract that the ragged wire
    packing depends on. Returns FULL boundary positions plus the valid mask;
    `chunk` may be a traced scalar (it is epoch % K inside the step)."""
    pos, valid = pair_sample(key, n_valid, s_valid, pad_b, pad_s)
    return chunk + stride * pos, valid


def chunk_identity_sample(n_valid: jax.Array, chunk, stride: int,
                          pad_s: int) -> tuple[jax.Array, jax.Array]:
    """Full-rate analog of `chunk_sample`: positions chunk + stride*t for
    t < n_valid, in order. The rate-1.0 refresh path (every boundary node in
    this epoch's chunk crosses the wire; no top_k)."""
    pos, valid = identity_sample(n_valid, pad_s)
    return chunk + stride * pos, valid
