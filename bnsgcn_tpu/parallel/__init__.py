from bnsgcn_tpu.parallel.coord import (Coordinator, CoordAbort, CoordError,
                                       CoordTimeout, FileTransport,
                                       TcpTransport, make_coordinator)
from bnsgcn_tpu.parallel.sampling import pair_key, pair_sample
from bnsgcn_tpu.parallel.halo import HaloSpec, make_halo_plan, halo_apply, sampled_presence
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.parallel.replicas import make_mesh, mesh_desc, n_replicas, replica_axis
from bnsgcn_tpu.parallel.reducer import (assert_replicated, grad_reduce_axes,
                                         psum_gradients)
