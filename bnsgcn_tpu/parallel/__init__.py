from bnsgcn_tpu.parallel.sampling import pair_key, pair_sample
from bnsgcn_tpu.parallel.halo import HaloSpec, make_halo_plan, halo_apply, sampled_presence
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.parallel.reducer import psum_gradients, assert_replicated
