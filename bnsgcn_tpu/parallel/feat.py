"""Feature/tensor axis of the 3-D ('replicas', 'parts', 'feat') mesh.

Partition parallelism alone is hostage to METIS skew (the slowest part sets
the epoch time) and its halo volume grows with P. Sharding the HIDDEN
dimension instead (NeutronTP, PAPERS.md) is perfectly load-balanced — every
device holds N x (H/T) activations and there are *no boundary nodes at all*
on that axis; Plexus shows the 3-D composition of data/partition/tensor axes
is what reaches billion-edge scale. This module owns the 'feat' axis:

  * the axis sits INNERMOST on the mesh (parallel/replicas.make_mesh):
    tensor traffic is per-layer and latency-sensitive, so it gets the
    fastest ICI hop; replicas stay outermost/DCN-friendly and the halo
    exchange keeps the middle 'parts' hop;
  * layer weights are SHARDED over 'feat' by regex-driven PartitionSpec
    rules (`gnn_partition_rules` + `match_partition_rules`, the fmengine
    pattern): GCN/SAGE weight matrices along their input-feature (row) dim,
    GAT along the head dim; biases and norm params stay replicated;
  * each layer computes its SpMM/attention on an H/T activation slice and
    psums the per-shard partials over 'feat' exactly where the layer
    transitions shards — ONE collective per layer (models/gnn._feat_layer),
    scoped to 'feat' the same way halo collectives stay scoped to 'parts';
  * the halo exchange therefore carries H/T-width payloads: halo wire bytes
    drop ~T x for free, multiplicative with BNS sampling, the ragged wire
    and --overlap split;
  * the BNS sampling keys never fold the feat index — all feat shards of a
    (replica, part) carry column slices of the SAME activations and must
    draw the SAME boundary sample (unlike the replica axis, which exists to
    draw independent ones).

`--feat 1` constructs no axis at all (make_mesh delegates to the 2-D/1-D
constructors), so every pre-existing compiled program is unchanged by
construction — pinned bitwise by tests/test_feat.py.
"""

from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

FEAT_AXIS = "feat"


def n_feat(mesh: Mesh) -> int:
    """Feat-axis size of a mesh; 1 for the 2-D/1-D meshes.

    Uses `mesh.shape` (name -> size) rather than `mesh.devices` so the
    analysis/ir abstract tracer can pass a host-only AbstractMesh."""
    return int(dict(mesh.shape).get(FEAT_AXIS, 1))


def feat_axis(mesh: Mesh):
    """'feat' when the mesh carries the axis, else None — what GraphEnv and
    grad_reduce_axes consume (None = the historical paths, bit-identical)."""
    return FEAT_AXIS if FEAT_AXIS in mesh.axis_names else None


# ----------------------------------------------------------------------------
# per-layer shardability — the ONE source of truth shared by the parameter
# rules below and the layer bodies in models/gnn.py (they must agree, or a
# sharded weight would meet an unsharded activation slice)
# ----------------------------------------------------------------------------

def layer_fin(spec, i: int) -> int:
    """Effective contraction (input) width of layer i — what the feat axis
    slices. GraphSAGE's precomputed layer 0 consumes the [feat, mean_nbr]
    concat, doubling it (module/layer.py:59)."""
    fin = spec.layer_sizes[i]
    if (spec.model == "graphsage" and spec.use_pp and i == 0
            and i < spec.n_graph_layers):
        fin *= 2
    return fin


def shardable_layers(spec, T: int) -> tuple[bool, ...]:
    """Which layers can shard over a feat axis of size T.

    GCN/SAGE (and every dense tail layer): the input width must divide T —
    the activation slice and the weight's row shard must tile exactly.
    GAT graph layers shard HEADS (the attention math is per-head
    independent; the halo exchange stays full-width there — GAT wins come
    from the per-head softmax/combine, not wire bytes): heads % T == 0.
    A non-shardable layer simply runs the historical full-width body with
    its weight replicated — mixed stacks are fine (e.g. a raw 602-wide
    layer 0 under --feat 4 stays full while every hidden layer shards)."""
    if T <= 1:
        return (False,) * spec.n_layers
    out = []
    for i in range(spec.n_layers):
        if spec.model == "gat" and i < spec.n_graph_layers:
            out.append(spec.heads % T == 0)
        else:
            out.append(layer_fin(spec, i) % T == 0)
    return tuple(out)


def feat_shardable(spec, i: int, T: int) -> bool:
    return shardable_layers(spec, T)[i]


def shard_width(width: int, T: int, shardable: bool = True) -> int:
    """Wire/activation width of one feat shard: width/T when the owning
    layer shards, else the full width (reporting + microbench helper)."""
    return width // T if (shardable and T > 1 and width % T == 0) else width


# ----------------------------------------------------------------------------
# regex-driven parameter PartitionSpecs (the fmengine match_partition_rules
# pattern, SNIPPETS.md [1]): rules are (regex, PartitionSpec) pairs matched
# against 'layer_0/w'-style param paths, first match wins
# ----------------------------------------------------------------------------

def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def param_path(path) -> str:
    return "/".join(_key_str(k) for k in path)


def match_partition_rules(rules, params):
    """Pytree of PartitionSpec for `params` from (regex, spec) rules.

    Paths are '/'-joined dict keys ('layer_0/linear1/w'); scalars are never
    partitioned; an unmatched leaf is an error (rules should end with a
    catch-all ('.', P()))."""
    def spec_of(path, leaf):
        name = param_path(path)
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0:
            return P()
        for rule, ps in rules:
            if re.search(rule, name) is not None:
                return ps
        raise ValueError(f"no partition rule matched param {name!r}")
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_of(path, leaf) for path, leaf in flat])


def gnn_partition_rules(spec, T: int):
    """(regex, PartitionSpec) rules for a ModelSpec under a feat axis of
    size T. Weight matrices shard along the dimension the per-layer psum
    contracts over — the input-feature rows for GCN/SAGE/dense layers
    ([fin, fout] -> P('feat', None); 'column-wise' in the torch [out, in]
    convention), the head dimension for GAT ([fin, H*F'] -> P(None, 'feat'),
    head-aligned because heads % T == 0). attn vectors and the per-head GAT
    bias follow their heads; plain biases and norm params replicate (the
    catch-all)."""
    rules = []
    for i, ok in enumerate(shardable_layers(spec, T)):
        if not ok:
            continue
        if spec.model == "gat" and i < spec.n_graph_layers:
            rules += [(rf"^layer_{i}/w$", P(None, FEAT_AXIS)),
                      (rf"^layer_{i}/attn_[lr]$", P(FEAT_AXIS, None)),
                      (rf"^layer_{i}/bias$", P(FEAT_AXIS))]
        elif (spec.model == "graphsage" and i < spec.n_graph_layers
              and not (spec.use_pp and i == 0)):
            rules += [(rf"^layer_{i}/linear[12]/w$", P(FEAT_AXIS, None))]
        else:
            rules += [(rf"^layer_{i}/w$", P(FEAT_AXIS, None))]
    rules.append((r".", P()))
    return rules


def param_specs_for(spec, T: int, params_abs=None):
    """PartitionSpec pytree for init_params(spec)'s tree under a T-wide feat
    axis. `params_abs`: an abstract or concrete params tree; derived via
    eval_shape when omitted (imports models.gnn lazily — gnn.py imports the
    predicates above, so the top level must stay acyclic)."""
    if params_abs is None:
        from bnsgcn_tpu.models.gnn import init_params
        params_abs = jax.eval_shape(
            # graftlint: disable=prng-literal-key(eval_shape only: the key never materializes)
            lambda: init_params(jax.random.key(0), spec))[0]
    return match_partition_rules(gnn_partition_rules(spec, T), params_abs)


# ----------------------------------------------------------------------------
# placement: host trees -> device arrays under the rules (params), or under
# a placed template's shardings (optimizer state, resume/rollback restores)
# ----------------------------------------------------------------------------

def place_params(params_host, mesh: Mesh, spec, specs=None):
    """Device-place a host params tree with the feat partition rules
    (replicated over 'replicas'/'parts', sharded over 'feat' where the rules
    say so). Checkpoints stay feat-invariant: jax.device_get of a sharded
    single-host array assembles the FULL array, so saves are always
    unsharded and restore into any mesh shape."""
    if specs is None:
        specs = param_specs_for(spec, n_feat(mesh), params_host)
    return jax.tree.map(
        lambda v, ps: jax.device_put(jnp.asarray(v), NamedSharding(mesh, ps)),
        params_host, specs)


def place_like(host_tree, sharding_tree):
    """Re-place a restored host tree under a captured sharding tree (the
    feat-aware analog of run.py's place_replicated restore sites)."""
    return jax.tree.map(
        lambda v, sh: jax.device_put(jnp.asarray(v), sh),
        host_tree, sharding_tree)


def place_state_like(state_host, params_placed, mesh: Mesh):
    """Device-place an optimizer-state tree: leaves living at a params path
    SUFFIX with a matching shape (optax mu/nu subtrees mirror the params
    tree) adopt that param's sharding; everything else (step counts, empty
    states) replicates. Keeps Adam moments sharded exactly like their
    weights without optax-version-specific structure knowledge."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params_placed)
    by_path = {}
    for path, leaf in flat:
        by_path[tuple(_key_str(k) for k in path)] = (leaf.shape, leaf.sharding)
    rep = NamedSharding(mesh, P())

    def put(path, leaf):
        keys = tuple(_key_str(k) for k in path)
        shape = getattr(leaf, "shape", ())
        for n in range(len(keys), 0, -1):
            hit = by_path.get(keys[-n:])
            if hit is not None and hit[0] == tuple(shape):
                return jax.device_put(jnp.asarray(leaf), hit[1])
        return jax.device_put(jnp.asarray(leaf), rep)

    flat_s, treedef = jax.tree_util.tree_flatten_with_path(state_host)
    return jax.tree_util.tree_unflatten(
        treedef, [put(path, leaf) for path, leaf in flat_s])
