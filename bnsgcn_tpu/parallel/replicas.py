"""Replica-axis hybrid parallelism: a 2-D ('replicas', 'parts') mesh.

BNS-GCN's sampled halo exchange trades communication for gradient variance
(the paper's central knob); this module spends *spare devices* to buy that
variance back. When a pod slice has more chips than graph partitions, the
extra chips form a second mesh axis of full replicas of the partitioned
graph: every replica runs the SAME partition-parallel step but draws an
INDEPENDENT boundary sample (parallel/sampling.pair_key folds the replica
index into the shared-PRNG stream), and the gradient is the cross-replica
mean — cutting per-step BNS gradient variance by ~1/R at constant epoch
math per replica (Plexus/DistGNN-style: scale full-graph training by adding
parallel axes beyond the partition axis).

Axis layout: 'replicas' is the OUTER mesh axis. Replica-axis traffic is one
fused gradient all-reduce per step (see parallel/reducer.grad_reduce_axes —
the cross-replica mean rides the SAME psum as the parts-axis reduction,
rescaled, never a second collective), so it tolerates the slow hop of a
(DCN, ICI) device order; the per-layer halo all_to_all stays scoped to the
inner 'parts' axis, where `jax.lax.axis_index('parts')` / collectives over
axis_name='parts' automatically act within each replica's sub-group.

`n_replicas == 1` returns the plain 1-D ('parts',) mesh — bit-identical to
the historical path by construction (same Mesh, same specs, same compiled
program), which tests/test_replicas.py pins across the full halo-strategy x
wire-codec matrix.

PR 6 grew `make_mesh` a third, INNERMOST 'feat' axis (parallel/feat.py):
hidden dimensions shard T-ways with one per-layer psum on the fastest ICI
hop; `n_feat == 1` likewise constructs no axis at all (tests/test_feat.py
pins the bit-identity).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bnsgcn_tpu.parallel.feat import FEAT_AXIS, n_feat as mesh_n_feat
from bnsgcn_tpu.parallel.mesh import make_parts_mesh

REPLICA_AXIS = "replicas"
PARTS_AXIS = "parts"


def make_mesh(n_parts: int, n_replicas: int = 1, n_feat: int = 1,
              devices=None) -> Mesh:
    """Up-to-3-D ('replicas', 'parts', 'feat') mesh of R x P x T devices.

    Axes are constructed innermost-first only as requested: n_feat == 1 and
    n_replicas == 1 (the defaults) delegate to the 2-D / 1-D constructors,
    so every existing call site and compiled program is unchanged unless an
    extra axis was explicitly asked for (tests pin --feat 1 / --replicas 1
    bitwise against the historical paths).

    Axis order encodes the traffic hierarchy: 'feat' is INNERMOST — its
    per-layer partial psum (parallel/feat.py) is the most latency-sensitive
    collective and gets the fastest ICI hop; the per-layer halo all_to_all
    rides the middle 'parts' hop; 'replicas' stay OUTER (their only traffic
    is the once-per-step fused gradient reduce, which tolerates DCN). With
    `jax.distributed` process-major device ordering, consecutive devices
    therefore land in the same (replica, part) feat group."""
    if n_feat <= 1 and n_replicas <= 1:
        return make_parts_mesh(n_parts, devices)
    if devices is None:
        devices = jax.devices()
    need = n_parts * n_replicas * n_feat
    if len(devices) < need:
        shape = (f"{n_replicas} replicas x {n_parts} partitions"
                 + (f" x {n_feat} feat shards" if n_feat > 1 else ""))
        raise ValueError(
            f"need >= {need} devices for {shape}, have {len(devices)}; "
            f"lower --replicas/--feat or use a CPU mesh via "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={need}")
    if n_feat <= 1:
        arr = np.asarray(devices[:need]).reshape(n_replicas, n_parts)
        return Mesh(arr, (REPLICA_AXIS, PARTS_AXIS))
    arr = np.asarray(devices[:need]).reshape(n_replicas, n_parts, n_feat)
    if n_replicas <= 1:
        # no replica axis requested: a 2-D ('parts', 'feat') mesh, so the
        # replica-free code paths (axis lookups, dedup) stay untouched
        return Mesh(arr[0], (PARTS_AXIS, FEAT_AXIS))
    return Mesh(arr, (REPLICA_AXIS, PARTS_AXIS, FEAT_AXIS))


def n_replicas(mesh: Mesh) -> int:
    """Replica-axis size of a mesh; 1 for the historical 1-D parts mesh.

    Uses `mesh.shape` (name -> size) rather than `mesh.devices` so the
    analysis/ir abstract tracer can pass a host-only AbstractMesh."""
    return int(dict(mesh.shape).get(REPLICA_AXIS, 1))


def replica_axis(mesh: Mesh):
    """'replicas' when the mesh carries the axis, else None — the value
    threaded into HaloSpec.replica_axis so `make_halo_plan` folds the
    replica index into the BNS sampling keys (and 1-D meshes never pay a
    fold, preserving bit-identity)."""
    return REPLICA_AXIS if REPLICA_AXIS in mesh.axis_names else None


def mesh_desc(mesh: Mesh) -> str:
    """Human-readable mesh shape for run headers: '2x4x2 replicas x parts
    x feat' on a 3-D mesh, '2x4 replicas x parts' on 2-D, '4 parts' on the
    historical 1-D mesh."""
    shape = dict(mesh.shape)
    axes = [(REPLICA_AXIS, "replicas"), (PARTS_AXIS, "parts"),
            (FEAT_AXIS, "feat")]
    present = [(shape[a], label) for a, label in axes if a in shape]
    if len(present) == 1:
        return f"{present[0][0]} parts"
    return ("x".join(str(n) for n, _ in present) + " "
            + " x ".join(label for _, label in present))


def slot_desc(slot_map, members) -> str:
    """Human-readable elastic hosting layout for resize logs/obs:
    'rank0:[p0,p1] rank1:[p2,p3]'. `slot_map` is the [P] part ->
    hosting-rank tuple a RESIZE decision carries in 'slots'
    (members[plan_slots(P, W)[p]], resilience.plan_resize); `members` is
    the member rank list, used only for the identity default when
    slot_map is empty (worker == part, today's layout)."""
    from bnsgcn_tpu.parallel.mesh import slot_members
    ranks = tuple(slot_map) if slot_map else tuple(members)
    by_rank = slot_members(ranks)
    return " ".join(
        f"rank{r}:[{','.join(f'p{p}' for p in parts)}]"
        for r, parts in sorted(by_rank.items()))


def stacked_spec(mesh: Mesh) -> P:
    """PartitionSpec stacking per-device rows along dim 0: every mesh axis
    together (global [R*P*T, ...], replica-major / feat-minor), plain
    ('parts',) on 1-D. Used as the shard_map out_spec for outputs that
    genuinely differ per replica (training-mode logits under independent
    BNS draws, the exchange-only microbench sum); feat shards produce
    identical post-psum copies that `dedup_replica0` strides past."""
    axes = tuple(a for a in (REPLICA_AXIS, PARTS_AXIS, FEAT_AXIS)
                 if a in mesh.axis_names)
    if axes == (PARTS_AXIS,):
        return P(PARTS_AXIS)
    return P(axes)


def dedup_replica0(out, mesh: Mesh, n_parts: int):
    """(Replica 0, feat shard 0)'s [n_parts, ...] slice of a `stacked_spec`
    output.

    Metric/eval outputs are de-duplicated so the host-side reporting
    pipeline (accuracy logs, result files, _gather_logits) sees the same
    [P, ...] shape regardless of the extra axes. `stacked_spec` is
    replica-major with feat innermost, so replica 0 is the leading
    n_parts * T rows and part p's feat-0 copy sits at row p * T."""
    T = mesh_n_feat(mesh)
    if T > 1:
        out = out[:n_parts * T:T]
    elif REPLICA_AXIS not in mesh.axis_names:
        return out
    return out[:n_parts]
