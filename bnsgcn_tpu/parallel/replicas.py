"""Replica-axis hybrid parallelism: a 2-D ('replicas', 'parts') mesh.

BNS-GCN's sampled halo exchange trades communication for gradient variance
(the paper's central knob); this module spends *spare devices* to buy that
variance back. When a pod slice has more chips than graph partitions, the
extra chips form a second mesh axis of full replicas of the partitioned
graph: every replica runs the SAME partition-parallel step but draws an
INDEPENDENT boundary sample (parallel/sampling.pair_key folds the replica
index into the shared-PRNG stream), and the gradient is the cross-replica
mean — cutting per-step BNS gradient variance by ~1/R at constant epoch
math per replica (Plexus/DistGNN-style: scale full-graph training by adding
parallel axes beyond the partition axis).

Axis layout: 'replicas' is the OUTER mesh axis. Replica-axis traffic is one
fused gradient all-reduce per step (see parallel/reducer.grad_reduce_axes —
the cross-replica mean rides the SAME psum as the parts-axis reduction,
rescaled, never a second collective), so it tolerates the slow hop of a
(DCN, ICI) device order; the per-layer halo all_to_all stays scoped to the
inner 'parts' axis, where `jax.lax.axis_index('parts')` / collectives over
axis_name='parts' automatically act within each replica's sub-group.

`n_replicas == 1` returns the plain 1-D ('parts',) mesh — bit-identical to
the historical path by construction (same Mesh, same specs, same compiled
program), which tests/test_replicas.py pins across the full halo-strategy x
wire-codec matrix.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bnsgcn_tpu.parallel.mesh import make_parts_mesh

REPLICA_AXIS = "replicas"
PARTS_AXIS = "parts"


def make_mesh(n_parts: int, n_replicas: int = 1, devices=None) -> Mesh:
    """('replicas', 'parts') mesh of n_replicas x n_parts devices.

    n_replicas == 1 (the default) delegates to `make_parts_mesh`: the 1-D
    ('parts',) mesh, so every existing call site and compiled program is
    unchanged unless a second axis was explicitly requested.

    Replicas take the outer axis: with `jax.distributed` multi-host device
    ordering (process-major), consecutive devices land in the same replica
    row, keeping the per-layer halo exchange on the fast intra-slice hop and
    only the once-per-step fused gradient reduce on the slow outer hop."""
    if n_replicas <= 1:
        return make_parts_mesh(n_parts, devices)
    if devices is None:
        devices = jax.devices()
    need = n_parts * n_replicas
    if len(devices) < need:
        raise ValueError(
            f"need >= {need} devices for {n_replicas} replicas x {n_parts} "
            f"partitions, have {len(devices)}; lower --replicas (devices // "
            f"n_parts = {len(devices) // max(n_parts, 1)} fit) or use a CPU "
            f"mesh via XLA_FLAGS=--xla_force_host_platform_device_count={need}")
    arr = np.asarray(devices[:need]).reshape(n_replicas, n_parts)
    return Mesh(arr, (REPLICA_AXIS, PARTS_AXIS))


def n_replicas(mesh: Mesh) -> int:
    """Replica-axis size of a mesh; 1 for the historical 1-D parts mesh."""
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get(
        REPLICA_AXIS, 1))


def replica_axis(mesh: Mesh):
    """'replicas' when the mesh carries the axis, else None — the value
    threaded into HaloSpec.replica_axis so `make_halo_plan` folds the
    replica index into the BNS sampling keys (and 1-D meshes never pay a
    fold, preserving bit-identity)."""
    return REPLICA_AXIS if REPLICA_AXIS in mesh.axis_names else None


def mesh_desc(mesh: Mesh) -> str:
    """Human-readable mesh shape for run headers: '2x4 replicas x parts'
    on a 2-D mesh, '4 parts' on the historical 1-D mesh."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    if REPLICA_AXIS in shape:
        return (f"{shape[REPLICA_AXIS]}x{shape[PARTS_AXIS]} "
                f"replicas x parts")
    return f"{shape[PARTS_AXIS]} parts"


def stacked_spec(mesh: Mesh) -> P:
    """PartitionSpec stacking per-device rows along dim 0: (replicas, parts)
    together on a 2-D mesh (global [R*P, ...], replica-major), plain
    ('parts',) on 1-D. Used as the shard_map out_spec for outputs that
    genuinely differ per replica (training-mode logits under independent
    BNS draws, the exchange-only microbench sum)."""
    if REPLICA_AXIS in mesh.axis_names:
        return P((REPLICA_AXIS, PARTS_AXIS))
    return P(PARTS_AXIS)


def dedup_replica0(out, mesh: Mesh, n_parts: int):
    """Replica 0's [n_parts, ...] slice of a `stacked_spec` output.

    Metric/eval outputs are de-duplicated to replica 0 so the host-side
    reporting pipeline (accuracy logs, result files, _gather_logits) sees
    the same [P, ...] shape regardless of the replica axis. `stacked_spec`
    is replica-major, so replica 0 is the leading n_parts rows."""
    if REPLICA_AXIS in mesh.axis_names:
        return out[:n_parts]
    return out
