"""The halo (boundary-activation) exchange — the heart of partition parallelism.

TPU-native redesign of the reference feature buffer (helper/feature_buffer.py):

  * one static-shape tiled `lax.all_to_all` over the 'parts' mesh axis
    replaces the gloo irecv/isend ring + pinned staging + deferred-send queues
    (helper/feature_buffer.py:102-129) and the MPI all_to_all (:132-153);
  * the BNS sample for the epoch is computed once per step on *both* endpoints
    from a shared key (`parallel/sampling.py`), replacing the per-epoch index
    exchange (reference train.py:389);
  * sampled activations are scaled by 1/ratio on the sender
    (helper/feature_buffer.py:117,143) and scattered into fixed per-peer halo
    slot blocks; unsampled slots stay zero, which under sum-aggregation over
    the *full* static halo edge list reproduces exactly the reference's
    aggregation over the per-epoch sampled subgraph (train.py:256-281) — no
    graph reconstruction, ever;
  * the backward pass needs no grad hooks (helper/feature_buffer.py:97-98,
    169-182): JAX AD transposes gather -> all_to_all -> scatter-add into
    scatter-add -> all_to_all -> gather, which is precisely the reference's
    gloo backward including the 1/ratio rescale (:129).

Slot layout (see data/artifacts.py): extended row `pad_inner + q*pad_b + k`
on part j holds the k-th entry of q's boundary list toward j.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.parallel.sampling import (chunk_identity_sample, chunk_sample,
                                          identity_sample, pair_key,
                                          pair_sample)


@dataclass(frozen=True)
class HaloSpec:
    """Static exchange geometry (python ints only — safe to close over in jit).

    The replicated device tables (n_b, send_size, inv_ratio) travel separately
    as a `tables` dict argument through shard_map with spec P().

    `strategy` picks the collective decomposition:
      * 'padded' — one tiled `lax.all_to_all`, every pair padded to the global
        max send size (round-1 behavior; best when partitions are balanced);
      * 'shift'  — P-1 `ppermute` rounds, round k padded only to
        max_p send_size[p, (p+k)%P]: wire bytes track the *actual* skewed
        boundary sizes, the TPU analog of the reference's exact per-pair
        isend sizes (helper/feature_buffer.py:111-121);
      * 'ragged' — ONE `lax.ragged_all_to_all` carrying each (sender, peer)
        pair's exact send_size[p, j] rows: shift's exact bytes without its
        P-1 serialized hops. Offsets/sizes are trace-time constants
        (`pair_send`). Native on TPU backends that ship the collective;
        elsewhere (XLA:CPU, old jax) a numerically identical emulation
        routes the same rows over the padded all_to_all through the same
        pack/unpack geometry, so the strategy is CPU-mesh-testable.
    `wire` picks the payload dtype on the interconnect:
      * 'native' — h.dtype as-is;
      * 'bf16'   — cast to bfloat16 on the wire;
      * 'int8'   — 1-byte symmetric int8 with per-(sender,peer)-block scales
        (v5e-native convert — preferred over fp8 on hardware);
      * 'fp8'    — float8_e4m3fn with one f32 scale per (sender, peer) block;
        backward gradients are re-quantized with their own scales (a fresh
        amax), not the activation scales — see `_a2a_wire`/`_ppermute_wire`.
    """
    n_parts: int
    pad_inner: int
    pad_boundary: int                  # B_pad: per-pair boundary padding
    pad_send: int                      # S_pad: per-pair send padding (<= B_pad)
    axis_name: str = "parts"
    exact: bool = False                # rate == 1.0: identity ordering, no top_k
    strategy: str = "padded"           # 'padded' | 'shift' | 'ragged'
    wire: str = "native"               # 'native' | 'bf16' | 'fp8' | 'int8'
    shift_pads: tuple = ()             # [P-1] per-shift send widths (strategy='shift')
    pair_send: tuple = ()              # [P][P] exact per-pair send sizes (python
                                       # ints — the ragged geometry is static)
    replica_axis: str | None = None    # 2-D ('replicas','parts') meshes: fold
                                       # axis_index(replica_axis) into the BNS
                                       # keys so each replica draws an
                                       # INDEPENDENT boundary sample. None
                                       # (1-D path) folds nothing —
                                       # bit-identical historical keys. Every
                                       # collective here stays scoped to
                                       # axis_name='parts' either way: inside
                                       # shard_map over a 2-D mesh a
                                       # parts-axis collective acts within
                                       # each replica's own sub-group.
    slot_map: tuple = ()               # [P] part -> hosting worker slot for
                                       # elastic worlds (mesh.plan_slots).
                                       # Host-side addressing metadata ONLY:
                                       # the traced programs keep the full
                                       # P-wide 'parts' axis regardless, and
                                       # nothing inside traced code reads
                                       # this field, so the compiled schedule
                                       # is slot-invariant (pinned by the
                                       # graftlint-ir slot-map section).
                                       # () = identity (worker == part).

    @property
    def n_halo(self) -> int:
        return self.n_parts * self.pad_boundary


def make_halo_spec(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                   rate: float, axis_name: str = "parts",
                   strategy: str = "padded", wire: str = "native",
                   replica_axis: str | None = None,
                   slot_map=None
                   ) -> tuple[HaloSpec, dict]:
    """Derive fixed send sizes and ratios from boundary sizes + sampling rate
    (reference get_send_size/get_recv_size, train.py:107-131).

    Returns (spec, tables): `tables` = {n_b, send_size, inv_ratio} device
    arrays, replicated across the mesh."""
    n_b = np.asarray(n_b, dtype=np.int64)
    P = n_b.shape[0]
    exact = rate >= 1.0
    send_size = n_b if exact else (rate * n_b).astype(np.int64)
    ratio = np.where(n_b > 0, send_size / np.maximum(n_b, 1), 0.0)
    inv_ratio = np.where(ratio > 0, 1.0 / np.maximum(ratio, 1e-30), 0.0)
    # S_pad: one uniform per-pair send width; multiple of 8 for lane friendliness
    pad_send = max(1, int(send_size.max())) if send_size.size else 1
    pad_send = min(((pad_send + 7) // 8) * 8, pad_boundary)
    # per-shift widths: round k only carries the (p -> p+k) pairs, so its pad
    # is that diagonal's max — zero-size shifts are skipped entirely at trace
    # time (static), making sparse peer topologies cost nothing
    shift_pads = []
    for k in range(1, P):
        m = int(max(send_size[p, (p + k) % P] for p in range(P)))
        shift_pads.append(0 if m == 0 else min(((m + 7) // 8) * 8, pad_send))
    assert strategy in ("padded", "shift", "ragged"), (
        f"unresolved halo strategy {strategy!r} (resolve 'auto' via "
        f"select_halo_strategy before make_halo_spec)")
    spec = HaloSpec(
        n_parts=P, pad_inner=pad_inner, pad_boundary=pad_boundary,
        pad_send=pad_send, axis_name=axis_name, exact=exact,
        strategy=strategy, wire=wire, shift_pads=tuple(shift_pads),
        pair_send=tuple(map(tuple, send_size.tolist())),
        replica_axis=replica_axis,
        slot_map=tuple(int(s) for s in (slot_map or ())),
    )
    tables = {"n_b": jnp.asarray(n_b, jnp.int32),
              "send_size": jnp.asarray(send_size, jnp.int32),
              "inv_ratio": jnp.asarray(inv_ratio, jnp.float32)}
    return spec, tables


def _ragged_exact_rows(pair_send, n_parts: int) -> int:
    """Bottleneck device's exact off-diagonal send rows — what the ragged
    collective puts on the wire (matches the hw-probe accounting,
    hw_logs/hw_session_r4.log:399: `send.sum(1).max()` with a zero diagonal)."""
    S = np.asarray(pair_send, dtype=np.int64).reshape(n_parts, n_parts).copy()
    np.fill_diagonal(S, 0)
    return int(S.sum(axis=1).max()) if S.size else 0


def wire_bytes(spec: HaloSpec, width: int, native_bytes: int = 4) -> int:
    """Per-device payload bytes of ONE forward exchange at the given feature
    width (excluding the [P] f32 scales, which are negligible). The backward
    exchange costs the same.

    Accounting matches the hardware probe (hw_logs/hw_session_r4.log:399):
    'padded' counts the full P-block tiled all_to_all buffer (the self block
    rides the same payload even though its hop is chip-local); 'shift' counts
    its per-diagonal pads; 'ragged' counts the bottleneck device's exact
    off-diagonal rows."""
    b = {"native": native_bytes, "bf16": 2, "fp8": 1, "int8": 1}[spec.wire]
    if spec.strategy == "shift":
        return sum(spec.shift_pads) * width * b
    if spec.strategy == "ragged":
        return _ragged_exact_rows(spec.pair_send, spec.n_parts) * width * b
    return spec.n_parts * spec.pad_send * width * b


def traced_wire_bytes(spec: HaloSpec, width: int, native_bytes: int = 4,
                      ragged_native: Optional[bool] = None) -> int:
    """Per-device payload bytes the COMPILED exchange program actually moves
    — the analysis/ir wire-byte contract's oracle, cross-checked against the
    collective operands extracted from the traced jaxpr.

    Equals `wire_bytes()` for 'padded' and 'shift' (their traced operands
    ARE the accounting). 'ragged' differs by construction: the native
    collective ships the lane-aligned [T_pad, d] operand (the bottleneck
    device's exact rows INCLUDING the self chunk, rounded up to 8), while
    the emulated path (XLA:CPU / old jax, `ragged_native_ok()` False)
    routes the same rows over the padded all_to_all — padded accounting,
    the documented emulation slack `wire_bytes()` deliberately ignores.
    The [P] f32 scale hop of the quantized wires is excluded on both sides
    (same convention as `wire_bytes`)."""
    b = {"native": native_bytes, "bf16": 2, "fp8": 1, "int8": 1}[spec.wire]
    if spec.strategy == "ragged":
        if ragged_native is None:
            ragged_native = ragged_native_ok()
        if ragged_native:
            t_pad = _ragged_geometry(spec.pair_send)[3]
            return t_pad * width * b
        return spec.n_parts * spec.pad_send * width * b
    return wire_bytes(spec, width, native_bytes)


def cross_slot_wire_bytes(spec: HaloSpec, width: int,
                          native_bytes: int = 4) -> int:
    """Per-device halo bytes that actually cross WORKER boundaries under an
    elastic part->slot mapping: pairs hosted on the same slot move through
    that worker's own HBM, not the interconnect. Exact pair_send rows (no
    padding — this is the planning/obs view of a resized world's wire cost,
    not the traced operand size). With an empty slot_map (identity, worker
    == part) only the self pair is intra-slot, matching `_ragged_exact_rows`
    accounting. Returns the bottleneck slot's worst part, summed over its
    cross-slot peers."""
    b = {"native": native_bytes, "bf16": 2, "fp8": 1, "int8": 1}[spec.wire]
    P = spec.n_parts
    slots = spec.slot_map or tuple(range(P))
    S = np.asarray(spec.pair_send, dtype=np.int64).reshape(P, P)
    rows = np.zeros(P, dtype=np.int64)
    for p in range(P):
        rows[p] = sum(int(S[p, q]) for q in range(P) if slots[q] != slots[p])
    return int(rows.max()) * width * b if P else 0


# auto-selection thresholds: ragged must save >=5% of padded's cross-chip
# bytes to be worth leaving the best-tuned dense collective; shift pays P-1
# serialized hop latencies for the same bytes as ragged, so it is only
# picked when ragged is unavailable AND the skew saving is large (>=25%).
RAGGED_MIN_SAVING = 0.05
SHIFT_MIN_SAVING = 0.25


def ragged_native_ok() -> bool:
    """True when `lax.ragged_all_to_all` will lower natively here: the op
    exists in this jax AND the backend is TPU (UNIMPLEMENTED on XLA:CPU —
    hw_logs/hw_session_r4.log probe note). BNSGCN_RAGGED_EMULATE=1 forces
    the emulation path for debugging."""
    if not hasattr(jax.lax, "ragged_all_to_all"):
        return False
    if os.environ.get("BNSGCN_RAGGED_EMULATE"):
        return False
    return jax.default_backend() == "tpu"


def ragged_auto_eligible() -> bool:
    """Whether `--halo-exchange auto` may pick 'ragged'. The emulated path is
    numerically exact everywhere but ships padded bytes PLUS pack/unpack
    gathers — strictly worse than 'padded' on any real accelerator — so auto
    only picks ragged where the native collective lowers, or on the CPU test
    mesh (bytes are fictional there and the strategy must stay selectable
    for the tier-1 suite). An explicit --halo-exchange ragged still runs the
    emulation anywhere."""
    return ragged_native_ok() or jax.default_backend() == "cpu"


def select_halo_strategy(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                         rate: float, wire: str = "native",
                         allow_ragged: bool = True) -> tuple[str, str]:
    """Resolve `--halo-exchange auto`: pick padded/shift/ragged from the
    `wire_bytes()` estimate (width/dtype cancel, so the pick is width-free)
    plus the hop-count tiebreak documented above. Returns (strategy, reason).

    Byte comparison is against padded's CROSS-CHIP rows (P-1 blocks; the
    self block never leaves the chip), not its full buffer accounting —
    otherwise ragged would "win" 1/P even on perfectly balanced partitions.
    Deterministic in the (global) n_b table: every host of a multi-host run
    resolves identically."""
    # one spec carries all three strategies' geometry (pad_send, shift_pads
    # and pair_send are derived unconditionally)
    spec = make_halo_spec(n_b, pad_inner, pad_boundary, rate, wire=wire)[0]
    P = spec.n_parts
    padded_rows = (P - 1) * spec.pad_send
    shift_rows = sum(spec.shift_pads)
    ragged_rows = _ragged_exact_rows(spec.pair_send, P)
    if P <= 1 or padded_rows == 0:
        return "padded", "single partition / empty halo"
    if allow_ragged and ragged_rows < (1.0 - RAGGED_MIN_SAVING) * padded_rows:
        return "ragged", (
            f"exact {ragged_rows} rows vs padded {padded_rows} "
            f"({ragged_rows / padded_rows:.0%}), one collective")
    if shift_rows < (1.0 - SHIFT_MIN_SAVING) * padded_rows:
        return "shift", (
            f"per-diagonal {shift_rows} rows vs padded {padded_rows} "
            f"({shift_rows / padded_rows:.0%}), worth P-1 serialized hops"
            + ("" if allow_ragged else "; ragged collective unavailable"))
    if allow_ragged:
        return "padded", (
            f"balanced boundaries (ragged {ragged_rows}/{padded_rows} rows "
            f"saves <{RAGGED_MIN_SAVING:.0%}); one dense collective")
    return "padded", (
        f"ragged collective unavailable and shift {shift_rows}/{padded_rows} "
        f"rows saves <{SHIFT_MIN_SAVING:.0%} (not worth P-1 serialized hops)")


def retune_strategy(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                    rate: float, current: str, wire: str = "native",
                    allow_ragged: Optional[bool] = None) -> Optional[tuple]:
    """The `--tune` controller's strategy re-pick: the same wire-bytes
    estimate `--halo-exchange auto` runs at launch, re-framed as "is there a
    better strategy than the one this run is EXECUTING". Returns
    ``(strategy, why)`` when the estimate prefers a different strategy, else
    None. The caller (tune.decide) only acts on it when the MEASURED epoch
    comm share is high — the estimate proposes, the measurement disposes,
    which is the difference from the launch-time pick that has nothing but
    the estimate to go on."""
    if allow_ragged is None:
        allow_ragged = ragged_auto_eligible()
    best, why = select_halo_strategy(n_b, pad_inner, pad_boundary, rate,
                                     wire=wire, allow_ragged=allow_ragged)
    if best == current:
        return None
    return best, why


@dataclass
class HaloPlan:
    """Per-epoch sampling decisions, shared by every layer's exchange
    (the reference samples once per epoch, train.py:388-390)."""
    sel: jax.Array                     # [P, S] my boundary positions to send to each peer
    weight: jax.Array                  # [P, S] f32: valid/ratio sender scaling
    slots: jax.Array                   # [P, S] int32: halo slots for received rows (trash = n_halo)
    presence: jax.Array                # [pad_inner + n_halo] bool: inner + sampled halos


def make_halo_plan(spec: HaloSpec, tables: dict, bnd: jax.Array,
                   epoch: jax.Array, base_key: jax.Array) -> HaloPlan:
    """Compute this epoch's send selection and receive scatter plan.

    `bnd`: [P, B_pad] — this device's boundary lists toward each peer
    (sharded row of artifacts.bnd). Runs inside shard_map.
    """
    P, Bp, Sp = spec.n_parts, spec.pad_boundary, spec.pad_send
    me = jax.lax.axis_index(spec.axis_name)
    peers = jnp.arange(P)

    n_send = tables["n_b"][me]                 # [P]
    s_send = tables["send_size"][me]
    n_recv = tables["n_b"][:, me]
    s_recv = tables["send_size"][:, me]

    if spec.exact:
        pos, valid = jax.vmap(lambda n: identity_sample(n, Sp))(n_send)
        rpos, rvalid = jax.vmap(lambda n: identity_sample(n, Sp))(n_recv)
    else:
        # replica-axis meshes: each replica folds its own index into the
        # pair keys, drawing an independent BNS sample from the one shared
        # base seed (both endpoints of a pair live in the same replica row,
        # so the zero-communication shared-PRNG contract is unchanged)
        rep = (jax.lax.axis_index(spec.replica_axis)
               if spec.replica_axis is not None else None)
        send_keys = jax.vmap(
            lambda j: pair_key(base_key, epoch, me, j, replica=rep))(peers)
        recv_keys = jax.vmap(
            lambda q: pair_key(base_key, epoch, q, me, replica=rep))(peers)
        pos, valid = jax.vmap(
            lambda k, n, s: pair_sample(k, n, s, Bp, Sp))(send_keys, n_send, s_send)
        rpos, rvalid = jax.vmap(
            lambda k, n, s: pair_sample(k, n, s, Bp, Sp))(recv_keys, n_recv, s_recv)

    sel = jnp.take_along_axis(bnd, pos.astype(bnd.dtype), axis=1)          # [P, S]
    weight = jnp.where(valid, tables["inv_ratio"][me][:, None], 0.0)       # [P, S]
    slots = jnp.where(rvalid, peers[:, None] * Bp + rpos, spec.n_halo)     # [P, S]

    presence = jnp.zeros(spec.n_halo + 1, dtype=bool).at[slots.reshape(-1)].set(True)
    presence = jnp.concatenate(
        [jnp.ones(spec.pad_inner, dtype=bool), presence[:-1]])
    return HaloPlan(sel=sel, weight=weight, slots=slots, presence=presence)


# ----------------------------------------------------------------------------
# staleness-bounded refresh (--halo-refresh K): epoch e re-exchanges only the
# boundary positions {k : k % K == e % K} of every pair ("chunk" e % K), so
# the per-epoch wire bytes drop ~K x while every halo row is at most K-1
# epochs stale, with staleness staggered across rows instead of cliffing all
# at once. The partial exchange reuses halo_start/halo_finish UNCHANGED: only
# the spec geometry (sized to the largest chunk) and the plan (chunk-domain
# draws mapped back to full boundary positions) differ, so all three
# strategies x four wire codecs compose for free.
# ----------------------------------------------------------------------------

def make_refresh_spec(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                      rate: float, refresh: int, axis_name: str = "parts",
                      strategy: str = "padded", wire: str = "native",
                      replica_axis: str | None = None,
                      slot_map=None
                      ) -> tuple[HaloSpec, dict]:
    """Geometry + tables for the --halo-refresh K partial exchange.

    The spec keeps the FULL pad_boundary (halo slot layout — and therefore
    n_halo and the cache buffer shape — identical to the full exchange's),
    but pad_send / shift_pads / pair_send are sized to the largest chunk, so
    `wire_bytes(spec)` reports the true steady-state cost. Tables are
    [K, P, P] chunk-major device arrays; the plan builder dynamically
    indexes them with the traced chunk e % K.

    Per-chunk inv_ratio = n_bc / s_c keeps each refreshed chunk an unbiased
    estimate of ITS slice of the boundary sum; mixed with cached rows drawn
    under earlier epochs' keys, the steady-state halo buffer remains an
    unbiased (stale) estimate of the full boundary aggregation — and exact
    at rate 1.0, where K > 1 differs from the per-epoch exchange only
    through staleness. At K=1 the tables and geometry reduce bit-identically
    to `make_halo_spec`'s."""
    K = int(refresh)
    assert K >= 1, f"halo refresh period must be >= 1, got {K}"
    n_b = np.asarray(n_b, dtype=np.int64)
    P = n_b.shape[0]
    exact = rate >= 1.0
    c_idx = np.arange(K, dtype=np.int64).reshape(K, 1, 1)
    # |{k in [0, n_b) : k % K == c}| — per-chunk boundary counts [K, P, P]
    n_bc = (np.maximum(n_b[None] - c_idx, 0) + K - 1) // K
    if exact:
        s_c = n_bc
    else:
        # floor(rate * chunk) like the full path, but never 0 for a pair the
        # full exchange serves: a permanently silent chunk would bias the
        # steady-state aggregation instead of merely adding variance
        full_send = (rate * n_b).astype(np.int64)
        s_c = np.where((n_bc > 0) & (full_send[None] > 0),
                       np.maximum((rate * n_bc).astype(np.int64), 1), 0)
    ratio_c = np.where(n_bc > 0, s_c / np.maximum(n_bc, 1), 0.0)
    inv_ratio_c = np.where(ratio_c > 0, 1.0 / np.maximum(ratio_c, 1e-30), 0.0)
    pair_send = s_c.max(axis=0)                    # [P, P] worst chunk per pair
    pad_b_chunk = (pad_boundary + K - 1) // K      # chunk-domain boundary pad
    # NO x8 lane rounding here, unlike make_halo_spec: chunk sends are small
    # and rounding up would erase exactly the ~K x byte saving the refresh
    # mode exists for (round8(ceil(s/K)) == round8(s) for modest s)
    pad_send = max(1, int(pair_send.max())) if pair_send.size else 1
    pad_send = min(pad_send, max(pad_b_chunk, 1))
    shift_pads = []
    for k in range(1, P):
        m = int(max(pair_send[p, (p + k) % P] for p in range(P)))
        shift_pads.append(0 if m == 0 else min(m, pad_send))
    assert strategy in ("padded", "shift", "ragged"), (
        f"unresolved halo strategy {strategy!r} (resolve 'auto' via "
        f"select_halo_strategy before make_refresh_spec)")
    spec = HaloSpec(
        n_parts=P, pad_inner=pad_inner, pad_boundary=pad_boundary,
        pad_send=pad_send, axis_name=axis_name, exact=exact,
        strategy=strategy, wire=wire, shift_pads=tuple(shift_pads),
        pair_send=tuple(map(tuple, pair_send.tolist())),
        replica_axis=replica_axis,
        slot_map=tuple(int(s) for s in (slot_map or ())),
    )
    tables = {"n_b": jnp.asarray(n_bc, jnp.int32),
              "send_size": jnp.asarray(s_c, jnp.int32),
              "inv_ratio": jnp.asarray(inv_ratio_c, jnp.float32)}
    return spec, tables


def make_halo_plan_refresh(spec: HaloSpec, tables: dict, bnd: jax.Array,
                           epoch: jax.Array, base_key: jax.Array,
                           refresh: int) -> HaloPlan:
    """This epoch's PARTIAL send/scatter plan under --halo-refresh K.

    Chunk c = epoch % K of every boundary list is redrawn through the SAME
    `pair_key` stream as the full plan — deterministic per (epoch, pair,
    replica, nonce) with zero index communication, exactly like BNS.
    `spec`/`tables` come from `make_refresh_spec`; slots and presence live
    in the FULL pad_boundary slot layout, so `halo_finish`'s buffer drops
    straight into the cache and this plan's presence covers ONLY the
    refreshed chunk's halo rows (the caller merges it with the cached
    presence). Runs inside shard_map, like `make_halo_plan`."""
    K = int(refresh)
    P, Bp, Sp = spec.n_parts, spec.pad_boundary, spec.pad_send
    Bp_c = (Bp + K - 1) // K
    c = jax.lax.rem(epoch.astype(jnp.uint32), jnp.uint32(K)).astype(jnp.int32)
    me = jax.lax.axis_index(spec.axis_name)
    peers = jnp.arange(P)

    n_b_c = tables["n_b"][c]                   # [P, P] this chunk's counts
    s_c = tables["send_size"][c]
    n_send, s_send = n_b_c[me], s_c[me]
    n_recv, s_recv = n_b_c[:, me], s_c[:, me]

    if spec.exact:
        pos, valid = jax.vmap(
            lambda n: chunk_identity_sample(n, c, K, Sp))(n_send)
        rpos, rvalid = jax.vmap(
            lambda n: chunk_identity_sample(n, c, K, Sp))(n_recv)
    else:
        rep = (jax.lax.axis_index(spec.replica_axis)
               if spec.replica_axis is not None else None)
        send_keys = jax.vmap(
            lambda j: pair_key(base_key, epoch, me, j, replica=rep))(peers)
        recv_keys = jax.vmap(
            lambda q: pair_key(base_key, epoch, q, me, replica=rep))(peers)
        pos, valid = jax.vmap(
            lambda k, n, s: chunk_sample(k, n, s, c, K, Bp_c, Sp))(
                send_keys, n_send, s_send)
        rpos, rvalid = jax.vmap(
            lambda k, n, s: chunk_sample(k, n, s, c, K, Bp_c, Sp))(
                recv_keys, n_recv, s_recv)

    # invalid rows carry chunk-domain padding positions that can map past
    # Bp; clamp them into range — their weight is 0 and their slot is trash,
    # so the clamped gather/scatter targets are never observed
    pos = jnp.minimum(pos, Bp - 1)
    rpos = jnp.minimum(rpos, Bp - 1)
    sel = jnp.take_along_axis(bnd, pos.astype(bnd.dtype), axis=1)          # [P, S]
    weight = jnp.where(valid, tables["inv_ratio"][c][me][:, None], 0.0)    # [P, S]
    slots = jnp.where(rvalid, peers[:, None] * Bp + rpos, spec.n_halo)     # [P, S]

    presence = jnp.zeros(spec.n_halo + 1, dtype=bool).at[slots.reshape(-1)].set(True)
    presence = jnp.concatenate(
        [jnp.ones(spec.pad_inner, dtype=bool), presence[:-1]])
    return HaloPlan(sel=sel, weight=weight, slots=slots, presence=presence)


def refresh_row_mask(spec: HaloSpec, refresh: int, epoch: jax.Array) -> jax.Array:
    """[n_halo] bool: halo slots whose boundary position belongs to this
    epoch's refresh chunk. Slot q*pad_boundary + k refreshes iff
    k % K == epoch % K; the cached step keeps every other slot's stored
    (stop-gradient) rows."""
    K = jnp.uint32(refresh)
    c = jax.lax.rem(epoch.astype(jnp.uint32), K)
    k = jnp.arange(spec.n_halo, dtype=jnp.uint32) % jnp.uint32(spec.pad_boundary)
    return (k % K) == c


# ----------------------------------------------------------------------------
# wire codec: quantize per (sender, peer) block for the interconnect hop only.
# fp8 rides float8_e4m3fn with one f32 scale per block; gradients on the
# backward hop get their OWN scales (activation scales would under/overflow
# gradient magnitudes — the standard fp8-comm pitfall).
# ----------------------------------------------------------------------------

def _quant(x: jax.Array, wire: str):
    """x [..., S, d] -> (payload, scales or None); scales over the last two axes."""
    if wire == "bf16":
        return x.astype(jnp.bfloat16), None
    if wire == "int8":
        # v5e-native 1-byte wire: the convert is hardware, unlike e4m3
        # decode (emulated; measured slower than bf16 in the SpMM gather)
        from bnsgcn_tpu.utils.quant import i8_quant
        return i8_quant(x, axes=(-2, -1))
    from bnsgcn_tpu.utils.quant import f8_quant
    return f8_quant(x, axes=(-2, -1))


def _dequant(payload: jax.Array, scale, dtype):
    if scale is None:
        return payload.astype(dtype)
    from bnsgcn_tpu.utils.quant import f8_dequant
    return f8_dequant(payload, scale, dtype)


def _a2a_wire_impl(spec: HaloSpec, send: jax.Array) -> jax.Array:
    P, S, d = send.shape
    payload, scale = _quant(send, spec.wire)
    recv = jax.lax.all_to_all(payload.reshape(P * S, d), spec.axis_name,
                              0, 0, tiled=True).reshape(P, S, d)
    rscale = None
    if scale is not None:
        rscale = jax.lax.all_to_all(scale.reshape(P, 1), spec.axis_name,
                                    0, 0, tiled=True).reshape(P, 1, 1)
    return _dequant(recv, rscale, send.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _a2a_wire(spec: HaloSpec, send: jax.Array) -> jax.Array:
    return _a2a_wire_impl(spec, send)


def _a2a_wire_fwd(spec, send):
    return _a2a_wire_impl(spec, send), None


def _a2a_wire_bwd(spec, _, g):
    # tiled all_to_all is an involution: the same call routes each received
    # block's cotangent back to its sender, re-quantized with g's own scales
    return (_a2a_wire_impl(spec, g),)


_a2a_wire.defvjp(_a2a_wire_fwd, _a2a_wire_bwd)


def _ppermute_wire_impl(spec: HaloSpec, k: int, send: jax.Array) -> jax.Array:
    P = spec.n_parts
    perm = [(i, (i + k) % P) for i in range(P)]
    payload, scale = _quant(send, spec.wire)
    recv = jax.lax.ppermute(payload, spec.axis_name, perm)
    rscale = None
    if scale is not None:
        rscale = jax.lax.ppermute(scale, spec.axis_name, perm)
    return _dequant(recv, rscale, send.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ppermute_wire(spec: HaloSpec, k: int, send: jax.Array) -> jax.Array:
    return _ppermute_wire_impl(spec, k, send)


def _ppermute_wire_fwd(spec, k, send):
    return _ppermute_wire_impl(spec, k, send), None


def _ppermute_wire_bwd(spec, k, _, g):
    return (_ppermute_wire_impl(spec, spec.n_parts - k, g),)


_ppermute_wire.defvjp(_ppermute_wire_fwd, _ppermute_wire_bwd)


# ----------------------------------------------------------------------------
# 'ragged' strategy: ONE collective carrying each pair's exact send_size[p,j]
# rows. All geometry (offsets, sizes, buffer bounds) is derived from the
# static pair_send table, so the per-device offset vectors are plain gathers
# of trace-time constants by axis_index — exactly the static-shape discipline
# the padded path established, minus its padding bytes.
# ----------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _ragged_geometry(sizes: tuple):
    """(S, in_off, recv_off, T_pad, R_pad) for a [P][P] pair-size tuple.

    S[p][j]     rows p sends j;
    in_off[p]   exclusive row-cumsum of S[p] — chunk offsets in p's operand;
    recv_off[p] exclusive row-cumsum of S[:,p] — chunk offsets in p's output;
    T_pad/R_pad lane-aligned uniform operand/output bounds (SPMD shapes must
    agree across devices; the ragged sizes say how much of each is real)."""
    S = np.asarray(sizes, dtype=np.int64)
    in_off = np.zeros_like(S)
    in_off[:, 1:] = np.cumsum(S, axis=1)[:, :-1]
    R = np.ascontiguousarray(S.T)
    recv_off = np.zeros_like(R)
    recv_off[:, 1:] = np.cumsum(R, axis=1)[:, :-1]
    pad8 = lambda n: max(8, ((int(n) + 7) // 8) * 8)
    return (S, in_off, recv_off,
            pad8(S.sum(axis=1).max()), pad8(R.sum(axis=1).max()))


def _transpose_sizes(sizes: tuple) -> tuple:
    return tuple(zip(*sizes))


def _ragged_pack(off_row, size_row, n_pad: int, blocks: jax.Array) -> jax.Array:
    """[P, S, d] per-peer blocks -> [n_pad, d] ragged buffer: chunk j's rows
    land contiguously at off_row[j]; slack rows are zero."""
    P, S, d = blocks.shape
    t = jnp.arange(n_pad)
    j = jnp.clip(jnp.searchsorted(off_row, t, side="right") - 1, 0, P - 1)
    i = t - off_row[j]
    src = jnp.where(i < size_row[j], j * S + i, P * S)
    flat = jnp.concatenate(
        [blocks.reshape(P * S, d), jnp.zeros((1, d), blocks.dtype)])
    return flat[src]


def _ragged_unpack(off_row, size_row, S: int, buf: jax.Array) -> jax.Array:
    """Inverse of `_ragged_pack`: [n_pad, d] -> [P, S, d]; rows beyond each
    chunk's ragged size come back zero."""
    n_pad, d = buf.shape
    P = off_row.shape[0]
    i = jnp.arange(S)
    idx = jnp.where(i[None, :] < size_row[:, None],
                    off_row[:, None] + i[None, :], n_pad)
    flat = jnp.concatenate([buf, jnp.zeros((1, d), buf.dtype)])
    return flat[idx.reshape(-1)].reshape(P, S, d)


def _ragged_a2a(spec: HaloSpec, sizes: tuple, payload: jax.Array) -> jax.Array:
    """The ragged collective with a block interface: [P, S, d] per-peer send
    blocks -> [P, S, d] per-sender recv blocks (rows >= sizes[q][me] zero).

    Native path: pack to the ragged operand and issue ONE
    `lax.ragged_all_to_all` (v5e-validated, hw_logs/hw_session_r4.log).
    Emulated path (XLA:CPU / old jax): the same pack/unpack geometry wrapped
    around a padded all_to_all — identical numerics, so the CPU mesh tests
    exercise the real offset math even where the op cannot lower."""
    P, S, d = payload.shape
    S_mat, in_off, recv_off, T_pad, R_pad = _ragged_geometry(sizes)
    me = jax.lax.axis_index(spec.axis_name)
    in_off_d = jnp.asarray(in_off, jnp.int32)[me]          # [P]
    send_d = jnp.asarray(S_mat, jnp.int32)[me]             # [P] rows to peer j
    recv_off_d = jnp.asarray(recv_off, jnp.int32)[me]      # [P]
    recv_d = jnp.asarray(S_mat.T, jnp.int32)[me]           # [P] rows from q
    operand = _ragged_pack(in_off_d, send_d, T_pad, payload)
    if ragged_native_ok():
        # output_offsets[j] = where MY chunk lands on receiver j
        out_off_d = jnp.asarray(recv_off.T, jnp.int32)[me]
        output = jnp.zeros((R_pad, d), payload.dtype)
        out = jax.lax.ragged_all_to_all(
            operand, output, in_off_d, send_d, out_off_d, recv_d,
            axis_name=spec.axis_name)
    else:
        blocks = _ragged_unpack(in_off_d, send_d, S, operand)
        recvb = jax.lax.all_to_all(blocks.reshape(P * S, d), spec.axis_name,
                                   0, 0, tiled=True).reshape(P, S, d)
        out = _ragged_pack(recv_off_d, recv_d, R_pad, recvb)
    return _ragged_unpack(recv_off_d, recv_d, S, out)


def _ragged_wire_impl(spec: HaloSpec, sizes: tuple, send: jax.Array) -> jax.Array:
    P = send.shape[0]
    if spec.wire == "native":
        payload, scale = send, None
    else:
        payload, scale = _quant(send, spec.wire)
    recv = _ragged_a2a(spec, sizes, payload)
    rscale = None
    if scale is not None:
        # per-(sender, peer) block scales ride a tiny dense all_to_all, as
        # on the padded path (P floats vs megabytes of rows)
        rscale = jax.lax.all_to_all(scale.reshape(P, 1), spec.axis_name,
                                    0, 0, tiled=True).reshape(P, 1, 1)
    return _dequant(recv, rscale, send.dtype)


@partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _ragged_wire(spec: HaloSpec, sizes: tuple, send: jax.Array) -> jax.Array:
    return _ragged_wire_impl(spec, sizes, send)


def _ragged_wire_fwd(spec, sizes, send):
    return _ragged_wire_impl(spec, sizes, send), None


def _ragged_wire_bwd(spec, sizes, _, g):
    # the transpose of a ragged all_to_all is the ragged all_to_all with the
    # pair-size matrix transposed: the cotangent of what I received from q
    # (S[q][me] rows) routes back to q. Quantized wires re-quantize g with
    # its OWN scales, never the activations' (the fp8-comm pitfall).
    return (_ragged_wire_impl(spec, _transpose_sizes(sizes), g),)


_ragged_wire.defvjp(_ragged_wire_fwd, _ragged_wire_bwd)


def halo_start(spec: HaloSpec, plan: HaloPlan, h: jax.Array):
    """Dispatch one layer's halo exchange WITHOUT consuming its result.

    Returns the in-flight received payload (a pytree of arrays: one
    [P*S_pad, d] buffer for 'padded'/'ragged', a tuple of per-round blocks
    for 'shift') to be scattered into halo slots by `halo_finish`. Nothing
    here depends on any aggregation output, and nothing downstream of the
    caller's independent (interior) compute depends on this value — that
    dependence gap is what lets the XLA latency-hiding scheduler run the
    collective concurrently with interior SpMM work (`--overlap split`).

    Composes with all three strategies and all four wire codecs; AD through
    start+finish is exactly halo_apply's transpose (the custom-vjp wire hops
    sit inside), so gradients re-quantize with their own scales as before.
    """
    P, Sp, d = spec.n_parts, spec.pad_send, h.shape[-1]
    if spec.strategy == "shift" and P > 1:
        me = jax.lax.axis_index(spec.axis_name)
        recvs = []
        for k in range(1, P):
            Sk = spec.shift_pads[k - 1]
            if Sk == 0:
                continue                       # no pair on this diagonal sends
            to = (me + k) % P                  # peer I send to this round
            sel_k = jax.lax.dynamic_index_in_dim(plan.sel, to, 0, False)[:Sk]
            w_k = jax.lax.dynamic_index_in_dim(plan.weight, to, 0, False)[:Sk]
            send = (h[sel_k] * w_k[:, None]).astype(h.dtype)       # [Sk, d]
            if spec.wire == "native":
                perm = [(i, (i + k) % P) for i in range(P)]
                recv = jax.lax.ppermute(send, spec.axis_name, perm)
            else:
                recv = _ppermute_wire(spec, k, send)
            recvs.append(recv)
        return tuple(recvs)

    # keep the payload in h's dtype: weight is f32, and bf16*f32 would promote
    # (doubling the wire bytes and tripping the bf16 scatter in halo_finish)
    send = (h[plan.sel] * plan.weight[..., None]).astype(h.dtype)  # [P, S, d]
    if spec.strategy == "ragged":
        # exact per-pair rows in ONE collective (runs even at P=1 so a
        # single-chip bench measures the real dispatch cost); the valid
        # sample rows are the FIRST send_size[me, j] of each S_pad block
        # (sampling.pair_sample contract), which is what makes the ragged
        # chunks contiguous prefixes
        return _ragged_wire(spec, spec.pair_send, send).reshape(P * Sp, d)
    # padded: one tiled all_to_all, uniform S_pad per pair
    if spec.wire == "native":
        return jax.lax.all_to_all(send.reshape(P * Sp, d), spec.axis_name,
                                  0, 0, tiled=True)             # [P*S, d]
    return _a2a_wire(spec, send).reshape(P * Sp, d)


def halo_finish(spec: HaloSpec, plan: HaloPlan, recv, like: jax.Array
                ) -> jax.Array:
    """Scatter `halo_start`'s received payload into the fixed per-peer halo
    slot blocks. Returns the halo buffer [n_halo, d] (NOT concatenated with
    the inner rows — the overlap-split caller scales/concatenates itself).
    `like` supplies only the static feature width and dtype; no data
    dependency on it is introduced."""
    P = spec.n_parts
    buf = jnp.zeros((spec.n_halo + 1, like.shape[-1]), dtype=like.dtype)
    if spec.strategy == "shift" and P > 1:
        me = jax.lax.axis_index(spec.axis_name)
        i = 0
        for k in range(1, P):
            Sk = spec.shift_pads[k - 1]
            if Sk == 0:
                continue                       # matches halo_start's rounds
            frm = (me - k) % P                 # peer I receive from
            slots_k = jax.lax.dynamic_index_in_dim(plan.slots, frm, 0, False)[:Sk]
            buf = buf.at[slots_k].add(recv[i])
            i += 1
        return buf[:-1]
    buf = buf.at[plan.slots.reshape(-1)].add(recv)
    return buf[:-1]


def halo_apply(spec: HaloSpec, plan: HaloPlan, h: jax.Array) -> jax.Array:
    """One layer's halo exchange: h [pad_inner, d] -> h_ext [pad_inner + n_halo, d].

    Fully differentiable; the AD transpose is the reference's backward
    all-to-all with scatter-add x (1/ratio) (helper/feature_buffer.py:119-129).
    The wire codec hops carry custom VJPs so fp8/bf16 compression applies to
    both directions with direction-appropriate scales.

    Implemented as halo_start + halo_finish (the `--overlap split` seam) so
    the fused and split paths share one collective implementation and cannot
    drift numerically.
    """
    recv = halo_start(spec, plan, h)
    return jnp.concatenate([h, halo_finish(spec, plan, recv, h)], axis=0)


def sampled_presence(spec: HaloSpec, plan: HaloPlan) -> jax.Array:
    """[pad_inner + n_halo] bool — which extended rows are live this epoch
    (GAT masks absent halos out of its edge softmax with this)."""
    return plan.presence


def full_rate_spec(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                   axis_name: str = "parts") -> tuple[HaloSpec, dict]:
    """rate-1.0 (spec, tables) used by the precompute exchange (train.py:170-189)."""
    return make_halo_spec(n_b, pad_inner, pad_boundary, 1.0, axis_name)


def precompute_exchange(spec_full: HaloSpec, tables_full: dict,
                        bnd: jax.Array, feat: jax.Array) -> jax.Array:
    """One full-rate exchange of raw input features at setup (`use_pp`,
    reference precompute train.py:170-189). Returns feat_ext
    [pad_inner + n_halo, F]; aggregation per model is done by the caller."""
    zero = jnp.zeros((), dtype=jnp.uint32)
    plan = make_halo_plan(spec_full, tables_full, bnd, zero,
                          # graftlint: disable=prng-literal-key(exact plan: key is a dead argument)
                          jax.random.key(0))  # exact => key unused
    return halo_apply(spec_full, plan, feat)
