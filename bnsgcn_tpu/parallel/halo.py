"""The halo (boundary-activation) exchange — the heart of partition parallelism.

TPU-native redesign of the reference feature buffer (helper/feature_buffer.py):

  * one static-shape tiled `lax.all_to_all` over the 'parts' mesh axis
    replaces the gloo irecv/isend ring + pinned staging + deferred-send queues
    (helper/feature_buffer.py:102-129) and the MPI all_to_all (:132-153);
  * the BNS sample for the epoch is computed once per step on *both* endpoints
    from a shared key (`parallel/sampling.py`), replacing the per-epoch index
    exchange (reference train.py:389);
  * sampled activations are scaled by 1/ratio on the sender
    (helper/feature_buffer.py:117,143) and scattered into fixed per-peer halo
    slot blocks; unsampled slots stay zero, which under sum-aggregation over
    the *full* static halo edge list reproduces exactly the reference's
    aggregation over the per-epoch sampled subgraph (train.py:256-281) — no
    graph reconstruction, ever;
  * the backward pass needs no grad hooks (helper/feature_buffer.py:97-98,
    169-182): JAX AD transposes gather -> all_to_all -> scatter-add into
    scatter-add -> all_to_all -> gather, which is precisely the reference's
    gloo backward including the 1/ratio rescale (:129).

Slot layout (see data/artifacts.py): extended row `pad_inner + q*pad_b + k`
on part j holds the k-th entry of q's boundary list toward j.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.parallel.sampling import identity_sample, pair_key, pair_sample


@dataclass(frozen=True)
class HaloSpec:
    """Static exchange geometry (python ints only — safe to close over in jit).

    The replicated device tables (n_b, send_size, inv_ratio) travel separately
    as a `tables` dict argument through shard_map with spec P()."""
    n_parts: int
    pad_inner: int
    pad_boundary: int                  # B_pad: per-pair boundary padding
    pad_send: int                      # S_pad: per-pair send padding (<= B_pad)
    axis_name: str = "parts"
    exact: bool = False                # rate == 1.0: identity ordering, no top_k

    @property
    def n_halo(self) -> int:
        return self.n_parts * self.pad_boundary


def make_halo_spec(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                   rate: float, axis_name: str = "parts"
                   ) -> tuple[HaloSpec, dict]:
    """Derive fixed send sizes and ratios from boundary sizes + sampling rate
    (reference get_send_size/get_recv_size, train.py:107-131).

    Returns (spec, tables): `tables` = {n_b, send_size, inv_ratio} device
    arrays, replicated across the mesh."""
    n_b = np.asarray(n_b, dtype=np.int64)
    P = n_b.shape[0]
    exact = rate >= 1.0
    send_size = n_b if exact else (rate * n_b).astype(np.int64)
    ratio = np.where(n_b > 0, send_size / np.maximum(n_b, 1), 0.0)
    inv_ratio = np.where(ratio > 0, 1.0 / np.maximum(ratio, 1e-30), 0.0)
    # S_pad: one uniform per-pair send width; multiple of 8 for lane friendliness
    pad_send = max(1, int(send_size.max())) if send_size.size else 1
    pad_send = min(((pad_send + 7) // 8) * 8, pad_boundary)
    spec = HaloSpec(
        n_parts=P, pad_inner=pad_inner, pad_boundary=pad_boundary,
        pad_send=pad_send, axis_name=axis_name, exact=exact,
    )
    tables = {"n_b": jnp.asarray(n_b, jnp.int32),
              "send_size": jnp.asarray(send_size, jnp.int32),
              "inv_ratio": jnp.asarray(inv_ratio, jnp.float32)}
    return spec, tables


@dataclass
class HaloPlan:
    """Per-epoch sampling decisions, shared by every layer's exchange
    (the reference samples once per epoch, train.py:388-390)."""
    sel: jax.Array                     # [P, S] my boundary positions to send to each peer
    weight: jax.Array                  # [P, S] f32: valid/ratio sender scaling
    slots: jax.Array                   # [P, S] int32: halo slots for received rows (trash = n_halo)
    presence: jax.Array                # [pad_inner + n_halo] bool: inner + sampled halos


def make_halo_plan(spec: HaloSpec, tables: dict, bnd: jax.Array,
                   epoch: jax.Array, base_key: jax.Array) -> HaloPlan:
    """Compute this epoch's send selection and receive scatter plan.

    `bnd`: [P, B_pad] — this device's boundary lists toward each peer
    (sharded row of artifacts.bnd). Runs inside shard_map.
    """
    P, Bp, Sp = spec.n_parts, spec.pad_boundary, spec.pad_send
    me = jax.lax.axis_index(spec.axis_name)
    peers = jnp.arange(P)

    n_send = tables["n_b"][me]                 # [P]
    s_send = tables["send_size"][me]
    n_recv = tables["n_b"][:, me]
    s_recv = tables["send_size"][:, me]

    if spec.exact:
        pos, valid = jax.vmap(lambda n: identity_sample(n, Sp))(n_send)
        rpos, rvalid = jax.vmap(lambda n: identity_sample(n, Sp))(n_recv)
    else:
        send_keys = jax.vmap(lambda j: pair_key(base_key, epoch, me, j))(peers)
        recv_keys = jax.vmap(lambda q: pair_key(base_key, epoch, q, me))(peers)
        pos, valid = jax.vmap(
            lambda k, n, s: pair_sample(k, n, s, Bp, Sp))(send_keys, n_send, s_send)
        rpos, rvalid = jax.vmap(
            lambda k, n, s: pair_sample(k, n, s, Bp, Sp))(recv_keys, n_recv, s_recv)

    sel = jnp.take_along_axis(bnd, pos.astype(bnd.dtype), axis=1)          # [P, S]
    weight = jnp.where(valid, tables["inv_ratio"][me][:, None], 0.0)       # [P, S]
    slots = jnp.where(rvalid, peers[:, None] * Bp + rpos, spec.n_halo)     # [P, S]

    presence = jnp.zeros(spec.n_halo + 1, dtype=bool).at[slots.reshape(-1)].set(True)
    presence = jnp.concatenate(
        [jnp.ones(spec.pad_inner, dtype=bool), presence[:-1]])
    return HaloPlan(sel=sel, weight=weight, slots=slots, presence=presence)


def halo_apply(spec: HaloSpec, plan: HaloPlan, h: jax.Array) -> jax.Array:
    """One layer's halo exchange: h [pad_inner, d] -> h_ext [pad_inner + n_halo, d].

    Fully differentiable; the AD transpose is the reference's backward
    all-to-all with scatter-add x (1/ratio) (helper/feature_buffer.py:119-129).
    """
    P, Sp, d = spec.n_parts, spec.pad_send, h.shape[-1]
    # keep the payload in h's dtype: weight is f32, and bf16*f32 would promote
    # (doubling the wire bytes and tripping the bf16 scatter below)
    send = (h[plan.sel] * plan.weight[..., None]).astype(h.dtype)  # [P, S, d]
    recv = jax.lax.all_to_all(send.reshape(P * Sp, d), spec.axis_name,
                              0, 0, tiled=True)                 # [P*S, d]
    buf = jnp.zeros((spec.n_halo + 1, d), dtype=h.dtype)
    buf = buf.at[plan.slots.reshape(-1)].add(recv)
    return jnp.concatenate([h, buf[:-1]], axis=0)


def sampled_presence(spec: HaloSpec, plan: HaloPlan) -> jax.Array:
    """[pad_inner + n_halo] bool — which extended rows are live this epoch
    (GAT masks absent halos out of its edge softmax with this)."""
    return plan.presence


def full_rate_spec(n_b: np.ndarray, pad_inner: int, pad_boundary: int,
                   axis_name: str = "parts") -> tuple[HaloSpec, dict]:
    """rate-1.0 (spec, tables) used by the precompute exchange (train.py:170-189)."""
    return make_halo_spec(n_b, pad_inner, pad_boundary, 1.0, axis_name)


def precompute_exchange(spec_full: HaloSpec, tables_full: dict,
                        bnd: jax.Array, feat: jax.Array) -> jax.Array:
    """One full-rate exchange of raw input features at setup (`use_pp`,
    reference precompute train.py:170-189). Returns feat_ext
    [pad_inner + n_halo, F]; aggregation per model is done by the caller."""
    zero = jnp.zeros((), dtype=jnp.uint32)
    plan = make_halo_plan(spec_full, tables_full, bnd, zero,
                          jax.random.key(0))  # exact => key unused
    return halo_apply(spec_full, plan, feat)
