"""Gradient reduction — the reference Reducer's TPU-native equivalent.

The reference (helper/reducer.py) builds a per-parameter apparatus: one
process group per tensor, pinned host mirrors, a thread pool and a side CUDA
stream, grad hooks dividing by global n_train and launching async
all_reduce(SUM), then an explicit `synchronize()` between backward and
optimizer step (train.py:337-338, 411-413).

Under SPMD none of that machinery exists as code: parameters enter the
shard_map'd loss with a replicated spec (P()), and the AD transpose of a
replicated value whose cotangents are device-varying *is* a psum — XLA emits
the all-reduce and schedules it to overlap the backward automatically
(verified by the exactness tests: P=4 grads == P=1 grads at rate 1.0). The
1/n_train normalization lives in the loss (trainer.ce_sum/bce_sum callers),
reproducing sum-loss / global-n_train + SUM-reduce == full-graph mean-loss
gradient (reference train.py:359-361, helper/reducer.py:34).

This module provides the *explicit* forms for code that computes gradients
inside shard_map directly (per-device jax.grad of a local loss), plus a
debugging check for replica consistency.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def grad_reduce_axes(axis_name: str = "parts",
                     replica_axis: str | None = None,
                     feat_axis: str | None = None):
    """Mesh axes of the ONE fused gradient/loss psum.

    On the 2-D ('replicas', 'parts') mesh (parallel/replicas.py) the
    cross-replica gradient MEAN is fused into the existing parts-axis
    reduction: the loss sums per-device losses with a single psum over BOTH
    axes and the 1/n_replicas rescale rides the existing 1/n_train scalar —
    never a second collective (XLA emits one all-reduce over the full mesh,
    which it can still overlap with the backward exactly as on the 1-D
    path). The 3-D mesh's 'feat' axis (parallel/feat.py) folds in the same
    way: per-device losses are identical along 'feat' (each layer already
    psummed its partials), so spanning the axis here and riding a
    1/n_feat rescale on the same 1/n_train scalar keeps the per-step
    gradient reduce ONE collective over the whole mesh — replicated params'
    AD transpose emits a single all-reduce, never a second feat-only hop.
    replica_axis=feat_axis=None returns the bare parts axis: the historical
    1-D reduction, bit-identical."""
    if replica_axis is None and feat_axis is None:
        return axis_name
    axes = [a for a in (replica_axis, axis_name, feat_axis) if a is not None]
    return tuple(axes)


def psum_gradients(grads, axis_name="parts", n_train: int | None = None):
    """Explicit SUM all-reduce of per-device gradients (+ optional /n_train).
    `axis_name` may be a tuple (e.g. grad_reduce_axes('parts', 'replicas'))
    — still ONE collective.

    Use ONLY when the gradients were computed per-device inside shard_map
    without a replicated-param transpose — the default trainer path must NOT
    call this (the AD transpose already summed; doing it twice multiplies by
    the mesh size)."""
    if n_train:
        grads = jax.tree.map(lambda g: g / n_train, grads)
    return jax.tree.map(lambda g: jax.lax.psum(g, axis_name), grads)


def assert_replicated(tree, atol: float = 0.0) -> None:
    """Host-side check that a replicated pytree is bitwise (or atol-close)
    identical across devices — the SPMD analog of 'did every rank apply the
    same update'. Cheap guard for multi-host debugging."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        arr = leaf
        if not hasattr(arr, "addressable_shards"):
            continue
        shards = arr.addressable_shards
        if len(shards) <= 1:
            continue
        import numpy as np
        first = np.asarray(jax.device_get(shards[0].data))
        for s in shards[1:]:
            same = np.allclose(first, np.asarray(jax.device_get(s.data)),
                               atol=atol, rtol=0)
            if not same:
                raise AssertionError(
                    f"replicated leaf {jax.tree_util.keystr(path)} diverges "
                    f"between devices {shards[0].device} and {s.device}")
