"""Partition-sharded serving, router half: one line-JSON listener fronting
a fleet of per-part backends (serve_backend.py).

The training partition artifacts become the serving shard map: `meta.json` +
each part's `global_nid` give the `global node id -> owning part` table, and
the router forwards every op to the backend(s) that own the nodes it
touches. Reads (`predict`/`predict_many`) go to ONE replica of the owning
part, round-robined, over pooled persistent connections (coord.
LineJsonClient — resends once on a torn response, safe because reads are
idempotent). Writes (`add_edges`/`update_feat`) are serialized under the
router's delta lock and fan out in three phases with the at-most-once
discipline (`rpc_line_json(retry_sent=False)` — a delta must never be
ingested twice):

  1. apply   — the owning parts' replicas append the edge halves / feature
               row they own (and journal them to their shard delta logs);
  2. invalidate — EVERY backend drops the touched nodes from its remote-
               halo cache (a cached boundary row is valid exactly until its
               owner changes it);
  3. mark    — the <= L-hop forward closure of the touched nodes is marked
               dirty by a distributed BFS: each owning part walks its local
               out-edges and returns the cross-part frontier with the
               remaining hop budget; the router continues the wave with a
               global best-budget dedup until it dries up.

The router replies to the writing client only after all three phases, so a
client's own follow-up read always sees its delta (the same ordering the
single-host core gets from one lock hold).

Failure semantics: a backend that misses its deadline on a read is evicted
from the fleet and the next replica is tried; with no live replica left the
client gets a named error (`RouteError: part P ...`) within the route
deadline — never a hang. A backend lost mid-write fan-out is evicted and
reported in the response; the delta is journaled by the replicas that took
it, and the resolve/halo path keeps serving from the survivors.

This module deliberately imports none of the model/XLA stack: the router
holds no table and runs no forward — it is pure routing + bookkeeping over
the coordinator transport (the CLI pulls resilience, and thus jax, only for
the signal-handling idiom; the routing classes stay import-light for unit
tests).

CLI:  python -m bnsgcn_tpu.main serve-router --dataset ... \
          --part-path ... --serve-port 18120 [--parts P] [--part-replicas R]
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

import numpy as np

from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu.config import Config, ConfigError, parse_config
from bnsgcn_tpu.parallel import coord as coord_mod


class RouteError(ValueError):
    """No live backend could answer for a part — named, deadline-bounded,
    and converted to an {"ok": False} response by the dispatcher."""


def router_endpoint(cfg: Config) -> tuple[str, int]:
    """(addr, port) a backend registers with / a client connects to, from
    --serve-router 'host:port' (default 127.0.0.1:{--serve-port})."""
    if cfg.serve_router:
        host, _, port = cfg.serve_router.rpartition(":")
        if not host or not port.isdigit():
            raise ConfigError(f"--serve-router must be 'host:port', got "
                              f"{cfg.serve_router!r}")
        return host, int(port)
    return "127.0.0.1", cfg.serve_port


def artifacts_dir(cfg: Config) -> str:
    """Where the training partition artifacts live — mirrors
    run.artifacts_dir without importing the jax-heavy training stack."""
    name = cfg.graph_name or cfg.derive_graph_name()
    return os.path.join(cfg.part_path, name)


def load_owner_map(part_dir: str) -> np.ndarray:
    """[n_nodes] int32 `global node id -> owning part`, from the training
    partition artifacts (meta.json n_inner + each part{p}.npz global_nid).
    The boundary-node tables the training halo exchange indexes by are the
    same ids — this map IS the serving shard map, no re-partitioning."""
    meta_path = os.path.join(part_dir, "meta.json")
    if not os.path.exists(meta_path):
        raise ConfigError(
            f"no partition artifacts at {part_dir} — build them first "
            f"(python -m bnsgcn_tpu.data.partition_cli ... or any training "
            f"run over this dataset/partition config)")
    with open(meta_path) as f:
        meta = json.load(f)
    n = int(np.sum(np.asarray(meta["n_inner"], dtype=np.int64)))
    owner = np.full(n, -1, dtype=np.int32)
    for p in range(int(meta["n_parts"])):
        with np.load(os.path.join(part_dir, f"part{p}.npz")) as z:
            gnid = np.asarray(z["global_nid"], dtype=np.int64)
        gnid = gnid[gnid >= 0]
        if gnid.size and (gnid.max() >= n or (owner[gnid] >= 0).any()):
            raise ConfigError(
                f"partition artifacts at {part_dir} are inconsistent: part "
                f"{p} claims nodes outside [0, {n}) or already owned")
        owner[gnid] = p
    if (owner < 0).any():
        raise ConfigError(
            f"partition artifacts at {part_dir} do not cover the graph "
            f"({int((owner < 0).sum())}/{n} nodes unowned)")
    return owner


# ----------------------------------------------------------------------------
# the fleet: registered backends + pooled read connections
# ----------------------------------------------------------------------------

class Fleet:
    """Registry of live backends keyed (part, replica): addresses, a small
    pool of persistent read connections each (a LineJsonClient serializes
    its in-flight request, so one connection per backend would queue
    concurrent routed reads behind each other), and per-part round-robin
    state."""

    POOL = 4        # persistent read connections per backend

    def __init__(self, n_parts: int, replicas: int,
                 route_timeout_s: float = 15.0):
        self.n_parts = int(n_parts)
        self.replicas = int(replicas)
        self.route_timeout_s = route_timeout_s
        self._lock = threading.Lock()
        self._backends: dict = {}   # guarded-by: self._lock
        self._clients: dict = {}    # guarded-by: self._lock
        self._rr: dict = {}         # guarded-by: self._lock
        self._crr: dict = {}        # guarded-by: self._lock

    def register(self, part: int, replica: int, addr: str, port: int) -> str:
        part, replica = int(part), int(replica)
        if not 0 <= part < self.n_parts:
            raise ValueError(f"part {part} out of range [0, {self.n_parts})")
        if not 0 <= replica < self.replicas:
            raise ValueError(f"replica {replica} out of range "
                             f"[0, {self.replicas})")
        bid = f"p{part}.r{replica}"
        with self._lock:
            old = self._clients.pop((part, replica), [])
            self._backends[(part, replica)] = {
                "addr": addr, "port": int(port), "id": bid}
        for c in old:
            c.close()       # re-registration (backend restart) wins
        return bid

    def evict(self, part: int, replica: int):
        with self._lock:
            self._backends.pop((part, replica), None)
            old = self._clients.pop((part, replica), [])
        for c in old:
            c.close()

    def missing_parts(self) -> list[int]:
        with self._lock:
            covered = {p for p, _ in self._backends}
        return [p for p in range(self.n_parts) if p not in covered]

    def replicas_of(self, part: int) -> list[int]:
        with self._lock:
            return sorted(r for p, r in self._backends if p == int(part))

    def endpoint(self, part: int, replica: int) -> Optional[dict]:
        with self._lock:
            be = self._backends.get((int(part), int(replica)))
            return dict(be) if be else None

    def client(self, part: int, replica: int
               ) -> Optional[coord_mod.LineJsonClient]:
        """A pooled read connection to one backend (idempotent ops only):
        grown lazily up to POOL, then round-robined — concurrent routed
        reads must not queue behind one another's round trip."""
        key = (int(part), int(replica))
        with self._lock:
            be = self._backends.get(key)
            if be is None:
                return None
            pool = self._clients.setdefault(key, [])
            if len(pool) < self.POOL:
                c = coord_mod.LineJsonClient(be["addr"], be["port"],
                                             timeout_s=self.route_timeout_s,
                                             what=f"backend {be['id']}")
                pool.append(c)
                return c
            i = self._crr.get(key, 0)
            self._crr[key] = i + 1
            return pool[i % len(pool)]

    def pick(self, part: int) -> Optional[int]:
        """Round-robin replica choice for a read on `part`."""
        part = int(part)
        with self._lock:
            live = sorted(r for p, r in self._backends if p == part)
            if not live:
                return None
            i = self._rr.get(part, 0)
            self._rr[part] = i + 1
        return live[i % len(live)]

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = {str(p): [] for p in range(self.n_parts)}
            for (p, r), be in sorted(self._backends.items()):
                out[str(p)].append({"replica": r, "addr": be["addr"],
                                    "port": be["port"], "id": be["id"]})
        return out

    def close(self):
        with self._lock:
            clients = [c for pool in self._clients.values() for c in pool]
            self._clients.clear()
        for c in clients:
            c.close()


# ----------------------------------------------------------------------------
# the router core: ownership routing + three-phase delta fan-out
# ----------------------------------------------------------------------------

class RouterCore:
    """Protocol-level router over a Fleet (the TCP layer below is a thin
    dispatcher; tests drive this directly). Thread-safe: counters under
    self._lock, delta fan-out serialized under self._delta_lock."""

    def __init__(self, owner: np.ndarray, n_parts: int, replicas: int = 1,
                 hops: int = 2, log=print,
                 obs: Optional[obs_mod.Obs] = None,
                 route_timeout_s: float = 15.0,
                 delta_timeout_s: float = 60.0):
        self.owner = np.asarray(owner, dtype=np.int32)
        self.n_nodes = int(self.owner.shape[0])
        self.hops = int(hops)
        self.log = log
        self.obs = obs
        self.route_timeout_s = route_timeout_s
        self.delta_timeout_s = delta_timeout_s
        self.fleet = Fleet(n_parts, replicas, route_timeout_s=route_timeout_s)
        self.registry = obs.registry if obs is not None else obs_mod.Registry()
        # router-side route-latency histograms, same key names the backends
        # use so `stats` answers serve_bench's existing server-vs-client
        # cross-check unchanged
        self._lat = {t: self.registry.histogram(f"serve/latency_ms/{t}")
                     for t in ("A", "B")}
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self.stats = {"requests": 0, "tier_a": 0, "tier_b": 0, "deltas": 0,
                      "fanout_rpcs": 0, "evictions": 0}
        self._delta_lock = threading.Lock()

    # -- readiness --

    def ready(self) -> list[int]:
        """[] when every part has at least one live backend; else the
        missing part ids."""
        return self.fleet.missing_parts()

    def _require_ready(self):
        missing = self.ready()
        if missing:
            raise RouteError(f"fleet not ready: no backend registered for "
                             f"part(s) {missing}")

    def _owner_of(self, node: int) -> int:
        node = int(node)
        if not 0 <= node < self.n_nodes:
            raise ValueError(f"node {node} out of range [0, {self.n_nodes})")
        return int(self.owner[node])

    # -- reads: round-robined, evict-on-timeout, pooled connections --

    def _forward_read(self, part: int, req: dict) -> tuple[dict, int]:
        """(response, replica) from the first live replica of `part`; a
        replica missing its deadline is evicted and the next one tried —
        no live replica left raises a named RouteError, never a hang."""
        tried: list[str] = []
        for _ in range(max(self.fleet.replicas, 1)):
            replica = self.fleet.pick(part)
            if replica is None:
                break
            client = self.fleet.client(part, replica)
            if client is None:
                continue
            try:
                resp = client.request(req)
            except coord_mod.CoordTimeout as ex:
                tried.append(f"r{replica} ({ex})")
                self.fleet.evict(part, replica)
                with self._lock:
                    self.stats["evictions"] += 1
                self.log(f"[router] evicted backend p{part}.r{replica}: {ex}")
                continue
            return resp, replica
        raise RouteError(
            f"part {part}: no live backend within {self.route_timeout_s}s "
            f"deadline (tried: {', '.join(tried) or 'none registered'})")

    def predict(self, node: int, tier: Optional[str] = None) -> dict:
        self._require_ready()
        t0 = time.perf_counter()
        part = self._owner_of(node)
        req = {"op": "predict", "node": int(node)}
        if tier is not None:
            req["tier"] = tier
        resp, replica = self._forward_read(part, req)
        with self._lock:
            self.stats["requests"] += 1
            self.stats["fanout_rpcs"] += 1
            if resp.get("tier") == "B":
                self.stats["tier_b"] += 1
            elif resp.get("tier") == "A":
                self.stats["tier_a"] += 1
        # client-side shard tags: serve_bench splits its percentiles by
        # these without a second round trip
        resp["part"] = part
        resp["backend"] = f"p{part}.r{replica}"
        if resp.get("tier") in ("A", "B"):
            self._lat[resp["tier"]].observe((time.perf_counter() - t0) * 1e3)
        return resp

    def predict_many(self, nodes, tier: Optional[str] = None) -> list[dict]:
        """Split by owning part, forward each shard's slice concurrently,
        merge back in request order (each result carries its shard tags)."""
        self._require_ready()
        nodes = [int(n) for n in nodes]
        by_part: dict[int, list[int]] = {}
        for n in nodes:
            by_part.setdefault(self._owner_of(n), []).append(n)
        results: dict[int, dict] = {}
        errors: list[str] = []
        res_lock = threading.Lock()

        def _one(part: int, shard: list[int]):
            req = {"op": "predict_many", "nodes": shard}
            if tier is not None:
                req["tier"] = tier
            try:
                resp, replica = self._forward_read(part, req)
            except (RouteError, ValueError) as ex:
                with res_lock:
                    errors.append(str(ex))
                return
            if not resp.get("ok"):
                with res_lock:
                    errors.append(f"part {part}: {resp.get('err')}")
                return
            with res_lock:
                for r in resp["results"]:
                    r["part"] = part
                    r["backend"] = f"p{part}.r{replica}"
                    results[int(r["node"])] = r

        threads = [threading.Thread(target=_one, args=(p, shard))
                   for p, shard in sorted(by_part.items())]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RouteError("; ".join(errors))
        with self._lock:
            self.stats["requests"] += len(nodes)
            self.stats["fanout_rpcs"] += len(by_part)
            for n in nodes:
                tr = results[n].get("tier")
                if tr == "B":
                    self.stats["tier_b"] += 1
                elif tr == "A":
                    self.stats["tier_a"] += 1
        return [results[n] for n in nodes]

    # -- writes: three-phase fan-out under the delta lock --

    def _send_write(self, part: int, replica: int, req: dict,
                    timeout_s: Optional[float] = None) -> Optional[dict]:
        """At-most-once write to ONE backend (rpc_line_json fresh
        connection, retry_sent=False — a delta must never apply twice).
        Returns None (and evicts) on failure."""
        be = self.fleet.endpoint(part, replica)
        if be is None:
            return None
        try:
            resp = coord_mod.rpc_line_json(
                be["addr"], be["port"], req,
                time.monotonic() + (timeout_s or self.delta_timeout_s),
                what=f"backend {be['id']}", retry_sent=False)
        except coord_mod.CoordTimeout as ex:
            self.fleet.evict(part, replica)
            with self._lock:
                self.stats["evictions"] += 1
            self.log(f"[router] evicted backend p{part}.r{replica} "
                     f"mid-write: {ex}")
            return None
        with self._lock:
            self.stats["fanout_rpcs"] += 1
        return resp

    def _fan_part_write(self, part: int, req: dict) -> list[dict]:
        """The same write to EVERY live replica of `part` (replica state
        must stay identical); returns the ok responses."""
        out = []
        for replica in self.fleet.replicas_of(part):
            resp = self._send_write(part, replica, req)
            if resp is not None and resp.get("ok"):
                out.append(resp)
        return out

    def _invalidate_all(self, nodes: list[int]):
        """Phase 2: every backend drops the touched nodes from its halo
        cache — a cached boundary row is valid exactly until its owner
        changes it."""
        req = {"op": "invalidate", "nodes": [int(v) for v in nodes]}
        for part in range(self.fleet.n_parts):
            self._fan_part_write(part, req)

    def _mark_bfs(self, seeds: dict[int, int]) -> int:
        """Phase 3: distributed dirty-mark BFS. Each wave sends every
        pending (node, hops_left) to the owning part (ALL replicas — their
        dirty sets must agree; the frontier is taken from the first ok
        response since replica graphs are identical); the router dedups
        globally on best remaining budget, so no node is ever re-walked
        with a smaller budget than it already got."""
        best: dict[int, int] = {}
        work = {int(v): int(h) for v, h in seeds.items()}
        best.update(work)
        marked = 0
        while work:
            by_part: dict[int, list] = {}
            for v, h in work.items():
                by_part.setdefault(self._owner_of(v), []).append([v, h])
            work = {}
            for part, batch in sorted(by_part.items()):
                resps = self._fan_part_write(
                    part, {"op": "mark", "nodes": sorted(batch)})
                if not resps:
                    raise RouteError(
                        f"part {part}: no live backend took the dirty-mark "
                        f"fan-out — delta partially applied, retry after "
                        f"the part re-registers")
                marked += int(resps[0].get("marked", 0))
                for v, h in resps[0].get("frontier", []):
                    v, h = int(v), int(h)
                    if best.get(v, -1) >= h:
                        continue
                    best[v] = h
                    work[v] = h
        return marked

    def _dirty_total(self) -> int:
        total = 0
        for part in range(self.fleet.n_parts):
            try:
                resp, _ = self._forward_read(part, {"op": "dirty"})
            except RouteError:
                continue
            total += int(resp.get("count", 0))
        return total

    def add_edges(self, edges: list) -> dict:
        self._require_ready()
        pairs = [(int(u), int(v)) for u, v in edges]
        for u, v in pairs:
            self._owner_of(u), self._owner_of(v)      # range check up front
        with self._delta_lock:
            # phase 1: the owning parts append the halves they own
            by_part: dict[int, list] = {}
            for u, v in pairs:
                by_part.setdefault(self._owner_of(u), []).append([u, v])
                pv = self._owner_of(v)
                if pv != self._owner_of(u):
                    by_part.setdefault(pv, []).append([u, v])
            for part, batch in sorted(by_part.items()):
                if not self._fan_part_write(
                        part, {"op": "apply_delta", "edges": batch}):
                    raise RouteError(
                        f"part {part}: no live backend took the delta — "
                        f"nothing applied there; retry after it re-registers")
            touched = sorted({n for uv in pairs for n in uv})
            self._invalidate_all(touched)
            marked = self._mark_bfs({n: self.hops for n in touched})
            with self._lock:
                self.stats["deltas"] += 1
        out = {"ok": True, "dirty_new": marked,
               "dirty_total": self._dirty_total()}
        if self.obs is not None:
            self.obs.emit("delta", op="add_edges", edges=len(pairs),
                          dirty_new=out["dirty_new"],
                          dirty_total=out["dirty_total"], routed=True)
        return out

    def update_feat(self, node: int, vec) -> dict:
        self._require_ready()
        node = int(node)
        part = self._owner_of(node)
        with self._delta_lock:
            if not self._fan_part_write(
                    part, {"op": "apply_feat", "node": node,
                           "feat": list(vec)}):
                raise RouteError(
                    f"part {part}: no live backend took the feature "
                    f"update — nothing applied; retry after it re-registers")
            self._invalidate_all([node])
            marked = self._mark_bfs({node: self.hops})
            with self._lock:
                self.stats["deltas"] += 1
        out = {"ok": True, "dirty_new": marked,
               "dirty_total": self._dirty_total()}
        if self.obs is not None:
            self.obs.emit("delta", op="update_feat", node=node,
                          dirty_new=out["dirty_new"],
                          dirty_total=out["dirty_total"], routed=True)
        return out

    # -- aggregation ops --

    def flush(self) -> int:
        """Drain every backend's dirty set (long deadline: a flush is a
        full re-score of the dirty frontier). Non-idempotent (expensive to
        double-start), so at-most-once per backend."""
        self._require_ready()
        total = 0
        for part in range(self.fleet.n_parts):
            for resp in self._fan_part_write(
                    part, {"op": "flush"}):
                total += int(resp.get("refreshed", 0))
        return total

    def snapshot_stats(self) -> dict:
        out: dict = {"ok": True, "n_nodes": self.n_nodes,
                     "parts": self.fleet.n_parts,
                     "router": True, "missing_parts": self.ready()}
        with self._lock:
            out.update(self.stats)
        out["dirty"] = self._dirty_total()
        backends = []
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                client = self.fleet.client(part, replica)
                if client is None:
                    continue
                try:
                    resp = client.request({"op": "stats"})
                except coord_mod.CoordTimeout:
                    continue
                if resp.get("ok"):
                    resp["backend"] = f"p{part}.r{replica}"
                    backends.append(resp)
        out["backends"] = backends
        # router-side route-latency percentiles under the SAME keys the
        # single-host server reports, so serve_bench's server-vs-client
        # p50 cross-check works against the router unchanged
        for t in ("A", "B"):
            snap = self._lat[t].snapshot()
            out[f"tier_{t.lower()}_p50_ms"] = snap["p50"]
            out[f"tier_{t.lower()}_p99_ms"] = snap["p99"]
        return out

    def metrics(self) -> dict:
        """Router registry + nested per-backend registry snapshots."""
        per_backend: dict = {}
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                client = self.fleet.client(part, replica)
                if client is None:
                    continue
                try:
                    resp = client.request({"op": "metrics"})
                except coord_mod.CoordTimeout:
                    continue
                if resp.get("ok"):
                    per_backend[f"p{part}.r{replica}"] = resp["metrics"]
        return {"ok": True, "metrics": self.registry.snapshot(),
                "backends": per_backend}

    def shutdown_fleet(self, log=None) -> int:
        """Forward shutdown to every backend (each drains, flushes its
        delta-log shard, and exits 0). Returns how many acknowledged."""
        n = 0
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                resp = self._send_write(part, replica, {"op": "shutdown"},
                                        timeout_s=10.0)
                if resp is not None and resp.get("ok"):
                    n += 1
        return n

    def close(self):
        self.fleet.close()


# ----------------------------------------------------------------------------
# TCP front end
# ----------------------------------------------------------------------------

class RouterServer:
    """Line-JSON dispatcher over a RouterCore — same framing, drain
    discipline and in-flight accounting as serve.ServeServer."""

    # ops that stay answerable while draining, or before the fleet is
    # complete (registration must be possible before readiness, by
    # definition)
    ALWAYS = ("ping", "stats", "metrics", "fleet", "register")

    def __init__(self, core: RouterCore, port: int, addr: str = "",
                 log=print):
        self.core = core
        self.log = log
        self._inflight = 0      # guarded-by: self._lock
        self._draining = False  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.shutdown_requested = threading.Event()
        self.server = coord_mod.LineJsonServer(port, self._handle,
                                               addr=addr).start()

    @property
    def port(self) -> int:
        return self.server.port

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        with self._lock:
            if self._draining and op not in self.ALWAYS:
                return {"ok": False, "err": "draining"}
            self._inflight += 1
        try:
            return self._dispatch(op, req)
        except (KeyError, ValueError, TypeError) as ex:
            return {"ok": False, "err": f"{type(ex).__name__}: {ex}"}
        finally:
            with self._lock:
                self._inflight -= 1

    def _dispatch(self, op: Optional[str], req: dict) -> dict:
        core = self.core
        if op == "ping":
            return {"ok": True, "router": True}
        if op == "register":
            bid = core.fleet.register(req["part"], req.get("replica", 0),
                                      req.get("addr") or "127.0.0.1",
                                      req["port"])
            missing = core.ready()
            self.log(f"[router] registered backend {bid} at "
                     f"{req.get('addr') or '127.0.0.1'}:{req['port']}"
                     + (f" (waiting on parts {missing})" if missing
                        else " (fleet complete)"))
            return {"ok": True, "id": bid, "missing_parts": missing}
        if op == "fleet":
            return {"ok": True, "parts": core.fleet.snapshot(),
                    "missing_parts": core.ready()}
        if op == "predict":
            return core.predict(req["node"], tier=req.get("tier"))
        if op == "predict_many":
            return {"ok": True, "results": core.predict_many(
                req["nodes"], tier=req.get("tier"))}
        if op == "add_edges":
            return core.add_edges(req["edges"])
        if op == "update_feat":
            return core.update_feat(req["node"], req["feat"])
        if op == "flush":
            return {"ok": True, "refreshed": core.flush()}
        if op == "dirty":
            core._require_ready()
            return {"ok": True, "count": core._dirty_total()}
        if op == "stats":
            return core.snapshot_stats()
        if op == "metrics":
            return core.metrics()
        if op == "shutdown":
            self.shutdown_requested.set()
            return {"ok": True}
        return {"ok": False, "err": f"unknown op {op!r}"}

    def drain(self, timeout_s: float = 30.0, stop: bool = True):
        """Reject new client ops, wait out in-flight handlers; `stop=False`
        keeps the listener up (the shutdown sequence still answers
        ping/stats while the backends drain behind it)."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.01)
        if stop:
            self.server.stop()

    def stop(self):
        self.server.stop()


# ----------------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------------

def router_main(argv=None) -> int:
    """`python -m bnsgcn_tpu.main serve-router ...`.

    Exit codes: 0 clean fleet shutdown (client 'shutdown' op — forwarded to
    every backend), 75 graceful SIGTERM/SIGINT drain (backends keep
    running; the orchestrator owns their lifecycle), 2 config error."""
    from bnsgcn_tpu import resilience
    cfg = parse_config(argv)
    if not cfg.graph_name:
        cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    log = print
    obs = obs_mod.make_obs(cfg, rank=0, log=log)
    try:
        part_dir = artifacts_dir(cfg)
        owner = load_owner_map(part_dir)
        n_parts_art = int(owner.max()) + 1
        n_parts = cfg.parts if cfg.parts > 0 else n_parts_art
        if n_parts != n_parts_art:
            raise ConfigError(
                f"--parts {n_parts} != the {n_parts_art} parts in the "
                f"artifacts at {part_dir} — the shard map comes from the "
                f"training partition; re-partition or drop --parts")
        if cfg.part_replicas < 1:
            raise ConfigError(f"--part-replicas must be >= 1, got "
                              f"{cfg.part_replicas}")
        # L-hop budget for the distributed dirty-mark BFS: the model's
        # graph-layer count (ModelSpec.n_graph_layers = n_layers - n_linear,
        # computed flag-side so the router stays jax-free), same hop budget
        # as the single-host forward_closure
        hops = cfg.n_layers - cfg.n_linear
        if hops < 1:
            raise ConfigError(f"--n-layers {cfg.n_layers} with --n-linear "
                              f"{cfg.n_linear} leaves no graph layer")
    except ConfigError as ex:
        print(f"[config] {ex}", file=sys.stderr)
        sys.exit(2)

    core = RouterCore(owner, n_parts, replicas=cfg.part_replicas, hops=hops,
                      log=log, obs=obs)
    signals = resilience.PreemptSignals(
        action="drain in-flight routed requests",
        boundary="request boundary")
    signals.install()
    server = RouterServer(core, cfg.serve_port, cfg.serve_addr, log=log)
    log(f"[router] ready on port {server.port}: {n_parts} part(s) x "
        f"{cfg.part_replicas} replica(s), {core.n_nodes} nodes, "
        f"{hops}-hop dirty fan-out; waiting for backends to register")
    try:
        while signals.requested is None:
            if server.shutdown_requested.wait(0.05):
                break
    finally:
        clean = server.shutdown_requested.is_set()
        # drain ordering: stop taking client ops -> wait in-flight -> (on a
        # clean shutdown) forward shutdown so every backend flushes its
        # delta-log shard -> stop the listener
        server.drain(stop=False)
        acked = core.shutdown_fleet() if clean else 0
        server.stop()
        with core._lock:
            stats = dict(core.stats)
        log(f"[router] drained: {stats['requests']} request(s) routed "
            f"(A {stats['tier_a']} / B {stats['tier_b']}), "
            f"{stats['deltas']} delta(s) fanned out over "
            f"{stats['fanout_rpcs']} backend RPCs, "
            f"{stats['evictions']} eviction(s)"
            + (f", {acked} backend(s) shut down" if clean else ""))
        if obs is not None:
            obs.emit("serve_fleet", parts=n_parts,
                     replicas=cfg.part_replicas, shutdown_acked=acked,
                     **{k: stats[k] for k in sorted(stats)})
            obs.close()
        core.close()
        signals.restore()
    if signals.requested is not None:
        log(f"[router] {signals.requested} honored: backends keep serving; "
            f"relaunch the router to resume fronting them")
        sys.exit(resilience.EXIT_PREEMPTED)
    return 0


if __name__ == "__main__":
    sys.exit(router_main())
