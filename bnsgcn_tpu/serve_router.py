"""Partition-sharded serving, router half: one line-JSON listener fronting
a fleet of per-part backends (serve_backend.py).

The training partition artifacts become the serving shard map: `meta.json` +
each part's `global_nid` give the `global node id -> owning part` table, and
the router forwards every op to the backend(s) that own the nodes it
touches. Reads (`predict`/`predict_many`) go to ONE replica of the owning
part, round-robined, over pooled persistent connections (coord.
LineJsonClient — resends once on a torn response, safe because reads are
idempotent). Writes (`add_edges`/`update_feat`) are serialized under the
router's delta lock and fan out in three phases with the at-most-once
discipline (`rpc_line_json(retry_sent=False)` — a delta must never be
ingested twice):

  1. apply   — the owning parts' replicas append the edge halves / feature
               row they own (and journal them to their shard delta logs);
  2. invalidate — EVERY backend drops the touched nodes from its remote-
               halo cache (a cached boundary row is valid exactly until its
               owner changes it);
  3. mark    — the <= L-hop forward closure of the touched nodes is marked
               dirty by a distributed BFS: each owning part walks its local
               out-edges and returns the cross-part frontier with the
               remaining hop budget; the router continues the wave with a
               global best-budget dedup until it dries up.

The router replies to the writing client only after all three phases, so a
client's own follow-up read always sees its delta (the same ordering the
single-host core gets from one lock hold).

Failure semantics: a backend that misses its deadline on a read is evicted
from the fleet and the next replica is tried; with no live replica left the
client gets a named error (`RouteError: part P ...`) within the route
deadline — never a hang. A backend lost mid-write fan-out is evicted and
reported in the response; the delta is journaled by the replicas that took
it, and the resolve/halo path keeps serving from the survivors.

This module deliberately imports none of the model/XLA stack: the router
holds no table and runs no forward — it is pure routing + bookkeeping over
the coordinator transport (the CLI pulls resilience, and thus jax, only for
the signal-handling idiom; the routing classes stay import-light for unit
tests).

CLI:  python -m bnsgcn_tpu.main serve-router --dataset ... \
          --part-path ... --serve-port 18120 [--parts P] [--part-replicas R]
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

import numpy as np

from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu.config import Config, ConfigError, parse_config
from bnsgcn_tpu.parallel import coord as coord_mod


class RouteError(ValueError):
    """No live backend could answer for a part — named, deadline-bounded,
    and converted to an {"ok": False} response by the dispatcher."""


def router_endpoint(cfg: Config) -> tuple[str, int]:
    """(addr, port) a backend registers with / a client connects to, from
    --serve-router 'host:port' (default 127.0.0.1:{--serve-port})."""
    if cfg.serve_router:
        host, _, port = cfg.serve_router.rpartition(":")
        if not host or not port.isdigit():
            raise ConfigError(f"--serve-router must be 'host:port', got "
                              f"{cfg.serve_router!r}")
        return host, int(port)
    return "127.0.0.1", cfg.serve_port


def artifacts_dir(cfg: Config) -> str:
    """Where the training partition artifacts live — mirrors
    run.artifacts_dir without importing the jax-heavy training stack."""
    name = cfg.graph_name or cfg.derive_graph_name()
    return os.path.join(cfg.part_path, name)


def load_owner_map(part_dir: str) -> np.ndarray:
    """[n_nodes] int32 `global node id -> owning part`, from the training
    partition artifacts (meta.json n_inner + each part{p}.npz global_nid).
    The boundary-node tables the training halo exchange indexes by are the
    same ids — this map IS the serving shard map, no re-partitioning."""
    meta_path = os.path.join(part_dir, "meta.json")
    if not os.path.exists(meta_path):
        raise ConfigError(
            f"no partition artifacts at {part_dir} — build them first "
            f"(python -m bnsgcn_tpu.data.partition_cli ... or any training "
            f"run over this dataset/partition config)")
    with open(meta_path) as f:
        meta = json.load(f)
    n = int(np.sum(np.asarray(meta["n_inner"], dtype=np.int64)))
    owner = np.full(n, -1, dtype=np.int32)
    for p in range(int(meta["n_parts"])):
        with np.load(os.path.join(part_dir, f"part{p}.npz")) as z:
            gnid = np.asarray(z["global_nid"], dtype=np.int64)
        gnid = gnid[gnid >= 0]
        if gnid.size and (gnid.max() >= n or (owner[gnid] >= 0).any()):
            raise ConfigError(
                f"partition artifacts at {part_dir} are inconsistent: part "
                f"{p} claims nodes outside [0, {n}) or already owned")
        owner[gnid] = p
    if (owner < 0).any():
        raise ConfigError(
            f"partition artifacts at {part_dir} do not cover the graph "
            f"({int((owner < 0).sum())}/{n} nodes unowned)")
    return owner


# ----------------------------------------------------------------------------
# self-healing: health-state machine, circuit breaker, failover WAL
# ----------------------------------------------------------------------------

def _env_f(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


class HealthPolicy:
    """Probe cadence + thresholds for the router-side health checker.
    Cadence is the CLI knob (--serve-probe-s; 0 disables probing), the
    thresholds are env knobs like the $BNSGCN_COORD_* family so CI can
    shrink them without widening the CLI surface."""

    def __init__(self, probe_s: float = 0.0):
        self.probe_s = float(probe_s)
        self.probe_timeout_s = _env_f("BNSGCN_SERVE_PROBE_TIMEOUT_S", 1.0)
        # consecutive failures: up -> suspect after N, -> down after M >= N
        self.suspect_after = int(_env_f("BNSGCN_SERVE_SUSPECT_AFTER", 1))
        self.down_after = int(_env_f("BNSGCN_SERVE_DOWN_AFTER", 3))
        # consecutive probe successes that earn re-admission
        self.readmit = int(_env_f("BNSGCN_SERVE_READMIT", 2))
        # circuit breaker: >= FLAPS down-transitions inside WINDOW_S
        # quarantines the backend for HOLD_S (probes ignored meanwhile)
        self.breaker_flaps = int(_env_f("BNSGCN_SERVE_BREAKER_FLAPS", 3))
        self.breaker_window_s = _env_f("BNSGCN_SERVE_BREAKER_WINDOW_S", 30.0)
        self.breaker_hold_s = _env_f("BNSGCN_SERVE_BREAKER_HOLD_S", 10.0)
        # warm-up: nodes spot-checked bitwise against an up peer replica
        # before a rejoining backend is promoted
        self.spotcheck = int(_env_f("BNSGCN_SERVE_SPOTCHECK", 3))
        # hedged reads: delay floor under the p99-derived trigger
        self.hedge_floor_ms = _env_f("BNSGCN_SERVE_HEDGE_FLOOR_MS", 10.0)


class HealthState:
    """Per-backend up/suspect/down/quarantined state machine, driven by
    probe and request outcomes. Pure (injectable clock, no I/O) so the
    unit matrix covers every transition directly.

    up --fail x suspect_after--> suspect --fail (total down_after)--> down
    down --ok x readmit--> ready (caller runs the warm-up spot-check,
    then admit() -> up); suspect recovers to up on the same streak with
    no warm-up (its table never left the fleet). A backend that flaps
    down >= breaker_flaps times inside breaker_window_s is quarantined:
    probe successes are ignored until the hold expires, then it resumes
    as down and must earn the streak again."""

    def __init__(self, policy: HealthPolicy, now: float = 0.0,
                 state: str = "up"):
        self.policy = policy
        self.state = state
        self.fails = 0
        self.oks = 0
        self.flaps: list[float] = []    # down-transition timestamps
        self.hold_until = 0.0
        self.down_since: Optional[float] = now if state == "down" else None

    def _expire_hold(self, now: float):
        if self.state == "quarantined" and now >= self.hold_until:
            self.state = "down"

    def on_fail(self, now: float) -> Optional[str]:
        """Returns the new state on a transition, else None."""
        self._expire_hold(now)
        self.oks = 0
        self.fails += 1
        if self.state == "up" and self.fails >= self.policy.suspect_after:
            self.state = "suspect"
            if self.fails >= self.policy.down_after:
                return self._to_down(now)
            return "suspect"
        if self.state == "suspect" and self.fails >= self.policy.down_after:
            return self._to_down(now)
        return None

    def _to_down(self, now: float) -> str:
        self.state = "down"
        self.down_since = now
        self.flaps = [t for t in self.flaps
                      if now - t < self.policy.breaker_window_s]
        self.flaps.append(now)
        if len(self.flaps) >= self.policy.breaker_flaps:
            self.state = "quarantined"
            self.hold_until = now + self.policy.breaker_hold_s
            return "quarantined"
        return "down"

    def on_ok(self, now: float) -> Optional[str]:
        """Returns 'up' (suspect recovered), 'ready' (down backend earned
        the streak — caller must warm-up then admit()), or None."""
        if self.state == "quarantined":
            if now < self.hold_until:
                return None             # breaker holds: successes ignored
            self.state = "down"
        self.fails = 0
        if self.state == "up":
            return None
        self.oks += 1
        if self.oks < self.policy.readmit:
            return None
        if self.state == "suspect":
            self.state = "up"
            self.oks = 0
            return "up"
        return "ready"                  # down: warm-up gate before up

    def admit(self, now: float) -> float:
        """Promote to up after the warm-up spot-check passed; returns the
        outage wall clock (seconds since the down transition)."""
        outage = now - self.down_since if self.down_since is not None else 0.0
        self.state = "up"
        self.oks = self.fails = 0
        self.down_since = None
        return outage

    def reject_warmup(self):
        """Spot-check failed: stay down, re-earn the whole streak."""
        self.oks = 0


class DeltaWAL:
    """Bounded router-side write-ahead log for delta ops a down backend
    missed: per-part ordered entries, each tagged with the replica set
    that confirmed it, drained per replica on rejoin. An entry retires
    once every replica slot of its part took it. Append past `cap`
    pending entries for one part raises RouteError — the WAL is a
    recovery buffer, not unbounded spool. Callers serialize through the
    router's delta lock; there is deliberately no internal lock."""

    def __init__(self, cap: int, slots: int):
        self.cap = int(cap)
        self.slots = int(slots)         # replica slots per part
        self._log: dict[int, list] = {}  # part -> [[seq, taken_set, op], ...]
        self._seq = 0
        self.queued = 0                 # lifetime appends (stats)
        self.replayed = 0               # lifetime per-replica replays

    def record(self, part: int, op: dict, taken) -> Optional[int]:
        """Remember `op` for the replicas of `part` NOT in `taken`;
        returns the entry seq (None when every slot already took it)."""
        taken = set(int(r) for r in taken)
        if len(taken) >= self.slots:
            return None
        q = self._log.setdefault(int(part), [])
        if len(q) >= self.cap:
            raise RouteError(
                f"part {part}: failover WAL full ({self.cap} queued "
                f"deltas) — the down backend(s) must rejoin (or be "
                f"re-provisioned) before more writes are accepted")
        self._seq += 1
        self.queued += 1
        q.append([self._seq, taken, dict(op)])
        return self._seq

    def pending_for(self, part: int, replica: int) -> list:
        """[(seq, op)] this replica still misses, in commit order."""
        return [(seq, op) for seq, taken, op in self._log.get(int(part), [])
                if int(replica) not in taken]

    def mark_taken(self, part: int, replica: int, seqs) -> None:
        seqs = set(seqs)
        q = self._log.get(int(part), [])
        for ent in q:
            if ent[0] in seqs:
                ent[1].add(int(replica))
                self.replayed += 1
        self._log[int(part)] = [e for e in q if len(e[1]) < self.slots]

    def depth(self, part: int) -> int:
        return len(self._log.get(int(part), []))

    def snapshot(self) -> dict:
        return {str(p): len(q) for p, q in sorted(self._log.items()) if q}


# ----------------------------------------------------------------------------
# the fleet: registered backends + pooled read connections
# ----------------------------------------------------------------------------

class Fleet:
    """Registry of live backends keyed (part, replica): addresses, a small
    pool of persistent read connections each (a LineJsonClient serializes
    its in-flight request, so one connection per backend would queue
    concurrent routed reads behind each other), and per-part round-robin
    state."""

    POOL = 4        # persistent read connections per backend

    def __init__(self, n_parts: int, replicas: int,
                 route_timeout_s: float = 15.0):
        self.n_parts = int(n_parts)
        self.replicas = int(replicas)
        self.route_timeout_s = route_timeout_s
        self._lock = threading.Lock()
        self._backends: dict = {}   # guarded-by: self._lock
        self._clients: dict = {}    # guarded-by: self._lock
        self._rr: dict = {}         # guarded-by: self._lock
        self._crr: dict = {}        # guarded-by: self._lock
        self._hedge_free: dict = {}  # guarded-by: self._lock

    def register(self, part: int, replica: int, addr: str, port: int) -> str:
        part, replica = int(part), int(replica)
        if not 0 <= part < self.n_parts:
            raise ValueError(f"part {part} out of range [0, {self.n_parts})")
        if not 0 <= replica < self.replicas:
            raise ValueError(f"replica {replica} out of range "
                             f"[0, {self.replicas})")
        bid = f"p{part}.r{replica}"
        with self._lock:
            old = self._clients.pop((part, replica), [])
            old += self._hedge_free.pop((part, replica), [])
            self._backends[(part, replica)] = {
                "addr": addr, "port": int(port), "id": bid}
        for c in old:
            c.close()       # re-registration (backend restart) wins
        return bid

    def evict(self, part: int, replica: int):
        with self._lock:
            self._backends.pop((part, replica), None)
            old = self._clients.pop((part, replica), [])
            old += self._hedge_free.pop((part, replica), [])
        for c in old:
            c.close()

    def missing_parts(self) -> list[int]:
        with self._lock:
            covered = {p for p, _ in self._backends}
        return [p for p in range(self.n_parts) if p not in covered]

    def replicas_of(self, part: int) -> list[int]:
        with self._lock:
            return sorted(r for p, r in self._backends if p == int(part))

    def endpoint(self, part: int, replica: int) -> Optional[dict]:
        with self._lock:
            be = self._backends.get((int(part), int(replica)))
            return dict(be) if be else None

    def client(self, part: int, replica: int
               ) -> Optional[coord_mod.LineJsonClient]:
        """A pooled read connection to one backend (idempotent ops only):
        grown lazily up to POOL, then round-robined — concurrent routed
        reads must not queue behind one another's round trip."""
        key = (int(part), int(replica))
        with self._lock:
            be = self._backends.get(key)
            if be is None:
                return None
            pool = self._clients.setdefault(key, [])
            if len(pool) < self.POOL:
                c = coord_mod.LineJsonClient(be["addr"], be["port"],
                                             timeout_s=self.route_timeout_s,
                                             what=f"backend {be['id']}")
                pool.append(c)
                return c
            i = self._crr.get(key, 0)
            self._crr[key] = i + 1
            return pool[i % len(pool)]

    def pick(self, part: int) -> Optional[int]:
        """Round-robin replica choice for a read on `part`."""
        part = int(part)
        with self._lock:
            live = sorted(r for p, r in self._backends if p == part)
            if not live:
                return None
            i = self._rr.get(part, 0)
            self._rr[part] = i + 1
        return live[i % len(live)]

    def entries(self) -> list[tuple[int, int, dict]]:
        """Snapshot of every registered backend as (part, replica, be) —
        the health prober's iteration set."""
        with self._lock:
            return [(p, r, dict(be))
                    for (p, r), be in sorted(self._backends.items())]

    # -- hedged-read clients: exclusive checkout, never shared --
    #
    # The shared pool above round-robins client objects across concurrent
    # requests, so cancel() on a pooled client could tear a socket some
    # OTHER request is using. Hedge losers are cancelled by design, so
    # hedged reads check out a dedicated client (reused when returned
    # intact, discarded when cancelled/errored).

    def checkout(self, part: int, replica: int
                 ) -> Optional[coord_mod.LineJsonClient]:
        key = (int(part), int(replica))
        with self._lock:
            be = self._backends.get(key)
            if be is None:
                return None
            free = self._hedge_free.setdefault(key, [])
            if free:
                return free.pop()
        return coord_mod.LineJsonClient(be["addr"], be["port"],
                                        timeout_s=self.route_timeout_s,
                                        what=f"backend {be['id']}")

    def checkin(self, part: int, replica: int,
                client: coord_mod.LineJsonClient) -> None:
        key = (int(part), int(replica))
        with self._lock:
            if key in self._backends \
                    and len(self._hedge_free.get(key, ())) < self.POOL:
                self._hedge_free.setdefault(key, []).append(client)
                return
        client.close()

    def snapshot(self) -> dict:
        with self._lock:
            out: dict = {str(p): [] for p in range(self.n_parts)}
            for (p, r), be in sorted(self._backends.items()):
                out[str(p)].append({"replica": r, "addr": be["addr"],
                                    "port": be["port"], "id": be["id"]})
        return out

    def close(self):
        with self._lock:
            clients = [c for pool in self._clients.values() for c in pool]
            clients += [c for pool in self._hedge_free.values() for c in pool]
            self._clients.clear()
            self._hedge_free.clear()
        for c in clients:
            c.close()


# ----------------------------------------------------------------------------
# the router core: ownership routing + three-phase delta fan-out
# ----------------------------------------------------------------------------

class RouterCore:
    """Protocol-level router over a Fleet (the TCP layer below is a thin
    dispatcher; tests drive this directly). Thread-safe: counters under
    self._lock, delta fan-out serialized under self._delta_lock."""

    def __init__(self, owner: np.ndarray, n_parts: int, replicas: int = 1,
                 hops: int = 2, log=print,
                 obs: Optional[obs_mod.Obs] = None,
                 route_timeout_s: float = 15.0,
                 delta_timeout_s: float = 60.0,
                 health: Optional[HealthPolicy] = None,
                 degraded: str = "off", hedge: bool = False,
                 wal_cap: int = 256):
        self.owner = np.asarray(owner, dtype=np.int32)
        self.n_nodes = int(self.owner.shape[0])
        self.hops = int(hops)
        self.log = log
        self.obs = obs
        self.route_timeout_s = route_timeout_s
        self.delta_timeout_s = delta_timeout_s
        self.fleet = Fleet(n_parts, replicas, route_timeout_s=route_timeout_s)
        self.registry = obs.registry if obs is not None else obs_mod.Registry()
        # router-side route-latency histograms, same key names the backends
        # use so `stats` answers serve_bench's existing server-vs-client
        # cross-check unchanged
        self._lat = {t: self.registry.histogram(f"serve/latency_ms/{t}")
                     for t in ("A", "B")}
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self.stats = {"requests": 0, "tier_a": 0, "tier_b": 0, "deltas": 0,
                      "fanout_rpcs": 0, "evictions": 0,
                      # self-healing counters (inert in legacy mode)
                      "requests_ok": 0, "requests_degraded": 0,
                      "requests_failed": 0, "failovers": 0, "hedges": 0,
                      "wal_queued": 0, "wal_replayed": 0, "recoveries": 0}
        self._delta_lock = threading.Lock()
        # -- self-healing state (all inert when health is None: the PR-16
        # evict-on-error protocol is the health=None code path, untouched) --
        self.health_policy = health
        self.degraded = degraded
        self.hedge = bool(hedge) and health is not None
        self.wal = DeltaWAL(wal_cap, replicas)
        # the WAL only queues when the operator opted into degraded mode —
        # with it off, a down part refuses writes exactly like PR 16
        self._wal_active = degraded != "off" and health is not None
        self._health: dict = {}         # (part, replica) -> HealthState;
                                        # guarded-by: self._lock
        self._incarnations: dict = {}   # (part, replica) -> token
        self._retired: set = set()      # superseded incarnation tokens
        self._read_rr: dict = {}        # per-part up-replica round-robin
        self._failover_lat = self.registry.histogram("serve/failover_ms")
        self._recovery_s: list[float] = []  # outage wall clocks (admits)
        self._probe_halt = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None

    # -- readiness --

    def ready(self) -> list[int]:
        """[] when every part has at least one live backend; else the
        missing part ids."""
        return self.fleet.missing_parts()

    def _require_ready(self):
        missing = self.ready()
        if missing:
            raise RouteError(f"fleet not ready: no backend registered for "
                             f"part(s) {missing}")

    def _owner_of(self, node: int) -> int:
        node = int(node)
        if not 0 <= node < self.n_nodes:
            raise ValueError(f"node {node} out of range [0, {self.n_nodes})")
        return int(self.owner[node])

    # -- health bookkeeping (health_policy=None keeps every path inert) --

    def _state_of(self, part: int, replica: int) -> Optional[HealthState]:
        with self._lock:
            return self._health.get((int(part), int(replica)))

    def _emit_health(self, part: int, replica: int, state: str, **kw):
        self.log(f"[router] backend p{part}.r{replica} -> {state}"
                 + (f" ({kw.get('why')})" if kw.get("why") else ""))
        if self.obs is not None:
            self.obs.emit("serve_health", part=int(part),
                          replica=int(replica), state=state, **kw)

    def _note_fail(self, part: int, replica: int, why: str):
        hs = self._state_of(part, replica)
        if hs is None:
            return
        with self._lock:
            trans = hs.on_fail(time.monotonic())
        if trans is not None:
            self._emit_health(part, replica, trans, why=why)

    def _note_ok(self, part: int, replica: int) -> Optional[str]:
        hs = self._state_of(part, replica)
        if hs is None:
            return None
        with self._lock:
            trans = hs.on_ok(time.monotonic())
        if trans == "up":
            self._emit_health(part, replica, "up", why="probe streak")
        return trans

    def _candidates(self, part: int) -> list[int]:
        """Replicas to try for a read, in preference order: `up` replicas
        round-robined first, then `suspect` as a last resort. `down` and
        quarantined backends are skipped entirely — that is what keeps a
        single dead backend from costing every request a timeout."""
        part = int(part)
        regs = self.fleet.replicas_of(part)
        ups, suspects = [], []
        with self._lock:
            for r in regs:
                hs = self._health.get((part, r))
                if hs is None or hs.state == "up":
                    ups.append(r)
                elif hs.state == "suspect":
                    suspects.append(r)
            i = self._read_rr.get(part, 0)
            self._read_rr[part] = i + 1
        ups = ups[i % len(ups):] + ups[:i % len(ups)] if ups else []
        return ups + suspects

    # -- reads: round-robined, evict-on-timeout, pooled connections --

    def _forward_read(self, part: int, req: dict) -> tuple[dict, int]:
        """(response, replica) from the first live replica of `part`; a
        replica missing its deadline is evicted and the next one tried —
        no live replica left raises a named RouteError, never a hang."""
        if self.health_policy is not None:
            return self._forward_read_health(part, req)
        tried: list[str] = []
        for _ in range(max(self.fleet.replicas, 1)):
            replica = self.fleet.pick(part)
            if replica is None:
                break
            client = self.fleet.client(part, replica)
            if client is None:
                continue
            try:
                resp = client.request(req)
            except coord_mod.CoordTimeout as ex:
                tried.append(f"r{replica} ({ex})")
                self.fleet.evict(part, replica)
                with self._lock:
                    self.stats["evictions"] += 1
                self.log(f"[router] evicted backend p{part}.r{replica}: {ex}")
                continue
            return resp, replica
        raise RouteError(
            f"part {part}: no live backend within {self.route_timeout_s}s "
            f"deadline (tried: {', '.join(tried) or 'none registered'})")

    def _forward_read_health(self, part: int, req: dict) -> tuple[dict, int]:
        """Health-aware twin of `_forward_read`: failures mark the replica
        (up -> suspect -> down, breaker past that) instead of evicting it,
        and the request fails over to the next candidate. A read answered
        by a non-primary candidate is a failover (counted, latency
        histogrammed, obs 'failover' event)."""
        t0 = time.perf_counter()
        tried: list[str] = []
        cands = self._candidates(part)
        for i, replica in enumerate(cands):
            client = self.fleet.client(part, replica)
            if client is None:
                continue
            try:
                resp = client.request(req)
            except coord_mod.CoordCancelled:
                raise
            except coord_mod.CoordTimeout as ex:
                tried.append(f"r{replica} ({ex})")
                self._note_fail(part, replica, f"read {req.get('op')!r}")
                continue
            self._note_ok(part, replica)
            if i > 0:
                ms = (time.perf_counter() - t0) * 1e3
                with self._lock:
                    self.stats["failovers"] += 1
                self._failover_lat.observe(ms)
                if self.obs is not None:
                    self.obs.emit("failover", what="read", part=int(part),
                                  to_replica=int(replica), attempts=i + 1,
                                  ms=ms)
            return resp, replica
        raise RouteError(
            f"part {part}: no live backend within {self.route_timeout_s}s "
            f"deadline (tried: {', '.join(tried) or 'none up'})")

    # -- hedged tier-A reads: second replica after a p99-derived delay --

    def _hedge_delay_s(self) -> float:
        p99 = self._lat["A"].snapshot()["p99"] or 0.0
        return max(float(p99), self.health_policy.hedge_floor_ms) / 1e3

    def _hedged_read(self, part: int, req: dict) -> tuple[dict, int]:
        """Fire the primary; if no answer within the hedge delay, fire the
        next up replica. First answer wins, the loser's in-flight request
        is cancelled (dedicated checked-out clients — never the shared
        pool, so a cancel cannot tear another request's socket)."""
        cands = self._candidates(part)
        if len(cands) < 2:
            return self._forward_read(part, req)
        done = threading.Event()
        state = {"resp": None, "replica": None, "fails": 0, "fired": 0}
        lock = threading.Lock()
        clients: dict[int, coord_mod.LineJsonClient] = {}

        def fire(replica: int):
            client = self.fleet.checkout(part, replica)
            if client is None:
                with lock:
                    state["fails"] += 1
                    if state["fails"] >= state["fired"]:
                        done.set()
                return
            with lock:
                clients[replica] = client
            try:
                resp = client.request(req)
            except coord_mod.CoordCancelled:
                client.close()          # cancelled loser: discard
                return
            except coord_mod.CoordTimeout as ex:
                client.close()
                self._note_fail(part, replica, f"hedged read ({ex})")
                with lock:
                    state["fails"] += 1
                    if state["fails"] >= state["fired"] \
                            and state["resp"] is None:
                        done.set()
                return
            self._note_ok(part, replica)
            losers = []
            with lock:
                if state["resp"] is None:
                    state["resp"], state["replica"] = resp, replica
                    losers = [c for r, c in clients.items() if r != replica]
                    self.fleet.checkin(part, replica, client)
                else:
                    losers = [client]   # raced a winner: we are the loser
                done.set()
            for c in losers:
                c.cancel()

        with lock:
            state["fired"] = 1
        threading.Thread(target=fire, args=(cands[0],), daemon=True).start()
        if not done.wait(self._hedge_delay_s()):
            with self._lock:
                self.stats["hedges"] += 1
            with lock:
                state["fired"] += 1
            threading.Thread(target=fire, args=(cands[1],),
                             daemon=True).start()
        done.wait(self.route_timeout_s + 1.0)
        with lock:
            resp, replica = state["resp"], state["replica"]
        if resp is None:
            # both attempts died: fall back to the sequential path for the
            # named RouteError (or a late-recovering replica)
            return self._forward_read(part, req)
        return resp, replica

    # -- graceful degradation: partial answers instead of request failure --

    def _degraded_rows(self, nodes, part: int, err: str) -> list[dict]:
        """Per-node answers for an unreachable part: `stale-ok` first tries
        a possibly-stale tier-A batch from any still-registered replica
        (whatever its health state — a suspect or warming backend's table
        is stale at worst, and the rows are tagged); otherwise (and in
        `partial`) each row is status:'unavailable'. Either way the
        request as a whole succeeds — that is the degradation contract."""
        nodes = [int(n) for n in nodes]
        part = int(part)
        if self.degraded == "stale-ok":
            budget = max(self.health_policy.probe_timeout_s
                         if self.health_policy else 1.0, 0.25)
            for p, r, be in self.fleet.entries():
                if p != part:
                    continue
                try:
                    resp = coord_mod.rpc_line_json(
                        be["addr"], be["port"],
                        {"op": "predict_many", "nodes": nodes, "tier": "A"},
                        time.monotonic() + budget,
                        what=f"backend {be['id']} (stale-ok)")
                except coord_mod.CoordTimeout:
                    continue
                if resp.get("ok"):
                    rows = resp["results"]
                    for row in rows:
                        row["status"] = "stale"
                        row["part"] = part
                        row["backend"] = be["id"]
                    if self.obs is not None:
                        self.obs.emit("failover", what="stale_read",
                                      part=part, nodes=len(rows),
                                      backend=be["id"])
                    return rows
        return [{"ok": True, "node": n, "status": "unavailable",
                 "part": part, "err": err} for n in nodes]

    def predict(self, node: int, tier: Optional[str] = None) -> dict:
        self._require_ready()
        t0 = time.perf_counter()
        part = self._owner_of(node)
        req = {"op": "predict", "node": int(node)}
        if tier is not None:
            req["tier"] = tier
        try:
            if self.hedge and tier != "B":
                resp, replica = self._hedged_read(part, req)
            else:
                resp, replica = self._forward_read(part, req)
        except RouteError as ex:
            if self.degraded == "off":
                with self._lock:
                    self.stats["requests_failed"] += 1
                raise
            row = self._degraded_rows([node], part, str(ex))[0]
            with self._lock:
                self.stats["requests"] += 1
                self.stats["requests_degraded"] += 1
            return row
        with self._lock:
            self.stats["requests"] += 1
            self.stats["fanout_rpcs"] += 1
            self.stats["requests_ok"] += 1
            if resp.get("tier") == "B":
                self.stats["tier_b"] += 1
            elif resp.get("tier") == "A":
                self.stats["tier_a"] += 1
        # client-side shard tags: serve_bench splits its percentiles by
        # these without a second round trip
        resp["part"] = part
        resp["backend"] = f"p{part}.r{replica}"
        if self.degraded != "off":
            resp.setdefault("status", "ok")
        if resp.get("tier") in ("A", "B"):
            self._lat[resp["tier"]].observe((time.perf_counter() - t0) * 1e3)
        return resp

    def predict_many(self, nodes, tier: Optional[str] = None) -> list[dict]:
        """Split by owning part, forward each shard's slice concurrently,
        merge back in request order (each result carries its shard tags)."""
        self._require_ready()
        nodes = [int(n) for n in nodes]
        by_part: dict[int, list[int]] = {}
        for n in nodes:
            by_part.setdefault(self._owner_of(n), []).append(n)
        results: dict[int, dict] = {}
        errors: list[str] = []
        res_lock = threading.Lock()

        degraded_n = [0]

        def _one(part: int, shard: list[int]):
            req = {"op": "predict_many", "nodes": shard}
            if tier is not None:
                req["tier"] = tier
            try:
                resp, replica = self._forward_read(part, req)
            except (RouteError, ValueError) as ex:
                if self.degraded == "off" or not isinstance(ex, RouteError):
                    with res_lock:
                        errors.append(str(ex))
                    return
                rows = self._degraded_rows(shard, part, str(ex))
                with res_lock:
                    degraded_n[0] += len(rows)
                    for r in rows:
                        results[int(r["node"])] = r
                return
            if not resp.get("ok"):
                with res_lock:
                    errors.append(f"part {part}: {resp.get('err')}")
                return
            with res_lock:
                for r in resp["results"]:
                    r["part"] = part
                    r["backend"] = f"p{part}.r{replica}"
                    if self.degraded != "off":
                        r.setdefault("status", "ok")
                    results[int(r["node"])] = r

        threads = [threading.Thread(target=_one, args=(p, shard))
                   for p, shard in sorted(by_part.items())]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            with self._lock:
                self.stats["requests_failed"] += len(nodes)
            raise RouteError("; ".join(errors))
        with self._lock:
            self.stats["requests"] += len(nodes)
            self.stats["fanout_rpcs"] += len(by_part)
            self.stats["requests_degraded"] += degraded_n[0]
            self.stats["requests_ok"] += len(nodes) - degraded_n[0]
            for n in nodes:
                tr = results[n].get("tier")
                if tr == "B":
                    self.stats["tier_b"] += 1
                elif tr == "A":
                    self.stats["tier_a"] += 1
        return [results[n] for n in nodes]

    # -- writes: three-phase fan-out under the delta lock --

    def _send_write(self, part: int, replica: int, req: dict,
                    timeout_s: Optional[float] = None) -> Optional[dict]:
        """At-most-once write to ONE backend (rpc_line_json fresh
        connection, retry_sent=False — a delta must never apply twice).
        Returns None on failure (and evicts when health tracking is off)."""
        return self._send_write2(part, replica, req, timeout_s)[0]

    def _send_write2(self, part: int, replica: int, req: dict,
                     timeout_s: Optional[float] = None
                     ) -> tuple[Optional[dict], bool]:
        """`_send_write` plus a delivered-maybe bit: True when the request
        reached the wire (ok response, or timeout AFTER send). A sent-but-
        unanswered write is delivered-unknown — it must count as taken and
        never be re-sent (at-most-once); under-delivery is caught later by
        the rejoin warm-up spot-check."""
        be = self.fleet.endpoint(part, replica)
        if be is None:
            return None, False
        try:
            resp = coord_mod.rpc_line_json(
                be["addr"], be["port"], req,
                time.monotonic() + (timeout_s or self.delta_timeout_s),
                what=f"backend {be['id']}", retry_sent=False)
        except coord_mod.CoordTimeout as ex:
            sent = bool(getattr(ex, "request_sent", False))
            if self.health_policy is not None:
                self._note_fail(part, replica, f"write {req.get('op')!r}")
            else:
                self.fleet.evict(part, replica)
                with self._lock:
                    self.stats["evictions"] += 1
                self.log(f"[router] evicted backend p{part}.r{replica} "
                         f"mid-write: {ex}")
            return None, sent
        with self._lock:
            self.stats["fanout_rpcs"] += 1
        return resp, True

    def _fan_part_write(self, part: int, req: dict) -> list[dict]:
        """The same write to EVERY live replica of `part` (replica state
        must stay identical); returns the ok responses."""
        return self._fan_part_write_taken(part, req)[0]

    def _fan_part_write_taken(self, part: int,
                              req: dict) -> tuple[list[dict], set[int]]:
        """`_fan_part_write` plus the set of replica slots that took (or
        may have taken — delivered-unknown) the write, for WAL cursors."""
        out: list[dict] = []
        taken: set[int] = set()
        for replica in self.fleet.replicas_of(part):
            if self.health_policy is not None:
                hs = self._state_of(part, replica)
                if hs is not None and hs.state in ("down", "quarantined"):
                    # known-dead: don't stall the whole delta fan-out on
                    # its connect-retry deadline — the WAL queues for it
                    continue
            resp, maybe = self._send_write2(part, replica, req)
            if resp is not None and resp.get("ok"):
                out.append(resp)
                taken.add(replica)
            elif maybe:
                taken.add(replica)  # delivered-unknown: never re-send
        return out, taken

    def _wal_record(self, part: int, op: dict, taken: set) -> bool:
        """Queue `op` for the replica slots of `part` that missed it.
        Slots that have never registered count as taken — a first-time
        replica builds from artifacts + its own journal, not the WAL.
        Raises RouteError when the per-part WAL is full (bounded memory:
        at that point the part must rejoin or the write is refused)."""
        if not self._wal_active:
            return False
        regs = set(self.fleet.replicas_of(part))
        taken = set(taken) | {r for r in range(self.fleet.replicas)
                              if r not in regs}
        seq = self.wal.record(int(part), op, taken)
        if seq is None:
            return False
        with self._lock:
            self.stats["wal_queued"] += 1
        self.log(f"[router] WAL p{part} seq {seq}: queued {op.get('op')!r} "
                 f"for replica(s) missing it (depth "
                 f"{self.wal.depth(int(part))})")
        if self.obs is not None:
            self.obs.emit("failover", what="wal_queue", part=int(part),
                          op=op.get("op"), seq=int(seq),
                          depth=self.wal.depth(int(part)))
        return True

    def _invalidate_all(self, nodes: list[int]):
        """Phase 2: every backend drops the touched nodes from its halo
        cache — a cached boundary row is valid exactly until its owner
        changes it."""
        req = {"op": "invalidate", "nodes": [int(v) for v in nodes]}
        for part in range(self.fleet.n_parts):
            self._fan_part_write(part, req)

    def _mark_bfs(self, seeds: dict[int, int]) -> int:
        """Phase 3: distributed dirty-mark BFS. Each wave sends every
        pending (node, hops_left) to the owning part (ALL replicas — their
        dirty sets must agree; the frontier is taken from the first ok
        response since replica graphs are identical); the router dedups
        globally on best remaining budget, so no node is ever re-walked
        with a smaller budget than it already got."""
        best: dict[int, int] = {}
        work = {int(v): int(h) for v, h in seeds.items()}
        best.update(work)
        marked = 0
        while work:
            by_part: dict[int, list] = {}
            for v, h in work.items():
                by_part.setdefault(self._owner_of(v), []).append([v, h])
            work = {}
            for part, batch in sorted(by_part.items()):
                req = {"op": "mark", "nodes": sorted(batch)}
                resps, taken = self._fan_part_write_taken(part, req)
                self._wal_record(part, req, taken)
                if not resps:
                    if self._wal_active:
                        # whole part down: the mark is queued; its frontier
                        # resumes when the rejoiner replays it (the replay
                        # path feeds the answered frontier back into BFS)
                        continue
                    raise RouteError(
                        f"part {part}: no live backend took the dirty-mark "
                        f"fan-out — delta partially applied, retry after "
                        f"the part re-registers")
                marked += int(resps[0].get("marked", 0))
                for v, h in resps[0].get("frontier", []):
                    v, h = int(v), int(h)
                    if best.get(v, -1) >= h:
                        continue
                    best[v] = h
                    work[v] = h
        return marked

    def _dirty_total(self) -> int:
        total = 0
        for part in range(self.fleet.n_parts):
            try:
                resp, _ = self._forward_read(part, {"op": "dirty"})
            except RouteError:
                continue
            total += int(resp.get("count", 0))
        return total

    def add_edges(self, edges: list) -> dict:
        self._require_ready()
        pairs = [(int(u), int(v)) for u, v in edges]
        for u, v in pairs:
            self._owner_of(u), self._owner_of(v)      # range check up front
        with self._delta_lock:
            # phase 1: the owning parts append the halves they own
            by_part: dict[int, list] = {}
            for u, v in pairs:
                by_part.setdefault(self._owner_of(u), []).append([u, v])
                pv = self._owner_of(v)
                if pv != self._owner_of(u):
                    by_part.setdefault(pv, []).append([u, v])
            for part, batch in sorted(by_part.items()):
                req = {"op": "apply_delta", "edges": batch}
                resps, taken = self._fan_part_write_taken(part, req)
                self._wal_record(part, req, taken)
                if not resps and not self._wal_active:
                    raise RouteError(
                        f"part {part}: no live backend took the delta — "
                        f"nothing applied there; retry after it re-registers")
            touched = sorted({n for uv in pairs for n in uv})
            self._invalidate_all(touched)
            marked = self._mark_bfs({n: self.hops for n in touched})
            with self._lock:
                self.stats["deltas"] += 1
        out = {"ok": True, "dirty_new": marked,
               "dirty_total": self._dirty_total()}
        if self.obs is not None:
            self.obs.emit("delta", op="add_edges", edges=len(pairs),
                          dirty_new=out["dirty_new"],
                          dirty_total=out["dirty_total"], routed=True)
        return out

    def update_feat(self, node: int, vec) -> dict:
        self._require_ready()
        node = int(node)
        part = self._owner_of(node)
        with self._delta_lock:
            req = {"op": "apply_feat", "node": node, "feat": list(vec)}
            resps, taken = self._fan_part_write_taken(part, req)
            self._wal_record(part, req, taken)
            if not resps and not self._wal_active:
                raise RouteError(
                    f"part {part}: no live backend took the feature "
                    f"update — nothing applied; retry after it re-registers")
            self._invalidate_all([node])
            marked = self._mark_bfs({node: self.hops})
            with self._lock:
                self.stats["deltas"] += 1
        out = {"ok": True, "dirty_new": marked,
               "dirty_total": self._dirty_total()}
        if self.obs is not None:
            self.obs.emit("delta", op="update_feat", node=node,
                          dirty_new=out["dirty_new"],
                          dirty_total=out["dirty_total"], routed=True)
        return out

    # -- rejoin: incarnation tokens, WAL replay, warm-up, probes --

    def register_backend(self, part: int, replica: int, addr: str,
                         port: int, incarnation: Optional[str] = None
                         ) -> dict:
        """Fleet registration, health-aware. A re-register of a slot the
        router has already seen is a rejoin: the new incarnation token
        retires the old one (a zombie of the previous process is refused),
        the backend starts `down`, replays the WAL tail it missed, and is
        promoted only after the warm-up spot-check answers bitwise against
        an up peer. With health off this is exactly fleet.register."""
        part, replica = int(part), int(replica)
        key = (part, replica)
        if self.health_policy is not None and incarnation:
            with self._lock:
                if incarnation in self._retired:
                    raise RouteError(
                        f"backend p{part}.r{replica}: stale incarnation "
                        f"token {incarnation!r} refused — a newer "
                        f"incarnation of this slot registered after it")
        bid = self.fleet.register(part, replica, addr, port)
        if self.health_policy is None:
            return {"id": bid, "state": "up"}
        now = time.monotonic()
        with self._lock:
            prev_tok = self._incarnations.get(key)
            if incarnation:
                if prev_tok and prev_tok != incarnation:
                    self._retired.add(prev_tok)
                self._incarnations[key] = incarnation
            prev_hs = self._health.get(key)
            rejoin = prev_hs is not None
            hs = HealthState(self.health_policy, now,
                             state="down" if rejoin else "up")
            if rejoin:
                # keep the outage clock and the breaker history — a
                # crash-looping backend must not reset its flap count by
                # re-registering
                if prev_hs.down_since is not None:
                    hs.down_since = prev_hs.down_since
                hs.flaps = list(prev_hs.flaps)
                if prev_hs.state == "quarantined" and now < prev_hs.hold_until:
                    hs.state = "quarantined"
                    hs.hold_until = prev_hs.hold_until
            self._health[key] = hs
        if not rejoin:
            self._emit_health(part, replica, "up", why="registered")
        elif hs.state == "quarantined":
            self._emit_health(part, replica, "quarantined",
                              why="re-registered inside breaker hold")
        else:
            self._emit_health(part, replica, "down",
                              why="re-registered; replaying + warming up")
            # inline admission attempt: deterministic for orchestrators
            # that re-register and immediately expect service; the probe
            # loop retries if the warm-up fails here
            self._try_admit(part, replica)
        with self._lock:
            state = self._health[key].state
        return {"id": bid, "state": state}

    def _replay_wal(self, part: int, replica: int) -> int:
        """Drain this replica's WAL cursor in commit order, at-most-once
        each (a delivered-unknown entry is marked taken and NEVER re-sent;
        the warm-up spot-check catches under-delivery). Returns entries
        confirmed. Stops at the first failure — remaining entries wait
        for the next admission attempt."""
        if not self._wal_active:
            return 0
        n = 0
        with self._delta_lock:
            for seq, op in self.wal.pending_for(part, replica):
                resp, maybe = self._send_write2(part, replica, op)
                if resp is not None and resp.get("ok"):
                    self.wal.mark_taken(part, replica, [seq])
                    n += 1
                    if op.get("op") == "mark":
                        # resume the dirty BFS the outage cut short
                        fr = {int(v): int(h)
                              for v, h in resp.get("frontier", [])}
                        if fr:
                            self._mark_bfs(fr)
                elif maybe:
                    self.wal.mark_taken(part, replica, [seq])
                    break
                else:
                    break
        if n:
            with self._lock:
                self.stats["wal_replayed"] += n
            self.log(f"[router] WAL p{part}.r{replica}: replayed {n} "
                     f"queued delta op(s) on rejoin")
            if self.obs is not None:
                self.obs.emit("failover", what="wal_replay", part=part,
                              replica=int(replica), entries=n)
        return n

    def _spot_read(self, part: int, replica: int,
                   node: int) -> Optional[dict]:
        be = self.fleet.endpoint(part, replica)
        if be is None:
            return None
        try:
            resp = coord_mod.rpc_line_json(
                be["addr"], be["port"],
                {"op": "predict", "node": int(node), "tier": "A"},
                time.monotonic() + max(self.health_policy.probe_timeout_s,
                                       0.25),
                what=f"backend {be['id']} (warm-up)")
        except coord_mod.CoordTimeout:
            return None
        return resp if resp.get("ok") else None

    def _warmup_check(self, part: int, replica: int) -> bool:
        """Bitwise tier-A spot-check of the rejoiner against an up peer
        replica on a spread of owned nodes. Rows that are dirty (stale
        tag) on either side are skipped — a mid-refresh table row differs
        legitimately. No up peer -> trivially passes (nothing to compare;
        the rejoiner IS the part now)."""
        peers = []
        for r in self.fleet.replicas_of(part):
            if r == replica:
                continue
            hs = self._state_of(part, r)
            if hs is not None and hs.state == "up":
                peers.append(r)
        if not peers:
            return True
        own = np.flatnonzero(self.owner == part)
        if own.size == 0:
            return True
        k = min(max(self.health_policy.spotcheck, 1), int(own.size))
        idx = np.linspace(0, own.size - 1, num=k).astype(np.int64)
        for node in (int(own[i]) for i in idx):
            a = self._spot_read(part, replica, node)
            b = self._spot_read(part, peers[0], node)
            if a is None or b is None:
                return False
            if a.get("stale") or b.get("stale"):
                continue
            if a.get("scores") != b.get("scores"):
                self.log(f"[router] warm-up p{part}.r{replica}: node "
                         f"{node} differs from peer r{peers[0]} — "
                         f"admission refused")
                return False
        return True

    def _try_admit(self, part: int, replica: int) -> bool:
        """WAL-tail replay -> bitwise warm-up -> promote to up."""
        hs = self._state_of(part, replica)
        if hs is None:
            return False
        with self._lock:
            if hs.state == "quarantined" and \
                    time.monotonic() < hs.hold_until:
                return False
        self._replay_wal(part, replica)
        if self._wal_active and self.wal.pending_for(part, replica):
            with self._lock:
                hs.reject_warmup()
            return False                # replay incomplete: stay down
        if not self._warmup_check(part, replica):
            with self._lock:
                hs.reject_warmup()
            self._emit_health(part, replica, "down",
                              why="warm-up spot-check mismatch")
            return False
        with self._lock:
            outage = hs.admit(time.monotonic())
            self.stats["recoveries"] += 1
            self._recovery_s.append(outage)
        self._emit_health(part, replica, "up",
                          why=f"rejoined after {outage:.2f}s outage")
        if self.obs is not None:
            self.obs.emit("failover", what="rejoin", part=int(part),
                          replica=int(replica), outage_s=round(outage, 3))
        return True

    def start_probes(self):
        """Background liveness prober (no-op unless --serve-probe-s > 0)."""
        if self.health_policy is None or self.health_policy.probe_s <= 0:
            return
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="bnsgcn-router-prober",
            daemon=True)
        self._probe_thread.start()

    def _probe_loop(self):
        while not self._probe_halt.wait(self.health_policy.probe_s):
            try:
                self.probe_once()
            except Exception as ex:        # noqa: BLE001 - prober must
                self.log(f"[router] probe sweep error: {ex}")  # outlive any

    def probe_once(self):
        """One liveness sweep over every registered backend; a down
        backend that earns its ok-streak goes through the full admission
        gate (WAL replay + warm-up) right here."""
        pol = self.health_policy
        for part, replica, be in self.fleet.entries():
            r = coord_mod.probe_line_json(be["addr"], be["port"],
                                          timeout_s=pol.probe_timeout_s,
                                          what=f"backend {be['id']}")
            if r.get("ok"):
                if self._note_ok(part, replica) == "ready":
                    self._try_admit(part, replica)
            else:
                self._note_fail(part, replica,
                                f"probe ({r.get('err', '?')})")

    def health_snapshot(self) -> dict:
        with self._lock:
            return {f"p{p}.r{r}": hs.state
                    for (p, r), hs in sorted(self._health.items())}

    def fleet_snapshot(self) -> dict:
        """The fleet map peers resolve halo rows through. Health-aware:
        down/quarantined replicas are dropped from a part's entry list so
        a peer's next resolve lands on a live replica — unless EVERY
        replica of the part is down (then the raw list stays; the peer's
        error should name the dead backend, not 'no backend')."""
        snap = self.fleet.snapshot()
        if self.health_policy is None:
            return snap
        with self._lock:
            dead = {(p, r) for (p, r), hs in self._health.items()
                    if hs.state in ("down", "quarantined")}
        for p, entries in snap.items():
            live = [e for e in entries
                    if (int(p), int(e["replica"])) not in dead]
            if live:
                snap[p] = live
        return snap

    def availability(self) -> dict:
        """Operator-facing fleet availability summary (chaos bench +
        obs_report read this verbatim)."""
        with self._lock:
            ok = self.stats["requests_ok"]
            deg = self.stats["requests_degraded"]
            failed = self.stats["requests_failed"]
            failovers = self.stats["failovers"]
            hedges = self.stats["hedges"]
            rec = list(self._recovery_s)
        total = ok + deg + failed
        snap = self._failover_lat.snapshot()
        return {"requests_ok": ok, "requests_degraded": deg,
                "requests_failed": failed,
                "availability": round((ok + deg) / total, 6) if total
                else None,
                "failovers": failovers, "hedges": hedges,
                "failover_p99_ms": snap["p99"],
                "recoveries": len(rec),
                "recovery_s": round(max(rec), 3) if rec else None}

    # -- aggregation ops --

    def flush(self) -> int:
        """Drain every backend's dirty set (long deadline: a flush is a
        full re-score of the dirty frontier). Non-idempotent (expensive to
        double-start), so at-most-once per backend."""
        self._require_ready()
        total = 0
        for part in range(self.fleet.n_parts):
            for resp in self._fan_part_write(
                    part, {"op": "flush"}):
                total += int(resp.get("refreshed", 0))
        return total

    def snapshot_stats(self) -> dict:
        out: dict = {"ok": True, "n_nodes": self.n_nodes,
                     "parts": self.fleet.n_parts,
                     "router": True, "missing_parts": self.ready()}
        with self._lock:
            out.update(self.stats)
        if self.health_policy is not None:
            out["health"] = self.health_snapshot()
            out["wal_depth"] = self.wal.snapshot()
            out["availability"] = self.availability()
        out["dirty"] = self._dirty_total()
        backends = []
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                client = self.fleet.client(part, replica)
                if client is None:
                    continue
                try:
                    resp = client.request({"op": "stats"})
                except coord_mod.CoordTimeout:
                    continue
                if resp.get("ok"):
                    resp["backend"] = f"p{part}.r{replica}"
                    backends.append(resp)
        out["backends"] = backends
        # router-side route-latency percentiles under the SAME keys the
        # single-host server reports, so serve_bench's server-vs-client
        # p50 cross-check works against the router unchanged
        for t in ("A", "B"):
            snap = self._lat[t].snapshot()
            out[f"tier_{t.lower()}_p50_ms"] = snap["p50"]
            out[f"tier_{t.lower()}_p99_ms"] = snap["p99"]
        return out

    def metrics(self) -> dict:
        """Router registry + nested per-backend registry snapshots."""
        per_backend: dict = {}
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                client = self.fleet.client(part, replica)
                if client is None:
                    continue
                try:
                    resp = client.request({"op": "metrics"})
                except coord_mod.CoordTimeout:
                    continue
                if resp.get("ok"):
                    per_backend[f"p{part}.r{replica}"] = resp["metrics"]
        return {"ok": True, "metrics": self.registry.snapshot(),
                "backends": per_backend}

    def shutdown_fleet(self, log=None) -> int:
        """Forward shutdown to every backend (each drains, flushes its
        delta-log shard, and exits 0). Returns how many acknowledged."""
        n = 0
        for part in range(self.fleet.n_parts):
            for replica in self.fleet.replicas_of(part):
                resp = self._send_write(part, replica, {"op": "shutdown"},
                                        timeout_s=10.0)
                if resp is not None and resp.get("ok"):
                    n += 1
        return n

    def close(self):
        self._probe_halt.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=2.0)
        self.fleet.close()


# ----------------------------------------------------------------------------
# TCP front end
# ----------------------------------------------------------------------------

class RouterServer:
    """Line-JSON dispatcher over a RouterCore — same framing, drain
    discipline and in-flight accounting as serve.ServeServer."""

    # ops that stay answerable while draining, or before the fleet is
    # complete (registration must be possible before readiness, by
    # definition)
    ALWAYS = ("ping", "stats", "metrics", "fleet", "register", "health")

    def __init__(self, core: RouterCore, port: int, addr: str = "",
                 log=print):
        self.core = core
        self.log = log
        self._inflight = 0      # guarded-by: self._lock
        self._draining = False  # guarded-by: self._lock
        self._lock = threading.Lock()
        self.shutdown_requested = threading.Event()
        self.server = coord_mod.LineJsonServer(port, self._handle,
                                               addr=addr).start()

    @property
    def port(self) -> int:
        return self.server.port

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        with self._lock:
            if self._draining and op not in self.ALWAYS:
                return {"ok": False, "err": "draining"}
            self._inflight += 1
        try:
            return self._dispatch(op, req)
        except (KeyError, ValueError, TypeError) as ex:
            return {"ok": False, "err": f"{type(ex).__name__}: {ex}"}
        finally:
            with self._lock:
                self._inflight -= 1

    def _dispatch(self, op: Optional[str], req: dict) -> dict:
        core = self.core
        if op == "ping":
            return {"ok": True, "router": True}
        if op == "register":
            reg = core.register_backend(req["part"], req.get("replica", 0),
                                        req.get("addr") or "127.0.0.1",
                                        req["port"],
                                        incarnation=req.get("incarnation"))
            missing = core.ready()
            self.log(f"[router] registered backend {reg['id']} at "
                     f"{req.get('addr') or '127.0.0.1'}:{req['port']}"
                     + (f" (waiting on parts {missing})" if missing
                        else " (fleet complete)"))
            return {"ok": True, "id": reg["id"], "missing_parts": missing,
                    "state": reg["state"]}
        if op == "health":
            return {"ok": True, "health": core.health_snapshot(),
                    "wal_depth": core.wal.snapshot(),
                    "availability": core.availability()}
        if op == "fleet":
            return {"ok": True, "parts": core.fleet_snapshot(),
                    "missing_parts": core.ready()}
        if op == "predict":
            return core.predict(req["node"], tier=req.get("tier"))
        if op == "predict_many":
            return {"ok": True, "results": core.predict_many(
                req["nodes"], tier=req.get("tier"))}
        if op == "add_edges":
            return core.add_edges(req["edges"])
        if op == "update_feat":
            return core.update_feat(req["node"], req["feat"])
        if op == "flush":
            return {"ok": True, "refreshed": core.flush()}
        if op == "dirty":
            core._require_ready()
            return {"ok": True, "count": core._dirty_total()}
        if op == "stats":
            return core.snapshot_stats()
        if op == "metrics":
            return core.metrics()
        if op == "shutdown":
            self.shutdown_requested.set()
            return {"ok": True}
        return {"ok": False, "err": f"unknown op {op!r}"}

    def drain(self, timeout_s: float = 30.0, stop: bool = True):
        """Reject new client ops, wait out in-flight handlers; `stop=False`
        keeps the listener up (the shutdown sequence still answers
        ping/stats while the backends drain behind it)."""
        with self._lock:
            self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.01)
        if stop:
            self.server.stop()

    def stop(self):
        self.server.stop()


# ----------------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------------

def router_main(argv=None) -> int:
    """`python -m bnsgcn_tpu.main serve-router ...`.

    Exit codes: 0 clean fleet shutdown (client 'shutdown' op — forwarded to
    every backend), 75 graceful SIGTERM/SIGINT drain (backends keep
    running; the orchestrator owns their lifecycle), 2 config error."""
    from bnsgcn_tpu import resilience
    cfg = parse_config(argv)
    if not cfg.graph_name:
        cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    log = print
    obs = obs_mod.make_obs(cfg, rank=0, log=log)
    try:
        part_dir = artifacts_dir(cfg)
        owner = load_owner_map(part_dir)
        n_parts_art = int(owner.max()) + 1
        n_parts = cfg.parts if cfg.parts > 0 else n_parts_art
        if n_parts != n_parts_art:
            raise ConfigError(
                f"--parts {n_parts} != the {n_parts_art} parts in the "
                f"artifacts at {part_dir} — the shard map comes from the "
                f"training partition; re-partition or drop --parts")
        if cfg.part_replicas < 1:
            raise ConfigError(f"--part-replicas must be >= 1, got "
                              f"{cfg.part_replicas}")
        # L-hop budget for the distributed dirty-mark BFS: the model's
        # graph-layer count (ModelSpec.n_graph_layers = n_layers - n_linear,
        # computed flag-side so the router stays jax-free), same hop budget
        # as the single-host forward_closure
        hops = cfg.n_layers - cfg.n_linear
        if hops < 1:
            raise ConfigError(f"--n-layers {cfg.n_layers} with --n-linear "
                              f"{cfg.n_linear} leaves no graph layer")
    except ConfigError as ex:
        print(f"[config] {ex}", file=sys.stderr)
        sys.exit(2)

    # any self-healing knob flips on health tracking; all defaults off
    # keeps the PR-16 evict-on-error protocol bit-for-bit
    healing = (cfg.serve_probe_s > 0 or cfg.serve_degraded != "off"
               or cfg.serve_hedge == "on")
    core = RouterCore(owner, n_parts, replicas=cfg.part_replicas, hops=hops,
                      log=log, obs=obs,
                      health=HealthPolicy(cfg.serve_probe_s) if healing
                      else None,
                      degraded=cfg.serve_degraded,
                      hedge=cfg.serve_hedge == "on",
                      wal_cap=cfg.serve_wal_cap)
    core.start_probes()
    signals = resilience.PreemptSignals(
        action="drain in-flight routed requests",
        boundary="request boundary")
    signals.install()
    server = RouterServer(core, cfg.serve_port, cfg.serve_addr, log=log)
    log(f"[router] ready on port {server.port}: {n_parts} part(s) x "
        f"{cfg.part_replicas} replica(s), {core.n_nodes} nodes, "
        f"{hops}-hop dirty fan-out; waiting for backends to register")
    try:
        while signals.requested is None:
            if server.shutdown_requested.wait(0.05):
                break
    finally:
        clean = server.shutdown_requested.is_set()
        # drain ordering: stop taking client ops -> wait in-flight -> (on a
        # clean shutdown) forward shutdown so every backend flushes its
        # delta-log shard -> stop the listener
        server.drain(stop=False)
        acked = core.shutdown_fleet() if clean else 0
        server.stop()
        with core._lock:
            stats = dict(core.stats)
        log(f"[router] drained: {stats['requests']} request(s) routed "
            f"(A {stats['tier_a']} / B {stats['tier_b']}), "
            f"{stats['deltas']} delta(s) fanned out over "
            f"{stats['fanout_rpcs']} backend RPCs, "
            f"{stats['evictions']} eviction(s)"
            + (f", {acked} backend(s) shut down" if clean else ""))
        avail = core.availability() if healing else {}
        if healing and avail["availability"] is not None:
            log(f"[router] availability {avail['availability']:.4f} "
                f"(ok {avail['requests_ok']} / degraded "
                f"{avail['requests_degraded']} / failed "
                f"{avail['requests_failed']}), {avail['failovers']} "
                f"failover(s), {avail['recoveries']} recovery(ies)")
        if obs is not None:
            obs.emit("serve_fleet", parts=n_parts,
                     replicas=cfg.part_replicas, shutdown_acked=acked,
                     **{k: stats[k] for k in sorted(stats)},
                     **({"availability": avail["availability"],
                         "failover_p99_ms": avail["failover_p99_ms"],
                         "recovery_s": avail["recovery_s"]}
                        if healing else {}))
            obs.close()
        core.close()
        signals.restore()
    if signals.requested is not None:
        log(f"[router] {signals.requested} honored: backends keep serving; "
            f"relaunch the router to resume fronting them")
        sys.exit(resilience.EXIT_PREEMPTED)
    return 0


if __name__ == "__main__":
    sys.exit(router_main())
