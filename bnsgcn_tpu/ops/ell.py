"""Bucketed-ELLPACK sparse aggregation — the TPU-shaped SpMM.

`jax.ops.segment_sum` lowers to an XLA scatter-add, which serializes on TPU
(~120 GB/s effective on a v5e where HBM does ~800). This module reformulates
the same aggregation (reference DGL SpMM, module/layer.py:35-37,88-90) as
dense, scatter-free work:

  * offline (numpy, per part): group destination rows by in-degree into
    power-of-two buckets; within a bucket store src indices as a dense
    [rows, width] ELL table padded with a dummy index;
  * on device: per bucket, `h[idx]` (a batched row gather — fast on TPU) and
    a dense sum over the width axis; results land via one unique-index
    row permutation (a gather, not a scatter);
  * backward uses a second, transposed layout (rows = source nodes, grouped
    by out-degree) through `jax.custom_vjp`, so the gradient is the same
    scatter-free shape: d_h[u] = sum over out-edges of g[dst].

Bucket widths are powers of two, so ELL padding wastes < 2x gathers; rows
with degree 0 (structural padding) are skipped entirely.

Layouts stack across partition parts (shared bucket shapes = max over parts)
and ride through shard_map as ordinary sharded int arrays.
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


def build_workers(n_tasks: int, cap: int = 8) -> int:
    """Host-parallelism width for offline layout builds (ROADMAP open item:
    the hybrid build was ~980 s of single-threaded numpy at bench scale).
    The heavy kernels (sorts, bincounts, fancy indexing) run per part /
    per direction in a ThreadPoolExecutor — no pickling of the multi-GB
    inputs. BNSGCN_BUILD_WORKERS=1 restores strictly serial builds (or any
    explicit width caps the pool)."""
    env = os.environ.get("BNSGCN_BUILD_WORKERS")
    if env:
        return max(1, min(int(env), max(n_tasks, 1)))
    return max(1, min(cap, os.cpu_count() or 1, max(n_tasks, 1)))


def run_parallel(fns):
    """Run thunks via ThreadPoolExecutor (results in order); serial when the
    worker budget is 1 so BNSGCN_BUILD_WORKERS=1 gives bit-identical
    single-threaded behavior."""
    w = build_workers(len(fns))
    if w <= 1 or len(fns) <= 1:
        return [f() for f in fns]
    with ThreadPoolExecutor(max_workers=w) as ex:
        futs = [ex.submit(f) for f in fns]
        return [f.result() for f in futs]


ELL_SPLIT_CAP = 128   # rows with degree > cap are split into cap-wide chunks


def layout_fastpath() -> bool:
    """BNSGCN_LAYOUT_FASTPATH=0 pins the legacy np.unique/argsort layout
    passes. Both paths are bitwise-identical by construction; the toggle
    exists so tests can assert that and bisects can isolate the builders."""
    return os.environ.get("BNSGCN_LAYOUT_FASTPATH", "1") != "0"


def grouped_order(keys: np.ndarray, n_keys: int) -> np.ndarray:
    """Stable argsort of small-int `keys` — the layout builders' dominant
    pass (edges sorted by destination row). Fast path packs (key, index)
    into one int64 and runs numpy's SIMD quicksort: the packed keys are
    distinct, so the unstable sort reproduces the kind='stable' order
    exactly (~7x on 20M edges, numpy 2.0). Falls back to stable argsort
    when the packed key would overflow int64 or the fast path is off."""
    n = len(keys)
    bits = max(int(n - 1).bit_length(), 1)
    if n and layout_fastpath() and (int(n_keys) << bits) < 2**63:
        packed = (keys.astype(np.int64) << bits) \
            | np.arange(n, dtype=np.int64)
        packed.sort()
        return packed & ((1 << bits) - 1)
    return np.argsort(keys, kind="stable")


@dataclass(frozen=True)
class EllSpec:
    """Static bucket geometry (identical across parts)."""
    widths: tuple[int, ...]            # bucket ELL widths, ascending powers of 2
    rows: tuple[int, ...]              # padded row count per bucket
    n_rows: int                        # output rows (n_dst for fwd, n_src_ext for bwd)
    n_src: int                         # gatherable rows (n_src_ext for fwd, n_dst for bwd)
    n_split: int = 0                   # padded count of split (degree > cap) rows
    n_chunks: int = 0                  # padded count of their cap-wide chunks


def _bucketize(deg: np.ndarray, widths: Sequence[int]) -> np.ndarray:
    """bucket index per row; deg 0 -> -1 (skipped)."""
    b = np.full(deg.shape, -1, dtype=np.int32)
    lo = 0
    for k, w in enumerate(widths):
        b[(deg > lo) & (deg <= w)] = k
        lo = w
    return b


def build_ell_numpy(src: np.ndarray, dst: np.ndarray, n_rows: int, n_src: int,
                    widths: Sequence[int] | None = None,
                    row_pad: Sequence[int] | None = None,
                    cap: int | None = None,
                    split_pad: int = 0, chunk_pad: int = 0):
    """Build one part's ELL tables for `out[r] = sum_{e: dst_e == r} h[src_e]`.

    Padded edges must already point at dst == n_rows (they are dropped).
    Returns (widths, rows_per_bucket, idx_arrays, perm, chunk_pos, chunk_seg).

    Split-row scheme (`cap`): rows with degree > cap become ceil(deg/cap)
    cap-wide pseudo-rows appended to the cap bucket (cutting the power-law
    padding waste from ~1.5x to ~1.15x of E); their partial sums are combined
    by a tiny sorted segment-sum over `chunk_pos`/`chunk_seg`. Table layout:
    [bucket rows 0..T-1 ; combine results T..T+split_pad-1 ; zero row].
    `perm[r]` points a normal row at its bucket position, a split row at its
    combine slot, and a degree-0 row at the zero row.
    """
    if cap is not None and (cap < 4 or cap & (cap - 1)):
        raise ValueError(f"split cap must be a power of two >= 4, got {cap}")
    real = dst < n_rows
    src, dst = src[real], dst[real]
    deg = np.bincount(dst, minlength=n_rows)
    split_mask = (deg > cap) if cap else np.zeros(n_rows, dtype=bool)
    deg_b = np.where(split_mask, 0, deg)
    if widths is None:
        # ladder from the FULL degree distribution so it reaches cap whenever
        # any row splits (deg_b alone would stop short of cap)
        widths = _choose_widths(deg, cap=cap)
    if cap and split_mask.any() and widths[-1] != cap:
        raise ValueError(f"width ladder {widths} must end at cap={cap} "
                         f"when split rows exist")
    bucket = _bucketize(deg_b, widths)

    order = grouped_order(dst, n_rows)
    src_sorted = src[order]
    dst_sorted = dst[order]
    indptr = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(deg, out=indptr[1:])

    # split bookkeeping: pseudo-row base per split row, chunk segments
    split_rows = np.nonzero(split_mask)[0]
    n_split = len(split_rows)
    chunks_per = np.ceil(deg[split_rows] / cap).astype(np.int64) if n_split else         np.zeros(0, np.int64)
    n_pseudo = int(chunks_per.sum())
    assert n_split <= max(split_pad, 0) or split_pad == 0
    pseudo_base = np.zeros(n_rows, dtype=np.int64)
    if n_split:
        pseudo_base[split_rows] = np.concatenate([[0], np.cumsum(chunks_per)[:-1]])

    # fully vectorized fill: for each edge, its (bucket, row-within-bucket,
    # slot-within-row) — no per-row python loop (matters at 100M edges)
    rpos = np.zeros(n_rows, dtype=np.int64)
    within = np.arange(len(dst_sorted), dtype=np.int64) - indptr[dst_sorted]
    e_bucket = bucket[dst_sorted]
    e_split = split_mask[dst_sorted]

    rows_per_bucket = []
    perm = np.zeros(n_rows, dtype=np.int32)
    offset = 0
    cap_k = len(widths) - 1
    # bucket geometry in one cheap row-level pass, shared by both fill paths
    flat_base = np.zeros(len(widths) + 1, dtype=np.int64)
    cap_offset = cap_normal = 0
    for k, w in enumerate(widths):
        rows_k = np.nonzero(bucket == k)[0]
        n_k = len(rows_k)
        extra = n_pseudo if (cap and k == cap_k) else 0
        pad_rows = row_pad[k] if row_pad is not None else n_k + extra
        assert pad_rows >= n_k + extra
        rpos[rows_k] = np.arange(n_k)
        perm[rows_k] = offset + np.arange(n_k, dtype=np.int32)
        if cap and k == cap_k:
            cap_offset, cap_normal = offset, n_k
        rows_per_bucket.append(pad_rows)
        offset += pad_rows
        flat_base[k + 1] = flat_base[k] + pad_rows * w
    total = offset                                 # table rows T

    if layout_fastpath():
        # one flat table + one collision-free scatter for ALL buckets —
        # each edge owns a distinct (row, slot), so a single fancy-index
        # write replaces the per-bucket O(E x buckets) full-edge masks
        idx_flat = np.full(int(flat_base[-1]), n_src, dtype=np.int32)
        w_arr = np.asarray(widths, dtype=np.int64)
        ns = ~e_split
        eb = e_bucket[ns]
        idx_flat[flat_base[eb] + rpos[dst_sorted[ns]] * w_arr[eb]
                 + within[ns]] = src_sorted[ns]
        if n_pseudo:
            es = e_split
            pr = cap_normal + pseudo_base[dst_sorted[es]] + within[es] // cap
            idx_flat[flat_base[cap_k] + pr * w_arr[cap_k]
                     + within[es] % cap] = src_sorted[es]
        idx_arrays = [idx_flat[flat_base[k]:flat_base[k + 1]]
                      .reshape(rows_per_bucket[k], w)
                      for k, w in enumerate(widths)]
    else:
        idx_arrays = []
        for k, w in enumerate(widths):
            idx = np.full((rows_per_bucket[k] * w,), n_src, dtype=np.int32)
            sel = (e_bucket == k) & ~e_split
            idx[rpos[dst_sorted[sel]] * w + within[sel]] = src_sorted[sel]
            if cap and k == cap_k and n_pseudo:
                sel = e_split
                pr = (cap_normal + pseudo_base[dst_sorted[sel]]
                      + within[sel] // cap)
                idx[pr * w + within[sel] % cap] = src_sorted[sel]
            idx_arrays.append(idx.reshape(rows_per_bucket[k], w))

    sp = split_pad if split_pad else ((n_split + 7) // 8 * 8 if n_split else 0)
    cp = chunk_pad if chunk_pad else ((n_pseudo + 7) // 8 * 8 if n_pseudo else 0)
    # chunk_pos indexes the CAP BUCKET's rows (plus one appended zero row at
    # rows_per_bucket[-1]) — not the whole table — so the combine gathers from
    # the cap bucket output directly without re-materializing the table
    cap_rows = rows_per_bucket[-1] if rows_per_bucket else 0
    chunk_pos = np.full(cp, cap_rows, dtype=np.int32)   # pad -> appended zero row
    chunk_seg = np.full(cp, sp, dtype=np.int32)         # pad -> dropped segment
    # row_of[table_pos] = the output row this table row computes (split
    # pseudo-rows map to their split source; padding -> n_rows). Consumers
    # that need per-table-row context (GAT attention broadcasts el/z by row)
    # index with this.
    row_of = np.full(total, n_rows, dtype=np.int32)
    normal = (bucket >= 0)
    rws = np.nonzero(normal)[0]
    row_of[perm[rws]] = rws
    if n_split:
        chunk_pos[:n_pseudo] = cap_normal + np.arange(n_pseudo)
        chunk_seg[:n_pseudo] = np.repeat(np.arange(n_split), chunks_per)
        perm[split_rows] = total + np.arange(n_split, dtype=np.int32)
        row_of[cap_offset + cap_normal + np.arange(n_pseudo)] = \
            np.repeat(split_rows, chunks_per)
    perm[(bucket == -1) & ~split_mask] = total + sp     # zero row
    return (tuple(widths), tuple(rows_per_bucket), idx_arrays, perm,
            chunk_pos, chunk_seg, row_of)


def _choose_widths(deg: np.ndarray, cap: int | None = None) -> tuple[int, ...]:
    """Power-of-2 bucket-width ladder from 4 up to min(max degree, cap).

    (An edge-mass-quantile scheme was tried and measured *slower* on a v5e
    despite ~25% fewer padded gathers — wide low-row-count buckets hurt the
    gather/reduce pipeline more than padding does. Keep the ladder; the
    split-row cap handles the power-law tail instead.)
    """
    deg = deg[deg > 0]
    max_deg = int(deg.max()) if deg.size else 1
    if cap:
        max_deg = min(max_deg, cap)
    widths, w = [], 4
    while True:
        widths.append(w)
        if w >= max(max_deg, 1):
            break
        w *= 2
    return tuple(widths)


def _part_edges(src, dst, n_dst, direction):
    """Real edges of one part, oriented for the requested layout direction."""
    real = dst < n_dst
    if direction == "fwd":             # rows = dst, gather = src
        return src[real], dst[real]
    return dst[real], src[real]        # rows = src(ext), gather = dst


def compute_geometry(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                     n_src_ext: int, cap: int = ELL_SPLIT_CAP,
                     directions: tuple = ("fwd", "bwd")) -> dict:
    """Global ELL geometry (widths, padded rows, split/chunk pads) for both
    directions — a pure graph property needing the FULL set of parts.
    JSON-serializable so the offline partitioner can store it in meta.json,
    letting multi-host processes build their ELL tables from local parts
    alone (data/artifacts.py)."""
    P = src_all.shape[0]
    geo = {}
    for direction in directions:
        n_rows = n_dst if direction == "fwd" else n_src_ext
        degs = []
        for p in range(P):
            _, d = _part_edges(src_all[p], dst_all[p], n_dst, direction)
            degs.append(np.bincount(d, minlength=n_rows))
        all_deg = np.concatenate(degs)
        widths = _choose_widths(all_deg, cap=cap)
        eff_cap = cap if (cap and all_deg.max() > cap) else None
        rows_max = [0] * len(widths)
        split_max = chunk_max = 0
        for d in degs:
            split = (d > eff_cap) if eff_cap else np.zeros_like(d, dtype=bool)
            b = _bucketize(np.where(split, 0, d), widths)
            for k in range(len(widths)):
                rows_max[k] = max(rows_max[k], int(np.sum(b == k)))
            if eff_cap:
                split_max = max(split_max, int(split.sum()))
                chunk_max = max(chunk_max, int(np.ceil(d[split] / eff_cap).sum()))
        if eff_cap:
            rows_max[-1] += chunk_max          # pseudo-rows live in the cap bucket
        pad8 = lambda r: ((r + 7) // 8) * 8 if r else 0
        geo[direction] = {
            "widths": [int(w) for w in widths],
            "rows": [pad8(r) for r in rows_max],
            "split": pad8(split_max), "chunks": pad8(chunk_max),
            "cap": eff_cap,
        }
    return geo


def build_layouts(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                  n_src_ext: int, cap: int = ELL_SPLIT_CAP,
                  geometry: dict | None = None
                  ) -> tuple[EllSpec, EllSpec, dict]:
    """Build stacked fwd (rows = dst) and bwd (rows = src_ext) ELL layouts.

    src_all/dst_all: [P_local, E] artifact edge arrays — may be a subset of
    parts when `geometry` (from compute_geometry, possibly via meta.json)
    provides the global pads. Returns (fwd_spec, bwd_spec, arrays) with
    arrays = {'{dir}_idx_k', '{dir}_perm', '{dir}_chunk_pos',
    '{dir}_chunk_seg'} stacked on the leading local-part axis.
    """
    P = src_all.shape[0]
    if geometry is None:
        geometry = compute_geometry(src_all, dst_all, n_dst, n_src_ext, cap)

    def build_all(direction):
        n_rows = n_dst if direction == "fwd" else n_src_ext
        n_src = n_src_ext if direction == "fwd" else n_dst
        g = geometry[direction]
        widths = tuple(g["widths"])
        rows_max = tuple(g["rows"])
        split_max, chunk_max, eff_cap = g["split"], g["chunks"], g["cap"]

        def build_one(p):
            s, d = _part_edges(src_all[p], dst_all[p], n_dst, direction)
            _, _, idx, perm, cp, cs, _ = build_ell_numpy(
                s, d, n_rows, n_src, widths=widths, row_pad=rows_max,
                cap=eff_cap, split_pad=split_max, chunk_pad=chunk_max)
            return idx, perm, cp, cs

        results = run_parallel([partial(build_one, p) for p in range(P)])
        idx_stacked = [[r[0][k] for r in results] for k in range(len(widths))]
        perms = [r[1] for r in results]
        cpos = [r[2] for r in results]
        csegs = [r[3] for r in results]
        spec = EllSpec(widths=widths, rows=rows_max, n_rows=n_rows,
                       n_src=n_src, n_split=split_max, n_chunks=chunk_max)
        return (spec, [np.stack(x) for x in idx_stacked], np.stack(perms),
                np.stack(cpos), np.stack(csegs))

    (fwd_spec, fwd_idx, fwd_perm, fwd_cp, fwd_cs), \
        (bwd_spec, bwd_idx, bwd_perm, bwd_cp, bwd_cs) = run_parallel(
            [partial(build_all, "fwd"), partial(build_all, "bwd")])
    arrays = {"fwd_perm": fwd_perm, "bwd_perm": bwd_perm}
    if fwd_spec.n_split:
        arrays["fwd_chunk_pos"], arrays["fwd_chunk_seg"] = fwd_cp, fwd_cs
    if bwd_spec.n_split:
        arrays["bwd_chunk_pos"], arrays["bwd_chunk_seg"] = bwd_cp, bwd_cs
    for k in range(len(fwd_spec.widths)):
        arrays[f"fwd_idx_{k}"] = fwd_idx[k]
    for k in range(len(bwd_spec.widths)):
        arrays[f"bwd_idx_{k}"] = bwd_idx[k]
    return fwd_spec, bwd_spec, arrays


def build_split_layouts(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                        n_src_ext: int, cap: int = ELL_SPLIT_CAP):
    """Interior/frontier row-partitioned ELL layouts (--overlap split).

    Each part's destination rows are split by ops/spmm.frontier_mask and
    remapped to two compact row spaces (compact ids ascend with original
    id), so one layer's aggregation becomes

        interior_spmm(h)             # gathers ONLY owned rows — no halo dep
        frontier_spmm([h ; halo])    # rows that need the exchange
        out = concat(int_out, fro_out)[merge_perm]

    with `merge_perm` the recombination permutation back to original row
    order. Row-exact vs the fused layout: every output row's complete edge
    set lands on exactly one side (a frontier row's LOCAL in-edges aggregate
    on the frontier side with it). Degree-0/padded rows are interior.

    The interior pair gathers from the owned space (n_src = n_dst), so its
    backward emits d_h directly; the frontier pair gathers from the full
    extended space and its backward emits d_h_ext (the halo slice of which
    transposes through the backward exchange).

    Returns ((int_fwd, int_bwd), (fro_fwd, fro_bwd), arrays, n_int_pad,
    n_fro_pad); arrays = 'int_*'/'fro_*'-prefixed build_layouts tables plus
    'merge_perm' [P, n_dst] int32."""
    from bnsgcn_tpu.ops.spmm import split_row_partition
    _, merge_perm, (si, di, n_int_pad), (sf, df, n_fro_pad) = \
        split_row_partition(src_all, dst_all, n_dst)
    (int_f, int_b, int_arr), (fro_f, fro_b, fro_arr) = run_parallel([
        partial(build_layouts, si, di, n_int_pad, n_dst, cap=cap),
        partial(build_layouts, sf, df, n_fro_pad, n_src_ext, cap=cap)])
    arrays = {"merge_perm": merge_perm}
    arrays.update({f"int_{k}": v for k, v in int_arr.items()})
    arrays.update({f"fro_{k}": v for k, v in fro_arr.items()})
    return (int_f, int_b), (fro_f, fro_b), arrays, n_int_pad, n_fro_pad


def _bucket_sum(hp, idx, w, chunk_gathers: int = 4_000_000,
                use_pallas: bool = False, accum: str = "auto"):
    """sum over ELL width for one bucket.

    accum='unroll' (the TPU default for native-dtype rows): per-column
    accumulation `acc += hp[idx[:, j]]` in 16-column unrolled f32 chains,
    scanned over column blocks for w > 16 — no [rows, w, H] gathered
    intermediate is ever materialized, so the bucket runs near the gather
    unit's row rate instead of paying an extra HBM round-trip.
    v5e-measured on the bench cap bucket ([150k, 128] idx, H=256):
    block-scan 81.5 ms (16-col) / 79.4 ms (32-col) vs 154.4 ms for the
    chunked reduce — 1.9x; a fully-unrolled 128-chain also wins (90.5 ms)
    but blows the remote compiler up at full train-step scale, and pure
    fori/scan per column loses it all to carry re-traffic (145.7 ms).
    f32 chains also accumulate more precisely than the bf16 tree reduce.

    int8 rows unroll too: exact int32 chains (the int8->int32 convert is
    v5e-native), the caller's one per-call scale multiplies back after the
    combine — bit-identical to the reduce path's int32 sums at ~2x the
    row rate (256B rows move ~519M rows/s vs 268M at 512B).

    accum='reduce': the materialize-then-sum path, row-chunked so the
    gathered intermediate never exceeds ~chunk_gathers * H elements; it
    serves fp8 gathers (their convert must happen on the gathered block;
    e4m3 decode is VPU-emulated and loses anyway) and non-TPU backends
    (unrolled gathers lower poorly there).

    use_pallas no longer affects this function (round 5): the
    pallas_bucket_reduce dispatch was retired — superseded by the unroll,
    never hardware-validated; the kernel remains in tools/pallas_spmm as a
    study artifact. The parameter stays for signature stability with
    make_ell_spmm/make_block_spmm, whose use_pallas switches the fused
    dense-tile kernel (ops/pallas_block), which IS hardware-validated."""
    if accum not in ("auto", "unroll", "reduce"):
        raise ValueError(f"unknown accum mode {accum!r}")
    r = idx.shape[0]
    h_dim = hp.shape[1]
    if accum == "auto":
        # unroll beats BOTH the jnp chunked reduce and pallas_bucket_reduce
        # (which only fuses the reduction, not the gather materialization),
        # so use_pallas does not disable it — pass accum='reduce' explicitly
        # to study the materializing paths. int8 rows unroll too (exact
        # int32 chains, v5e-native converts); fp8 stays on reduce — e4m3
        # decode is emulated on the VPU and measured 1.8x slower than bf16.
        from bnsgcn_tpu.utils.platform import tpu_codepaths
        accum = ("unroll" if hp.dtype != jnp.float8_e4m3fn
                 and tpu_codepaths() else "reduce")
    BS = 16
    if accum == "unroll" and hp.dtype == jnp.float8_e4m3fn:
        raise ValueError("accum='unroll' supports native and int8 rows; "
                         "fp8 gathers take accum='reduce'")
    if (accum == "unroll" and r > 0 and w > 1
            and (w <= BS or w % BS == 0)):
        # int8 rows accumulate in int32 (exact, like the reduce path's
        # int32 sums — the caller's one per-call scale multiplies back
        # after the combine); native rows in f32 chains
        acc_dt = jnp.int32 if hp.dtype == jnp.int8 else jnp.float32
        out_dt = jnp.int32 if hp.dtype == jnp.int8 else hp.dtype

        def chain(cb, n):
            a = hp[cb[0]].astype(acc_dt)
            for j in range(1, n):
                a = a + hp[cb[j]].astype(acc_dt)
            return a

        if w <= BS:
            return chain(idx.T, w).astype(out_dt)
        cols = idx.T.reshape(w // BS, BS, r)
        # derive the init from the input so the carry has the same varying
        # manual axes as the body output under shard_map (same contract as
        # block_spmm._dense_apply's acc0); the empty slice reads no data
        acc0 = jnp.zeros((r, h_dim), acc_dt) \
            + jnp.sum(hp[:0]).astype(acc_dt)
        out, _ = jax.lax.scan(lambda acc, cb: (acc + chain(cb, BS), None),
                              acc0, cols)
        return out.astype(out_dt)
    rows_per_chunk = max(1, chunk_gathers // max(w, 1))
    # (round 5) pallas_bucket_reduce is no longer dispatched here: the
    # unrolled chains beat it end-to-end on the v5e (it fuses only the
    # reduction, not the gather materialization — its own docstring), its
    # hardware validation slot never materialized across two windows, and
    # keeping a non-winning TPU-only branch inside the accumulation
    # hot-path risks exactly the untested-on-hardware escapes the CPU
    # preflight exists to prevent. The kernel survives in tools/pallas_spmm
    # as a study artifact with its interpret-mode test.

    def reduce_tile(g):
        if g.dtype == jnp.float8_e4m3fn:
            # fp8 gather mode: rows travel at 1 byte/element through the
            # gather unit; the reduction must leave fp8 immediately
            return g.astype(jnp.float32).sum(axis=1)
        if g.dtype == jnp.int8:
            # int8 gather mode: same 1-byte wire, but the int8->int32
            # convert is v5e-native (fp8 decode is emulated and measured
            # 1.8x SLOWER than bf16 end to end); int32 sums of <=1024
            # rows of |q|<=127 are exact
            return g.astype(jnp.int32).sum(axis=1)
        return g.sum(axis=1)

    if r <= rows_per_chunk:
        return reduce_tile(hp[idx.reshape(-1)].reshape(r, w, h_dim))
    n_chunks = -(-r // rows_per_chunk)
    pad = n_chunks * rows_per_chunk - r
    idx_p = jnp.pad(idx, ((0, pad), (0, 0)), constant_values=hp.shape[0] - 1)
    idx_c = idx_p.reshape(n_chunks, rows_per_chunk, w)

    def body(_, ix):
        g = hp[ix.reshape(-1)].reshape(rows_per_chunk, w, h_dim)
        return None, reduce_tile(g)

    _, out = jax.lax.scan(body, None, idx_c)
    return out.reshape(n_chunks * rows_per_chunk, h_dim)[:r]


def ell_combine(spec: EllSpec, outs, perm, chunk_pos=None, chunk_seg=None):
    """Per-bucket outputs [R_k, ...] -> [n_rows, ...] via the split-row chunk
    combine (tiny sorted segment-sum) + one permutation gather. Shared by the
    SpMM and any other bucketed row computation (GAT attention backward)."""
    trailing = outs[0].shape[1:]
    zero = jnp.zeros((1,) + trailing, outs[0].dtype)
    if spec.n_split:
        # combine split-row chunks straight from the cap bucket's output
        # (chunk_pos is cap-bucket-relative; its pad points at the zero row)
        cap_z = jnp.concatenate([outs[-1], zero], axis=0)
        gathered = cap_z[chunk_pos]                    # [n_chunks, ...]
        comb = jax.ops.segment_sum(gathered, chunk_seg,
                                   num_segments=spec.n_split + 1,
                                   indices_are_sorted=True)[:spec.n_split]
        full = jnp.concatenate(list(outs) + [comb, zero], axis=0)
    else:
        full = jnp.concatenate(list(outs) + [zero], axis=0)
    return full[perm]


def _ell_apply(spec: EllSpec, idx_list, perm, h, use_pallas: bool = False,
               chunk_pos=None, chunk_seg=None, gather_dtype: str = "native",
               accum: str = "auto"):
    """Bucketed gather+sum (+ split-row combine), then one permutation gather.
    The only scatter is the tiny sorted segment-sum over split-row chunks.

    gather_dtype='fp8': rows are quantized (one per-call e4m3 scale) BEFORE
    the gather, halving wire bytes vs bf16 — the gather unit is row-rate
    bound below 512B rows, so 256-feature bf16 rows gain ~1.5x (measured);
    the reduction runs in f32 and the single scale multiplies back after the
    combine (linear, exact). Quantization noise is ~2-3 significant digits
    per element, the same class as the fp8 halo wire."""
    scale = None
    if gather_dtype == "fp8":
        # NOTE: fp8 rows take the jnp f32 reduce — the Pallas bucket kernel
        # is bypassed for them (reduce_tile) until f8 loads are validated
        # in Mosaic on hardware
        from bnsgcn_tpu.utils.quant import f8_quant
        hq, scale = f8_quant(h)
        hp = jnp.concatenate([hq, jnp.zeros((1, h.shape[1]), hq.dtype)], 0)
    elif gather_dtype == "int8":
        # native 1-byte wire: int32 bucket sums stay exact; one per-call
        # scale multiplies back after the combine (linear, exact)
        from bnsgcn_tpu.utils.quant import i8_quant
        hq, scale = i8_quant(h)
        hp = jnp.concatenate([hq, jnp.zeros((1, h.shape[1]), hq.dtype)], 0)
    else:
        hp = jnp.concatenate([h, jnp.zeros((1, h.shape[1]), h.dtype)], 0)
    outs = []
    for k, w in enumerate(spec.widths):
        outs.append(_bucket_sum(hp, idx_list[k], w, use_pallas=use_pallas,
                                accum=accum))
    out = ell_combine(spec, outs, perm, chunk_pos, chunk_seg)
    if scale is not None:
        out = (out.astype(jnp.float32) * scale).astype(h.dtype)
    return out


def make_ell_spmm(fwd_spec: EllSpec, bwd_spec: EllSpec, n_buckets_fwd: int,
                  n_buckets_bwd: int, use_pallas: bool = False,
                  gather_dtype: str = "native", accum: str = "auto"):
    """Returns spmm(arrays, h_ext) -> [n_dst, H] with a custom VJP that runs
    the transposed layout (also scatter-free) on the backward pass. The
    backward quantizes the cotangent with its OWN fp8 scale when
    gather_dtype='fp8' (gradient magnitudes differ from activations)."""

    @jax.custom_vjp
    def spmm(arrays, h_ext):
        idx = [arrays[f"fwd_idx_{k}"] for k in range(n_buckets_fwd)]
        return _ell_apply(fwd_spec, idx, arrays["fwd_perm"], h_ext, use_pallas,
                          arrays.get("fwd_chunk_pos"), arrays.get("fwd_chunk_seg"),
                          gather_dtype=gather_dtype, accum=accum)

    def fwd(arrays, h_ext):
        return spmm(arrays, h_ext), (arrays,)

    def bwd(res, g):
        (arrays,) = res
        idx = [arrays[f"bwd_idx_{k}"] for k in range(n_buckets_bwd)]
        d_h = _ell_apply(bwd_spec, idx, arrays["bwd_perm"], g, use_pallas,
                         arrays.get("bwd_chunk_pos"), arrays.get("bwd_chunk_seg"),
                         gather_dtype=gather_dtype, accum=accum)
        return None, d_h

    spmm.defvjp(fwd, bwd)
    return spmm


def _pow2_bucket(deg: np.ndarray) -> np.ndarray:
    """Ladder bucket index of each positive degree for widths (4, 8, 16, ...):
    deg in (0,4] -> 0, (4,8] -> 1, (2^j, 2^(j+1)] -> j-1 (matches
    ops/ell._bucketize against ops/ell._choose_widths ladders exactly)."""
    d = np.maximum(deg, 1)
    return np.maximum(np.ceil(np.log2(d)).astype(np.int64), 2) - 2


class GeoAccum:
    """Accumulates per-part degree statistics into the compute_geometry dict
    without holding any stacked arrays: per-part pow2-bucket counts (below the
    cap), split-row counts and chunk sums (above it), and the global max."""

    def __init__(self, cap):
        self.cap = cap
        self.rows_max = np.zeros(64, dtype=np.int64)
        self.split_max = 0
        self.chunk_max = 0
        self.max_deg = 0

    def add_part(self, deg: np.ndarray):
        deg = deg[deg > 0]
        if deg.size == 0:
            return
        self.max_deg = max(self.max_deg, int(deg.max()))
        if self.cap:
            over = deg > self.cap
            n_split = int(over.sum())
            if n_split:
                self.split_max = max(self.split_max, n_split)
                self.chunk_max = max(self.chunk_max, int(
                    np.ceil(deg[over] / self.cap).sum()))
                deg = deg[~over]
        if deg.size:
            b = np.bincount(_pow2_bucket(deg), minlength=64)
            self.rows_max = np.maximum(self.rows_max, b)

    def state(self) -> "np.ndarray":
        """Fixed-size mergeable stats vector (for cross-host agreement):
        [rows_max[64], split_max, chunk_max, max_deg]."""
        return np.concatenate([self.rows_max,
                               [self.split_max, self.chunk_max, self.max_deg]]
                              ).astype(np.int64)

    def merge_state(self, state: "np.ndarray"):
        """Elementwise-max another accumulator's state() into this one."""
        self.rows_max = np.maximum(self.rows_max, state[:64])
        self.split_max = max(self.split_max, int(state[64]))
        self.chunk_max = max(self.chunk_max, int(state[65]))
        self.max_deg = max(self.max_deg, int(state[66]))

    def finish(self) -> dict:
        if self.max_deg == 0:
            return {"widths": [4], "rows": [0], "split": 0, "chunks": 0,
                    "cap": None}
        fake = np.asarray([self.max_deg])
        widths = _choose_widths(fake, cap=self.cap)
        eff_cap = self.cap if (self.cap and self.max_deg > self.cap) else None
        rows = [int(r) for r in self.rows_max[:len(widths)]]
        pad8 = lambda r: ((r + 7) // 8) * 8 if r else 0
        split = chunks = 0
        if eff_cap:
            split, chunks = pad8(self.split_max), pad8(self.chunk_max)
            rows[-1] += self.chunk_max
        return {"widths": [int(w) for w in widths], "rows": [pad8(r) for r in rows],
                "split": split, "chunks": chunks, "cap": eff_cap}
