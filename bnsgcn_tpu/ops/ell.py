"""Bucketed-ELLPACK sparse aggregation — the TPU-shaped SpMM.

`jax.ops.segment_sum` lowers to an XLA scatter-add, which serializes on TPU
(~120 GB/s effective on a v5e where HBM does ~800). This module reformulates
the same aggregation (reference DGL SpMM, module/layer.py:35-37,88-90) as
dense, scatter-free work:

  * offline (numpy, per part): group destination rows by in-degree into
    power-of-two buckets; within a bucket store src indices as a dense
    [rows, width] ELL table padded with a dummy index;
  * on device: per bucket, `h[idx]` (a batched row gather — fast on TPU) and
    a dense sum over the width axis; results land via one unique-index
    row permutation (a gather, not a scatter);
  * backward uses a second, transposed layout (rows = source nodes, grouped
    by out-degree) through `jax.custom_vjp`, so the gradient is the same
    scatter-free shape: d_h[u] = sum over out-edges of g[dst].

Bucket widths are powers of two, so ELL padding wastes < 2x gathers; rows
with degree 0 (structural padding) are skipped entirely.

Layouts stack across partition parts (shared bucket shapes = max over parts)
and ride through shard_map as ordinary sharded int arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class EllSpec:
    """Static bucket geometry (identical across parts)."""
    widths: tuple[int, ...]            # bucket ELL widths, ascending powers of 2
    rows: tuple[int, ...]              # padded row count per bucket
    n_rows: int                        # output rows (n_dst for fwd, n_src_ext for bwd)
    n_src: int                         # gatherable rows (n_src_ext for fwd, n_dst for bwd)


def _bucketize(deg: np.ndarray, widths: Sequence[int]) -> np.ndarray:
    """bucket index per row; deg 0 -> -1 (skipped)."""
    b = np.full(deg.shape, -1, dtype=np.int32)
    lo = 0
    for k, w in enumerate(widths):
        b[(deg > lo) & (deg <= w)] = k
        lo = w
    return b


def build_ell_numpy(src: np.ndarray, dst: np.ndarray, n_rows: int, n_src: int,
                    widths: Sequence[int] | None = None,
                    row_pad: Sequence[int] | None = None):
    """Build one part's ELL tables for `out[r] = sum_{e: dst_e == r} h[src_e]`.

    Padded edges must already point at dst == n_rows (they are dropped).
    Returns (spec_widths, rows_per_bucket, arrays) where arrays =
    {idx_k: [R_k, W_k] int32 (pad = n_src), perm: [n_rows] int32}.
    `perm[r]` = position of row r in the bucket-concatenated output, or
    `sum(R_k)` (a trailing zero row) for degree-0 rows.
    """
    real = dst < n_rows
    src, dst = src[real], dst[real]
    deg = np.bincount(dst, minlength=n_rows)
    if widths is None:
        widths = _choose_widths(deg)
    bucket = _bucketize(deg, widths)

    order = np.argsort(dst, kind="stable")
    src_sorted = src[order]
    dst_sorted = dst[order]
    indptr = np.zeros(n_rows + 1, dtype=np.int64)
    np.cumsum(deg, out=indptr[1:])

    # fully vectorized fill: for each edge, its (bucket, row-within-bucket,
    # slot-within-row) — no per-row python loop (matters at 100M edges)
    rpos = np.zeros(n_rows, dtype=np.int64)
    within = np.arange(len(dst_sorted), dtype=np.int64) - indptr[dst_sorted]
    e_bucket = bucket[dst_sorted]

    idx_arrays, rows_per_bucket = [], []
    perm = np.zeros(n_rows, dtype=np.int32)
    offset = 0
    for k, w in enumerate(widths):
        rows_k = np.nonzero(bucket == k)[0]
        n_k = len(rows_k)
        pad_rows = row_pad[k] if row_pad is not None else n_k
        assert pad_rows >= n_k
        rpos[rows_k] = np.arange(n_k)
        idx = np.full((pad_rows * w,), n_src, dtype=np.int32)
        sel = e_bucket == k
        idx[rpos[dst_sorted[sel]] * w + within[sel]] = src_sorted[sel]
        idx_arrays.append(idx.reshape(pad_rows, w))
        perm[rows_k] = offset + np.arange(n_k, dtype=np.int32)
        rows_per_bucket.append(pad_rows)
        offset += pad_rows
    perm[bucket == -1] = offset        # trailing zero row
    return tuple(widths), tuple(rows_per_bucket), idx_arrays, perm


def _choose_widths(deg: np.ndarray) -> tuple[int, ...]:
    """Power-of-2 bucket-width ladder from 4 up to the max degree.

    (An edge-mass-quantile scheme was tried and measured *slower* on a v5e
    despite ~25% fewer padded gathers — wide low-row-count buckets hurt the
    gather/reduce pipeline more than padding does. Keep the ladder.)
    """
    deg = deg[deg > 0]
    max_deg = int(deg.max()) if deg.size else 1
    widths, w = [], 4
    while True:
        widths.append(w)
        if w >= max(max_deg, 1):
            break
        w *= 2
    return tuple(widths)


def _part_edges(src, dst, n_dst, direction):
    """Real edges of one part, oriented for the requested layout direction."""
    real = dst < n_dst
    if direction == "fwd":             # rows = dst, gather = src
        return src[real], dst[real]
    return dst[real], src[real]        # rows = src(ext), gather = dst


def build_layouts(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                  n_src_ext: int) -> tuple[EllSpec, EllSpec, dict]:
    """Build stacked fwd (rows = dst) and bwd (rows = src_ext) ELL layouts.

    src_all/dst_all: [P, E] artifact edge arrays. Returns (fwd_spec, bwd_spec,
    arrays) with arrays = {'fwd_idx_k', 'bwd_idx_k', 'fwd_perm', 'bwd_perm'}
    stacked on a leading P axis (shard on 'parts').
    """
    P = src_all.shape[0]

    def build_all(direction):
        n_rows = n_dst if direction == "fwd" else n_src_ext
        n_src = n_src_ext if direction == "fwd" else n_dst
        # global bucket widths + per-bucket row maxima across parts
        degs = []
        for p in range(P):
            _, d = _part_edges(src_all[p], dst_all[p], n_dst, direction)
            degs.append(np.bincount(d, minlength=n_rows))
        widths = _choose_widths(np.concatenate(degs))
        rows_max = [0] * len(widths)
        for d in degs:
            b = _bucketize(d, widths)
            for k in range(len(widths)):
                rows_max[k] = max(rows_max[k], int(np.sum(b == k)))
        # lane-friendly row padding
        rows_max = tuple(((r + 7) // 8) * 8 if r else 0 for r in rows_max)

        idx_stacked = [[] for _ in widths]
        perms = []
        for p in range(P):
            s, d = _part_edges(src_all[p], dst_all[p], n_dst, direction)
            _, _, idx, perm = build_ell_numpy(s, d, n_rows, n_src,
                                              widths=widths, row_pad=rows_max)
            for k in range(len(widths)):
                idx_stacked[k].append(idx[k])
            perms.append(perm)
        spec = EllSpec(widths=widths, rows=rows_max, n_rows=n_rows, n_src=n_src)
        return spec, [np.stack(x) for x in idx_stacked], np.stack(perms)

    fwd_spec, fwd_idx, fwd_perm = build_all("fwd")
    bwd_spec, bwd_idx, bwd_perm = build_all("bwd")
    arrays = {"fwd_perm": fwd_perm, "bwd_perm": bwd_perm}
    for k in range(len(fwd_spec.widths)):
        arrays[f"fwd_idx_{k}"] = fwd_idx[k]
    for k in range(len(bwd_spec.widths)):
        arrays[f"bwd_idx_{k}"] = bwd_idx[k]
    return fwd_spec, bwd_spec, arrays


def _bucket_sum(hp, idx, w, chunk_gathers: int = 4_000_000,
                use_pallas: bool = False):
    """sum over ELL width for one bucket, row-chunked so the gathered
    [rows, w, H] intermediate never exceeds ~chunk_gathers * H elements.

    use_pallas routes the width reduction through the standard-pipeline
    Pallas kernel (ops/pallas_spmm.pallas_bucket_reduce)."""
    r = idx.shape[0]
    h_dim = hp.shape[1]
    rows_per_chunk = max(1, chunk_gathers // max(w, 1))
    # Pallas path: on-TPU only (off-TPU falls back to the jnp reduce — Mosaic
    # doesn't lower there and the interpreter doesn't compose with shard_map's
    # vma checks), and only for widths whose (8, W, H) block fits VMEM.
    pallas_ok = (use_pallas and w <= 1024
                 and jax.default_backend() == "tpu")

    def reduce_tile(g):
        if pallas_ok and g.shape[0] > 0 and g.shape[0] % 8 == 0:
            from bnsgcn_tpu.ops.pallas_spmm import pallas_bucket_reduce
            return pallas_bucket_reduce(g)
        return g.sum(axis=1)

    if r <= rows_per_chunk:
        return reduce_tile(hp[idx.reshape(-1)].reshape(r, w, h_dim))
    n_chunks = -(-r // rows_per_chunk)
    pad = n_chunks * rows_per_chunk - r
    idx_p = jnp.pad(idx, ((0, pad), (0, 0)), constant_values=hp.shape[0] - 1)
    idx_c = idx_p.reshape(n_chunks, rows_per_chunk, w)

    def body(_, ix):
        g = hp[ix.reshape(-1)].reshape(rows_per_chunk, w, h_dim)
        return None, reduce_tile(g)

    _, out = jax.lax.scan(body, None, idx_c)
    return out.reshape(n_chunks * rows_per_chunk, h_dim)[:r]


def _ell_apply(spec: EllSpec, idx_list, perm, h, use_pallas: bool = False):
    """Scatter-free aggregation: bucketed gather+sum, then one permutation gather."""
    hp = jnp.concatenate([h, jnp.zeros((1, h.shape[1]), h.dtype)], 0)  # pad row
    outs = []
    for k, w in enumerate(spec.widths):
        outs.append(_bucket_sum(hp, idx_list[k], w, use_pallas=use_pallas))
    outs.append(jnp.zeros((1, h.shape[1]), h.dtype))  # degree-0 row target
    table = jnp.concatenate(outs, axis=0)
    return table[perm]


def make_ell_spmm(fwd_spec: EllSpec, bwd_spec: EllSpec, n_buckets_fwd: int,
                  n_buckets_bwd: int, use_pallas: bool = False):
    """Returns spmm(arrays, h_ext) -> [n_dst, H] with a custom VJP that runs
    the transposed layout (also scatter-free) on the backward pass."""

    @jax.custom_vjp
    def spmm(arrays, h_ext):
        idx = [arrays[f"fwd_idx_{k}"] for k in range(n_buckets_fwd)]
        return _ell_apply(fwd_spec, idx, arrays["fwd_perm"], h_ext, use_pallas)

    def fwd(arrays, h_ext):
        return spmm(arrays, h_ext), (arrays,)

    def bwd(res, g):
        (arrays,) = res
        idx = [arrays[f"bwd_idx_{k}"] for k in range(n_buckets_bwd)]
        d_h = _ell_apply(bwd_spec, idx, arrays["bwd_perm"], g, use_pallas)
        return None, d_h

    spmm.defvjp(fwd, bwd)
    return spmm
