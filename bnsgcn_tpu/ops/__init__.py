from bnsgcn_tpu.ops.spmm import gather_scatter_sum, agg_sum, agg_mean
