"""GAT attention over the ELL layout — dense per-row edge softmax.

The segment-softmax GAT path (ops/spmm.segment_softmax + segment sums) runs
three scatter-shaped passes over the edge list. With destination rows in ELL
form (ops/ell.py, built WITHOUT the split cap so every dst row is one table
row), the edge softmax becomes a dense masked softmax over the row width and
the weighted sum a dense einsum — the DGL edge-softmax replacement (SURVEY
§2.4) in the same scatter-free shape as the SpMM. The geometry is the
uncapped 'fwd' entry of ops/ell.compute_geometry and rides meta.json like the
SpMM geometry, so multi-host processes build the layout from local parts.

Forward-only formulation: the backward runs through JAX AD (gather transposes
to scatter-add); a transposed-layout custom VJP is the planned follow-up.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.ops.ell import build_ell_numpy, compute_geometry


@dataclass(frozen=True)
class GatEllSpec:
    widths: tuple[int, ...]
    rows: tuple[int, ...]
    n_rows: int                        # dst rows (pad_inner)
    n_src: int                         # extended rows


def gat_geometry(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                 n_src_ext: int) -> dict:
    """Uncapped fwd geometry (whole rows — the softmax can't span split
    chunks); same schema as compute_geometry entries, JSON-serializable."""
    return compute_geometry(src_all, dst_all, n_dst, n_src_ext, cap=None,
                            directions=("fwd",))["fwd"]


def build_gat_layouts(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                      n_src_ext: int,
                      geometry: dict | None = None) -> tuple[GatEllSpec, dict]:
    """Dst-major uncapped ELL layout plus per-table-position row ids.

    `geometry` may come from meta.json (multi-host partial parts). Returns
    (spec, arrays): {'gat_idx_k': [P, R_k, W_k], 'gat_rows': [P, T],
    'gat_perm': [P, n_dst]}."""
    P = src_all.shape[0]
    if geometry is None:
        geometry = gat_geometry(src_all, dst_all, n_dst, n_src_ext)
    widths = tuple(geometry["widths"])
    rows_max = tuple(geometry["rows"])

    idx_stacked = [[] for _ in widths]
    perms, rows_ids = [], []
    total = sum(rows_max)
    for p in range(P):
        _, _, idx, perm, _, _ = build_ell_numpy(
            src_all[p], dst_all[p], n_dst, n_src_ext,
            widths=widths, row_pad=rows_max, cap=None)
        for k in range(len(widths)):
            idx_stacked[k].append(idx[k])
        perms.append(perm)
        row_of = np.full(total, n_dst, dtype=np.int32)   # pad -> trash dst row
        real = perm < total                              # degree-0 rows point at total
        row_of[perm[real]] = np.nonzero(real)[0]
        rows_ids.append(row_of)
    spec = GatEllSpec(widths=widths, rows=rows_max, n_rows=n_dst,
                      n_src=n_src_ext)
    arrays = {"gat_perm": np.stack(perms), "gat_rows": np.stack(rows_ids)}
    for k in range(len(widths)):
        arrays[f"gat_idx_{k}"] = np.stack(idx_stacked[k])
    return spec, arrays


def _attn_bucket(zp, elp, erp, pres, idx, rows, n_src, rng, dropout, training,
                 negative_slope, chunk_gathers: int = 2_000_000):
    """Masked softmax + weighted sum for one bucket, row-chunked so the
    [rows, W, heads(, F')] intermediates stay HBM-bounded (the attention
    analog of ops/ell._bucket_sum's chunking)."""
    heads, fdim = zp.shape[1], zp.shape[2]
    r, w = idx.shape

    def tile(idx_t, rows_t, key):
        mask = idx_t != n_src
        if pres is not None:
            mask = mask & pres[idx_t]
        e = elp[idx_t] + erp[rows_t][:, None, :]         # [r, W, heads]
        e = jax.nn.leaky_relu(e, negative_slope)
        e = jnp.where(mask[:, :, None], e.astype(jnp.float32), -1e30)
        m = jnp.max(e, axis=1, keepdims=True)
        ex = jnp.exp(e - jnp.maximum(m, -1e29))
        ex = jnp.where(mask[:, :, None], ex, 0.0)
        denom = jnp.maximum(ex.sum(axis=1, keepdims=True), 1e-16)
        alpha = (ex / denom).astype(zp.dtype)
        if training and key is not None and dropout > 0.0:
            keep = 1.0 - dropout
            bmask = jax.random.bernoulli(key, keep, alpha.shape)
            alpha = jnp.where(bmask, alpha / keep, 0.0).astype(zp.dtype)
        return jnp.einsum("rwh,rwhf->rhf", alpha, zp[idx_t])

    rows_per_chunk = max(1, chunk_gathers // max(w, 1))
    if r <= rows_per_chunk:
        return tile(idx, rows, rng)
    n_chunks = -(-r // rows_per_chunk)
    pad = n_chunks * rows_per_chunk - r
    idx_p = jnp.pad(idx, ((0, pad), (0, 0)), constant_values=n_src)
    rows_p = jnp.pad(rows, (0, pad), constant_values=elp.shape[0] - 1)
    keys = (jax.random.split(rng, n_chunks) if (training and rng is not None
                                                and dropout > 0.0)
            else jnp.zeros((n_chunks, 2), jnp.uint32))

    def body(_, args):
        ix, rw, key_bits = args
        key = (jax.random.wrap_key_data(key_bits)
               if training and rng is not None and dropout > 0.0 else None)
        return None, tile(ix, rw, key)

    key_data = (jax.vmap(jax.random.key_data)(keys)
                if training and rng is not None and dropout > 0.0 else keys)
    _, out = jax.lax.scan(
        body, None,
        (idx_p.reshape(n_chunks, rows_per_chunk, w),
         rows_p.reshape(n_chunks, rows_per_chunk), key_data))
    return out.reshape(n_chunks * rows_per_chunk, heads, fdim)[:r]


def gat_ell_attention(spec: GatEllSpec, arrays: dict, z: jax.Array,
                      el: jax.Array, er: jax.Array,
                      presence: jax.Array | None,
                      attn_rng, attn_dropout: float, training: bool,
                      negative_slope: float = 0.2) -> jax.Array:
    """out[v] = sum_u softmax_u(leaky(el[u] + er[v])) * z[u] over v's ELL row.

    z: [n_ext, heads, F'], el: [n_ext, heads], er: [n_dst, heads].
    Returns [n_dst, heads, F']. Padded slots and absent (unsampled) halos are
    masked out of the softmax (the reference's sampled-subgraph semantics,
    train.py:256-281).
    """
    heads, fdim = z.shape[1], z.shape[2]
    zp = jnp.concatenate([z, jnp.zeros((1, heads, fdim), z.dtype)], 0)
    elp = jnp.concatenate([el, jnp.zeros((1, heads), el.dtype)], 0)
    erp = jnp.concatenate([er, jnp.zeros((1, heads), er.dtype)], 0)
    pres = None
    if presence is not None:
        pres = jnp.concatenate([presence, jnp.zeros((1,), bool)], 0)

    outs = []
    offset = 0
    for k, w in enumerate(spec.widths):
        idx = arrays[f"gat_idx_{k}"]                     # [R, W]
        r = idx.shape[0]
        rows = jax.lax.dynamic_slice_in_dim(arrays["gat_rows"], offset, r)
        offset += r
        rng_k = (jax.random.fold_in(attn_rng, k)
                 if attn_rng is not None else None)
        outs.append(_attn_bucket(zp, elp, erp, pres, idx, rows, spec.n_src,
                                 rng_k, attn_dropout, training, negative_slope))
    outs.append(jnp.zeros((1, heads, fdim), z.dtype))    # degree-0 target
    table = jnp.concatenate(outs, axis=0)
    return table[arrays["gat_perm"]]
