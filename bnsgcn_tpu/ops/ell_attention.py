"""GAT attention over the ELL layout — dense per-row edge softmax with a
transposed-layout custom VJP.

The segment-softmax GAT path (ops/spmm.segment_softmax + segment sums) runs
three scatter-shaped passes over the edge list. With destination rows in ELL
form (ops/ell.py, built WITHOUT the split cap so every dst row is one table
row), the edge softmax becomes a dense masked softmax over the row width and
the weighted sum a dense einsum — the DGL edge-softmax replacement (SURVEY
§2.4; reference module/model.py:102) in the same scatter-free shape as the
SpMM.

Backward (jax.custom_vjp — the GAT analog of ops/ell.make_ell_spmm's
transposed layout):
  * pass A on the FORWARD layout (rows = dst v): recompute alpha from saved
    per-row softmax stats (max, denom), form q = <g[v], z[u]> per edge, and
    produce d_er plus the per-row sum s_v = sum_u alpha*q~ — all dense;
  * pass B on the TRANSPOSED layout (rows = src u, degree-capped with
    split-row chunks like the SpMM backward): d_z[u] = sum_v alpha~ * g[v]
    and d_el[u] = sum_v alpha*(q~ - s_v)*leaky' — gathers only, partial
    sums combined by ops/ell.ell_combine.
No scatter touches [n_ext, heads, F'] anywhere.

Attention dropout (the reference passes dropout as GATConv attn_drop,
module/model.py:102) is EDGE-DETERMINISTIC: the keep decision is a stateless
integer hash of (src id, dst id, head, key-derived seed), so the forward and
the transposed backward reproduce the identical mask without storing it.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.ops.ell import (ELL_SPLIT_CAP, EllSpec, build_ell_numpy,
                                compute_geometry, ell_combine)


@dataclass(frozen=True)
class GatEllSpec:
    widths: tuple[int, ...]
    rows: tuple[int, ...]
    n_rows: int                        # dst rows (pad_inner)
    n_src: int                         # extended rows
    bwd: EllSpec = None                # transposed (src-major, capped) layout


def gat_geometry(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                 n_src_ext: int) -> dict:
    """Uncapped fwd geometry (whole rows — the softmax can't span split
    chunks); same schema as compute_geometry entries, JSON-serializable."""
    return compute_geometry(src_all, dst_all, n_dst, n_src_ext, cap=None,
                            directions=("fwd",))["fwd"]


def build_gat_layouts(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int,
                      n_src_ext: int, geometry: dict | None = None,
                      geometry_bwd: dict | None = None
                      ) -> tuple[GatEllSpec, dict]:
    """Dst-major uncapped ELL layout (forward) + src-major capped layout
    (backward), with per-table-position row ids for both.

    `geometry`/`geometry_bwd` may come from meta.json ('gat_fwd' and 'bwd'
    entries — multi-host partial parts). Returns (spec, arrays):
    {'gat_idx_k', 'gat_rows', 'gat_perm',
     'gat_bwd_idx_k', 'gat_bwd_rows', 'gat_bwd_perm'
     [, 'gat_bwd_chunk_pos', 'gat_bwd_chunk_seg']}, stacked on parts."""
    P = src_all.shape[0]
    if geometry is None:
        geometry = gat_geometry(src_all, dst_all, n_dst, n_src_ext)
    if geometry_bwd is None:
        geometry_bwd = compute_geometry(src_all, dst_all, n_dst, n_src_ext,
                                        cap=ELL_SPLIT_CAP,
                                        directions=("bwd",))["bwd"]
    widths = tuple(geometry["widths"])
    rows_max = tuple(geometry["rows"])

    arrays = {}
    # ---- forward layout (rows = dst, uncapped) ----
    idx_stacked = [[] for _ in widths]
    perms, rows_ids = [], []
    for p in range(P):
        _, _, idx, perm, _, _, row_of = build_ell_numpy(
            src_all[p], dst_all[p], n_dst, n_src_ext,
            widths=widths, row_pad=rows_max, cap=None)
        for k in range(len(widths)):
            idx_stacked[k].append(idx[k])
        perms.append(perm)
        rows_ids.append(row_of)
    arrays["gat_perm"] = np.stack(perms)
    arrays["gat_rows"] = np.stack(rows_ids)
    for k in range(len(widths)):
        arrays[f"gat_idx_{k}"] = np.stack(idx_stacked[k])

    # ---- transposed layout (rows = src_ext, capped like the SpMM bwd) ----
    bw = tuple(geometry_bwd["widths"])
    br = tuple(geometry_bwd["rows"])
    b_cap = geometry_bwd["cap"]
    b_split, b_chunks = geometry_bwd["split"], geometry_bwd["chunks"]
    bidx_stacked = [[] for _ in bw]
    bperms, brows, bcp, bcs = [], [], [], []
    for p in range(P):
        real = dst_all[p] < n_dst
        _, _, idx, perm, cp, cs, row_of = build_ell_numpy(
            dst_all[p][real], src_all[p][real], n_src_ext, n_dst,
            widths=bw, row_pad=br, cap=b_cap,
            split_pad=b_split, chunk_pad=b_chunks)
        for k in range(len(bw)):
            bidx_stacked[k].append(idx[k])
        bperms.append(perm)
        brows.append(row_of)
        bcp.append(cp)
        bcs.append(cs)
    arrays["gat_bwd_perm"] = np.stack(bperms)
    arrays["gat_bwd_rows"] = np.stack(brows)
    if b_split:
        arrays["gat_bwd_chunk_pos"] = np.stack(bcp)
        arrays["gat_bwd_chunk_seg"] = np.stack(bcs)
    for k in range(len(bw)):
        arrays[f"gat_bwd_idx_{k}"] = np.stack(bidx_stacked[k])

    bwd_spec = EllSpec(widths=bw, rows=br, n_rows=n_src_ext, n_src=n_dst,
                       n_split=b_split, n_chunks=b_chunks)
    spec = GatEllSpec(widths=widths, rows=rows_max, n_rows=n_dst,
                      n_src=n_src_ext, bwd=bwd_spec)
    return spec, arrays


# ----------------------------------------------------------------------------
# edge-deterministic dropout: keep(u, v, h) from an integer hash — identical
# on the forward (dst-major) and transposed (src-major) layouts.
# ----------------------------------------------------------------------------

def _hash_keep(u32, v32, h_idx, seed0, seed1, keep_prob):
    """u32/v32: broadcast-compatible uint32 arrays of src/dst ids; h_idx [H].
    Returns bool [..., H]: murmur3-finalized hash of (u, v, h, seeds)."""
    x = (u32 * np.uint32(2654435761)) ^ (v32 * np.uint32(2246822519)) ^ seed0
    x = x[..., None] ^ (h_idx * np.uint32(3266489917)) ^ seed1
    x = x ^ (x >> 16)
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    unit = x.astype(jnp.float32) * np.float32(1.0 / 4294967296.0)
    return unit < keep_prob


def _row_chunked(tile, r, rows_per_chunk, pads, *arrs):
    """scan `tile` over row chunks of the leading axis; `pads` gives the
    pad value per array. Outputs (array or tuple) are row-concatenated."""
    if r <= rows_per_chunk:
        return tile(*arrs)
    n_chunks = -(-r // rows_per_chunk)
    pad = n_chunks * rows_per_chunk - r
    padded = []
    for a, pv in zip(arrs, pads):
        cfg = [(0, pad)] + [(0, 0)] * (a.ndim - 1)
        padded.append(jnp.pad(a, cfg, constant_values=pv)
                      .reshape((n_chunks, rows_per_chunk) + a.shape[1:]))

    def body(_, chunk):
        return None, tile(*chunk)

    _, out = jax.lax.scan(body, None, tuple(padded))
    if isinstance(out, tuple):
        return tuple(o.reshape((n_chunks * rows_per_chunk,) + o.shape[2:])[:r]
                     for o in out)
    return out.reshape((n_chunks * rows_per_chunk,) + out.shape[2:])[:r]


def _leaky(x, slope):
    return jnp.where(x > 0, x, x * slope)


def _pad_rows(x, value=0.0):
    pad = jnp.full((1,) + x.shape[1:], value, x.dtype)
    return jnp.concatenate([x, pad], axis=0)


def _seeds_of(attn_rng, training, drop):
    if attn_rng is None or not training or drop <= 0.0:
        return jnp.zeros((2,), jnp.uint32)
    return jax.random.key_data(attn_rng).astype(jnp.uint32).reshape(-1)[:2]


def _head_idx(heads, head_off):
    """Global head ids of this call's head block. `head_off` (feat-sharded
    GAT, parallel/feat.py) offsets the dropout hash so shard f's masks are
    exactly heads [off, off+heads) of the feat=1 masks; None = heads 0..H."""
    hidx = jnp.arange(heads, dtype=jnp.uint32)
    if head_off is not None:
        hidx = hidx + jnp.asarray(head_off).astype(jnp.uint32)
    return hidx


def _fwd_buckets(spec, arrays, zp, elp, erp, pres, drop, training,
                 slope, seeds, head_off=None, chunk_gathers=2_000_000):
    """Forward over the dst-major layout. Returns per-bucket weighted sums
    and per-bucket softmax stats (m', denom), all in table-row order."""
    heads = zp.shape[1]
    hidx = _head_idx(heads, head_off)
    outs, ms, ds = [], [], []
    offset = 0
    for k, w in enumerate(spec.widths):
        idx = arrays[f"gat_idx_{k}"]
        r = idx.shape[0]
        rows = jax.lax.dynamic_slice_in_dim(arrays["gat_rows"], offset, r)
        offset += r

        def tile(idx_t, rows_t):
            mask = (idx_t != spec.n_src) & (rows_t != spec.n_rows)[:, None]
            if pres is not None:
                mask = mask & pres[idx_t]
            e = _leaky((elp[idx_t] + erp[rows_t][:, None, :])
                       .astype(jnp.float32), slope)
            e = jnp.where(mask[:, :, None], e, -1e30)
            m = jnp.maximum(jnp.max(e, axis=1), -1e29)          # [r, H]
            ex = jnp.where(mask[:, :, None], jnp.exp(e - m[:, None, :]), 0.0)
            denom = jnp.maximum(ex.sum(axis=1), 1e-16)          # [r, H]
            alpha = (ex / denom[:, None, :])
            if training and drop > 0.0:
                keep = _hash_keep(idx_t.astype(jnp.uint32),
                                  rows_t.astype(jnp.uint32)[:, None],
                                  hidx, seeds[0], seeds[1], 1.0 - drop)
                alpha = jnp.where(keep, alpha / (1.0 - drop), 0.0)
            return (jnp.einsum("rwh,rwhf->rhf", alpha.astype(zp.dtype),
                               zp[idx_t]), m, denom)

        rpc = max(1, chunk_gathers // max(w, 1))
        o, m, d = _row_chunked(tile, r, rpc, (spec.n_src, spec.n_rows),
                               idx, rows)
        outs.append(o)
        ms.append(m)
        ds.append(d)
    return outs, ms, ds


def _gat_fwd_impl(spec, arrays, z, el, er, presence, attn_rng, head_off,
                  attn_dropout, training, negative_slope):
    heads, fdim = z.shape[1], z.shape[2]
    zp = _pad_rows(z)
    elp = _pad_rows(el)
    erp = _pad_rows(er)
    pres = _pad_rows(presence, False) if presence is not None else None
    seeds = _seeds_of(attn_rng, training, attn_dropout)
    outs, ms, ds = _fwd_buckets(spec, arrays, zp, elp, erp, pres,
                                attn_dropout, training, negative_slope, seeds,
                                head_off=head_off)
    zero = jnp.zeros((1, heads, fdim), z.dtype)
    out = jnp.concatenate(outs + [zero], axis=0)[arrays["gat_perm"]]
    # per-dst stats for the transposed backward (degree-0 rows hit the
    # appended neutral row: m=-1e29, denom=1)
    m_tab = jnp.concatenate(ms + [jnp.full((1, heads), -1e29, jnp.float32)], 0)
    d_tab = jnp.concatenate(ds + [jnp.ones((1, heads), jnp.float32)], 0)
    return out, (m_tab[arrays["gat_perm"]], d_tab[arrays["gat_perm"]], seeds)


@partial(jax.custom_vjp, nondiff_argnums=(0, 8, 9, 10))
def gat_ell_attention(spec: GatEllSpec, arrays: dict, z: jax.Array,
                      el: jax.Array, er: jax.Array,
                      presence, attn_rng, head_off,
                      attn_dropout: float, training: bool,
                      negative_slope: float = 0.2) -> jax.Array:
    """out[v] = sum_u softmax_u(leaky(el[u] + er[v])) * z[u] over v's ELL row.

    z: [n_ext, heads, F'], el: [n_ext, heads], er: [n_dst, heads].
    Returns [n_dst, heads, F']. Padded slots and absent (unsampled) halos are
    masked out of the softmax (the reference's sampled-subgraph semantics,
    train.py:256-281). `head_off` (None = 0) shifts the dropout hash's head
    ids for feat-sharded head blocks (parallel/feat.py).
    """
    out, _ = _gat_fwd_impl(spec, arrays, z, el, er, presence, attn_rng,
                           head_off, attn_dropout, training, negative_slope)
    return out


def _gat_fwd_rule(spec, arrays, z, el, er, presence, attn_rng, head_off,
                  attn_dropout, training, negative_slope):
    out, (m_v, denom_v, seeds) = _gat_fwd_impl(
        spec, arrays, z, el, er, presence, attn_rng, head_off, attn_dropout,
        training, negative_slope)
    return out, (arrays, z, el, er, presence, head_off, m_v, denom_v, seeds)


def _gat_bwd_rule(spec, attn_dropout, training, negative_slope, res, g):
    arrays, z, el, er, presence, head_off, m_v, denom_v, seeds = res
    heads = z.shape[1]
    hidx = _head_idx(heads, head_off)
    drop = attn_dropout if training else 0.0
    keep_p = 1.0 - drop

    zp = _pad_rows(z)
    elp = _pad_rows(el)
    erp = _pad_rows(er)
    pres = _pad_rows(presence, False) if presence is not None else None
    gp = _pad_rows(g.astype(jnp.float32))
    m_p = _pad_rows(m_v, -1e29)
    den_p = _pad_rows(denom_v, 1.0)

    # ---- pass A: forward layout — d_er and s_v = sum_u alpha * q~ ----
    der_list, s_list = [], []
    offset = 0
    for k, w in enumerate(spec.widths):
        idx = arrays[f"gat_idx_{k}"]
        r = idx.shape[0]
        rows = jax.lax.dynamic_slice_in_dim(arrays["gat_rows"], offset, r)
        offset += r

        def tileA(idx_t, rows_t):
            mask = (idx_t != spec.n_src) & (rows_t != spec.n_rows)[:, None]
            if pres is not None:
                mask = mask & pres[idx_t]
            e_pre = (elp[idx_t] + erp[rows_t][:, None, :]).astype(jnp.float32)
            e = _leaky(e_pre, negative_slope)
            alpha = jnp.where(
                mask[:, :, None],
                jnp.exp(e - m_p[rows_t][:, None, :]) / den_p[rows_t][:, None, :],
                0.0)                                            # [r, W, H]
            q = jnp.einsum("rwhf,rhf->rwh", zp[idx_t].astype(jnp.float32),
                           gp[rows_t])
            if drop > 0.0:
                keep = _hash_keep(idx_t.astype(jnp.uint32),
                                  rows_t.astype(jnp.uint32)[:, None],
                                  hidx, seeds[0], seeds[1], keep_p)
                q = jnp.where(keep, q / keep_p, 0.0)
            s_row = jnp.einsum("rwh,rwh->rh", alpha, q)          # [r, H]
            d_e = alpha * (q - s_row[:, None, :])
            d_pre = d_e * jnp.where(e_pre > 0, 1.0, negative_slope)
            return d_pre.sum(axis=1), s_row

        rpc = max(1, 2_000_000 // max(w, 1))
        der_k, s_k = _row_chunked(tileA, r, rpc, (spec.n_src, spec.n_rows),
                                  idx, rows)
        der_list.append(der_k)
        s_list.append(s_k)
    zeroH = jnp.zeros((1, heads), jnp.float32)
    d_er = jnp.concatenate(der_list + [zeroH], 0)[arrays["gat_perm"]]
    s_v = jnp.concatenate(s_list + [zeroH], 0)[arrays["gat_perm"]]
    s_p = _pad_rows(s_v)

    # ---- pass B: transposed layout — d_z and d_el (gathers only) ----
    bspec = spec.bwd
    dz_outs, del_outs = [], []
    offset = 0
    for k, w in enumerate(bspec.widths):
        idx = arrays[f"gat_bwd_idx_{k}"]                         # [R, W] dst ids
        r = idx.shape[0]
        rows = jax.lax.dynamic_slice_in_dim(arrays["gat_bwd_rows"], offset, r)
        offset += r

        def tileB(idx_t, rows_t):
            # rows_t: src ext ids (split pseudo-rows share their source id)
            mask = idx_t != bspec.n_src                          # pad dst slot
            if pres is not None:
                mask = mask & pres[rows_t][:, None]
            e_pre = (elp[rows_t][:, None, :] + erp[idx_t]).astype(jnp.float32)
            e = _leaky(e_pre, negative_slope)
            alpha = jnp.where(mask[:, :, None],
                              jnp.exp(e - m_p[idx_t]) / den_p[idx_t], 0.0)
            g_t = gp[idx_t]                                      # [r, W, H, F]
            q = jnp.einsum("rwhf,rhf->rwh", g_t,
                           zp[rows_t].astype(jnp.float32))
            alpha_d = alpha
            if drop > 0.0:
                # hash args must match pass A: u = src id, v = dst id
                keep = _hash_keep(rows_t.astype(jnp.uint32)[:, None],
                                  idx_t.astype(jnp.uint32),
                                  hidx, seeds[0], seeds[1], keep_p)
                alpha_d = jnp.where(keep, alpha / keep_p, 0.0)
                q = jnp.where(keep, q / keep_p, 0.0)
            d_z_row = jnp.einsum("rwh,rwhf->rhf", alpha_d, g_t)
            d_e = alpha * (q - s_p[idx_t])
            d_pre = d_e * jnp.where(e_pre > 0, 1.0, negative_slope)
            return d_z_row, d_pre.sum(axis=1)

        rpc = max(1, 2_000_000 // max(w, 1))
        dz_k, del_k = _row_chunked(tileB, r, rpc,
                                   (bspec.n_src, bspec.n_rows), idx, rows)
        dz_outs.append(dz_k)
        del_outs.append(del_k)

    cp = arrays.get("gat_bwd_chunk_pos")
    cs = arrays.get("gat_bwd_chunk_seg")
    d_z = ell_combine(bspec, dz_outs, arrays["gat_bwd_perm"], cp, cs)
    d_el = ell_combine(bspec, del_outs, arrays["gat_bwd_perm"], cp, cs)
    return (None, d_z.astype(z.dtype), d_el.astype(el.dtype),
            d_er.astype(er.dtype), None, None, None)


gat_ell_attention.defvjp(_gat_fwd_rule, _gat_bwd_rule)
