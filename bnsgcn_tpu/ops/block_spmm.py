"""Hybrid block-dense + ELL sparse aggregation — the MXU-path SpMM.

The pure-ELL SpMM (ops/ell.py) is bound by the TPU gather unit (~110 GB/s of
512B rows measured on a v5e — far below HBM stream). Real graphs in this
workload's class (Reddit: 41 communities, strong homophily; METIS partitions
of anything) are CLUSTERED: with rows reordered by locality, much of the
edge mass falls into a small set of dense adjacency tiles. Those tiles can
be aggregated on the MXU instead of the gather unit:

  offline (numpy, per part):
    * cluster-order the local node space (cluster_order: native-partitioner
      LDG clustering; halo slots keep their per-peer grouping);
    * tile the (dst x src) adjacency into [TR x TC] blocks; blocks with
      >= occupancy_min edges become DENSE int8 tiles (edge multiplicities)
      with (row_block, col_block) ids sorted by row_block; every remaining
      edge goes to the usual bucketed-ELL residual;
    * the backward layout is the exact per-tile TRANSPOSE (tiles [TC x TR],
      ids swapped, re-sorted) — same edges, so the VJP is exact; the ELL
      residual already builds its own fwd+bwd pair over the SAME edges.
  on device, per pass:
    * X_perm = X[inv perm] (one cheap permutation gather) sliced into
      [n_col_blocks, TC, H] slabs; slab gather by col_block id (contiguous
      TC*H*2-byte reads — byte-efficient even on the gather unit);
    * int8 tiles cast to the compute dtype and ONE batched matmul
      [B, TR, TC] @ [B, TC, H] (MXU);
    * sorted segment-sum over row_block ids, inverse permutation, plus the
      ELL residual output.

On graphs with no locality (uniform synthetic), no tile clears the
occupancy threshold and the operator degenerates to the ELL SpMM — the
hybrid never loses. Replaces: reference DGL SpMM update_all(copy_u, sum)
(module/layer.py:35-37,88-90).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.ops.ell import (ELL_SPLIT_CAP, GeoAccum, build_layouts,
                                layout_fastpath, make_ell_spmm, run_parallel)

TR = 512          # default dst rows per dense tile (square: transposes keep
TC = 512          # shape, and per-edge slab/output overhead beats narrow
                  # tiles). Finer tiles (256) capture more edge mass per tile
                  # byte on clustered graphs — same budget, less ELL residual
                  # — at the cost of ~2x slab-gather traffic per tile byte;
                  # selectable per run (config --block-tile, bench +t256).


@dataclass(frozen=True)
class BlockSpec:
    """Static geometry of one direction's dense-tile layout."""
    n_rows: int                    # output rows (original id space)
    n_src: int                     # gatherable rows (original id space)
    row_tile: int
    col_tile: int
    n_blocks: int                  # padded dense-tile count
    n_row_blocks: int              # ceil(n_rows / row_tile)
    max_row_dense: int = 0         # max dense edges on any output row (over
                                   # parts; 0 = unknown, e.g. a layout cached
                                   # before this field existed). Bounds the
                                   # int8 Pallas path's int32 accumulator:
                                   # |row sum| <= 127*127*max_row_dense.


def effective_occupancy(occupancy: int, tile_r: int = TR,
                        tile_c: int = TC) -> int:
    """Resolve the occupancy knob: 0 = auto, the byte break-even of a
    tile_r x tile_c int8 tile vs 512B gather rows (~tile_bytes/512 edges:
    512 at the default 512x512 tile, 128 at 256x256). Explicit values are
    absolute edge counts. Centralized so trainer CLI runs, bench variants,
    and tools all scale the threshold with tile area identically."""
    return occupancy if occupancy > 0 else max(tile_r * tile_c // 512, 16)


def _select_dense(tile_id, occupancy_min, tile_budget_bytes,
                  tile_bytes=TR * TC, need_inverse=True, n_tiles=None):
    """Which tiles densify: >= occupancy_min edges, highest-count tiles win
    under the HBM budget (ties trimmed last). Shared by the real layout
    build and the O(E) coverage estimator behind --spmm auto (which skips
    the len(E) int64 inverse array — need_inverse=False).

    With `n_tiles` (the dense tile-grid extent) the unique pass runs as one
    O(E + n_tiles) bincount + rank LUT instead of np.unique's O(E log E)
    sort — bitwise-identical output (bincount indices are ascending, the
    same order np.unique emits; ~24x at 20M edges). The sort fallback
    covers grids too large to histogram and BNSGCN_LAYOUT_FASTPATH=0."""
    if (n_tiles is not None and layout_fastpath()
            and n_tiles <= (1 << 26)):
        cf = np.bincount(tile_id, minlength=n_tiles)
        uniq = np.flatnonzero(cf)
        counts = cf[uniq]
        if need_inverse:
            lut = np.zeros(n_tiles, dtype=np.int64)
            lut[uniq] = np.arange(len(uniq))
            inv = lut[tile_id]
        else:
            inv = None
    elif need_inverse:
        uniq, inv, counts = np.unique(tile_id, return_inverse=True,
                                      return_counts=True)
    else:
        uniq, counts = np.unique(tile_id, return_counts=True)
        inv = None
    max_tiles = max(int(tile_budget_bytes // tile_bytes), 1)
    dense_sel = counts >= occupancy_min
    if int(dense_sel.sum()) > max_tiles:
        # keep every tile strictly above the cut, trim only among ties
        thresh = np.sort(counts[dense_sel])[-max_tiles]
        above = counts > thresh
        ties = np.nonzero(dense_sel & (counts == thresh))[0]
        dense_sel = above
        dense_sel[ties[:max_tiles - int(above.sum())]] = True
    return uniq, inv, counts, dense_sel


def estimate_coverage(perm_rows, perm_cols, n_rows, n_src, rows, cols,
                      occupancy_min=512, tile_budget_bytes=2 << 30,
                      tile_r=TR, tile_c=TC) -> float:
    """Fraction of edges that would land on dense MXU tiles under the
    given cluster order — the decision statistic for --spmm auto. One
    O(E) histogram pass over exactly _build_tiles' selection rule; no
    tile stacks or residual tables are materialized.

    Known bias: edges beyond 127 per-(tile,row,col) multiplicity count as
    dense here, but _build_tiles pushes that excess back to the ELL
    residual — so on high-multiplicity multigraphs the estimate can
    overstate coverage and flip --spmm auto toward hybrid near the
    decision threshold. Negligible on simple graphs (every bench/reference
    dataset); clamping would need the per-cell histogram this estimator
    exists to avoid."""
    if len(rows) == 0:
        return 0.0
    n_cb = (n_src + tile_c - 1) // tile_c
    tile_id = (perm_rows[rows] // tile_r).astype(np.int64) * n_cb \
        + perm_cols[cols] // tile_c
    n_rb = (n_rows + tile_r - 1) // tile_r
    _, _, counts, dense_sel = _select_dense(tile_id, occupancy_min,
                                            tile_budget_bytes,
                                            tile_bytes=tile_r * tile_c,
                                            need_inverse=False,
                                            n_tiles=n_rb * n_cb)
    return float(counts[dense_sel].sum()) / float(len(rows))


def _build_tiles(perm_rows, perm_cols, n_rows, n_src, rows, cols,
                 occupancy_min, tile_budget_bytes=2 << 30,
                 tile_r=TR, tile_c=TC):
    """Dense tiles over cluster-ordered (rows x cols); fully vectorized.

    A tile densifies only if it carries >= occupancy_min edges (an int8
    512x512 tile costs TR*TC = 256KB of HBM reads per pass plus its slab
    and output shares — byte break-even vs 512B-row gathers lands around
    ~512 edges, the default threshold; scale occupancy with tile area) AND
    the total dense storage stays under tile_budget_bytes (highest-count
    tiles win; ties trimmed last).
    Returns (tiles int8 [B,tile_r,tile_c] sorted by row_blk, row_blk,
    col_blk, residual_edge_mask, extra_rows, extra_cols, rle) — the extras
    are >127 multiplicity overflow in PERMUTED coordinates. Tiles fill by a
    cell-id sort + run-length encode (writes only occupied cells); peak
    transient memory is O(E), not O(tiles). `rle` is the occupied-cell
    encoding (cell ids, clamped int8 counts) on the fast path (None on
    legacy) — it lets the caller build the transposed bwd stack and the
    per-row dense maxima by O(occupied) scatter/bincount instead of three
    more passes over the multi-GB stack."""
    n_cb = (n_src + tile_c - 1) // tile_c
    pr = perm_rows[rows]
    pc = perm_cols[cols]
    tile_id = (pr // tile_r).astype(np.int64) * n_cb + pc // tile_c
    n_rb = (n_rows + tile_r - 1) // tile_r
    uniq, inv, counts, dense_sel = _select_dense(tile_id, occupancy_min,
                                                 tile_budget_bytes,
                                                 tile_bytes=tile_r * tile_c,
                                                 n_tiles=n_rb * n_cb)
    B = int(dense_sel.sum())
    if B == 0:
        return (np.zeros((0, tile_r, tile_c), np.int8),
                np.zeros(0, np.int32),
                np.zeros(0, np.int32), np.ones(len(rows), dtype=bool),
                np.zeros(0, np.int64), np.zeros(0, np.int64), None)

    rank = np.full(len(uniq), -1, dtype=np.int64)
    rank[np.nonzero(dense_sel)[0]] = np.arange(B)        # uniq sorted => rb-major
    e_rank = rank[inv]
    m = e_rank >= 0
    resid_mask = ~m
    sel_ids = uniq[dense_sel]
    row_blk = (sel_ids // n_cb).astype(np.int32)
    col_blk = (sel_ids % n_cb).astype(np.int32)

    # fill by run-length encoding instead of a dense int accumulator: sort
    # the dense edges by exact cell id (tile-major), count runs, and write
    # only the OCCUPIED cells straight into the int8 stack. Replaces the
    # chunked np.add.at histogram + full-stack >127 scan + int32->int8
    # cast — each a pass over B*tile_r*tile_c elements — with one O(E log E)
    # sort plus O(E) writes (2.1x on the scale-0.1 dcsbm build where edges
    # fill ~2% of the selected tiles' cells; BENCH_NOTES has the runs).
    area = tile_r * tile_c
    tiles8 = np.zeros((B, tile_r, tile_c), dtype=np.int8)
    cell = (e_rank[m] * area + (pr[m] % tile_r) * tile_c
            + (pc[m] % tile_c))
    cell.sort()
    starts = np.concatenate(
        [[0], np.flatnonzero(np.diff(cell)) + 1]).astype(np.int64)
    uc = cell[starts]                                    # occupied cells
    cnt = np.diff(np.concatenate([starts, [len(cell)]]))
    cnt8 = np.minimum(cnt, 127).astype(np.int8)
    tiles8.reshape(-1)[uc] = cnt8
    over = cnt > 127                                     # int8 overflow:
    if over.any():                                       # excess -> residual
        rep = cnt[over] - 127
        ob = uc[over] // area
        orr = (uc[over] % area) // tile_c
        occ = uc[over] % tile_c
        extra_rows = np.repeat(orr + row_blk[ob].astype(np.int64) * tile_r,
                               rep)
        extra_cols = np.repeat(occ + col_blk[ob].astype(np.int64) * tile_c,
                               rep)
    else:
        extra_rows = extra_cols = np.zeros(0, np.int64)
    rle = (uc, cnt8) if layout_fastpath() else None
    return tiles8, row_blk, col_blk, resid_mask, extra_rows, extra_cols, rle


def _row_dense_maxima(tiles, rb, cb, n_dst, n_src_ext, tile_r, tile_c):
    """(max fwd-row, max bwd-row) dense edge counts for one part's tile
    stack. sum(dtype=int64) — NOT astype — so no 8x copy of the (up to
    multi-GB) int8 stack is ever materialized."""
    # +1 row block: stacked cached layouts pad unused tile slots with
    # row_blk == n_row_blocks (their tiles are all-zero, so the extra row
    # accumulates nothing and is simply not read)
    per_row = np.zeros(((n_dst + tile_r - 1) // tile_r + 1, tile_r),
                       np.int64)
    np.add.at(per_row, rb, tiles.sum(axis=2, dtype=np.int64))
    per_col = np.zeros(((n_src_ext + tile_c - 1) // tile_c + 1, tile_c),
                       np.int64)
    np.add.at(per_col, cb, tiles.sum(axis=1, dtype=np.int64))
    return int(per_row.max()), int(per_col.max())


def repair_max_row_dense(fwd: BlockSpec, bwd: BlockSpec, arrays):
    """Fill max_row_dense on BlockSpecs unpickled from a cache written
    before the field existed (they deserialize with the class default 0 =
    unknown, which would silently skip the int8 Pallas overflow guard).
    Recomputed from the cached tile stacks; returns (fwd, bwd) updated.
    A few seconds of host numpy per load at the 2 GB-stack bench scale —
    vs invalidating every multi-GB layout cache with a version bump."""
    if getattr(fwd, "max_row_dense", 0) and getattr(bwd, "max_row_dense", 0):
        return fwd, bwd
    import dataclasses
    tiles_all = arrays["blk_tiles_fwd"]
    mrd_f = mrd_b = 0
    for p in range(tiles_all.shape[0]):
        m_f, m_b = _row_dense_maxima(
            np.asarray(tiles_all[p]), np.asarray(arrays["blk_rowb_fwd"][p]),
            np.asarray(arrays["blk_colb_fwd"][p]), fwd.n_rows, bwd.n_rows,
            fwd.row_tile, fwd.col_tile)
        mrd_f, mrd_b = max(mrd_f, m_f), max(mrd_b, m_b)
    return (dataclasses.replace(fwd, max_row_dense=mrd_f),
            dataclasses.replace(bwd, max_row_dense=mrd_b))


def build_block_layouts(src_all, dst_all, n_dst, n_src_ext, perm_inner,
                        perm_ext, occupancy_min=512,
                        tile_budget_bytes=2 << 30, agree=None,
                        tile_r=TR, tile_c=TC):
    """Hybrid layout for all local parts. perm_inner [P, n_dst] /
    perm_ext [P, n_src_ext]: cluster position per original row (the inner
    prefix of perm_ext must equal perm_inner).

    `agree`: optional callable (dict of int arrays) -> elementwise-maxed
    dict, used on multi-host runs so every process builds identically-shaped
    tile stacks and residual ELL tables from its LOCAL parts alone (the
    trainer wires jax process_allgather through it).

    Returns (fwd BlockSpec, bwd BlockSpec, ell pair (spec, spec, buckets),
    arrays dict stacked on parts)."""
    P = src_all.shape[0]

    def one_part(p):
        real = dst_all[p] < n_dst
        s, d = src_all[p][real], dst_all[p][real]
        tiles, rb, cb, resid, xr, xc, rle = _build_tiles(
            perm_inner[p], perm_ext[p], n_dst, n_src_ext, d, s, occupancy_min,
            tile_budget_bytes, tile_r=tile_r, tile_c=tile_c)
        # excess-multiplicity edges come back in PERMUTED coordinates —
        # map to original ids for the residual ELL. perm_* are true
        # permutations, so the inverse is a single scatter (~8x vs the
        # legacy argsort; same values).
        if layout_fastpath():
            orig_inner = np.empty(n_dst, dtype=np.intp)
            orig_inner[perm_inner[p]] = np.arange(n_dst)
            orig_ext = np.empty(n_src_ext, dtype=np.intp)
            orig_ext[perm_ext[p]] = np.arange(n_src_ext)
        else:
            orig_inner = np.argsort(perm_inner[p], kind="stable")
            orig_ext = np.argsort(perm_ext[p], kind="stable")
        return ((tiles, rb, cb, rle),
                np.concatenate([s[resid], orig_ext[xc]]),
                np.concatenate([d[resid], orig_inner[xr]]))

    # parts build concurrently (ell.build_workers pool; results in part
    # order, so stacked layouts are bit-identical to the serial build)
    results = run_parallel([partial(one_part, p) for p in range(P)])
    per_part = [r[0] for r in results]
    res_src = [r[1] for r in results]
    res_dst = [r[2] for r in results]

    B = max(max(e[0].shape[0] for e in per_part), 1)
    # max dense edges on any single output row, per direction (the spmm
    # runs per part under shard_map, so the per-part max is the bound):
    # caps the int8 Pallas accumulator at 127*127*max_row_dense
    mrd_f = mrd_b = 0
    area = tile_r * tile_c
    for p, (tiles, rb, cb, rle) in enumerate(per_part):
        if tiles.shape[0] == 0:
            continue
        if rle is not None:
            # O(occupied cells) bincount over the RLE — same clamped int8
            # counts the stack stores, grouped by the same (block, lane)
            # keys _row_dense_maxima sums, so the maxima are identical
            # without two more full passes over the multi-GB stack
            uc, c8 = rle
            t = uc // area
            r = (uc % area) // tile_c
            c = uc % tile_c
            m_f = int(np.bincount(rb[t].astype(np.int64) * tile_r + r,
                                  weights=c8).max())
            m_b = int(np.bincount(cb[t].astype(np.int64) * tile_c + c,
                                  weights=c8).max())
        else:
            m_f, m_b = _row_dense_maxima(tiles, rb, cb, n_dst, n_src_ext,
                                         tile_r, tile_c)
        mrd_f, mrd_b = max(mrd_f, m_f), max(mrd_b, m_b)
    # residual geometry stats (mergeable across hosts)
    acc_f, acc_b = GeoAccum(ELL_SPLIT_CAP), GeoAccum(ELL_SPLIT_CAP)
    for p in range(P):
        acc_f.add_part(np.bincount(res_dst[p], minlength=n_dst))
        acc_b.add_part(np.bincount(res_src[p], minlength=n_src_ext))
    if agree is not None:
        merged = agree({"B": np.asarray([B], np.int64),
                        "mrd": np.asarray([mrd_f, mrd_b], np.int64),
                        "geo_f": acc_f.state(), "geo_b": acc_b.state()})
        B = int(merged["B"][0])
        mrd_f, mrd_b = int(merged["mrd"][0]), int(merged["mrd"][1])
        acc_f.merge_state(merged["geo_f"])
        acc_b.merge_state(merged["geo_b"])
    res_geometry = {"fwd": acc_f.finish(), "bwd": acc_b.finish()}
    n_rb_f = (n_dst + tile_r - 1) // tile_r
    n_rb_b = (n_src_ext + tile_c - 1) // tile_c

    def build_residual():
        # residual ELL over the leftover edges (shared fwd+bwd edge set)
        e_max = max(max((len(s) for s in res_src), default=0), 8)
        e_max = ((e_max + 7) // 8) * 8
        r_src = np.zeros((P, e_max), dtype=np.int32)
        r_dst = np.full((P, e_max), n_dst, dtype=np.int32)
        for p in range(P):
            k = len(res_src[p])
            r_src[p, :k] = res_src[p]
            r_dst[p, :k] = res_dst[p]
            res_src[p] = res_dst[p] = None
        return build_layouts(r_src, r_dst, n_dst, n_src_ext,
                             geometry=res_geometry)

    def build_stacks():
        nonlocal tiles_f
        if P == 1 and per_part[0][0].shape[0] == B:
            # single local part fills the stack exactly: alias instead of
            # a second 2+ GB copy (the fwd stack IS the part's tile stack)
            tiles_f = per_part[0][0][None]
        else:
            tiles_f = np.zeros((P, B, tile_r, tile_c), dtype=np.int8)
        for p in range(P):
            tiles, rb, cb, rle = per_part[p]
            bp = tiles.shape[0]
            if bp:
                if tiles_f.base is not tiles:
                    tiles_f[p, :bp] = tiles
                rowb_f[p, :bp] = rb
                colb_f[p, :bp] = cb
                # transpose: bwd tile (cb,rb) = fwd tile (rb,cb)^T, cb-sorted
                o = np.argsort(cb, kind="stable")
                if rle is not None:
                    # write the transposed stack straight from the occupied-
                    # cell RLE: O(occupied) scatter vs fancy-indexing +
                    # assigning a strided transpose of the whole stack
                    uc, c8 = rle
                    t = uc // area
                    r = (uc % area) // tile_c
                    c = uc % tile_c
                    pos_b = np.empty(bp, dtype=np.int64)
                    pos_b[o] = np.arange(bp)
                    tiles_b[p].reshape(-1)[pos_b[t] * area + c * tile_r
                                           + r] = c8
                else:
                    tiles_b[p, :bp] = tiles[o].transpose(0, 2, 1)
                rowb_b[p, :bp] = cb[o]
                colb_b[p, :bp] = rb[o]
            # release this part's stack as soon as it's copied (the P==1
            # alias survives through tiles_f.base)
            per_part[p] = None

    tiles_f = None
    rowb_f = np.full((P, B), n_rb_f, dtype=np.int32)
    colb_f = np.zeros((P, B), dtype=np.int32)
    tiles_b = np.zeros((P, B, tile_c, tile_r), dtype=np.int8)
    rowb_b = np.full((P, B), n_rb_b, dtype=np.int32)
    colb_b = np.zeros((P, B), dtype=np.int32)
    if layout_fastpath():
        # residual ELL FIRST, while the per-part stacks are the only live
        # multi-GB objects: with the assembled fwd+bwd stacks also resident
        # the same build measures ~5x slower on a 1-vCPU host (page-table /
        # TLB pressure from the extra GBs dominates its random gathers)
        ell_fwd, ell_bwd, ell_arrays = build_residual()
        build_stacks()
    else:
        build_stacks()
        ell_fwd, ell_bwd, ell_arrays = build_residual()

    arrays = {
        "blk_tiles_fwd": tiles_f, "blk_rowb_fwd": rowb_f,
        "blk_colb_fwd": colb_f,
        "blk_tiles_bwd": tiles_b, "blk_rowb_bwd": rowb_b,
        "blk_colb_bwd": colb_b,
        "blk_perm_ext": perm_ext.astype(np.int32),
        "blk_perm_inner": perm_inner.astype(np.int32),
    }
    for k, v in ell_arrays.items():
        arrays[f"res_{k}"] = v

    fwd = BlockSpec(n_rows=n_dst, n_src=n_src_ext, row_tile=tile_r,
                    col_tile=tile_c, n_blocks=B, n_row_blocks=n_rb_f,
                    max_row_dense=mrd_f)
    bwd = BlockSpec(n_rows=n_src_ext, n_src=n_dst, row_tile=tile_c,
                    col_tile=tile_r, n_blocks=B, n_row_blocks=n_rb_b,
                    max_row_dense=mrd_b)
    return fwd, bwd, (ell_fwd, ell_bwd), arrays


def _compact_rank_perm(perm_full: np.ndarray, mask: np.ndarray,
                       n_pad: int) -> np.ndarray:
    """Cluster positions for a compact row subset: compact row c (the c-th
    True of `mask` in ascending original id) takes the RANK of its full
    cluster position among the subset — the split layouts inherit the full
    build's locality without re-clustering. Padded compact slots fill the
    remaining positions (each position used exactly once)."""
    rows = np.nonzero(mask)[0]
    vals = perm_full[rows]
    if layout_fastpath():
        # rank of each subset value = count of smaller subset values: one
        # presence mask + cumsum over the full space, O(N) vs the argsort's
        # O(S log S) — identical ranks (the values are distinct)
        present = np.zeros(len(perm_full), dtype=bool)
        present[vals] = True
        rank = (np.cumsum(present) - 1)[vals]
    else:
        order = np.argsort(vals, kind="stable")
        rank = np.empty(len(rows), dtype=np.int64)
        rank[order] = np.arange(len(rows))
    out = np.empty(n_pad, dtype=np.int64)
    out[:len(rows)] = rank
    out[len(rows):] = np.arange(len(rows), n_pad)
    return out


def build_split_block_layouts(src_all, dst_all, n_dst, n_src_ext, perm_inner,
                              perm_ext, occupancy_min=512,
                              tile_budget_bytes=2 << 30,
                              tile_r=TR, tile_c=TC):
    """Interior/frontier row-partitioned hybrid layouts (--overlap split).

    Same row split as ops/ell.build_split_layouts — interior rows (no halo
    in-neighbor) aggregate from the owned rows alone, frontier rows from the
    extended space — realized as two complete hybrid builds (dense MXU tiles
    + ELL residual each): the interior build's dense tiles are what the XLA
    scheduler overlaps with the halo collective. Dense-tile coverage is
    preserved because the compact row orders keep the full build's cluster
    locality (_compact_rank_perm).

    Returns ((int_fwd, int_bwd, int_ell_pair), (fro_fwd, fro_bwd,
    fro_ell_pair), arrays, n_int_pad, n_fro_pad); arrays holds the two
    builds' tables under 'int_*'/'fro_*' prefixes plus 'merge_perm'
    [P, n_dst] int32 (recombination back to original row order)."""
    from bnsgcn_tpu.ops.spmm import split_row_partition
    P = src_all.shape[0]
    masks, merge_perm, (si, di, n_int_pad), (sf, df, n_fro_pad) = \
        split_row_partition(src_all, dst_all, n_dst)
    pi_int = np.stack([_compact_rank_perm(perm_inner[p], ~masks[p],
                                          n_int_pad) for p in range(P)])
    pi_fro = np.stack([_compact_rank_perm(perm_inner[p], masks[p],
                                          n_fro_pad) for p in range(P)])
    # interior gathers from the owned row space (cols perm = the full inner
    # cluster order); frontier gathers from the full extended space
    (int_build, fro_build) = run_parallel([
        partial(build_block_layouts, si, di, n_int_pad, n_dst,
                pi_int, perm_inner, occupancy_min=occupancy_min,
                tile_budget_bytes=tile_budget_bytes,
                tile_r=tile_r, tile_c=tile_c),
        partial(build_block_layouts, sf, df, n_fro_pad, n_src_ext,
                pi_fro, perm_ext, occupancy_min=occupancy_min,
                tile_budget_bytes=tile_budget_bytes,
                tile_r=tile_r, tile_c=tile_c)])
    int_f, int_b, int_pair, int_arr = int_build
    fro_f, fro_b, fro_pair, fro_arr = fro_build
    arrays = {"merge_perm": merge_perm}
    arrays.update({f"int_{k}": v for k, v in int_arr.items()})
    arrays.update({f"fro_{k}": v for k, v in fro_arr.items()})
    return ((int_f, int_b, int_pair), (fro_f, fro_b, fro_pair),
            arrays, n_int_pad, n_fro_pad)


def dense_edge_count(arrays, part: int = 0) -> int:
    """Diagnostic: number of edges carried by the dense tiles of one part.

    Layout-shape agnostic: the unified layout stores a bare
    `blk_tiles_fwd`; the split-overlap layout prefixes its two stacks
    (`int_blk_tiles_fwd` + `fro_blk_tiles_fwd`); and a side whose
    occupancy filter kept zero dense tiles omits its key entirely.
    Summing whichever keys exist covers all three (a fully-ELL layout
    counts 0 dense edges)."""
    total = 0
    for key in ("blk_tiles_fwd", "int_blk_tiles_fwd", "fro_blk_tiles_fwd"):
        tiles = arrays.get(key)
        if tiles is not None:
            total += int(np.asarray(tiles[part]).astype(np.int64).sum())
    return total


def build_x_slabs(spec: BlockSpec, perm_src, h):
    """X in cluster order, sliced into [n_cb, col_tile, H] slabs — shared by
    the XLA and Pallas dense paths so pad/permutation handling cannot drift."""
    H = h.shape[1]
    n_cb = (spec.n_src + spec.col_tile - 1) // spec.col_tile
    pad_src = n_cb * spec.col_tile
    # inv_src[pos] = original id at cluster position pos (pad -> zero row)
    inv_src = jnp.full((pad_src,), spec.n_src, jnp.int32).at[perm_src].set(
        jnp.arange(spec.n_src, dtype=jnp.int32))
    hp = jnp.concatenate([h, jnp.zeros((1, H), h.dtype)], 0)
    return hp[inv_src].reshape(n_cb, spec.col_tile, H)


def _tile_chunk_for(n_blocks: int, row_tile: int, width: int,
                    budget_bytes: int = 768 << 20,
                    col_tile: int = 0) -> int:
    """Tiles per scan chunk so the f32 per-tile partial product stays under
    `budget_bytes`. Without chunking, [B, TR, H] f32 partials at bench scale
    (B=8192, H=602 in the use_pp precompute) are 9.5 GB of HLO temp — over
    a v5e's 16 GB HBM (observed OOM at jit(precompute)). The budget trades
    peak temp against accumulator re-traffic: each scan iteration re-reads
    and re-writes the [n_row_blocks+1, TR, H] carry (~120 MB at H=256), so
    fewer/larger chunks cost less HBM bandwidth — 768 MB keeps the
    width-602 precompute near 2 GB of live temps and the H=256 train step
    at ~6 chunks (~1.4 GB of carry traffic per pass instead of ~3.8 GB)."""
    per_tile = row_tile * width * 4
    # the int8 path (col_tile > 0) adds per-chunk quantization temps on top
    # of the f32 partial: xc [C, TC, H] f32 + qc [C, TC, H] int8 — without
    # this the budget understates int8 peak temps ~3x (round-4 OOM class)
    if col_tile:
        per_tile += col_tile * width * 5
    c = max(64, budget_bytes // per_tile)
    return int(min(n_blocks, c))


def _dense_apply(spec: BlockSpec, tiles, rowb, colb, perm_src, perm_out, h,
                 dense_dtype: str = "native"):
    """Dense-tile aggregation; returns [n_rows, H] in ORIGINAL row order.

    dense_dtype='int8' quantizes each [TC, H] activation slab to int8 with
    one scale (symmetric, amax/127) and runs the tile matmul fully in int8
    (the tiles are int8 edge multiplicities already): the v5e MXU moves
    int8 at ~2x the bf16 rate, the bf16 tile conversion disappears, and
    slab HBM traffic halves. The per-slab scale is finer than the fp8
    gather path's per-call scale; sums over ~10^2-edge rows average the
    rounding error out. Guarded end-to-end by the bench loss gates.

    The tile stack is processed in `lax.scan` chunks (bounded [C, TR, H]
    partials + one [n_row_blocks+1, TR, H] accumulator) instead of one
    [B, TR, H] einsum, keeping HLO temps flat in B; rowb is sorted, so
    per-chunk segment ids stay sorted."""
    H = h.shape[1]
    B = tiles.shape[0]
    x_perm = build_x_slabs(spec, perm_src, h)
    if dense_dtype == "int8":
        # per-slab scales from the input-dtype amax (bf16 values are exact
        # in f32, so this equals the old full-f32 amax); quantization runs
        # chunk-wise inside the scan body — the old whole-stack
        # `x_perm.astype(f32)` copy OOM'd the v5e HBM at the width-602
        # use_pp precompute (round-4 measured RESOURCE_EXHAUSTED)
        scale = jnp.maximum(
            jnp.max(jnp.abs(x_perm), axis=(1, 2)).astype(jnp.float32) / 127.0,
            1e-30)                                         # [n_cb]

        def chunk_prod(tiles_c, colb_c):
            xc = x_perm[colb_c].astype(jnp.float32)
            qc = jnp.clip(jnp.round(xc / scale[colb_c][:, None, None]),
                          -127, 127).astype(jnp.int8)
            p = jnp.einsum("brc,bch->brh", tiles_c, qc,
                           preferred_element_type=jnp.int32)
            return p.astype(jnp.float32) * scale[colb_c][:, None, None]
    else:
        def chunk_prod(tiles_c, colb_c):
            return jnp.einsum("brc,bch->brh", tiles_c.astype(h.dtype),
                              x_perm[colb_c],
                              preferred_element_type=jnp.float32)

    n_seg = spec.n_row_blocks + 1
    C = _tile_chunk_for(B, spec.row_tile, H,
                        col_tile=(spec.col_tile
                                  if dense_dtype == "int8" else 0))
    n_full = B // C                       # >= 1: C = min(B, ...) above
    rem = B - n_full * C

    def body(acc, x):
        tiles_c, rowb_c, colb_c = x
        s = jax.ops.segment_sum(chunk_prod(tiles_c, colb_c), rowb_c,
                                num_segments=n_seg,
                                indices_are_sorted=True)
        return acc + s, None

    # full chunks go through the scan as a prefix-slice + reshape (both
    # copy-free in XLA); the B%C remainder runs as ONE extra, smaller
    # segment-sum below instead of zero-padding the whole tile stack —
    # the old pad-concatenate materialized a transient copy of the stack
    # (~2 GB at bench scale) inside jit whenever B wasn't a chunk multiple
    xs = (tiles[:n_full * C].reshape(n_full, C, *tiles.shape[1:]),
          rowb[:n_full * C].reshape(n_full, C),
          colb[:n_full * C].reshape(n_full, C))

    # derive the init carry from the input so it carries the same varying
    # manual axes as the body output under shard_map (scan rejects an
    # unvarying zeros init against a parts-varying accumulator); the empty
    # slice reads no data, so a non-finite activation cannot leak NaN here
    acc0 = jnp.zeros((n_seg, spec.row_tile, H), jnp.float32) \
        + jnp.sum(x_perm[:0]).astype(jnp.float32)
    seg, _ = jax.lax.scan(body, acc0, xs)
    if rem:
        seg = seg + jax.ops.segment_sum(
            chunk_prod(tiles[n_full * C:], colb[n_full * C:]),
            rowb[n_full * C:], num_segments=n_seg, indices_are_sorted=True)
    seg = seg[:spec.n_row_blocks]
    flat = seg.reshape(spec.n_row_blocks * spec.row_tile, H).astype(h.dtype)
    return flat[perm_out]                                  # original row order


def make_block_spmm(fwd: BlockSpec, bwd: BlockSpec, ell_pair,
                    use_pallas: bool = False, gather_dtype: str = "native",
                    dense_dtype: str = "native", accum: str = "auto"):
    """Returns spmm(arrays, h_ext) -> [n_dst, H]: dense tiles on the MXU +
    ELL residual, custom VJP running the transposed tiles.
    dense_dtype='int8': quantized int8 MXU tile path — per-slab scales on
    the XLA formulation (_dense_apply), one per-call scale on the fused
    Pallas kernel (pallas_block.dense_apply_pallas).
    accum: residual-ELL accumulation strategy (ops/ell._bucket_sum)."""
    ell_fwd, ell_bwd = ell_pair
    ell = make_ell_spmm(ell_fwd, ell_bwd, len(ell_fwd.widths),
                        len(ell_bwd.widths), use_pallas=use_pallas,
                        gather_dtype=gather_dtype, accum=accum)
    # transposed residual operator for the backward: same tables with the
    # fwd/bwd roles swapped (a nested vjp at a dummy point would record an
    # unvarying primal and trip shard_map's varying-axes check)
    ell_t = make_ell_spmm(ell_bwd, ell_fwd, len(ell_bwd.widths),
                          len(ell_fwd.widths), use_pallas=use_pallas,
                          gather_dtype=gather_dtype, accum=accum)

    def _res_arrays(arrays):
        return {k[len("res_"):]: v for k, v in arrays.items()
                if k.startswith("res_")}

    # int8 Pallas accumulator bound: the fused kernel keeps exact int32 row
    # sums of |q|<=127 x |mult|<=127 products, so a row with more than
    # int32_max/(127*127) ~= 133k dense edges could silently wrap. The max
    # per-row dense edge count is static in the layout (max_row_dense;
    # getattr for layouts cached before the field existed -> 0 = unknown,
    # guard skipped). Overflow-risk rows route to the XLA path, whose int8
    # formulation rescales to f32 per chunk (no wrap possible).
    _I8_ROW_CAP = (2**31 - 1) // (127 * 127)

    def _i8_pallas_safe(spec_d):
        return getattr(spec_d, "max_row_dense", 0) <= _I8_ROW_CAP

    def _dense(spec_d, arrays, tiles_key, rowb_key, colb_key, perm_src_key,
               perm_out_key, h):
        # Pallas fused grouped-matmul on TPU (use_pallas); XLA path elsewhere
        if (use_pallas and jax.default_backend() == "tpu"
                and (dense_dtype != "int8" or _i8_pallas_safe(spec_d))):
            from bnsgcn_tpu.ops.pallas_block import dense_apply_pallas
            return dense_apply_pallas(
                spec_d, arrays[tiles_key], arrays[rowb_key], arrays[colb_key],
                arrays[perm_src_key], arrays[perm_out_key], h,
                dense_dtype=dense_dtype)
        return _dense_apply(spec_d, arrays[tiles_key], arrays[rowb_key],
                            arrays[colb_key], arrays[perm_src_key],
                            arrays[perm_out_key], h, dense_dtype=dense_dtype)

    def _swap_dirs(arrays):
        out = {}
        for k, v in arrays.items():
            if k.startswith("fwd_"):
                out["bwd_" + k[4:]] = v
            elif k.startswith("bwd_"):
                out["fwd_" + k[4:]] = v
            else:
                out[k] = v
        return out

    @jax.custom_vjp
    def spmm(arrays, h_ext):
        dense = _dense(fwd, arrays, "blk_tiles_fwd", "blk_rowb_fwd",
                       "blk_colb_fwd", "blk_perm_ext", "blk_perm_inner",
                       h_ext)
        return dense + ell(_res_arrays(arrays), h_ext)

    def fwd_rule(arrays, h_ext):
        return spmm(arrays, h_ext), (arrays,)

    def bwd_rule(res, g):
        (arrays,) = res
        d_dense = _dense(bwd, arrays, "blk_tiles_bwd", "blk_rowb_bwd",
                         "blk_colb_bwd", "blk_perm_inner", "blk_perm_ext", g)
        d_res = ell_t(_swap_dirs(_res_arrays(arrays)), g)
        return None, (d_dense + d_res).astype(g.dtype)

    spmm.defvjp(fwd_rule, bwd_rule)
    return spmm


def cluster_order(src, dst, n_rows, n_ext, target=TC
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Locality permutation of the (inner, extended) row spaces.

    Inner rows: clustered by the native partitioner (LDG streaming + light
    refinement) into ~n_rows/target balanced groups, ordered group-major —
    structural clustering, no labels involved. Halo rows keep their slot
    order (already grouped by owning peer). Returns (perm_inner [n_rows],
    perm_ext [n_ext]): each row's position in cluster order; the inner
    prefix of perm_ext equals perm_inner."""
    n_clusters = max(int(np.ceil(n_rows / max(target, 1))), 1)
    order = None
    src = np.asarray(src)
    dst = np.asarray(dst)
    inner = (src < n_rows) & (dst < n_rows)
    if n_clusters > 1 and inner.any():
        try:
            from bnsgcn_tpu.native import native_partition

            class _G:                       # minimal adapter for the binding
                pass

            gg = _G()
            gg.src = src[inner].astype(np.int64)
            gg.dst = dst[inner].astype(np.int64)
            gg.n_nodes = n_rows
            cid = native_partition(gg, n_clusters, obj="cut",
                                   seed=0, refine_passes=2, n_seeds=1)
            if cid is not None:
                order = np.argsort(cid, kind="stable")
        except Exception:
            order = None
    if order is None:
        order = np.arange(n_rows)
    perm_inner = np.empty(n_rows, dtype=np.int64)
    perm_inner[order] = np.arange(n_rows)
    perm_ext = np.concatenate([perm_inner,
                               np.arange(n_rows, n_ext, dtype=np.int64)])
    return perm_inner, perm_ext
