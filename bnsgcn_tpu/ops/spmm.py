"""Sparse neighbor aggregation — the TPU replacement for DGL's C++/CUDA SpMM.

The reference's hottest compute is `update_all(copy_u('h'), sum('h'))`
(reference module/layer.py:35-37,88-90): for every edge (u -> v), gather h[u]
and segment-sum into v. Here that is a gather + `segment_sum` in static shape,
optionally chunked over the edge axis with `lax.scan` so the [E, H] gathered
intermediate never exceeds `edge_chunk * H` (HBM bound for 100M-edge graphs).

Padded-edge convention (shared with the partition artifacts): `dst == n_dst`
(one trash row, sliced off) and `src == 0` (value irrelevant). This module is
the pure-XLA reference implementation; a Pallas kernel path is selected by the
trainer when `Config.use_pallas` is set and the kernel module is present.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def gather_scatter_sum(h_src: jax.Array, src: jax.Array, dst: jax.Array,
                       n_dst: int, edge_chunk: int = 0) -> jax.Array:
    """sum_{e:(src_e -> dst_e)} h_src[src_e]  ->  [n_dst, H].

    `dst` may contain the value `n_dst` for padded edges; those land in a trash
    row that is dropped.

    edge_chunk > 0 bounds peak memory: edges are processed in chunks of that
    size via `lax.scan` (E must be divisible by edge_chunk; artifacts pad E
    accordingly).
    """
    n_out = n_dst + 1
    if edge_chunk and src.shape[0] > edge_chunk:
        e = src.shape[0]
        assert e % edge_chunk == 0, f"E={e} not divisible by edge_chunk={edge_chunk}"
        n_chunks = e // edge_chunk
        src_c = src.reshape(n_chunks, edge_chunk)
        dst_c = dst.reshape(n_chunks, edge_chunk)

        def body(acc, sd):
            s, d = sd
            msg = h_src[s]
            acc = acc.at[d].add(msg, mode="drop")
            return acc, None

        # derive init from h_src so it carries the same shard_map varying axes
        # (a plain jnp.zeros is 'unvarying' and trips the scan VMA check)
        init = jnp.zeros((n_out, h_src.shape[1]), dtype=h_src.dtype) + h_src[0] * 0
        out, _ = jax.lax.scan(body, init, (src_c, dst_c))
    else:
        out = jax.ops.segment_sum(h_src[src], dst, num_segments=n_out)
    return out[:n_dst]


def agg_sum(h_src, src, dst, n_dst, edge_chunk: int = 0):
    """Plain copy_u/sum aggregation (GCN/GraphSAGE numerator)."""
    return gather_scatter_sum(h_src, src, dst, n_dst, edge_chunk)


def agg_mean(h_src, src, dst, n_dst, in_deg, edge_chunk: int = 0):
    """Sum aggregation divided by a caller-provided in-degree.

    The reference's GraphSAGE mean uses the *global* in-degree stored as ndata
    before partitioning (reference helper/utils.py:92-93, train.py:380,
    module/layer.py:85-91) — NOT the degree of the sampled subgraph; that is
    what makes BNS unbiased for the mean aggregator.
    """
    s = gather_scatter_sum(h_src, src, dst, n_dst, edge_chunk)
    return s / in_deg[:, None]


def segment_softmax(scores: jax.Array, dst: jax.Array, n_dst: int,
                    mask: jax.Array | None = None) -> jax.Array:
    """Numerically-stable softmax over edges grouped by destination.

    Replaces DGL's C++ edge_softmax used by GATConv (reference
    module/model.py:102). `scores`: [E, heads]; `mask`: [E] bool — masked
    edges (absent sampled halos, padding) get zero weight.
    """
    n_out = n_dst + 1
    neg = jnp.asarray(-1e30, dtype=scores.dtype)
    s = scores if mask is None else jnp.where(mask[:, None], scores, neg)
    smax = jax.ops.segment_max(s, dst, num_segments=n_out)
    smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
    ex = jnp.exp(s - smax[dst])
    if mask is not None:
        ex = jnp.where(mask[:, None], ex, 0.0)
    denom = jax.ops.segment_sum(ex, dst, num_segments=n_out)
    denom = jnp.maximum(denom, jnp.asarray(1e-16, dtype=scores.dtype))
    return ex / denom[dst]
