"""Sparse neighbor aggregation — the TPU replacement for DGL's C++/CUDA SpMM.

The reference's hottest compute is `update_all(copy_u('h'), sum('h'))`
(reference module/layer.py:35-37,88-90): for every edge (u -> v), gather h[u]
and segment-sum into v. Here that is a gather + `segment_sum` in static shape,
optionally chunked over the edge axis with `lax.scan` so the [E, H] gathered
intermediate never exceeds `edge_chunk * H` (HBM bound for 100M-edge graphs).

Padded-edge convention (shared with the partition artifacts): `dst == n_dst`
(one trash row, sliced off) and `src == 0` (value irrelevant). This module is
the pure-XLA reference implementation; a Pallas kernel path is selected by the
trainer when `Config.use_pallas` is set and the kernel module is present.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# ----------------------------------------------------------------------------
# interior / frontier row split (offline numpy) — the --overlap split
# foundation shared by every SpMM layout family. A destination row is
# FRONTIER when at least one of its in-edges arrives from a halo slot
# (src >= n_dst in the extended index space) and INTERIOR otherwise; an
# interior row's whole aggregation is independent of the halo exchange, so
# the per-layer collective can run concurrently with it (DistGNN's
# local/remote-aggregate overlap, arXiv:2104.06700).
# ----------------------------------------------------------------------------

def frontier_mask(src: np.ndarray, dst: np.ndarray, n_dst: int) -> np.ndarray:
    """[n_dst] bool: rows with >= 1 in-edge from a halo slot. Computed from
    the FULL static edge list — BNS sampling only zeroes halo values, never
    removes edges, so the split is epoch-invariant."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    m = np.zeros(n_dst, dtype=bool)
    halo = (dst < n_dst) & (src >= n_dst)
    m[dst[halo]] = True
    return m


def _pad8(n: int) -> int:
    return max(8, ((n + 7) // 8) * 8)


def _classify_edges(s: np.ndarray, d: np.ndarray, fm: np.ndarray,
                    n_dst: int):
    """(interior_edge_mask, frontier_edge_mask) for one part's padded COO
    edges under frontier row mask `fm` (trash edges d == n_dst in neither)."""
    fmx = np.append(fm, False)
    real = d < n_dst
    is_f = real & fmx[d]
    return real & ~fmx[d], is_f


def _pack_edge_sets(sets, trash: int):
    """Stack per-part (src, dst) edge lists to [P, E_pad] int32 with the
    trash convention dst == `trash`, src == 0 — the one padding
    implementation every split family shares."""
    P = len(sets)
    e_max = _pad8(max((len(s) for s, _ in sets), default=0))
    sa = np.zeros((P, e_max), dtype=np.int32)
    da = np.full((P, e_max), trash, dtype=np.int32)
    for p, (s, d) in enumerate(sets):
        sa[p, :len(s)] = s
        da[p, :len(d)] = d
    return sa, da


def split_row_partition(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int):
    """The shared interior/frontier row split consumed by every split layout
    family (ops/ell.build_split_layouts, ops/block_spmm
    .build_split_block_layouts) — one implementation so the compact-id,
    padding and merge conventions cannot drift between them.

    Per part, destination rows are remapped to two compact row spaces
    (compact ids ascend with original id; degree-0/padded rows are
    interior). Returns (masks, merge_perm, (src_int, dst_int, n_int_pad),
    (src_fro, dst_fro, n_fro_pad)):

      * masks: per-part frontier bool [n_dst] arrays;
      * merge_perm [P, n_dst] int32: out[r] = concat(int_out [n_int_pad],
        fro_out [n_fro_pad])[merge_perm[r]] — the recombination back to
        original row order;
      * edge arrays [P, E_pad] int32 in the compact row spaces, padded to a
        common length with the trash convention dst == n_X_pad, src == 0.
        Both row spaces are floored at 8 rows so degenerate parts (zero
        interior or zero frontier anywhere) build ordinary all-padded
        tables instead of zero-size special cases.
    """
    P = src_all.shape[0]
    masks = [frontier_mask(src_all[p], dst_all[p], n_dst) for p in range(P)]
    n_int_pad = _pad8(max(int((~m).sum()) for m in masks))
    n_fro_pad = _pad8(max(int(m.sum()) for m in masks))
    merge_perm = np.zeros((P, n_dst), dtype=np.int32)
    e_int, e_fro = [], []
    for p in range(P):
        fm = masks[p]
        int_id = (np.cumsum(~fm) - 1).astype(np.int64)
        fro_id = (np.cumsum(fm) - 1).astype(np.int64)
        merge_perm[p] = np.where(fm, n_int_pad + fro_id, int_id)
        s = np.asarray(src_all[p])
        d = np.asarray(dst_all[p])
        is_i, is_f = _classify_edges(s, d, fm, n_dst)
        e_int.append((s[is_i], int_id[d[is_i]]))
        e_fro.append((s[is_f], fro_id[d[is_f]]))
    si, di = _pack_edge_sets(e_int, n_int_pad)
    sf, df = _pack_edge_sets(e_fro, n_fro_pad)
    return (masks, merge_perm, (si, di, n_int_pad), (sf, df, n_fro_pad))


def split_coo(src_all: np.ndarray, dst_all: np.ndarray, n_dst: int
              ) -> dict[str, np.ndarray]:
    """Row-partition each part's COO edges into the interior set (edges whose
    dst row has no halo in-neighbor — all such edges have src < n_dst) and
    the frontier set (ALL edges of rows with >= 1 halo in-neighbor, local
    sources included). Padded per set to a common length across parts with
    the usual trash convention (dst == n_dst, src == 0).

    Returns {'seg_int_src','seg_int_dst','seg_fro_src','seg_fro_dst'}
    stacked [P, E_pad]. Because the two sets cover disjoint OUTPUT rows, the
    recombination is an exact elementwise add of the two aggregations (dst
    ids stay in the ORIGINAL row space — no compaction, no merge perm)."""
    P = src_all.shape[0]
    ints, fros = [], []
    for p in range(P):
        s = np.asarray(src_all[p])
        d = np.asarray(dst_all[p])
        is_i, is_f = _classify_edges(s, d, frontier_mask(s, d, n_dst), n_dst)
        ints.append((s[is_i], d[is_i]))
        fros.append((s[is_f], d[is_f]))
    out = {}
    for name, sets in (("int", ints), ("fro", fros)):
        sa, da = _pack_edge_sets(sets, n_dst)
        out[f"seg_{name}_src"] = sa
        out[f"seg_{name}_dst"] = da
    return out


def gather_scatter_sum(h_src: jax.Array, src: jax.Array, dst: jax.Array,
                       n_dst: int, edge_chunk: int = 0) -> jax.Array:
    """sum_{e:(src_e -> dst_e)} h_src[src_e]  ->  [n_dst, H].

    `dst` may contain the value `n_dst` for padded edges; those land in a trash
    row that is dropped.

    edge_chunk > 0 bounds peak memory: edges are processed in chunks of that
    size via `lax.scan` (E must be divisible by edge_chunk; artifacts pad E
    accordingly).
    """
    n_out = n_dst + 1
    if edge_chunk and src.shape[0] > edge_chunk:
        e = src.shape[0]
        assert e % edge_chunk == 0, f"E={e} not divisible by edge_chunk={edge_chunk}"
        n_chunks = e // edge_chunk
        src_c = src.reshape(n_chunks, edge_chunk)
        dst_c = dst.reshape(n_chunks, edge_chunk)

        def body(acc, sd):
            s, d = sd
            msg = h_src[s]
            acc = acc.at[d].add(msg, mode="drop")
            return acc, None

        # derive init from h_src so it carries the same shard_map varying axes
        # (a plain jnp.zeros is 'unvarying' and trips the scan VMA check)
        init = jnp.zeros((n_out, h_src.shape[1]), dtype=h_src.dtype) + h_src[0] * 0
        out, _ = jax.lax.scan(body, init, (src_c, dst_c))
    else:
        out = jax.ops.segment_sum(h_src[src], dst, num_segments=n_out)
    return out[:n_dst]


def agg_sum(h_src, src, dst, n_dst, edge_chunk: int = 0):
    """Plain copy_u/sum aggregation (GCN/GraphSAGE numerator)."""
    return gather_scatter_sum(h_src, src, dst, n_dst, edge_chunk)


def agg_mean(h_src, src, dst, n_dst, in_deg, edge_chunk: int = 0):
    """Sum aggregation divided by a caller-provided in-degree.

    The reference's GraphSAGE mean uses the *global* in-degree stored as ndata
    before partitioning (reference helper/utils.py:92-93, train.py:380,
    module/layer.py:85-91) — NOT the degree of the sampled subgraph; that is
    what makes BNS unbiased for the mean aggregator.
    """
    s = gather_scatter_sum(h_src, src, dst, n_dst, edge_chunk)
    return s / in_deg[:, None]


def segment_softmax(scores: jax.Array, dst: jax.Array, n_dst: int,
                    mask: jax.Array | None = None) -> jax.Array:
    """Numerically-stable softmax over edges grouped by destination.

    Replaces DGL's C++ edge_softmax used by GATConv (reference
    module/model.py:102). `scores`: [E, heads]; `mask`: [E] bool — masked
    edges (absent sampled halos, padding) get zero weight.
    """
    n_out = n_dst + 1
    neg = jnp.asarray(-1e30, dtype=scores.dtype)
    s = scores if mask is None else jnp.where(mask[:, None], scores, neg)
    smax = jax.ops.segment_max(s, dst, num_segments=n_out)
    smax = jnp.where(jnp.isfinite(smax), smax, 0.0)
    ex = jnp.exp(s - smax[dst])
    if mask is not None:
        ex = jnp.where(mask[:, None], ex, 0.0)
    denom = jax.ops.segment_sum(ex, dst, num_segments=n_out)
    denom = jnp.maximum(denom, jnp.asarray(1e-16, dtype=scores.dtype))
    return ex / denom[dst]
