"""Pallas grouped-matmul kernel for the hybrid SpMM's dense tiles.

The XLA formulation (ops/block_spmm._dense_apply) materializes the slab
gather [B, TC, H] and the per-tile partial products [B, TR, H] f32 in HBM
before the segment-sum. This kernel fuses all three: a standard block
pipeline (NO manual DMA — this environment's remote compiler rejects
make_async_copy kernels, see tools/pallas_spmm.py) over grid=(B,) where

  * the adjacency tile [TR, TC] int8 streams in per step,
  * the X slab block index comes from the scalar-prefetched colb table
    (PrefetchScalarGridSpec — the megablocks/gmm pattern),
  * the output block index comes from rowb; tiles are rowb-sorted, so
    revisited output blocks stay resident and accumulate in VMEM, zeroed on
    first visit.

Per pass this reads tiles once + one slab per tile at pipeline DMA rates and
writes each output row-block once — no [B, TR, H] partials, no segment-sum.

Correctness is pinned against the XLA path in tests (interpret mode off-TPU).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(rowb_ref, colb_ref, a_ref, x_ref, o_ref):
    b = pl.program_id(0)
    first = b == 0
    changed = rowb_ref[b] != rowb_ref[jnp.maximum(b, 1) - 1]

    @pl.when(jnp.logical_or(first, changed))
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    # int8 slabs: int8 multiplicity tiles x int8 activations -> int32 on
    # the MXU (~2x the bf16 rate, exact integer accumulation across tiles;
    # the caller's one per-call scale multiplies back outside). Float
    # slabs: tiles convert to the slab dtype, f32 accumulation.
    a = a_ref[0].astype(x_ref.dtype)
    o_ref[...] += jax.lax.dot_general(
        a, x_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=o_ref.dtype)[None]


def pallas_tile_matmul(tiles: jax.Array, rowb: jax.Array, colb: jax.Array,
                       x_slabs: jax.Array, n_row_blocks: int,
                       interpret: bool = False) -> jax.Array:
    """tiles [B, TR, TC] int8, rowb/colb [B] int32 (rowb sorted ascending,
    pads = n_row_blocks), x_slabs [n_cb, TC, H] -> out [n_row_blocks+1, TR, H]
    (f32 for float slabs; RAW int32 accumulator for int8 slabs — the caller
    owns the dequant scale; last block is the pad-tile trash; caller
    slices it off).

    Row blocks NO tile maps to are never written by the kernel — on hardware
    Pallas out buffers are uninitialized, so the CALLER must mask them
    (dense_apply_pallas does, via the statically-known visited set)."""
    B, TR, TC = tiles.shape
    H = x_slabs.shape[-1]
    out_dtype = jnp.int32 if x_slabs.dtype == jnp.int8 else jnp.float32
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, TR, TC), lambda b, rowb, colb: (b, 0, 0)),
            pl.BlockSpec((1, TC, H), lambda b, rowb, colb: (colb[b], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, TR, H), lambda b, rowb, colb: (rowb[b], 0, 0)),
    )
    try:
        # under shard_map with check_vma the out aval must carry the same
        # varying-mesh-axes set as the input (see tools/pallas_spmm.py)
        out_shape = jax.ShapeDtypeStruct((n_row_blocks + 1, TR, H),
                                         out_dtype,
                                         vma=jax.typeof(x_slabs).vma)
    except (AttributeError, TypeError):
        out_shape = jax.ShapeDtypeStruct((n_row_blocks + 1, TR, H),
                                         out_dtype)
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(rowb, colb, tiles, x_slabs)


def dense_apply_pallas(spec, tiles, rowb, colb, perm_src, perm_out, h,
                       dense_dtype: str = "native",
                       interpret: bool = False):
    """Drop-in for ops/block_spmm._dense_apply running the fused kernel.

    dense_dtype='int8': slabs quantize to int8 with ONE per-call symmetric
    scale (amax/127) and the kernel runs int8 x int8 -> int32 on the MXU —
    exact integer accumulation across tiles, so only the quantization
    itself loses precision; the scale multiplies back here (linear,
    exact). Coarser than the XLA path's per-slab scales but scale-free
    inside the kernel. Overflow bound: |row sum| <= 127 * 127 * row's
    dense-tile degree — safe below ~1.3e5 (the bench graph's hubs are
    well under; a multiplicity-127 hub at that degree is pathological).

    Unvisited output row-blocks hold uninitialized memory on hardware; they
    are zeroed here with a mask derived from rowb (visited row-blocks), which
    is cheap and fuses into the final permutation gather."""
    from bnsgcn_tpu.ops.block_spmm import build_x_slabs
    H = h.shape[1]
    x_slabs = build_x_slabs(spec, perm_src, h)
    scale = None
    if dense_dtype == "int8":
        scale = jnp.maximum(
            jnp.max(jnp.abs(x_slabs)).astype(jnp.float32) / 127.0, 1e-30)
        x_slabs = jnp.clip(
            jnp.round(x_slabs.astype(jnp.float32) / scale),
            -127, 127).astype(jnp.int8)
    out = pallas_tile_matmul(tiles, rowb, colb, x_slabs, spec.n_row_blocks,
                             interpret=interpret)
    visited = jnp.zeros((spec.n_row_blocks + 1,), bool).at[rowb].set(True)
    out = jnp.where(visited[:, None, None], out, 0)
    if scale is not None:
        out = out.astype(jnp.float32) * scale
    flat = out[:spec.n_row_blocks].reshape(
        spec.n_row_blocks * spec.row_tile, H).astype(h.dtype)
    return flat[perm_out]
