from bnsgcn_tpu.models.gnn import ModelSpec, GraphEnv, init_params, apply_model, spec_from_config
