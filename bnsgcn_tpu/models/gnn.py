"""GCN / GraphSAGE / GAT as pure functions over explicit parameter pytrees.

Semantics mirror the reference model layer-for-layer (module/model.py,
module/layer.py, module/sync_bn.py) but the implementation is JAX-native:
aggregation is gather+segment_sum (ops/spmm.py), the halo exchange is injected
via `GraphEnv.exchange` (a shard_map collective in distributed training, the
identity on a single device), and cross-partition BatchNorm moments travel by
`lax.psum` instead of a custom autograd.Function.

Reference math preserved exactly:
  * GCN train: h/out_norm -> copy_u/sum -> /in_norm -> linear
    (module/layer.py:26-46); eval recomputes norms as sqrt(graph degrees).
  * GraphSAGE: linear1(h_self) + linear2(sum(h_nbr)/in_deg) with the *global*
    in-degree (module/layer.py:79-103, train.py:380); use_pp layer 0 is a
    single Linear(2*in, out) over the precomputed [feat, mean_nbr] concat.
  * GAT: DGL-GATConv equivalent (shared fc, additive attention, leaky_relu 0.2,
    edge softmax, feat/attn dropout, bias), mean over heads
    (module/model.py:102,111-132). Absent sampled halos are removed from the
    softmax by an edge mask — the static-shape replacement for the reference's
    per-epoch bipartite graph rebuild (train.py:256-281).
  * layer stack: dropout -> exchange -> layer -> norm -> activation with
    `n_linear` dense tail layers (module/model.py:42-58).
  * SyncBatchNorm: moments summed over all real local rows, psum'd across
    parts, normalized by whole_size = global n_train (module/sync_bn.py:15-22).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from bnsgcn_tpu.ops.spmm import agg_sum, segment_softmax
from bnsgcn_tpu.config import Config
from bnsgcn_tpu.parallel.feat import feat_shardable


@dataclass(frozen=True)
class ModelSpec:
    model: str                         # 'gcn' | 'graphsage' | 'gat'
    layer_sizes: tuple[int, ...]       # (n_feat, hidden, ..., n_class)
    n_linear: int = 0
    norm: Optional[str] = "layer"
    dropout: float = 0.5
    use_pp: bool = False
    heads: int = 1
    train_size: int = 0                # global n_train, for SyncBN whole_size

    @property
    def n_layers(self) -> int:
        return len(self.layer_sizes) - 1

    @property
    def n_graph_layers(self) -> int:
        return self.n_layers - self.n_linear


def spec_from_config(cfg: Config) -> ModelSpec:
    # GAT is always use_pp in the reference trainer (train.py:222)
    use_pp = True if cfg.model == "gat" else cfg.use_pp
    return ModelSpec(
        model=cfg.model,
        layer_sizes=tuple(cfg.layer_sizes()),
        n_linear=cfg.n_linear,
        norm=cfg.norm,
        dropout=cfg.dropout,
        use_pp=use_pp,
        heads=cfg.heads,
        train_size=cfg.n_train,
    )


@dataclass
class GraphEnv:
    """Everything a forward pass needs to know about the (local) graph.

    Index space: edge endpoints index the *extended* node array
    [inner nodes ; halo slots]; `dst` always lands in [0, n_dst] where n_dst is
    the inner count (dst == n_dst is the padded-edge trash row).
    """
    src: Optional[jax.Array]           # [E] int32, extended index space (None when the
    dst: Optional[jax.Array]           # ELL aggregate owns the graph structure)
    n_dst: int
    in_norm: jax.Array                 # [n_dst] float — GCN: sqrt(in_deg); SAGE: in_deg
    out_norm: Optional[jax.Array]      # [n_src_ext] float — GCN: sqrt(out_deg) incl. halos
    exchange: Callable[[int, jax.Array], tuple[jax.Array, Optional[jax.Array]]]
    # exchange(layer, h[n_dst, d]) -> (h_ext [n_src_ext, d], presence [n_src_ext] bool|None)
    #
    # Contract: the halo tail of h_ext need NOT come from a live collective
    # this step — it only has to be zero wherever presence is False, so
    # sum-aggregation skips absent slots and the GAT softmax masks them.
    # Besides the per-epoch halo_apply, trainer.py injects: the
    # --halo-refresh cached step (this epoch's refreshed chunk live, every
    # other row a stop-gradient cached block from an earlier epoch, presence
    # merged accordingly) and --halo-mode grad-only (all-zero halo tail,
    # presence False on every halo slot — aggregation over local rows only).
    gat_feat0: Optional[tuple[jax.Array, Optional[jax.Array]]] = None
    training: bool = True
    rng: Optional[jax.Array] = None
    edge_chunk: int = 0
    axis_name: Optional[str] = None    # mesh axis for SyncBN psum
    inner_mask: Optional[jax.Array] = None  # [n_dst] bool, real (non-padded) rows
    aggregate: Optional[Callable] = None
    # aggregate(h_ext [n_src_ext, d]) -> [n_dst, d]: scatter-free ELL SpMM
    # (ops/ell.py) when set; falls back to segment_sum otherwise
    gat_ell: Optional[tuple] = None
    # (GatEllSpec, arrays dict): dense per-row GAT attention over the ELL
    # layout (ops/ell_attention.py) when set; segment softmax otherwise
    remat: bool = False                # jax.checkpoint each layer (HBM for FLOPs+comm)
    replica_axis: Optional[str] = None # 2-D ('replicas','parts') mesh: SyncBN
    n_replicas: int = 1                # moments mean over replicas too (one
                                       # fused psum over both axes, divided by
                                       # whole_size * n_replicas — each replica
                                       # sees the whole graph). None/1 = the
                                       # historical parts-only reduction.
    agg_exchange: Optional[Callable] = None
    # agg_exchange(layer, h [n_dst, d], scale_out_norm) -> [n_dst, d]:
    # fused exchange + sum-aggregation override (--overlap split re-threads
    # the layer body as start-exchange -> interior-agg -> finish-exchange ->
    # frontier-agg -> merge through this seam). None = the historical
    # exchange-then-aggregate path. Under --halo-refresh the cached step
    # threads the same split body through the ~K-x-smaller partial-refresh
    # exchange and merges stored halo rows after halo_finish — a cache-hit
    # epoch's "collective" is tiny, so the split is near-pure compute.
    feat_axis: Optional[str] = None    # 3-D ('replicas','parts','feat') mesh
    n_feat_shards: int = 1             # (parallel/feat.py): shardable layers
                                       # run exchange+SpMM on an H/T column
                                       # slice and psum the weight-shard
                                       # partials over 'feat' (one collective
                                       # per layer). None/1 = the historical
                                       # full-width bodies, bit-identical.


def env_agg_sum(env: "GraphEnv", h_ext: jax.Array) -> jax.Array:
    """sum_{e:(u->v)} h_ext[u] at v via the env's preferred SpMM backend."""
    if env.aggregate is not None:
        return env.aggregate(h_ext)
    return agg_sum(h_ext, env.src, env.dst, env.n_dst, env.edge_chunk)


def env_agg_exchange(env: "GraphEnv", i: int, h: jax.Array,
                     scale_out_norm: bool = False) -> jax.Array:
    """One layer's exchange + sum-aggregation: h [n_dst, d] -> [n_dst, d].

    `scale_out_norm` divides the extended rows by env.out_norm BEFORE
    aggregating (the GCN symmetric norm, module/layer.py:26-46). Default
    path is the historical fused exchange-then-aggregate, op for op; when
    `env.agg_exchange` is set (--overlap split), it runs the interior/
    frontier split so the collective overlaps interior compute."""
    if env.agg_exchange is not None:
        return env.agg_exchange(i, h, scale_out_norm)
    h_ext, _ = env.exchange(i, h)
    if scale_out_norm:
        h_ext = (h_ext / env.out_norm[:, None]).astype(h_ext.dtype)
    return env_agg_sum(env, h_ext)


# ----------------------------------------------------------------------------
# init
# ----------------------------------------------------------------------------

def _uniform(key, shape, bound, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def _linear_init(key, fan_in, fan_out, dtype=jnp.float32):
    """uniform(-1/sqrt(fan_in), +1/sqrt(fan_in)) for W and b — the reference's
    reset_parameters (module/layer.py:20-24) and torch.nn.Linear default."""
    kw, kb = jax.random.split(key)
    bound = 1.0 / (fan_in ** 0.5)
    return {"w": _uniform(kw, (fan_in, fan_out), bound, dtype),
            "b": _uniform(kb, (fan_out,), bound, dtype)}


def _xavier_normal(key, shape, fan_in, fan_out, gain, dtype=jnp.float32):
    std = gain * (2.0 / (fan_in + fan_out)) ** 0.5
    return std * jax.random.normal(key, shape, dtype)


def init_params(key: jax.Array, spec: ModelSpec, dtype=jnp.float32):
    """Returns (params, state). `state` holds SyncBN running stats."""
    params: dict[str, Any] = {}
    state: dict[str, Any] = {}
    keys = jax.random.split(key, spec.n_layers)
    for i in range(spec.n_layers):
        fin, fout = spec.layer_sizes[i], spec.layer_sizes[i + 1]
        name = f"layer_{i}"
        if i >= spec.n_graph_layers:                    # dense tail
            params[name] = _linear_init(keys[i], fin, fout, dtype)
        elif spec.model == "gcn":
            params[name] = _linear_init(keys[i], fin, fout, dtype)
        elif spec.model == "graphsage":
            if spec.use_pp and i == 0:
                # precompute doubles layer-0 input width (module/layer.py:59)
                params[name] = _linear_init(keys[i], 2 * fin, fout, dtype)
            else:
                k1, k2 = jax.random.split(keys[i])
                params[name] = {"linear1": _linear_init(k1, fin, fout, dtype),
                                "linear2": _linear_init(k2, fin, fout, dtype)}
        elif spec.model == "gat":
            kf, kl, kr = jax.random.split(keys[i], 3)
            h = spec.heads
            params[name] = {
                "w": _xavier_normal(kf, (fin, h * fout), fin, h * fout, 2.0 ** 0.5, dtype),
                "attn_l": _xavier_normal(kl, (h, fout), fout, 1, 2.0 ** 0.5, dtype),
                "attn_r": _xavier_normal(kr, (h, fout), fout, 1, 2.0 ** 0.5, dtype),
                "bias": jnp.zeros((h * fout,), dtype),
            }
        else:
            raise ValueError(spec.model)
        if i < spec.n_layers - 1 and spec.norm is not None:
            if spec.norm == "layer":
                params[f"norm_{i}"] = {"scale": jnp.ones((fout,), dtype),
                                       "bias": jnp.zeros((fout,), dtype)}
            elif spec.norm == "batch":
                params[f"norm_{i}"] = {"scale": jnp.ones((fout,), dtype),
                                       "bias": jnp.zeros((fout,), dtype)}
                state[f"norm_{i}"] = {"mean": jnp.zeros((fout,), jnp.float32),
                                      "var": jnp.ones((fout,), jnp.float32)}
    return params, state


# ----------------------------------------------------------------------------
# building blocks
# ----------------------------------------------------------------------------

def _dropout(h, rate, rng, training):
    if not training or rate <= 0.0 or rng is None:
        return h
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, h.shape)
    return jnp.where(mask, h / keep, 0.0).astype(h.dtype)


def _dropout_heads(a, rate, rng, training, n_total, off):
    """Last-dim (head) dropout whose mask is drawn at the FULL width
    `n_total` and sliced at `off` — a feat-sharded GAT layer therefore
    reproduces exactly the feat=1 run's per-head masks (the exactness tests
    compare feat=T against feat=1 with dropout on). off=None with
    n_total == a.shape[-1] is bit-identical to `_dropout`."""
    if not training or rate <= 0.0 or rng is None:
        return a
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, a.shape[:-1] + (n_total,))
    if off is not None:
        mask = jax.lax.dynamic_slice_in_dim(mask, off, a.shape[-1], a.ndim - 1)
    return jnp.where(mask, a / keep, 0.0).astype(a.dtype)


# ----------------------------------------------------------------------------
# feat-axis (tensor-parallel) layer body — parallel/feat.py's contract:
# slice the input activations to this shard's H/T columns, run the (sliced)
# exchange + SpMM and the local weight-row-shard matmul, then ONE psum over
# 'feat' where the layer transitions shards. Dropout always fires on the
# FULL pre-slice activations (identical masks to feat=1); biases are
# replicated and added once, after the psum.
# ----------------------------------------------------------------------------

def _feat_slice(env: "GraphEnv", h: jax.Array) -> jax.Array:
    """This feat shard's column slice h[:, f*k:(f+1)*k], k = width/T."""
    k = h.shape[-1] // env.n_feat_shards
    f = jax.lax.axis_index(env.feat_axis)
    return jax.lax.dynamic_slice_in_dim(h, f * k, k, h.ndim - 1)


def _feat_psum(env: "GraphEnv", x: jax.Array) -> jax.Array:
    return jax.lax.psum(x, env.feat_axis)


def _feat_layer(p, i, h, env: "GraphEnv", spec: "ModelSpec") -> jax.Array:
    """One feat-sharded GCN / GraphSAGE / dense layer (h arrives full-width,
    already dropped out; returns the full-width psummed output). The halo
    exchange inside rides the H/T slice — its wire bytes drop T x."""
    is_graph = i < spec.n_graph_layers
    if not is_graph or (env.training and spec.use_pp and i == 0):
        # pure dense matmul: the linear tail and the precomputed layer 0
        part = _feat_slice(env, h) @ p["w"]
        return _feat_psum(env, part) + p["b"]
    if spec.model == "gcn":
        s = env_agg_exchange(env, i, _feat_slice(env, h), scale_out_norm=True)
        part = (s / env.in_norm[:, None]).astype(h.dtype) @ p["w"]
        return _feat_psum(env, part) + p["b"]
    if (not env.training) and spec.use_pp and i == 0:
        # eval pp layer 0: cat(feat, mean) @ W — the concat consumes the
        # full-width mean, so only the linear shards (full-rate eval runs
        # once per log_every; the training exchange is what the axis thins)
        ah = env_agg_exchange(env, i, h) / env.in_norm[:, None]
        part = _feat_slice(env, jnp.concatenate([h[:env.n_dst], ah], 1)) @ p["w"]
        return _feat_psum(env, part) + p["b"]
    hs = _feat_slice(env, h)
    ah = (env_agg_exchange(env, i, hs) / env.in_norm[:, None]).astype(h.dtype)
    part = hs[:env.n_dst] @ p["linear1"]["w"] + ah @ p["linear2"]["w"]
    return _feat_psum(env, part) + p["linear1"]["b"] + p["linear2"]["b"]


def _layer_norm(p, h, eps=1e-5):
    # stats in f32 (bf16 activations would lose the variance), output in h.dtype
    hf = h.astype(jnp.float32)
    mu = hf.mean(-1, keepdims=True)
    var = ((hf - mu) ** 2).mean(-1, keepdims=True)
    out = (hf - mu) / jnp.sqrt(var + eps) * p["scale"] + p["bias"]
    return out.astype(h.dtype)


def _sync_batch_norm(p, st, h, env: GraphEnv, whole_size, momentum=0.1, eps=1e-5):
    """module/sync_bn.py:10-28 — moments over all real rows of all parts,
    normalized by whole_size (= global n_train in the reference trainer)."""
    if env.training:
        if whole_size <= 0:
            raise ValueError("SyncBatchNorm requires train_size (global n_train) > 0; "
                             "is n_train missing from the partition meta?")
        hm = h if env.inner_mask is None else jnp.where(env.inner_mask[:, None], h, 0.0)
        sum_x = hm.sum(0)
        sum_x2 = (hm * hm).sum(0)
        if env.axis_name is not None:
            # replica-axis meshes fold the cross-replica moment mean into
            # the same psum (one collective over both axes; whole_size
            # scales by n_replicas below because each replica holds the
            # full graph, not a shard of it). The feat axis rides the same
            # psum the same way: its moments are identical per shard
            # (computed on the full post-psum activations), so summing
            # them and scaling whole_size by n_feat_shards keeps the value
            # exact with still ONE collective.
            if env.replica_axis is None and env.feat_axis is None:
                axes = env.axis_name
            else:
                axes = tuple(a for a in (env.replica_axis, env.axis_name,
                                         env.feat_axis) if a is not None)
            sum_x = jax.lax.psum(sum_x, axes)
            sum_x2 = jax.lax.psum(sum_x2, axes)
        whole_size = (whole_size * max(env.n_replicas, 1)
                      * max(env.n_feat_shards, 1))
        mean = sum_x / whole_size
        # the reference's estimator (module/sync_bn.py:19-20) sums over ALL
        # local rows but divides by whole_size = n_train; when n_train < the
        # summed row count the quirky formula can go negative (where the
        # reference would silently sqrt(NaN)) — clamp at 0, a no-op whenever
        # the estimate is a valid variance
        var = jnp.maximum((sum_x2 - mean * sum_x) / whole_size, 0.0)
        new_st = {"mean": (1 - momentum) * st["mean"] + momentum * jax.lax.stop_gradient(mean),
                  "var": (1 - momentum) * st["var"] + momentum * jax.lax.stop_gradient(var)}
    else:
        mean, var = st["mean"], st["var"]
        new_st = st
    x_hat = (h - mean) / jnp.sqrt(var + eps)
    return x_hat * p["scale"] + p["bias"], new_st


def _linear(p, h):
    return h @ p["w"] + p["b"]


def _gcn_layer(p, i, h, env: GraphEnv):
    """Symmetric-norm SpMM then linear (module/layer.py:26-46).

    Degree norms are f32; divisions happen in f32 but the result is cast back
    to the activation dtype so the (bytes-bound) gather stays bf16 in bf16 runs.
    The exchange rides inside env_agg_exchange so --overlap split can run the
    collective concurrently with the interior rows' aggregation.
    """
    s = env_agg_exchange(env, i, h, scale_out_norm=True)
    return _linear(p, (s / env.in_norm[:, None]).astype(h.dtype))


def _sage_layer(p, i, h, env: GraphEnv):
    """linear1(self) + linear2(sum(nbrs)/in_deg) (module/layer.py:79-92)."""
    ah = (env_agg_exchange(env, i, h) / env.in_norm[:, None]).astype(h.dtype)
    return _linear(p["linear1"], h[:env.n_dst]) + _linear(p["linear2"], ah)


def _gat_layer(p, h_dst, h_ext, presence, env: GraphEnv, heads, out_feats,
               rng, dropout, training, negative_slope=0.2,
               total_heads=None, head_off=None):
    """DGL-GATConv equivalent over the extended (inner+halo) node space.

    `presence` masks softmax contributions of halo slots that were not sampled
    this epoch (and of padded edges) — reference semantics where unsampled
    halos simply don't appear in the constructed graph (train.py:256-281).

    Feat-sharded GAT (parallel/feat.py): `heads` is this shard's local head
    count, `p` its head-sliced params; `total_heads`/`head_off` make the
    attention-dropout masks the exact head slice of the feat=1 masks
    (defaults keep the historical full-head behavior bit-identical).
    """
    if total_heads is None:
        total_heads = heads
    r1 = r2 = r3 = None
    if training and rng is not None:
        r1, r2, r3 = jax.random.split(rng, 3)
    h_ext = _dropout(h_ext, dropout, r1, training)       # feat_drop
    z = h_ext @ p["w"]                                    # [n_ext, heads*out]
    z = z.reshape(z.shape[0], heads, out_feats)
    el = (z * p["attn_l"][None]).sum(-1)                  # [n_ext, heads]
    if training and r2 is not None:
        # dst projections from independently dropped-out dst features
        h_d = _dropout(h_dst, dropout, r2, training)
        zd = (h_d @ p["w"]).reshape(h_dst.shape[0], heads, out_feats)
    else:
        # eval: h_dst is a prefix of h_ext and dropout is off — reuse z
        zd = z[:h_dst.shape[0]]
    er = (zd * p["attn_r"][None]).sum(-1)                 # [n_dst, heads]
    if env.gat_ell is not None:
        # dense per-row attention over the ELL layout — no COO edge arrays
        from bnsgcn_tpu.ops.ell_attention import gat_ell_attention
        spec_e, arrays_e = env.gat_ell
        out = gat_ell_attention(spec_e, arrays_e, z, el, er, presence,
                                r3, head_off, dropout, training,
                                negative_slope)
        return out + p["bias"].reshape(1, heads, out_feats)
    er_pad = jnp.concatenate([er, jnp.zeros((1, heads), er.dtype)], 0)
    e = el[env.src] + er_pad[jnp.minimum(env.dst, env.n_dst)]
    e = jax.nn.leaky_relu(e, negative_slope)
    edge_mask = None
    if presence is not None:
        edge_mask = presence[env.src]
    alpha = segment_softmax(e, env.dst, env.n_dst, mask=edge_mask)
    alpha = _dropout_heads(alpha, dropout, r3, training,  # attn_drop
                           total_heads, head_off)
    msg = z[env.src] * alpha[:, :, None]                  # [E, heads, out]
    out = jax.ops.segment_sum(msg.reshape(msg.shape[0], heads * out_feats),
                              env.dst, num_segments=env.n_dst + 1)[:env.n_dst]
    out = out + p["bias"]
    return out.reshape(env.n_dst, heads, out_feats)


# ----------------------------------------------------------------------------
# full forward
# ----------------------------------------------------------------------------

def apply_model(params, state, spec: ModelSpec, feat, env: GraphEnv,
                return_hidden: bool = False):
    """Forward pass. Returns (logits [n_dst, n_class], new_state).

    In training mode `feat` is the (possibly precomputed) per-partition inner
    feature block; in eval mode it is the raw full-graph features and
    `env.exchange` is the identity.

    `return_hidden=True` additionally returns the penultimate activations
    (the final layer's input, post norm/relu) as a third element — the
    embedding-table export seam the serving subsystem (serve.py,
    `--dump-embeddings`) precomputes from. Default calls are unchanged.
    """
    h = feat
    hidden = None
    new_state = dict(state)
    rngs = [None] * spec.n_layers
    if env.training and env.rng is not None:
        rngs = list(jax.random.split(env.rng, spec.n_layers))

    for i in range(spec.n_layers):
        if i == spec.n_layers - 1:
            hidden = h
        body = partial(_layer_forward, i=i, params=params, state=state,
                       spec=spec, env=env, rng=rngs[i])
        if env.remat and env.training:
            # rematerialize per layer: activations (incl. the halo-extended
            # block) are recomputed in the backward instead of stored —
            # HBM-for-FLOPs/comm, jax.checkpoint per TPU guidance
            h, st_i = jax.checkpoint(body)(h)
        else:
            h, st_i = body(h)
        if st_i is not None:
            new_state[f"norm_{i}"] = st_i

    if return_hidden:
        return h, new_state, hidden
    return h, new_state


def _layer_forward(h, *, i, params, state, spec: ModelSpec, env: GraphEnv, rng):
    """One layer of the stack: returns (h, bn_state_or_None). Extracted so
    apply_model can wrap it in jax.checkpoint (remat)."""
    name = f"layer_{i}"
    p = params[name]
    is_graph_layer = i < spec.n_graph_layers
    # feat-axis tensor parallelism (parallel/feat.py): layers whose width
    # tiles the axis run the sharded body; the rest keep the historical one
    # (their params matched the replicated catch-all rule)
    fshard = (env.feat_axis is not None
              and feat_shardable(spec, i, env.n_feat_shards))

    if spec.model in ("gcn", "graphsage"):
        # dropout -> (exchange) -> layer   (module/model.py:44-51,79-86);
        # dropout fires on the FULL width even when the layer shards — the
        # feat=T masks are exactly the feat=1 masks
        h = _dropout(h, spec.dropout, rng, env.training)
        if fshard:
            h = _feat_layer(p, i, h, env, spec)
        elif not is_graph_layer:
            h = _linear(p, h)
        elif env.training and spec.use_pp and i == 0:
            # precomputed layer 0: pure dense matmul (module/layer.py:29-30,83-84)
            h = _linear(p, h)
        elif spec.model == "gcn":
            h = _gcn_layer(p, i, h, env)
        elif (not env.training) and spec.use_pp and i == 0:
            # eval pp layer 0: cat(feat, mean) @ W  (module/layer.py:99-100)
            ah = env_agg_exchange(env, i, h) / env.in_norm[:, None]
            h = _linear(p, jnp.concatenate([h[:env.n_dst], ah], 1))
        else:
            h = _sage_layer(p, i, h, env)
    elif spec.model == "gat":
        out_feats = spec.layer_sizes[i + 1]
        if is_graph_layer:
            # feat-sharded GAT: each shard owns heads/T heads (params are
            # head-sliced by the partition rules); the exchange stays
            # full-width and the head mean becomes local-sum -> one psum
            heads_l = (spec.heads // env.n_feat_shards if fshard
                       else spec.heads)
            head_off = (jax.lax.axis_index(env.feat_axis) * heads_l
                        if fshard else None)
            if env.training:
                if i == 0 and spec.use_pp:
                    assert env.gat_feat0 is not None
                    h_ext, presence = env.gat_feat0
                    h_d = h[:env.n_dst] if h.shape[0] > env.n_dst else h
                else:
                    h_ext, presence = env.exchange(i, h)
                    h_d = h
            else:
                # eval: exchange is the identity on a single device and a
                # full-rate halo exchange under mesh-distributed eval
                h_ext, presence = env.exchange(i, h)
                h_d = h
            h = _gat_layer(p, h_d, h_ext, presence, env, heads_l, out_feats,
                           rng, spec.dropout, env.training,
                           total_heads=spec.heads, head_off=head_off)
            if fshard:
                # mean over ALL heads = psum of local head sums / H
                h = _feat_psum(env, h.sum(1)) / spec.heads
            else:
                h = h.mean(1)          # mean over heads (module/model.py:124)
        elif fshard:
            h = _dropout(h, spec.dropout, rng, env.training)
            h = _feat_layer(p, i, h, env, spec)
        else:
            h = _dropout(h, spec.dropout, rng, env.training)
            h = _linear(p, h)
    else:
        raise ValueError(spec.model)

    st_i = None
    if i < spec.n_layers - 1:
        if spec.norm == "layer":
            h = _layer_norm(params[f"norm_{i}"], h)
        elif spec.norm == "batch":
            h, st_i = _sync_batch_norm(
                params[f"norm_{i}"], state[f"norm_{i}"], h, env, spec.train_size)
        h = jax.nn.relu(h)
    return h, st_i
