"""Full-graph evaluation (reference train.py:22-61,427-456).

The reference evaluates on the whole undistributed graph on CPU in a
background thread. Here the eval forward is the same `apply_model` in eval
mode (norms recomputed from the eval graph's degrees, module/layer.py:39-45),
jitted on whichever backend the caller picks; the trainer can run it in a
host thread to overlap with training exactly like the reference.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.data.graph import Graph
from bnsgcn_tpu.models.gnn import GraphEnv, ModelSpec, apply_model
from bnsgcn_tpu.utils.metrics import calc_acc


def _identity_exchange(i, h):
    return h, None


def build_eval_env(g: Graph, spec: ModelSpec, edge_chunk: int = 0) -> GraphEnv:
    """Eval-path env: norms from the eval graph's own degrees
    (module/layer.py:40-41,94)."""
    in_deg = g.in_degrees().astype(np.float32)
    out_deg = g.out_degrees().astype(np.float32)
    if spec.model == "gcn":
        in_norm = np.sqrt(in_deg)
        out_norm = np.sqrt(out_deg)
    else:
        in_norm = in_deg
        out_norm = out_deg  # unused by SAGE/GAT but harmless
    return GraphEnv(
        src=jnp.asarray(g.src, jnp.int32),
        dst=jnp.asarray(g.dst, jnp.int32),
        n_dst=g.n_nodes,
        in_norm=jnp.asarray(in_norm),
        out_norm=jnp.asarray(out_norm),
        exchange=_identity_exchange,
        training=False,
        edge_chunk=edge_chunk,
    )


def full_graph_logits(params, state, spec: ModelSpec, g: Graph,
                      edge_chunk: int = 0) -> np.ndarray:
    env = build_eval_env(g, spec, edge_chunk)
    feat = jnp.asarray(g.feat)
    logits, _ = apply_model(params, state, spec, feat, env)
    return np.asarray(jax.device_get(logits))


def full_graph_embeddings(params, state, spec: ModelSpec, g: Graph,
                          edge_chunk: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """(hidden [N, H], logits [N, C]): the all-node embedding table the
    serving subsystem (serve.py) and `--dump-embeddings` precompute — the
    penultimate activations (final layer's input) plus the final-layer
    scores, through the SAME eval forward as `full_graph_logits`, so served
    tier-A scores are bitwise the full-eval logits."""
    env = build_eval_env(g, spec, edge_chunk)
    feat = jnp.asarray(g.feat)
    logits, _, hidden = apply_model(params, state, spec, feat, env,
                                    return_hidden=True)
    return (np.asarray(jax.device_get(hidden)),
            np.asarray(jax.device_get(logits)))


def evaluate_trans(name: str, params, state, spec: ModelSpec, g: Graph,
                   result_file: Optional[str] = None,
                   edge_chunk: int = 0) -> tuple[float, float]:
    """Transductive: val+test in one pass (reference train.py:44-61)."""
    logits = full_graph_logits(params, state, spec, g, edge_chunk)
    val_acc = calc_acc(logits[g.val_mask], np.asarray(g.label)[g.val_mask])
    test_acc = calc_acc(logits[g.test_mask], np.asarray(g.label)[g.test_mask])
    buf = "{:s} | Validation Accuracy {:.2%} | Test Accuracy {:.2%}".format(name, val_acc, test_acc)
    _emit(buf, result_file)
    return val_acc, test_acc


def evaluate_induc(name: str, params, state, spec: ModelSpec, g: Graph,
                   mode: str, result_file: Optional[str] = None,
                   edge_chunk: int = 0) -> float:
    """Inductive: evaluate `mode` ('val'|'test') mask on subgraph g
    (reference train.py:22-41)."""
    logits = full_graph_logits(params, state, spec, g, edge_chunk)
    mask = g.val_mask if mode == "val" else g.test_mask
    acc = calc_acc(logits[mask], np.asarray(g.label)[mask])
    buf = "{:s} | Accuracy {:.2%}".format(name, acc)
    _emit(buf, result_file)
    return acc


def gather_parts(art, stacked) -> np.ndarray:
    """[P, pad_inner, ...] stacked per-part rows -> [N, ...] in global node
    order (drops padding via inner_mask, places via global_nid)."""
    stacked = np.asarray(stacked)
    out = np.zeros((int(art.n_inner.sum()),) + stacked.shape[2:], stacked.dtype)
    for p in range(art.n_parts):
        ids = art.global_nid[p][art.inner_mask[p]]
        out[ids] = stacked[p][art.inner_mask[p]]
    return out


# back-compat alias used by tests/benchmarks
def gather_part_logits(art, logits) -> np.ndarray:
    return gather_parts(art, logits)


def _local_part_rows(arr) -> np.ndarray:
    """This process's rows of a parts-sharded [P, R, ...] array, in mesh order."""
    shards = sorted(arr.addressable_shards, key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(jax.device_get(s.data)) for s in shards], 0)


def _metric_stats(logits, labels, mask, multilabel) -> np.ndarray:
    """Sufficient statistics for accuracy / micro-F1 as a summable vector."""
    lg, lb = logits[mask], labels[mask]
    if multilabel:
        pred = lg > 0
        pos = lb.astype(bool)
        return np.array([np.sum(pos & pred), np.sum(~pos & pred),
                         np.sum(pos & ~pred)], dtype=np.int64)
    correct = np.sum(np.argmax(lg, 1) == lb) if lg.size else 0
    return np.array([correct, lb.shape[0], 0], dtype=np.int64)


def _stats_to_acc(s, multilabel) -> float:
    if multilabel:
        denom = 2 * s[0] + s[1] + s[2]
        return float(2 * s[0] / denom) if denom else 0.0
    return float(s[0] / s[1]) if s[1] else 0.0


def evaluate_mesh(name: str, eval_forward, params, state, blk_eval, tables_full,
                  art_eval, modes: tuple[str, ...],
                  result_file: Optional[str] = None) -> dict[str, float]:
    """Mesh-distributed evaluation: full-rate eval forward over the parts
    mesh, metrics on host. `modes` from {'val','test'}; returns accuracies.
    Capability upgrade over the reference's single-process CPU eval
    (train.py:313-319,427-441). Multi-host: each process computes metric
    statistics from its addressable shards; tiny allgather-sum combines them
    (art_eval then holds only this process's part rows)."""
    out = eval_forward(params, state, blk_eval, tables_full)
    masks = {"val": art_eval.val_mask, "test": art_eval.test_mask}
    accs = {}
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        logits_l = _local_part_rows(out)                  # [P_local, R, C]
        for mode in modes:
            s = np.zeros(3, dtype=np.int64)
            for i in range(logits_l.shape[0]):
                m = masks[mode][i] & art_eval.inner_mask[i]
                s += _metric_stats(logits_l[i], art_eval.label[i], m,
                                   art_eval.multilabel)
            total = np.asarray(multihost_utils.process_allgather(s)).sum(0)
            accs[mode] = _stats_to_acc(total, art_eval.multilabel)
    else:
        logits = gather_parts(art_eval, out)
        labels = gather_parts(art_eval, art_eval.label)
        for mode in modes:
            m = gather_parts(art_eval, masks[mode])
            accs[mode] = calc_acc(logits[m], labels[m])
    if "test" in accs and "val" in accs:
        buf = "{:s} | Validation Accuracy {:.2%} | Test Accuracy {:.2%}".format(
            name, accs["val"], accs["test"])
    else:
        buf = "{:s} | Accuracy {:.2%}".format(name, list(accs.values())[0])
    if jax.process_index() == 0:
        _emit(buf, result_file)
    return accs


def _emit(buf: str, result_file: Optional[str]):
    print(buf)
    if result_file is not None:
        with open(result_file, "a+") as f:
            f.write(buf + "\n")
