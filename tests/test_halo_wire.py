"""Halo exchange strategies and wire formats.

  * 'shift' (P-1 per-diagonal ppermute rounds) computes EXACTLY the same
    extended features and gradients as the padded all_to_all — only the
    collective decomposition and padding differ;
  * wire='fp8' (e4m3 + per-block scales) stays within quantization tolerance
    forward and backward, with fresh scales on the gradient hop;
  * wire_bytes tracks real skewed boundary sizes under 'shift' and the
    dtype compression factor.

Reference equivalents: exact per-pair isend sizes helper/feature_buffer.py:111-121
(skew-proportional), payload dtype has no reference equivalent (capability
upgrade for byte-bound ICI comm, the reference epoch is ~63% comm).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.parallel.halo import (halo_apply, make_halo_plan,
                                      make_halo_spec, wire_bytes)
from bnsgcn_tpu.parallel.mesh import make_parts_mesh


def _skewed_graph():
    """Graph whose partitions have very different boundary sizes."""
    g = synthetic_graph(n_nodes=120, avg_degree=7, n_feat=6, seed=41,
                        power_law=True)
    # skewed partition: sizes ~ [60, 30, 20, 10]
    pid = np.zeros(g.n_nodes, dtype=np.int32)
    pid[60:90] = 1
    pid[90:110] = 2
    pid[110:] = 3
    return g, pid


def _apply_and_grad(art, spec, tables, mesh, feat, epoch=3):
    """Runs halo_apply in shard_map; returns (h_ext, d_feat) for a fixed
    cotangent (sum of squares loss) so strategies can be compared."""
    base = jax.random.key(42)

    def local(blk, tables):
        b = {k: v[0] for k, v in blk.items()}
        plan = make_halo_plan(spec, tables, b["bnd"], jnp.uint32(epoch), base)

        def loss_fn(h):
            hx = halo_apply(spec, plan, h)
            return jnp.sum(hx.astype(jnp.float32) ** 2), hx

        (_, hx), g = jax.value_and_grad(loss_fn, has_aux=True)(b["feat"])
        return hx[None], g[None]

    f = jax.jit(jax.shard_map(local, mesh=mesh,
                              in_specs=(P("parts"), P()), out_specs=(P("parts"), P("parts"))))
    from bnsgcn_tpu.trainer import place_blocks, place_replicated
    blk = place_blocks({"feat": feat, "bnd": art.bnd}, mesh)
    hx, gr = f(blk, place_replicated(tables, mesh))
    return np.asarray(hx), np.asarray(gr)


@pytest.mark.parametrize("rate", [1.0, 0.5])
def test_shift_equals_padded(rate):
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_pad, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate)
    sp_shift, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate,
                                 strategy="shift")
    hx_p, g_p = _apply_and_grad(art, sp_pad, tb, mesh, feat)
    hx_s, g_s = _apply_and_grad(art, sp_shift, tb, mesh, feat)
    np.testing.assert_allclose(hx_s, hx_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(g_s, g_p, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("wire", ["fp8", "int8"])
@pytest.mark.parametrize("strategy", ["padded", "shift"])
def test_quantized_wire_close_to_native(strategy, wire):
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_nat, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                                strategy=strategy)
    sp_q, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                             strategy=strategy, wire=wire)
    hx_n, g_n = _apply_and_grad(art, sp_nat, tb, mesh, feat)
    hx_8, g_8 = _apply_and_grad(art, sp_q, tb, mesh, feat)
    # inner rows are untouched by the wire; halo rows quantized (e4m3/int8
    # ~ 2-3 significant digits with per-block scale)
    scale = np.abs(hx_n).max() + 1e-9
    assert np.abs(hx_8 - hx_n).max() / scale < 0.05, f"{wire} fwd too lossy"
    gscale = np.abs(g_n).max() + 1e-9
    assert np.abs(g_8 - g_n).max() / gscale < 0.05, f"{wire} bwd too lossy"
    assert not np.allclose(hx_8, hx_n), f"{wire} path appears to be a no-op"


def test_bf16_wire_close_to_native():
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_nat, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5)
    sp_bf, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                              wire="bf16")
    hx_n, g_n = _apply_and_grad(art, sp_nat, tb, mesh, feat)
    hx_b, g_b = _apply_and_grad(art, sp_bf, tb, mesh, feat)
    scale = np.abs(hx_n).max() + 1e-9
    assert np.abs(hx_b - hx_n).max() / scale < 0.02


def test_wire_bytes_track_skew_and_dtype():
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    rate = 0.5
    sp_pad, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate)
    sp_shift, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate,
                                 strategy="shift")
    send = (rate * art.n_b).astype(np.int64)
    # per-shift pads bound each diagonal's true max within alignment
    for k in range(1, 4):
        true = max(send[p, (p + k) % 4] for p in range(4))
        pad = sp_shift.shift_pads[k - 1]
        assert true <= pad <= max(8, true + 7), (k, true, pad)
    # shift total strictly below the uniform padding on a skewed partition
    assert wire_bytes(sp_shift, 64) < wire_bytes(sp_pad, 64)
    # and proportional to the summed diagonal maxima
    exact_total = sum(max(send[p, (p + k) % 4] for p in range(4)) for k in range(1, 4))
    assert wire_bytes(sp_shift, 1, 1) <= exact_total + 8 * 3
    # dtype factors
    assert wire_bytes(sp_pad, 64, 4) == 4 * wire_bytes(sp_pad.__class__(
        **{**sp_pad.__dict__, "wire": "fp8"}), 64, 4)
    assert wire_bytes(sp_pad, 64, 2) == 2 * wire_bytes(sp_pad.__class__(
        **{**sp_pad.__dict__, "wire": "fp8"}), 64, 2)


def test_e2e_training_shift_fp8():
    """Training with halo_exchange=shift + halo_wire=fp8 learns the SBM task
    and lands near the native-run loss."""
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks,
                                    place_replicated)

    g = sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.08, p_out=0.004,
                  seed=44)
    losses = {}
    for name, kw in [("native", {}),
                     ("shift_fp8", dict(halo_exchange="shift", halo_wire="fp8"))]:
        cfg = Config(model="graphsage", dropout=0.0, use_pp=True, norm="layer",
                     n_train=g.n_train, lr=0.01, sampling_rate=0.5, **kw)
        spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.0,
                         use_pp=True, train_size=g.n_train)
        mesh = make_parts_mesh(4)
        art = build_artifacts(g, partition_graph(g, 4, method="random", seed=2))
        fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
        blk_np = build_block_arrays(art, "graphsage")
        blk_np.update(fns.extra_blk)
        for k in fns.drop_blk_keys:
            blk_np.pop(k, None)
        blk = place_blocks(blk_np, mesh)
        tb = place_replicated(tables, mesh)
        blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
        params, state = init_params(jax.random.key(5), spec)
        params = place_replicated(params, mesh)
        state = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        traj = []
        for e in range(40):
            params, state, opt, loss = fns.train_step(
                params, state, opt, jnp.uint32(e), blk, tb,
                jax.random.key(0), jax.random.key(1))
            traj.append(float(loss))
        losses[name] = traj
    assert losses["shift_fp8"][-1] < losses["shift_fp8"][0] * 0.5
    assert abs(losses["shift_fp8"][-1] - losses["native"][-1]) < \
        0.25 * abs(losses["native"][0]), (losses["native"][-1], losses["shift_fp8"][-1])
