"""Halo exchange strategies and wire formats.

  * 'shift' (P-1 per-diagonal ppermute rounds) and 'ragged' (ONE exact-bytes
    ragged collective) compute EXACTLY the same extended features and
    gradients as the padded all_to_all — only the collective decomposition
    and padding differ (strategy x wire matrix below, on the 8-device mesh);
  * wire='fp8' (e4m3 + per-block scales) stays within quantization tolerance
    forward and backward, with fresh scales on the gradient hop;
  * wire_bytes tracks real skewed boundary sizes under 'shift'/'ragged' and
    the dtype compression factor, pinned to the hardware-probed 38%-of-padded
    ratio on the logged skewed profile (hw_logs/hw_session_r4.log:399);
  * `--halo-exchange auto` picks ragged on that profile, padded on balanced
    boundaries, and falls back per the documented hop-count tiebreak.

Reference equivalents: exact per-pair isend sizes helper/feature_buffer.py:111-121
(skew-proportional), payload dtype has no reference equivalent (capability
upgrade for byte-bound ICI comm, the reference epoch is ~63% comm).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.parallel.halo import (halo_apply, make_halo_plan,
                                      make_halo_spec, select_halo_strategy,
                                      wire_bytes)
from bnsgcn_tpu.parallel.mesh import make_parts_mesh, shard_map


def _skewed_graph():
    """Graph whose partitions have very different boundary sizes."""
    g = synthetic_graph(n_nodes=120, avg_degree=7, n_feat=6, seed=41,
                        power_law=True)
    # skewed partition: sizes ~ [60, 30, 20, 10]
    pid = np.zeros(g.n_nodes, dtype=np.int32)
    pid[60:90] = 1
    pid[90:110] = 2
    pid[110:] = 3
    return g, pid


def _apply_and_grad(art, spec, tables, mesh, feat, epoch=3):
    """Runs halo_apply in shard_map; returns (h_ext, d_feat) for a fixed
    cotangent (sum of squares loss) so strategies can be compared."""
    base = jax.random.key(42)

    def local(blk, tables):
        b = {k: v[0] for k, v in blk.items()}
        plan = make_halo_plan(spec, tables, b["bnd"], jnp.uint32(epoch), base)

        def loss_fn(h):
            hx = halo_apply(spec, plan, h)
            return jnp.sum(hx.astype(jnp.float32) ** 2), hx

        (_, hx), g = jax.value_and_grad(loss_fn, has_aux=True)(b["feat"])
        return hx[None], g[None]

    f = jax.jit(shard_map(local, mesh=mesh,
                              in_specs=(P("parts"), P()), out_specs=(P("parts"), P("parts"))))
    from bnsgcn_tpu.trainer import place_blocks, place_replicated
    blk = place_blocks({"feat": feat, "bnd": art.bnd}, mesh)
    hx, gr = f(blk, place_replicated(tables, mesh))
    return np.asarray(hx), np.asarray(gr)


# ----------------------------------------------------------------------------
# strategy x wire matrix on the full 8-device mesh: every decomposition under
# every payload dtype must agree (forward AND backward) with padded+native
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def skew8():
    """8-part skewed partition (sizes 90..8) + the padded+native reference
    exchange results, shared across the matrix cases."""
    g = synthetic_graph(n_nodes=240, avg_degree=7, n_feat=6, seed=46,
                        power_law=True)
    sizes = [90, 50, 30, 20, 16, 14, 12, 8]
    pid = np.repeat(np.arange(8), sizes).astype(np.int32)
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(8)
    feat = art.feat.astype(np.float32)
    sp_ref, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5)
    hx_ref, g_ref = _apply_and_grad(art, sp_ref, tb, mesh, feat)
    return art, mesh, feat, tb, hx_ref, g_ref


@pytest.mark.parametrize("wire", ["native", "bf16", "int8", "fp8"])
@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
def test_strategy_wire_matrix_matches_padded_native(skew8, strategy, wire):
    art, mesh, feat, tb, hx_ref, g_ref = skew8
    sp, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                           strategy=strategy, wire=wire)
    hx, gr = _apply_and_grad(art, sp, tb, mesh, feat)
    # native decompositions are exact; quantized wires carry per-block-scale
    # rounding (e4m3 ~2-3 significant digits)
    tol = {"native": 1e-6, "bf16": 0.02, "int8": 0.05, "fp8": 0.06}[wire]
    scale = np.abs(hx_ref).max() + 1e-9
    assert np.abs(hx - hx_ref).max() / scale < tol, (strategy, wire, "fwd")
    gscale = np.abs(g_ref).max() + 1e-9
    assert np.abs(gr - g_ref).max() / gscale < tol, (strategy, wire, "bwd")
    if wire != "native":
        assert not np.allclose(hx, hx_ref), (strategy, wire, "no-op?")


@pytest.mark.quickgate
def test_wire_bytes_ragged_pins_hw_profile():
    """wire_bytes on the hardware-probed skewed profile (P=8, rate=0.1,
    H=256 bf16 — hw_logs/hw_session_r4.log:399) must reproduce the logged
    numbers: padded 20.5 MB, ragged exact 7.8 MB = 38% (<= 40%), and the
    auto selector must pick ragged there."""
    P_ = 8
    rng = np.random.default_rng(1)
    base = (50000 / np.arange(1, P_) ** 0.8).astype(np.int64)
    n_b = np.zeros((P_, P_), np.int64)
    for i in range(P_):
        n_b[i, np.arange(P_) != i] = rng.permutation(base)
    sp_pad, _ = make_halo_spec(n_b, 0, 50048, 0.1)
    sp_rag, _ = make_halo_spec(n_b, 0, 50048, 0.1, strategy="ragged")
    bp = wire_bytes(sp_pad, 256, 2)
    br = wire_bytes(sp_rag, 256, 2)
    assert abs(bp / 1e6 - 20.5) < 0.3, bp      # the logged padded MB
    assert abs(br / 1e6 - 7.8) < 0.3, br       # the logged exact MB
    assert br <= 0.40 * bp, (br, bp)
    strategy, why = select_halo_strategy(n_b, 0, 50048, 0.1)
    assert strategy == "ragged", why
    # byte estimate is dtype/width-free: same pick for every wire
    for wire in ("bf16", "int8", "fp8"):
        assert select_halo_strategy(n_b, 0, 50048, 0.1, wire=wire)[0] == "ragged"


@pytest.mark.quickgate
def test_auto_selection_tiebreaks():
    """Balanced boundaries -> padded (ragged saves <5%); ragged disallowed
    on a skew that shift's per-diagonal pads cannot capture -> padded with
    the hop-count rationale; ragged disallowed on a diagonal-banded skew
    (each shift round nearly empty) -> shift."""
    nb_bal = np.full((4, 4), 64, np.int64)
    np.fill_diagonal(nb_bal, 0)
    assert select_halo_strategy(nb_bal, 0, 64, 1.0)[0] == "padded"
    # banded: only the +1 diagonal is big, the rest tiny -> shift pads track it
    nb_band = np.full((4, 4), 8, np.int64)
    np.fill_diagonal(nb_band, 0)
    for p in range(4):
        nb_band[p, (p + 1) % 4] = 512
    s, why = select_halo_strategy(nb_band, 0, 512, 1.0, allow_ragged=False)
    assert s == "shift", why
    # and with ragged allowed it wins outright (same bytes, one hop)
    assert select_halo_strategy(nb_band, 0, 512, 1.0)[0] == "ragged"


@pytest.mark.parametrize("rate", [1.0, 0.5])
def test_shift_equals_padded(rate):
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_pad, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate)
    sp_shift, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate,
                                 strategy="shift")
    hx_p, g_p = _apply_and_grad(art, sp_pad, tb, mesh, feat)
    hx_s, g_s = _apply_and_grad(art, sp_shift, tb, mesh, feat)
    np.testing.assert_allclose(hx_s, hx_p, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(g_s, g_p, rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("wire", ["fp8", "int8"])
@pytest.mark.parametrize("strategy", ["padded", "shift"])
def test_quantized_wire_close_to_native(strategy, wire):
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_nat, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                                strategy=strategy)
    sp_q, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                             strategy=strategy, wire=wire)
    hx_n, g_n = _apply_and_grad(art, sp_nat, tb, mesh, feat)
    hx_8, g_8 = _apply_and_grad(art, sp_q, tb, mesh, feat)
    # inner rows are untouched by the wire; halo rows quantized (e4m3/int8
    # ~ 2-3 significant digits with per-block scale)
    scale = np.abs(hx_n).max() + 1e-9
    assert np.abs(hx_8 - hx_n).max() / scale < 0.05, f"{wire} fwd too lossy"
    gscale = np.abs(g_n).max() + 1e-9
    assert np.abs(g_8 - g_n).max() / gscale < 0.05, f"{wire} bwd too lossy"
    assert not np.allclose(hx_8, hx_n), f"{wire} path appears to be a no-op"


def test_bf16_wire_close_to_native():
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)
    feat = art.feat.astype(np.float32)
    sp_nat, tb = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5)
    sp_bf, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5,
                              wire="bf16")
    hx_n, g_n = _apply_and_grad(art, sp_nat, tb, mesh, feat)
    hx_b, g_b = _apply_and_grad(art, sp_bf, tb, mesh, feat)
    scale = np.abs(hx_n).max() + 1e-9
    assert np.abs(hx_b - hx_n).max() / scale < 0.02


def test_wire_bytes_track_skew_and_dtype():
    g, pid = _skewed_graph()
    art = build_artifacts(g, pid)
    rate = 0.5
    sp_pad, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate)
    sp_shift, _ = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, rate,
                                 strategy="shift")
    send = (rate * art.n_b).astype(np.int64)
    # per-shift pads bound each diagonal's true max within alignment
    for k in range(1, 4):
        true = max(send[p, (p + k) % 4] for p in range(4))
        pad = sp_shift.shift_pads[k - 1]
        assert true <= pad <= max(8, true + 7), (k, true, pad)
    # shift total strictly below the uniform padding on a skewed partition
    assert wire_bytes(sp_shift, 64) < wire_bytes(sp_pad, 64)
    # and proportional to the summed diagonal maxima
    exact_total = sum(max(send[p, (p + k) % 4] for p in range(4)) for k in range(1, 4))
    assert wire_bytes(sp_shift, 1, 1) <= exact_total + 8 * 3
    # dtype factors
    assert wire_bytes(sp_pad, 64, 4) == 4 * wire_bytes(sp_pad.__class__(
        **{**sp_pad.__dict__, "wire": "fp8"}), 64, 4)
    assert wire_bytes(sp_pad, 64, 2) == 2 * wire_bytes(sp_pad.__class__(
        **{**sp_pad.__dict__, "wire": "fp8"}), 64, 2)


def test_e2e_training_shift_fp8():
    """Training with halo_exchange=shift + halo_wire=fp8 learns the SBM task
    and lands near the native-run loss."""
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks,
                                    place_replicated)

    g = sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.08, p_out=0.004,
                  seed=44)
    losses = {}
    for name, kw in [("native", {}),
                     ("shift_fp8", dict(halo_exchange="shift", halo_wire="fp8"))]:
        cfg = Config(model="graphsage", dropout=0.0, use_pp=True, norm="layer",
                     n_train=g.n_train, lr=0.01, sampling_rate=0.5, **kw)
        spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.0,
                         use_pp=True, train_size=g.n_train)
        mesh = make_parts_mesh(4)
        art = build_artifacts(g, partition_graph(g, 4, method="random", seed=2))
        fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
        blk_np = build_block_arrays(art, "graphsage")
        blk_np.update(fns.extra_blk)
        for k in fns.drop_blk_keys:
            blk_np.pop(k, None)
        blk = place_blocks(blk_np, mesh)
        tb = place_replicated(tables, mesh)
        blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
        params, state = init_params(jax.random.key(5), spec)
        params = place_replicated(params, mesh)
        state = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        traj = []
        for e in range(40):
            params, state, opt, loss = fns.train_step(
                params, state, opt, jnp.uint32(e), blk, tb,
                jax.random.key(0), jax.random.key(1))
            traj.append(float(loss))
        losses[name] = traj
    assert losses["shift_fp8"][-1] < losses["shift_fp8"][0] * 0.5
    assert abs(losses["shift_fp8"][-1] - losses["native"][-1]) < \
        0.25 * abs(losses["native"][0]), (losses["native"][-1], losses["shift_fp8"][-1])
