"""bench.py supervisor bookkeeping: best-known persistence + status honesty.

Round-3 advisor found the carried-forward machinery could silently lie
(_record_best never called; lexicographic timestamp compares). These tests
pin the fixed contracts without touching any JAX backend.
"""

import importlib.util
import json
import os
import sys
import time
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench():
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _args(tmp_path, graph="dcsbm", scale=0.5, avg_degree=492, epochs=8,
          model="graphsage"):
    return types.SimpleNamespace(graph=graph, scale=scale,
                                 avg_degree=avg_degree,
                                 cache_dir=str(tmp_path),
                                 epochs=epochs, dtype="bf16",
                                 hidden=256, layers=4, model=model)


def test_record_best_writes_and_keeps_minimum(tmp_path):
    b = _bench()
    a = _args(tmp_path)
    b._record_best(a, 1.5, "ell")
    d = json.load(open(os.path.join(str(tmp_path), "best_known.json")))
    ent = d["dcsbm_0.5_492"]
    assert ent["value"] == 1.5 and ent["spmm"] == "ell"
    assert isinstance(ent["measured_epoch"], float)
    # a better value replaces
    b._record_best(a, 0.9, "hybrid")
    ent = json.load(open(os.path.join(str(tmp_path),
                                      "best_known.json")))["dcsbm_0.5_492"]
    assert ent["value"] == 0.9 and ent["spmm"] == "hybrid"
    # a worse value does NOT replace, but stamps freshness
    b._record_best(a, 1.2, "ell")
    ent = json.load(open(os.path.join(str(tmp_path),
                                      "best_known.json")))["dcsbm_0.5_492"]
    assert ent["value"] == 0.9 and ent["spmm"] == "hybrid"
    assert ent["last_measured_epoch"] > ent["measured_epoch"] - 1


def test_vname_vocabulary_stable():
    """The queued-candidate vocabulary: .watch_queue lines and BENCH_NOTES
    reference these exact names; a drift silently invalidates them."""
    b = _bench()
    cases = {
        ("ell", False, "native", "native", 512): "ell",
        ("hybrid", True, "native", "native", 512): "hybrid+pallas",
        ("hybrid", True, "native", "native", 256): "hybrid+pallas+t256",
        ("hybrid", True, "int8", "native", 512): "hybrid+pallas+i8g",
        ("hybrid", True, "int8", "native", 256): "hybrid+pallas+i8g+t256",
        ("hybrid", True, "native", "int8", 512): "hybrid+pallas+i8d",
        ("hybrid", True, "int8", "int8", 512): "hybrid+pallas+i8g+i8d",
        ("hybrid", True, "int8", "int8", 256): "hybrid+pallas+i8g+i8d+t256",
        ("hybrid", False, "fp8", "int8", 512): "hybrid+f8g+i8d",
        ("ell", False, "int8", "native", 512): "ell+i8g",
        # 8th field: replica-axis size (queued rep2 lines depend on these)
        ("hybrid", True, "native", "native", 512, "padded", "off", 2):
            "hybrid+pallas+rep2",
        ("hybrid", True, "native", "native", 512, "ragged", "split", 2):
            "hybrid+pallas+rag+ovl+rep2",
        ("ell", False, "native", "native", 512, "padded", "off", 2):
            "ell+rep2",
    }
    for v, name in cases.items():
        assert b._vname(v) == name
        assert b._vrep(v) == (v[7] if len(v) > 7 else 1)


def test_record_anchor_and_best_share_entry_without_clobbering(tmp_path):
    """anchor_l0/lf and value/spmm live in ONE tag entry; each record call
    must merge, never replace (a new-best write used to wipe the anchor
    fields the previous line just persisted)."""
    b = _bench()
    a = _args(tmp_path)
    b._record_anchor(a, 3.8, 3.37)
    b._record_best(a, 1.5, "ell")         # new best AFTER anchor
    path = os.path.join(str(tmp_path), "best_known.json")
    ent = json.load(open(path))["dcsbm_0.5_492"]
    assert ent["anchor_l0"] == 3.8 and ent["value"] == 1.5
    b._record_best(a, 0.9, "hybrid")      # better best: anchor survives
    ent = json.load(open(path))["dcsbm_0.5_492"]
    assert ent["anchor_l0"] == 3.8 and ent["value"] == 0.9
    b._record_anchor(a, 3.9, 3.40)        # anchor refresh: best survives
    ent = json.load(open(path))["dcsbm_0.5_492"]
    assert ent["anchor_l0"] == 3.9 and ent["value"] == 0.9
    assert ent["anchor_cfg"] == [8, "bf16", 256, 4]


def test_load_best_known_prefers_file_over_seed(tmp_path):
    b = _bench()
    a = _args(tmp_path)
    # seed fallback when no file
    seed = b._load_best_known(a)
    assert seed is b._SEED_BEST["dcsbm_0.5_492"]
    b._record_best(a, 0.8, "hybrid+i8g+i8d")
    fresh = b._load_best_known(a)
    assert fresh["value"] == 0.8


def test_seed_data_never_classifies_partial(tmp_path):
    """The seed entries carry no numeric stamp, so the supervisor's final
    fallback must label them tpu-unavailable, never partial (round-3
    advisor: the old lexicographic compare mislabeled exactly this)."""
    b = _bench()
    a = _args(tmp_path)
    t0 = time.time()
    fresh = b._load_best_known(a) or {}
    last = max(fresh.get("measured_epoch", 0) or 0,
               fresh.get("last_measured_epoch", 0) or 0)
    assert not last > t0          # seed: stamp absent -> tpu-unavailable

    # a measurement recorded DURING the run classifies partial...
    b._record_best(a, 1.0, "ell")
    fresh = b._load_best_known(a)
    last = max(fresh.get("measured_epoch", 0) or 0,
               fresh.get("last_measured_epoch", 0) or 0)
    assert last > t0
    # ...including a non-improving one (freshness without a better value)
    t1 = time.time()
    b._record_best(a, 2.0, "ell")
    fresh = b._load_best_known(a)
    last = max(fresh.get("measured_epoch", 0) or 0,
               fresh.get("last_measured_epoch", 0) or 0)
    assert last > t1 and fresh["value"] == 1.0


def test_corrupt_best_known_falls_back_to_seed(tmp_path):
    b = _bench()
    a = _args(tmp_path)
    os.makedirs(str(tmp_path), exist_ok=True)
    with open(os.path.join(str(tmp_path), "best_known.json"), "w") as f:
        f.write("{not json")
    assert b._load_best_known(a) is b._SEED_BEST["dcsbm_0.5_492"]


def test_gat_model_gets_own_namespace_and_metric(tmp_path):
    """--model gat must never read or clobber the GraphSAGE flagship's
    best_known entry, and its metric line must not carry vs_baseline (the
    reference publishes no in-repo GAT epoch time, README.md:94-95 is the
    GraphSAGE run)."""
    b = _bench()
    sage, gat = _args(tmp_path), _args(tmp_path, model="gat")
    assert b._workload_tag(gat) == b._workload_tag(sage) + "_gat"
    b._record_best(sage, 0.5, "hybrid+pallas")
    assert b._load_best_known(gat) is None          # no seed, no file entry
    b._record_best(gat, 3.0, "ell")
    assert b._load_best_known(sage)["value"] == 0.5  # untouched
    assert b._load_best_known(gat)["value"] == 3.0
    assert b._metric_name(gat) == "reddit_gat_rank_share_epoch_time_per_chip"
    assert b._metric_name(sage) == "reddit_rank_share_epoch_time_per_chip"
