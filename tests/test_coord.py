"""Unit tests for the rank-coordination layer (parallel/coord.py).

Two Coordinator instances driven from threads stand in for two ranks: the
layer needs no jax and no XLA collectives, so every exchange — agree,
broadcast, gather_ok, liveness, timeout — is provable in-process. The real
2-subprocess contract (exit codes, bit-for-bit resume) lives in
tests/test_coord_e2e.py.
"""

import json
import threading
import time

import pytest

from bnsgcn_tpu import resilience
from bnsgcn_tpu.config import Config, parse_config
from bnsgcn_tpu.parallel.coord import (Coordinator, CoordTimeout,
                                       FileTransport, TcpTransport,
                                       make_coordinator, reduce_states)


def _pair(transport_factory, timeout_s=10.0):
    t0 = transport_factory(0, serve=True)
    t1 = transport_factory(1, serve=False)
    return (Coordinator(0, 2, t0, timeout_s, log=lambda *a: None),
            Coordinator(1, 2, t1, timeout_s, log=lambda *a: None))


def _run2(f0, f1):
    """Run the two ranks' halves concurrently; return their results."""
    out, errs = {}, {}

    def wrap(rank, fn):
        try:
            out[rank] = fn()
        except Exception as ex:          # surfaced by the assert below
            errs[rank] = ex

    ts = [threading.Thread(target=wrap, args=(r, f))
          for r, f in ((0, f0), (1, f1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return out[0], out[1]


@pytest.fixture(params=["tcp", "file"])
def coord_pair(request, tmp_path):
    if request.param == "tcp":
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        c0, c1 = _pair(lambda r, serve: TcpTransport("127.0.0.1", port, serve))
    else:
        c0, c1 = _pair(
            lambda r, serve: FileTransport(str(tmp_path / "coord"), r))
    yield c0, c1
    c0.close()
    c1.close()


# ----------------------------------------------------------------------------
# verdict reduce
# ----------------------------------------------------------------------------

def test_reduce_states_worst_wins():
    assert reduce_states({0: "ok", 1: "ok"}) == "ok"
    assert reduce_states({0: "ok", 1: "preempted"}) == "preempt"
    # diverged outranks preempted: a preempt checkpoint written from NaN
    # state would poison the resume
    assert reduce_states({0: "preempted", 1: "diverged"}) == "rollback"
    assert reduce_states({0: "abort", 1: "diverged"}) == "abort"
    assert reduce_states({0: "ok", 1: "garbage"}) == "abort"


# ----------------------------------------------------------------------------
# collectives over both transports
# ----------------------------------------------------------------------------

def test_agree_ok_and_rollback_payload(coord_pair):
    c0, c1 = coord_pair
    d0, d1 = _run2(lambda: c0.agree(4, "ok"), lambda: c1.agree(4, "ok"))
    assert d0["decision"] == d1["decision"] == "ok"

    def decide(name, states):
        assert name == "rollback" and states == {0: "ok", 1: "diverged"}
        return {"decision": "rollback", "restart": 2, "nonce": 1,
                "source": "x.ckpt", "backoff_s": 0.0}

    d0, d1 = _run2(lambda: c0.agree(5, "ok", decide),
                   lambda: c1.agree(5, "diverged"))
    assert d0 == d1
    assert (d1["decision"], d1["restart"], d1["nonce"]) == ("rollback", 2, 1)
    assert d1["epoch"] == 5              # filled in when decide omits it


def test_agree_preempt_confirms_on_both_ranks(coord_pair):
    c0, c1 = coord_pair

    def decide(name, states):
        return {"decision": "preempt",
                "ranks": [r for r, s in states.items() if s == "preempted"]}

    d0, d1 = _run2(lambda: c0.agree(3, "preempted", decide),
                   lambda: c1.agree(3, "ok"))
    assert d0["decision"] == d1["decision"] == "preempt"
    assert d1["ranks"] == [0]


def test_broadcast_and_gather_ok(coord_pair):
    c0, c1 = coord_pair
    b0, b1 = _run2(lambda: c0.broadcast("seed", {"seed": 77}),
                   lambda: c1.broadcast("seed"))
    assert b0 == b1 == {"seed": 77}

    g0, g1 = _run2(lambda: c0.gather_ok("resume", True),
                   lambda: c1.gather_ok("resume", True))
    assert g0 == g1 == (True, {})

    g0, g1 = _run2(lambda: c0.gather_ok("resume", True),
                   lambda: c1.gather_ok("resume", False, "torn file"))
    assert g0 == g1 == (False, {1: "torn file"})


def test_liveness_reports_epoch_and_age(coord_pair):
    c0, c1 = coord_pair
    c0.heartbeat(7)
    c1.heartbeat(6)
    c1.heartbeat(0, c1.ALIVE_KEY)
    live = c0.liveness()
    assert live[0]["epoch"] == 7 and live[1]["epoch"] == 6
    assert live[0]["step_age_s"] < 5.0
    assert "alive_age_s" in live[1] and "alive_age_s" not in live[0]
    lines = []
    c0.log_liveness(write=lines.append)
    text = "\n".join(lines)
    assert "rank 0" in text and "rank 1" in text and "epoch 6" in text


def test_log_liveness_invents_no_culprit_before_any_heartbeat(coord_pair):
    # a startup failure (before ANY rank heartbeats) has no straggler to
    # name: every age is inf and the dump must not arbitrarily mark rank 0
    c0, _ = coord_pair
    lines = []
    c0.log_liveness(write=lines.append)
    text = "\n".join(lines)
    assert "rank 0" in text and "stalled" not in text


def test_log_liveness_names_the_rank_that_never_reported(coord_pair):
    c0, _ = coord_pair
    c0.heartbeat(3)                     # rank 0 reported; rank 1 never did
    lines = []
    c0.log_liveness(write=lines.append)
    stalled = [ln for ln in lines if "stalled" in ln]
    assert len(stalled) == 1 and "rank 1" in stalled[0]


def test_heartbeat_swallows_transport_oserror():
    # FileTransport.put hits the raw filesystem: ENOSPC / flaky NFS must
    # not take down the rank healthy enough to send a heartbeat
    class _Broken:
        def put(self, *a):
            raise OSError("no space left on device")

    c = Coordinator(0, 2, _Broken(), 1.0, log=lambda *a: None)
    c.heartbeat(4)                      # must not raise


def test_peer_decision_window_covers_slow_rank0_decide(coord_pair):
    # rank 0's decide_fn does real checkpoint I/O (chain walk + checksums);
    # a healthy decide that outlives ONE exchange timeout must not make the
    # peer cry hang — the peer's decision fetch allows 2x
    c0, c1 = coord_pair
    c0.timeout_s = c1.timeout_s = 1.0

    def decide(name, states):
        time.sleep(1.4)
        return {"decision": "rollback", "restart": 1, "nonce": 1}

    d0, d1 = _run2(lambda: c0.agree(6, "diverged", decide),
                   lambda: c1.agree(6, "ok"))
    assert d0 == d1 and d1["restart"] == 1


def test_spent_exchange_keys_are_pruned(coord_pair):
    # one agree per epoch for a run's whole lifetime must not grow the KV
    # store (or the --coord file dir the liveness dump listdir's) without
    # bound: rank 0 deletes a spent exchange's per-seq keys once they fall
    # past the prune horizon
    c0, c1 = coord_pair
    n = Coordinator.PRUNE_HORIZON + 4
    for e in range(n):
        _run2(lambda: c0.agree(e, "ok"), lambda: c1.agree(e, "ok"))
    dl = time.monotonic() + 5
    seqs = {int(k.split("/")[1]) for k in c0.transport.dump("v/", dl)}
    assert max(seqs) == n - 1           # the live tail is intact
    assert min(seqs) >= n - Coordinator.PRUNE_HORIZON
    assert c0.transport.dump("d/", dl).keys() == {f"d/{s}" for s in seqs}


def test_get_times_out_with_bounded_wait(coord_pair):
    c0, c1 = coord_pair
    c0.timeout_s = 1.0
    t0 = time.monotonic()
    with pytest.raises(CoordTimeout, match="rank 1"):
        c0.agree(9, "ok")               # rank 1 never contributes
    waited = time.monotonic() - t0
    assert 0.9 <= waited < 5.0          # bounded: no way to hang forever


def test_get_poll_backoff_caps_at_50ms():
    """Pin the _get poll schedule: 2 ms initial, exponential, capped at
    50 ms. An absent key over a 1 s window must cost ~25 polls (~20/s at
    the cap) — the earlier 0.5 s cap left only ~10, adding up to half a
    second of discovery latency to every healthy decision fetch."""
    polls = []
    slept = []

    class _Absent:
        def try_get(self, key, deadline):
            polls.append(key)
            return None

        def dump(self, prefix, deadline):
            return {}                   # liveness snapshot on the timeout

    c = Coordinator(0, 2, _Absent(), 1.0, log=lambda *a: None)
    t = [0.0]
    c._clock = lambda: t[0]
    c._sleep = lambda dt: (slept.append(dt), t.__setitem__(0, t[0] + dt))
    with pytest.raises(CoordTimeout, match="key 'nope'"):
        c._get("nope", 1.0, "a peer that never answers")
    assert 20 <= len(polls) <= 30, len(polls)
    assert max(slept) == pytest.approx(0.05)    # the cap
    assert slept[0] == pytest.approx(0.002)     # fine-grained first poll


def test_tcp_client_times_out_when_no_server():
    t = TcpTransport("127.0.0.1", 1, serve=False)   # nothing listens on :1
    c = Coordinator(1, 2, t, 1.0, log=lambda *a: None)
    t0 = time.monotonic()
    with pytest.raises(CoordTimeout):
        c.broadcast("seed")
    assert time.monotonic() - t0 < 5.0


# ----------------------------------------------------------------------------
# construction / resolution
# ----------------------------------------------------------------------------

def test_make_coordinator_off_and_single_rank_are_none():
    # every one of these must construct NOTHING: the --coord off /
    # single-rank paths are pinned bit-identical to the pre-coordinator loop
    for cfg in (Config(coord="off", coord_world=2, coord_rank=0),
                Config(coord="auto"),
                Config(coord="tcp")):
        c, rank, world = make_coordinator(cfg, log=lambda *a: None)
        assert c is None, cfg.coord


def test_make_coordinator_auto_resolves_tcp_for_multi_rank(tmp_path):
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = Config(coord="auto", coord_world=2, coord_rank=0, coord_port=port,
                 coord_addr="127.0.0.1")
    c, rank, world = make_coordinator(cfg, log=lambda *a: None)
    assert c is not None and (rank, world) == (0, 2)
    assert isinstance(c.transport, TcpTransport)
    c.close()
    cfg = Config(coord="file", coord_world=2, coord_rank=1,
                 coord_dir=str(tmp_path / "c"))
    c, rank, world = make_coordinator(cfg, log=lambda *a: None)
    assert isinstance(c.transport, FileTransport) and rank == 1
    c.close()


def test_file_transport_new_run_never_reads_stale_keys(tmp_path):
    """The coord dir outlives a run and sequence numbers restart at 0: a
    resumed run must never see the previous run's keys (e.g. adopt a stale
    'preempt' decision at the same seq). Rank 0 purges + re-namespaces."""
    root = str(tmp_path / "coord")
    run1 = FileTransport(root, 0)
    run1.put("d/5", "stale-preempt", time.monotonic() + 5)
    t0 = FileTransport(root, 0)         # the relaunch
    t1 = FileTransport(root, 1)
    assert t0.try_get("d/5", time.monotonic() + 5) is None
    assert t1.try_get("d/5", time.monotonic() + 5) is None
    t0.put("d/0", "fresh", time.monotonic() + 5)
    assert t1.try_get("d/0", time.monotonic() + 5) == "fresh"
    assert t1.dump("d/", time.monotonic() + 5).keys() == {"d/0"}


def test_file_transport_peer_refuses_dead_previous_runs_boot(tmp_path):
    """Requeue race: run 2's peer starts BEFORE run 2's rank 0 purges. The
    previous run's .boot AND its keys (same deterministic names, e.g. the
    seq-0 seed broadcast) are still on disk — the peer must not adopt the
    dead run's namespace and read its stale seed; it polls until the new
    rank 0 mints, then converges on the fresh keys."""
    import subprocess
    from bnsgcn_tpu.parallel import coord as coord_mod
    root = tmp_path / "coord"
    root.mkdir()
    p = subprocess.Popen(["true"])
    p.wait()                            # reaped: os.kill(pid, 0) now fails
    dead = f"{coord_mod._host()}:{p.pid:x}-1"
    (root / FileTransport.BOOT).write_text(dead)
    (root / f"{dead}@b@seed@0").write_text('{"seed": 1234}')
    t1 = FileTransport(str(root), 1)
    with pytest.raises(CoordTimeout):   # never adopts the dead namespace
        t1.try_get("b/seed/0", time.monotonic() + 0.3)
    t0 = FileTransport(str(root), 0)    # the new rank 0 arrives
    t0.put("b/seed/0", '{"seed": 77}', time.monotonic() + 5)
    assert t1.try_get("b/seed/0", time.monotonic() + 5) == '{"seed": 77}'


def test_coord_world_requires_explicit_in_range_rank():
    # defaulting a missing rank to 0 would make every misconfigured peer a
    # serving rank 0 (split-brain) — it must be a named config error
    with pytest.raises(ValueError, match="coord-rank"):
        make_coordinator(Config(coord="tcp", coord_world=2),
                         log=lambda *a: None)
    with pytest.raises(ValueError, match="out of range"):
        make_coordinator(Config(coord="tcp", coord_world=2, coord_rank=2),
                         log=lambda *a: None)


def test_harness_without_coordination_needs_skip_partition(tmp_path):
    # --coord-world > 1 with coordination disabled has NO cross-process
    # partition barrier: main must refuse (exit 2) instead of letting two
    # builders race on the shared artifact dir
    from bnsgcn_tpu.main import main
    with pytest.raises(SystemExit) as ex:
        main(["--dataset", "sbm", "--n-partitions", "2",
              "--coord-world", "2", "--coord-rank", "1",
              "--resilience", "off",
              "--part-path", str(tmp_path / "p")])
    assert ex.value.code == 2


def test_coord_flags_reach_config():
    cfg = parse_config(["--coord", "file", "--coord-dir", "/x",
                        "--coord-rank", "1", "--coord-world", "2",
                        "--coord-port", "19999", "--coord-addr", "h0"])
    assert (cfg.coord, cfg.coord_dir, cfg.coord_rank, cfg.coord_world,
            cfg.coord_port, cfg.coord_addr) == ("file", "/x", 1, 2, 19999,
                                                "h0")


# ----------------------------------------------------------------------------
# rank-targeted inject grammar (satellite)
# ----------------------------------------------------------------------------

def test_inject_rank_targeting_filters_by_rank():
    spec = "nan@E5:r0,sigterm@E3:r1,hang@E2"
    assert resilience.FaultPlan.parse(spec, rank=0).faults == {
        "nan": {5}, "hang": {2}}
    assert resilience.FaultPlan.parse(spec, rank=1).faults == {
        "sigterm": {3}, "hang": {2}}
    # rank-less form keeps its historical all-ranks meaning
    assert resilience.FaultPlan.parse("nan@E4", rank=3).faults == {"nan": {4}}


@pytest.mark.parametrize("bad", ["nan@E5:1", "nan@E5:rx", "nan@E5:r",
                                 "nan@E5:r-1", "nan@E5r1"])
def test_inject_rank_grammar_rejects_malformed(bad):
    with pytest.raises(ValueError):
        resilience.FaultPlan.parse(bad, rank=0)


def test_inject_terms_for_other_ranks_still_validated():
    # a typo'd term must raise even when it targets a different rank —
    # silently dropping it would make a CI fault run vacuously green
    with pytest.raises(ValueError):
        resilience.FaultPlan.parse("oom@E3:r1", rank=0)
