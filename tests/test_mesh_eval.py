"""Mesh-distributed eval == host full-graph eval (same params, same graph)."""

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.evaluate import full_graph_logits, gather_part_logits
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                place_blocks, place_replicated)


def _mesh_logits(g, spec, params, state, P=4, use_pp=False):
    cfg = Config(model=spec.model, use_pp=use_pp, dropout=0.0,
                 n_train=g.n_train, sampling_rate=0.5, heads=spec.heads)
    mesh = make_parts_mesh(P)
    art = build_artifacts(g, partition_graph(g, P, method="random", seed=4))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, spec.model)
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tf = place_replicated(tables_full, mesh)
    p = place_replicated(params, mesh)
    s = place_replicated(state, mesh)
    return gather_part_logits(art, fns.eval_forward(p, s, blk, tf))


def test_mesh_eval_matches_host_eval_sage_pp():
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=6, n_class=3, seed=60)
    spec = ModelSpec("graphsage", (6, 8, 3), norm="layer", dropout=0.0,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(0), spec)
    host = full_graph_logits(params, state, spec, g)
    mesh = _mesh_logits(g, spec, params, state, use_pp=True)
    np.testing.assert_allclose(mesh, host, rtol=2e-4, atol=2e-4)


def test_mesh_eval_matches_host_eval_gcn():
    g = synthetic_graph(n_nodes=70, avg_degree=5, n_feat=5, n_class=4, seed=61)
    spec = ModelSpec("gcn", (5, 8, 4), norm="layer", dropout=0.0,
                     train_size=g.n_train)
    params, state = init_params(jax.random.key(1), spec)
    host = full_graph_logits(params, state, spec, g)
    mesh = _mesh_logits(g, spec, params, state)
    np.testing.assert_allclose(mesh, host, rtol=2e-4, atol=2e-4)


def test_mesh_eval_matches_host_eval_gat():
    g = synthetic_graph(n_nodes=50, avg_degree=4, n_feat=5, n_class=3, seed=62)
    spec = ModelSpec("gat", (5, 8, 3), norm="layer", dropout=0.0, heads=2,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(2), spec)
    host = full_graph_logits(params, state, spec, g)
    mesh = _mesh_logits(g, spec, params, state, use_pp=True)
    np.testing.assert_allclose(mesh, host, rtol=2e-4, atol=2e-4)
