"""graftperf (analysis/perf): roofline model, calibration, gate 4, prior.

  * the numpy halo-geometry mirror is pinned BIT-EQUAL to
    parallel/halo.make_halo_spec / make_refresh_spec / wire_bytes across
    partitions x rates x strategies x codecs x refresh rungs — the one
    contract that lets gate 4 price wire with zero devices;
  * physical orderings (more wire / less coverage / wider rows / coarser
    refresh can never be predicted faster) and the calibration file
    round-trip + one-parameter fit;
  * the bundled v5e table re-predicts the committed round-4 ladder
    within the ±25% gate band, and an injected 2x gather miscalibration
    is CAUGHT by `run_perf_audit` (the gate actually gates);
  * gate 4 runs clean at HEAD in seconds on CPU;
  * `--tune-prior model`: the prior picks the comm-/compute-bound rung,
    `startup_changes` folds it without ever loosening, validation
    rejects the flag outside --tune auto, and the 20-epoch CPU e2e
    reaches a frontier lever state (K <= 2) in strictly fewer retune
    windows than the default ladder — with `--tune auto` (no prior)
    left bitwise on the historical startup path.
"""

import copy
import json
import os
import re
import subprocess
import sys

import numpy as np
import pytest

from bnsgcn_tpu.analysis.perf import (AUDIT_N_B, AUDIT_PAD_BOUNDARY,
                                      AUDIT_RATE, AUDIT_WIDTH, DRIFT_BAND,
                                      check_obs_log, run_perf_audit)
from bnsgcn_tpu.analysis.perf import calibration as C
from bnsgcn_tpu.analysis.perf import model as M
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.tune import startup_changes, validate_mode

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# skewed, zero-diagonal boundary tables (the audit matrix + a 2-part and
# an odd 5-part one so padded/shift/ragged all diverge)
N_B_CASES = {
    "p2": np.array([[0, 37], [11, 0]], dtype=np.int64),
    "p4-audit": AUDIT_N_B,
    "p5": np.array([[0, 3, 0, 7, 30],
                    [3, 0, 12, 0, 5],
                    [0, 12, 0, 9, 1],
                    [7, 0, 9, 0, 16],
                    [30, 5, 1, 16, 0]], dtype=np.int64),
}


# ----------------------------------------------------------------------------
# the halo-geometry mirror is bit-equal to parallel/halo.py
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
@pytest.mark.parametrize("case", sorted(N_B_CASES))
@pytest.mark.parametrize("rate", [0.5, 1.0])
def test_exchange_mirror_matches_halo_spec(case, rate):
    from bnsgcn_tpu.parallel import halo
    n_b = N_B_CASES[case]
    pad_b = int(((n_b.max() + 7) // 8) * 8 + 8)
    geom = M.exchange_geometry(n_b, pad_b, rate)
    for strategy in ("padded", "shift", "ragged"):
        spec, _ = halo.make_halo_spec(n_b, 64, pad_b, rate,
                                      strategy=strategy)
        assert geom["n_parts"] == spec.n_parts
        assert geom["pad_send"] == spec.pad_send
        assert geom["shift_pads"] == tuple(spec.shift_pads)
        assert geom["pair_send"] == tuple(map(tuple, spec.pair_send))
        for wire, nb in (("native", 4), ("native", 2), ("bf16", 4),
                         ("int8", 4), ("fp8", 4)):
            spec_w, _ = halo.make_halo_spec(n_b, 64, pad_b, rate,
                                            strategy=strategy, wire=wire)
            assert M.geometry_wire_bytes(geom, strategy, wire, AUDIT_WIDTH,
                                         native_bytes=nb) \
                == halo.wire_bytes(spec_w, AUDIT_WIDTH, native_bytes=nb), \
                (case, rate, strategy, wire, nb)


@pytest.mark.quickgate
@pytest.mark.parametrize("case", sorted(N_B_CASES))
@pytest.mark.parametrize("rate", [0.5, 1.0])
@pytest.mark.parametrize("K", [2, 3, 4])
def test_refresh_mirror_matches_refresh_spec(case, rate, K):
    from bnsgcn_tpu.parallel import halo
    n_b = N_B_CASES[case]
    pad_b = int(((n_b.max() + 7) // 8) * 8 + 8)
    geom = M.refresh_geometry(n_b, pad_b, rate, K)
    for strategy in ("padded", "shift", "ragged"):
        spec, _ = halo.make_refresh_spec(n_b, 64, pad_b, rate, K,
                                         strategy=strategy)
        assert geom["pad_send"] == spec.pad_send, (case, rate, K, strategy)
        assert geom["shift_pads"] == tuple(spec.shift_pads)
        assert geom["pair_send"] == tuple(map(tuple, spec.pair_send))
        assert M.geometry_wire_bytes(geom, strategy, "native", AUDIT_WIDTH) \
            == halo.wire_bytes(spec, AUDIT_WIDTH)


def test_steady_wire_modes():
    kw = dict(strategy="padded", wire="native", width=AUDIT_WIDTH)
    full = M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE, **kw)
    assert M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE,
                            mode="grad-only", **kw) == 0.0
    # K=1 steady state IS the full exchange
    assert M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE,
                            refresh=1, **kw) == full
    assert 0 < M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE,
                                refresh=4, **kw) < full


# ----------------------------------------------------------------------------
# physical orderings
# ----------------------------------------------------------------------------

def _table():
    return C.backend_table(C.default_calibration(), "tpu-v5e")


def _feat(**kw):
    base = dict(n_edges=50e6, coverage=0.6, fill=0.74, dense_tiles=4096,
                row_bytes=512, n_apps=6)
    base.update(kw)
    return M.hybrid_features(**base)


def test_monotone_wire_coverage_rows():
    t = _table()
    assert M.predict_step_s(_feat(wire_mb=20.0), t) \
        > M.predict_step_s(_feat(wire_mb=10.0), t)
    assert M.predict_step_s(_feat(coverage=0.8), t) \
        < M.predict_step_s(_feat(coverage=0.4), t)
    rates = [M.gather_rows_per_s(t, rb)
             for rb in (16, 32, 64, 128, 256, 384, 512, 1024, 2048, 8192)]
    assert all(b <= a * (1 + 1e-9) for a, b in zip(rates, rates[1:]))
    # interpolation pins the measured points exactly
    for k, v in t["gather_rows_per_s"].items():
        assert M.gather_rows_per_s(t, int(k)) == pytest.approx(float(v))


def test_monotone_refresh_and_codecs():
    mbs = [M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE,
                            strategy="padded", wire="native", refresh=k,
                            width=AUDIT_WIDTH) for k in (1, 2, 3, 4, 8)]
    assert all(b <= a * (1 + 1e-9) for a, b in zip(mbs, mbs[1:]))
    for strategy in ("padded", "shift", "ragged"):
        by = {w: M.steady_wire_mb(AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE,
                                  strategy=strategy, wire=w,
                                  width=AUDIT_WIDTH)
              for w in ("int8", "fp8", "bf16", "native")}
        assert by["int8"] == by["fp8"] <= by["bf16"] <= by["native"]
        # ragged ships exact rows; padded ships the padded buffer
        assert M.steady_wire_mb(
            AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE, strategy="ragged",
            wire="native", width=AUDIT_WIDTH) <= M.steady_wire_mb(
            AUDIT_N_B, AUDIT_PAD_BOUNDARY, AUDIT_RATE, strategy="padded",
            wire="native", width=AUDIT_WIDTH)


# ----------------------------------------------------------------------------
# calibration: round-trip, fit, ladder pin, miscalibration caught
# ----------------------------------------------------------------------------

def test_calibration_roundtrip_and_bundled_file(tmp_path):
    calib = C.default_calibration()
    assert C.validate_calibration(calib) == []
    p = str(tmp_path / "cal.json")
    C.save_calibration(calib, p)
    assert C.load_calibration(p) == json.loads(json.dumps(calib))
    # the committed file IS the bundled default, serialized
    committed = C.load_calibration(root=REPO)
    assert committed == json.loads(json.dumps(calib)), \
        "tools/perf_calibration.json drifted from default_calibration()"
    # dict sources are deep-copied: mutating the load must not leak back
    src = C.default_calibration()
    loaded = C.load_calibration(src)
    loaded["backends"]["tpu-v5e"]["link_GBps"] = 1.0
    assert src["backends"]["tpu-v5e"]["link_GBps"] != 1.0


def test_validate_calibration_flags_problems():
    calib = C.default_calibration()
    calib["backends"]["tpu-v5e"]["gather_rows_per_s"]["-4"] = 1e6
    calib["records"][0]["backend"] = "nonexistent"
    calib["records"][1]["measured_s"] = 0.0
    probs = C.validate_calibration(calib)
    assert len(probs) >= 3
    assert C.validate_calibration({"nope": 1})


def test_fit_scale_median():
    t = _table()
    feat = _feat()
    raw = M.predict_step_s(feat, dict(t, calib_scale=1.0, fixed_step_s=0.0))
    fitted = M.fit_scale([(feat, 2.0 * raw), (feat, 2.2 * raw),
                          (feat, 50.0 * raw)], t)
    # median, not mean: the 50x compile-tail outlier must not drag it
    assert fitted["calib_scale"] == pytest.approx(2.2)
    assert M.predict_step_s(feat, fitted) == pytest.approx(2.2 * raw)
    with pytest.raises(ValueError):
        M.fit_scale([], t)


@pytest.mark.quickgate
def test_bundled_ladder_within_band():
    """The v5e table re-predicts the committed round-4 ladder
    (1.672 / 0.87 / 0.667 / 0.5715 s/epoch) within the gate band."""
    calib = C.default_calibration()
    assert len(calib["records"]) == 4
    for rec in calib["records"]:
        table = calib["backends"][rec["backend"]]
        pred = M.predict_step_s(C.record_features(rec), table)
        d = M.drift(pred, rec["measured_s"])
        assert abs(d) <= DRIFT_BAND, \
            f"{rec['name']}: predicted {pred:.4f} vs {rec['measured_s']} " \
            f"({d:+.1%} outside ±{DRIFT_BAND:.0%})"


def test_injected_miscalibration_is_caught():
    """Double the v5e gather rates: every record's prediction halves its
    gather term and the ladder re-prediction leaves the band — gate 4
    must FAIL, not shrug."""
    calib = C.default_calibration()
    bad = copy.deepcopy(calib)
    tb = bad["backends"]["tpu-v5e"]
    tb["gather_rows_per_s"] = {k: 2.0 * float(v)
                               for k, v in tb["gather_rows_per_s"].items()}
    report = run_perf_audit(root=REPO, calibration=bad)
    drifted = [f for f in report["findings"]
               if f["rule"] == "perf-model-drift"]
    assert drifted and not report["ok"]
    # the gather-dominated cells name the drift direction
    assert any("-" in f["message"] for f in drifted)
    # sanity: the unmutated tables pass the same audit
    assert run_perf_audit(root=REPO, calibration=calib)["ok"]


# ----------------------------------------------------------------------------
# gate 4 at HEAD
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
def test_gate4_clean_at_head():
    report = run_perf_audit(root=REPO)
    assert report["ok"], report["findings"]
    assert report["errors"] == []
    assert report["n_records"] == 4
    assert report["n_variants"] > 40
    assert report["elapsed_s"] < 30.0       # "seconds, zero devices"


def test_gate4_cli_subprocess(tmp_path):
    out = str(tmp_path / "perf_report.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               PALLAS_AXON_POOL_IPS="")
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "perf", "-q",
         "--json", out], capture_output=True, text=True, timeout=300,
        cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "graftperf: clean" in r.stderr
    rep = json.load(open(out))
    assert rep["ok"] and rep["graftperf"] == 1


def test_check_obs_log_drift(tmp_path):
    p = str(tmp_path / "obs.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "run_header",
                            "wire_mb_per_exchange": 1.5,
                            "wire_mb_steady": 0.75}) + "\n")
        f.write(json.dumps({"kind": "epoch", "epoch": 0, "loss": 1.0,
                            "wire_mb": 1.5}) + "\n")
        f.write(json.dumps({"kind": "epoch", "epoch": 1, "loss": 0.9,
                            "wire_mb": 0.75}) + "\n")
        f.write(json.dumps({"kind": "epoch", "epoch": 2, "loss": 0.8,
                            "wire_mb": 0.0}) + "\n")
    findings, stats = check_obs_log(p)
    assert findings == [] and stats["epochs_checked"] == 3
    with open(p, "a") as f:
        f.write(json.dumps({"kind": "epoch", "epoch": 3, "loss": 0.7,
                            "wire_mb": 0.33}) + "\n")
    findings, stats = check_obs_log(p)
    assert [f.rule for f in findings] == ["perf-obs-drift"]
    assert stats["mismatched"] == 1


# ----------------------------------------------------------------------------
# --tune-prior model: prior units + config surface
# ----------------------------------------------------------------------------

def test_model_prior_picks_rung_by_comm_fraction():
    t = _table()
    compute_bound = M.model_prior(_feat(wire_mb=0.01), t)
    assert compute_bound["halo_refresh"] == 2
    assert compute_bound["comm_frac"] < 0.30
    assert "compute-bound" in compute_bound["why"]
    # wire the step until the model calls it comm-bound
    comm_bound = M.model_prior(_feat(wire_mb=1e5), t)
    assert comm_bound["halo_refresh"] == 4
    assert comm_bound["comm_frac"] >= 0.30
    assert "comm-bound" in comm_bound["why"]
    # scaled_features changes only the wire term
    a, b = _feat(wire_mb=1.0), M.scaled_features(_feat(wire_mb=1.0),
                                                 wire_mb=2.0)
    pa, pb = M.predict_parts(a, t), M.predict_parts(b, t)
    assert pb["wire_s"] == pytest.approx(2 * pa["wire_s"])
    assert pb["gather_s"] == pa["gather_s"] and pb["dense_s"] == pa["dense_s"]


def test_startup_changes_folds_prior_and_never_loosens():
    prior = {"halo_refresh": 2, "why": "model-prior: test"}
    cfg = Config(tune="auto")
    ch, why = startup_changes(cfg, prior=prior)
    assert ch == {"halo_refresh": 2} and "model-prior" in why
    # positional/backward-compatible default: the ladder K=4 start
    ch, why = startup_changes(cfg)
    assert ch == {"halo_refresh": 4} and "coarse staleness" in why
    # never loosens: a user who launched at K=4 keeps it against a K=2 pick
    ch, _ = startup_changes(Config(tune="auto", halo_refresh=4), prior=prior)
    assert ch == {}
    # grad-only launches are left alone entirely
    ch, _ = startup_changes(Config(tune="auto", halo_mode="grad-only"),
                            prior=prior)
    assert ch == {}


def test_validate_mode_tune_prior_surface():
    validate_mode(Config(tune="auto", tune_prior="model"))
    validate_mode(Config(tune="auto", tune_prior="ladder"))
    validate_mode(Config(tune="off", tune_prior="ladder"))
    with pytest.raises(ConfigError):
        validate_mode(Config(tune="off", tune_prior="model"))
    with pytest.raises(ConfigError):
        validate_mode(Config(tune="schedule", tune_schedule="K=2@3",
                             tune_prior="model"))
    with pytest.raises(ConfigError):
        validate_mode(Config(tune="auto", tune_prior="oracle"))


def test_run_features_from_artifacts():
    """run_features prices a run from (cfg, art) alone — numpy stand-in
    artifact, no partition build needed."""
    class Art:
        n_b = AUDIT_N_B
        pad_boundary = AUDIT_PAD_BOUNDARY
        pad_edges = 12345
        ell_geometry = {"fwd": {"widths": [4, 16], "rows": [100, 10]},
                        "bwd": {"widths": [4, 16], "rows": [120, 8]}}
    cfg = Config(n_layers=2, n_hidden=8, sampling_rate=0.5, dtype="float32")
    feat = M.run_features(cfg, Art(), strategy="padded")
    assert feat.n_apps == 4 and feat.row_bytes == 32
    fwd = 4 * 100 + 16 * 10
    bwd = 4 * 120 + 16 * 8
    assert feat.gather_slots == pytest.approx(0.5 * (fwd + bwd))
    geom = M.exchange_geometry(AUDIT_N_B, AUDIT_PAD_BOUNDARY, 0.5)
    per_ex = M.geometry_wire_bytes(geom, "padded", "native", 8, 4) / 1e6
    assert feat.wire_mb == pytest.approx(per_ex * 2)   # 2*(L-1) exchanges
    # without stored geometry the padded edge count stands in
    class Bare(Art):
        ell_geometry = None
    assert M.run_features(cfg, Bare(), strategy="padded").gather_slots \
        == 12345


# ----------------------------------------------------------------------------
# e2e: --tune-prior model beats the ladder to the frontier rung (CPU)
# ----------------------------------------------------------------------------

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "20",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", PYTHONPATH=REPO)
    return env


def _run(tmp_path, tag, extra_args=(), timeout=420):
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
           + ["--part-path", str(tmp_path / f"parts_{tag}"),
              "--ckpt-path", str(tmp_path / f"ckpt_{tag}"),
              "--results-path", str(tmp_path / f"res_{tag}"),
              "--obs-log", str(tmp_path / f"obs_{tag}.jsonl")]
           + list(extra_args))
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                       cwd=REPO, env=_env())
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    return r


def _tune_trail(path):
    from bnsgcn_tpu.obs import load_events
    return [(int(e["epoch"]), dict(e.get("changes") or {}), e["reason"])
            for e in load_events(str(path)) if e["kind"] == "tune_decision"]


def _windows_to_frontier(trail):
    """Retune windows (post-startup applied decisions) until the lever
    state first sits at halo_refresh <= 2. The startup fold is window 0;
    never reaching the frontier counts every window plus one."""
    k = 1
    for i, (_, changes, _) in enumerate(trail):
        k = int(changes.get("halo_refresh", k))
        if k <= 2:
            return max(i, 0)        # i==0: the startup fold itself
    return len(trail) + 1


@pytest.mark.quickgate
def test_e2e_model_prior_beats_ladder_to_frontier(tmp_path):
    r_model = _run(tmp_path, "model",
                   ["--tune", "auto", "--tune-prior", "model"])
    r_ladder = _run(tmp_path, "ladder", ["--tune", "auto"])

    # the model run logged its prediction before the first compile
    assert "[tune] prior: predicted step" in r_model.stdout + r_model.stderr

    tm = _tune_trail(tmp_path / "obs_model.jsonl")
    tl = _tune_trail(tmp_path / "obs_ladder.jsonl")
    assert tm and tm[0][0] == 0 and "model-prior" in tm[0][2]
    assert tm[0][1].get("halo_refresh") == 2, tm
    assert tl and tl[0][0] == 0 and tl[0][1].get("halo_refresh") == 4, tl

    wm, wl = _windows_to_frontier(tm), _windows_to_frontier(tl)
    assert wm == 0, tm
    assert wm < wl, (tm, tl)

    # gate 4's obs contract holds on both live logs: every epoch wire_mb
    # is a declared figure
    for tag in ("model", "ladder"):
        findings, stats = check_obs_log(str(tmp_path / f"obs_{tag}.jsonl"))
        assert findings == [] and stats["epochs_checked"] > 0, (tag, findings)


@pytest.mark.quickgate
def test_e2e_auto_without_prior_unchanged(tmp_path):
    """`--tune auto` with the default --tune-prior walks the historical
    ladder startup — same fold, same reason string — so the pinned
    no-prior trajectory is untouched by this PR."""
    r = _run(tmp_path, "plain", ["--tune", "auto", "--n-epochs", "4"],
             timeout=300)
    trail = _tune_trail(tmp_path / "obs_plain.jsonl")
    assert trail and trail[0][0] == 0
    assert trail[0][1] == {"halo_refresh": 4}
    assert "coarse staleness" in trail[0][2]
    assert "[tune] prior:" not in r.stdout + r.stderr


def test_cpu_obs_history_self_calibration(tmp_path):
    """The calibration workflow the cpu table's `calibrated: false`
    points at: fit `calib_scale` from a live run's obs epoch records,
    then the fitted table re-predicts those records inside the gate
    band (median residual 0 by construction of the median fit; the
    band absorbs epoch-to-epoch CPU noise)."""
    _run(tmp_path, "cal", ["--halo-refresh", "2"], timeout=300)
    from bnsgcn_tpu.obs import load_events
    evs = load_events(str(tmp_path / "obs_cal.jsonl"))
    epochs = [e for e in evs if e["kind"] == "epoch"
              and isinstance(e.get("step_s"), (int, float))]
    assert len(epochs) >= 3
    steady = epochs[1:]                    # epoch 0 carries the compile
    table = C.backend_table(C.default_calibration(), "cpu")
    feat = M.StepFeatures(n_apps=4, gather_slots=2e4, row_bytes=32,
                          gather_path="materialize",
                          wire_mb=float(np.median(
                              [e.get("wire_mb", 0.0) for e in steady])))
    pairs = [(feat, float(e["step_s"])) for e in steady]
    fitted = M.fit_scale(pairs, table)
    resids = [M.drift(M.predict_step_s(feat, fitted), m) for _, m in pairs]
    assert float(np.median(np.abs(resids))) <= DRIFT_BAND
    # and at least the median epoch is matched essentially exactly
    assert min(abs(r) for r in resids) <= 0.05
