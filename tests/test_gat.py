"""GAT parity vs a dense numpy attention reference + multilabel e2e."""

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.evaluate import full_graph_logits
from bnsgcn_tpu.models.gnn import ModelSpec, init_params


def _dense_gat_layer(g, p, h, heads, out_feats, neg_slope=0.2):
    """DGL-GATConv eval semantics in numpy: additive attention, edge softmax
    per destination, sum, +bias (reference module/model.py:102,111-124)."""
    n = g.n_nodes
    w = np.asarray(p["w"], np.float64)
    al = np.asarray(p["attn_l"], np.float64)
    ar = np.asarray(p["attn_r"], np.float64)
    z = (h @ w).reshape(n, heads, out_feats)
    el = (z * al[None]).sum(-1)            # [N, heads]
    er = (z * ar[None]).sum(-1)
    out = np.zeros((n, heads, out_feats))
    for v in range(n):
        nbrs = g.src[g.dst == v]
        if len(nbrs) == 0:
            continue
        e = el[nbrs] + er[v][None]
        e = np.where(e > 0, e, neg_slope * e)
        e = e - e.max(0)
        a = np.exp(e) / np.exp(e).sum(0)
        out[v] = (a[:, :, None] * z[nbrs]).sum(0)
    out = out.reshape(n, heads * out_feats) + np.asarray(p["bias"], np.float64)
    return out.reshape(n, heads, out_feats)


def test_gat_eval_matches_dense_attention():
    g = synthetic_graph(n_nodes=24, avg_degree=4, n_feat=5, n_class=3, seed=50)
    heads, hidden = 2, 6
    spec = ModelSpec("gat", (5, hidden, 3), norm=None, dropout=0.0,
                     heads=heads, use_pp=True)
    params, state = init_params(jax.random.key(1), spec)
    logits = full_graph_logits(params, state, spec, g)

    h = np.asarray(g.feat, np.float64)
    h1 = _dense_gat_layer(g, params["layer_0"], h, heads, hidden).mean(1)
    h1 = np.maximum(h1, 0)
    h2 = _dense_gat_layer(g, params["layer_1"], h1, heads, 3).mean(1)
    np.testing.assert_allclose(logits, h2, rtol=1e-4, atol=1e-4)


def test_gat_distributed_rate1_matches_single():
    """Covered more broadly in test_distributed; here with 2 heads + n_linear."""
    from bnsgcn_tpu.data.artifacts import build_artifacts
    from bnsgcn_tpu.data.partitioner import partition_graph
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    place_blocks, place_replicated)

    g = synthetic_graph(n_nodes=60, avg_degree=5, n_feat=5, n_class=3, seed=51)
    cfg = Config(model="gat", dropout=0.0, heads=2, n_train=g.n_train,
                 sampling_rate=1.0, n_linear=1)
    spec = ModelSpec("gat", (5, 8, 8, 3), n_linear=1, norm="layer", dropout=0.0,
                     heads=2, use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(2), spec)

    outs = {}
    for P_ in (4, 1):
        mesh = make_parts_mesh(P_)
        art = build_artifacts(g, partition_graph(g, P_, method="random", seed=1))
        fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
        blk_np = build_block_arrays(art, "gat")
        blk_np.update(fns.extra_blk)
        blk = place_blocks(blk_np, mesh)
        tb = place_replicated(tables, mesh)
        blk["feat0_ext"] = fns.precompute(blk, place_replicated(tables_full, mesh))
        p = place_replicated(params, mesh)
        s = place_replicated(state, mesh)
        logits = np.asarray(fns.forward(p, s, jnp.uint32(0), blk, tb,
                                        jax.random.key(0)))
        full = np.zeros((g.n_nodes, 3), np.float32)
        for q in range(art.n_parts):
            ids = art.global_nid[q][art.inner_mask[q]]
            full[ids] = logits[q][art.inner_mask[q]]
        outs[P_] = full
    np.testing.assert_allclose(outs[4], outs[1], rtol=2e-4, atol=2e-4)


def test_multilabel_bce_training_learns():
    """Yelp-style multilabel path end-to-end (BCE sum loss, micro-F1 eval)."""
    from bnsgcn_tpu.data.artifacts import build_artifacts
    from bnsgcn_tpu.data.partitioner import partition_graph
    from bnsgcn_tpu.models.gnn import init_params as ip
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks, place_replicated)
    from bnsgcn_tpu.utils.metrics import calc_acc

    g = synthetic_graph(n_nodes=160, avg_degree=6, n_feat=8, n_class=5,
                        seed=52, multilabel=True)
    cfg = Config(model="graphsage", dataset="yelp", dropout=0.1, use_pp=True,
                 norm="layer", n_train=g.n_train, lr=0.01, sampling_rate=0.5,
                 n_linear=1)
    spec = ModelSpec("graphsage", (8, 16, 16, 5), n_linear=1, norm="layer",
                     dropout=0.1, use_pp=True, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=2))
    assert art.multilabel
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    params, state = ip(jax.random.key(3), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    first = None
    for e in range(50):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.8
    logits = np.asarray(fns.forward(params, state, jnp.uint32(0), blk, tb,
                                    jax.random.key(0)))
    full = np.zeros((g.n_nodes, 5), np.float32)
    lab = np.zeros((g.n_nodes, 5), np.float32)
    for q in range(art.n_parts):
        ids = art.global_nid[q][art.inner_mask[q]]
        full[ids] = logits[q][art.inner_mask[q]]
        lab[ids] = art.label[q][art.inner_mask[q]]
    f1 = calc_acc(full[g.train_mask], lab[g.train_mask])
    assert f1 > 0.5, f1
