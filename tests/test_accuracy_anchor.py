"""Calibrated accuracy anchor — a convergence gate that can actually FAIL.

Round-2/3 verdicts: the old anchors saturate (sbm/reddit_like hit 100%), so
a silently-broken sampler could pass them. This suite fixes that with a
difficulty-calibrated graph (reddit_like_graph feat_snr=0.12,
label_noise=0.03: exact training plateaus ~96.6%, mirroring real Reddit's
97.2% ceiling, reference README.md:100-101) plus MUTATION tests proving
each gate trips when the BNS math is deliberately broken.

Detector split (measured, tools/calibrate_anchor.py):
  * biased sampler  -> ACCURACY gate trips hard (96.6% -> 47%).
  * broken 1/ratio  -> accuracy CANNOT see it (measured 96.6% with and
    without the rescale): all ratios equal the global rate under the
    reference's sizing law (train.py:107-119), so losing 1/ratio is a
    near-uniform scale on aggregates, and a ReLU network is positively
    homogeneous — argmax is scale-invariant. The right detector is the
    ESTIMATOR-level unbiasedness gate (test_distributed.py
    test_bns_unbiasedness); here we prove that gate fails under the
    mutation.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import reddit_like_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.ops.spmm import agg_sum
from bnsgcn_tpu.parallel.halo import halo_apply, make_halo_plan, make_halo_spec
from bnsgcn_tpu.parallel.mesh import make_parts_mesh, shard_map
from bnsgcn_tpu.trainer import place_blocks, place_replicated
from tools.anchor_harness import _biased_pair_sample, train_eval

# calibrated by tools/calibrate_anchor.py (8192 nodes, mean degree 96,
# feat_snr 0.12, label_noise 0.03, GraphSAGE 3x32 no-norm no-pp, 200
# epochs): exact=0.9658 bns=0.9658 biased_sampler=0.4737
ANCHOR_GRAPH = dict(n_nodes=8192, avg_degree=96, n_class=16, n_feat=32,
                    seed=11, feat_snr=0.12, label_noise=0.03)
EPOCHS = 200


@pytest.fixture(scope="module")
def anchor_graph():
    return reddit_like_graph(**ANCHOR_GRAPH)


@pytest.fixture(scope="module")
def exact_acc(anchor_graph):
    """Exact (P=1, rate=1.0) plateau accuracy — shared across gate tests."""
    return train_eval(anchor_graph, P=1, rate=1.0, epochs=EPOCHS)


# slow: a 200-epoch train-to-plateau run (plus the shared exact fixture) —
# out of the 870s tier-1 budget on the CPU mesh; runs in the full tier
@pytest.mark.slow
def test_calibrated_anchor_bns_matches_exact(anchor_graph, exact_acc):
    """Exact plateaus BELOW saturation (the gate has headroom to fail) and
    rate-0.1 BNS lands within 0.5% of it (reference README.md:100-101:
    97.13% vs 97.21% on real Reddit)."""
    acc_bns = train_eval(anchor_graph, P=4, rate=0.1, epochs=EPOCHS)
    assert 0.93 < exact_acc < 0.985, exact_acc
    assert abs(acc_bns - exact_acc) <= 0.005, (acc_bns, exact_acc)


# slow: a 200-epoch train-to-plateau run (plus the shared exact fixture) —
# out of the 870s tier-1 budget on the CPU mesh; runs in the full tier
@pytest.mark.slow
def test_calibrated_anchor_through_quantized_stack(anchor_graph, exact_acc,
                                                   monkeypatch):
    """Converged accuracy through the WINNING kernel stack, not just the
    default f32 agg_sum path (round-4 verdict missing-item #3): the
    headline TPU recipe is hybrid SpMM (Pallas-fused on hardware, XLA twin
    here) + int8 residual gathers + int8 dense tiles + int8 halo wire, and
    until now nothing proved that recipe reaches the plateau rather than
    quietly costing 1-2% (reference's claim is end-of-training accuracy,
    README.md:100-101). BNSGCN_BENCH_PREFLIGHT=1 forces the TPU-side
    unrolled int32-chain accumulation so the exact arithmetic that sets the
    headline number is what trains here. Gate: same 0.5%-of-exact band as
    the unquantized BNS anchor."""
    monkeypatch.setenv("BNSGCN_BENCH_PREFLIGHT", "1")
    acc_q = train_eval(anchor_graph, P=4, rate=0.1, epochs=EPOCHS,
                       spmm="hybrid", use_pallas=True,
                       spmm_gather="int8", spmm_dense="int8",
                       halo_wire="int8")
    assert abs(acc_q - exact_acc) <= 0.005, (acc_q, exact_acc)


# slow: a 200-epoch train-to-plateau run (plus the shared exact fixture) —
# out of the 870s tier-1 budget on the CPU mesh; runs in the full tier
@pytest.mark.slow
def test_mutation_biased_sampler_trips_accuracy_gate(anchor_graph, exact_acc):
    """A deterministic first-k 'sample' (biased: the estimator's expectation
    is no longer the full aggregate) must crater accuracy far past the 0.5%
    gate — measured 96.6% -> 47%."""
    acc_mut = train_eval(anchor_graph, P=4, rate=0.1, epochs=EPOCHS,
                         biased_sampler=True)
    assert acc_mut < exact_acc - 0.05, (acc_mut, exact_acc)


# ---------------------------------------------------------------------------
# estimator-level mutations: the unbiasedness gate (same law as
# test_distributed.test_bns_unbiasedness, rel-err < 0.05) must FAIL when the
# 1/ratio rescale is dropped or the sampler is biased.
# ---------------------------------------------------------------------------

def _estimator_rel_err(break_rescale=False, biased=False, rate=0.5,
                       n_ep=300):
    """Mean over epochs of the sampled+rescaled halo aggregation vs the
    full-rate one; returns mean relative error (the gate passes < 0.05)."""
    g = synthetic_graph(n_nodes=60, avg_degree=6, n_feat=4, seed=33)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=5))
    mesh = make_parts_mesh(4)
    hspec, tables = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                   rate)
    hfull, tfull = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                  1.0)
    if break_rescale:
        tables = dict(tables)
        tables["inv_ratio"] = jnp.where(tables["inv_ratio"] > 0, 1.0,
                                        0.0).astype(jnp.float32)
    blk = place_blocks({"feat": art.feat.astype(np.float32),
                        "bnd": art.bnd, "src": art.src, "dst": art.dst}, mesh)
    base = jax.random.key(42)

    def make_agg(spec):
        def local(blk, tables, epoch):
            b = {k: v[0] for k, v in blk.items()}
            plan = make_halo_plan(spec, tables, b["bnd"], epoch, base)
            hx = halo_apply(spec, plan, b["feat"])
            return agg_sum(hx, b["src"], b["dst"], spec.pad_inner)[None]
        return jax.jit(shard_map(
            local, mesh=mesh, in_specs=(P("parts"), P(), P()),
            out_specs=P("parts")))

    import contextlib
    ctx = _biased_pair_sample() if biased else contextlib.nullcontext()
    with ctx:
        full = np.asarray(make_agg(hfull)(
            blk, place_replicated(tfull, mesh), jnp.uint32(0)))
        agg = make_agg(hspec)
        tb = place_replicated(tables, mesh)
        acc = np.zeros_like(full)
        for e in range(n_ep):
            acc += np.asarray(agg(blk, tb, jnp.uint32(e)))
    mean = acc / n_ep
    err = np.abs(mean - full)
    return err.mean() / (np.abs(full).mean() + 1e-6)


def test_mutation_broken_rescale_trips_unbiasedness_gate():
    healthy = _estimator_rel_err()
    broken = _estimator_rel_err(break_rescale=True)
    assert healthy < 0.05, healthy           # the real gate passes
    assert broken > 0.05, broken             # the mutation trips it


def test_mutation_biased_sampler_trips_unbiasedness_gate():
    biased = _estimator_rel_err(biased=True)
    assert biased > 0.05, biased
