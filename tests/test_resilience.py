"""Resilience subsystem: checkpoint integrity chain, divergence rollback,
deterministic fault injection, preemption, and the new CLI surface.

Every recovery path the tentpole adds is proven here on the CPU mesh —
in-process where the path is observable through run_training's API, and in
tests/test_resilience_e2e.py via subprocess where the contract is an exit
code. `--resilience off` bit-identity is pinned directly against the on-path.
"""

import os
import time

import jax
import numpy as np
import pytest

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import resilience
from bnsgcn_tpu.config import Config, config_from_args, create_parser
from bnsgcn_tpu.data.graph import sbm_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.trainer import make_tx, param_global_norm


# ----------------------------------------------------------------------------
# inject grammar
# ----------------------------------------------------------------------------

def test_inject_grammar_parses_full_matrix():
    plan = resilience.FaultPlan.parse(
        "nan@E12,sigterm@E20,hang@E8,ckpt-corrupt@E10")
    assert plan.faults == {"nan": {12}, "sigterm": {20}, "hang": {8},
                           "ckpt-corrupt": {10}}
    # pop fires exactly once
    assert plan.pop("nan", 12) and not plan.pop("nan", 12)
    assert not plan.pop("sigterm", 19)
    assert plan.pop("sigterm", 20)


def test_inject_grammar_multiple_epochs_same_kind_and_empty():
    plan = resilience.FaultPlan.parse("nan@E3,nan@E7")
    assert plan.faults["nan"] == {3, 7}
    assert resilience.FaultPlan.parse("").empty()
    assert resilience.FaultPlan.parse("  ,  ").empty()


@pytest.mark.parametrize("bad", ["nan@12", "nan", "oom@E3", "nan@Ex",
                                 "nan@E-2", "sigkill@E1"])
def test_inject_grammar_rejects_malformed(bad):
    with pytest.raises(ValueError):
        resilience.FaultPlan.parse(bad)


# ----------------------------------------------------------------------------
# checkpoint integrity chain
# ----------------------------------------------------------------------------

def _tiny_state(seed=0):
    spec = ModelSpec("gcn", (4, 4, 2), norm="batch", dropout=0.1,
                     train_size=10)
    params, state = init_params(jax.random.key(seed), spec)
    opt = make_tx(Config(lr=0.01)).init(params)
    return params, state, opt


def test_checksum_detects_flipped_byte(tmp_path):
    params, state, opt = _tiny_state()
    path = str(tmp_path / "a.ckpt")
    ckpt.save_checkpoint(path, params=params, opt_state=opt, bn_state=state,
                         epoch=3)
    assert ckpt.load_checkpoint(path)["epoch"] == 3
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0x01          # single bit flip mid-payload
    open(path, "wb").write(bytes(raw))
    with pytest.raises(ckpt.CheckpointCorrupt, match="checksum"):
        ckpt.load_checkpoint(path)


def test_zero_byte_and_truncated_files_raise(tmp_path):
    params, _, _ = _tiny_state()
    path = str(tmp_path / "a.ckpt")
    ckpt.save_checkpoint(path, params=params)
    open(str(tmp_path / "zero.ckpt"), "wb").close()
    with pytest.raises(ckpt.CheckpointCorrupt, match="zero-byte"):
        ckpt.load_checkpoint(str(tmp_path / "zero.ckpt"))
    raw = open(path, "rb").read()
    open(path, "wb").write(raw[:20])    # torn inside the header
    with pytest.raises(ckpt.CheckpointCorrupt):
        ckpt.load_checkpoint(path)


def test_legacy_checkpoint_without_magic_still_loads(tmp_path):
    """Pre-checksum checkpoint dirs must keep resuming (no magic header)."""
    from flax import serialization
    path = str(tmp_path / "legacy.ckpt")
    blob = serialization.msgpack_serialize(
        {"params": {}, "opt_state": {}, "bn_state": {}, "epoch": 9,
         "best_acc": 0.5, "seed": 1, "extra": {}})
    open(path, "wb").write(blob)
    payload = ckpt.load_checkpoint(path)
    assert payload["epoch"] == 9


def test_latest_valid_checkpoint_walks_past_corrupt_chain(tmp_path):
    """The fallback chain: newest torn, next zero-byte, oldest good."""
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g")
    params, _, _ = _tiny_state()
    for ep in (1, 3, 5):
        ckpt.save_checkpoint(ckpt.periodic_path(cfg, ep), params=params,
                             epoch=ep)
    resilience.corrupt_file(ckpt.periodic_path(cfg, 5))
    open(ckpt.periodic_path(cfg, 3), "wb").close()      # zero-byte
    skipped = []
    found = ckpt.latest_valid_checkpoint(cfg, log=skipped.append)
    assert found is not None
    path, payload = found
    assert path.endswith("_1.ckpt") and payload["epoch"] == 1
    assert len(skipped) == 2            # both bad files logged
    # before_epoch guards rollback against "future" files of older runs
    assert ckpt.latest_valid_checkpoint(cfg, before_epoch=1) is None
    # all files bad -> None, not a crash
    resilience.corrupt_file(ckpt.periodic_path(cfg, 1))
    assert ckpt.latest_valid_checkpoint(cfg) is None


# ----------------------------------------------------------------------------
# divergence rollback
# ----------------------------------------------------------------------------

def _mgr(cfg, **kw):
    return resilience.ResilienceManager(cfg, log=lambda *a, **k: None, **kw)


def test_rollback_restores_bitwise_equal_to_checkpoint(tmp_path, monkeypatch):
    """Post-rollback trees are bitwise-equal the checkpoint they restore."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g", resil_retries=3)
    params, state, opt = _tiny_state(seed=1)
    ckpt.save_checkpoint(ckpt.periodic_path(cfg, 3), params=params,
                         opt_state=opt, bn_state=state, epoch=3)
    # templates are a DIFFERENT (poisoned-looking) state: restore must
    # overwrite every leaf with the checkpoint bytes
    p2, s2, o2 = _tiny_state(seed=9)
    m = _mgr(cfg)
    rp, ro, rs, restart, nonce = m.rollback(5, float("nan"), p2, o2, s2)
    assert restart == 4 and nonce == 1
    saved = ckpt.load_checkpoint(ckpt.periodic_path(cfg, 3))
    expect, _, _ = ckpt.restore_into(saved, p2, o2, s2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), rp, expect)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), rp, params)
    assert m.rollbacks[0]["epoch"] == 5
    assert m.rollbacks[0]["source"].endswith("_3.ckpt")


def test_rollback_uses_initial_snapshot_before_any_checkpoint(
        tmp_path, monkeypatch):
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path / "empty"), graph_name="g")
    params, state, opt = _tiny_state(seed=2)
    m = _mgr(cfg, start_epoch=0)
    m.set_initial_snapshot(params, opt, state)
    rp, ro, rs, restart, nonce = m.rollback(1, float("inf"), params, opt,
                                            state)
    assert restart == 0 and nonce == 1
    assert m.rollbacks[0]["source"] == "<initial state>"
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(
        np.asarray(a), np.asarray(b)), rp, params)


def test_rollback_exhaustion_raises_diagnostic_report(tmp_path, monkeypatch):
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g", resil_retries=1)
    params, state, opt = _tiny_state()
    m = _mgr(cfg)
    m.set_initial_snapshot(params, opt, state)
    m.rollback(4, float("nan"), params, opt, state)     # retry 1: allowed
    with pytest.raises(resilience.DivergenceError, match="unrecovered"):
        m.rollback(4, float("nan"), params, opt, state)
    reports = [f for f in os.listdir(tmp_path) if f.startswith("divergence")]
    assert reports, "diagnostic report file not written"


def test_retry_budget_resets_after_healed_checkpoint(tmp_path, monkeypatch):
    """N independent transients over a long run must each get the full
    retry budget: a guard-verified checkpoint strictly past the last
    rollback resets the counter (the key-fold nonce stays monotonic)."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g", resil_retries=1)
    params, state, opt = _tiny_state()
    m = _mgr(cfg)
    m.set_initial_snapshot(params, opt, state)
    m.rollback(2, float("nan"), params, opt, state)
    assert m.retries == 1
    m.note_progress(2)              # not past the rollback epoch: no reset
    assert m.retries == 1
    m.note_progress(3)
    assert m.retries == 0
    # an independent later transient rolls back again instead of aborting
    _, _, _, _, nonce = m.rollback(6, float("nan"), params, opt, state)
    assert nonce == 2               # nonce never resets


def test_two_distant_nan_transients_both_recover(tmp_path, small_graph,
                                                 monkeypatch):
    """e2e: with --resil-retries 1, two nan injections separated by healthy
    checkpoints must BOTH recover (the budget reset in action)."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    from bnsgcn_tpu.run import run_training
    res = run_training(
        _base_cfg(tmp_path, inject="nan@E3,nan@E6", resil_retries=1),
        g=small_graph, verbose=False)
    assert [rb["epoch"] for rb in res.rollbacks] == [3, 6]
    assert [rb["nonce"] for rb in res.rollbacks] == [1, 2]
    assert len(res.losses) == 8 and np.all(np.isfinite(res.losses))


def test_param_global_norm_flags_poisoned_params():
    params, _, _ = _tiny_state()
    assert np.isfinite(float(param_global_norm(params)))
    poisoned = jax.tree.map(lambda x: x * np.nan, params)
    assert not np.isfinite(float(param_global_norm(poisoned)))


# ----------------------------------------------------------------------------
# in-process fault-injection e2e through run_training
# ----------------------------------------------------------------------------

def _base_cfg(tmp_path, **kw):
    d = dict(dataset="sbm", model="graphsage", n_partitions=2, n_layers=2,
             n_hidden=8, sampling_rate=0.5, dropout=0.5, use_pp=True,
             eval=False, n_epochs=8, log_every=2, seed=7, comm_trace=False,
             part_path=str(tmp_path / "parts"),
             ckpt_path=str(tmp_path / "ckpt"),
             results_path=str(tmp_path / "res"))
    d.update(kw)
    return Config(**d)


@pytest.fixture(scope="module")
def small_graph():
    return sbm_graph(n_nodes=240, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                     seed=3)


def test_resilience_on_without_faults_bit_identical_to_off(tmp_path,
                                                           small_graph):
    """The default-on guard path must not perturb the training math: same
    losses bitwise as --resilience off (the exact pre-resilience loop)."""
    from bnsgcn_tpu.run import run_training
    g = small_graph
    r_off = run_training(
        _base_cfg(tmp_path, resilience="off", ckpt_path=str(tmp_path / "c0")),
        g=g, verbose=False)
    r_on = run_training(
        _base_cfg(tmp_path, resilience="on", ckpt_path=str(tmp_path / "c1")),
        g=g, verbose=False)
    np.testing.assert_array_equal(r_off.losses, r_on.losses)
    assert r_on.rollbacks == []


def test_nan_inject_rolls_back_and_recovers(tmp_path, small_graph,
                                            monkeypatch):
    """nan@E5: epoch 5 diverges, the guard rolls back to the epoch-3
    periodic checkpoint and the run completes with finite losses under the
    refolded sampling streams."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    from bnsgcn_tpu.run import run_training
    res = run_training(_base_cfg(tmp_path, inject="nan@E5"), g=small_graph,
                       verbose=False)
    assert len(res.rollbacks) == 1
    rb = res.rollbacks[0]
    assert rb["epoch"] == 5 and rb["restart"] == 4 and rb["nonce"] == 1
    assert rb["source"].endswith("_3.ckpt")
    assert len(res.losses) == 8
    assert np.all(np.isfinite(res.losses))


def test_ckpt_corrupt_inject_falls_back_to_older_checkpoint(
        tmp_path, small_graph, monkeypatch):
    """ckpt-corrupt@E6 tears the newest (epoch-5) checkpoint; the nan@E6
    rollback must walk past it to the epoch-3 file instead of crashing."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    from bnsgcn_tpu.run import run_training
    res = run_training(
        _base_cfg(tmp_path, inject="ckpt-corrupt@E6,nan@E6"),
        g=small_graph, verbose=False)
    assert len(res.rollbacks) == 1
    assert res.rollbacks[0]["source"].endswith("_3.ckpt")
    assert np.all(np.isfinite(res.losses))


def test_sigterm_inject_preempts_then_resume_matches_uninterrupted(
        tmp_path, small_graph):
    """sigterm@E3: PreemptedError at the epoch-3 step boundary with a
    resumable checkpoint; --resume continues and the remaining losses match
    the uninterrupted run of the same seed (the e2e subprocess variant in
    test_resilience_e2e.py additionally pins exit code 75)."""
    from bnsgcn_tpu.run import run_training
    g = small_graph
    full = run_training(
        _base_cfg(tmp_path, ckpt_path=str(tmp_path / "ck_full")),
        g=g, verbose=False)
    cfg_b = _base_cfg(tmp_path, ckpt_path=str(tmp_path / "ck_int"),
                      inject="sigterm@E3")
    with pytest.raises(resilience.PreemptedError) as ei:
        run_training(cfg_b, g=g, verbose=False)
    assert ei.value.epoch == 3
    assert os.path.exists(ei.value.ckpt_path)
    resumed = run_training(
        cfg_b.replace(inject="", resume=True, seed=999), g=g, verbose=False)
    np.testing.assert_allclose(resumed.losses, full.losses[4:], rtol=1e-6)


def test_divergence_abort_after_retry_budget(tmp_path, small_graph,
                                             monkeypatch):
    """Injecting nan on every retry epoch exhausts --resil-retries and the
    run aborts with the diagnostic DivergenceError instead of looping."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    from bnsgcn_tpu.run import run_training
    # every epoch from 4 on is poisoned: rollback can never get past it
    inj = ",".join(f"nan@E{e}" for e in range(4, 8))
    with pytest.raises(resilience.DivergenceError):
        run_training(_base_cfg(tmp_path, inject=inj, resil_retries=2),
                     g=small_graph, verbose=False)


# ----------------------------------------------------------------------------
# diskcache stale-tmp sweep
# ----------------------------------------------------------------------------

def test_sweep_stale_tmp(tmp_path):
    from bnsgcn_tpu.utils.diskcache import sweep_stale_tmp
    d = str(tmp_path)
    t_old = time.time() - 7200
    # dead-PID tmp past the write grace: removed (crashed writer)
    dead = os.path.join(d, "layouts_a.pkl.999999999.tmp")
    open(dead, "wb").write(b"x")
    os.utime(dead, (t_old, t_old))
    # dead-LOOKING PID but freshly written: KEPT — on a shared volume this
    # is another host's live writer mid-dump (its PID means nothing here)
    peer = os.path.join(d, "layouts_p.pkl.999999998.tmp")
    open(peer, "wb").write(b"x")
    # live-PID fresh tmp: kept (a concurrent local writer mid-dump)
    live = os.path.join(d, f"layouts_b.pkl.{os.getpid()}.tmp")
    open(live, "wb").write(b"x")
    # un-parsable tmp name, ancient mtime: removed by the age fallback
    old = os.path.join(d, "noext.tmp")
    open(old, "wb").write(b"x")
    os.utime(old, (t_old, t_old))
    # non-tmp files: untouched
    keep = os.path.join(d, "layouts_c.pkl")
    open(keep, "wb").write(b"x")
    msgs = []
    assert sweep_stale_tmp(d, log=msgs.append) == 2
    assert os.path.exists(live) and os.path.exists(peer) and os.path.exists(keep)
    assert not os.path.exists(dead) and not os.path.exists(old)
    assert msgs and "2 stale" in msgs[0]
    # second sweep: nothing left to remove, no log line
    assert sweep_stale_tmp(d, log=msgs.append) == 0


# ----------------------------------------------------------------------------
# CLI arg-matrix: config.py drift guard for the new flags
# (test_bench_preflight-style: every row must parse AND land in Config)
# ----------------------------------------------------------------------------

RESIL_ARG_MATRIX = [
    ([], {"resilience": "on", "inject": "", "resil_retries": 3}),
    (["--resilience", "off"], {"resilience": "off"}),
    (["--resilience", "on"], {"resilience": "on"}),
    (["--inject", "nan@E12,sigterm@E20,hang@E8,ckpt-corrupt@E10"],
     {"inject": "nan@E12,sigterm@E20,hang@E8,ckpt-corrupt@E10"}),
    (["--resil-retries", "7"], {"resil_retries": 7}),
    (["--resil_retries", "7"], {"resil_retries": 7}),   # underscore alias
    (["--resilience", "off", "--inject", "nan@E1", "--resil-retries", "0"],
     {"resilience": "off", "inject": "nan@E1", "resil_retries": 0}),
]


@pytest.mark.quickgate
@pytest.mark.parametrize("argv,expect", RESIL_ARG_MATRIX,
                         ids=[" ".join(a) or "<defaults>"
                              for a, _ in RESIL_ARG_MATRIX])
def test_resilience_flags_reach_config(argv, expect):
    cfg = config_from_args(create_parser().parse_args(argv))
    for k, v in expect.items():
        assert getattr(cfg, k) == v, (argv, k)
    # every --inject value the matrix ships must parse under the grammar
    resilience.FaultPlan.parse(cfg.inject)


def test_resilience_flag_rejects_unknown_mode(capsys):
    with pytest.raises(SystemExit):
        create_parser().parse_args(["--resilience", "maybe"])
    capsys.readouterr()


def test_bad_inject_spec_fails_fast_at_manager_construction(tmp_path):
    cfg = Config(inject="oom@E3", ckpt_path=str(tmp_path))
    with pytest.raises(ValueError, match="unknown --inject fault"):
        resilience.ResilienceManager(cfg, log=lambda *a: None)
