"""Real multi-process SPMD: two jax.distributed processes (4 CPU devices
each) drive one 8-part mesh end-to-end — partial artifact loading,
process-local placement, seed broadcast, shared-PRNG BNS exchange across
hosts, and resume-broadcast. The reference's multi-node flow
(scripts/reddit_multi_node.sh) without a cluster (SURVEY §4: 'multi-node
without a cluster')."""

import functools
import os
import re
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Some jaxlib builds (e.g. 0.4.36's PJRT CPU client in this container) accept
# jax.distributed.initialize but raise `INVALID_ARGUMENT: Multiprocess
# computations aren't implemented on the CPU backend` at the first cross-
# process collective — an environment bound, not a code defect. Probe once
# per session with a minimal 2-process allgather and skip the whole suite
# with the probe's own error as the reason; on a jax with CPU multiprocess
# collectives (or a real pod) the suite runs as before.
_MP_PROBE = """
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
import numpy as np
from jax.experimental import multihost_utils
out = multihost_utils.process_allgather(np.int64(jax.process_index()))
assert sorted(np.asarray(out).ravel().tolist()) == [0, 1]
print("MP_OK")
"""


@functools.lru_cache(maxsize=1)
def _multiprocess_cpu_error():
    """None when 2-process jax.distributed CPU collectives work here, else a
    one-line reason string (cached: one probe per test session)."""
    addr = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE, addr, str(r)], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, cwd=REPO)
        for r in (0, 1)]
    try:
        outs = [p.communicate(timeout=180)[0] for p in procs]
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate()
        return "2-process jax.distributed CPU probe timed out"
    if all(p.returncode == 0 for p in procs) and all("MP_OK" in o for o in outs):
        return None
    for o in outs:
        m = re.search(r"XlaRuntimeError: [^\n]+", o)
        if m:
            return m.group(0).strip()
    return f"probe exit codes {[p.returncode for p in procs]}"


@pytest.fixture(scope="module")
def multiprocess_cpu():
    err = _multiprocess_cpu_error()
    if err:
        pytest.skip("environment-bound: this jaxlib's CPU client cannot run "
                    f"cross-process computations ({err}); needs a jaxlib "
                    "with CPU multiprocess collectives or a real pod")


def _launch(rank, port, tmp, epochs, resume=False, mesh_eval=False,
            inductive=False, model="graphsage", spmm=None):
    env = os.environ.copy()
    env.update({
        "PALLAS_AXON_POOL_IPS": "",
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": REPO,
    })
    cmd = [sys.executable, "-m", "bnsgcn_tpu.main",
           "--dataset", "sbm", "--n-partitions", "8", "--model", model,
           "--n-layers", "2", "--n-hidden", "16", "--n-epochs", str(epochs),
           "--log-every", "10", "--sampling-rate", "0.5", "--use-pp",
           "--fix-seed", "--skip-partition",
           "--n-nodes", "2", "--node-rank", str(rank), "--port", str(port),
           "--part-path", f"{tmp}/parts", "--ckpt-path", f"{tmp}/ckpt",
           "--results-path", f"{tmp}/res"]
    if spmm:
        cmd += ["--spmm", spmm]
    cmd.append("--eval-device" if mesh_eval else "--no-eval")
    if mesh_eval:
        cmd.append("mesh")
    if inductive:
        cmd.append("--inductive")
    if resume:
        cmd.append("--resume")
    return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, cwd=REPO)


def test_two_process_training_and_resume(tmp_path, multiprocess_cpu):
    tmp = str(tmp_path)
    env = os.environ.copy()
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO})
    subprocess.run([sys.executable, "-m", "bnsgcn_tpu.partition_cli",
                    "--dataset", "sbm", "--n-partitions", "8", "--fix-seed",
                    "--part-path", f"{tmp}/parts"],
                   env=env, check=True, capture_output=True, cwd=REPO)

    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=12) for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    # identical losses on both ranks == shared-PRNG BNS + replicated params hold
    losses = [[ln for ln in o.splitlines() if "Loss" in ln][-1].split()[-1]
              for o in outs]
    assert losses[0] == losses[1], losses

    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=20, resume=True) for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    for o in outs:
        assert "Resumed (broadcast from rank 0) at epoch 10" in o, o[-2000:]
    losses2 = [[ln for ln in o.splitlines() if "Loss" in ln][-1].split()[-1]
               for o in outs]
    assert losses2[0] == losses2[1]
    assert float(losses2[0]) < float(losses[0])   # training continued
    # ELL ran multi-host (geometry from meta.json — no segment fallback)
    assert "falling back" not in outs[0]

    # mesh-distributed eval across both processes (collective test eval incl.)
    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=12, mesh_eval=True) for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert "Test Result" in outs[0]               # rank 0 reports
    assert "Validation Accuracy" not in outs[1]   # rank 1 stays silent


def test_two_process_gat_ell_attention(tmp_path, multiprocess_cpu):
    """Multi-host GAT rides the ELL attention path (gat_fwd + bwd geometry
    from meta.json — no segment fallback), trains with identical losses on
    both ranks, and custom-VJP backward runs under jax.distributed."""
    tmp = str(tmp_path)
    env = os.environ.copy()
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO})
    subprocess.run([sys.executable, "-m", "bnsgcn_tpu.partition_cli",
                    "--dataset", "sbm", "--n-partitions", "8", "--fix-seed",
                    "--part-path", f"{tmp}/parts"],
                   env=env, check=True, capture_output=True, cwd=REPO)
    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=25, model="gat") for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    losses = [[ln for ln in o.splitlines() if "Loss" in ln] for o in outs]
    assert losses[0] and losses[0][-1].split()[-1] == losses[1][-1].split()[-1]
    first = float(losses[0][0].split()[-1])
    last = float(losses[0][-1].split()[-1])
    assert last < first, (first, last)
    assert "falling back" not in outs[0]          # ELL attention ran


def test_two_process_hybrid_spmm(tmp_path, multiprocess_cpu):
    """Multi-host --spmm hybrid: each process tiles its LOCAL parts and the
    stack/residual shapes agree via the host allgather — identical losses,
    no ell fallback."""
    tmp = str(tmp_path)
    env = os.environ.copy()
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO})
    subprocess.run([sys.executable, "-m", "bnsgcn_tpu.partition_cli",
                    "--dataset", "sbm", "--n-partitions", "8", "--fix-seed",
                    "--part-path", f"{tmp}/parts"],
                   env=env, check=True, capture_output=True, cwd=REPO)
    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=25, spmm="hybrid") for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    losses = [[ln for ln in o.splitlines() if "Loss" in ln] for o in outs]
    assert losses[0] and losses[0][-1].split()[-1] == losses[1][-1].split()[-1]
    assert float(losses[0][-1].split()[-1]) < float(losses[0][0].split()[-1])
    assert "falling back" not in outs[0]


def test_two_process_inductive_mesh_eval(tmp_path, multiprocess_cpu):
    """Inductive multi-host mesh eval: rank 0 partitions the eval subgraphs
    behind a barrier; all ranks join the collective val/test evals."""
    tmp = str(tmp_path)
    env = os.environ.copy()
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
                "PYTHONPATH": REPO})
    subprocess.run([sys.executable, "-m", "bnsgcn_tpu.partition_cli",
                    "--dataset", "sbm", "--n-partitions", "8", "--fix-seed",
                    "--inductive", "--part-path", f"{tmp}/parts"],
                   env=env, check=True, capture_output=True, cwd=REPO)
    port = _free_port()
    procs = [_launch(r, port, tmp, epochs=12, mesh_eval=True, inductive=True)
             for r in (0, 1)]
    outs = [p.communicate(timeout=280)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), outs
    assert "Test Result" in outs[0]
    assert "Accuracy" in outs[0]
