"""Unified telemetry bus (bnsgcn_tpu/obs.py) + its wiring.

Unit level: the streaming histogram against known-quantile inputs (the
fixed-log-bucket error bound), registry snapshots, event-log rotation bound
and strict-JSON sanitization. Integration level: `--obs off` is pinned
bitwise against `on` (the bus must never perturb training math), a real
`--inject nan@..` CLI run leaves header + epoch + rollback + run_end events
that tools/obs_report.py renders without error [quickgate], and a genuine
2-process coordinated run produces rank 0's merged cross-rank epoch record
(the agree_step piggyback — no extra collective) [quickgate]. Serving:
`stats` carries registry-backed per-tier p50/p99 + refresh lag, and the
`metrics` op serves the full registry snapshot.
"""

import json
import os
import re
import socket
import subprocess
import sys

import numpy as np
import pytest

from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu.config import Config, parse_config
from bnsgcn_tpu.data.graph import sbm_graph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------------
# histogram / registry units
# ----------------------------------------------------------------------------

def test_histogram_known_quantiles():
    """1..1000 observed in shuffled order: every quantile must land within
    the documented bucket error bound (sqrt(growth) - 1 ~= 4.4% at the
    default growth) of the exact order statistic."""
    h = obs_mod.Histogram()
    vals = np.arange(1, 1001, dtype=np.float64)
    rng = np.random.default_rng(0)
    rng.shuffle(vals)
    for v in vals:
        h.observe(float(v))
    assert h.count == 1000
    assert h.total == pytest.approx(float(vals.sum()))
    assert h.vmin == 1.0 and h.vmax == 1000.0
    for q, exact in ((50, 500.0), (90, 900.0), (99, 990.0)):
        got = h.percentile(q)
        assert abs(got - exact) <= 0.06 * exact, (q, got, exact)
    snap = h.snapshot()
    assert snap["count"] == 1000 and snap["max"] == 1000.0
    assert snap["p50"] == pytest.approx(h.percentile(50))


def test_histogram_empty_single_and_clamping():
    h = obs_mod.Histogram()
    assert h.percentile(50) == 0.0
    assert h.snapshot()["count"] == 0
    h.observe(3.7)
    # a one-sample histogram must report the sample, not a bucket midpoint
    # outside [vmin, vmax]
    assert h.percentile(50) == pytest.approx(3.7)
    assert h.percentile(99) == pytest.approx(3.7)
    h2 = obs_mod.Histogram()
    h2.observe(0.0)         # underflow bucket (below lo)
    h2.observe(1e9)         # overflow bucket
    h2.observe(float("nan"))    # non-finite: dropped, never a crash
    h2.observe(float("inf"))
    assert h2.count == 2
    assert h2.percentile(1) == pytest.approx(0.0)
    assert h2.percentile(99) == pytest.approx(1e9)


def test_registry_snapshot_and_idempotent_instruments():
    r = obs_mod.Registry()
    c = r.counter("a/b")
    c.inc()
    c.inc(4)
    assert r.counter("a/b") is c            # creation is idempotent
    r.gauge("g").set(2.5)
    r.histogram("h").observe(10.0)
    snap = r.snapshot()
    assert snap["counters"]["a/b"] == 5
    assert snap["gauges"]["g"] == 2.5
    assert snap["histograms"]["h"]["count"] == 1


# ----------------------------------------------------------------------------
# event log: rank tag, rotation bound, strict JSON
# ----------------------------------------------------------------------------

def test_eventlog_emit_and_load(tmp_path):
    path = str(tmp_path / "obs.jsonl")
    ev = obs_mod.EventLog(path, rank=3)
    ev.emit("epoch", epoch=1, loss=0.5)
    ev.emit("rollback", epoch=2, restart=1)
    ev.close()
    got = obs_mod.load_events(path)
    assert [e["kind"] for e in got] == ["epoch", "rollback"]
    assert all(e["rank"] == 3 and "ts" in e for e in got)


def test_eventlog_rotation_bound(tmp_path):
    """A size-capped log rotates once (PATH.1) and total disk stays bounded
    at ~2x the cap no matter how many events land."""
    path = str(tmp_path / "obs.jsonl")
    ev = obs_mod.EventLog(path, max_bytes=2000)
    for i in range(300):
        ev.emit("epoch", epoch=i, loss=1.0 / (i + 1))
    ev.close()
    assert os.path.exists(path) and os.path.exists(path + ".1")
    total = os.path.getsize(path) + os.path.getsize(path + ".1")
    assert total <= 2 * 2000 + 200      # one event of slack per file
    # both generations parse, and load_events stitches them oldest-first
    got = obs_mod.load_events(path)
    assert len(got) >= 2
    assert got[0]["epoch"] < got[-1]["epoch"]


def test_eventlog_nan_is_strict_json(tmp_path):
    """The rollback event's whole point is recording a NaN loss — the line
    must still parse under a STRICT reader (no bare NaN token)."""
    path = str(tmp_path / "obs.jsonl")
    ev = obs_mod.EventLog(path)
    ev.emit("rollback", loss=float("nan"), inf=float("inf"),
            nested={"v": float("nan")})
    ev.close()
    line = open(path).read().strip()

    def no_const(_):
        raise AssertionError("non-strict JSON constant in event line")

    rec = json.loads(line, parse_constant=no_const)
    assert rec["loss"] == "nan" and rec["nested"]["v"] == "nan"


def test_rank_log_path_and_make_obs(tmp_path):
    assert obs_mod.rank_log_path("/x/o.jsonl", 0) == "/x/o.jsonl"
    assert obs_mod.rank_log_path("/x/o.jsonl", 2) == "/x/o.jsonl.r2"
    cfg = Config(obs="off", obs_log=str(tmp_path / "o.jsonl"))
    assert obs_mod.make_obs(cfg, log=lambda *a: None) is None
    cfg = Config(obs="on", obs_log=str(tmp_path / "o.jsonl"))
    obs = obs_mod.make_obs(cfg, rank=1, log=lambda *a: None)
    obs.emit("x")
    obs.close()
    assert os.path.exists(str(tmp_path / "o.jsonl.r1"))


def test_obs_report_renders_nan_sanitized_records(tmp_path):
    """A --resilience off diverged run logs epoch records with loss "nan"
    (the strict-JSON sanitization); the report tool must render — not
    crash on — exactly the log it exists to triage."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "div.jsonl")
    ev = obs_mod.EventLog(path)
    for e in range(3):
        ev.emit("epoch", epoch=e, loss=float("nan") if e else 1.2,
                step_s=0.01, comm_s=float("nan"), comm_tag="sampled")
    ev.emit("eval", epoch=2, val_acc=float("nan"))
    ev.close()
    s = obs_report.summarize(obs_report.load_run([path]))
    lines = []
    obs_report.render(s, write=lines.append)
    assert any("nan" in ln for ln in lines)
    obs_report.compare(s, s, path, path, write=lines.append)


def test_obs_report_elastic_resize_section_and_compare_note(tmp_path):
    """An elastic run's resize events (every member mirrors the agreed
    verdict into its own rank log) render as ONE de-duplicated world-size
    timeline, and --compare flags a resize-trail difference as a NOTE —
    the trajectories part ways at the shrink epoch by design."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "elastic.jsonl")
    ev = obs_mod.EventLog(path)
    for rank in (0, 1):     # rank 1's mirror of the same shrink verdict
        ev.emit("resize", rank=rank, epoch=3, old_world=2, world=1,
                members=[0], lost=[1], slots=[0, 0], trigger="ranklost",
                nonce=1, restart=2, source="ckpt_E1.ckpt")
    ev.emit("resize", rank=0, epoch=5, old_world=1, world=2,
            members=[0, 1], lost=[], slots=[0, 1], trigger="rejoin",
            nonce=1, restart=2, source="ckpt_E1.ckpt")
    ev.close()
    s = obs_report.summarize(obs_report.load_run([path]))
    assert len(obs_report._resize_verdicts(s)) == 2     # mirrors collapsed
    lines = []
    obs_report.render(s, write=lines.append)
    text = "\n".join(lines)
    assert "elastic resizes (2 verdict(s)):" in text
    assert "2->1   ranklost" in text and "(lost [1])" in text
    assert "1->2   rejoin" in text
    assert "r0:[p0,p1]" in text and "r0:[p0] r1:[p1]" in text
    # --compare: a resized run vs an uninterrupted one gets the NOTE...
    plain = str(tmp_path / "plain.jsonl")
    pv = obs_mod.EventLog(plain)
    pv.emit("epoch", epoch=0, loss=1.0, step_s=0.01)
    pv.close()
    sp = obs_report.summarize(obs_report.load_run([plain]))
    lines = []
    obs_report.compare(sp, s, plain, path, write=lines.append)
    note = next(ln for ln in lines if "elastic RESIZE" in ln)
    assert "A: none" in note and "E3:ranklost 2->1" in note
    assert "from epoch 3 on" in note
    # ...while identical resize trails stay silent
    lines = []
    obs_report.compare(s, s, path, path, write=lines.append)
    assert not any("elastic RESIZE" in ln for ln in lines)


def test_obs_report_serving_fleet_section(tmp_path):
    """Sharded-serving logs (router rank 0 + backend `.rN` siblings) render
    a per-backend fleet table plus the router fan-out line, while the
    legacy single-host `serve` slot keeps its meaning: it only ever holds a
    drain record WITHOUT a backend tag."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    path = str(tmp_path / "fleet.jsonl")
    ev = obs_mod.EventLog(path)       # rank 0 = the router
    ev.emit("serve_fleet", parts=2, replicas=1, shutdown_acked=2,
            requests=40, tier_a=36, tier_b=4, deltas=3, fanout_rpcs=9,
            evictions=0)
    ev.close()
    for part in (0, 1):               # backend shards on sibling logs
        bev = obs_mod.EventLog(obs_mod.rank_log_path(path, 1 + part))
        bev.emit("serve_drain", requests=20, tier_a=18, tier_b=2,
                 deltas=3, refreshed_nodes=5, part=part, replica=0,
                 backend=f"p{part}.r0", n_own=150, queue_depth=0,
                 tier_a_p50_ms=0.4, tier_a_p99_ms=1.1, tier_b_p50_ms=8.0,
                 tier_b_p99_ms=20.0, refresh_lag_p50_s=0.01,
                 refresh_lag_p99_s=0.05, halo_cached=7, halo_fetches=2,
                 halo_hits=11)
        bev.close()
    s = obs_report.summarize(obs_report.load_run([path]))
    assert s["serve"] is None                 # no untagged drain in this log
    assert len(s["serve_drains"]) == 2
    assert s["serve_fleet"]["fanout_rpcs"] == 9
    lines = []
    obs_report.render(s, write=lines.append)
    text = "\n".join(lines)
    assert "serving fleet:" in text
    assert "p0.r0" in text and "p1.r0" in text
    assert "9 fan-out RPCs" in text
    # a single-host drain (no backend tag) still lands in the legacy slot
    s2 = obs_report.summarize([{"kind": "serve_drain", "requests": 1,
                                "ts": 0.0}])
    assert s2["serve"] is not None and s2["serve_drains"]


def test_write_postmortem_failure_returns_empty():
    """An unwritable post-mortem dir returns "" (no breadcrumb to a ghost
    file) instead of a path that was never written."""
    assert obs_mod.write_postmortem("/proc/nonexistent/pm", "t") == ""


def test_eventlog_unwritable_path_degrades_not_raises(capsys):
    """An unwritable $BNSGCN_OBS_LOG must degrade to a no-log run at
    construction — never crash-loop a watchdog5 relaunch before training."""
    ev = obs_mod.EventLog("/proc/nonexistent/obs.jsonl")
    ev.emit("epoch", epoch=0)       # no-op, no raise
    ev.close()
    assert "telemetry log disabled" in capsys.readouterr().err


def test_eventlog_bad_max_mb_env_degrades(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("BNSGCN_OBS_MAX_MB", "64MB")
    ev = obs_mod.EventLog(str(tmp_path / "o.jsonl"))
    assert ev.max_bytes == 64 * 2 ** 20
    ev.emit("x")
    ev.close()
    assert "bad $BNSGCN_OBS_MAX_MB" in capsys.readouterr().err


def test_eventlog_emit_bounded_skips_on_held_lock(tmp_path):
    """The watchdog's exit-path emit must give up on a held writer lock
    (a disk-stalled main thread inside emit) instead of deadlocking the
    os._exit(77) escape hatch."""
    ev = obs_mod.EventLog(str(tmp_path / "o.jsonl"))
    ev.emit("a")
    assert ev._lock.acquire()       # simulate a stalled writer holding it
    try:
        t0 = __import__("time").monotonic()
        ev.emit_bounded("watchdog_fire", timeout_s=0.2)
        assert __import__("time").monotonic() - t0 < 2.0
    finally:
        ev._lock.release()
    ev.emit_bounded("b")            # lock free again: this one lands
    ev.close()
    kinds = [e["kind"] for e in obs_mod.load_events(str(tmp_path / "o.jsonl"))]
    assert kinds == ["a", "b"]      # the blocked emit was skipped, not queued


def test_write_postmortem(tmp_path):
    r = obs_mod.Registry()
    r.counter("c").inc()
    path = obs_mod.write_postmortem(str(tmp_path / "pm"), "watchdog_E3",
                                    text="hung", registry=r)
    body = open(path).read()
    assert "hung" in body and "all-thread stacks" in body
    metrics = path.replace(".txt", "_metrics.json")
    assert json.load(open(metrics))["counters"]["c"] == 1


def test_cli_obs_flags_parse():
    cfg = parse_config(["--obs", "off", "--obs-log", "/tmp/x.jsonl",
                        "--obs-dir", "/tmp/pm"])
    assert (cfg.obs, cfg.obs_log, cfg.obs_dir) == ("off", "/tmp/x.jsonl",
                                                   "/tmp/pm")
    assert parse_config([]).obs == "on"


# ----------------------------------------------------------------------------
# --obs off == on, bitwise (the bus must never touch training math)
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_graph():
    return sbm_graph(n_nodes=240, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                     seed=3)


def _base_cfg(tmp_path, **kw):
    d = dict(dataset="sbm", model="graphsage", n_partitions=2, n_layers=2,
             n_hidden=8, sampling_rate=0.5, dropout=0.5, use_pp=True,
             eval=False, n_epochs=8, log_every=2, seed=7, comm_trace=False,
             part_path=str(tmp_path / "parts"),
             ckpt_path=str(tmp_path / "ckpt"),
             results_path=str(tmp_path / "res"))
    d.update(kw)
    return Config(**d)


def test_obs_off_bitwise_identical_to_on(tmp_path, small_graph):
    from bnsgcn_tpu.run import run_training
    r_off = run_training(
        _base_cfg(tmp_path, obs="off", ckpt_path=str(tmp_path / "c0")),
        g=small_graph, verbose=False)
    r_on = run_training(
        _base_cfg(tmp_path, obs="on",
                  obs_log=str(tmp_path / "obs.jsonl"),
                  ckpt_path=str(tmp_path / "c1")),
        g=small_graph, verbose=False)
    np.testing.assert_array_equal(r_off.losses, r_on.losses)
    assert r_off.final_loss == r_on.final_loss
    # and the on-run actually recorded its trail
    kinds = {e["kind"] for e in
             obs_mod.load_events(str(tmp_path / "obs.jsonl"))}
    assert {"run_header", "epoch", "run_end"} <= kinds


def test_rollback_run_leaves_lifecycle_trail(tmp_path, small_graph,
                                             monkeypatch):
    """In-process: a nan@E5 divergence leaves inject + rollback events whose
    fields match the RunResult, and the header records the resolved mesh."""
    monkeypatch.setenv("BNSGCN_RETRY_BACKOFF_S", "0")
    from bnsgcn_tpu.run import run_training
    log = str(tmp_path / "obs.jsonl")
    res = run_training(_base_cfg(tmp_path, obs_log=log, inject="nan@E5"),
                       g=small_graph, verbose=False)
    evs = obs_mod.load_events(log)
    kinds = [e["kind"] for e in evs]
    assert kinds.count("run_header") == 1 and "run_end" in kinds
    hdr = next(e for e in evs if e["kind"] == "run_header")
    assert hdr["parts"] == 2 and hdr["config"]["model"] == "graphsage"
    assert hdr["wire_mb_per_exchange"] > 0
    rb = [e for e in evs if e["kind"] == "rollback"]
    assert len(rb) == len(res.rollbacks) == 1
    assert rb[0]["epoch"] == 5 and rb[0]["nonce"] == 1
    assert rb[0]["loss"] == "nan"       # sanitized, not a bare NaN token
    inj = [e for e in evs if e["kind"] == "inject"]
    assert inj and inj[0]["kind_injected"] == "nan"
    # per-epoch records cover every EXECUTED epoch: the diverged epoch-5
    # pass rolls back before its record (no poisoned row), and the restart
    # epoch (4, from the epoch-3 checkpoint) is recorded twice
    eps = [e["epoch"] for e in evs if e["kind"] == "epoch"]
    assert eps.count(4) == 2 and eps.count(5) == 1
    assert max(eps) == 7


# ----------------------------------------------------------------------------
# e2e through the real CLI (the artifact the ROADMAP campaigns audit)
# ----------------------------------------------------------------------------

def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", BNSGCN_COORD_TIMEOUT_S="60",
               PYTHONPATH=REPO)
    env.update(extra or {})
    return env


BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


@pytest.mark.quickgate
def test_cli_obs_e2e_and_report(tmp_path):
    """A real `--inject nan@E5` CLI run produces a parseable JSONL log with
    header + epoch + rollback + run_end, and tools/obs_report.py renders it
    without error."""
    log = str(tmp_path / "obs.jsonl")
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
        + ["--part-path", str(tmp_path / "parts"),
           "--ckpt-path", str(tmp_path / "ckpt"),
           "--results-path", str(tmp_path / "res"),
           "--inject", "nan@E5", "--obs-log", log],
        capture_output=True, text=True, timeout=240, cwd=REPO, env=_env())
    assert r.returncode == 0, r.stdout + r.stderr
    kinds = [e["kind"] for e in obs_mod.load_events(log)]
    for want in ("run_header", "epoch", "inject", "rollback", "run_end"):
        assert want in kinds, (want, kinds)
    rep = subprocess.run(
        [sys.executable, "tools/obs_report.py", log],
        capture_output=True, text=True, timeout=60, cwd=REPO, env=_env())
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "rollback" in rep.stdout and "per-epoch" in rep.stdout
    # --compare against itself must also render (the bench-window audit path)
    cmp_ = subprocess.run(
        [sys.executable, "tools/obs_report.py", "--compare", log, log],
        capture_output=True, text=True, timeout=60, cwd=REPO, env=_env())
    assert cmp_.returncode == 0, cmp_.stdout + cmp_.stderr
    assert "mean step" in cmp_.stdout


@pytest.mark.quickgate
def test_two_rank_merged_epoch_record(tmp_path):
    """2 real coordinated processes (the PR-5 harness): each rank's epoch
    summary piggybacks on agree_step's verdict value, and rank 0's log holds
    ONE merged `epoch_ranks` record per epoch naming both ranks — no new
    collective existed for this (pinned by the coord suite's lockstep seq
    accounting staying green)."""
    subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.partition_cli",
         "--dataset", "sbm", "--partition-method", "random",
         "--n-partitions", "2", "--fix-seed",
         "--part-path", str(tmp_path / "parts")],
        env=_env(), check=True, capture_output=True, cwd=REPO)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    log = str(tmp_path / "obs.jsonl")
    procs = []
    for rank in (0, 1):
        cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
               + ["--skip-partition", "--n-epochs", "6",
                  "--part-path", str(tmp_path / "parts"),
                  "--ckpt-path", str(tmp_path / f"ck{rank}"),
                  "--results-path", str(tmp_path / "res"),
                  "--coord", "tcp", "--coord-port", str(port),
                  "--coord-world", "2", "--coord-rank", str(rank),
                  "--obs-log", log])
        procs.append(subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                      stderr=subprocess.STDOUT, text=True,
                                      cwd=REPO, env=_env()))
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append((p.returncode, out))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    assert [rc for rc, _ in outs] == [0, 0], outs
    # rank 0 owns the bare path; rank 1 wrote its own .r1 sibling
    ev0 = obs_mod.load_events(log)
    merged = [e for e in ev0 if e["kind"] == "epoch_ranks"]
    assert merged, [e["kind"] for e in ev0]
    for rec in merged:
        assert set(rec["ranks"]) == {"0", "1"}
        for info in rec["ranks"].values():
            assert "loss" in info and "step_ms" in info
    # exactly one merged record per executed epoch, all on rank 0
    assert sorted(rec["epoch"] for rec in merged) == list(range(6))
    assert all(rec["rank"] == 0 for rec in merged)
    ev1 = obs_mod.load_events(log + ".r1")
    assert any(e["kind"] == "epoch" and e["rank"] == 1 for e in ev1)
    assert not any(e["kind"] == "epoch_ranks" for e in ev1)


# ----------------------------------------------------------------------------
# serving: registry-backed stats + the metrics op
# ----------------------------------------------------------------------------

def test_serve_stats_percentiles_and_metrics_op():
    import jax

    from bnsgcn_tpu import serve
    from bnsgcn_tpu.models.gnn import init_params, spec_from_config
    g = sbm_graph(n_nodes=120, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                  seed=3)
    cfg = Config(dataset="sbm", model="graphsage", n_layers=2, n_hidden=8,
                 use_pp=True, n_feat=g.n_feat, n_class=g.n_class,
                 n_train=g.n_train)
    spec = spec_from_config(cfg)
    params, state = init_params(jax.random.key(0), spec)
    core = serve.build_core(cfg, g, params, state, log=lambda *a: None)
    try:
        for n in (1, 2, 3):
            core.predict(n)                 # tier A
        core.add_edges([[0, 1]])
        core.predict(1)                     # dirty -> tier B
        core.flush()
        st = core.snapshot_stats()
        # previously counters only; now registry-backed latency + lag
        assert st["tier_a_p50_ms"] > 0 and st["tier_a_p99_ms"] > 0
        assert st["tier_b_p50_ms"] > 0
        assert st["tier_b_p99_ms"] >= st["tier_b_p50_ms"]
        assert st["refresh_lag_p50_s"] > 0  # the flushed dirty row's age
        assert st["refresh_lag_s"] == 0.0   # nothing left dirty
        assert st["queue_depth"] == 0
        # old counter vocabulary intact (BENCH/serve_bench compatibility)
        assert st["requests"] == 4 and st["tier_b"] == 1
        server = serve.ServeServer(core, port=0, log=lambda *a: None)
        try:
            m = server._handle({"op": "metrics"})
            assert m["ok"]
            hists = m["metrics"]["histograms"]
            assert hists["serve/latency_ms/A"]["count"] == 3
            assert hists["serve/latency_ms/B"]["count"] == 1
            assert hists["serve/refresh_lag_s"]["count"] >= 1
            assert m["metrics"]["gauges"]["serve/dirty"] == 0
            s2 = server._handle({"op": "stats"})
            assert s2["ok"] and s2["tier_b_p99_ms"] > 0
        finally:
            server.drain(timeout_s=5.0)
    finally:
        core.close()
