"""Elastic world size (--elastic on): coordinated RESIZE instead of exit.

The tentpole contract, proven with real subprocesses on the CPU container
(the same external-rank harness as tests/test_coord_e2e.py):

* rank loss at W=2 -> the survivor detects the heartbeat silence, agrees a
  RESIZE verdict with itself, re-maps both parts onto its slots
  (mesh.plan_slots — no METIS rerun), restores the agreed checkpoint with
  the resize nonce folded into the sampling/dropout streams, and trains to
  completion with exit 0 — no process ever exits non-zero;
* a replacement rank relaunched after the shrink verdict rejoins through
  the lost-rank beacon, the world grows back to W=2, and the healed final
  loss is BITWISE the shrink-only run's (grow restores the newest valid
  checkpoint with NO new nonce, so the replay is timing-independent);
* --elastic off (the default) and --elastic on with no fault are both
  bitwise-identical to the historical coordinated pair;
* the verdict cadence knob ($BNSGCN_COORD_AGREE_EVERY) defers off-boundary
  exchanges while latching the worst local state — verdict latency is at
  most K boundaries, and `final=True` always flushes.

tools/fault_matrix.sh runs the shrink/grow stages from the shell.
"""

import os
import re
import socket
import subprocess
import sys
import threading
import time

import pytest

from bnsgcn_tpu import obs as obs_mod
from bnsgcn_tpu.config import ConfigError
from bnsgcn_tpu.parallel.coord import Coordinator, TcpTransport
from bnsgcn_tpu.parallel.mesh import plan_slots, slot_members
from bnsgcn_tpu.parallel.replicas import slot_desc
from bnsgcn_tpu.resilience import FaultPlan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11", "--skip-partition",
]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", BNSGCN_COORD_TIMEOUT_S="60",
               # fast loss detection: 3s > the 2s alive-beat period
               BNSGCN_ELASTIC_DEAD_S="3",
               PYTHONPATH=REPO)
    env.update(extra or {})
    return env


def _prepartition(tmp_path):
    subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.partition_cli",
         "--dataset", "sbm", "--partition-method", "random",
         "--n-partitions", "2", "--fix-seed",
         "--part-path", str(tmp_path / "parts")],
        env=_env(), check=True, capture_output=True, cwd=REPO)


def _cmd(tmp_path, ckpt, port, rank, extra_args=()):
    return ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
            + ["--part-path", str(tmp_path / "parts"),
               "--ckpt-path", str(ckpt),
               "--results-path", str(tmp_path / "res"),
               "--coord", "tcp", "--coord-port", str(port),
               "--coord-world", "2", "--coord-rank", str(rank)]
            + list(extra_args))


def _spawn(tmp_path, ckpt, port, rank, extra_args=(), env=None, tag=""):
    """One rank process with stdout to a FILE (pollable mid-run)."""
    logf = open(tmp_path / f"rank{rank}{tag}.log", "w")
    p = subprocess.Popen(
        _cmd(tmp_path, ckpt, port, rank, extra_args),
        stdout=logf, stderr=subprocess.STDOUT, text=True, cwd=REPO,
        env=env or _env())
    p._logf, p._logpath = logf, logf.name
    return p


def _finish(p, timeout=240):
    try:
        rc = p.wait(timeout=timeout)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
        p._logf.close()
    with open(p._logpath) as f:
        return rc, f.read()


def _wait_for(path, needle, timeout=120):
    dl = time.time() + timeout
    while time.time() < dl:
        with open(path) as f:
            if needle in f.read():
                return True
        time.sleep(0.25)
    return False


def _run_pair(tmp_path, ckpt, extra_args=(), env=None, timeout=240):
    port = _free_port()
    procs = [_spawn(tmp_path, ckpt, port, r, extra_args, env=env)
             for r in (0, 1)]
    return [_finish(p, timeout) for p in procs]


def _final_loss(out: str) -> str:
    m = re.search(r"RESULT final_loss=(\S+)", out)
    assert m, f"no RESULT line in output:\n{out[-2000:]}"
    return m.group(1)       # string compare == bitwise pin


# ----------------------------------------------------------------------------
# part -> slot planning (mesh.plan_slots) + rendering
# ----------------------------------------------------------------------------

def test_plan_slots_contiguous_balanced_blocks():
    assert plan_slots(4, 2) == (0, 0, 1, 1)
    assert plan_slots(5, 2) == (0, 0, 0, 1, 1)
    assert plan_slots(4, 3) == (0, 0, 1, 2)
    # identity at P == W: today's worker-per-part layout
    assert plan_slots(4, 4) == (0, 1, 2, 3)
    assert plan_slots(1, 1) == (0,)
    with pytest.raises(ValueError):
        plan_slots(4, 0)
    with pytest.raises(ValueError):
        plan_slots(2, 3)            # empty workers are never planned


def test_slot_members_inverse_view():
    assert slot_members((0, 0, 1, 1)) == {0: [0, 1], 1: [2, 3]}
    # works on part -> rank maps too (a RESIZE decision's 'slots')
    assert slot_members((2, 2, 5, 5)) == {2: [0, 1], 5: [2, 3]}


def test_slot_desc_renders_hosting_ranks():
    assert slot_desc((0, 0, 1, 1), [0, 1]) == "rank0:[p0,p1] rank1:[p2,p3]"
    # survivor set {0, 2}: parts re-hosted onto the remaining rank ids
    assert slot_desc((0, 0, 2, 2), [0, 2]) == "rank0:[p0,p1] rank2:[p2,p3]"
    # empty map = identity world (worker == part)
    assert slot_desc((), [0, 1]) == "rank0:[p0] rank1:[p1]"


# ----------------------------------------------------------------------------
# --inject ranklost grammar
# ----------------------------------------------------------------------------

def test_ranklost_grammar_requires_rank_target():
    with pytest.raises(ConfigError, match="losing every rank"):
        FaultPlan.parse("ranklost@E3")
    # targeted form arms only the named rank; the other ranks validate the
    # term but skip it
    assert FaultPlan.parse("ranklost@E3:r1", rank=1).faults == {
        "ranklost": {3}}
    assert FaultPlan.parse("ranklost@E3:r1", rank=0).empty()
    with pytest.raises(ValueError, match="unknown --inject fault"):
        FaultPlan.parse("rankloss@E3:r1")


# ----------------------------------------------------------------------------
# verdict cadence ($BNSGCN_COORD_AGREE_EVERY)
# ----------------------------------------------------------------------------

def _cadence_pair(k=None):
    port = _free_port()
    t0 = TcpTransport("127.0.0.1", port, serve=True)
    t1 = TcpTransport("127.0.0.1", port, serve=False)
    return (Coordinator(0, 2, t0, 10.0, log=lambda *a: None),
            Coordinator(1, 2, t1, 10.0, log=lambda *a: None))


def _run2(f0, f1):
    out, errs = {}, {}

    def wrap(rank, fn):
        try:
            out[rank] = fn()
        except Exception as ex:
            errs[rank] = ex

    ts = [threading.Thread(target=wrap, args=(r, f))
          for r, f in ((0, f0), (1, f1))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not errs, errs
    return out[0], out[1]


def test_cadence_defers_latches_and_bounds_verdict_latency(monkeypatch):
    """K=3: off-boundary calls return an immediate deferred 'ok' with no
    exchange; a 'diverged' reported at call 0 latches and MUST be decided
    by call 2 (the K-th boundary) — verdict latency <= K boundaries."""
    monkeypatch.setenv("BNSGCN_COORD_AGREE_EVERY", "3")
    c0, c1 = _cadence_pair()
    try:
        assert c0.agree_every == c1.agree_every == 3

        def decide(name, states):
            assert name == "rollback" and states[1] == "diverged"
            return {"decision": "rollback", "restart": 1, "nonce": 1,
                    "source": "<test>", "backoff_s": 0.0}

        # calls 0 and 1: both ranks defer instantly (no exchange — no
        # threads needed), rank 1's diverged latches
        for ep, s1 in ((0, "diverged"), (1, "ok")):
            d0 = c0.agree(ep, "ok", decide_fn=decide)
            d1 = c1.agree(ep, s1)
            assert d0 == {"decision": "ok", "epoch": ep, "deferred": True}
            assert d1 == {"decision": "ok", "epoch": ep, "deferred": True}
        # call 2 is the K-th boundary: the latched diverged must surface
        d0, d1 = _run2(lambda: c0.agree(2, "ok", decide_fn=decide),
                       lambda: c1.agree(2, "ok"))
        for d in (d0, d1):
            assert d["decision"] == "rollback" and not d.get("deferred")
            assert d["restart"] == 1
    finally:
        c0.close()
        c1.close()


def test_cadence_final_flushes_off_boundary(monkeypatch):
    """final=True (the last step boundary) always exchanges, so a latched
    verdict can never die with the run."""
    monkeypatch.setenv("BNSGCN_COORD_AGREE_EVERY", "4")
    c0, c1 = _cadence_pair()
    try:
        d0 = c0.agree(0, "ok")
        d1 = c1.agree(0, "preempted")
        assert d0.get("deferred") and d1.get("deferred")

        def decide(name, states):
            return {"decision": name, "ranks": [r for r, s in states.items()
                                                if s == "preempted"]}

        d0, d1 = _run2(
            lambda: c0.agree(1, "ok", decide_fn=decide, final=True),
            lambda: c1.agree(1, "ok", final=True))
        for d in (d0, d1):
            assert d["decision"] == "preempt" and not d.get("deferred")
        assert d0["ranks"] == [1]
    finally:
        c0.close()
        c1.close()


def test_cadence_default_is_every_boundary():
    c0, c1 = _cadence_pair()
    try:
        assert c0.agree_every == 1
        d0, d1 = _run2(lambda: c0.agree(0, "ok"), lambda: c1.agree(0, "ok"))
        assert not d0.get("deferred") and not d1.get("deferred")
    finally:
        c0.close()
        c1.close()


# ----------------------------------------------------------------------------
# subprocess e2e: shrink, grow, bitwise pins
# ----------------------------------------------------------------------------

def test_elastic_on_needs_coordinator():
    """--elastic on without the rank coordinator is a named config error
    (exit 2), never a silent no-op."""
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
        + ["--coord", "off", "--elastic", "on", "--part-path", "/nonexistent"],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=_env())
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "--elastic on needs the rank coordinator" in r.stderr


@pytest.mark.quickgate
def test_elastic_shrink_trains_through_rank_loss(tmp_path):
    """The tentpole pin, shrink half: rank 1 vanishes at epoch 3 with no
    goodbye; the survivor imputes the loss from heartbeat silence, agrees
    a RESIZE with itself, re-hosts both parts, folds the resize nonce, and
    trains to completion — exit 0 on every process, resize obs event with
    the part -> rank map emitted."""
    _prepartition(tmp_path)
    obs_log = str(tmp_path / "obs.jsonl")
    outs = _run_pair(tmp_path, tmp_path / "ck",
                     ["--elastic", "on", "--inject", "ranklost@E3:r1",
                      "--obs-log", obs_log])
    assert [rc for rc, _ in outs] == [0, 0], outs
    r0, r1 = outs[0][1], outs[1][1]
    assert "imputing 'lost'" in r0, r0[-2000:]
    assert "agreed resize, world 2 -> 1 (survivors [0])" in r0
    assert "world resized to 1 (members [0], lost [1])" in r0
    assert "ranklost resize to world 1" in r0 and "rank0:[p0,p1]" in r0
    assert "resize-nonce 1" in r0
    assert "RESULT final_loss=" in r0          # trained to completion
    assert "injected rank loss at epoch 3" in r1
    assert "RESULT" not in r1                  # the lost rank never finished
    ev = [e for e in obs_mod.load_events(obs_log) if e["kind"] == "resize"]
    assert len(ev) == 1, ev
    assert ev[0]["old_world"] == 2 and ev[0]["world"] == 1
    assert ev[0]["members"] == [0] and ev[0]["lost"] == [1]
    assert ev[0]["slots"] == [0, 0] and ev[0]["trigger"] == "ranklost"
    assert ev[0]["nonce"] == 1


@pytest.mark.quickgate
def test_elastic_grow_round_trip_bitwise_replay(tmp_path):
    """The tentpole pin, grow half: after the shrink verdict a replacement
    rank 1 relaunches (same CLI, no injection — the documented contract),
    finds the lost-rank beacon, rejoins through the grant handshake, and
    the world grows back to 2. Both ranks finish with exit 0 and BITWISE
    equal final losses; the healed loss also equals a shrink-only run of
    the same fault — grow restores the newest valid checkpoint with NO new
    nonce, so the outcome is independent of when the rejoin happened."""
    _prepartition(tmp_path)
    # throttle epochs so the fast CPU run stays alive across the
    # replacement's process startup (JAX init + compile)
    env = _env({"BNSGCN_EPOCH_THROTTLE_S": "1.0"})
    args = ["--elastic", "on", "--n-epochs", "24"]
    port = _free_port()
    p0 = _spawn(tmp_path, tmp_path / "ck", port, 0, args, env=env)
    p1 = _spawn(tmp_path, tmp_path / "ck", port, 1,
                args + ["--inject", "ranklost@E3:r1"], env=env)
    rc1, out1 = _finish(p1)
    assert rc1 == 0 and "injected rank loss" in out1, out1[-2000:]
    # the relaunch contract: the replacement comes up AFTER the shrink
    # verdict has landed on the survivor
    assert _wait_for(p0._logpath, "world resized to 1"), "no shrink verdict"
    p1b = _spawn(tmp_path, tmp_path / "ck", port, 1, args, env=env, tag="b")
    rc0, out0 = _finish(p0, timeout=300)
    rc1b, out1b = _finish(p1b, timeout=300)
    assert rc0 == 0 and rc1b == 0, (rc0, out0[-2000:], rc1b, out1b[-2000:])
    assert "rejoined at epoch" in out0 and "world resized to 2" in out0
    assert "rejoining a resized world (lost-rank beacon found)" in out1b
    assert "rejoined world 2" in out1b and "in lockstep" in out1b
    healed = _final_loss(out0)
    assert _final_loss(out1b) == healed        # joiner is bitwise in step

    # deterministic replay: the same fault with NO rejoin must land on the
    # same trajectory (throttle off — wall time never changes the numbers)
    outs = _run_pair(tmp_path, tmp_path / "ck_replay",
                     args + ["--inject", "ranklost@E3:r1"], timeout=300)
    assert outs[0][0] == 0, outs[0][1][-2000:]
    assert _final_loss(outs[0][1]) == healed


@pytest.mark.quickgate
def test_elastic_off_and_idle_elastic_on_are_bitwise_identical(tmp_path):
    """--elastic off (the default protocol, exit-code table unchanged) and
    --elastic on with no fault must both be bitwise the historical
    coordinated pair: elastic only changes what a rank LOSS means."""
    _prepartition(tmp_path)
    off = _run_pair(tmp_path, tmp_path / "ck_off")
    assert [rc for rc, _ in off] == [0, 0], off
    want = _final_loss(off[0][1])
    assert _final_loss(off[1][1]) == want
    on = _run_pair(tmp_path, tmp_path / "ck_on", ["--elastic", "on"])
    assert [rc for rc, _ in on] == [0, 0], on
    assert _final_loss(on[0][1]) == want
    assert _final_loss(on[1][1]) == want
    # no resize machinery ever engaged
    for _, out in on:
        assert "resize" not in out
