"""CPU preflight of the serving load generator (tools/serve_bench.py).

Mirror of tests/test_bench_preflight.py for the serving bench: the ACTUAL
tool runs as a subprocess at tiny scale on CPU and must emit every metric
in bench.py's SERVE_METRICS vocabulary, for both tiers, as parseable JSON
lines — a serve-bench invocation that cannot produce its metrics here would
waste a hardware window (and the driver would record an empty BENCH entry).
"""

import json
import os
import subprocess
import sys

import pytest

from bench import SERVE_METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    # the suite conftest forces an 8-device CPU mesh; the serving bench
    # needs no mesh — drop the forced device count for the subprocess
    flags = env.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", "").strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_serve_bench_emits_full_metric_vocabulary():
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
           "--requests", "40", "--concurrency", "2", "--warmup", "4",
           "--hidden", "8", "--json-only"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                       cwd=REPO, env=_env())
    tail = "\n".join((r.stdout + "\n" + r.stderr).splitlines()[-25:])
    assert r.returncode == 0, f"serve_bench failed preflight:\n{tail}"
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON metric lines:\n{tail}"
    seen = {(ln["metric"], ln.get("tier")) for ln in lines}
    for metric in SERVE_METRICS:
        for tier in ("A", "B"):
            assert (metric, tier) in seen, f"missing {metric}/{tier}:\n{tail}"
    for ln in lines:
        assert ln["metric"] in SERVE_METRICS, f"off-vocabulary: {ln}"
        assert ln["unit"] == SERVE_METRICS[ln["metric"]]
        assert ln["value"] > 0, f"non-positive metric: {ln}"
    # last line wins for the driver: it must be a valid vocabulary metric
    last = lines[-1]
    assert last["metric"] == "serve_qps" and last["tier"] == "A"


@pytest.mark.slow
def test_bench_serve_dispatch_tags_backend_counts():
    """bench.py --serve both: the driver-facing entry point runs BOTH
    serving variants (single-host and the 2-part router-fronted fleet)
    and every metric line carries the backend-count tags that keep a
    serve1 number from ever being compared against a serve2p one."""
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--serve", "both", "--serve-requests", "24",
           "--serve-concurrency", "2"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                       cwd=REPO, env=_env())
    tail = "\n".join((r.stdout + "\n" + r.stderr).splitlines()[-25:])
    assert r.returncode == 0, f"bench --serve failed preflight:\n{tail}"
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON metric lines:\n{tail}"
    by_variant: dict = {}
    for ln in lines:
        assert ln["metric"] in SERVE_METRICS, f"off-vocabulary: {ln}"
        assert ln["variant"] in ("serve1", "serve2p"), ln
        by_variant.setdefault(ln["variant"], []).append(ln)
    assert set(by_variant) == {"serve1", "serve2p"}, f"missing variant:\n{tail}"
    assert all(ln["backends"] == 1 for ln in by_variant["serve1"])
    for ln in by_variant["serve2p"]:
        assert ln["backends"] == 2
        # the routed fleet measured its own router tax vs a direct backend
        assert ln["router_overhead_x"] > 0
    # both variants emit the full vocabulary for both tiers
    for variant, vlines in by_variant.items():
        seen = {(ln["metric"], ln.get("tier")) for ln in vlines}
        for metric in SERVE_METRICS:
            for tier in ("A", "B"):
                assert (metric, tier) in seen, \
                    f"missing {metric}/{tier} in {variant}:\n{tail}"
    # last line wins: the serve2p tier-A qps closes the run
    last = lines[-1]
    assert last["metric"] == "serve_qps" and last["tier"] == "A"
    assert last["variant"] == "serve2p" and last["backends"] == 2
