"""CPU preflight of the serving load generator (tools/serve_bench.py).

Mirror of tests/test_bench_preflight.py for the serving bench: the ACTUAL
tool runs as a subprocess at tiny scale on CPU and must emit every metric
in bench.py's SERVE_METRICS vocabulary, for both tiers, as parseable JSON
lines — a serve-bench invocation that cannot produce its metrics here would
waste a hardware window (and the driver would record an empty BENCH entry).
"""

import json
import os
import subprocess
import sys

import pytest

from bench import SERVE_METRICS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    # the suite conftest forces an 8-device CPU mesh; the serving bench
    # needs no mesh — drop the forced device count for the subprocess
    flags = env.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", "").strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_serve_bench_emits_full_metric_vocabulary():
    cmd = [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
           "--requests", "40", "--concurrency", "2", "--warmup", "4",
           "--hidden", "8", "--json-only"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                       cwd=REPO, env=_env())
    tail = "\n".join((r.stdout + "\n" + r.stderr).splitlines()[-25:])
    assert r.returncode == 0, f"serve_bench failed preflight:\n{tail}"
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON metric lines:\n{tail}"
    seen = {(ln["metric"], ln.get("tier")) for ln in lines}
    for metric in SERVE_METRICS:
        for tier in ("A", "B"):
            assert (metric, tier) in seen, f"missing {metric}/{tier}:\n{tail}"
    for ln in lines:
        assert ln["metric"] in SERVE_METRICS, f"off-vocabulary: {ln}"
        assert ln["unit"] == SERVE_METRICS[ln["metric"]]
        assert ln["value"] > 0, f"non-positive metric: {ln}"
    # last line wins for the driver: it must be a valid vocabulary metric
    last = lines[-1]
    assert last["metric"] == "serve_qps" and last["tier"] == "A"
