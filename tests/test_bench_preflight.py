"""CPU preflight of every queued tunnel-window bench run.

Round-4 postmortem (VERDICT r4, "What's weak" #3): three hardware launches
crashed on a scan-carry-type mismatch that only manifested through bench.py's
exact worker path with the TPU-side accumulation choice — a code path no CPU
test compiled. With ~4 h tunnel windows, each such escape costs a measurable
fraction of a round.

This test runs the ACTUAL ``bench.py`` worker (subprocess, supervisor
bypassed) for each physical line of ``.watch_queue``, at tiny scale on CPU,
with ``BNSGCN_BENCH_PREFLIGHT=1`` forcing the TPU code-path decisions
(unrolled ELL accumulation, Pallas candidate vocabulary — kernel bodies fall
back to their XLA twins off-TPU, whose logic the dedicated interpret-mode
tests pin). A queue line that cannot produce a winner here would waste a
tunnel window; the suite fails before that can happen.

Reference test-strategy analog: the reference's scripts ARE its integration
harness (SURVEY §4); this is that idea turned into an executable gate for
the hardware queue.
"""

import json
import os
import shlex
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
QUEUE = os.path.join(REPO, ".watch_queue")

# Flags the preflight overrides (argparse last-occurrence-wins, so simply
# appending ours after the queue line's own flags is enough).
_OVERRIDES = ["--scale", "0.005", "--epochs", "2", "--budget-s", "600"]


def queue_lines():
    if not os.path.exists(QUEUE):
        return []
    with open(QUEUE) as f:
        return [ln.strip() for ln in f if ln.strip()]


def _preflight_env(cache_dir):
    env = dict(os.environ)
    env.update(
        # beat the axon sitecustomize BEFORE interpreter start — a wedged
        # tunnel hangs jax import otherwise
        PALLAS_AXON_POOL_IPS="",
        JAX_PLATFORMS="cpu",
        BNSGCN_BENCH_WORKER="1",      # run the worker path, not the supervisor
        BNSGCN_BENCH_ALLOW_CPU="1",
        BNSGCN_BENCH_PREFLIGHT="1",   # TPU code-path decisions on CPU
    )
    # the suite conftest forces an 8-device CPU mesh; the bench worker uses a
    # 1-part mesh, so drop the forced device count for the subprocess
    flags = env.get("XLA_FLAGS", "").replace(
        "--xla_force_host_platform_device_count=8", "").strip()
    if flags:
        env["XLA_FLAGS"] = flags
    else:
        env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
@pytest.mark.parametrize("line", queue_lines(),
                         ids=[f"q{i+1}" for i in range(len(queue_lines()))])
def test_queued_bench_line_preflights(line, tmp_path):
    cmd = ([sys.executable, os.path.join(REPO, "bench.py")]
           + shlex.split(line) + _OVERRIDES
           + ["--cache-dir", str(tmp_path)])
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=_preflight_env(str(tmp_path)))
    tail = "\n".join((r.stdout + "\n" + r.stderr).splitlines()[-30:])
    assert r.returncode == 0, f"queue line {line!r} failed preflight:\n{tail}"
    json_lines = [ln for ln in r.stdout.splitlines() if ln.startswith("{")]
    assert json_lines, f"no JSON result line from {line!r}:\n{tail}"
    rec = json.loads(json_lines[-1])
    assert rec.get("value"), f"no measured value from {line!r}:\n{tail}"
    # a fresh worker result line carries no status field (fallback/stale
    # lines do) — a preflight must have measured, not carried forward
    assert not rec.get("status"), f"stale/fallback line from {line!r}: {rec}"


def test_queue_is_nonempty_while_candidates_are_pending():
    """The queue file is the hardware plan of record; if it exists it must
    parse (physical lines, no partial flags) so the watchdog's line cursor
    and this preflight agree on its contents."""
    for ln in queue_lines():
        toks = shlex.split(ln)
        assert toks, "blank-but-nonempty queue line"
        assert all(t.startswith("--") or not t.startswith("-")
                   for t in toks), f"malformed queue line: {ln!r}"
