"""The dgl/ogb loader adapters can't run against the real libraries here
(no network egress); exercise their conversion logic with stand-in objects
so shape/dtype/mask handling is still covered."""

import sys
import types

import numpy as np
import torch

from bnsgcn_tpu.data.datasets import _from_dgl, _load_ogb, load_data
from bnsgcn_tpu.config import Config


class _FakeDglGraph:
    def __init__(self, n, src, dst, feat, label, multilabel=False):
        self._n = n
        self._src = torch.as_tensor(src)
        self._dst = torch.as_tensor(dst)
        self.ndata = {
            "feat": torch.as_tensor(feat),
            "label": torch.as_tensor(label),
            "train_mask": torch.zeros(n, dtype=torch.bool),
            "val_mask": torch.zeros(n, dtype=torch.bool),
            "test_mask": torch.zeros(n, dtype=torch.bool),
        }
        self.ndata["train_mask"][: n // 2] = True
        self.ndata["val_mask"][n // 2: 3 * n // 4] = True
        self.ndata["test_mask"][3 * n // 4:] = True

    def num_nodes(self):
        return self._n

    def edges(self):
        return self._src, self._dst


def test_from_dgl_single_label():
    rng = np.random.default_rng(0)
    n = 20
    fake = _FakeDglGraph(n, rng.integers(0, n, 60), rng.integers(0, n, 60),
                         rng.normal(size=(n, 4)).astype(np.float32),
                         rng.integers(0, 3, n))
    g = _from_dgl(fake)
    assert g.n_nodes == n and g.feat.shape == (n, 4)
    assert g.label.dtype == np.int64 and g.n_class == 3
    assert g.train_mask.sum() == n // 2


def test_from_dgl_multilabel():
    rng = np.random.default_rng(1)
    n = 16
    lab = (rng.random((n, 5)) < 0.3).astype(np.float32)
    fake = _FakeDglGraph(n, rng.integers(0, n, 40), rng.integers(0, n, 40),
                         rng.normal(size=(n, 4)).astype(np.float32), lab)
    g = _from_dgl(fake, multilabel=True)
    assert g.multilabel and g.label.shape == (n, 5)
    assert g.label.dtype == np.float32


def test_load_ogb_via_stub(monkeypatch):
    """Install a stub ogb.nodeproppred module and run the real adapter."""
    rng = np.random.default_rng(2)
    n, e = 30, 90

    class _FakeDs:
        def __init__(self, name, root):
            assert name == "ogbn-products"

        def get_idx_split(self):
            idx = rng.permutation(n)
            return {"train": idx[:18], "valid": idx[18:24], "test": idx[24:]}

        def __getitem__(self, i):
            graph = {"num_nodes": n,
                     "edge_index": np.stack([rng.integers(0, n, e),
                                             rng.integers(0, n, e)]),
                     "node_feat": rng.normal(size=(n, 6)).astype(np.float32)}
            label = rng.integers(0, 4, size=(n, 1))
            return graph, label

    mod = types.ModuleType("ogb.nodeproppred")
    mod.NodePropPredDataset = _FakeDs
    pkg = types.ModuleType("ogb")
    pkg.nodeproppred = mod
    monkeypatch.setitem(sys.modules, "ogb", pkg)
    monkeypatch.setitem(sys.modules, "ogb.nodeproppred", mod)

    g = _load_ogb("ogbn-products", "/tmp/nowhere")
    assert g.n_nodes == n and g.n_feat == 6
    assert g.train_mask.sum() == 18 and g.val_mask.sum() == 6
    assert g.label.shape == (n,) and g.label.dtype == np.int64

    # and through the public load_data entry (canonicalization applied)
    cfg = Config(dataset="ogbn-products", data_path="/tmp/nowhere")
    g2, n_feat, n_class = load_data(cfg)
    assert n_feat == 6 and n_class == 4
    # canonical form: every node has a self loop
    self_loops = np.sum(g2.src == g2.dst)
    assert self_loops == g2.n_nodes
