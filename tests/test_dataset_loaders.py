"""The dgl/ogb loader adapters can't run against the real libraries here
(no network egress); exercise their conversion logic with stand-in objects
so shape/dtype/mask handling is still covered."""

import sys
import types

import numpy as np
import torch

from bnsgcn_tpu.data.datasets import _from_dgl, _load_ogb, load_data
from bnsgcn_tpu.config import Config


class _FakeDglGraph:
    def __init__(self, n, src, dst, feat, label, multilabel=False):
        self._n = n
        self._src = torch.as_tensor(src)
        self._dst = torch.as_tensor(dst)
        self.ndata = {
            "feat": torch.as_tensor(feat),
            "label": torch.as_tensor(label),
            "train_mask": torch.zeros(n, dtype=torch.bool),
            "val_mask": torch.zeros(n, dtype=torch.bool),
            "test_mask": torch.zeros(n, dtype=torch.bool),
        }
        self.ndata["train_mask"][: n // 2] = True
        self.ndata["val_mask"][n // 2: 3 * n // 4] = True
        self.ndata["test_mask"][3 * n // 4:] = True

    def num_nodes(self):
        return self._n

    def edges(self):
        return self._src, self._dst


def test_from_dgl_single_label():
    rng = np.random.default_rng(0)
    n = 20
    fake = _FakeDglGraph(n, rng.integers(0, n, 60), rng.integers(0, n, 60),
                         rng.normal(size=(n, 4)).astype(np.float32),
                         rng.integers(0, 3, n))
    g = _from_dgl(fake)
    assert g.n_nodes == n and g.feat.shape == (n, 4)
    assert g.label.dtype == np.int64 and g.n_class == 3
    assert g.train_mask.sum() == n // 2


def test_from_dgl_multilabel():
    rng = np.random.default_rng(1)
    n = 16
    lab = (rng.random((n, 5)) < 0.3).astype(np.float32)
    fake = _FakeDglGraph(n, rng.integers(0, n, 40), rng.integers(0, n, 40),
                         rng.normal(size=(n, 4)).astype(np.float32), lab)
    g = _from_dgl(fake, multilabel=True)
    assert g.multilabel and g.label.shape == (n, 5)
    assert g.label.dtype == np.float32


def _write_scipy_csr(path, n, src, dst):
    """scipy.sparse.save_npz CSR layout, written without scipy."""
    order = np.argsort(src, kind="stable")
    indices = dst[order].astype(np.int64)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr[1:], src, 1)
    indptr = np.cumsum(indptr)
    np.savez(path, format=np.bytes_("csr"), shape=np.array([n, n]),
             data=np.ones(len(src)), indices=indices, indptr=indptr)


def test_reddit_disk_reader(tmp_path):
    """load_data('reddit') without dgl reads DGL's on-disk npz layout."""
    rng = np.random.default_rng(3)
    n, e = 40, 160
    d = tmp_path / "reddit"
    d.mkdir()
    src = rng.integers(0, n, e)
    dst = rng.integers(0, n, e)
    types = np.ones(n, dtype=np.int64)
    types[20:30] = 2
    types[30:] = 3
    np.savez(d / "reddit_data.npz",
             feature=rng.normal(size=(n, 6)).astype(np.float32),
             label=rng.integers(0, 5, n), node_types=types)
    _write_scipy_csr(d / "reddit_graph.npz", n, src, dst)
    g, n_feat, n_class = load_data(Config(dataset="reddit",
                                          data_path=str(tmp_path)))
    assert g.n_nodes == n and n_feat == 6 and n_class == 5
    assert g.train_mask.sum() == 20 and g.val_mask.sum() == 10
    assert np.sum(g.src == g.dst) == n          # canonical self-loops


def test_yelp_disk_reader(tmp_path):
    """load_data('yelp') without dgl reads the GraphSAINT layout (+ scaling)."""
    import json
    rng = np.random.default_rng(4)
    n, e, c = 30, 90, 4
    d = tmp_path / "yelp"
    d.mkdir()
    _write_scipy_csr(d / "adj_full.npz", n, rng.integers(0, n, e),
                     rng.integers(0, n, e))
    np.save(d / "feats.npy", rng.normal(size=(n, 5)).astype(np.float32))
    cmap = {str(i): (rng.random(c) < 0.4).astype(float).tolist()
            for i in range(n)}
    (d / "class_map.json").write_text(json.dumps(cmap))
    ids = rng.permutation(n)
    (d / "role.json").write_text(json.dumps(
        {"tr": ids[:18].tolist(), "va": ids[18:24].tolist(),
         "te": ids[24:].tolist()}))
    g, n_feat, n_class = load_data(Config(dataset="yelp",
                                          data_path=str(tmp_path)))
    assert g.multilabel and g.label.shape == (n, c) and n_class == c
    # standard scaling fit on train rows (reference helper/utils.py:54-57)
    mu = g.feat[g.train_mask].mean(0)
    assert np.abs(mu).max() < 1e-5


def test_ogb_disk_reader_csv(tmp_path):
    """load_data('ogbn-products') without ogb reads the csv.gz layout."""
    import gzip
    rng = np.random.default_rng(5)
    n, e = 25, 70
    d = tmp_path / "ogbn_products"
    (d / "raw").mkdir(parents=True)
    sd = d / "split" / "sales_ranking"
    sd.mkdir(parents=True)

    def wgz(path, arr, fmt):
        with gzip.open(path, "wt") as f:
            np.savetxt(f, arr, delimiter=",", fmt=fmt)

    wgz(d / "raw" / "edge.csv.gz",
        np.stack([rng.integers(0, n, e), rng.integers(0, n, e)], 1), "%d")
    wgz(d / "raw" / "node-feat.csv.gz",
        rng.normal(size=(n, 4)).astype(np.float32), "%.6f")
    wgz(d / "raw" / "node-label.csv.gz",
        rng.integers(0, 3, size=(n, 1)), "%d")
    ids = rng.permutation(n)
    wgz(sd / "train.csv.gz", ids[:15].reshape(-1, 1), "%d")
    wgz(sd / "valid.csv.gz", ids[15:20].reshape(-1, 1), "%d")
    wgz(sd / "test.csv.gz", ids[20:].reshape(-1, 1), "%d")
    g, n_feat, n_class = load_data(Config(dataset="ogbn-products",
                                          data_path=str(tmp_path)))
    assert g.n_nodes == n and n_feat == 4 and n_class == 3
    assert g.train_mask.sum() == 15


def test_ogb_disk_reader_binary_nan_labels(tmp_path):
    """papers100M binary layout: raw/data.npz + NaN labels -> -1 sentinel."""
    rng = np.random.default_rng(6)
    n, e = 20, 50
    d = tmp_path / "ogbn_papers100M"
    (d / "raw").mkdir(parents=True)
    sd = d / "split" / "time"
    sd.mkdir(parents=True)
    np.savez(d / "raw" / "data.npz",
             edge_index=np.stack([rng.integers(0, n, e),
                                  rng.integers(0, n, e)]),
             node_feat=rng.normal(size=(n, 4)).astype(np.float32),
             num_nodes_list=np.array([n]))
    lab = rng.integers(0, 3, n).astype(np.float64)
    lab[10:] = np.nan                              # unlabeled tail
    np.savez(d / "raw" / "node-label.npz", node_label=lab)
    np.savez(sd / "train.npz", ids=np.arange(0, 6))
    np.savez(sd / "valid.npz", ids=np.arange(6, 8))
    np.savez(sd / "test.npz", ids=np.arange(8, 10))
    g, n_feat, n_class = load_data(Config(dataset="ogbn-papers100m",
                                          data_path=str(tmp_path)))
    assert g.n_nodes == n and n_feat == 4
    assert g.label.min() == -1 and g.label[g.train_mask].min() >= 0


def test_load_ogb_via_stub(monkeypatch):
    """Install a stub ogb.nodeproppred module and run the real adapter."""
    rng = np.random.default_rng(2)
    n, e = 30, 90

    class _FakeDs:
        def __init__(self, name, root):
            assert name == "ogbn-products"

        def get_idx_split(self):
            idx = rng.permutation(n)
            return {"train": idx[:18], "valid": idx[18:24], "test": idx[24:]}

        def __getitem__(self, i):
            graph = {"num_nodes": n,
                     "edge_index": np.stack([rng.integers(0, n, e),
                                             rng.integers(0, n, e)]),
                     "node_feat": rng.normal(size=(n, 6)).astype(np.float32)}
            label = rng.integers(0, 4, size=(n, 1))
            return graph, label

    mod = types.ModuleType("ogb.nodeproppred")
    mod.NodePropPredDataset = _FakeDs
    pkg = types.ModuleType("ogb")
    pkg.nodeproppred = mod
    monkeypatch.setitem(sys.modules, "ogb", pkg)
    monkeypatch.setitem(sys.modules, "ogb.nodeproppred", mod)

    g = _load_ogb("ogbn-products", "/tmp/nowhere")
    assert g.n_nodes == n and g.n_feat == 6
    assert g.train_mask.sum() == 18 and g.val_mask.sum() == 6
    assert g.label.shape == (n,) and g.label.dtype == np.int64

    # and through the public load_data entry (canonicalization applied)
    cfg = Config(dataset="ogbn-products", data_path="/tmp/nowhere")
    g2, n_feat, n_class = load_data(cfg)
    assert n_feat == 6 and n_class == 4
    # canonical form: every node has a self loop
    self_loops = np.sum(g2.src == g2.dst)
    assert self_loops == g2.n_nodes
