"""graftlint-ir (bnsgcn_tpu/analysis/ir/): jaxpr-level contract audit.

Seeded-violation fixtures per contract — each checker MUST fire on a
hand-built program carrying exactly that violation (rank-asymmetric
collective, dead donation, wire-byte mismatch, hidden transfer), fed
through the same trace_program/trace_jitted entry points the real
variant runner uses — plus unit coverage for the variant enumeration,
`tune.reachable_lever_states`, `run.step_variants`,
`halo.traced_wire_bytes`, the repo-level checks (tune-schedule grammar
lint, README knob-table drift, suppression staleness), and the
quickgate clean-at-HEAD gate: `python -m bnsgcn_tpu.analysis ir` over
the full strategy x wire x overlap x refresh x tune-target matrix on
CPU with zero findings.
"""

import json
import os
import subprocess
import sys
import textwrap
from functools import partial
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import AbstractMesh, PartitionSpec as P

from bnsgcn_tpu.analysis.ir import contracts as C
from bnsgcn_tpu.analysis.ir import trace as T
from bnsgcn_tpu.analysis.ir.variants import enumerate_variants
from bnsgcn_tpu.parallel.mesh import shard_map

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MESH = AbstractMesh((("parts", 4),))
AVAL = jax.ShapeDtypeStruct((4, 8), jnp.float32)


def _rules(findings):
    return sorted(f.rule for f in findings)


# ----------------------------------------------------------------------------
# contract 1: rank symmetry (seeded violations)
# ----------------------------------------------------------------------------

def test_rank_branched_collective_fires():
    def local(x):
        r = jax.lax.axis_index("parts")

        def yes(v):
            return jax.lax.psum(v, "parts")

        return jax.lax.cond(r == 0, yes, lambda v: v, x)

    f = shard_map(local, mesh=MESH, in_specs=P("parts"),
                  out_specs=P("parts"))
    tp = T.trace_program("fix", f, AVAL)
    found = C.check_rank_symmetry(tp, "ir://fix#prog")
    assert "ir-rank-asymmetry" in _rules(found)
    assert any("cond/switch" in f.message for f in found)
    assert all(f.file == "ir://fix#prog" for f in found)


def test_axis_index_groups_fires():
    def local(x):
        return jax.lax.all_gather(x, "parts",
                                  axis_index_groups=[[0, 1], [2, 3]])

    f = shard_map(local, mesh=MESH, in_specs=P("parts"),
                  out_specs=P(None, "parts"))
    tp = T.trace_program("fix", f, AVAL)
    found = C.check_rank_symmetry(tp, "ir://fix#prog")
    assert "ir-rank-asymmetry" in _rules(found)
    assert any("axis_index_groups" in f.message for f in found)


def test_symmetric_collective_is_clean():
    def local(x):
        return jax.lax.psum(x, "parts")

    f = shard_map(local, mesh=MESH, in_specs=P("parts"),
                  out_specs=P("parts"))
    tp = T.trace_program("ok", f, AVAL)
    assert C.check_rank_symmetry(tp, "ir://ok#prog") == []
    assert len(tp.collectives) >= 1
    assert tp.collectives[0].axes == ("parts",)


def test_schedule_match_flags_divergence():
    def mk(name, shapes):
        return T.TracedProgram(name=name, collectives=[
            T.Collective("all_to_all", ("parts",), s, "float32", False,
                         (), False) for s in shapes])

    a = mk("launch", [(16, 8), (4, 8)])
    b = mk("retuned", [(16, 8), (8, 8)])
    found = C.check_schedule_match(a, b, "ir://x#train_step")
    assert _rules(found) == ["ir-rank-asymmetry"]
    assert "divergence at collective #1" in found[0].message
    assert C.check_schedule_match(a, mk("again", [(16, 8), (4, 8)]),
                                  "ir://x#train_step") == []


# ----------------------------------------------------------------------------
# contract 2: donation (seeded violation)
# ----------------------------------------------------------------------------

def test_dead_donation_fires():
    @partial(jax.jit, donate_argnums=(0, 1))
    def f(a, b):
        return a + 1.0      # b donated but unused: pruned, never aliased

    tp = T.trace_jitted("fix", f, AVAL, AVAL)
    found = C.check_donation(tp, "ir://fix#prog")
    assert _rules(found) == ["ir-dead-donation"]
    assert tp.donation.dead == (1,)
    assert 0 in tp.donation.aliased     # the live donation still aliases


def test_live_donation_is_clean():
    @partial(jax.jit, donate_argnums=(0,))
    def f(a, b):
        return a + b

    tp = T.trace_jitted("ok", f, AVAL, AVAL)
    assert C.check_donation(tp, "ir://ok#prog") == []
    assert tp.donation.donated == (0,) and tp.donation.dead == ()


def test_peak_live_bytes_positive():
    tp = T.trace_program("p", lambda a, b: a @ b.T + 1.0, AVAL, AVAL)
    # two 4x8 f32 inputs live at once -> at least 256 B
    assert tp.peak_live_bytes >= 2 * 4 * 8 * 4


# ----------------------------------------------------------------------------
# contract 3: wire bytes (seeded mismatch + oracle unit)
# ----------------------------------------------------------------------------

def _exchange_tp(width=8):
    def local(x):
        return jax.lax.all_to_all(x, "parts", 0, 0, tiled=True)

    f = shard_map(local, mesh=MESH, in_specs=P("parts"), out_specs=P("parts"))
    return T.trace_program("exch", f,
                           jax.ShapeDtypeStruct((16, width), jnp.float32))


def test_wire_drift_fires_on_mismatched_oracle():
    tp = _exchange_tp()
    traced = T.payload_wire_bytes(tp, 8)
    assert traced == 4 * 8 * 4
    found = C.check_wire(tp, 8, traced + 64, "ir://fix#exchange_only")
    assert _rules(found) == ["ir-wire-drift"]
    assert str(traced) in found[0].message
    assert C.check_wire(tp, 8, traced, "ir://fix#exchange_only") == []


def test_no_payload_fires_on_forward_exchange():
    tp = _exchange_tp()
    found = C.check_no_payload(tp, 8, "ir://fix#train_step")
    assert _rules(found) == ["ir-wire-drift"]
    assert "grad-only" in found[0].message


def test_payload_excludes_scale_hops():
    # a [4,1] scale all_to_all (last dim 1) must not count toward the
    # width-8 payload — the quantized-wire accounting convention
    def local(x, s):
        a = jax.lax.all_to_all(x, "parts", 0, 0, tiled=True)
        b = jax.lax.all_to_all(s, "parts", 0, 0, tiled=True)
        return a, b

    f = shard_map(local, mesh=MESH, in_specs=(P("parts"), P("parts")),
                  out_specs=(P("parts"), P("parts")))
    tp = T.trace_program("q", f, jax.ShapeDtypeStruct((16, 8), jnp.int8),
                         jax.ShapeDtypeStruct((16, 1), jnp.float32))
    assert T.payload_wire_bytes(tp, 8) == 4 * 8 * 1      # int8 payload only


def test_traced_wire_bytes_oracle():
    from bnsgcn_tpu.parallel.halo import (make_halo_spec, traced_wire_bytes,
                                          wire_bytes)
    n_b = np.array([[0, 3, 2, 1], [3, 0, 1, 1], [2, 1, 0, 2], [1, 1, 2, 0]])
    for strat in ("padded", "shift"):
        spec, _ = make_halo_spec(n_b, 32, 8, 0.5, strategy=strat)
        assert traced_wire_bytes(spec, 8) == wire_bytes(spec, 8)
    spec, _ = make_halo_spec(n_b, 32, 8, 0.5, strategy="ragged")
    # CPU emulation routes over the padded all_to_all: padded accounting,
    # NOT the exact-rows number wire_bytes reports for ragged
    assert (traced_wire_bytes(spec, 8, ragged_native=False)
            == spec.n_parts * spec.pad_send * 8 * 4)
    assert (traced_wire_bytes(spec, 8, ragged_native=True)
            != traced_wire_bytes(spec, 8, ragged_native=False))


# ----------------------------------------------------------------------------
# contract 4: hidden transfers (seeded violation)
# ----------------------------------------------------------------------------

def test_hidden_transfer_fires():
    def f(x):
        y = jax.pure_callback(
            lambda v: v, jax.ShapeDtypeStruct(x.shape, x.dtype), x)
        return y + 1.0

    tp = T.trace_program("fix", jax.jit(f), AVAL)
    found = C.check_transfers(tp, "ir://fix#prog")
    assert _rules(found) == ["ir-hidden-transfer"]
    assert "pure_callback" in found[0].message


def test_clean_program_has_no_transfers():
    tp = T.trace_program("ok", jax.jit(lambda x: x * 2.0), AVAL)
    assert tp.transfers == []
    assert C.check_transfers(tp, "ir://ok#prog") == []


# ----------------------------------------------------------------------------
# variant enumeration + seams
# ----------------------------------------------------------------------------

def test_enumerate_variants_covers_matrix_and_tune():
    vs = enumerate_variants()
    keys = {(v.strategy, v.wire, v.overlap, v.refresh, v.mode) for v in vs}
    assert len(keys) == len(vs)                       # deduplicated
    for strat in ("padded", "shift", "ragged"):
        for wire in ("native", "bf16", "fp8", "int8"):
            for ovl in ("off", "split"):
                for k in (1, 2):
                    assert (strat, wire, ovl, k, "exchange") in keys
        assert (strat, "native", "off", 1, "grad-only") in keys
    # the auto controller's coarse-staleness rung reaches K=4
    assert any(v.refresh == 4 and v.source == "tune" for v in vs)
    assert not any(v.strategy == "auto" for v in vs)


def test_enumerate_variants_with_schedule():
    # K=8 is outside the static matrix, so the schedule-reached state must
    # survive dedup as a tune-sourced extra cell
    vs = enumerate_variants(tune_schedule="K=8@5,wire=int8@9")
    assert any(v.refresh == 8 and v.source == "tune" for v in vs)
    assert any(v.refresh == 8 and v.wire == "int8" for v in vs)


def test_reachable_lever_states_schedule():
    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.tune import reachable_lever_states
    cfg = Config(tune="schedule",
                 tune_schedule="K=2@3,wire=bf16@7,mode=grad-only@9")
    states = reachable_lever_states(cfg)
    assert states[0] == {"halo_exchange": "padded", "halo_wire": "native",
                         "halo_refresh": 1, "halo_mode": "exchange"}
    assert {"halo_exchange": "padded", "halo_wire": "bf16",
            "halo_refresh": 2, "halo_mode": "exchange"} in states
    assert any(s["halo_mode"] == "grad-only" for s in states)
    # off: only the launch state
    assert len(reachable_lever_states(Config(tune="off"))) == 1


def test_step_variants():
    from bnsgcn_tpu.run import step_variants
    assert step_variants(SimpleNamespace(train_step_full=None)) == ("step",)
    assert step_variants(
        SimpleNamespace(train_step_full=object())) == ("full", "cached")


def test_transfer_primitives_registry():
    from bnsgcn_tpu.strict import TRANSFER_PRIMITIVES
    assert "device_put" in TRANSFER_PRIMITIVES
    assert "pure_callback" in TRANSFER_PRIMITIVES


# ----------------------------------------------------------------------------
# repo-level checks: tune-schedule lint, knob-table drift, stale suppressions
# ----------------------------------------------------------------------------

def _lint(root, paths=None):
    from bnsgcn_tpu.analysis import lint_paths
    return lint_paths(paths, root=str(root))


def test_tune_schedule_lint_fires(tmp_path):
    (tmp_path / "scripts").mkdir()
    (tmp_path / "scripts" / "run.sh").write_text(
        '#!/bin/bash\npython -m bnsgcn_tpu --tune schedule '
        '--tune-schedule "K=banana@5"\n')
    (tmp_path / ".watch_queue").write_text(
        "--tune schedule --tune-schedule wire=bf16@3\n"
        "--tune schedule --tune-schedule=nope=1@9\n")
    active, _, _ = _lint(tmp_path)
    assert _rules(active) == ["tune-schedule-invalid",
                              "tune-schedule-invalid"]
    files = sorted(f.file for f in active)
    assert files == [".watch_queue", os.path.join("scripts", "run.sh")]
    assert active[0].line == 2      # the bad .watch_queue line, not line 1


def test_tune_schedule_lint_python_argv(tmp_path):
    (tmp_path / "bench.py").write_text(textwrap.dedent("""\
        cmd = ["prog", "--tune-schedule", "K=2@4"]
        bad = ["prog", "--tune-schedule", "K=zero@4"]
        kw = dict(tune_schedule="wire=fp8@7")
    """))
    active, _, _ = _lint(tmp_path)
    assert _rules(active) == ["tune-schedule-invalid"]
    assert active[0].line == 2


def test_config_doc_drift_fires_and_clean(tmp_path):
    from bnsgcn_tpu.analysis.repo_checks import (KNOB_BEGIN, KNOB_END,
                                                 check_config_docs,
                                                 render_knob_table)
    # missing marker block
    (tmp_path / "README.md").write_text("# hi\n")
    assert _rules(check_config_docs(str(tmp_path))) == ["config-doc-drift"]
    # stale table (a knob row the parser doesn't have)
    (tmp_path / "README.md").write_text(
        f"# hi\n{KNOB_BEGIN}\n| knob | default | choices |\n|---|---|---|\n"
        f"| `--no-such-flag` | `1` |  |\n{KNOB_END}\n")
    found = check_config_docs(str(tmp_path))
    assert _rules(found) == ["config-doc-drift"]
    assert "drifted" in found[0].message
    # generated table verbatim -> clean
    (tmp_path / "README.md").write_text("# hi\n" + render_knob_table())
    assert check_config_docs(str(tmp_path)) == []


def test_knob_table_clean_at_head():
    """README knob table matches the live parser — the drift gate the
    full lint run enforces, asserted directly for a fast signal."""
    from bnsgcn_tpu.analysis.repo_checks import check_config_docs
    assert check_config_docs(REPO) == []


def test_suppression_stale_fires(tmp_path):
    (tmp_path / "fix.py").write_text(textwrap.dedent("""\
        import jax
        # graftlint: disable=prng-literal-key(was needed before a refactor)
        x = 1 + 1
    """))
    active, _, _ = _lint(tmp_path, [str(tmp_path)])
    assert _rules(active) == ["suppression-stale"]
    assert "prng-literal-key" in active[0].message
    assert active[0].line == 2


def test_suppression_used_not_stale(tmp_path):
    (tmp_path / "fix.py").write_text(textwrap.dedent("""\
        import jax
        # graftlint: disable=prng-literal-key(fixture: literal key on purpose)
        k = jax.random.PRNGKey(0)
    """))
    active, suppressed, _ = _lint(tmp_path, [str(tmp_path)])
    assert _rules(active) == []
    assert _rules(suppressed) == ["prng-literal-key"]


def test_suppression_multi_rule_partially_used_not_stale(tmp_path):
    # line-level semantics: one firing rule keeps the whole comment
    # load-bearing, even if the other listed rule no longer matches
    (tmp_path / "fix.py").write_text(textwrap.dedent("""\
        import jax
        # graftlint: disable=prng-key-reuse(fixture A),prng-literal-key(B)
        k = jax.random.PRNGKey(0)
    """))
    active, suppressed, _ = _lint(tmp_path, [str(tmp_path)])
    assert _rules(active) == []
    assert _rules(suppressed) == ["prng-literal-key"]


def test_suppression_stale_skipped_under_select(tmp_path):
    from bnsgcn_tpu.analysis import lint_paths
    (tmp_path / "fix.py").write_text(
        "# graftlint: disable=prng-literal-key(covered elsewhere)\nx = 1\n")
    active, _, _ = lint_paths([str(tmp_path)], root=str(tmp_path),
                              select={"prng-literal-key"})
    assert _rules(active) == []     # select runs can't judge staleness


# ----------------------------------------------------------------------------
# CLI + clean-at-HEAD gate
# ----------------------------------------------------------------------------

def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO)
    return env


def test_ir_cli_smoke_subset(tmp_path):
    """One --max-variants run covers the CLI surface: JSON report schema,
    wire-byte rows, and the ir_audit obs event (a single subprocess — the
    jax import dominates, so don't pay it twice)."""
    rep = tmp_path / "ir.json"
    log = tmp_path / "events.jsonl"
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "ir", "-q",
         "--max-variants", "2", "--json", str(rep), "--obs-log", str(log)],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=_env())
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(rep.read_text())
    assert data["graftlint_ir"] == 1 and data["ok"] is True
    assert data["n_variants"] == 2 and data["variants_dropped"] > 0
    progs = data["variants"][0]["programs"]
    assert "train_step" in progs and "exchange_only" in progs
    assert progs["exchange_only"]["wire_bytes"]["traced"] == \
        progs["exchange_only"]["wire_bytes"]["oracle"]
    events = [json.loads(l) for l in log.read_text().splitlines()]
    ev = [e for e in events if e["kind"] == "ir_audit"]
    assert len(ev) == 1 and ev[0]["ok"] is True and ev[0]["n_variants"] == 2
    # elastic slot-map invariance rides every audit: the first variant is
    # re-traced under part -> slot maps for two world sizes and must keep
    # the identical (and rank-symmetric) collective schedule
    sw = data["slot_worlds"]
    assert [r["world"] for r in sw] == [2, 4]
    assert all(r["findings"] == 0 for r in sw)
    assert len({r["collectives"] for r in sw}) == 1


@pytest.mark.quickgate
def test_ir_audit_clean_at_head(tmp_path):
    """The gate: the FULL variant matrix (strategies x wires x overlap x
    refresh x tune targets) traces clean at HEAD on CPU with no devices —
    rank-symmetric schedules, no dead donations, wire bytes matching the
    plan oracle, no hidden transfers, zero trace errors."""
    rep = tmp_path / "ir.json"
    r = subprocess.run(
        [sys.executable, "-m", "bnsgcn_tpu.analysis", "ir", "-q",
         "--json", str(rep)],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=_env())
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(rep.read_text())
    assert data["ok"] is True and data["findings"] == []
    assert data["errors"] == [] and data["variants_dropped"] == 0
    assert data["n_variants"] >= 40
    keys = {v["key"] for v in data["variants"]}
    assert "padded/native/ovl-off/K1/exchange" in keys
    assert any(k.endswith("grad-only") for k in keys)
    assert any("/K4/" in k for k in keys)             # tune-reachable rung
    # RESIZE survivors recompile through the same layout cache: the
    # slot-mapped retraces (W=2 shrink and W=4 identity) must already be
    # schedule-identical at HEAD, or an elastic verdict would silently
    # change the program a survivor runs
    assert [r["world"] for r in data["slot_worlds"]] == [2, 4]
    assert all(r["findings"] == 0 for r in data["slot_worlds"])
    # every exchange program's traced payload matched its oracle
    for row in data["variants"]:
        for name, prog in row["programs"].items():
            wb = prog.get("wire_bytes")
            if wb is not None:
                assert wb["traced"] == wb["oracle"], (row["key"], name)
