"""Closed-loop communication auto-tuner (`--tune {off,schedule,auto}`).

  * schedule grammar: parse/merge/sort, every malformed entry a named
    ConfigError; mode validation (auto is single-process only, a schedule
    text without --tune schedule is an error, not silently ignored);
  * decide() on synthetic metric streams: the staleness anneal fires only
    after a full window + AUTO_HOLD consecutive flat verdicts, every move
    starts an AUTO_COOLDOWN dwell, the ladder is MONOTONE (never loosens),
    and the strategy/codec moves are one-shot — the controller cannot
    flip-flop by construction;
  * Tuner recovery: decisions are sticky — rewind() reverts the levers to
    the restart point but keeps the history, on_epoch_end() replays it by
    epoch, restore() reconstructs a schedule (pure function of the epoch)
    or adopts the checkpointed auto history;
  * the CLI path: `--tune off` is bitwise-pinned to the no-flag run, a
    scheduled run emits a tune_decision per applied move with a clean
    --strict-exec audit (each retune re-arms the compile allowance), and a
    faulted run replays the SAME schedule after rollback — bitwise
    deterministic across two identical injected runs.

No reference equivalent: BNS-GCN freezes every comm lever at launch; the
epoch-boundary feedback loop is a capability upgrade built on the obs bus.
"""

import json
import os
import re
import shutil
import subprocess
import sys

import pytest

from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu import tune
from bnsgcn_tpu.tune import (AUTO_COOLDOWN, AUTO_HOLD, AUTO_WINDOW,
                             STALENESS_LADDER, AutoState, Tuner,
                             _ladder_pos, bench_schedule, decide,
                             parse_schedule, startup_changes, validate_mode)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------------
# schedule grammar
# ----------------------------------------------------------------------------

@pytest.mark.quickgate
def test_parse_schedule_grammar_merge_and_sort():
    """Entries parse through the lever aliases, same-epoch entries merge
    into one fold, and the result is epoch-sorted regardless of input
    order."""
    sched = parse_schedule("K=1@60, wire=bf16@30 ,K=4@0,K=2@30,mode=grad-only@0")
    assert [ep for ep, _ in sched] == [0, 30, 60]
    by = dict(sched)
    assert by[0] == {"halo_refresh": 4, "halo_mode": "grad-only"}
    assert by[30] == {"halo_wire": "bf16", "halo_refresh": 2}
    assert by[60] == {"halo_refresh": 1}
    # lowercase k aliases the same lever; empty text parses to nothing
    assert parse_schedule("k=2@5") == [(5, {"halo_refresh": 2})]
    assert parse_schedule("") == [] and parse_schedule("  , ,") == []
    # strategy alias maps to halo_exchange with a CONCRETE strategy
    assert parse_schedule("strategy=ragged@3") == [(3, {"halo_exchange":
                                                        "ragged"})]


@pytest.mark.quickgate
def test_parse_schedule_rejects_malformed_entries():
    for bad, why in (
            ("K=4", "missing @epoch"),
            ("K@4", "missing =value"),
            ("K=4@x", "non-integer epoch"),
            ("warp=9@0", "unknown lever"),
            ("K=fast@0", "non-integer K"),
            ("K=0@0", "K < 1"),
            ("K=4@-1", "negative epoch"),
            ("mode=sometimes@0", "bad mode value"),
            ("strategy=auto@0", "schedule must pick a CONCRETE strategy"),
            ("wire=int4@0", "unknown codec"),
            ("K=4@2,k=2@2", "same lever twice at one epoch"),
    ):
        with pytest.raises(ConfigError):
            parse_schedule(bad), why


@pytest.mark.quickgate
def test_validate_mode():
    validate_mode(Config(tune="off"))
    validate_mode(Config(tune="schedule", tune_schedule="K=2@3"))
    validate_mode(Config(tune="auto"))
    # a schedule text under any other mode is an error, never silently dropped
    with pytest.raises(ConfigError, match="only read under"):
        validate_mode(Config(tune="off", tune_schedule="K=2@3"))
    with pytest.raises(ConfigError, match="needs a --tune-schedule"):
        validate_mode(Config(tune="schedule"))
    with pytest.raises(ConfigError, match="off/schedule/auto"):
        validate_mode(Config(tune="always"))
    # rank-local timings would desync retuned programs across ranks
    with pytest.raises(ConfigError, match="single-process"):
        validate_mode(Config(tune="auto"), multi_host=True)
    with pytest.raises(ConfigError, match="single-process"):
        validate_mode(Config(tune="auto"), coordinated=True)
    # the declarative schedule is rank-symmetric: allowed everywhere
    validate_mode(Config(tune="schedule", tune_schedule="K=2@3"),
                  multi_host=True, coordinated=True)


@pytest.mark.quickgate
def test_startup_changes():
    # schedule: only the epoch-0 entries that actually differ fold in
    ch, why = startup_changes(Config(tune="schedule",
                                     tune_schedule="K=4@0,K=1@9"))
    assert ch == {"halo_refresh": 4} and why == "schedule@0"
    ch, _ = startup_changes(Config(tune="schedule", halo_refresh=4,
                                   tune_schedule="K=4@0,K=1@9"))
    assert ch == {}
    # auto coarsens a fine exchange launch point to the K=4 rung...
    ch, why = startup_changes(Config(tune="auto"))
    assert ch == {"halo_refresh": 4} and "coarse" in why
    # ...but never loosens a launch point already at/above that rung
    assert startup_changes(Config(tune="auto", halo_refresh=8))[0] == {}
    assert startup_changes(Config(tune="auto",
                                  halo_mode="grad-only"))[0] == {}
    assert startup_changes(Config(tune="off")) == ({}, "")


@pytest.mark.quickgate
def test_bench_schedule_is_a_monotone_anneal():
    for n in (3, 8, 12, 100):
        sched = bench_schedule(n)
        eps = [ep for ep, _ in sched]
        ks = [ch["halo_refresh"] for _, ch in sched]
        assert eps[0] == 0 and eps == sorted(set(eps)), (n, sched)
        assert ks == [4, 2, 1], (n, sched)


# ----------------------------------------------------------------------------
# decide(): the pure feedback policy on synthetic streams
# ----------------------------------------------------------------------------

def _feed(st, losses, comm_frac=0.0):
    for lo in losses:
        st.observe({"loss": lo, "step_s": 1.0,
                    "comm_s": comm_frac if comm_frac else None})


@pytest.mark.quickgate
def test_decide_needs_full_window_then_hold_then_moves():
    """A flat loss stream: no verdict until the window fills, no move until
    the flat verdict holds AUTO_HOLD consecutive epochs, then exactly one
    ladder tightening (K=4 -> K=2) that clears the window and starts a
    cooldown dwell."""
    st, levers = AutoState(), {"halo_mode": "exchange", "halo_refresh": 4,
                               "halo_exchange": "padded",
                               "halo_wire": "native"}
    moved = None
    for i in range(AUTO_WINDOW + AUTO_HOLD):
        st.observe({"loss": 1.0})       # perfectly flat
        out = decide(st, levers)
        if out is not None:
            moved = (i, out)
            break
    assert moved is not None, "flat stream never tightened the staleness"
    i, (changes, reason, trigger) = moved
    # window must be full AND the verdict held AUTO_HOLD times first
    assert i == AUTO_WINDOW + AUTO_HOLD - 1 - 1, i  # 0-indexed epoch count
    assert changes == {"halo_refresh": 2} and "tighten" in reason
    assert "rel_improvement" in trigger and "threshold" in trigger
    assert st.cooldown == AUTO_COOLDOWN and st.losses == []
    # the dwell: nothing fires for AUTO_COOLDOWN epochs even though the
    # stream stays flat
    levers["halo_refresh"] = 2
    for _ in range(AUTO_COOLDOWN):
        st.observe({"loss": 1.0})
        assert decide(st, levers) is None


@pytest.mark.quickgate
def test_decide_improving_loss_never_tightens():
    st, levers = AutoState(), {"halo_mode": "exchange", "halo_refresh": 4}
    loss = 10.0
    for _ in range(30):
        st.observe({"loss": loss})
        loss *= 0.90                    # 10%/epoch: far above every rtol
        assert decide(st, levers) is None
    assert st.flat == 0


@pytest.mark.quickgate
def test_decide_ladder_is_monotone_and_single_lever():
    """Drive a long mixed stream (flat bursts separated by improving
    bursts) through the whole ladder from grad-only: the ladder position
    NEVER decreases, each decision moves at most the staleness pair, and
    once K=1 is reached no staleness move ever fires again — the
    no-flip-flop proof on a synthetic stream."""
    st = AutoState()
    levers = {"halo_mode": "grad-only", "halo_refresh": 1,
              "halo_exchange": "padded", "halo_wire": "bf16"}
    positions = [_ladder_pos(levers)]
    stream = ([1.0] * 12 + [0.5, 0.4, 0.3, 0.25] + [0.25] * 12
              + [0.12, 0.1] + [0.1] * 12 + [0.1] * 20)
    for lo in stream:
        st.observe({"loss": lo})
        out = decide(st, levers)
        if out is not None:
            changes, _, _ = out
            assert set(changes) <= {"halo_mode", "halo_refresh"}, changes
            levers.update(changes)
        positions.append(_ladder_pos(levers))
    assert positions == sorted(positions), "ladder loosened mid-run"
    assert _ladder_pos(levers) == len(STALENESS_LADDER) - 1, levers
    assert levers["halo_mode"] == "exchange" and levers["halo_refresh"] == 1
    # bottom rung: a permanently flat stream produces no further move
    for _ in range(20):
        st.observe({"loss": 0.1})
        assert decide(st, levers) is None


@pytest.mark.quickgate
def test_decide_comm_share_strategy_then_wire_one_shot():
    """At the bottom of the ladder with a high measured comm share: the
    strategy re-pick fires first (when retune_strategy found a cheaper
    one), then after the dwell the codec anneal native->bf16, then NOTHING
    — both moves are one-shot, no matter how long the share stays high."""
    st = AutoState()
    levers = {"halo_mode": "exchange", "halo_refresh": 1,
              "halo_exchange": "padded", "halo_wire": "native"}
    alt = ("shift", "shift beats padded on bytes at this skew")
    fired = []
    for _ in range(40):
        st.observe({"loss": 0.1, "step_s": 1.0, "comm_s": 0.6})
        out = decide(st, levers, strategy_alt=alt)
        if out is not None:
            changes, reason, trigger = out
            fired.append(changes)
            levers.update(changes)
            assert trigger["comm_frac"] == pytest.approx(0.6)
    assert fired == [{"halo_exchange": "shift"}, {"halo_wire": "bf16"}]
    assert st.strategy_moved and st.wire_moved
    # below the share threshold nothing ever fires
    st2 = AutoState()
    for _ in range(20):
        st2.observe({"loss": 0.1, "step_s": 1.0, "comm_s": 0.1})
        assert decide(st2, levers, strategy_alt=alt) is None


@pytest.mark.quickgate
def test_decide_no_strategy_alt_goes_straight_to_wire():
    st = AutoState()
    levers = {"halo_mode": "exchange", "halo_refresh": 1,
              "halo_exchange": "ragged", "halo_wire": "native"}
    fired = []
    for _ in range(20):
        st.observe({"loss": 0.1, "step_s": 1.0, "comm_s": 0.5})
        out = decide(st, levers)    # launch strategy already wins on bytes
        if out is not None:
            fired.append(out[0])
            levers.update(out[0])
    # bf16 is the ONLY codec move auto takes by itself; fp8/int8 stay opt-in
    assert fired == [{"halo_wire": "bf16"}]


# ----------------------------------------------------------------------------
# Tuner: sticky history, rewind/replay, restore
# ----------------------------------------------------------------------------

_LEVERS0 = {"halo_refresh": 4, "halo_mode": "exchange",
            "halo_exchange": "padded", "halo_wire": "native"}


def _sched_tuner(text="K=4@0,K=2@3,K=1@6", levers=None):
    cfg = Config(tune="schedule", tune_schedule=text)
    return Tuner(cfg, levers=dict(levers or _LEVERS0), log=lambda *a: None)


@pytest.mark.quickgate
def test_tuner_schedule_decides_at_boundaries():
    """on_epoch_end(e) returns the decision taking effect at e+1; entries
    equal to the applied levers fold to nothing."""
    t = _sched_tuner()
    t.record_startup({"halo_refresh": 4}, "schedule@0")
    decisions = {}
    for e in range(8):
        d = t.on_epoch_end(e, {"loss": 1.0})
        if d is not None:
            decisions[e] = d
    assert sorted(decisions) == [2, 5]
    assert decisions[2]["epoch"] == 3 and \
        decisions[2]["changes"] == {"halo_refresh": 2}
    assert decisions[5]["epoch"] == 6 and \
        decisions[5]["changes"] == {"halo_refresh": 1}
    assert decisions[2]["reason"] == "schedule"
    assert t.levers["halo_refresh"] == 1 and t.max_seen == 8


@pytest.mark.quickgate
def test_tuner_rewind_keeps_history_and_replays():
    """Rollback to epoch 4: the levers revert to the epoch-4 fold (K=2) but
    the epoch-6 decision stays recorded, and the healed run REPLAYS it at
    the same boundary instead of re-deriving anything."""
    t = _sched_tuner()
    t.record_startup({"halo_refresh": 4}, "schedule@0")
    for e in range(8):
        t.on_epoch_end(e, {"loss": 1.0})
    assert t.levers["halo_refresh"] == 1
    diff = t.rewind(4)
    assert diff == {"halo_refresh": 2}          # back to the epoch-4 levers
    assert t.levers["halo_refresh"] == 2
    assert len(t.history) == 3                  # startup + 2 moves, all kept
    replayed = {}
    for e in range(4, 8):
        d = t.on_epoch_end(e, {"loss": 9.9})    # post-rollback metrics differ
        if d is not None:
            replayed[e] = d
    assert sorted(replayed) == [5]
    assert replayed[5]["reason"] == "replay" and \
        replayed[5]["changes"] == {"halo_refresh": 1}
    assert t.levers["halo_refresh"] == 1
    # rewinding to a point where nothing differs returns None (no actuation)
    t2 = _sched_tuner()
    t2.record_startup({"halo_refresh": 4}, "schedule@0")
    assert t2.rewind(0) is None


@pytest.mark.quickgate
def test_tuner_restore_reconstructs_schedule():
    """A resumed process builds a FRESH Tuner with the launch levers, then
    restore(start_epoch) reconstructs the history a schedule implies (pure
    function of the epoch) and returns the diff to actuate before the first
    resumed step."""
    t = _sched_tuner()                  # resumed run built with K=4 levers
    t.record_startup({"halo_refresh": 4}, "schedule@0")
    diff = t.restore(5, None)           # schedule says K=2 since epoch 3
    assert diff == {"halo_refresh": 2}
    assert t.max_seen == 5 and t.levers["halo_refresh"] == 2
    # the remaining entry still fires as a FRESH schedule decision
    d = t.on_epoch_end(5, {"loss": 1.0})
    assert d["epoch"] == 6 and d["changes"] == {"halo_refresh": 1} and \
        d["reason"] == "schedule"
    # resume before any non-zero entry: nothing to actuate
    t2 = _sched_tuner()
    t2.record_startup({"halo_refresh": 4}, "schedule@0")
    assert t2.restore(2, None) is None


@pytest.mark.quickgate
def test_tuner_auto_state_dict_roundtrip():
    """auto persists its sticky history through extra['tune']; the resumed
    Tuner adopts it, actuates the fold diff, and REPLAYS the recorded
    decisions instead of re-deriving them from (different) resumed
    metrics."""
    cfg = Config(tune="auto", halo_refresh=4)
    t = Tuner(cfg, levers=dict(_LEVERS0), log=lambda *a: None)
    t.record_startup({"halo_refresh": 4}, "auto-start")
    fired = {}
    for e in range(16):
        d = t.on_epoch_end(e, {"loss": 1.0})    # flat: anneal walks the ladder
        if d is not None:
            fired[d["epoch"]] = d
    assert fired, "flat stream produced no auto decision"
    first_ep = min(fired)
    state = t.state_dict()
    assert state["mode"] == "auto" and len(state["history"]) == 1 + len(fired)
    # simulate the checkpoint JSON round-trip
    state = json.loads(json.dumps(state))
    resumed = Tuner(cfg, levers=dict(_LEVERS0), log=lambda *a: None)
    resumed.record_startup({"halo_refresh": 4}, "auto-start")
    diff = resumed.restore(first_ep, state)
    assert diff == fired[first_ep]["changes"]
    assert resumed.max_seen == t.max_seen
    # every later recorded decision REPLAYS at its boundary, fresh metrics
    # notwithstanding
    replayed = {}
    for e in range(first_ep, t.max_seen):
        d = resumed.on_epoch_end(e, {"loss": 123.0})
        if d is not None:
            replayed[d["epoch"]] = d
    later = {ep: f for ep, f in fired.items() if ep > first_ep}
    assert sorted(replayed) == sorted(later)
    for ep, f in later.items():
        assert replayed[ep]["reason"] == "replay" and \
            replayed[ep]["changes"] == f["changes"]
    assert resumed.levers == t.levers
    # a mode-mismatched checkpoint state is warned about and ignored
    msgs = []
    other = Tuner(cfg, levers=dict(_LEVERS0), log=msgs.append)
    other.restore(2, {"mode": "schedule", "max_seen": 9,
                      "history": [{"epoch": 3, "changes":
                                   {"halo_refresh": 2}, "reason": "schedule",
                                   "trigger": {}}]})
    assert other.history == []
    assert any("ignoring" in m for m in msgs), msgs


# ----------------------------------------------------------------------------
# e2e through the CLI: bitwise pin, events + strict audit, fault replay
# ----------------------------------------------------------------------------

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0", PYTHONPATH=REPO)
    env.update(extra or {})
    return env


def _run(tmp_path, extra_args=(), timeout=240):
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
           + ["--part-path", str(tmp_path / "parts"),
              "--ckpt-path", str(tmp_path / "ckpt"),
              "--results-path", str(tmp_path / "res")]
           + list(extra_args))
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=_env())


def _final_loss(stdout: str) -> float:
    m = re.search(r"RESULT final_loss=(\S+)", stdout)
    assert m, f"no RESULT line in output:\n{stdout[-2000:]}"
    return float(m.group(1))


def _load_events(path):
    from bnsgcn_tpu.obs import load_events
    return load_events(path)


def _tune_trail(path):
    """(epoch, sorted changes, reason) per tune_decision — the applied
    schedule a run walked."""
    return [(e["epoch"], tuple(sorted(e["changes"].items())), e["reason"])
            for e in _load_events(path) if e["kind"] == "tune_decision"]


@pytest.mark.quickgate
def test_cli_tune_off_is_bitwise_pinned(tmp_path):
    """`--tune off` (the default) must be bitwise identical to a run that
    never heard of the flag: same final loss, no controller artifacts."""
    base = _run(tmp_path / "a")
    assert base.returncode == 0, base.stdout + base.stderr
    off = _run(tmp_path / "b", ["--tune", "off"])
    assert off.returncode == 0, off.stdout + off.stderr
    assert _final_loss(base.stdout) == _final_loss(off.stdout)
    assert "[tune]" not in off.stdout


@pytest.mark.quickgate
def test_cli_tune_off_rejects_schedule_text(tmp_path):
    r = _run(tmp_path, ["--tune", "off", "--tune-schedule", "K=2@3"])
    assert r.returncode == 2, (r.returncode, r.stdout, r.stderr)
    assert "only read under --tune schedule" in (r.stdout + r.stderr)


@pytest.mark.quickgate
def test_cli_schedule_events_and_strict_audit(tmp_path):
    """A declarative anneal under --strict-exec: the epoch-0 fold plus both
    mid-run retunes each land a tune_decision event, every retune replays a
    logged full-refresh (reason retune), the strict audit stays CLEAN with
    one re-arm per retune, and the report tool renders the applied
    schedule."""
    log = str(tmp_path / "obs.jsonl")
    r = _run(tmp_path, ["--n-epochs", "10", "--tune", "schedule",
                        "--tune-schedule", "K=4@0,K=2@4,K=1@7",
                        "--strict-exec", "--obs-log", log])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[tune] schedule@0" in r.stdout
    assert re.search(r"\[tune\] epoch 4: schedule -> halo_refresh=2",
                     r.stdout), r.stdout[-4000:]
    assert re.search(r"\[tune\] epoch 7: schedule -> halo_refresh=1",
                     r.stdout), r.stdout[-4000:]
    evs = _load_events(log)
    hdr = next(e for e in evs if e["kind"] == "run_header")
    assert hdr["config"]["tune"] == "schedule"
    assert "K=2@4" in hdr["config"]["tune_schedule"]
    td = [e for e in evs if e["kind"] == "tune_decision"]
    assert [e["epoch"] for e in td] == [0, 4, 7], td
    assert [e["reason"] for e in td] == ["schedule@0", "schedule",
                                         "schedule"], td
    assert td[1]["changes"] == {"halo_refresh": 2}
    assert td[2]["changes"] == {"halo_refresh": 1}
    # the K=4->2 retune invalidates the PR-10 halo cache (a logged full
    # refresh); the K=1 retune DROPS the cache machinery — the plain step
    # has nothing to refresh, so exactly one retune refresh appears
    ref = [e["reason"] for e in evs if e["kind"] == "halo_refresh"]
    assert ref.count("retune") == 1, ref
    # strict-exec: the retune recompiles are SANCTIONED (re-armed), audit
    # line reports them and zero violations
    m = re.search(r"(\d+) retune re-arm\(s\), (\d+) violation\(s\)",
                  r.stdout)
    assert m, r.stdout[-4000:]
    assert (int(m.group(1)), int(m.group(2))) == (2, 0)
    # the report tool renders the applied schedule as a table
    rep = subprocess.run([sys.executable, "tools/obs_report.py", log],
                         capture_output=True, text=True, timeout=60,
                         cwd=REPO, env=_env())
    assert rep.returncode == 0, rep.stdout + rep.stderr
    assert "tune schedule (3 applied decision(s))" in rep.stdout
    # --compare against itself surfaces the retune NOTE (schedule effects,
    # not noise)
    cmp_ = subprocess.run([sys.executable, "tools/obs_report.py",
                           "--compare", log, log],
                          capture_output=True, text=True, timeout=60,
                          cwd=REPO, env=_env())
    assert cmp_.returncode == 0, cmp_.stdout + cmp_.stderr
    assert "retuned the comm stack mid-run" in cmp_.stdout


@pytest.mark.quickgate
def test_cli_rollback_replays_schedule_deterministically(tmp_path):
    """nan@E5 one epoch after a scheduled retune (K=2@5): the rollback
    rewinds the levers to the restart point (a tune_decision with reason
    rollback), the healed run REPLAYS the recorded K=2 move at the same
    boundary (reason replay), and two identical injected runs land
    bitwise-equal final losses with identical applied-schedule trails."""
    losses, trails = [], []
    for i in (0, 1):
        log = str(tmp_path / f"obs{i}.jsonl")
        r = _run(tmp_path, ["--tune", "schedule",
                            "--tune-schedule", "K=4@0,K=2@5",
                            "--inject", "nan@E5",
                            "--ckpt-path", str(tmp_path / f"ck{i}"),
                            "--obs-log", log])
        assert r.returncode == 0, r.stdout + r.stderr
        kinds = [e["kind"] for e in _load_events(log)]
        assert "rollback" in kinds
        trail = _tune_trail(log)
        reasons = [t[2] for t in trail]
        assert "rollback" in reasons and "replay" in reasons, trail
        # the replayed move re-applies exactly the recorded change
        rep = next(t for t in trail if t[2] == "replay")
        assert rep == (5, (("halo_refresh", 2),), "replay"), trail
        losses.append(_final_loss(r.stdout))
        trails.append(trail)
    assert losses[0] == losses[1], losses
    assert trails[0] == trails[1], trails


@pytest.mark.slow
def test_cli_resume_continues_the_schedule(tmp_path):
    """sigterm@E3 under a 3-stage schedule, then --resume twice from copies
    of the same checkpoint: restore() reconstructs the schedule state, the
    remaining entries still fire at their epochs, and the two resumed runs
    land bitwise-identical final losses."""
    interrupted = _run(tmp_path, ["--n-epochs", "10", "--tune", "schedule",
                                  "--tune-schedule", "K=4@0,K=2@2,K=1@7",
                                  "--inject", "sigterm@E3"])
    assert interrupted.returncode == 75, (
        interrupted.returncode, interrupted.stderr[-2000:])
    losses = []
    for i in (0, 1):
        ck = str(tmp_path / f"ck_resume{i}")
        shutil.copytree(str(tmp_path / "ckpt"), ck)
        log = str(tmp_path / f"obs_resume{i}.jsonl")
        r = _run(tmp_path, ["--n-epochs", "10", "--tune", "schedule",
                            "--tune-schedule", "K=4@0,K=2@2,K=1@7",
                            "--resume", "--skip-partition",
                            "--ckpt-path", ck, "--obs-log", log])
        assert r.returncode == 0, r.stdout + r.stderr
        assert "Resumed from" in r.stdout
        trail = _tune_trail(log)
        # the K=2@2 entry predates the resume point: actuated as a resume
        # diff; the K=1@7 entry fires fresh at its boundary
        assert any(t[2] == "resume" and ("halo_refresh", 2) in t[1]
                   for t in trail), trail
        assert any(t[0] == 7 and ("halo_refresh", 1) in t[1]
                   for t in trail), trail
        losses.append(_final_loss(r.stdout))
    assert losses[0] == losses[1], losses
