"""graftlint (bnsgcn_tpu/analysis/) + --strict-exec runtime guards.

Fixture matrix: every rule family gets seeded-violation fixtures (the
rule MUST fire) and clean fixtures (it MUST NOT), written into tmp dirs
and linted with --root pointed there so each fixture set is
self-contained — the axis vocabulary, donation registry and event
registry are collected from the fixture files themselves.

Framework coverage: suppression grammar (reasoned suppressions move
findings to the suppressed list, reasonless ones are themselves
findings, unknown rule ids are flagged), the JSON report schema, CLI
exit codes, `tools/lint.sh` clean at HEAD (the repo lints itself), and
the `--strict-exec` end-to-end proof: a CLI training run under the
transfer guard + compile listener finishes with zero violations and
lands the audit on the telemetry bus.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from bnsgcn_tpu.analysis import RULE_DOCS, lint_paths, report_json
from bnsgcn_tpu.analysis.core import iter_py_files, resolve_root

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# mesh-vocabulary preamble shared by SPMD fixtures: collect() reads the
# axis names out of this make_mesh literal
MESH_PREAMBLE = """\
import jax
from jax import lax
mesh = make_mesh((2,), ('parts',))
"""


def lint_dir(tmp_path, files, select=None):
    """Write {name: source} fixtures and lint the dir as its own root."""
    tmp_path.mkdir(parents=True, exist_ok=True)
    for name, src in files.items():
        (tmp_path / name).write_text(textwrap.dedent(src))
    return lint_paths([str(tmp_path)], root=str(tmp_path), select=select)


def rules(findings):
    return sorted(f.rule for f in findings)


# ----------------------------------------------------------------------------
# family 1: SPMD collective discipline
# ----------------------------------------------------------------------------

def test_spmd_unbound_axis_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_a.py": MESH_PREAMBLE + """\
def f(x):
    return lax.psum(x, 'bogus_axis')
"""})
    assert rules(active) == ["spmd-unbound-axis"]
    assert "bogus_axis" in active[0].message


def test_spmd_unbound_axis_tuple_and_kw(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_a.py": MESH_PREAMBLE + """\
def f(x):
    a = lax.all_gather(x, axis_name=('parts', 'nope'))
    b = lax.axis_index('also_nope')
    return a, b
"""})
    assert rules(active) == ["spmd-unbound-axis", "spmd-unbound-axis"]


def test_spmd_rank_branch_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_a.py": MESH_PREAMBLE + """\
def f(x):
    r = lax.axis_index('parts')
    if r == 0:
        x = lax.psum(x, 'parts')
    return x
"""})
    assert "spmd-rank-branch" in rules(active)


def test_spmd_clean_and_inactive_without_vocab(tmp_path):
    # bound axis + collective outside any rank branch: clean
    active, _, _ = lint_dir(tmp_path, {"fix_a.py": MESH_PREAMBLE + """\
def f(x):
    return lax.psum(x, 'parts')
"""})
    assert active == []
    # no mesh constructor in the target set -> empty vocabulary -> the
    # axis rule stays silent rather than flagging every axis it can't see
    active, _, _ = lint_dir(tmp_path / "sub",
                            {"fix_b.py": """\
from jax import lax
def f(x):
    return lax.psum(x, 'unknowable')
"""})
    assert active == []


# ----------------------------------------------------------------------------
# family 2: PRNG key discipline
# ----------------------------------------------------------------------------

def test_prng_literal_key_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_k.py": """\
import jax
k1 = jax.random.PRNGKey(0)
k2 = jax.random.key(42)
"""})
    assert rules(active) == ["prng-literal-key", "prng-literal-key"]


def test_prng_literal_key_exempt_in_tests(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"test_fix.py": """\
import jax
k = jax.random.PRNGKey(0)
"""})
    assert active == []


def test_prng_key_reuse_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_k.py": """\
import jax
def draw(key):
    a = jax.random.uniform(key)
    b = jax.random.normal(key)
    return a, b
"""})
    assert rules(active) == ["prng-key-reuse"]
    assert active[0].line == 4


def test_prng_key_reuse_clean_after_split(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_k.py": """\
import jax
def draw(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.uniform(k1)
    b = jax.random.normal(k2)
    return a, b

def refold(key, i):
    a = jax.random.uniform(key)
    key = jax.random.fold_in(key, i)
    b = jax.random.uniform(key)
    return a, b
"""})
    assert active == []


def test_prng_replica_fold_order_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_k.py": """\
import jax
def pair(base, epoch, replica_id):
    k = jax.random.fold_in(base, epoch)
    k = jax.random.fold_in(k, replica_id)
    return k
"""})
    assert rules(active) == ["prng-replica-fold-order"]
    assert active[0].line == 4


def test_prng_replica_fold_first_clean(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_k.py": """\
import jax
def pair(base, epoch, replica_id):
    k = jax.random.fold_in(base, replica_id)
    k = jax.random.fold_in(k, epoch)
    return k
"""})
    assert active == []


# ----------------------------------------------------------------------------
# family 3: host-sync hazards in jitted scopes
# ----------------------------------------------------------------------------

def test_hostsync_item_and_cast_fire(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_h.py": """\
import jax
import jax.numpy as jnp

@jax.jit
def step(x):
    s = jnp.sum(x)
    bad = s.item()
    worse = float(s)
    return bad + worse
"""})
    assert rules(active) == ["host-sync-cast", "host-sync-item"]


def test_hostsync_traced_branch_and_numpy_fire(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_h.py": """\
import jax
import jax.numpy as jnp
import numpy as np

def _loss(x):
    y = jnp.sum(x)
    if y > 0:
        y = -y
    h = np.asarray(y)
    return h

loss_fn = jax.jit(_loss)
"""})
    assert rules(active) == ["host-sync-numpy", "host-sync-traced-branch"]


def test_hostsync_silent_outside_jit_and_on_none_checks(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_h.py": """\
import jax
import jax.numpy as jnp

def host_side(x):
    # not a jit scope: host casts are fine here
    return float(jnp.sum(x).item())

@jax.jit
def step(x, y):
    if y is None:
        return x
    return x + y
"""})
    assert active == []


# ----------------------------------------------------------------------------
# family 4: donation safety
# ----------------------------------------------------------------------------

def test_donate_use_after_assign_form(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_d.py": """\
import jax

def _step(params, x):
    return params

step = jax.jit(_step, donate_argnums=(0,))

def loop(params, xs):
    out = step(params, xs)
    norm = params.sum()
    return out, norm
"""})
    assert rules(active) == ["donate-use-after"]
    assert active[0].line == 10


def test_donate_use_after_decorator_form(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_d.py": """\
import jax
from functools import partial

@partial(jax.jit, donate_argnums=(0, 2))
def train(p, x, cache):
    return p, cache

def drive(p, x, cache):
    p2, c2 = train(p, x, cache)
    return cache
"""})
    assert rules(active) == ["donate-use-after"]


def test_donate_same_statement_rebind_clean(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_d.py": """\
import jax

def _step(params, x, cache):
    return params, cache

step = jax.jit(_step, donate_argnums=(0, 2))

def loop(params, xs, cache):
    for x in xs:
        params, cache = step(params, x, cache)
    return params, cache
"""})
    assert active == []


# ----------------------------------------------------------------------------
# family 5: lock discipline (# guarded-by:)
# ----------------------------------------------------------------------------

def test_lock_unguarded_access_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_l.py": """\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []        # guarded-by: self._lock

    def add(self, x):
        self._items.append(x)
"""})
    assert rules(active) == ["lock-unguarded-access"]
    assert "_items" in active[0].message


def test_lock_standalone_annotation_and_wrong_lock(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_l.py": """\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._other = threading.Lock()
        # guarded-by: self._lock
        self._n = 0

    def bump(self):
        with self._other:
            self._n += 1
"""})
    assert rules(active) == ["lock-unguarded-access"]


def test_lock_clean_inside_with_and_locked_helpers(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_l.py": """\
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []        # guarded-by: self._lock

    def add(self, x):
        with self._lock:
            self._append_locked(x)

    def _append_locked(self, x):
        self._items.append(x)
"""})
    assert active == []


# ----------------------------------------------------------------------------
# family 9: lock-order discipline
# ----------------------------------------------------------------------------

_LOCKORDER = {"lock-order-cycle", "lock-held-blocking-call"}


def test_lockorder_abba_cycle_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_lo.py": """\
import threading

class Box:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def fwd(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def rev(self):
        with self._lock_b:
            with self._lock_a:
                pass
"""}, select=_LOCKORDER)
    # both edges of the ABBA pair are on the cycle — one finding each
    assert rules(active) == ["lock-order-cycle", "lock-order-cycle"]
    assert "Box._lock_a" in (active[0].message + active[1].message)
    assert "reverse order" in active[0].message


def test_lockorder_self_nest_lock_fires_rlock_clean(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_lo.py": """\
import threading

class Plain:
    def __init__(self):
        self._lock = threading.Lock()

    def f(self):
        with self._lock:
            with self._lock:
                pass

class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()

    def f(self):
        with self._lock:
            with self._lock:
                pass
"""}, select=_LOCKORDER)
    assert rules(active) == ["lock-order-cycle"]
    assert "Plain._lock" in active[0].message


def test_lockorder_consistent_order_clean(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_lo.py": """\
import threading

class Box:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def f(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def g(self):
        with self._lock_a, self._lock_b:
            pass
"""}, select=_LOCKORDER)
    assert active == []


def test_lockorder_blocking_call_under_lock(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_lo.py": """\
import os
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)

    def flush(self, fd, t):
        with self._lock:
            os.fsync(fd)        # blocks every contender on a slow disk
            t.join()            # thread join: unbounded

    def fine(self, xs):
        with self._lock:
            s = ",".join(xs)    # string join: not a thread join
        with self._cv:
            self._cv.wait()     # releases the lock while waiting
        return s
"""}, select=_LOCKORDER)
    assert rules(active) == ["lock-held-blocking-call",
                             "lock-held-blocking-call"]
    assert any("fsync" in f.message for f in active)
    assert any("join" in f.message for f in active)


# ----------------------------------------------------------------------------
# family 6: contract lints (obs registry, exit codes)
# ----------------------------------------------------------------------------

def test_obs_unregistered_event_fires(tmp_path):
    active, _, _ = lint_dir(tmp_path, {
        "obs.py": 'EVENT_KINDS = ("epoch", "run_end")\n',
        "fix_c.py": """\
def report(obs):
    obs.emit("epoch", n=1)
    obs.emit("totally_new_kind", n=2)
"""})
    assert rules(active) == ["obs-unregistered-event"]
    assert "totally_new_kind" in active[0].message


def test_obs_rule_inactive_without_registry(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_c.py": """\
def report(obs):
    obs.emit("anything_goes", n=1)
"""})
    assert active == []


def test_exit_code_literal_fires_and_named_clean(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_c.py": """\
import os
import sys
EXIT_DIVERGED = 76

def die(kind):
    if kind == "preempt":
        sys.exit(75)
    if kind == "watchdog":
        os._exit(77)
    sys.exit(EXIT_DIVERGED)     # named constant: fine
    sys.exit(1)                 # outside the lifecycle range: fine
"""})
    assert rules(active) == ["exit-code-literal", "exit-code-literal"]
    assert "EXIT_PREEMPTED" in active[0].message


# ----------------------------------------------------------------------------
# suppressions
# ----------------------------------------------------------------------------

def test_reasoned_suppression_moves_finding(tmp_path):
    active, suppressed, _ = lint_dir(tmp_path, {"fix_s.py": """\
import jax
# graftlint: disable=prng-literal-key(fixture: the reason travels)
k = jax.random.PRNGKey(0)
"""})
    assert active == []
    assert rules(suppressed) == ["prng-literal-key"]
    assert suppressed[0].reason == "fixture: the reason travels"


def test_trailing_suppression_covers_own_line(tmp_path):
    active, suppressed, _ = lint_dir(tmp_path, {"fix_s.py": (
        "import jax\n"
        "k = jax.random.PRNGKey(0)  "
        "# graftlint: disable=prng-literal-key(same line)\n")})
    assert active == [] and rules(suppressed) == ["prng-literal-key"]


def test_reasonless_suppression_is_a_finding(tmp_path):
    active, suppressed, _ = lint_dir(tmp_path, {"fix_s.py": """\
import jax
# graftlint: disable=prng-literal-key
k = jax.random.PRNGKey(0)
"""})
    # the reasonless marker does NOT suppress, and is itself flagged
    assert rules(active) == ["prng-literal-key", "suppression-missing-reason"]
    assert suppressed == []


def test_unknown_rule_suppression_is_a_finding(tmp_path):
    active, _, _ = lint_dir(tmp_path, {"fix_s.py": """\
x = 1  # graftlint: disable=not-a-rule(whatever)
"""})
    assert rules(active) == ["suppression-unknown-rule"]


def test_multi_rule_suppression_list(tmp_path):
    active, suppressed, _ = lint_dir(tmp_path, {"fix_s.py": """\
import jax
def draw(key):
    # graftlint: disable=prng-key-reuse(fixture A),prng-literal-key(fixture B)
    a = jax.random.uniform(jax.random.key(7))
    return a
"""})
    assert active == []
    assert rules(suppressed) == ["prng-literal-key"]


# ----------------------------------------------------------------------------
# report schema + select + parse errors
# ----------------------------------------------------------------------------

def test_report_json_schema(tmp_path):
    active, suppressed, errors = lint_dir(tmp_path, {
        "fix_r.py": "import jax\nk = jax.random.PRNGKey(3)\n",
        "broken.py": "def oops(:\n"})
    assert errors == ["broken.py"]
    rep = report_json(active, suppressed, errors, root=str(tmp_path),
                      n_files=2)
    assert rep["graftlint"] == 1 and rep["files_scanned"] == 2
    assert rep["ok"] is False
    assert rep["counts"] == {"prng-literal-key": 1}
    f = rep["findings"][0]
    assert set(f) == {"file", "line", "col", "rule", "message", "hint"}
    assert f["hint"] == RULE_DOCS["prng-literal-key"][1]
    json.dumps(rep)     # serializable end to end


def test_select_filters_but_keeps_suppression_rules(tmp_path):
    files = {"fix_r.py": """\
import sys
import jax
k = jax.random.PRNGKey(3)  # graftlint: disable=no-such-rule
def die():
    sys.exit(76)
"""}
    active, _, _ = lint_dir(tmp_path, files,
                            select={"exit-code-literal"})
    # selected rule + the framework's suppression lints always run
    # (an unknown rule id is one finding — it can't also be reasonless)
    assert rules(active) == ["exit-code-literal",
                             "suppression-unknown-rule"]


def test_every_rule_family_documented():
    fams = {"spmd-", "prng-", "host-sync-", "donate-", "lock-", "obs-"}
    for fam in fams:
        assert any(r.startswith(fam) for r in RULE_DOCS), fam
    for rule, (desc, hint) in RULE_DOCS.items():
        assert desc and hint, rule


# ----------------------------------------------------------------------------
# CLI + lint.sh
# ----------------------------------------------------------------------------

def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO)
    return env


def _cli(args, cwd=REPO):
    return subprocess.run([sys.executable, "-m", "bnsgcn_tpu.analysis"]
                          + list(args), capture_output=True, text=True,
                          timeout=300, cwd=cwd, env=_env())


def test_cli_seeded_violations_exit_nonzero(tmp_path):
    (tmp_path / "fix_v.py").write_text(
        "import jax\nk = jax.random.PRNGKey(1)\n")
    rep = tmp_path / "report.json"
    r = _cli(["--root", str(tmp_path), "--json", str(rep), str(tmp_path)])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "prng-literal-key" in r.stdout and "fix:" in r.stdout
    data = json.loads(rep.read_text())
    assert data["ok"] is False and data["counts"]["prng-literal-key"] == 1


def test_cli_unknown_select_and_list_rules(tmp_path):
    r = _cli(["--select", "no-such-rule", str(tmp_path)])
    assert r.returncode == 2 and "unknown rule" in r.stderr
    r = _cli(["--list-rules"])
    assert r.returncode == 0
    for rule in RULE_DOCS:
        assert rule in r.stdout


@pytest.mark.quickgate
def test_lint_sh_clean_at_head(tmp_path):
    """The repo lints itself: tools/lint.sh exits 0 at HEAD (the CI gate
    fault_matrix.sh and the quickgate tier both invoke)."""
    env = _env()
    env["LINT_REPORT"] = str(tmp_path / "lint_report.json")
    # gate 1 only: the IR and proto tiers' clean-at-HEAD runs are their
    # own quickgates (test_analysis_ir.test_ir_audit_clean_at_head,
    # test_analysis_proto.test_proto_audit_clean_at_head) — no doubling
    env["LINT_SKIP_IR"] = "1"
    env["LINT_SKIP_PROTO"] = "1"
    r = subprocess.run(["bash", "tools/lint.sh"], capture_output=True,
                       text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads((tmp_path / "lint_report.json").read_text())
    assert data["ok"] is True and data["findings"] == []
    assert data["files_scanned"] >= 50
    # every checked-in suppression carries its reason into the report
    assert all(s["reason"] for s in data["suppressed"])


def test_default_targets_exclude_tests():
    files = iter_py_files(["bnsgcn_tpu", "tools"], resolve_root(REPO))
    assert not any(os.sep + "tests" + os.sep in f for f in files)


# ----------------------------------------------------------------------------
# --strict-exec end to end
# ----------------------------------------------------------------------------

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "6",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


@pytest.mark.quickgate
def test_strict_exec_e2e_clean_run(tmp_path):
    """--strict-exec on a real CLI run: the transfer guard + compile
    listener wrap every hot-loop step; --halo-refresh 2 exercises BOTH
    compiled step programs (full + cached) as separate variants. The run
    must finish rc=0 with zero violations, each variant compiling exactly
    once (its first guarded step), and the audit landing on the obs bus."""
    log = str(tmp_path / "obs.jsonl")
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
           + ["--part-path", str(tmp_path / "parts"),
              "--ckpt-path", str(tmp_path / "ckpt"),
              "--results-path", str(tmp_path / "res"),
              "--halo-refresh", "2", "--strict-exec", "--obs-log", log])
    env = _env()
    env.update(XLA_FLAGS="--xla_force_host_platform_device_count=2",
               BNSGCN_RETRY_BACKOFF_S="0")
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                       cwd=REPO, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "[strict] exec audit:" in r.stdout
    assert "0 violation(s)" in r.stdout
    from bnsgcn_tpu.obs import load_events
    evs = load_events(log)
    se = [e for e in evs if e["kind"] == "strict_exec"]
    assert len(se) == 1, se
    s = se[0]
    assert s["violations"] == 0
    assert sorted(s["variants"]) == ["cached", "full"]
    # each program compiles exactly once, in its first guarded step
    assert s["first_compiles"] == {"full": 1, "cached": 1}
    assert sum(s["steps"].values()) == 6 and s["fetches"] == 6


def test_strict_exec_unit_recompile_and_fetch():
    """StrictExec unit semantics: a compile during a variant's first step
    arms it; a compile in any later step raises StrictExecError; fetch()
    counts; finish() emits the summary through a provided obs."""
    import jax
    import jax.numpy as jnp

    from bnsgcn_tpu.strict import StrictExec, StrictExecError

    emitted = []

    class FakeObs:
        def emit(self, kind, **kw):
            emitted.append((kind, kw))

    lines = []
    st = StrictExec(obs=FakeObs(), log=lines.append)

    @jax.jit
    def f(x):
        return x * 2

    x = jnp.arange(4.0)
    with st.step("v"):
        f(x)                    # first step: compiling is legal
    assert st.first_compiles["v"] >= 1
    with st.step("v"):
        f(x)                    # cached: no compile, still clean
    with pytest.raises(StrictExecError, match="recompile"):
        with st.step("v"):
            f(jnp.arange(8.0))  # new shape -> steady-state recompile
    assert float(st.fetch(jnp.float32(3.0))) == 3.0 and st.fetches == 1
    s = st.finish()
    # 3 steps entered (the raising one still counts), 1 violation recorded
    assert s["violations"] == 1 and s["steps"]["v"] == 3
    assert emitted and emitted[0][0] == "strict_exec"
    assert any("[strict] exec audit:" in ln for ln in lines)
