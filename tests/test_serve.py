"""Online inference serving (bnsgcn_tpu/serve.py): the two-tier contract.

What is pinned, per ISSUE/ROADMAP:
  (a) tier-A scores are BITWISE the full-eval logits for clean nodes (the
      table is the eval forward's own output — serving must never drift
      from what training reported);
  (b) tier-B fresh L-hop re-aggregation equals a recompute-from-scratch on
      the mutated graph for dirty nodes, across GCN/SAGE/GAT;
  (c) batching invariance: a request scored alone is bitwise the same
      request scored inside a full padded-SpMM bucket (per-row edge order
      is batch-composition-invariant by construction);
  (d) delta ingestion marks and refreshes EXACTLY the <= L-hop forward
      closure of the touched nodes — and refresh touches nothing else
      (clean table rows stay bitwise untouched);
  (e) quickgate e2e: a real subprocess server + TCP client round trip, and
      the SIGTERM drain -> exit 75 -> resumable delta-log replay contract
      (the serving twin of tests/test_resilience_e2e.py).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
from functools import lru_cache

import jax
import numpy as np
import pytest

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import serve
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.data.graph import Graph, sbm_graph
from bnsgcn_tpu.evaluate import full_graph_embeddings, full_graph_logits
from bnsgcn_tpu.models.gnn import init_params, spec_from_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODELS = [("gcn", False, 1), ("graphsage", True, 1), ("gat", False, 2)]
MODEL_IDS = [m[0] for m in MODELS]


@lru_cache(maxsize=None)
def _setup(model: str, use_pp: bool, heads: int):
    g = sbm_graph(n_nodes=300, n_class=4, n_feat=8, seed=0)
    cfg = Config(dataset="sbm", model=model, n_layers=2, n_hidden=8,
                 heads=heads, use_pp=use_pp, n_feat=g.n_feat,
                 n_class=g.n_class, n_train=g.n_train, serve_max_batch=16)
    spec = spec_from_config(cfg)
    params, state = init_params(jax.random.key(1), spec)
    return g, cfg, spec, params, state


def _core(model, use_pp, heads):
    g, cfg, spec, params, state = _setup(model, use_pp, heads)
    return g, spec, params, state, serve.build_core(
        cfg, g, params, state, log=lambda *a, **k: None)


def _appended(g: Graph, edges) -> Graph:
    """Ground-truth graph with `edges` appended — what tier B must match."""
    src = np.concatenate([g.src, np.asarray([u for u, _ in edges])]).astype(
        g.src.dtype)
    dst = np.concatenate([g.dst, np.asarray([v for _, v in edges])]).astype(
        g.dst.dtype)
    return Graph(g.n_nodes, src, dst, g.feat, g.label, g.train_mask,
                 g.val_mask, g.test_mask, g.multilabel)


def _fwd_closure(src, dst, seeds, hops):
    """Independent (edge-list scan) forward closure the dirty set must equal."""
    seen = set(int(s) for s in seeds)
    frontier = set(seen)
    for _ in range(hops):
        nxt = {int(d) for s, d in zip(src, dst) if int(s) in frontier} - seen
        seen |= nxt
        frontier = nxt
    return seen


# ----------------------------------------------------------------------------
# (a) tier A bitwise vs full eval
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,use_pp,heads", MODELS, ids=MODEL_IDS)
def test_tier_a_bitwise_vs_full_eval(model, use_pp, heads):
    g, spec, params, state, core = _core(model, use_pp, heads)
    try:
        ref = full_graph_logits(params, state, spec, g)
        for v in (0, 7, 123, g.n_nodes - 1):
            r = core.predict(v)
            assert r["tier"] == "A"
            assert np.array_equal(np.asarray(r["scores"], ref.dtype), ref[v])
            assert r["pred"] == int(np.argmax(ref[v]))
    finally:
        core.close()


# ----------------------------------------------------------------------------
# (b) tier B == recompute-from-scratch for dirty nodes after edge appends
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,use_pp,heads", MODELS, ids=MODEL_IDS)
def test_tier_b_matches_scratch_recompute(model, use_pp, heads):
    g, spec, params, state, core = _core(model, use_pp, heads)
    try:
        edges = [(7, 5), (11, 5), (7, 5)]      # incl. a multi-edge
        core.add_edges(edges)
        ref2 = full_graph_logits(params, state, spec, _appended(g, edges))
        dirty = sorted(core.dirty)[:6] + [5]
        for v in set(dirty):
            r = core.predict(v)
            assert r["tier"] == "B", f"node {v} should be dirty"
            np.testing.assert_allclose(np.asarray(r["scores"]), ref2[v],
                                       rtol=1e-5, atol=1e-5)
    finally:
        core.close()


def test_tier_b_exact_after_feature_update():
    g, spec, params, state, core = _core("graphsage", True, 1)
    try:
        new_feat = np.full(g.n_feat, 0.25, dtype=np.float32)
        core.update_feat(9, new_feat)
        g2 = Graph(g.n_nodes, g.src, g.dst, g.feat.copy(), g.label,
                   g.train_mask, g.val_mask, g.test_mask, g.multilabel)
        g2.feat[9] = new_feat
        ref2 = full_graph_logits(params, state, spec, g2)
        assert 9 in core.dirty
        r = core.predict(9)
        assert r["tier"] == "B"
        np.testing.assert_allclose(np.asarray(r["scores"]), ref2[9],
                                   rtol=1e-5, atol=1e-5)
    finally:
        core.close()


# ----------------------------------------------------------------------------
# (c) batching invariance: alone == inside a full bucket
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,use_pp,heads", MODELS, ids=MODEL_IDS)
def test_batching_invariance_bitwise(model, use_pp, heads):
    g, spec, params, state, core = _core(model, use_pp, heads)
    try:
        target = 42
        alone = core.scorer.score(core.graph, params, state, [target])
        full = core.scorer.score(core.graph, params, state,
                                 [target] + list(range(16)))
        assert np.array_equal(alone[target][1], full[target][1])
        assert np.array_equal(alone[target][0], full[target][0])
    finally:
        core.close()


def test_predict_many_coalesces_tier_b_into_bucket_steps():
    """A batch request's tier-B set must run as whole-bucket steps (never
    one step per node) and agree with the per-node tier-B path."""
    g, spec, params, state, core = _core("gcn", False, 1)
    try:
        core.add_edges([(3, 17)])
        dirty_pick = sorted(core.dirty)[:10]
        clean_pick = [n for n in range(g.n_nodes)
                      if n not in core.dirty][:2]
        nodes = dirty_pick + clean_pick
        solo = {n: core.scorer.score(core.graph, params, state, [n])[n][1]
                for n in dirty_pick}
        before = core.snapshot_stats()["refreshed_nodes"]
        out = core.predict_many(nodes)
        tiers = {r["node"]: r for r in out}
        n_b = sum(1 for r in out if r["tier"] == "B")
        assert n_b == len(dirty_pick) and len(out) == len(nodes)
        for n, ref in solo.items():
            assert np.array_equal(np.asarray(tiers[n]["scores"],
                                             ref.dtype), ref)
        # the whole tier-B set fit one serve_max_batch bucket step, which
        # also refreshed those rows (they were dirty)
        assert core.snapshot_stats()["refreshed_nodes"] == before + n_b
        assert all(tiers[n]["tier"] == "A" for n in clean_pick)
    finally:
        core.close()


def test_concurrent_requests_coalesce_into_buckets():
    """Concurrent tier-B submissions share batcher steps AND each equals its
    solo score — the batching path itself is invariant, not just the
    scorer."""
    g, spec, params, state, core = _core("graphsage", True, 1)
    try:
        targets = list(range(12))
        solo = {t: core.scorer.score(core.graph, params, state, [t])[t][1]
                for t in targets}
        results = {}

        def one(t):
            results[t] = np.asarray(core.predict(t, tier="B")["scores"])

        threads = [threading.Thread(target=one, args=(t,)) for t in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for t in targets:
            assert np.array_equal(results[t], solo[t]), f"node {t}"
        stats = core.snapshot_stats()
        assert stats["batches"] <= len(targets)   # at least some coalescing
        assert stats["batched_requests"] == len(targets)
    finally:
        core.close()


# ----------------------------------------------------------------------------
# (d) delta ingestion: exactly the <= L-hop dirty set, nothing else
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,use_pp,heads", MODELS, ids=MODEL_IDS)
def test_delta_refreshes_exactly_the_dirty_set(model, use_pp, heads):
    g, spec, params, state, core = _core(model, use_pp, heads)
    try:
        edges = [(3, 17)]
        core.add_edges(edges)
        g2 = _appended(g, edges)
        expected = _fwd_closure(g2.src, g2.dst, {3, 17}, core.hops)
        assert core.dirty == expected
        before_logits = core.logits.copy()
        before_hidden = core.hidden.copy()
        refreshed = core.flush()
        assert refreshed == len(expected)
        assert core.snapshot_stats()["refreshed_nodes"] == len(expected)
        assert not core.dirty
        clean = np.setdiff1d(np.arange(g.n_nodes), sorted(expected))
        # nothing else: clean rows bitwise untouched
        assert np.array_equal(core.logits[clean], before_logits[clean])
        assert np.array_equal(core.hidden[clean], before_hidden[clean])
        # the dirty set: refreshed to the scratch recompute
        ref2 = full_graph_logits(params, state, spec, g2)
        ids = sorted(expected)
        np.testing.assert_allclose(core.logits[ids], ref2[ids],
                                   rtol=1e-5, atol=1e-5)
        # and tier A serves the refreshed rows again
        r = core.predict(17)
        assert r["tier"] == "A"
    finally:
        core.close()


def test_dirty_mark_survives_concurrent_delta_mid_refresh():
    """A delta landing while a refresh step is in flight must not have its
    fresh dirty mark cleared by the step's (now stale) result — and claimed
    nodes are never double-picked by a concurrent refresh."""
    g, spec, params, state, core = _core("gcn", False, 1)
    try:
        core.add_edges([(3, 17)])
        orig_run = core.scorer.run_arrays

        def run_then_mutate(*a, **kw):
            out = orig_run(*a, **kw)
            # lands between the step's snapshot and its write-back; also
            # proves the claim: node 17 is in _refreshing, not dirty, so
            # refresh_some here must not double-pick it
            assert 17 in core._refreshing
            assert 17 not in core.dirty
            core.add_edges([(1, 17)])
            return out

        core.scorer.run_arrays = run_then_mutate
        try:
            core._score_batch([17])
        finally:
            core.scorer.run_arrays = orig_run
        assert 17 in core.dirty          # stale result did not clear it
        assert 17 not in core._refreshing
        # and tier routing still treats it as dirty
        assert core.predict(17)["tier"] == "B"
        core.flush()
        assert not core.dirty and not core._refreshing
    finally:
        core.close()


# ----------------------------------------------------------------------------
# checkpoint selection + embedding artifact (satellites)
# ----------------------------------------------------------------------------

def _ckpt_cfg(tmp_path):
    g, cfg, spec, params, state = _setup("graphsage", True, 1)
    cfg = cfg.replace(ckpt_path=str(tmp_path),
                      graph_name=cfg.derive_graph_name())
    return g, cfg, spec, params, state


def test_serving_checkpoint_prefers_final_then_walks_chain(tmp_path):
    g, cfg, spec, params, state = _ckpt_cfg(tmp_path)
    ckpt.save_checkpoint(ckpt.periodic_path(cfg, 3), params=params,
                         bn_state=state, epoch=3, seed=1)
    assert ckpt.serving_checkpoint(cfg)[0] == ckpt.periodic_path(cfg, 3)
    ckpt.save_checkpoint(ckpt.final_path(cfg), params=params,
                         bn_state=state, epoch=9, best_acc=0.7, seed=1)
    path, payload = ckpt.serving_checkpoint(cfg)
    assert path == ckpt.final_path(cfg) and payload["epoch"] == 9
    # torn final -> fall back to the newest valid periodic, loudly
    from bnsgcn_tpu.resilience import corrupt_file
    corrupt_file(ckpt.final_path(cfg))
    logged = []
    path, payload = ckpt.serving_checkpoint(cfg, log=logged.append)
    assert path == ckpt.periodic_path(cfg, 3) and payload["epoch"] == 3
    assert any("final checkpoint unusable" in s for s in logged)
    # everything torn -> None (serve exits 2 with a named error, never
    # loads garbage)
    corrupt_file(ckpt.periodic_path(cfg, 3))
    assert ckpt.serving_checkpoint(cfg, log=logged.append) is None


def test_embedding_table_roundtrip_and_integrity(tmp_path):
    g, cfg, spec, params, state = _setup("gcn", False, 1)
    hidden, logits = full_graph_embeddings(params, state, spec, g)
    path = str(tmp_path / "emb.tbl")
    serve.save_table(path, hidden, logits, meta={"graph_name": "x",
                                                 "n_nodes": g.n_nodes})
    h2, l2, meta = serve.load_table(path)
    assert np.array_equal(h2, hidden) and np.array_equal(l2, logits)
    assert meta["n_nodes"] == g.n_nodes
    from bnsgcn_tpu.resilience import corrupt_file
    corrupt_file(path)
    with pytest.raises(ckpt.CheckpointCorrupt):
        serve.load_table(path)
    # a wrong-sized artifact is a named config error, not a silent mismatch
    with pytest.raises(ConfigError):
        serve.ServeCore(cfg, spec, serve.DynamicGraph(g), params, state,
                        hidden[:10], logits[:10], log=lambda *a: None)


def test_cold_start_from_table_matches_precompute():
    """build_core(hidden=..., logits=...) — the --embeddings cold start —
    serves bitwise what a fresh precompute serves."""
    g, cfg, spec, params, state = _setup("gcn", False, 1)
    hidden, logits = full_graph_embeddings(params, state, spec, g)
    core = serve.build_core(cfg, g, params, state, log=lambda *a: None,
                            hidden=hidden, logits=logits)
    try:
        ref = full_graph_logits(params, state, spec, g)
        r = core.predict(33)
        assert np.array_equal(np.asarray(r["scores"], ref.dtype), ref[33])
    finally:
        core.close()


def test_dump_embeddings_flag_writes_loadable_table(tmp_path):
    """--dump-embeddings on the eval path: run_training writes the
    integrity-headed all-node table an external serve cold-starts from."""
    from bnsgcn_tpu.run import run_training
    out = str(tmp_path / "emb.tbl")
    cfg = Config(dataset="sbm", partition_method="random", n_partitions=2,
                 model="graphsage", n_layers=2, n_hidden=8, use_pp=True,
                 sampling_rate=1.0, n_epochs=4, log_every=2, fix_seed=True,
                 seed=5, part_path=str(tmp_path / "parts"),
                 ckpt_path=str(tmp_path / "ckpt"),
                 results_path=str(tmp_path / "res"),
                 comm_trace=False, dump_embeddings=out)
    run_training(cfg, verbose=False)
    hidden, logits, meta = serve.load_table(out)
    assert hidden.shape[0] == logits.shape[0] == 2000
    assert hidden.shape[1] == 8 and meta["model"] == "graphsage"
    assert np.isfinite(hidden).all() and np.isfinite(logits).all()


# ----------------------------------------------------------------------------
# DynamicGraph units
# ----------------------------------------------------------------------------

def test_dynamic_graph_neighbors_and_degrees_track_deltas():
    g = sbm_graph(n_nodes=100, n_class=4, n_feat=4, seed=2)
    dg = serve.DynamicGraph(g)
    in_before = list(dg.in_nbrs(5))
    od_u, id_v = dg.out_deg[9], dg.in_deg[5]
    dg.add_edges([(9, 5), (9, 5)])
    assert dg.in_nbrs(5) == in_before + [9, 9]
    assert dg.out_deg[9] == od_u + 2 and dg.in_deg[5] == id_v + 2
    with pytest.raises(ValueError):
        dg.add_edges([(0, 100)])
    with pytest.raises(ValueError):
        dg.set_feat(0, np.zeros(3, np.float32))


def test_in_closure_depths_cover_the_computation_subgraph():
    g = sbm_graph(n_nodes=100, n_class=4, n_feat=4, seed=2)
    dg = serve.DynamicGraph(g)
    depth = dg.in_closure([7], 2)
    assert depth[7] == 0
    for u in dg.in_nbrs(7):
        assert depth[u] <= 1
        for w in dg.in_nbrs(u):
            assert w in depth
    # every node at depth <= hops-1 has its FULL in-neighborhood present
    for v, d in depth.items():
        if d <= 1:
            assert all(u in depth for u in dg.in_nbrs(v))


def test_bucket_ladder_is_static_shapes():
    assert serve._bucket(1, 32) == 32
    assert serve._bucket(32, 32) == 32
    assert serve._bucket(33, 32) == 64
    assert serve._bucket(1000, 128) == 1024


# ----------------------------------------------------------------------------
# (e) e2e: subprocess server + client round trip; SIGTERM drain contract
# ----------------------------------------------------------------------------

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    return env


def _write_serving_ckpt(tmp_path):
    """A loadable (random-init) checkpoint + the flag set serve launches
    with — serving correctness does not depend on trained weights."""
    cfg = Config(dataset="sbm", model="graphsage", n_layers=2, n_hidden=8,
                 use_pp=True, seed=3, sampling_rate=1.0,
                 ckpt_path=str(tmp_path / "ckpt"))
    cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    from bnsgcn_tpu.data.datasets import load_data
    g, _, _ = load_data(cfg)
    cfg2 = cfg.replace(n_feat=g.n_feat, n_class=g.n_class, n_train=g.n_train)
    params, state = init_params(jax.random.key(3),
                                spec_from_config(cfg2))
    ckpt.save_checkpoint(ckpt.final_path(cfg2), params=params,
                         bn_state=state, epoch=7, best_acc=0.5, seed=3)
    return ["--dataset", "sbm", "--model", "graphsage", "--n-layers", "2",
            "--n-hidden", "8", "--use-pp", "--fix-seed", "--seed", "3",
            "--ckpt-path", str(tmp_path / "ckpt")]


def _launch(args, port):
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main", "serve"] + args
           + ["--serve-port", str(port)])
    p = subprocess.Popen(cmd, env=_env(), cwd=REPO, stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT, text=True)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if p.poll() is not None:
            raise AssertionError(f"server died rc={p.returncode}:\n"
                                 f"{p.stdout.read()[-2000:]}")
        try:
            if serve.request(port, {"op": "ping"}, timeout_s=1.0).get("ok"):
                return p
        except Exception:
            pass
        time.sleep(0.2)
    p.kill()
    raise AssertionError("server never became ready")


@pytest.mark.quickgate
def test_e2e_subprocess_server_roundtrip(tmp_path):
    args = _write_serving_ckpt(tmp_path)
    port = _free_port()
    p = _launch(args, port)
    try:
        r = serve.request(port, {"op": "predict", "node": 11})
        assert r["ok"] and r["tier"] == "A" and len(r["scores"]) == 8
        r = serve.request(port, {"op": "add_edges", "edges": [[4, 11]]})
        assert r["ok"] and r["dirty_total"] > 0
        r = serve.request(port, {"op": "predict", "node": 11})
        assert r["ok"] and r["tier"] == "B"
        r = serve.request(port, {"op": "predict_many",
                                 "nodes": [1, 2, 3]})
        assert r["ok"] and len(r["results"]) == 3
        assert serve.request(port, {"op": "nope"})["ok"] is False
        stats = serve.request(port, {"op": "stats"})
        # nodes 1-3 may or may not sit in the appended edge's dirty
        # frontier, so only the totals are pinned, not the tier split
        assert stats["requests"] >= 5
        assert stats["tier_a"] >= 1 and stats["tier_b"] >= 1
        serve.request(port, {"op": "shutdown"})
        assert p.wait(timeout=60) == 0
    finally:
        if p.poll() is None:
            p.kill()


def test_e2e_sigterm_drains_flushes_delta_log_exit_75(tmp_path):
    """The serving half of the PR-4 preemption contract: SIGTERM -> drain,
    delta log flushed, exit 75; a relaunch replays the log (the ingested
    delta — and its dirty frontier — survives the restart)."""
    args = _write_serving_ckpt(tmp_path)
    serve_dir = str(tmp_path / "servedir")
    args += ["--serve-dir", serve_dir]
    port = _free_port()
    p = _launch(args, port)
    try:
        serve.request(port, {"op": "add_edges", "edges": [[4, 11], [7, 2]]})
        p.send_signal(15)
        rc = p.wait(timeout=60)
        out = p.stdout.read()
        assert rc == 75, (rc, out[-2000:])
        assert "delta(s) flushed" in out
        log_path = os.path.join(serve_dir, serve.DELTA_LOG)
        assert os.path.exists(log_path)
        lines = [json.loads(l) for l in open(log_path) if l.strip()]
        assert lines == [{"op": "add_edges", "edges": [[4, 11], [7, 2]]}]
    finally:
        if p.poll() is None:
            p.kill()
    # relaunch: the delta (and its dirty frontier) must be live again
    p2 = _launch(args, port)
    try:
        stats = serve.request(port, {"op": "stats"})
        assert stats["deltas"] == 1
        r = serve.request(port, {"op": "flush"})
        assert r["ok"]
        assert serve.request(port, {"op": "dirty"})["count"] == 0
        serve.request(port, {"op": "shutdown"})
        assert p2.wait(timeout=60) == 0
    finally:
        if p2.poll() is None:
            p2.kill()
