"""BNS convergence parity: sampled training (P=4, rate 0.1) reaches the same
accuracy neighborhood as exact training (P=1, rate 1.0) — the paper's core
claim (README.md:123-130) at test scale."""

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.evaluate import gather_parts
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)
from bnsgcn_tpu.utils.metrics import calc_acc


def _train(g, P, rate, epochs=80):
    cfg = Config(model="graphsage", dropout=0.1, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=rate)
    spec = ModelSpec("graphsage", (g.n_feat, 16, g.n_class), norm="layer",
                     dropout=0.1, use_pp=True, train_size=g.n_train)
    mesh = make_parts_mesh(P)
    art = build_artifacts(g, partition_graph(g, P, method="random", seed=2))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
    params, state = init_params(jax.random.key(5), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    for e in range(epochs):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
    logits = gather_parts(art, fns.forward(params, state, jnp.uint32(0), blk,
                                           tb, jax.random.key(0)))
    labels = gather_parts(art, art.label)
    mask = gather_parts(art, art.val_mask)
    return calc_acc(logits[mask], labels[mask])


def test_bns_rate01_converges_like_exact():
    g = sbm_graph(n_nodes=400, n_class=4, n_feat=12, p_in=0.10, p_out=0.004,
                  seed=70)
    acc_exact = _train(g, P=1, rate=1.0)
    acc_bns = _train(g, P=4, rate=0.1)
    assert acc_exact > 0.85, acc_exact
    assert acc_bns > acc_exact - 0.08, (acc_bns, acc_exact)
