"""Pallas ELL bucket kernel vs jnp reference (interpret mode on CPU).

The kernel is a STUDY ARTIFACT living in tools/pallas_spmm.py (round 5: the
unrolled column-chain accumulation beat it on hardware and the dispatch was
retired); its interpreter checks are kept but slow-marked, out of the
default (tier-1) run. test_ell_accum_modes_agree pins the LIVE ops/ell
accumulation paths and stays in the default tier."""

import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.ops.ell import build_layouts
from tools.pallas_spmm import pallas_bucket_sum, pallas_ell_apply
from bnsgcn_tpu.ops.spmm import agg_sum


@pytest.mark.slow
def test_bucket_sum_matches_gather():
    rng = np.random.default_rng(0)
    n, h_dim, r, w = 50, 8, 16, 4
    hp = jnp.asarray(rng.normal(size=(n + 1, h_dim)).astype(np.float32))
    hp = hp.at[n].set(0.0)
    idx = jnp.asarray(rng.integers(0, n + 1, size=(r, w)).astype(np.int32))
    out = pallas_bucket_sum(hp, idx, interpret=True)
    expect = np.asarray(hp)[np.asarray(idx)].sum(axis=1)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_pallas_ell_apply_matches_segment():
    g = synthetic_graph(n_nodes=60, avg_degree=6, n_feat=5, seed=2,
                        power_law=True)
    art = build_artifacts(g, partition_graph(g, 1))
    fs, bs, arrays = build_layouts(art.src, art.dst, art.pad_inner, art.n_ext)
    idx_list = [jnp.asarray(arrays[f"fwd_idx_{k}"][0])
                for k in range(len(fs.widths))]
    perm = jnp.asarray(arrays["fwd_perm"][0])
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 5)).astype(np.float32))
    out = pallas_ell_apply(fs, idx_list, perm, h, interpret=True)
    expect = agg_sum(h, jnp.asarray(art.src[0]), jnp.asarray(art.dst[0]),
                     art.pad_inner)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_pallas_bucket_reduce_matches_sum():
    rng = np.random.default_rng(5)
    g = jnp.asarray(rng.normal(size=(24, 8, 16)).astype(np.float32))
    from tools.pallas_spmm import pallas_bucket_reduce
    out = pallas_bucket_reduce(g, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(g.sum(1)),
                               rtol=1e-5, atol=1e-5)


def test_ell_accum_modes_agree():
    """The ELL accumulation strategies must be numerically interchangeable:
    'unroll' (the TPU/headline path, forced here via accum) vs 'reduce'
    (the fp8/off-TPU materializing path). Replaces the retired
    use_pallas-vs-jnp comparison, which became vacuous once the
    pallas_bucket_reduce dispatch was removed from _bucket_sum (round 5 —
    use_pallas now switches only the fused dense-tile kernel)."""
    g = synthetic_graph(n_nodes=40, avg_degree=5, n_feat=4, seed=7)
    art = build_artifacts(g, partition_graph(g, 1))
    fs, bs, arrays = build_layouts(art.src, art.dst, art.pad_inner, art.n_ext)
    from bnsgcn_tpu.ops.ell import make_ell_spmm
    spmm_u = make_ell_spmm(fs, bs, len(fs.widths), len(bs.widths),
                           accum="unroll")
    spmm_r = make_ell_spmm(fs, bs, len(fs.widths), len(bs.widths),
                           accum="reduce")
    a0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    h = jnp.asarray(np.random.default_rng(8).normal(
        size=(art.n_ext, 4)).astype(np.float32))
    np.testing.assert_allclose(np.asarray(spmm_u(a0, h)),
                               np.asarray(spmm_r(a0, h)),
                               rtol=1e-5, atol=1e-5)
