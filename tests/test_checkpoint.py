"""Checkpoint round-trip, resume state, and reducer utilities."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu.config import Config
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.trainer import make_tx


def test_checkpoint_roundtrip(tmp_path):
    spec = ModelSpec("graphsage", (5, 8, 3), norm="batch", dropout=0.1,
                     train_size=10)
    params, state = init_params(jax.random.key(0), spec)
    tx = make_tx(Config(lr=0.01, weight_decay=1e-4))
    opt = tx.init(params)
    path = str(tmp_path / "a.ckpt")
    ckpt.save_checkpoint(path, params=params, opt_state=opt, bn_state=state,
                         epoch=17, best_acc=0.93, seed=5)
    payload = ckpt.load_checkpoint(path)
    assert payload["epoch"] == 17 and abs(payload["best_acc"] - 0.93) < 1e-9
    p2, o2, s2 = ckpt.restore_into(payload, params, opt, state)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 params, p2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 opt, o2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 state, s2)


def test_latest_checkpoint_selection(tmp_path):
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g")
    spec = ModelSpec("gcn", (4, 4, 2), norm=None)
    params, _ = init_params(jax.random.key(0), spec)
    for ep in (9, 19, 4):
        ckpt.save_checkpoint(ckpt.periodic_path(cfg, ep), params=params, epoch=ep)
    latest = ckpt.latest_checkpoint(cfg)
    assert latest and latest.endswith("_19.ckpt")
    # different rate -> no match
    assert ckpt.latest_checkpoint(cfg.replace(sampling_rate=0.1)) is None


def test_prune_checkpoints_keeps_newest_and_final(tmp_path):
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g", keep_ckpt=2)
    spec = ModelSpec("gcn", (4, 4, 2), norm=None)
    params, _ = init_params(jax.random.key(0), spec)
    for ep in (4, 9, 19, 29):
        ckpt.save_checkpoint(ckpt.periodic_path(cfg, ep), params=params, epoch=ep)
    ckpt.save_checkpoint(ckpt.final_path(cfg), params=params, epoch=29)
    # a different-rate run in the same dir must be untouched
    other = cfg.replace(sampling_rate=0.1)
    ckpt.save_checkpoint(ckpt.periodic_path(other, 3), params=params, epoch=3)
    ckpt.prune_checkpoints(cfg, cfg.keep_ckpt)
    left = sorted(os.listdir(tmp_path))
    assert os.path.basename(ckpt.periodic_path(cfg, 19)) in left
    assert os.path.basename(ckpt.periodic_path(cfg, 29)) in left
    assert os.path.basename(ckpt.periodic_path(cfg, 4)) not in left
    assert os.path.basename(ckpt.periodic_path(cfg, 9)) not in left
    assert os.path.basename(ckpt.final_path(cfg)) in left
    assert os.path.basename(ckpt.periodic_path(other, 3)) in left
    # keep=0 disables pruning
    ckpt.prune_checkpoints(cfg.replace(keep_ckpt=0), 0)
    assert os.path.basename(ckpt.periodic_path(cfg, 19)) in os.listdir(tmp_path)


def test_atomic_write_no_tmp_left(tmp_path):
    spec = ModelSpec("gcn", (4, 4, 2), norm=None)
    params, _ = init_params(jax.random.key(0), spec)
    path = str(tmp_path / "x.ckpt")
    ckpt.save_checkpoint(path, params=params)
    assert os.path.exists(path) and not os.path.exists(path + ".tmp")


def test_resume_adopts_checkpoint_seed(tmp_path):
    """A resumed run must continue the saved BNS/dropout streams even when the
    relaunch got a different randomized cfg.seed (main.py re-rolls per launch):
    losses after resume match the uninterrupted run bit-for-bit."""
    from bnsgcn_tpu.data.graph import sbm_graph
    from bnsgcn_tpu.run import run_training

    g = sbm_graph(n_nodes=240, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                  seed=3)
    base = Config(dataset="sbm", model="graphsage", n_partitions=2,
                  n_layers=2, n_hidden=8, sampling_rate=0.5, dropout=0.5,
                  use_pp=True, eval=False, n_epochs=8, log_every=2, seed=7,
                  part_path=str(tmp_path / "parts"),
                  ckpt_path=str(tmp_path / "ckpt_a"),
                  results_path=str(tmp_path / "res"))
    full = run_training(base, g=g, verbose=False)
    # interrupted run: 4 epochs (ckpts at 1,3), then resume with a DIFFERENT seed
    cfg_b = base.replace(ckpt_path=str(tmp_path / "ckpt_b"), n_epochs=4)
    run_training(cfg_b, g=g, verbose=False)
    resumed = run_training(cfg_b.replace(n_epochs=8, resume=True, seed=999),
                           g=g, verbose=False)
    np.testing.assert_allclose(resumed.losses, full.losses[4:], rtol=1e-6)


def test_assert_replicated_passes_on_replicated():
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.parallel.reducer import assert_replicated
    from bnsgcn_tpu.trainer import place_replicated
    mesh = make_parts_mesh(4)
    tree = place_replicated({"w": jnp.ones((8, 8))}, mesh)
    assert_replicated(tree)
