"""Checkpoint round-trip, resume state, and reducer utilities."""

import os

import jax
import jax.numpy as jnp
import numpy as np

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu.config import Config
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.trainer import make_tx


def test_checkpoint_roundtrip(tmp_path):
    spec = ModelSpec("graphsage", (5, 8, 3), norm="batch", dropout=0.1,
                     train_size=10)
    params, state = init_params(jax.random.key(0), spec)
    tx = make_tx(Config(lr=0.01, weight_decay=1e-4))
    opt = tx.init(params)
    path = str(tmp_path / "a.ckpt")
    ckpt.save_checkpoint(path, params=params, opt_state=opt, bn_state=state,
                         epoch=17, best_acc=0.93, seed=5)
    payload = ckpt.load_checkpoint(path)
    assert payload["epoch"] == 17 and abs(payload["best_acc"] - 0.93) < 1e-9
    p2, o2, s2 = ckpt.restore_into(payload, params, opt, state)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 params, p2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 opt, o2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
                 state, s2)


def test_latest_checkpoint_selection(tmp_path):
    cfg = Config(dataset="sbm", n_partitions=2, sampling_rate=0.5,
                 ckpt_path=str(tmp_path), graph_name="g")
    spec = ModelSpec("gcn", (4, 4, 2), norm=None)
    params, _ = init_params(jax.random.key(0), spec)
    for ep in (9, 19, 4):
        ckpt.save_checkpoint(ckpt.periodic_path(cfg, ep), params=params, epoch=ep)
    latest = ckpt.latest_checkpoint(cfg)
    assert latest and latest.endswith("_19.ckpt")
    # different rate -> no match
    assert ckpt.latest_checkpoint(cfg.replace(sampling_rate=0.1)) is None


def test_atomic_write_no_tmp_left(tmp_path):
    spec = ModelSpec("gcn", (4, 4, 2), norm=None)
    params, _ = init_params(jax.random.key(0), spec)
    path = str(tmp_path / "x.ckpt")
    ckpt.save_checkpoint(path, params=params)
    assert os.path.exists(path) and not os.path.exists(path + ".tmp")


def test_assert_replicated_passes_on_replicated():
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.parallel.reducer import assert_replicated
    from bnsgcn_tpu.trainer import place_replicated
    mesh = make_parts_mesh(4)
    tree = place_replicated({"w": jnp.ones((8, 8))}, mesh)
    assert_replicated(tree)
