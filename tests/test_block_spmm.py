"""Hybrid block-dense + ELL SpMM == plain ELL SpMM == dense oracle
(forward and gradients), on clustered and uniform graphs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.ops.block_spmm import (build_block_layouts, cluster_order,
                                       dense_edge_count, make_block_spmm)
from bnsgcn_tpu.ops.ell import build_layouts, make_ell_spmm


def _hybrid_for(art, occupancy_min, tile=512):
    P = art.n_parts
    perms_i, perms_e = [], []
    for p in range(P):
        pi, pe = cluster_order(art.src[p], art.dst[p], art.pad_inner,
                               art.n_ext, target=min(tile, 64))
        perms_i.append(pi)
        perms_e.append(pe)
    fwd, bwd, ell_pair, arrays = build_block_layouts(
        art.src, art.dst, art.pad_inner, art.n_ext,
        np.stack(perms_i), np.stack(perms_e), occupancy_min=occupancy_min,
        tile_r=tile, tile_c=tile)
    return fwd, bwd, ell_pair, arrays


def _dense_oracle(art, p, h_ext):
    out = np.zeros((art.pad_inner, h_ext.shape[1]))
    real = art.dst[p] < art.pad_inner
    np.add.at(out, art.dst[p][real], np.asarray(h_ext)[art.src[p][real]])
    return out


def _assert_oracle_and_grads(art, spmm, arrays, H=7, seed=0):
    """Forward == dense oracle and d/dh == A^T cot on every part."""
    rng = np.random.default_rng(seed)
    for p in range(art.n_parts):
        h = jnp.asarray(rng.normal(size=(art.n_ext, H)), jnp.float32)
        arr_p = {k: jnp.asarray(v[p]) for k, v in arrays.items()}
        out = np.asarray(spmm(arr_p, h))
        np.testing.assert_allclose(out, _dense_oracle(art, p, h),
                                   rtol=1e-4, atol=1e-4)
        cot = rng.normal(size=out.shape).astype(np.float32)
        gfn = jax.grad(lambda hh: jnp.sum(spmm(arr_p, hh) * cot))
        d_h = np.asarray(gfn(h))
        d_ref = np.zeros((art.n_ext, H))
        real = art.dst[p] < art.pad_inner
        np.add.at(d_ref, art.src[p][real], cot[art.dst[p][real]])
        np.testing.assert_allclose(d_h, d_ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("graph,occ", [("sbm", 4), ("uniform", 4),
                                       ("sbm", 10**9)])
def test_hybrid_matches_oracle_and_grads(graph, occ):
    """occ=4: most edges densify on the clustered graph; occ=huge: pure-ELL
    degeneration — all must equal the dense oracle exactly."""
    if graph == "sbm":
        g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15,
                      p_out=0.003, seed=61)
    else:
        g = synthetic_graph(n_nodes=300, avg_degree=8, n_feat=6, seed=62)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, occ)
    spmm = make_block_spmm(fwd, bwd, ell_pair)
    if graph == "sbm" and occ == 4:
        assert dense_edge_count(arrays, 0) > 0, "no tiles densified"
    _assert_oracle_and_grads(art, spmm, arrays)


@pytest.mark.parametrize("tile", [32, 64])
def test_hybrid_tile_size_matches_oracle(tile):
    """Non-default tile geometry (the bench's +t256 class, scaled to test
    size): multiple row/col blocks per part, forward and VJP exact."""
    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15, p_out=0.003,
                  seed=61)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, 4, tile=tile)
    assert fwd.row_tile == tile and fwd.n_row_blocks > 1
    assert dense_edge_count(arrays, 0) > 0, "no tiles densified"
    spmm = make_block_spmm(fwd, bwd, ell_pair)
    _assert_oracle_and_grads(art, spmm, arrays)


def test_hybrid_equals_pure_ell():
    g = sbm_graph(n_nodes=240, n_class=4, n_feat=6, p_in=0.12, p_out=0.004,
                  seed=63)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=4))
    fwd_h, bwd_h, ell_pair, arrays_h = _hybrid_for(art, 4)
    hybrid = make_block_spmm(fwd_h, bwd_h, ell_pair)
    f_spec, b_spec, ell_arrays = build_layouts(art.src, art.dst,
                                               art.pad_inner, art.n_ext)
    ell = make_ell_spmm(f_spec, b_spec, len(f_spec.widths),
                        len(b_spec.widths))
    rng = np.random.default_rng(1)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 5)), jnp.float32)
    a_h = {k: jnp.asarray(v[0]) for k, v in arrays_h.items()}
    a_e = {k: jnp.asarray(v[0]) for k, v in ell_arrays.items()}
    np.testing.assert_allclose(np.asarray(hybrid(a_h, h)),
                               np.asarray(ell(a_e, h)), rtol=1e-4, atol=1e-4)


def test_multiplicity_overflow_rides_residual():
    """>127 duplicate edges of one (u,v) pair exceed int8 tile headroom; the
    excess must ride the ELL residual so hybrid == oracle exactly."""
    g = sbm_graph(n_nodes=200, n_class=3, n_feat=5, p_in=0.2, p_out=0.01,
                  seed=65)
    g.src = np.concatenate([g.src, np.full(300, 7, dtype=np.int64)])
    g.dst = np.concatenate([g.dst, np.full(300, 9, dtype=np.int64)])
    art = build_artifacts(g, np.zeros(g.n_nodes, dtype=np.int32))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, 4)
    assert int(arrays["blk_tiles_fwd"].max()) == 127, "no tile saturated"
    spmm = make_block_spmm(fwd, bwd, ell_pair)
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 5)), jnp.float32)
    arr0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    np.testing.assert_allclose(np.asarray(spmm(arr0, h)),
                               _dense_oracle(art, 0, h), rtol=1e-4, atol=1e-4)
    cot = rng.normal(size=(art.pad_inner, 5)).astype(np.float32)
    d_h = np.asarray(jax.grad(lambda hh: jnp.sum(spmm(arr0, hh) * cot))(h))
    d_ref = np.zeros((art.n_ext, 5))
    real = art.dst[0] < art.pad_inner
    np.add.at(d_ref, art.src[0][real], cot[art.dst[0][real]])
    np.testing.assert_allclose(d_h, d_ref, rtol=1e-4, atol=1e-4)


def test_hybrid_train_step_matches_ell():
    """--spmm hybrid inside the sharded train step (custom VJP under
    shard_map's varying-axes checks) == --spmm ell, losses and params."""
    import jax.numpy as jnp
    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks,
                                    place_replicated)

    g = sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.1, p_out=0.005,
                  seed=66)
    spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.0,
                     use_pp=True, train_size=g.n_train)
    params0, state0 = init_params(jax.random.key(6), spec)
    params_np = jax.tree.map(np.asarray, params0)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=7))
    results = {}
    for spmm in ("hybrid", "ell"):
        cfg = Config(model="graphsage", dropout=0.0, use_pp=True,
                     norm="layer", n_train=g.n_train, lr=0.01,
                     sampling_rate=0.5, spmm=spmm)
        fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
        blk_np = build_block_arrays(art, "graphsage")
        blk_np.update(fns.extra_blk)
        for k in fns.drop_blk_keys:
            blk_np.pop(k, None)
        blk = place_blocks(blk_np, mesh)
        tb = place_replicated(tables, mesh)
        blk["feat"] = fns.precompute(blk, place_replicated(tables_full, mesh))
        p = place_replicated(params_np, mesh)
        s = place_replicated(state0, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        for e in range(3):
            p, s, opt, loss = fns.train_step(p, s, opt, jnp.uint32(e), blk, tb,
                                             jax.random.key(0), jax.random.key(1))
        results[spmm] = (float(loss), jax.tree.map(np.asarray, jax.device_get(p)))
    assert abs(results["hybrid"][0] - results["ell"][0]) < 1e-5
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4,
                                                         atol=1e-5),
                 results["hybrid"][1], results["ell"][1])


@pytest.mark.parametrize("dense_dtype", ["native", "int8"])
def test_pallas_tile_matmul_matches_xla(dense_dtype):
    """The fused Pallas grouped-matmul (interpret mode off-TPU) == the XLA
    dense-tile path; the int8 variant quantizes with one per-call scale so
    it gets the quantization tolerance against the NATIVE reference."""
    from bnsgcn_tpu.ops.block_spmm import _dense_apply
    from bnsgcn_tpu.ops.pallas_block import dense_apply_pallas

    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15, p_out=0.003,
                  seed=67)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, 4)
    assert dense_edge_count(arrays, 0) > 0
    rng = np.random.default_rng(3)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 7)), jnp.float32)
    a = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    ref = _dense_apply(fwd, a["blk_tiles_fwd"], a["blk_rowb_fwd"],
                       a["blk_colb_fwd"], a["blk_perm_ext"],
                       a["blk_perm_inner"], h)
    got = dense_apply_pallas(fwd, a["blk_tiles_fwd"], a["blk_rowb_fwd"],
                             a["blk_colb_fwd"], a["blk_perm_ext"],
                             a["blk_perm_inner"], h,
                             dense_dtype=dense_dtype, interpret=True)
    tol = (dict(rtol=1e-4, atol=1e-4) if dense_dtype == "native"
           else dict(atol=0.05 * float(np.abs(np.asarray(ref)).max())))
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), **tol)
    if dense_dtype == "int8":
        assert not np.allclose(np.asarray(got), np.asarray(ref))  # quantized


def test_cluster_order_is_permutation():
    g = sbm_graph(n_nodes=200, n_class=4, n_feat=4, seed=64)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=5))
    pi, pe = cluster_order(art.src[0], art.dst[0], art.pad_inner, art.n_ext)
    assert sorted(pi.tolist()) == list(range(art.pad_inner))
    assert sorted(pe.tolist()) == list(range(art.n_ext))
    np.testing.assert_array_equal(pe[:art.pad_inner], pi)


def test_int8_dense_path_close_to_native():
    """dense_dtype='int8' (quantized slabs, int8 x int8 MXU tiles) tracks
    the exact path within quantization tolerance, forward and gradient."""
    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15, p_out=0.003,
                  seed=68)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, 4)
    assert dense_edge_count(arrays, 0) > 0
    exact = make_block_spmm(fwd, bwd, ell_pair)
    quant = make_block_spmm(fwd, bwd, ell_pair, dense_dtype="int8")
    rng = np.random.default_rng(5)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 7)), jnp.float32)
    a = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    ref = np.asarray(exact(a, h))
    got = np.asarray(quant(a, h))
    scale = np.abs(ref).max()
    np.testing.assert_allclose(got, ref, atol=0.05 * scale)
    cot = rng.normal(size=ref.shape).astype(np.float32)
    d_ref = np.asarray(jax.grad(
        lambda hh: jnp.sum(exact(a, hh) * cot))(h))
    d_got = np.asarray(jax.grad(
        lambda hh: jnp.sum(quant(a, hh) * cot))(h))
    np.testing.assert_allclose(d_got, d_ref,
                               atol=0.05 * np.abs(d_ref).max())


@pytest.mark.parametrize("chunked", [True, False])
@pytest.mark.parametrize("dense_dtype", ["native", "int8"])
def test_chunked_dense_path_matches_oracle(dense_dtype, chunked, monkeypatch):
    """The lax.scan tile accumulation (keeps HLO temps flat in B — the
    jit(precompute) OOM fix) must stay exact, forward and gradient, both
    multi-chunk (incl. B % C != 0 zero-tile padding) and single-chunk
    (B <= C), on a multi-block geometry where rowb != colb — a wrong
    slab-gather index (colb vs rowb) only shows up off the diagonal."""
    import bnsgcn_tpu.ops.block_spmm as bs
    if chunked:
        monkeypatch.setattr(bs, "_tile_chunk_for", lambda *a, **k: 4)
    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15, p_out=0.003,
                  seed=61)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    fwd, bwd, ell_pair, arrays = _hybrid_for(art, 4, tile=64)
    assert np.any(arrays["blk_rowb_fwd"][0][:fwd.n_blocks]
                  != arrays["blk_colb_fwd"][0][:fwd.n_blocks]), \
        "all tiles on the diagonal — wrong-slab-index bug invisible"
    if chunked:
        assert fwd.n_blocks > 4 and fwd.n_blocks % 4 != 0, \
            "chunking path (incl. padding) not exercised"
    else:
        assert fwd.n_blocks <= bs._tile_chunk_for(
            fwd.n_blocks, fwd.row_tile, 7), "expected single-chunk case"
    spmm = make_block_spmm(fwd, bwd, ell_pair, dense_dtype=dense_dtype)
    rng = np.random.default_rng(9)
    h = jnp.asarray(rng.normal(size=(art.n_ext, 7)), jnp.float32)
    arr0 = {k: jnp.asarray(v[0]) for k, v in arrays.items()}
    ref = _dense_oracle(art, 0, h)
    tol = dict(rtol=1e-4, atol=1e-4) if dense_dtype == "native" else \
        dict(atol=0.05 * np.abs(ref).max())
    np.testing.assert_allclose(np.asarray(spmm(arr0, h)), ref, **tol)
    cot = rng.normal(size=ref.shape).astype(np.float32)
    d_h = np.asarray(jax.grad(lambda hh: jnp.sum(spmm(arr0, hh) * cot))(h))
    d_ref = np.zeros((art.n_ext, 7))
    real = art.dst[0] < art.pad_inner
    np.add.at(d_ref, art.src[0][real], cot[art.dst[0][real]])
    d_tol = tol if dense_dtype == "native" else \
        dict(atol=0.05 * np.abs(d_ref).max())
    np.testing.assert_allclose(d_h, d_ref, **d_tol)


def test_estimate_coverage_matches_build():
    """The --spmm auto estimator equals the dense-edge fraction the real
    layout build produces (same _select_dense rule, no materialization)."""
    from bnsgcn_tpu.ops.block_spmm import estimate_coverage
    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15,
                  p_out=0.003, seed=61)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    for occ in (4, 64, 10**9):
        fwd, bwd, ell_pair, arrays = _hybrid_for(art, occ)
        for p in range(art.n_parts):
            pi, pe = cluster_order(art.src[p], art.dst[p], art.pad_inner,
                                   art.n_ext, target=64)
            real = art.dst[p] < art.pad_inner
            d, s = art.dst[p][real], art.src[p][real]
            est = estimate_coverage(pi, pe, art.pad_inner, art.n_ext, d, s,
                                    occupancy_min=occ)
            frac = dense_edge_count(arrays, p) / max(len(d), 1)
            assert abs(est - frac) < 1e-9, (occ, p, est, frac)


def test_spmm_auto_resolution():
    """cfg.spmm='auto' picks hybrid on a clustered graph at low occupancy
    and ell when no tile can reach occupancy; both train."""
    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.models.gnn import ModelSpec, init_params
    from bnsgcn_tpu.parallel.mesh import make_parts_mesh
    from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                    init_training, place_blocks,
                                    place_replicated)
    g = sbm_graph(n_nodes=300, n_class=5, n_feat=6, p_in=0.15,
                  p_out=0.003, seed=61)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=3))
    mesh = make_parts_mesh(2)
    for occ, expect_dense in ((4, True), (10**9, False)):
        cfg = Config(model="graphsage", n_layers=2, n_hidden=8, spmm="auto",
                     block_occupancy=occ, sampling_rate=1.0,
                     n_feat=art.n_feat, n_class=art.n_class,
                     n_train=art.n_train)
        spec = ModelSpec("graphsage", (art.n_feat, 8, art.n_class),
                         train_size=art.n_train)
        fns, hspec, tables, _ = build_step_fns(cfg, spec, art, mesh)
        has_tiles = any("tiles" in k for k in fns.extra_blk)
        assert has_tiles == expect_dense, (occ, sorted(fns.extra_blk))
        blk_np = build_block_arrays(art, spec.model)
        blk_np.update(fns.extra_blk)
        for k in fns.drop_blk_keys:
            blk_np.pop(k, None)
        blk = place_blocks(blk_np, mesh)
        params, state, opt = init_training(cfg, spec, mesh)
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(0), blk,
            place_replicated(tables, mesh),
            jax.random.key(0), jax.random.key(1))
        assert np.isfinite(float(loss))


def test_max_row_dense_repair_matches_build():
    """Layouts cached before BlockSpec.max_row_dense existed deserialize
    with 0 (= unknown), which would skip the int8 Pallas overflow guard;
    repair_max_row_dense must recompute the exact build-time values from
    the cached tile stacks (round-4 advisor / round-5 review finding)."""
    import dataclasses
    from bnsgcn_tpu.ops.block_spmm import repair_max_row_dense
    g = synthetic_graph(n_nodes=120, avg_degree=8, n_feat=4, seed=9)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=1))
    fwd, bwd, _, arrays = _hybrid_for(art, occupancy_min=4, tile=32)
    assert fwd.max_row_dense > 0       # build computed real values
    stale_f = dataclasses.replace(fwd, max_row_dense=0)
    stale_b = dataclasses.replace(bwd, max_row_dense=0)
    rf, rb = repair_max_row_dense(stale_f, stale_b, arrays)
    assert rf.max_row_dense == fwd.max_row_dense
    assert rb.max_row_dense == bwd.max_row_dense
    # already-filled specs pass through untouched
    pf, pb = repair_max_row_dense(fwd, bwd, arrays)
    assert pf is fwd and pb is bwd


def test_dense_edge_count_split_and_missing_keys():
    """dense_edge_count across all three layout shapes (bench preflight
    regression: the hybrid+rag+ovl candidate KeyError'd on the split
    layout's int_/fro_-prefixed tile stacks and fell back to ell, so +ovl
    never got measured).

    * unified layout: bare blk_tiles_fwd
    * split-overlap layout: int_blk_tiles_fwd + fro_blk_tiles_fwd
    * fully-ELL layout (occupancy filter kept nothing): no tiles keys -> 0
    """
    from bnsgcn_tpu.ops.block_spmm import build_split_block_layouts

    g = sbm_graph(n_nodes=240, n_class=4, n_feat=5, p_in=0.2, p_out=0.01,
                  seed=17)
    art = build_artifacts(g, partition_graph(g, 2, method="random", seed=2))
    # unified layout counts == per-part tile sums (sanity baseline)
    _, _, _, uni = _hybrid_for(art, occupancy_min=4, tile=32)
    assert "blk_tiles_fwd" in uni
    for p in range(art.n_parts):
        assert dense_edge_count(uni, p) == int(
            uni["blk_tiles_fwd"][p].astype(np.int64).sum())

    # split layout: keys are int_/fro_-prefixed; the old implementation
    # raised KeyError here
    perms_i = np.stack([cluster_order(art.src[p], art.dst[p], art.pad_inner,
                                      art.n_ext, target=32)[0]
                        for p in range(art.n_parts)])
    perms_e = np.stack([cluster_order(art.src[p], art.dst[p], art.pad_inner,
                                      art.n_ext, target=32)[1]
                        for p in range(art.n_parts)])
    _, _, split_arrays, _, _ = build_split_block_layouts(
        art.src, art.dst, art.pad_inner, art.n_ext, perms_i, perms_e,
        occupancy_min=4, tile_r=32, tile_c=32)
    assert "blk_tiles_fwd" not in split_arrays
    for p in range(art.n_parts):
        want = sum(int(split_arrays[k][p].astype(np.int64).sum())
                   for k in ("int_blk_tiles_fwd", "fro_blk_tiles_fwd")
                   if k in split_arrays)
        got = dense_edge_count(split_arrays, p)
        assert got == want and got >= 0

    # impossible occupancy keeps only a placeholder tile carrying 0 edges
    _, _, _, empty = _hybrid_for(art, occupancy_min=10**9, tile=32)
    assert dense_edge_count(empty) == 0
    # arrays with no tiles keys at all (the auto path drops empty stacks
    # from extra_blk, test_spmm_auto_resolution) -> 0, not KeyError
    assert dense_edge_count({"merge_perm": np.arange(4)}) == 0
