"""Distributed serving e2e: a real router subprocess fronting two real
backend subprocesses (serve-router / serve-backend CLI entry points, real
TCP), proving the routed fleet serves BITWISE what the single-host server
serves — tier A, tier B with cross-part closures, and post-delta refresh —
then shuts the whole fleet down cleanly through one client op."""

import json
import os
import socket
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import serve
from bnsgcn_tpu.config import Config
from bnsgcn_tpu.models.gnn import init_params, spec_from_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    return env


def _setup_fleet_dirs(tmp_path):
    """One checkpoint + partition artifacts (random 2-way owner map over
    the deterministic sbm graph) + the flag set every process launches
    with. Returns (args, g, cfg2, params, state, owner)."""
    cfg = Config(dataset="sbm", model="graphsage", n_layers=2, n_hidden=8,
                 use_pp=True, seed=3, sampling_rate=1.0,
                 ckpt_path=str(tmp_path / "ckpt"),
                 part_path=str(tmp_path / "parts"))
    cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    from bnsgcn_tpu.data.datasets import load_data
    g, _, _ = load_data(cfg)
    cfg2 = cfg.replace(n_feat=g.n_feat, n_class=g.n_class, n_train=g.n_train)
    params, state = init_params(jax.random.key(3), spec_from_config(cfg2))
    ckpt.save_checkpoint(ckpt.final_path(cfg2), params=params,
                         bn_state=state, epoch=7, best_acc=0.5, seed=3)
    # the serving shard map, in the training artifacts' own format
    rng = np.random.default_rng(11)
    owner = rng.integers(0, 2, size=g.n_nodes).astype(np.int32)
    owner[:2] = [0, 1]
    part_dir = os.path.join(cfg.part_path, cfg.graph_name)
    os.makedirs(part_dir, exist_ok=True)
    gnids = [np.flatnonzero(owner == p).astype(np.int64) for p in (0, 1)]
    with open(os.path.join(part_dir, "meta.json"), "w") as f:
        json.dump({"n_parts": 2, "n_inner": [len(x) for x in gnids]}, f)
    for p, ids in enumerate(gnids):
        np.savez(os.path.join(part_dir, f"part{p}.npz"), global_nid=ids)
    args = ["--dataset", "sbm", "--model", "graphsage", "--n-layers", "2",
            "--n-hidden", "8", "--use-pp", "--fix-seed", "--seed", "3",
            "--ckpt-path", str(tmp_path / "ckpt"),
            "--part-path", str(tmp_path / "parts")]
    return args, g, cfg2, params, state, owner


def _spawn(subcmd, args, extra):
    cmd = [sys.executable, "-m", "bnsgcn_tpu.main", subcmd] + args + extra
    return subprocess.Popen(cmd, env=_env(), cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)


def _dump(procs):
    out = []
    for name, p in procs:
        p.kill()
        try:
            out.append(f"--- {name} ---\n{p.stdout.read()[-3000:]}")
        except Exception:
            pass
    return "\n".join(out)


@pytest.mark.quickgate
def test_e2e_two_backend_fleet_bitwise_and_clean_shutdown(tmp_path):
    args, g, cfg2, params, state, owner = _setup_fleet_dirs(tmp_path)
    rport = _free_port()
    router = _spawn("serve-router", args, ["--serve-port", str(rport)])
    procs = [("router", router)]
    backends = []
    try:
        for part in (0, 1):
            # --serve-refresh-s 0: the background refresher otherwise races
            # the post-delta tier-B assertions (it can clean a dirty node in
            # the ~1s the in-process ref spends compiling between the two
            # predicts); this test drains via the explicit `flush` op instead
            b = _spawn("serve-backend", args,
                       ["--serve-part", str(part),
                        "--serve-router", f"127.0.0.1:{rport}",
                        "--serve-refresh-s", "0",
                        "--serve-dir", str(tmp_path / f"sdir{part}")])
            backends.append(b)
            procs.append((f"backend{part}", b))
        # fleet complete = router answers `fleet` with no missing parts
        deadline = time.monotonic() + 300
        while True:
            for name, p in procs:
                if p.poll() is not None:
                    raise AssertionError(f"{name} died rc={p.returncode}:\n"
                                         f"{_dump(procs)}")
            try:
                r = serve.request(rport, {"op": "fleet"}, timeout_s=2.0)
                if r.get("ok") and not r.get("missing_parts"):
                    break
            except Exception:
                pass
            if time.monotonic() > deadline:
                raise AssertionError(f"fleet never ready:\n{_dump(procs)}")
            time.sleep(0.5)

        # the single-host reference, in-process from the same checkpoint
        ref = serve.build_core(cfg2, g, params, state, log=lambda *a: None)
        try:
            probe = [0, 1, 17, 123, g.n_nodes - 1]
            for v in probe:
                r = serve.request(rport, {"op": "predict", "node": v})
                local = ref.predict(v)
                assert r["ok"] and r["tier"] == "A"
                assert r["scores"] == local["scores"], f"node {v}"
                assert r["part"] == owner[v]
            r = serve.request(rport, {"op": "predict_many", "nodes": probe})
            assert [x["scores"] for x in r["results"]] == \
                   [ref.predict(v)["scores"] for v in probe]

            # cross-part delta: apply fans to both owners, the mark BFS
            # crosses the cut, tier-B closures pull remote halo rows
            u = int(np.flatnonzero(owner == 0)[4])
            w = int(np.flatnonzero(owner == 1)[4])
            r = serve.request(rport, {"op": "add_edges",
                                      "edges": [[u, w], [w, u]]},
                              timeout_s=120.0)
            ref_r = ref.add_edges([[u, w], [w, u]])
            assert r["ok"] and r["dirty_total"] == ref_r["dirty_total"]
            for v in (u, w):
                r = serve.request(rport, {"op": "predict", "node": v},
                                  timeout_s=120.0)
                local = ref.predict(v)
                assert r["tier"] == local["tier"] == "B", f"node {v}"
                assert r["scores"] == local["scores"], f"node {v}"

            # post-delta refresh: drain the dirty frontier everywhere, then
            # tier A is bitwise again
            r = serve.request(rport, {"op": "flush"}, timeout_s=300.0)
            ref.flush()
            assert r["ok"]
            assert serve.request(rport, {"op": "dirty"})["count"] == 0
            for v in (u, w):
                r = serve.request(rport, {"op": "predict", "node": v})
                local = ref.predict(v)
                assert r["tier"] == local["tier"] == "A", f"node {v}"
                assert r["scores"] == local["scores"], f"node {v}"

            stats = serve.request(rport, {"op": "stats"})
            assert stats["router"] and len(stats["backends"]) == 2
            assert stats["deltas"] == 1 and stats["evictions"] == 0
        finally:
            ref.close()

        # one client op shuts the whole fleet down: router forwards the
        # shutdown, every backend drains + flushes its delta-log shard and
        # exits 0, then the router exits 0
        serve.request(rport, {"op": "shutdown"})
        assert router.wait(timeout=120) == 0, _dump(procs)
        for part, b in enumerate(backends):
            assert b.wait(timeout=120) == 0, _dump(procs)
            log = os.path.join(str(tmp_path / f"sdir{part}"),
                               f"delta_log.p{part}.r0.jsonl")
            assert os.path.exists(log)      # the journaled delta survived
            with open(log) as f:
                assert any(json.loads(ln)["op"] == "apply_delta"
                           for ln in f if ln.strip())
    finally:
        for _, p in procs:
            if p.poll() is None:
                p.kill()
