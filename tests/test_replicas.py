"""Replica-axis hybrid parallelism exactness (parallel/replicas.py).

The acceptance matrix for the 2-D ('replicas', 'parts') mesh:

  (a) --replicas 1 is BIT-identical (fwd + bwd) to the historical 1-D
      ('parts',) path across the full halo-strategy x wire-codec matrix;
  (b) --replicas 2 on a 4 parts x 2 replicas CPU mesh produces exactly the
      mean of the two corresponding single-replica runs (sample and dropout
      keys folded with the replica index — pair_key's fold-first contract),
      at rate 1.0 and 0.5;
  (c) checkpoints round-trip replica-invariantly (params are replicated over
      both axes, so a 2-D run's checkpoint restores into a 1-D run bitwise
      and vice versa);

plus the pair_key distinctness/overflow guard (sampling satellite).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.parallel.replicas import (dedup_replica0, make_mesh,
                                          mesh_desc, n_replicas,
                                          replica_axis)
from bnsgcn_tpu.parallel.sampling import pair_key
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)


def _setup(g, n_parts, cfg, spec, mesh, art=None):
    if art is None:
        pid = partition_graph(g, n_parts, method="random", seed=3)
        art = build_artifacts(g, pid)
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, spec.model)
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tables = place_replicated(tables, mesh)
    tables_full = place_replicated(tables_full, mesh)
    if spec.use_pp:
        out = fns.precompute(blk, tables_full)
        if spec.model == "gat":
            blk["feat0_ext"] = out
        else:
            blk["feat"] = out
    return art, fns, blk, tables


def _np_tree(t):
    return jax.tree.map(lambda x: np.asarray(jax.device_get(x)), t)


# ----------------------------------------------------------------------------
# mesh construction
# ----------------------------------------------------------------------------

def test_make_mesh_replicas1_is_the_parts_mesh():
    """R=1 must not even construct a second axis: same Mesh as the
    historical path, so every compiled program is shared verbatim."""
    m1 = make_mesh(4, 1)
    m0 = make_parts_mesh(4)
    assert m1.axis_names == m0.axis_names == ("parts",)
    assert list(m1.devices.flat) == list(m0.devices.flat)
    assert n_replicas(m1) == 1 and replica_axis(m1) is None
    assert mesh_desc(m1) == "4 parts"


def test_make_mesh_2d_layout():
    m = make_mesh(4, 2)
    assert m.axis_names == ("replicas", "parts")   # replicas OUTER (DCN)
    assert m.devices.shape == (2, 4)
    assert n_replicas(m) == 2 and replica_axis(m) == "replicas"
    assert mesh_desc(m) == "2x4 replicas x parts"
    devs = jax.devices()
    # row r holds devices [r*P, (r+1)*P): consecutive ids share a replica
    assert list(m.devices[0]) == devs[:4]
    assert list(m.devices[1]) == devs[4:8]
    with pytest.raises(ValueError, match="need >= 16 devices"):
        make_mesh(8, 2)


def test_dedup_replica0_slices_leading_parts():
    m2 = make_mesh(2, 2)
    out = jnp.arange(4 * 3).reshape(4, 3)
    np.testing.assert_array_equal(dedup_replica0(out, m2, 2), out[:2])
    m1 = make_mesh(2, 1)
    np.testing.assert_array_equal(dedup_replica0(out, m1, 2), out)


# ----------------------------------------------------------------------------
# (a) --replicas 1 bit-identity across strategy x wire
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
@pytest.mark.parametrize("wire", ["native", "bf16", "fp8", "int8"])
def test_replicas1_bit_identical_to_1d(strategy, wire):
    """fwd+bwd (loss_and_grad) through cfg.replicas=1 + make_mesh equals the
    pre-replica construction BITWISE for every halo strategy x wire codec."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=32)
    cfg = Config(model="graphsage", dropout=0.5, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5,
                 halo_exchange=strategy, halo_wire=wire, replicas=1)
    spec = ModelSpec("graphsage", (5, 8, 3), norm="layer", dropout=0.5,
                     use_pp=True, train_size=g.n_train)
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    ep = jnp.uint32(1)

    pid = partition_graph(g, 4, method="random", seed=3)
    art = build_artifacts(g, pid)
    outs = {}
    for tag, mesh in (("new", make_mesh(4, cfg.replicas)),
                      ("old", make_parts_mesh(4))):
        _, fns, blk, tb = _setup(g, 4, cfg, spec, mesh, art=art)
        assert fns.n_replicas == 1
        p = place_replicated(params_np, mesh)
        s = place_replicated(state, mesh)
        loss, grads = fns.loss_and_grad(p, s, ep, blk, tb, skey, dkey)
        outs[tag] = (np.asarray(loss), _np_tree(grads))

    assert np.array_equal(outs["new"][0], outs["old"][0])   # bitwise
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 outs["new"][1], outs["old"][1])


# ----------------------------------------------------------------------------
# (b) --replicas 2 == mean of the two folded-seed single-replica runs
# ----------------------------------------------------------------------------

@pytest.mark.parametrize("model,rate", [("graphsage", 1.0),
                                        ("graphsage", 0.5),
                                        # GAT: presence-masked edge softmax
                                        # under per-replica sampled halos
                                        ("gat", 0.5)])
def test_replicas2_grad_is_mean_of_folded_single_runs(model, rate):
    """4 parts x 2 replicas: the fused psum's gradient equals the mean of
    two 1-D runs whose sample/dropout keys carry the replica fold — the
    acceptance pin that the replica axis is exactly variance reduction,
    never a change of estimator."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=32)
    cfg = Config(model=model, dropout=0.5, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=rate,
                 heads=2 if model == "gat" else 1)
    spec = ModelSpec(model, (5, 8, 3), norm="layer", dropout=0.5,
                     use_pp=True, train_size=g.n_train,
                     heads=2 if model == "gat" else 1)
    params, state = init_params(jax.random.key(9), spec)
    params_np = _np_tree(params)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    ep = jnp.uint32(0)
    pid = partition_graph(g, 4, method="random", seed=3)
    art = build_artifacts(g, pid)

    mesh2 = make_mesh(4, 2)
    _, fns2, blk2, tb2 = _setup(g, 4, cfg.replace(replicas=2), spec, mesh2,
                                art=art)
    assert fns2.n_replicas == 2 and fns2.loss_and_grad is not None
    p2 = place_replicated(params_np, mesh2)
    s2 = place_replicated(state, mesh2)
    l2, g2 = fns2.loss_and_grad(p2, s2, ep, blk2, tb2, skey, dkey)
    l2, g2 = float(l2), _np_tree(g2)

    mesh1 = make_parts_mesh(4)
    _, fns1, blk1, tb1 = _setup(g, 4, cfg, spec, mesh1, art=art)
    p1 = place_replicated(params_np, mesh1)
    s1 = place_replicated(state, mesh1)
    singles = []
    for r in range(2):
        lr_, gr_ = fns1.loss_and_grad(
            p1, s1, ep, blk1, tb1,
            jax.random.fold_in(skey, r), jax.random.fold_in(dkey, r))
        singles.append((float(lr_), _np_tree(gr_)))
    if rate < 1.0:
        # the replicas really drew DIFFERENT samples (else the mean test
        # would pass vacuously on identical draws)
        assert abs(singles[0][0] - singles[1][0]) > 1e-9

    np.testing.assert_allclose(l2, (singles[0][0] + singles[1][0]) / 2,
                               rtol=1e-5, atol=1e-7)
    gm = jax.tree.map(lambda a, b: (a + b) / 2, singles[0][1], singles[1][1])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        a, b, rtol=1e-4, atol=1e-6), g2, gm)


def test_replicas2_syncbn_trains():
    """SyncBN under the replica axis: moments mean over BOTH axes (one fused
    psum, whole_size x n_replicas) — pin that the estimator stays sane by
    training to a decreasing loss and bit-consistent state across devices."""
    g = sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.08, p_out=0.004,
                  seed=35)
    cfg = Config(model="graphsage", dropout=0.1, use_pp=True, norm="batch",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5, replicas=2)
    spec = ModelSpec("graphsage", (8, 16, 4), norm="batch", dropout=0.1,
                     use_pp=True, train_size=g.n_train)
    mesh = make_mesh(4, 2)
    _, fns, blk, tb = _setup(g, 4, cfg, spec, mesh)
    params, state = init_params(jax.random.key(11), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    key, dkey = jax.random.key(0), jax.random.key(1)
    first = None
    for e in range(25):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb, key, dkey)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.8, (first, float(loss))
    # BN running stats came out of a both-axes psum: finite and replicated
    st = _np_tree(jax.device_get(state))
    for leaf in jax.tree.leaves(st):
        assert np.all(np.isfinite(leaf))


@pytest.mark.quickgate
def test_run_training_replicas2_e2e(tmp_path):
    """Full run_training pass on the 2-D mesh: partitioning, precompute,
    train loop, mesh-distributed eval (de-duplicated to replica 0),
    checkpointing — the whole stack under --replicas 2."""
    from bnsgcn_tpu.run import run_training
    cfg = Config(dataset="sbm", n_partitions=4, replicas=2,
                 model="graphsage", n_layers=2, n_hidden=16, n_epochs=12,
                 log_every=5, sampling_rate=0.5, use_pp=True,
                 eval_device="mesh",
                 part_path=str(tmp_path / "parts"),
                 ckpt_path=str(tmp_path / "ckpt"),
                 results_path=str(tmp_path / "res"))
    res = run_training(cfg, verbose=False)
    assert np.isfinite(res.final_loss)
    assert res.losses[-1] < res.losses[0]
    assert res.best_val_acc > 0.5, res.best_val_acc


# ----------------------------------------------------------------------------
# (c) checkpoint invariance
# ----------------------------------------------------------------------------

def test_checkpoint_roundtrip_replica_invariant(tmp_path):
    """Params/opt/BN state are replicated over BOTH mesh axes, so a 2-D
    run's checkpoint is byte-for-byte a 1-D run's checkpoint: save from
    replicas=2, restore into replicas=1 (and back) bitwise."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=32)
    spec = ModelSpec("graphsage", (5, 8, 3), norm="layer", dropout=0.2,
                     use_pp=True, train_size=g.n_train)
    skey, dkey = jax.random.key(0), jax.random.key(1)
    pid = partition_graph(g, 4, method="random", seed=3)
    art = build_artifacts(g, pid)

    def train2(mesh, cfg):
        _, fns, blk, tb = _setup(g, 4, cfg, spec, mesh, art=art)
        params, state = init_params(jax.random.key(9), spec)
        params = place_replicated(params, mesh)
        state = place_replicated(state, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        for e in range(2):
            params, state, opt, _ = fns.train_step(
                params, state, opt, jnp.uint32(e), blk, tb, skey, dkey)
        return params, state, opt

    base = Config(model="graphsage", dropout=0.2, use_pp=True, norm="layer",
                  n_train=g.n_train, lr=0.01, sampling_rate=1.0)
    # rate 1.0: both mesh shapes draw the identical (exact) plan, so even
    # the trained states agree and the checkpoint comparison is exact
    p2, s2, o2 = train2(make_mesh(4, 2), base.replace(replicas=2))
    p1, s1, o1 = train2(make_parts_mesh(4), base)

    path2 = str(tmp_path / "rep2.ckpt")
    ckpt.save_checkpoint(path2, params=p2, opt_state=o2, bn_state=s2,
                         epoch=1, best_acc=0.5, seed=7)
    payload = ckpt.load_checkpoint(path2)
    # restore into templates living on the OTHER mesh's host copies
    rp, ro, rs = ckpt.restore_into(payload, _np_tree(p1), _np_tree(o1),
                                   _np_tree(s1))
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 _np_tree(p2), rp)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 _np_tree(o2), ro)
    # and the restored host tree re-places cleanly onto a replica mesh
    mesh2 = make_mesh(4, 2)
    back = place_replicated(rp, mesh2)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
                 _np_tree(p2), _np_tree(back))


# ----------------------------------------------------------------------------
# pair_key: replica folding, distinctness grid, overflow guard (satellite)
# ----------------------------------------------------------------------------

def test_pair_key_replica_fold_first_contract():
    """pair_key(base, e, p, j, replica=r) == pair_key(fold_in(base, r),
    e, p, j): the contract that lets single-replica runs reproduce any
    replica of a 2-D run by pre-folding the base key."""
    base = jax.random.key(42)
    e = jnp.uint32(3)
    a = pair_key(base, e, 1, 2, replica=1)
    b = pair_key(jax.random.fold_in(base, 1), e, 1, 2)
    np.testing.assert_array_equal(jax.random.key_data(a),
                                  jax.random.key_data(b))
    # replica=None is a no-fold, NOT replica 0: the 1-D path keeps its
    # historical key stream bit-identical
    none_k = jax.random.key_data(pair_key(base, e, 1, 2))
    zero_k = jax.random.key_data(pair_key(base, e, 1, 2, replica=0))
    assert not np.array_equal(none_k, zero_k)


def test_pair_key_distinct_on_exhaustive_grid():
    """Distinct (replica, epoch, p, j) tuples never collide, exhaustively on
    a small grid INCLUDING colliding scalar values (epoch==p==j etc.) — the
    satellite pin that replica folding cannot alias any pre-existing pair
    stream."""
    base = jax.random.key(0)
    seen = {}
    for rep in [None, 0, 1, 2]:
        for e in range(3):
            for p in range(4):
                for j in range(4):
                    k = tuple(np.asarray(jax.random.key_data(
                        pair_key(base, jnp.uint32(e), p, j, replica=rep)
                    )).ravel().tolist())
                    assert k not in seen, (
                        f"key collision: {(rep, e, p, j)} vs {seen[k]}")
                    seen[k] = (rep, e, p, j)
    assert len(seen) == 4 * 3 * 4 * 4


def test_pair_key_fold_guard_rejects_out_of_range():
    base = jax.random.key(0)
    e = jnp.uint32(0)
    with pytest.raises(ValueError, match="replica=-1 outside"):
        pair_key(base, e, 0, 1, replica=-1)
    with pytest.raises(ValueError, match="epoch"):
        pair_key(base, 2 ** 32, 0, 1)
    with pytest.raises(ValueError, match="p="):
        pair_key(base, e, -3, 1)
    with pytest.raises(ValueError, match="j="):
        pair_key(base, e, 0, 2 ** 40)
    # boundary values are legal
    pair_key(base, e, 0, 2 ** 32 - 1, replica=0)
