"""Config-matrix smoke: one train step compiles and yields a finite loss for
every supported flag combination (models x pp x norm x spmm x dtype x remat
x n_linear x edge_chunk). Locks rarely-hit paths against regressions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)

CASES = [
    # (model, use_pp, norm, spmm, dtype, remat, n_linear, edge_chunk)
    ("gcn",       False, "layer", "ell",     "float32",  False, 0, 0),
    ("gcn",       True,  None,    "segment", "float32",  False, 0, 64),
    ("gcn",       True,  "batch", "ell",     "bfloat16", True,  0, 0),
    ("graphsage", False, "batch", "segment", "float32",  False, 0, 0),
    ("graphsage", True,  "layer", "ell",     "bfloat16", False, 1, 0),
    ("graphsage", False, "layer", "ell",     "float32",  True,  0, 0),
    ("graphsage", True,  None,    "segment", "float32",  False, 2, 128),
    ("gat",       True,  "layer", "ell",     "float32",  False, 0, 0),
    ("gat",       True,  "batch", "segment", "float32",  True,  1, 0),
    ("gat",       True,  "layer", "ell",     "bfloat16", False, 0, 0),
]


@pytest.fixture(scope="module")
def graph():
    return synthetic_graph(n_nodes=64, avg_degree=5, n_feat=6, n_class=3,
                           seed=99)


@pytest.mark.parametrize("model,use_pp,norm,spmm,dtype,remat,n_linear,edge_chunk",
                         CASES)
@pytest.mark.quickgate
def test_one_step_finite(graph, model, use_pp, norm, spmm, dtype, remat,
                         n_linear, edge_chunk):
    g = graph
    n_layers = 3
    cfg = Config(model=model, dropout=0.2, use_pp=use_pp, norm=norm, spmm=spmm,
                 dtype=dtype, remat=remat, n_linear=n_linear,
                 edge_chunk=edge_chunk, n_train=g.n_train, lr=0.01,
                 sampling_rate=0.5, heads=2)
    sizes = (6,) + (8,) * (n_layers - 1) + (3,)
    spec = ModelSpec(model, sizes, n_linear=n_linear, norm=norm, dropout=0.2,
                     use_pp=(True if model == "gat" else use_pp), heads=2,
                     train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=7),
                          edge_mult=max(edge_chunk, 8))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, model)
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    if dtype == "bfloat16":
        blk["feat"] = blk["feat"].astype(jdtype)
    tb = place_replicated(tables, mesh)
    if spec.use_pp:
        out = fns.precompute(blk, place_replicated(tables_full, mesh)).astype(
            jdtype if dtype == "bfloat16" else out_dtype_default(blk))
        if model == "gat":
            blk["feat0_ext"] = out
        else:
            blk["feat"] = out
    params, state = init_params(jax.random.key(0), spec, dtype=jdtype)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh, dtype=jdtype)
    params, state, opt, loss = fns.train_step(
        params, state, opt, jnp.uint32(0), blk, tb,
        jax.random.key(0), jax.random.key(1))
    assert np.isfinite(float(loss)), (model, use_pp, norm, spmm, dtype)


def out_dtype_default(blk):
    return blk["feat"].dtype


HALO_CASES = [
    # (model, spmm, halo_exchange, halo_wire, dtype)
    ("graphsage", "hybrid", "padded", "native", "float32"),
    ("gcn",       "hybrid", "shift",  "fp8",    "bfloat16"),
    ("graphsage", "ell",    "shift",  "bf16",   "float32"),
    ("gat",       "ell",    "shift",  "fp8",    "float32"),
    ("graphsage", "hybrid", "shift",  "fp8",    "bfloat16"),
    # exact-bytes ragged exchange x models x wires, and the auto selector
    # resolving inside build_step_fns
    ("graphsage", "ell",    "ragged", "int8",   "float32"),
    ("gcn",       "hybrid", "ragged", "bf16",   "bfloat16"),
    ("gat",       "ell",    "ragged", "fp8",    "float32"),
    ("graphsage", "hybrid", "auto",   "native", "float32"),
]


@pytest.mark.parametrize("model,spmm,halo_exchange,halo_wire,dtype", HALO_CASES)
def test_one_step_finite_halo_variants(graph, model, spmm, halo_exchange,
                                       halo_wire, dtype):
    """New round-2 flags: hybrid SpMM x shift exchange x fp8/bf16 wire."""
    g = graph
    cfg = Config(model=model, dropout=0.2, use_pp=True, norm="layer",
                 spmm=spmm, dtype=dtype, halo_exchange=halo_exchange,
                 halo_wire=halo_wire, n_train=g.n_train, lr=0.01,
                 sampling_rate=0.5, heads=2)
    sizes = (6, 8, 8, 3)
    spec = ModelSpec(model, sizes, norm="layer", dropout=0.2, use_pp=True,
                     heads=2, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=7))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, model)
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    if dtype == "bfloat16":
        blk["feat"] = blk["feat"].astype(jdtype)
    tb = place_replicated(tables, mesh)
    out = fns.precompute(blk, place_replicated(tables_full, mesh)).astype(jdtype)
    if model == "gat":
        blk["feat0_ext"] = out
    else:
        blk["feat"] = out
    params, state = init_params(jax.random.key(0), spec, dtype=jdtype)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh, dtype=jdtype)
    for e in range(2):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
    assert np.isfinite(float(loss)), (model, spmm, halo_exchange, halo_wire)


def test_one_step_finite_all_int8_recipe(graph):
    """The all-int8 TPU recipe: hybrid SpMM with int8 residual gathers +
    int8 MXU dense tiles + int8 halo wire + shift exchange, bf16 compute —
    the preferred narrow-format stack on v5e (e4m3 decode is emulated and
    measured slower; see BENCH_NOTES.md)."""
    g = graph
    cfg = Config(model="graphsage", dropout=0.2, use_pp=True, norm="layer",
                 spmm="hybrid", dtype="bfloat16", halo_exchange="shift",
                 halo_wire="int8", spmm_gather="int8", spmm_dense="int8",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5)
    sizes = (6, 8, 8, 3)
    spec = ModelSpec("graphsage", sizes, norm="layer", dropout=0.2,
                     use_pp=True, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art = build_artifacts(g, partition_graph(g, 4, method="random", seed=7))
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    blk["feat"] = blk["feat"].astype(jnp.bfloat16)
    tb = place_replicated(tables, mesh)
    blk["feat"] = fns.precompute(
        blk, place_replicated(tables_full, mesh)).astype(jnp.bfloat16)
    params, state = init_params(jax.random.key(0), spec, dtype=jnp.bfloat16)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh, dtype=jnp.bfloat16)
    for e in range(2):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
    assert np.isfinite(float(loss))
