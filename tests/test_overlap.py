"""--overlap split exactness: interior/frontier split aggregation.

The tentpole invariant: splitting each layer's aggregation into an interior
part (rows with no halo in-neighbor — aggregated while the collective is in
flight) and a frontier part (rows needing the exchange), then recombining
through the merge permutation, is numerically identical (allclose, forward
AND backward) to the fused exchange-then-aggregate path for EVERY halo
strategy x wire codec combination, at rate 1.0 and a sampled rate, on the
8-device CPU mesh. Both paths send the exact same wire payloads (halo_apply
IS halo_start + halo_finish), so even quantized wires must agree to float
reassociation tolerance.

Also pinned: degenerate partitions (a part with zero interior rows, a part
with zero frontier rows, and the P=1 no-cross-edges case) build and train
identically to --overlap off.

Reference context: DistGNN (arXiv:2104.06700) overlaps remote-aggregate
communication with local aggregation; the reference BNS-GCN serializes
exchange-then-aggregate (train.py:256-281 after the buffer update).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import Graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.ops.ell import build_layouts, build_split_layouts, make_ell_spmm
from bnsgcn_tpu.ops.spmm import frontier_mask
from bnsgcn_tpu.parallel.halo import (halo_apply, halo_finish, halo_start,
                                      make_halo_plan, make_halo_spec)
from bnsgcn_tpu.parallel.mesh import make_parts_mesh, shard_map
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                init_training, place_blocks, place_replicated)


# ----------------------------------------------------------------------------
# seam-level matrix: halo_start/finish + split ELL layouts vs halo_apply +
# fused ELL layout, forward and grad, for every strategy x wire x rate
# ----------------------------------------------------------------------------

@pytest.fixture(scope="module")
def split8():
    """8-part skewed partition + fused and split ELL SpMMs over the same
    edges, shared across the matrix cases."""
    g = synthetic_graph(n_nodes=240, avg_degree=7, n_feat=6, seed=46,
                        power_law=True)
    sizes = [90, 50, 30, 20, 16, 14, 12, 8]
    pid = np.repeat(np.arange(8), sizes).astype(np.int32)
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(8)
    fwd, bwd, f_arrays = build_layouts(art.src, art.dst, art.pad_inner,
                                       art.n_ext)
    fused = make_ell_spmm(fwd, bwd, len(fwd.widths), len(bwd.widths))
    (i_f, i_b), (r_f, r_b), s_arrays, _, _ = build_split_layouts(
        art.src, art.dst, art.pad_inner, art.n_ext)
    int_spmm = make_ell_spmm(i_f, i_b, len(i_f.widths), len(i_b.widths))
    fro_spmm = make_ell_spmm(r_f, r_b, len(r_f.widths), len(r_b.widths))
    blk_np = {"feat": art.feat.astype(np.float32), "bnd": art.bnd}
    f_keys = tuple(f_arrays)
    s_keys = tuple(s_arrays)
    blk_np.update(f_arrays)
    blk_np.update(s_arrays)
    blk = place_blocks(blk_np, mesh)
    return art, mesh, blk, fused, (int_spmm, fro_spmm), f_keys, s_keys


@pytest.mark.parametrize("rate", [1.0, 0.5])
@pytest.mark.parametrize("wire", ["native", "bf16", "int8", "fp8"])
@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
def test_split_matches_fused_matrix(split8, strategy, wire, rate):
    art, mesh, blk, fused, (int_spmm, fro_spmm), f_keys, s_keys = split8
    hspec, tables = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary,
                                   rate, strategy=strategy, wire=wire)
    base = jax.random.key(42)

    def local(blk, tables):
        b = {k: v[0] for k, v in blk.items()}
        plan = make_halo_plan(hspec, tables, b["bnd"], jnp.uint32(3), base)
        a_fused = {k: b[k] for k in f_keys}
        a_int = {k[4:]: b[k] for k in s_keys if k.startswith("int_")}
        a_fro = {k[4:]: b[k] for k in s_keys if k.startswith("fro_")}

        def loss_fused(h):
            out = fused(a_fused, halo_apply(hspec, plan, h))
            return jnp.sum(out.astype(jnp.float32) ** 2), out

        def loss_split(h):
            recv = halo_start(hspec, plan, h)
            o_i = int_spmm(a_int, h)
            buf = halo_finish(hspec, plan, recv, h)
            o_f = fro_spmm(a_fro, jnp.concatenate([h, buf], 0))
            out = jnp.concatenate([o_i, o_f], 0)[b["merge_perm"]]
            return jnp.sum(out.astype(jnp.float32) ** 2), out

        (_, of), gf = jax.value_and_grad(loss_fused, has_aux=True)(b["feat"])
        (_, os_), gs = jax.value_and_grad(loss_split, has_aux=True)(b["feat"])
        return of[None], gf[None], os_[None], gs[None]

    f = jax.jit(shard_map(local, mesh=mesh, in_specs=(P("parts"), P()),
                          out_specs=(P("parts"),) * 4))
    of, gf, os_, gs = f(blk, place_replicated(tables, mesh))
    of, gf, os_, gs = map(np.asarray, (of, gf, os_, gs))
    # same wire payloads on both sides: only float reassociation differs
    scale = np.abs(of).max() + 1e-9
    assert np.abs(os_ - of).max() / scale < 1e-5, (strategy, wire, rate, "fwd")
    gscale = np.abs(gf).max() + 1e-9
    assert np.abs(gs - gf).max() / gscale < 1e-5, (strategy, wire, rate, "bwd")


# ----------------------------------------------------------------------------
# end-to-end: build_step_fns(--overlap split) == (--overlap off) — forward
# logits, train losses and updated params after real train steps
# ----------------------------------------------------------------------------

def _run_training(g, art, mesh, overlap, *, model="graphsage", spmm="ell",
                  strategy="padded", wire="native", rate=0.5, epochs=3):
    n_parts = mesh.devices.size
    cfg = Config(model=model, dropout=0.0, use_pp=False, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=rate, spmm=spmm,
                 halo_exchange=strategy, halo_wire=wire, overlap=overlap,
                 n_partitions=n_parts, n_feat=g.n_feat, n_class=g.n_class)
    spec = ModelSpec(model, (g.n_feat, 16, g.n_class), norm="layer",
                     dropout=0.0, train_size=g.n_train)
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, model)
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    params, state = init_params(jax.random.key(5), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    logits = fns.forward(params, state, jnp.uint32(2), blk, tb,
                         jax.random.key(0))
    losses = []
    for e in range(epochs):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb,
            jax.random.key(0), jax.random.key(1))
        losses.append(float(loss))
    return np.asarray(logits), losses, jax.device_get(params), fns.overlap


def _assert_off_equals_split(g, art, mesh, **kw):
    lo, lso, po, _ = _run_training(g, art, mesh, "off", **kw)
    ls, lss, ps, resolved = _run_training(g, art, mesh, "split", **kw)
    assert resolved == "split"          # really ran the split path
    scale = np.abs(lo).max() + 1e-9
    assert np.abs(ls - lo).max() / scale < 1e-4, kw
    for a, b in zip(lso, lss):
        assert abs(a - b) <= 1e-5 * max(abs(a), 1.0), (kw, lso, lss)
    for a, b in zip(jax.tree.leaves(po), jax.tree.leaves(ps)):
        a, b = np.asarray(a), np.asarray(b)
        s = np.abs(a).max() + 1e-9
        assert np.abs(b - a).max() / s < 1e-4, kw


@pytest.fixture(scope="module")
def skew4():
    g = synthetic_graph(n_nodes=120, avg_degree=7, n_feat=6, seed=41,
                        power_law=True)
    pid = np.zeros(g.n_nodes, dtype=np.int32)
    pid[60:90] = 1
    pid[90:110] = 2
    pid[110:] = 3
    return g, build_artifacts(g, pid), make_parts_mesh(4)


@pytest.mark.quickgate
def test_e2e_split_equals_off_ell(skew4):
    g, art, mesh = skew4
    _assert_off_equals_split(g, art, mesh, spmm="ell", rate=0.5)


def test_e2e_split_equals_off_hybrid_ragged_int8(skew4):
    g, art, mesh = skew4
    _assert_off_equals_split(g, art, mesh, model="gcn", spmm="hybrid",
                             strategy="ragged", wire="int8", rate=1.0)


def test_e2e_split_equals_off_segment_shift(skew4):
    g, art, mesh = skew4
    _assert_off_equals_split(g, art, mesh, spmm="segment", strategy="shift",
                             wire="bf16", rate=0.5)


def test_gat_falls_back_to_off(skew4):
    """GAT aggregates through the masked edge softmax — --overlap split must
    resolve to 'off' (logged), not crash or silently mis-aggregate."""
    g, art, mesh = skew4
    cfg = Config(model="gat", use_pp=True, n_train=g.n_train,
                 overlap="split", n_feat=g.n_feat, n_class=g.n_class)
    spec = ModelSpec("gat", (g.n_feat, 8, g.n_class), dropout=0.0,
                     use_pp=True, heads=2, train_size=g.n_train)
    fns, _, _, _ = build_step_fns(cfg, spec, art, mesh)
    assert fns.overlap == "off"


# ----------------------------------------------------------------------------
# degenerate partitions: zero interior rows / zero frontier rows
# ----------------------------------------------------------------------------

def _degenerate_graph():
    """16 nodes, 2 parts of 8 (pad_inner == 8, NO padded rows — padding
    would count as interior and un-degenerate part 0): every part-0 row has
    a cross in-edge (zero interior), part 1 receives no cross edges (zero
    frontier)."""
    n = 16
    rng = np.random.default_rng(7)
    src = list(range(n))                       # self-loops (canonical form)
    dst = list(range(n))
    for i in range(8):                         # 8+i -> i : part0 all-frontier
        src.append(8 + i)
        dst.append(i)
    for i in range(7):                         # local chain inside part 1
        src.append(8 + i)
        dst.append(9 + i)
    label = rng.integers(0, 3, size=n)
    feat = rng.normal(size=(n, 5)).astype(np.float32)
    ones = np.ones(n, dtype=bool)
    g = Graph(n, np.asarray(src, np.int64), np.asarray(dst, np.int64),
              feat, label.astype(np.int64), ones, ones, ones)
    pid = np.repeat(np.arange(2), 8).astype(np.int32)
    return g, pid


@pytest.mark.quickgate
def test_degenerate_zero_interior_and_zero_frontier():
    g, pid = _degenerate_graph()
    art = build_artifacts(g, pid)
    assert art.pad_inner == 8 and art.n_inner.tolist() == [8, 8]
    fm0 = frontier_mask(art.src[0], art.dst[0], art.pad_inner)
    fm1 = frontier_mask(art.src[1], art.dst[1], art.pad_inner)
    assert fm0.all(), "part 0 must have zero interior rows"
    assert not fm1.any(), "part 1 must have zero frontier rows"
    mesh = make_parts_mesh(2)
    _assert_off_equals_split(g, art, mesh, spmm="ell", rate=1.0)
    _assert_off_equals_split(g, art, mesh, spmm="hybrid", rate=0.5)


@pytest.mark.quickgate
def test_degenerate_single_part_no_frontier_anywhere():
    """P=1 (the bench preflight shape): no cross edges at all — the
    frontier side is all-padding everywhere and split must still equal
    off."""
    g = synthetic_graph(n_nodes=64, avg_degree=5, n_feat=6, seed=9)
    art = build_artifacts(g, partition_graph(g, 1, method="random", seed=0))
    mesh = make_parts_mesh(1)
    _assert_off_equals_split(g, art, mesh, spmm="ell", rate=1.0)
