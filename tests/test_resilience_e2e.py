"""Fault-injection e2e through the real CLI: exit codes are the contract.

A requeue wrapper (the role tools/tpu_watchdog*.sh played out-of-process)
only ever sees the process exit status, so these tests drive
`python -m bnsgcn_tpu.main` in a subprocess and assert the resilience exit
codes directly: 75 preempted-resumable, 77 hung-step watchdog. The
sigterm-then-resume pair additionally pins bit-for-bit continuation: the
resumed run's RESULT final_loss equals the uninterrupted run's.

tools/fault_matrix.sh runs the same matrix from the shell for manual/CI use.
"""

import os
import re
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_ARGS = [
    "--dataset", "sbm", "--partition-method", "random", "--n-partitions", "2",
    "--model", "graphsage", "--n-layers", "2", "--n-hidden", "8",
    "--sampling-rate", "0.5", "--use-pp", "--n-epochs", "8",
    "--log-every", "2", "--no-eval", "--no-comm-trace",
    "--fix-seed", "--seed", "11",
]


def _env(extra=None):
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
               BNSGCN_RETRY_BACKOFF_S="0")
    env.update(extra or {})
    return env


def _run(tmp_path, extra_args=(), extra_env=None, timeout=240):
    cmd = ([sys.executable, "-m", "bnsgcn_tpu.main"] + BASE_ARGS
           + ["--part-path", str(tmp_path / "parts"),
              "--ckpt-path", str(tmp_path / "ckpt"),
              "--results-path", str(tmp_path / "res")]
           + list(extra_args))
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout, cwd=REPO, env=_env(extra_env))


def _final_loss(stdout: str) -> float:
    m = re.search(r"RESULT final_loss=(\S+)", stdout)
    assert m, f"no RESULT line in output:\n{stdout[-2000:]}"
    return float(m.group(1))


@pytest.mark.quickgate
def test_sigterm_preempts_resumable_then_resume_reaches_same_loss(tmp_path):
    """The acceptance pin: sigterm@E3 exits EXIT_PREEMPTED with a resumable
    checkpoint, and `--resume` reaches the same final loss as an
    uninterrupted run of the same seed."""
    full = _run(tmp_path)
    assert full.returncode == 0, full.stderr[-2000:]
    want = _final_loss(full.stdout)

    interrupted = _run(tmp_path, ["--inject", "sigterm@E3",
                                  "--ckpt-path", str(tmp_path / "ckpt_b")])
    assert interrupted.returncode == 75, (
        interrupted.returncode, interrupted.stderr[-2000:])
    assert "resumable checkpoint" in (interrupted.stdout + interrupted.stderr)

    # resume with a DIFFERENT seed flag: the checkpoint's saved seed must win
    resumed = _run(tmp_path, ["--resume", "--seed", "999", "--skip-partition",
                              "--ckpt-path", str(tmp_path / "ckpt_b")])
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    assert "Resumed from" in resumed.stdout
    got = _final_loss(resumed.stdout)
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_hang_injection_trips_watchdog_with_stack_dump(tmp_path):
    """hang@E3 blocks the step; the in-process watchdog (deadline shrunk via
    env) must dump all-thread stacks + live-array state and exit 77."""
    r = _run(tmp_path, ["--inject", "hang@E3"],
             extra_env={"BNSGCN_WATCHDOG_MIN_S": "1.5",
                        "BNSGCN_WATCHDOG_FACTOR": "2",
                        "BNSGCN_WATCHDOG_GRACE_S": "120"},
             timeout=300)
    assert r.returncode == 77, (r.returncode, r.stderr[-2000:])
    assert "[watchdog] step hung" in r.stderr
    assert "Current thread" in r.stderr or "Thread 0x" in r.stderr
    assert "live arrays" in r.stderr


def test_resume_walks_past_zero_byte_latest_checkpoint(tmp_path):
    """Truncate the newest checkpoint after a preemption: --resume must fall
    back to the previous periodic file instead of crashing, losing only the
    epochs in between."""
    interrupted = _run(tmp_path, ["--inject", "sigterm@E5"])
    assert interrupted.returncode == 75, interrupted.stderr[-2000:]
    ckpt_dir = str(tmp_path / "ckpt")
    cks = sorted(os.listdir(ckpt_dir), key=lambda f: int(f.split("_")[-1][:-5]))
    open(os.path.join(ckpt_dir, cks[-1]), "wb").close()    # zero-byte newest
    resumed = _run(tmp_path, ["--resume", "--skip-partition"])
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    assert "skipping corrupt checkpoint" in resumed.stdout
    assert re.search(r"Resumed from .*_3\.ckpt", resumed.stdout), (
        resumed.stdout[-2000:])
