"""Streaming artifact builder ≡ in-memory builder.

The papers100M-scale path (data/artifacts.build_artifacts_streaming) must
produce artifacts equivalent to build_artifacts + save_artifacts: identical
node data, boundary metadata, degrees and ELL geometry; edge sets equal as
multisets per part (within-part order may differ — aggregation is a sum).
Reference equivalents: helper/utils.py:73-140 partition write/load at the
scale of README.md:32 (papers100M on a 120 GB host).
"""

import numpy as np
import pytest

from bnsgcn_tpu.data.artifacts import (build_artifacts,
                                       build_artifacts_streaming,
                                       load_artifacts, save_artifacts)
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph


def _edge_multiset(src, dst, pad_inner):
    real = dst < pad_inner
    pairs = np.stack([src[real], dst[real]], axis=1)
    return pairs[np.lexsort((pairs[:, 0], pairs[:, 1]))]


@pytest.mark.parametrize("power_law", [False, True])
def test_streaming_matches_inmemory(tmp_path, power_law):
    g = synthetic_graph(n_nodes=140, avg_degree=7, n_feat=6, n_class=5,
                        seed=51, power_law=power_law)
    pid = partition_graph(g, 4, method="random", seed=6)
    art = build_artifacts(g, pid)

    build_artifacts_streaming(g, pid, str(tmp_path / "s"))
    art_s = load_artifacts(str(tmp_path / "s"))

    assert art_s.n_parts == art.n_parts
    assert art_s.pad_inner == art.pad_inner
    assert art_s.pad_boundary == art.pad_boundary
    assert art_s.pad_edges == art.pad_edges
    np.testing.assert_array_equal(art_s.n_inner, art.n_inner)
    np.testing.assert_array_equal(art_s.n_b, art.n_b)
    np.testing.assert_array_equal(art_s.bnd, art.bnd)
    np.testing.assert_array_equal(art_s.global_nid, art.global_nid)
    np.testing.assert_array_equal(art_s.inner_mask, art.inner_mask)
    np.testing.assert_array_equal(art_s.train_mask, art.train_mask)
    np.testing.assert_array_equal(art_s.label, art.label)
    np.testing.assert_allclose(art_s.feat, art.feat, rtol=0, atol=0)
    np.testing.assert_allclose(art_s.in_deg, art.in_deg)
    np.testing.assert_allclose(art_s.out_deg_ext, art.out_deg_ext)
    for p in range(art.n_parts):
        np.testing.assert_array_equal(
            _edge_multiset(art_s.src[p], art_s.dst[p], art.pad_inner),
            _edge_multiset(art.src[p], art.dst[p], art.pad_inner))
    # ELL geometry identical (histogram-accumulated == stacked computation)
    assert art_s.ell_geometry["fwd"] == art.ell_geometry["fwd"]
    assert art_s.ell_geometry["bwd"] == art.ell_geometry["bwd"]
    assert art_s.ell_geometry["gat_fwd"] == art.ell_geometry["gat_fwd"]


def test_streaming_bf16_features(tmp_path):
    g = synthetic_graph(n_nodes=96, avg_degree=5, n_feat=6, seed=52)
    pid = partition_graph(g, 2, method="random", seed=1)
    build_artifacts_streaming(g, pid, str(tmp_path / "b"),
                              feat_dtype="bfloat16")
    art = load_artifacts(str(tmp_path / "b"))
    import ml_dtypes
    assert art.feat.dtype == ml_dtypes.bfloat16
    ref = build_artifacts(g, pid)
    np.testing.assert_allclose(art.feat.astype(np.float32), ref.feat,
                               rtol=8e-3, atol=8e-3)


def test_streaming_trains_like_inmemory(tmp_path):
    """run_training from streamed artifacts reaches the same losses as from
    in-memory artifacts (rate 1.0 — exact up to edge-order fp reassociation)."""
    import jax
    from bnsgcn_tpu.config import Config
    from bnsgcn_tpu.run import run_training

    g = sbm_graph(n_nodes=200, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                  seed=53)
    losses = {}
    for mode in ("never", "always"):
        cfg = Config(dataset="sbm", model="graphsage", n_partitions=4,
                     n_layers=2, n_hidden=8, sampling_rate=1.0, dropout=0.0,
                     use_pp=True, eval=False, n_epochs=5, log_every=10,
                     seed=3, streaming_artifacts=mode,
                     part_path=str(tmp_path / f"parts_{mode}"),
                     ckpt_path=str(tmp_path / f"ckpt_{mode}"),
                     results_path=str(tmp_path / "res"))
        losses[mode] = run_training(cfg, g=g, verbose=False).losses
    np.testing.assert_allclose(losses["always"], losses["never"],
                               rtol=1e-5, atol=1e-6)


def test_streaming_multilabel(tmp_path):
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=6, n_class=4,
                        seed=54, multilabel=True)
    pid = partition_graph(g, 2, method="random", seed=2)
    build_artifacts_streaming(g, pid, str(tmp_path / "m"))
    art = load_artifacts(str(tmp_path / "m"))
    ref = build_artifacts(g, pid)
    assert art.multilabel and art.label.ndim == 3
    np.testing.assert_array_equal(art.label, ref.label)
