"""Distributed-runtime correctness on a virtual CPU mesh (SURVEY §4):

  * P=4, rate=1.0 training forward/loss/step ≡ P=1 (the reference's own
    exactness ground truth: sampling_rate 1 == exact full-graph training);
  * BNS unbiasedness: E[sampled halo aggregation] == full aggregation;
  * presence mask semantics for GAT.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu.config import Config
from bnsgcn_tpu.data.artifacts import build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph, synthetic_graph
from bnsgcn_tpu.data.partitioner import partition_graph
from bnsgcn_tpu.models.gnn import ModelSpec, init_params
from bnsgcn_tpu.parallel.halo import halo_apply, make_halo_plan, make_halo_spec
from bnsgcn_tpu.parallel.mesh import make_parts_mesh, shard_map
from bnsgcn_tpu.ops.spmm import agg_sum
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns, init_training,
                                place_blocks, place_replicated)
from jax.sharding import PartitionSpec as P


def _setup(g, n_parts, cfg, spec, mesh, rate=None):
    pid = partition_graph(g, n_parts, method="random", seed=3)
    art = build_artifacts(g, pid)
    fns, hspec, tables, tables_full = build_step_fns(cfg, spec, art, mesh, rate=rate)
    blk_np = build_block_arrays(art, spec.model)
    blk_np.update(fns.extra_blk)
    blk = place_blocks(blk_np, mesh)
    tables = place_replicated(tables, mesh)
    tables_full = place_replicated(tables_full, mesh)
    if spec.use_pp:
        out = fns.precompute(blk, tables_full)
        if spec.model == "gat":
            blk["feat0_ext"] = out
        else:
            blk["feat"] = out
    return art, fns, blk, tables


def _gather_logits(art, logits):
    """[P, pad_inner, C] device logits -> [N, C] global order."""
    logits = np.asarray(logits)
    n_class = logits.shape[-1]
    n = int(art.n_inner.sum())
    out = np.zeros((n, n_class), dtype=logits.dtype)
    for p in range(art.n_parts):
        ids = art.global_nid[p][art.inner_mask[p]]
        out[ids] = logits[p][art.inner_mask[p]]
    return out


MODELS = [
    ("gcn", False, "layer"),
    ("gcn", True, "layer"),
    ("graphsage", False, "layer"),
    ("graphsage", True, "layer"),
    ("graphsage", False, "batch"),
    ("gat", True, "layer"),
]


@pytest.mark.parametrize("model,use_pp,norm", MODELS)
def test_p4_rate1_forward_equals_p1(model, use_pp, norm):
    g = synthetic_graph(n_nodes=90, avg_degree=6, n_feat=6, n_class=4, seed=31)
    cfg = Config(model=model, dropout=0.0, use_pp=use_pp, norm=norm,
                 n_train=g.n_train, lr=0.01, sampling_rate=1.0)
    spec = ModelSpec(model, (6, 8, 4), norm=norm, dropout=0.0, use_pp=use_pp,
                     train_size=g.n_train, heads=2 if model == "gat" else 1)
    params, state = init_params(jax.random.key(7), spec)

    mesh4 = make_parts_mesh(4)
    mesh1 = make_parts_mesh(1)
    key = jax.random.key(0)
    ep = jnp.uint32(0)

    art4, fns4, blk4, tb4 = _setup(g, 4, cfg, spec, mesh4)
    art1, fns1, blk1, tb1 = _setup(g, 1, cfg, spec, mesh1)
    p4 = place_replicated(params, mesh4)
    s4 = place_replicated(state, mesh4)
    p1 = place_replicated(params, mesh1)
    s1 = place_replicated(state, mesh1)

    l4 = _gather_logits(art4, fns4.forward(p4, s4, ep, blk4, tb4, key))
    l1 = _gather_logits(art1, fns1.forward(p1, s1, ep, blk1, tb1, key))
    np.testing.assert_allclose(l4, l1, rtol=2e-4, atol=2e-4)


@pytest.mark.quickgate
@pytest.mark.parametrize("model,use_pp,halo",
                         [("gcn", True, "padded"), ("graphsage", True, "padded"),
                          ("graphsage", False, "padded"),
                          # rate-1.0 'ragged' must reproduce exact full-graph
                          # training like the padded path (ISSUE 1 acceptance)
                          ("graphsage", True, "ragged"),
                          ("graphsage", False, "ragged")])
def test_p4_rate1_train_step_equals_p1(model, use_pp, halo):
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=32)
    cfg = Config(model=model, dropout=0.0, use_pp=use_pp, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=1.0,
                 halo_exchange=halo)
    spec = ModelSpec(model, (5, 8, 3), norm="layer", dropout=0.0, use_pp=use_pp,
                     train_size=g.n_train)
    params, state = init_params(jax.random.key(9), spec)
    # host copies: train_step donates its inputs, so place fresh per mesh
    params_np = jax.tree.map(np.asarray, params)
    state_np = jax.tree.map(np.asarray, state)
    key = jax.random.key(0)
    dkey = jax.random.key(1)

    results = {}
    for np_, meshn in [(4, make_parts_mesh(4)), (1, make_parts_mesh(1))]:
        art, fns, blk, tb = _setup(g, np_, cfg, spec, meshn)
        pp = place_replicated(params_np, meshn)
        ss = place_replicated(state_np, meshn)
        _, _, opt = init_training(cfg, spec, meshn)
        losses = []
        for e in range(3):
            pp, ss, opt, loss = fns.train_step(pp, ss, opt, jnp.uint32(e), blk, tb, key, dkey)
            losses.append(float(loss))
        results[np_] = (losses, jax.tree.map(np.asarray, jax.device_get(pp)))

    np.testing.assert_allclose(results[4][0], results[1][0], rtol=1e-4, atol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=5e-4, atol=1e-5),
                 results[4][1], results[1][1])


@pytest.mark.quickgate
def test_bns_unbiasedness():
    """E over epochs of (sampled, 1/ratio-scaled) halo aggregation equals the
    full-rate aggregation (SURVEY §4: unbiasedness of BNS)."""
    g = synthetic_graph(n_nodes=60, avg_degree=6, n_feat=4, seed=33)
    pid = partition_graph(g, 4, method="random", seed=5)
    art = build_artifacts(g, pid)
    mesh = make_parts_mesh(4)

    hspec, tables = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.5)
    hfull, tfull = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 1.0)
    blk = place_blocks({"feat": art.feat.astype(np.float32),
                        "bnd": art.bnd, "src": art.src, "dst": art.dst}, mesh)
    base = jax.random.key(42)

    def make_agg(spec):
        def local(blk, tables, epoch):
            b = {k: v[0] for k, v in blk.items()}
            plan = make_halo_plan(spec, tables, b["bnd"], epoch, base)
            hx = halo_apply(spec, plan, b["feat"])
            return agg_sum(hx, b["src"], b["dst"], spec.pad_inner)[None]
        return jax.jit(shard_map(
            local, mesh=mesh, in_specs=(P("parts"), P(), P()),
            out_specs=P("parts")))

    full = np.asarray(make_agg(hfull)(blk, place_replicated(tfull, mesh), jnp.uint32(0)))
    n_ep = 300
    acc = np.zeros_like(full)
    tb = place_replicated(tables, mesh)
    agg = make_agg(hspec)
    for e in range(n_ep):
        acc += np.asarray(agg(blk, tb, jnp.uint32(e)))
    mean = acc / n_ep
    # inner-edge contribution is identical; compare totals with MC tolerance
    err = np.abs(mean - full)
    scale = np.abs(full).mean() + 1e-6
    assert err.mean() / scale < 0.05, f"biased? mean rel err {err.mean() / scale}"


def test_sampling_rate_reduces_payload_not_shapes():
    g = synthetic_graph(n_nodes=60, avg_degree=6, n_feat=4, seed=34)
    pid = partition_graph(g, 4, method="random", seed=5)
    art = build_artifacts(g, pid)
    h_low, t_low = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 0.1)
    h_hi, t_hi = make_halo_spec(art.n_b, art.pad_inner, art.pad_boundary, 1.0)
    assert h_low.pad_send <= h_hi.pad_send
    ss_low = np.asarray(t_low["send_size"])
    nb = np.asarray(t_low["n_b"])
    assert np.all(ss_low == (0.1 * nb).astype(np.int64))


def test_training_improves_accuracy_sbm():
    """End-to-end: distributed BNS training on an SBM graph learns (accuracy
    over 60 epochs clearly above chance)."""
    g = sbm_graph(n_nodes=240, n_class=4, n_feat=8, p_in=0.08, p_out=0.004, seed=35)
    cfg = Config(model="graphsage", dropout=0.1, use_pp=True, norm="layer",
                 n_train=g.n_train, lr=0.01, sampling_rate=0.5)
    spec = ModelSpec("graphsage", (8, 16, 4), norm="layer", dropout=0.1,
                     use_pp=True, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    art, fns, blk, tb = _setup(g, 4, cfg, spec, mesh)
    params, state = init_params(jax.random.key(11), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    _, _, opt = init_training(cfg, spec, mesh)
    key, dkey = jax.random.key(0), jax.random.key(1)
    first = None
    for e in range(60):
        params, state, opt, loss = fns.train_step(
            params, state, opt, jnp.uint32(e), blk, tb, key, dkey)
        if first is None:
            first = float(loss)
    assert float(loss) < first * 0.7, (first, float(loss))
    logits = _gather_logits(art, fns.forward(params, state, jnp.uint32(0), blk, tb, key))
    acc = float((logits.argmax(1) == g.label)[g.train_mask].mean())
    assert acc > 0.6, acc


def test_remat_matches_no_remat():
    """jax.checkpoint per layer changes memory, not math: losses and updated
    params identical with and without --remat."""
    g = synthetic_graph(n_nodes=80, avg_degree=5, n_feat=5, n_class=3, seed=90)
    spec = ModelSpec("graphsage", (5, 8, 8, 3), norm="layer", dropout=0.2,
                     use_pp=True, train_size=g.n_train)
    params0, state0 = init_params(jax.random.key(9), spec)
    params_np = jax.tree.map(np.asarray, params0)
    mesh = make_parts_mesh(4)
    results = {}
    for remat in (False, True):
        cfg = Config(model="graphsage", dropout=0.2, use_pp=True, norm="layer",
                     n_train=g.n_train, lr=0.01, sampling_rate=0.5, remat=remat)
        art, fns, blk, tb = _setup(g, 4, cfg, spec, mesh)
        p = place_replicated(params_np, mesh)
        s = place_replicated(state0, mesh)
        _, _, opt = init_training(cfg, spec, mesh)
        for e in range(3):
            p, s, opt, loss = fns.train_step(p, s, opt, jnp.uint32(e), blk, tb,
                                             jax.random.key(0), jax.random.key(1))
        results[remat] = (float(loss), jax.tree.map(np.asarray, jax.device_get(p)))
    assert abs(results[True][0] - results[False][0]) < 1e-5
    jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
                 results[True][1], results[False][1])
