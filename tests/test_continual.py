"""Continual training on an evolving graph (continual.py, data/incremental.py,
serve.py promotion protocol).

What is pinned, per ISSUE/ROADMAP:
  (a) the incrementally-updated artifact is ARRAY-FOR-ARRAY bitwise a
      from-scratch build of the mutated graph at the same part assignment,
      and produces bitwise-identical eval logits through the partitioned
      forward across all three halo strategies x reorder on/off;
  (b) the staleness budget (staleness_decision) re-partitions exactly when
      edge-cut growth or imbalance crosses the configured thresholds;
  (c) --cycle-nonce refolds the BNS/dropout streams deterministically:
      same nonce -> bitwise-identical losses, different nonce -> different
      draws, nonce 0 -> bitwise the historical (pre-continual) run;
  (d) promotion rollback: a corrupted/stale promotion blob is rejected and
      the prior serving table/params stay live bitwise; the run_cycle
      accuracy gate keeps serving weights while the consumed cursor still
      advances (deltas are facts, only weights roll back);
  (e) quickgate e2e: train -> subprocess serve -> mutate via deltas ->
      `main continual --continual-source server` -> the promoted serving
      answers reflect the fine-tuned weights.
"""

import dataclasses
import json
import os
import subprocess
import sys
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bnsgcn_tpu import checkpoint as ckpt
from bnsgcn_tpu import continual, serve
from bnsgcn_tpu.config import Config, ConfigError
from bnsgcn_tpu.data import incremental as inc
from bnsgcn_tpu.data.artifacts import PartitionArtifacts, build_artifacts
from bnsgcn_tpu.data.graph import sbm_graph
from bnsgcn_tpu.data.partitioner import (degree_norm_row, degree_tables,
                                         partition_graph,
                                         validate_artifact_dir)
from bnsgcn_tpu.data.reorder import apply_reorder, compute_orders
from bnsgcn_tpu.evaluate import full_graph_embeddings, gather_parts
from bnsgcn_tpu.models.gnn import ModelSpec, init_params, spec_from_config
from bnsgcn_tpu.parallel.mesh import make_parts_mesh
from bnsgcn_tpu.run import run_training
from bnsgcn_tpu.trainer import (build_block_arrays, build_step_fns,
                                place_blocks, place_replicated)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------------
# (a) incremental fold == from-scratch build at the pinned assignment
# ----------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _evolved():
    """Base 4-part artifact, a delta batch touching a strict subset of the
    parts (own + cross-part edges + one feature row), the incremental fold,
    and the from-scratch rebuild of the mutated graph at the SAME part_of."""
    g = sbm_graph(n_nodes=240, n_class=3, n_feat=6, seed=1)
    pid = partition_graph(g, 4, seed=0)
    art = build_artifacts(g, pid)
    _, part_of, _ = inc._global_maps(art)
    by_part = {p: np.flatnonzero(part_of == p) for p in range(4)}
    edges = [
        # own-part edges inside part 0 and part 1
        [int(by_part[0][0]), int(by_part[0][3])],
        [int(by_part[1][2]), int(by_part[1][5])],
        # cross-part edges (grow the boundary/halo tables both directions)
        [int(by_part[0][1]), int(by_part[1][0])],
        [int(by_part[1][1]), int(by_part[0][2])],
        [int(by_part[0][4]), int(by_part[1][3])],
    ]
    entries = [{"op": "add_edges", "edges": edges[:2]},
               {"op": "update_feat", "node": int(by_part[0][0]),
                "feat": [0.5] * g.n_feat},
               {"op": "add_edges", "edges": edges[2:]}]
    batch = inc.delta_batch(entries)
    incr_art, info = inc.update_artifacts(art, batch)
    g2 = inc.apply_delta_batch(g, batch)
    scratch_art = build_artifacts(g2, part_of)
    return g2, art, incr_art, scratch_art, info


def test_incremental_artifact_bitwise_vs_scratch():
    g2, art, incr_art, scratch_art, info = _evolved()
    # the deltas deliberately touch only parts {0, 1}
    assert set(info["touched_edges"]) == {0, 1}
    for f in dataclasses.fields(PartitionArtifacts):
        a = getattr(incr_art, f.name)
        b = getattr(scratch_art, f.name)
        if isinstance(a, np.ndarray):
            assert a.dtype == b.dtype and a.shape == b.shape, f.name
            assert np.array_equal(a, b), f"field {f.name} diverged"
        elif f.name == "ell_geometry":
            assert (a is None) == (b is None)
            if a is not None:
                assert json.dumps(a, sort_keys=True, default=str) == \
                    json.dumps(b, sort_keys=True, default=str)
        else:
            assert a == b, f.name


def _part_logits(g, art, strategy: str, reorder: bool) -> np.ndarray:
    """Global-order forward logits through the real partitioned stack."""
    if reorder:
        art = apply_reorder(art, compute_orders(art, tile_r=32))
    cfg = Config(model="graphsage", dropout=0.0, use_pp=False, norm="layer",
                 n_train=g.n_train, sampling_rate=1.0, spmm="ell",
                 halo_exchange=strategy, n_partitions=4, n_feat=g.n_feat,
                 n_class=g.n_class,
                 reorder="cluster" if reorder else "off")
    spec = ModelSpec("graphsage", (g.n_feat, 16, g.n_class), norm="layer",
                     dropout=0.0, train_size=g.n_train)
    mesh = make_parts_mesh(4)
    fns, _, tables, _ = build_step_fns(cfg, spec, art, mesh)
    blk_np = build_block_arrays(art, "graphsage")
    blk_np.update(fns.extra_blk)
    for k in fns.drop_blk_keys:
        blk_np.pop(k, None)
    blk = place_blocks(blk_np, mesh)
    tb = place_replicated(tables, mesh)
    params, state = init_params(jax.random.key(5), spec)
    params = place_replicated(params, mesh)
    state = place_replicated(state, mesh)
    logits = fns.forward(params, state, jnp.uint32(0), blk, tb,
                         jax.random.key(0))
    return gather_parts(art, np.asarray(logits))


@pytest.mark.parametrize("reorder", [False, True], ids=["raw", "reorder"])
@pytest.mark.parametrize("strategy", ["padded", "shift", "ragged"])
def test_incremental_eval_logits_bitwise_pin(strategy, reorder):
    g2, _, incr_art, scratch_art, _ = _evolved()
    got = _part_logits(g2, incr_art, strategy, reorder)
    want = _part_logits(g2, scratch_art, strategy, reorder)
    assert np.array_equal(got, want), \
        f"eval logits diverged for halo={strategy} reorder={reorder}"


# ----------------------------------------------------------------------------
# (b) staleness budget thresholds + partitioner helpers
# ----------------------------------------------------------------------------

def test_staleness_decision_thresholds():
    base = {"cut": 100, "edges": [50, 50], "imbalance": 1.0}
    ok = {"cut": 120, "edges": [60, 60], "imbalance": 1.2}
    repart, why = inc.staleness_decision(ok, base, 1.5, 2.0)
    assert not repart and why["repartition"] is False
    assert why["cut_growth"] == pytest.approx(1.2)
    # cut growth past budget
    repart, why = inc.staleness_decision(
        {"cut": 160, "edges": [80, 80], "imbalance": 1.0}, base, 1.5, 2.0)
    assert repart and why["cut_growth"] == pytest.approx(1.6)
    # imbalance past budget, cut fine
    repart, why = inc.staleness_decision(
        {"cut": 100, "edges": [150, 10], "imbalance": 2.5}, base, 1.5, 2.0)
    assert repart and why["imbalance"] == pytest.approx(2.5)
    # a zero-cut baseline must not divide by zero
    repart, _ = inc.staleness_decision(
        {"cut": 0, "edges": [10, 10], "imbalance": 1.0},
        {"cut": 0, "edges": [10, 10], "imbalance": 1.0}, 1.5, 2.0)
    assert not repart


def test_degree_norm_row_matches_artifact_rows():
    g2, _, incr_art, _, _ = _evolved()
    in_deg, _ = degree_tables(g2.src, g2.dst, g2.n_nodes)
    for p in range(incr_art.n_parts):
        ids = incr_art.global_nid[p][incr_art.global_nid[p] >= 0]
        row = degree_norm_row(in_deg, ids, incr_art.pad_inner)
        assert np.array_equal(row, incr_art.in_deg[p])


def test_validate_artifact_dir_named_config_error(tmp_path):
    d = tmp_path / "parts"
    d.mkdir()
    np.savez(d / "part0.npz", x=np.zeros(1))
    np.savez(d / "part3.npz", x=np.zeros(1))
    with pytest.raises(ConfigError, match="part"):
        validate_artifact_dir(str(d), 4, None)


# ----------------------------------------------------------------------------
# (c) cycle-nonce stream refolding determinism
# ----------------------------------------------------------------------------

def _nonce_cfg(tmp_path, tag: str, nonce: int) -> Config:
    return Config(dataset="sbm", model="graphsage", n_partitions=2,
                  n_layers=2, n_hidden=8, sampling_rate=0.5, dropout=0.5,
                  use_pp=True, eval=False, n_epochs=3, log_every=2, seed=7,
                  cycle_nonce=nonce,
                  part_path=str(tmp_path / "parts"),
                  ckpt_path=str(tmp_path / f"ckpt_{tag}"),
                  results_path=str(tmp_path / f"res_{tag}"))


def test_cycle_nonce_determinism(tmp_path):
    g = sbm_graph(n_nodes=240, n_class=3, n_feat=8, p_in=0.12, p_out=0.01,
                  seed=3)
    hist = run_training(_nonce_cfg(tmp_path, "hist", 0), g=g, verbose=False)
    # nonce 0 (the default / --continual off path) is bitwise the
    # historical run: the fold is gated, not applied-with-zero
    again = run_training(_nonce_cfg(tmp_path, "again", 0), g=g,
                         verbose=False)
    assert again.losses == hist.losses
    # a cycle nonce refolds both the BNS sampling and dropout streams
    c1 = run_training(_nonce_cfg(tmp_path, "c1", 1), g=g, verbose=False)
    assert c1.losses != hist.losses
    # and is itself deterministic: same nonce -> bitwise-identical draws
    c1b = run_training(_nonce_cfg(tmp_path, "c1b", 1), g=g, verbose=False)
    assert c1b.losses == c1.losses
    # distinct cycles get distinct streams
    c2 = run_training(_nonce_cfg(tmp_path, "c2", 2), g=g, verbose=False)
    assert c2.losses != c1.losses


# ----------------------------------------------------------------------------
# (d) promotion protocol: corrupt/stale rejection, export cursor, acc gate
# ----------------------------------------------------------------------------

@lru_cache(maxsize=None)
def _serve_setup():
    g = sbm_graph(n_nodes=300, n_class=4, n_feat=8, seed=0)
    cfg = Config(dataset="sbm", model="graphsage", n_layers=2, n_hidden=8,
                 use_pp=True, n_feat=g.n_feat, n_class=g.n_class,
                 n_train=g.n_train, serve_max_batch=16)
    spec = spec_from_config(cfg)
    params, state = init_params(jax.random.key(1), spec)
    return g, cfg, spec, params, state


def _promotion_blob(serve_dir: str, cycle: int, scale: float = 1.5):
    """A promotion blob carrying 'fine-tuned' (scaled) weights + the
    matching full-graph table."""
    g, cfg, spec, params, state = _serve_setup()
    p2 = jax.tree.map(lambda x: x * scale, params)
    hidden, logits = full_graph_embeddings(p2, state, spec, g)
    return ckpt.write_promotion(
        serve_dir, params=p2, bn_state=state, hidden=hidden, logits=logits,
        lineage={"cycle": cycle, "consumed": 0}), np.asarray(logits)


def test_promote_rollback_on_corrupt_then_adopt_then_stale(tmp_path):
    g, cfg, spec, params, state = _serve_setup()
    core = serve.build_core(cfg, g, params, state, log=lambda *a, **k: None)
    try:
        before = np.asarray(core.predict(11)["scores"])
        promo, new_logits = _promotion_blob(str(tmp_path), cycle=1)
        # corrupted blob: rejected by the integrity chain, prior table live
        corrupt = str(tmp_path / "corrupt.blob")
        blob = bytearray(open(promo, "rb").read())
        blob[40] ^= 0xFF
        blob[41] ^= 0xFF
        open(corrupt, "wb").write(bytes(blob))
        r = core.promote(corrupt)
        assert not r["ok"] and "rejected" in r["err"]
        assert core.stats["promotions"] == 0
        assert np.array_equal(np.asarray(core.predict(11)["scores"]), before)
        # the intact blob adopts atomically: tier-A now serves the promoted
        # table bitwise
        r = core.promote(promo)
        assert r["ok"] and r["cycle"] == 1
        assert core.stats["promotions"] == 1
        got = core.predict(11)
        assert got["tier"] == "A"
        assert np.array_equal(
            np.asarray(got["scores"], new_logits.dtype), new_logits[11])
        # re-promoting the same cycle is stale (double-promote guard)
        r = core.promote(promo)
        assert not r["ok"] and "stale" in r["err"]
        assert core.stats["promotions"] == 1
    finally:
        core.close()


def test_promotion_admissible_rule():
    ok, _ = serve.promotion_admissible(1, 0)
    assert ok
    for cyc, adopted in ((1, 1), (1, 2), (0, 0)):
        ok, why = serve.promotion_admissible(cyc, adopted)
        assert not ok and "stale" in why


def test_export_deltas_cursor_semantics(tmp_path):
    g, cfg, spec, params, state = _serve_setup()
    core = serve.build_core(cfg, g, params, state, log=lambda *a, **k: None)
    try:
        core.add_edges([(7, 5)])
        core.add_edges([(11, 9)])
        r = core.export_deltas(0)
        assert r["ok"] and r["total"] == 2 and len(r["deltas"]) == 2
        r = core.export_deltas(1)
        assert r["ok"] and len(r["deltas"]) == 1
        assert r["deltas"][0]["edges"] == [[11, 9]]
        # a cursor past the journal is a named error, not an empty tail
        assert not core.export_deltas(3)["ok"]
        # compaction folds the prefix: an older cursor must resync
        core.compact(str(tmp_path))
        r = core.export_deltas(1)
        assert r["ok"] and r.get("snapshot_required") and r["folded"] == 2
        r = core.export_deltas(2)
        assert r["ok"] and not r.get("snapshot_required") \
            and r["deltas"] == []
    finally:
        core.close()


def _trained(tmp_path, tag="base"):
    """A short real training run: artifacts on disk + a serving ckpt."""
    cfg = Config(dataset="sbm", model="graphsage", n_partitions=2,
                 n_layers=2, n_hidden=8, sampling_rate=1.0, dropout=0.0,
                 use_pp=True, eval=True, n_epochs=4, log_every=2, seed=5,
                 part_path=str(tmp_path / "parts"),
                 ckpt_path=str(tmp_path / f"ckpt_{tag}"),
                 results_path=str(tmp_path / f"res_{tag}"),
                 serve_dir=str(tmp_path / "serve"))
    cfg = cfg.replace(graph_name=cfg.derive_graph_name())
    from bnsgcn_tpu.data.datasets import load_data
    g, _, _ = load_data(cfg)
    cfg = cfg.replace(n_feat=g.n_feat, n_class=g.n_class, n_train=g.n_train)
    run_training(cfg, g=g, verbose=False)
    return cfg, g


def _write_delta_log(serve_dir: str, g, seed=9, k=10):
    os.makedirs(serve_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, g.n_nodes, (k, 2))
    entries = [{"op": "add_edges",
                "edges": [[int(u), int(v)] for u, v in pairs if u != v]}]
    with open(os.path.join(serve_dir, "delta_log.jsonl"), "w") as f:
        for e in entries:
            f.write(json.dumps(e) + "\n")
    return entries


def test_run_cycle_acc_gate_rolls_back_but_cursor_advances(tmp_path):
    cfg, g = _trained(tmp_path)
    _write_delta_log(cfg.serve_dir, g)
    # an impossible gate (the fine-tune would need +1.0 val acc) forces the
    # rollback path: weights stay, the consumed cursor still advances
    out = continual.run_cycle(
        cfg.replace(cycle_epochs=1, continual_acc_drop=-1.0),
        log=lambda *a, **k: None)
    assert out["ok"] and not out["promoted"] and out["consumed"] == 1
    assert not os.path.exists(ckpt.promotion_path(cfg.serve_dir))
    st = continual.load_state(cfg.serve_dir)
    assert st["cycle"] == 1 and st["consumed"] == 1
    # the next cycle has nothing left to consume
    out = continual.run_cycle(cfg.replace(cycle_epochs=1),
                              log=lambda *a, **k: None)
    assert out.get("noop")


def test_continual_main_noop_and_config_exit(tmp_path):
    args = ["--dataset", "sbm", "--model", "graphsage",
            "--n-partitions", "2", "--use-pp", "--fix-seed", "--seed", "5",
            "--part-path", str(tmp_path / "parts"),
            "--ckpt-path", str(tmp_path / "ckpt"),
            "--serve-dir", str(tmp_path / "serve")]
    # empty serve dir: a clean no-op, exit 0
    assert continual.continual_main(args) == 0
    # deltas but no artifacts/checkpoint to fold them into: exit 2, named
    g = sbm_graph(n_nodes=60, n_class=3, n_feat=4, seed=0)
    _write_delta_log(str(tmp_path / "serve"), g, k=3)
    assert continual.continual_main(args) == 2


# ----------------------------------------------------------------------------
# (e) quickgate e2e: train -> serve -> deltas -> continual -> promoted answers
# ----------------------------------------------------------------------------

def _env():
    env = dict(os.environ)
    env.update(PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    return env


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _cli_flags(cfg: Config) -> list:
    return ["--dataset", "sbm", "--model", "graphsage",
            "--n-partitions", "2", "--n-layers", "2", "--n-hidden", "8",
            "--use-pp", "--fix-seed", "--seed", "5",
            "--sampling-rate", "1.0", "--dropout", "0.0",
            "--graph-name", cfg.graph_name,
            "--part-path", cfg.part_path, "--ckpt-path", cfg.ckpt_path,
            "--serve-dir", cfg.serve_dir]


@pytest.mark.quickgate
def test_e2e_train_serve_mutate_continual_promote(tmp_path):
    import time
    cfg, g = _trained(tmp_path)
    port = _free_port()
    flags = _cli_flags(cfg)
    p = subprocess.Popen(
        [sys.executable, "-m", "bnsgcn_tpu.main", "serve"] + flags
        + ["--serve-port", str(port)],
        env=_env(), cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if p.poll() is not None:
                raise AssertionError(f"server died rc={p.returncode}:\n"
                                     f"{p.stdout.read()[-2000:]}")
            try:
                if serve.request(port, {"op": "ping"},
                                 timeout_s=1.0).get("ok"):
                    break
            except Exception:
                time.sleep(0.2)
        else:
            raise AssertionError("server never became ready")
        before = serve.request(port, {"op": "predict", "node": 3})
        assert before["ok"]
        # mutate the live graph through the serving delta journal
        rng = np.random.default_rng(2)
        edges = [[int(u), int(v)]
                 for u, v in rng.integers(0, g.n_nodes, (8, 2)) if u != v]
        r = serve.request(port, {"op": "add_edges", "edges": edges})
        assert r["ok"]
        # one continual cycle against the live server: export handshake,
        # incremental fold, warm-start fine-tune, live promotion
        out = subprocess.run(
            [sys.executable, "-m", "bnsgcn_tpu.main", "continual"] + flags
            + ["--serve-port", str(port), "--continual-source", "server",
               "--cycle-epochs", "2", "--cycles", "1"],
            env=_env(), cwd=REPO, capture_output=True, text=True,
            timeout=420)
        assert out.returncode == 0, \
            f"continual failed:\n{out.stdout[-3000:]}\n{out.stderr[-3000:]}"
        stats = serve.request(port, {"op": "stats"})
        assert stats["promotions"] == 1
        st = continual.load_state(cfg.serve_dir)
        assert st["cycle"] == 1 and st["last"]["promoted"]
        # one add_edges request = one journal entry = one cursor step
        assert st["consumed"] == 1
        # the promoted serving answers reflect the fine-tuned weights
        after = serve.request(port, {"op": "predict", "node": 3})
        assert after["ok"]
        assert not np.array_equal(np.asarray(before["scores"]),
                                  np.asarray(after["scores"]))
        promo = ckpt.read_promotion(ckpt.promotion_path(cfg.serve_dir))
        assert int(promo["lineage"]["cycle"]) == 1
        logits = np.asarray(promo["logits"])
        # some tier-A (clean) node must serve the promoted table bitwise
        for v in range(0, g.n_nodes, max(1, g.n_nodes // 40)):
            got = serve.request(port, {"op": "predict", "node": int(v)})
            if got["tier"] == "A":
                assert np.array_equal(
                    np.asarray(got["scores"], logits.dtype), logits[v])
                break
        else:
            raise AssertionError("no clean tier-A node found")
    finally:
        p.terminate()
        try:
            p.wait(timeout=30)
        except subprocess.TimeoutExpired:
            p.kill()
