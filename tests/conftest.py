"""Force an 8-device CPU mesh before JAX initializes.

The SURVEY test strategy (§4): JAX CPU multi-device exercises the same
shard_map/collective code paths a TPU pod uses. Must run before `import jax`
anywhere, hence top of conftest. PALLAS_AXON_POOL_IPS is cleared so the axon
TPU plugin's sitecustomize doesn't steal the backend.
"""

import os

os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import re

flags = os.environ.get("XLA_FLAGS", "")
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", flags)
os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# The axon TPU plugin's sitecustomize imports jax at interpreter startup, so
# the env vars above are read too late; re-assert them through the config
# (backends initialize lazily, so this still takes effect).
from bnsgcn_tpu.utils.platform import honor_platform_request  # noqa: E402

honor_platform_request(strict=True)

import jax  # noqa: E402

assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for mesh tests"

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    return jax.make_mesh((8,), ("parts",))


@pytest.fixture(scope="session")
def mesh4():
    import jax
    return jax.make_mesh((4,), ("parts",))


@pytest.fixture
def rng():
    return np.random.default_rng(0)
